#!/bin/bash
# Round-4 hardware queue, third pass.
#
# Run 1 (tpu_queue_v2.sh) wedged mid-profile: the googlenet_bn variant's
# dispatch hung the tunnel at 10:27 UTC and the single-process profile
# script lost everything it had measured (salvaged by hand into
# profile/flagship.json from the log).  Changes here:
#   * bench.py runs FIRST — it is the round's single most valuable
#     artifact (headline + engine/batch extras + last_good cache) and is
#     already outage-proof;
#   * profile_flagship.py now defaults to a per-variant orchestrator
#     (child process per variant, hard timeout, artifact re-written after
#     every variant, resume skips what run 1 already measured) — a wedge
#     costs one variant, not the run;
#   * every step is gated on a fresh tunnel probe (wait_tunnel) so a
#     wedge in step N doesn't burn step N+1's timeout while down.
# Run detached:  setsid nohup scripts/tpu_queue_v3.sh &
# Log: /tmp/tpu_queue_v3.log
cd "$(dirname "$0")/.."
exec > /tmp/tpu_queue_v3.log 2>&1

# Step sentinels are keyed to the HEAD short-sha (ADVICE #3): a later
# run of this script in the same container AFTER source changes must
# not silently skip steps 3-6 on stale sentinels — new code means
# re-measure.  (Committed artifacts like step 4's JSON are separate:
# they are evidence tied to the commit that produced them.)
SHA=$(git rev-parse --short HEAD 2>/dev/null || echo nosha)
S3=/tmp/tpu_q_${SHA}_step3.done
S4=/tmp/tpu_q_${SHA}_step4.done
S5=/tmp/tpu_q_${SHA}_step5.done
S6=/tmp/tpu_q_${SHA}_step6.done

probe() {
  timeout 100 python -c \
    'import jax,sys; sys.exit(jax.devices()[0].platform != "tpu")' \
    >/dev/null 2>&1
}

wait_tunnel() {
  # Up to ~1.6h per step; the tunnel recovers on its own (observed).
  for i in $(seq 1 30); do
    probe && { echo "tunnel up after probe $i ($(date))"; return 0; }
    echo "probe $i failed ($(date)); sleeping 180s"
    sleep 180
  done
  echo "tunnel still down after 30 probes"
  return 1
}

echo "=== $(date) waiting for tunnel ==="
wait_tunnel || { echo "GAVE UP"; exit 1; }

echo "=== $(date) 1/6 bench.py full ==="
# A fresh same-day measured headline in last_good means a re-run would
# spend ~50 min of tunnel re-measuring what we already captured —
# while the profile re-measure (the round's #1 evidence item) starves.
# Skip and let tpu_queue_r5_extras' coverage-gated re-pass pick up any
# batch rows this pass lost (it runs after this queue completes).
bench_fresh=$(python - <<'EOF'
import datetime, json
try:
    d = json.load(open("bench_cache/last_good.json"))
    fresh = (d.get("date") == datetime.date.today().isoformat()
             and d.get("payload", {}).get("value", 0) > 0
             and d["payload"].get("platform") == "tpu")
    print("yes" if fresh else "no")
except Exception:
    print("no")
EOF
)
if [ "$bench_fresh" = "yes" ]; then
  echo "bench SKIPPED: last_good already holds a same-day measured TPU headline"
else
  # Budget > bench's own worst case (~3870s: probe phase up to 270s
  # [120 + 30 retry-wait + 120] plus a 90s CPU probe on the degraded
  # path, full child 3000s [two timed windows per row since the 08:04
  # jitter finding], two smoke fallbacks 600s) so the outer timeout can
  # never kill it mid-fallback and lose the degraded JSON.
  timeout 4200 python bench.py > /tmp/bench_out.json
  echo "bench rc=$?"
  tail -c 1000 /tmp/bench_out.json
fi

# From here on, a wait_tunnel failure ABORTS the pass (supervisor
# restarts us) instead of falling through: the old && gating let a
# dead-tunnel pass crawl through every step's 1.6h probe budget and
# still print DONE, which stops the supervisor for good with nothing
# measured.
echo "=== $(date) 2/6 profile orchestrator (resumable, per-variant) ==="
wait_tunnel || { echo "GAVE UP (step 2)"; exit 1; }
timeout 4200 python scripts/profile_flagship.py --steps 10
profile_rc=$?
echo "profile rc=$profile_rc"

# Steps 3-6 leave a success sentinel so a supervisor restart (the
# abort-on-outage semantics above) retries only what hasn't finished,
# instead of re-burning ~2h of tunnel on already-captured artifacts.
# Sentinels live in /tmp: a container restart clears them, which only
# costs a re-measure, never correctness.
echo "=== $(date) 3/6 tpu_pallas_check (parity + stretch, cached@16k) ==="
if [ -f "$S3" ]; then
  echo "step 3 SKIPPED: done sentinel present"
else
  wait_tunnel || { echo "GAVE UP (step 3)"; exit 1; }
  timeout 3300 python scripts/tpu_pallas_check.py --pool 4096 \
    --stretch 32768 --stretch-cached 16384 > /tmp/tpu_check_out.json
  rc=$?
  echo "tpu_pallas_check rc=$rc"
  tail -c 2000 /tmp/tpu_check_out.json
  if [ "$rc" = 0 ]; then
    python scripts/split_pallas_check.py && touch "$S3"
  fi
fi

echo "=== $(date) 4/6 TPU accuracy smoke (e2e real-JPEG on the chip) ==="
if [ -f "$S4" ] || [ -f accuracy/e2e_real_jpeg_tpu.json ]
then
  echo "step 4 SKIPPED: artifact or sentinel present"
else
  wait_tunnel || { echo "GAVE UP (step 4)"; exit 1; }
  timeout 2400 env E2E_JAX_PLATFORM=default \
    python scripts/e2e_real_jpeg.py \
    --steps 200 --workdir /tmp/e2e_jpeg_tpu2 \
    --artifact accuracy/e2e_real_jpeg_tpu.json
  rc=$?
  echo "e2e tpu rc=$rc"
  [ "$rc" = 0 ] && touch "$S4"
fi

echo "=== $(date) 5/6 diag_sim_cache 8192,16384 (safe pools) ==="
if [ -f "$S5" ]; then
  echo "step 5 SKIPPED: done sentinel present"
else
  wait_tunnel || { echo "GAVE UP (step 5)"; exit 1; }
  timeout 1800 python scripts/diag_sim_cache.py \
    --pools 8192,16384
  rc=$?
  echo "diag safe rc=$rc"
  [ "$rc" = 0 ] && touch "$S5"
fi

echo "=== $(date) 6/6 diag_sim_cache 24576 (WEDGE-RISK, runs last) ==="
if [ -f "$S6" ]; then
  echo "step 6 SKIPPED: done sentinel present"
else
  wait_tunnel || { echo "GAVE UP (step 6)"; exit 1; }
  timeout 1200 python scripts/diag_sim_cache.py --pools 24576
  rc=$?
  echo "diag 24576 rc=$rc"
  [ "$rc" = 0 ] && touch "$S6"
fi

# DONE only when the profile re-measure — the round's #1 evidence item
# — is complete (rc 0 = every variant measured or terminally wedged)
# AND every step 3-6 left its sentinel (ADVICE #4: the old gate checked
# only profile_rc, so a failed step's artifact was silently lost for
# the round once the supervisor saw DONE and stopped relaunching).
# Step 4's committed artifact counts as its sentinel — it is evidence,
# not a /tmp marker.
missing=""
[ -f "$S3" ] || missing="$missing step3"
[ -f "$S4" ] || [ -f accuracy/e2e_real_jpeg_tpu.json ] || missing="$missing step4"
[ -f "$S5" ] || missing="$missing step5"
[ -f "$S6" ] || missing="$missing step6"
if [ "${profile_rc:-1}" = 0 ] && [ -z "$missing" ]; then
  echo "=== $(date) QUEUE V3 DONE ==="
elif [ "${profile_rc:-1}" = 0 ]; then
  echo "=== $(date) QUEUE V3 PARTIAL: steps without sentinels:${missing} — supervisor will relaunch (sentinels are keyed to HEAD=$SHA) ==="
  exit 1
else
  echo "=== $(date) QUEUE V3 PASS COMPLETE but profile incomplete (rc=${profile_rc:-unset}); supervisor will relaunch ==="
  exit 1
fi
