#!/bin/bash
# Round-4 hardware queue, third pass.
#
# Run 1 (tpu_queue_v2.sh) wedged mid-profile: the googlenet_bn variant's
# dispatch hung the tunnel at 10:27 UTC and the single-process profile
# script lost everything it had measured (salvaged by hand into
# profile/flagship.json from the log).  Changes here:
#   * bench.py runs FIRST — it is the round's single most valuable
#     artifact (headline + engine/batch extras + last_good cache) and is
#     already outage-proof;
#   * profile_flagship.py now defaults to a per-variant orchestrator
#     (child process per variant, hard timeout, artifact re-written after
#     every variant, resume skips what run 1 already measured) — a wedge
#     costs one variant, not the run;
#   * every step is gated on a fresh tunnel probe (wait_tunnel) so a
#     wedge in step N doesn't burn step N+1's timeout while down.
# Run detached:  setsid nohup scripts/tpu_queue_v3.sh &
# Log: /tmp/tpu_queue_v3.log
cd "$(dirname "$0")/.."
exec > /tmp/tpu_queue_v3.log 2>&1

probe() {
  timeout 100 python -c \
    'import jax,sys; sys.exit(jax.devices()[0].platform != "tpu")' \
    >/dev/null 2>&1
}

wait_tunnel() {
  # Up to ~1.6h per step; the tunnel recovers on its own (observed).
  for i in $(seq 1 30); do
    probe && { echo "tunnel up after probe $i ($(date))"; return 0; }
    echo "probe $i failed ($(date)); sleeping 180s"
    sleep 180
  done
  echo "tunnel still down after 30 probes"
  return 1
}

echo "=== $(date) waiting for tunnel ==="
wait_tunnel || { echo "GAVE UP"; exit 1; }

echo "=== $(date) 1/6 bench.py full ==="
# Budget > bench's own worst case (~3870s: probe phase up to 270s
# [120 + 30 retry-wait + 120] plus a 90s CPU probe on the degraded
# path, full child 3000s [two timed windows per row since the 08:04
# jitter finding], two smoke fallbacks 600s) so the outer timeout can
# never kill it mid-fallback and lose the degraded JSON.
timeout 4200 python bench.py > /tmp/bench_out.json
echo "bench rc=$?"
tail -c 1000 /tmp/bench_out.json

echo "=== $(date) 2/6 profile orchestrator (resumable, per-variant) ==="
wait_tunnel && timeout 4200 python scripts/profile_flagship.py --steps 10
echo "profile rc=$?"

echo "=== $(date) 3/6 tpu_pallas_check (parity + stretch, cached@16k) ==="
wait_tunnel && timeout 3300 python scripts/tpu_pallas_check.py --pool 4096 \
  --stretch 32768 --stretch-cached 16384 > /tmp/tpu_check_out.json
rc=$?
echo "tpu_pallas_check rc=$rc"
tail -c 2000 /tmp/tpu_check_out.json
if [ "$rc" = 0 ]; then python scripts/split_pallas_check.py; fi

echo "=== $(date) 4/6 TPU accuracy smoke (e2e real-JPEG on the chip) ==="
wait_tunnel && timeout 2400 env E2E_JAX_PLATFORM=default \
  python scripts/e2e_real_jpeg.py \
  --steps 200 --workdir /tmp/e2e_jpeg_tpu2 \
  --artifact accuracy/e2e_real_jpeg_tpu.json
echo "e2e tpu rc=$?"

echo "=== $(date) 5/6 diag_sim_cache 8192,16384 (safe pools) ==="
wait_tunnel && timeout 1800 python scripts/diag_sim_cache.py \
  --pools 8192,16384
echo "diag safe rc=$?"

echo "=== $(date) 6/6 diag_sim_cache 24576 (WEDGE-RISK, runs last) ==="
wait_tunnel && timeout 1200 python scripts/diag_sim_cache.py --pools 24576
echo "diag 24576 rc=$?"

echo "=== $(date) QUEUE V3 DONE ==="
