#!/usr/bin/env bash
# The one CI entry point: lint + the ROADMAP.md tier-1 test command.
#
#   scripts/ci.sh            # lint, then full tier-1 pytest
#   scripts/ci.sh --lint-only
#
# Keep the pytest invocation in sync with ROADMAP.md "Tier-1 verify" —
# the driver enforces that exact command; this script exists so humans
# and hooks run the same thing.

set -euo pipefail
cd "$(dirname "$0")/.."

echo "== lint: no bare print() in library code =="
python scripts/check_no_print.py

if [[ "${1:-}" == "--lint-only" ]]; then
    exit 0
fi

echo "== tier-1 tests (ROADMAP.md) =="
rm -f /tmp/_t1.log
# `|| rc=$?` keeps set -e from aborting on test failures so the
# DOTS_PASSED diagnostic still prints; the script's exit code is the
# pytest pipeline's.
rc=0
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee /tmp/_t1.log || rc=$?
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit "$rc"
