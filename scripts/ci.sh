#!/usr/bin/env bash
# The one CI entry point: lint + fault-injection smoke + the ROADMAP.md
# tier-1 test command.
#
#   scripts/ci.sh            # lint, smoke, then full tier-1 pytest
#   scripts/ci.sh --lint-only
#
# Keep the pytest invocation in sync with ROADMAP.md "Tier-1 verify" —
# the driver enforces that exact command; this script exists so humans
# and hooks run the same thing.

set -euo pipefail
cd "$(dirname "$0")/.."

echo "== lint: no bare print() in library code =="
python scripts/check_no_print.py

echo "== invariant staticcheck (docs/STATICCHECK.md) =="
# Jax-free by contract (the tool never imports jax; JAX_PLATFORMS may
# be anything): the full suite must run clean — every finding outside
# scripts/staticcheck_allow.json fails here, in milliseconds, instead
# of hours into a TPU window.
python scripts/bench_check.py --static
# The report artifact is itself a versioned contract: emit + revalidate.
SC_TMP=$(mktemp -d)
python -m npairloss_tpu staticcheck --out "$SC_TMP/staticcheck_report.json" >/dev/null
python - "$SC_TMP/staticcheck_report.json" <<'EOF'
import json, sys
sys.path.insert(0, ".")
from npairloss_tpu.analysis.report import validate_staticcheck_report
err = validate_staticcheck_report(json.load(open(sys.argv[1])))
assert err is None, f"staticcheck report invalid: {err}"
EOF
# Teeth probe: a seeded-violation fixture tree must be REFUSED — a
# gate that accepts everything is worse than no gate.
if python scripts/bench_check.py --static \
        tests/fixtures/staticcheck/unscoped_collective >/dev/null 2>&1; then
    echo "FAIL: staticcheck accepted a seeded violation (gate has no teeth)"
    exit 1
fi
rm -rf "$SC_TMP"
echo "staticcheck OK (suite clean, report valid, gate has teeth)"

if [[ "${1:-}" == "--lint-only" ]]; then
    exit 0
fi

echo "== fault-injection smoke (docs/RESILIENCE.md) =="
# Train with an injected transient snapshot fault (must be retried, not
# fatal), then SIGTERM a long run mid-train (must exit 75 with a
# committed emergency snapshot) and relaunch with --resume auto (must
# restore and finish).  Exercises the whole preemption-safety loop in
# two real processes, exactly as a supervisor would drive it.
smoke_dir=$(mktemp -d)
trap 'rm -rf "$smoke_dir"' EXIT
cat > "$smoke_dir/solver.prototxt" <<EOF
net: "examples/tiny_net.prototxt"
base_lr: 0.05
lr_policy: "fixed"
momentum: 0.9
max_iter: 1000000
display: 0
test_interval: 0
test_iter: 0
snapshot: 2
snapshot_prefix: "$smoke_dir/m_"
EOF

NPAIRLOSS_FAILPOINTS="snapshot.save.io:1" JAX_PLATFORMS=cpu \
    python -m npairloss_tpu train --solver "$smoke_dir/solver.prototxt" \
    --model mlp --synthetic --resume auto --max_iter 4 \
    > "$smoke_dir/run1.log" 2>&1 \
    || { echo "smoke: injected-fault run failed"; cat "$smoke_dir/run1.log"; exit 1; }
[[ -f "$smoke_dir/m_iter_4.ckpt/manifest.json" ]] \
    || { echo "smoke: snapshot 4 missing after injected fault"; exit 1; }

JAX_PLATFORMS=cpu python -m npairloss_tpu train \
    --solver "$smoke_dir/solver.prototxt" --model mlp --synthetic \
    --resume auto > "$smoke_dir/run2.log" 2>&1 &
pid=$!
for _ in $(seq 1 120); do  # wait for a post-resume snapshot, then preempt
    [[ -f "$smoke_dir/m_iter_6.ckpt/manifest.json" ]] && break
    # The run dying before its first snapshot is exactly the regression
    # this smoke exists to catch — surface its log instead of burning
    # the full wait and failing on the kill below.
    kill -0 "$pid" 2>/dev/null \
        || { echo "smoke: resumed run died early"; cat "$smoke_dir/run2.log"; exit 1; }
    sleep 1
done
kill -TERM "$pid" 2>/dev/null || true
rc=0; wait "$pid" || rc=$?
[[ "$rc" -eq 75 ]] \
    || { echo "smoke: expected exit 75 after SIGTERM, got $rc"; cat "$smoke_dir/run2.log"; exit 1; }
k=$(ls "$smoke_dir" | grep -oE 'm_iter_[0-9]+' | grep -oE '[0-9]+' | sort -n | tail -1)
JAX_PLATFORMS=cpu python -m npairloss_tpu train \
    --solver "$smoke_dir/solver.prototxt" --model mlp --synthetic \
    --resume auto --max_iter "$((k + 2))" > "$smoke_dir/run3.log" 2>&1 \
    || { echo "smoke: auto-resume relaunch failed"; cat "$smoke_dir/run3.log"; exit 1; }
grep -q "resuming from iteration" "$smoke_dir/run3.log" \
    || { echo "smoke: relaunch did not resume"; cat "$smoke_dir/run3.log"; exit 1; }
echo "fault-injection smoke OK (preempted at iter $k, resumed, finished)"

echo "== pipelined-solver smoke (docs/PIPELINE.md) =="
# Sync-free loop, 20 steps, with the strict sync guard armed: ANY host
# transfer on the step-loop thread between window boundaries raises
# SyncGuardViolation and fails the run — the counting-device_put-shim
# assertion of the no-mid-window-host-syncs contract.
cat > "$smoke_dir/p_solver.prototxt" <<EOF
net: "examples/tiny_net.prototxt"
base_lr: 0.05
lr_policy: "fixed"
momentum: 0.9
max_iter: 20
display: 5
test_interval: 0
test_iter: 0
snapshot: 0
snapshot_prefix: "$smoke_dir/p_"
EOF
NPAIRLOSS_PIPELINE_SYNC_GUARD=strict JAX_PLATFORMS=cpu \
    python -m npairloss_tpu train --solver "$smoke_dir/p_solver.prototxt" \
    --model mlp --synthetic --pipeline > "$smoke_dir/pipe.log" 2>&1 \
    || { echo "smoke: pipelined run failed (mid-window host sync?)"; cat "$smoke_dir/pipe.log"; exit 1; }
grep -q "iter 20 " "$smoke_dir/pipe.log" \
    || { echo "smoke: pipelined run missing display output"; cat "$smoke_dir/pipe.log"; exit 1; }
echo "pipelined smoke OK (20 steps, zero mid-window host syncs)"

echo "== compile-cache round-trip (persistent XLA cache) =="
# Two fresh processes compile the same step; the second must hit the
# cache: the cache dir gains no new entries and its step/compile span
# is the deserialization cost, not an XLA compile.
cache_dir="$smoke_dir/xla_cache"
for i in 1 2; do
    JAX_PLATFORMS=cpu python -m npairloss_tpu train \
        --solver "$smoke_dir/p_solver.prototxt" --model mlp --synthetic \
        --max_iter 2 --compile-cache "$cache_dir" \
        --trace-dir "$smoke_dir/trace$i" > "$smoke_dir/cc$i.log" 2>&1 \
        || { echo "smoke: compile-cache run $i failed"; cat "$smoke_dir/cc$i.log"; exit 1; }
    n=$(ls "$cache_dir" | grep -c -- '-cache$' || true)
    eval "entries$i=$n"
done
[[ "${entries1:-0}" -gt 0 ]] \
    || { echo "smoke: compile cache not populated"; exit 1; }
[[ "${entries2}" -eq "${entries1}" ]] \
    || { echo "smoke: second process MISSED the compile cache (${entries1} -> ${entries2} entries)"; exit 1; }
python - "$smoke_dir/trace1/trace.json" "$smoke_dir/trace2/trace.json" <<'EOF'
import json, sys
durs = []
for path in sys.argv[1:]:
    evs = json.load(open(path))["traceEvents"]
    compiles = [e for e in evs if e["name"] == "step/compile"]
    assert compiles, f"{path}: no step/compile span"
    durs.append(max(e["dur"] for e in compiles) / 1e3)
print(f"step/compile: cold {durs[0]:.0f} ms -> cached {durs[1]:.0f} ms")
EOF
echo "compile-cache round-trip OK (no new entries on the second process)"

echo "== serving smoke (docs/SERVING.md) =="
# Build a synthetic gallery index, serve it over stdin/JSONL with the
# strict compile guard armed, issue 100 queries, assert every answer
# (incl. exact self-match top-1), a p99 bound, and ZERO post-warmup
# compiles from the counted drain summary — then kill -TERM and assert
# the graceful-drain contract: exit 75, all admitted queries answered,
# telemetry flushed to disk.
serve_dir="$smoke_dir/serve"
mkdir -p "$serve_dir"
python - "$serve_dir" <<'EOF'
import json, sys
import numpy as np
d = sys.argv[1]
rng = np.random.default_rng(0)
emb = rng.standard_normal((512, 64)).astype(np.float32)
emb /= np.linalg.norm(emb, axis=1, keepdims=True)
np.save(d + "/g.emb.npy", emb)
np.save(d + "/g.labels.npy", np.repeat(np.arange(64), 8).astype(np.int32))
with open(d + "/queries.jsonl", "w") as f:
    for i in range(100):  # queries ARE gallery rows: top-1 must self-match
        f.write(json.dumps({"id": i, "embedding": emb[i].tolist()}) + "\n")
EOF
JAX_PLATFORMS=cpu python -m npairloss_tpu index \
    --emb "$serve_dir/g.emb.npy" --labels "$serve_dir/g.labels.npy" \
    --no-normalize --out "$serve_dir/g.gidx" > "$serve_dir/index.log" 2>&1 \
    || { echo "smoke: index build failed"; cat "$serve_dir/index.log"; exit 1; }
mkfifo "$serve_dir/in"
JAX_PLATFORMS=cpu NPAIRLOSS_SERVE_COMPILE_GUARD=strict \
    python -m npairloss_tpu serve --index "$serve_dir/g.gidx" \
    --top-k 5 --buckets 1,8,32 --telemetry-dir "$serve_dir/tel" \
    < "$serve_dir/in" > "$serve_dir/answers.jsonl" \
    2> "$serve_dir/serve.log" &
spid=$!
exec 3> "$serve_dir/in"  # hold the writer open: EOF must not end the run
cat "$serve_dir/queries.jsonl" >&3
for _ in $(seq 1 240); do  # wait for all 100 answers (warmup included)
    [[ "$(wc -l < "$serve_dir/answers.jsonl")" -ge 100 ]] && break
    kill -0 "$spid" 2>/dev/null \
        || { echo "smoke: server died mid-serve"; cat "$serve_dir/serve.log"; exit 1; }
    sleep 0.5
done
kill -TERM "$spid" 2>/dev/null || true
exec 3>&-
rc=0; wait "$spid" || rc=$?
[[ "$rc" -eq 75 ]] \
    || { echo "smoke: expected exit 75 after SIGTERM, got $rc"; cat "$serve_dir/serve.log"; exit 1; }
python - "$serve_dir" <<'EOF'
import json, sys
d = sys.argv[1]
lines = [json.loads(ln) for ln in open(d + "/answers.jsonl") if ln.strip()]
drain = lines[-1]
assert drain.get("event") == "serve_drain", f"last line is not the drain summary: {drain}"
answers = {a["id"]: a for a in lines[:-1]}
assert len(answers) == 100, f"expected 100 answers, got {len(answers)}"
for i in range(100):
    a = answers[i]
    assert "neighbors" in a, f"query {i} answered with an error: {a}"
    top1 = a["neighbors"][0]
    assert top1["row"] == i, f"query {i}: top-1 row {top1['row']} != self"
assert drain["answered"] == 100 and drain["errors"] == 0, drain
assert drain["compiles_after_warmup"] == 0, drain  # counted, not eyeballed
assert drain["p99_ms"] < 500.0, f"p99 {drain['p99_ms']} ms over bound"
tel = [json.loads(ln) for ln in open(d + "/tel/metrics.jsonl") if ln.strip()]
assert any(r.get("event") == "serve_drain" for r in tel), "drain summary not flushed to telemetry"
assert json.load(open(d + "/tel/manifest.json"))["config"]["serve"], "manifest missing"
print(f"serving smoke OK (100 answers, p99 {drain['p99_ms']:.1f} ms, "
      f"0 post-warmup compiles, clean drain)")
EOF

echo "== durable-ingest cold-restart smoke (docs/RESILIENCE.md §Durability) =="
# SIGKILL the serving tier mid-ingest (no handler, no drain), then
# cold-restart from the published artifacts + WAL alone: every ACKED
# ingest batch must survive, the jax-free gate must accept the real
# WAL at the acked watermark, and refuse a truncated-then-patched copy
# (clean record-boundary truncation — structurally valid, but the
# acked records are gone).
wd="$smoke_dir/waldrill"
mkdir -p "$wd/idx"
cp -r "$serve_dir/g.gidx" "$wd/idx/g_0000.gidx"
mkfifo "$wd/in"
JAX_PLATFORMS=cpu python -m npairloss_tpu serve \
    --index-prefix "$wd/idx/g_" --wal-dir "$wd/wal" \
    --wal-checkpoint-every 2 --top-k 5 --buckets 1,8 \
    < "$wd/in" > "$wd/answers.jsonl" 2> "$wd/serve1.log" &
wpid=$!
exec 4> "$wd/in"
python - <<'EOF' >&4  # three ingest batches (ids 1000+, seeded vectors)
import json
import numpy as np
rng = np.random.default_rng(7)
for b in range(3):
    v = rng.standard_normal((2, 64)).astype(np.float32)
    v /= np.linalg.norm(v, axis=1, keepdims=True)
    print(json.dumps({"id": f"ing-{b}", "ingest": {
        "ids": [1000 + 10 * b, 1001 + 10 * b],
        "labels": [7, 7], "embeddings": v.tolist()}}), flush=True)
EOF
for _ in $(seq 1 240); do  # wait for the three acks (warmup included)
    [[ "$(grep -c '"ingested"' "$wd/answers.jsonl" 2>/dev/null)" -ge 3 ]] && break
    kill -0 "$wpid" 2>/dev/null \
        || { echo "smoke: server died before acking ingest"; cat "$wd/serve1.log"; exit 1; }
    sleep 0.5
done
[[ "$(grep -c '"ingested"' "$wd/answers.jsonl")" -ge 3 ]] \
    || { echo "smoke: ingest never acked"; cat "$wd/serve1.log"; exit 1; }
# a fourth batch races the kill: it may or may not be acked — the
# durability claim is about ACKED batches only
python - <<'EOF' >&4
import json
import numpy as np
rng = np.random.default_rng(8)
v = rng.standard_normal((2, 64)).astype(np.float32)
v /= np.linalg.norm(v, axis=1, keepdims=True)
print(json.dumps({"id": "ing-race", "ingest": {
    "ids": [2000, 2001], "labels": [7, 7],
    "embeddings": v.tolist()}}), flush=True)
EOF
kill -KILL "$wpid" 2>/dev/null || true
rc=0; wait "$wpid" || rc=$?
exec 4>&-
[[ "$rc" -ne 75 ]] \
    || { echo "smoke: SIGKILL ran the drain handler (exit 75)?"; exit 1; }
wm=$(python - "$wd/answers.jsonl" <<'EOF'
import json, sys
seqs = []
for line in open(sys.argv[1]):
    try:
        r = json.loads(line)
    except ValueError:
        continue  # torn tail — the writer was SIGKILLed
    if isinstance(r, dict) and r.get("ingested"):
        seqs.append(int(r["seq"]))
print(max(seqs) if seqs else 0)
EOF
)
[[ "$wm" -ge 3 ]] || { echo "smoke: acked watermark $wm < 3"; exit 1; }
python scripts/bench_check.py --wal "$wd/wal" --wal-watermark "$wm" \
    || { echo "smoke: gate refused the REAL crashed WAL at watermark $wm"; exit 1; }
python - "$wd/wal" "$wd/walcopy" "$wm" <<'EOF'
import os, shutil, struct, sys
src, dst, wm = sys.argv[1], sys.argv[2], int(sys.argv[3])
shutil.copytree(src, dst)
segs = sorted(n for n in os.listdir(dst) if n.endswith(".seg"))
last = os.path.join(dst, segs[-1])
with open(last, "rb") as f:
    data = f.read()
H = struct.Struct("<II")
ends, off = [0], 0
while off + H.size <= len(data):
    ln, _ = H.unpack_from(data, off)
    if off + H.size + ln > len(data):
        break  # torn tail from the kill — drop it too
    off += H.size + ln
    ends.append(off)
keep = wm - 1  # one ACKED record short of the watermark
assert len(ends) > keep, f"segment holds {len(ends) - 1} record(s)"
with open(last, "r+b") as f:
    f.truncate(ends[keep])
EOF
if python scripts/bench_check.py --wal "$wd/walcopy" --wal-watermark "$wm" \
    > "$wd/tamper.log" 2>&1; then
    echo "smoke: gate ACCEPTED a truncated-then-patched WAL copy"
    cat "$wd/tamper.log"; exit 1
fi
grep -q "acknowledged watermark" "$wd/tamper.log" \
    || { echo "smoke: tampered WAL refused for the wrong reason"; cat "$wd/tamper.log"; exit 1; }
# cold restart: recovery replays the WAL tail above the newest
# checkpoint; the first acked batch's vector must retrieve ITSELF.
mkfifo "$wd/in2"
JAX_PLATFORMS=cpu python -m npairloss_tpu serve \
    --index-prefix "$wd/idx/g_" --wal-dir "$wd/wal" \
    --wal-checkpoint-every 2 --top-k 5 --buckets 1,8 \
    < "$wd/in2" > "$wd/answers2.jsonl" 2> "$wd/serve2.log" &
wpid=$!
exec 4> "$wd/in2"
python - <<'EOF' >&4
import json
import numpy as np
rng = np.random.default_rng(7)  # batch 0's vectors, regenerated
v = rng.standard_normal((2, 64)).astype(np.float32)
v /= np.linalg.norm(v, axis=1, keepdims=True)
print(json.dumps({"id": "q-replay", "embedding": v[0].tolist()}),
      flush=True)
EOF
for _ in $(seq 1 240); do
    [[ -s "$wd/answers2.jsonl" ]] && break
    kill -0 "$wpid" 2>/dev/null \
        || { echo "smoke: restarted server died"; cat "$wd/serve2.log"; exit 1; }
    sleep 0.5
done
kill -TERM "$wpid" 2>/dev/null || true
exec 4>&-
rc=0; wait "$wpid" || rc=$?
[[ "$rc" -eq 75 ]] \
    || { echo "smoke: restart drain expected exit 75, got $rc"; cat "$wd/serve2.log"; exit 1; }
grep -q "wal: recovered" "$wd/serve2.log" \
    || { echo "smoke: restart did not run WAL recovery"; cat "$wd/serve2.log"; exit 1; }
ls "$wd"/idx/g_w*.gidx > /dev/null 2>&1 \
    || { echo "smoke: no ingest checkpoint published under the prefix"; ls "$wd/idx"; exit 1; }
python - "$wd/answers2.jsonl" <<'EOF'
import json, sys
lines = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
drain = lines[-1]
assert drain.get("event") == "serve_drain", f"no drain summary: {drain}"
ans = next(a for a in lines if a.get("id") == "q-replay")
assert "neighbors" in ans, f"replay query errored: {ans}"
top1 = ans["neighbors"][0]
assert top1.get("gallery_id") == 1000, \
    f"acked ingest vector did not survive the crash: top-1 {top1}"
ing = drain.get("ingest") or {}
wal = ing.get("wal") or {}
print(f"cold-restart smoke OK (watermark {ing.get('watermark')}, "
      f"checkpoint {ing.get('checkpoint_watermark')}, "
      f"torn_records {wal.get('torn_records')})")
EOF

echo "== perf observatory smoke (docs/OBSERVABILITY.md §Perf) =="
# A 10-step prof run on the tiny trunk must produce a schema-valid
# report whose step-time decomposition reconciles to wall time, and
# the offline bench gate must pass on the committed BENCH_r* trajectory
# (it fails CI on a regressed one — tests/test_perf.py pins that).
prof_dir="$smoke_dir/prof"
JAX_PLATFORMS=cpu python -m npairloss_tpu prof --step train \
    --model mlp --image 32 --batch 16 --steps 10 --out "$prof_dir" \
    > "$prof_dir.log" 2>&1 \
    || { echo "smoke: prof run failed"; cat "$prof_dir.log"; exit 1; }
python - "$prof_dir/perf_report.json" <<'EOF'
import json, sys
from npairloss_tpu.obs.perf import validate_report
report = json.load(open(sys.argv[1]))
# validate_report IS the contract (bound enum, region keys, the
# reconciliation invariant) — the smoke only adds what it can't know:
# that THIS run produced a non-degenerate report.
err = validate_report(report)
assert err is None, f"schema-invalid prof report: {err}"
assert report["regions"], "prof report has no regions"
assert "decomposition" in report, "prof report has no decomposition"
dec = report["decomposition"]
print(f"prof smoke OK ({len(report['regions'])} regions, wall "
      f"{dec['wall_ms']:.0f} ms, unattributed {dec['unattributed_ms']:.0f} ms)")
EOF
python scripts/bench_check.py --offline \
    || { echo "smoke: offline bench gate FAILED"; exit 1; }

echo "== pallas stem interpret smoke (ops/pallas_stem.py) =="
# The fused stem kernels must hold interpret-mode parity against the
# XLA references — forward and backward — on every box that runs CI
# (the full ragged-tile matrix lives in tests/test_pallas_stem.py).
JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np, jax, jax.numpy as jnp
from npairloss_tpu.models.layers import local_response_norm
from npairloss_tpu.ops import pallas_stem as ps
x = jnp.asarray(np.random.default_rng(0).standard_normal(
    (2, 6, 6, 24)).astype(np.float32))
b = jnp.asarray(np.random.default_rng(1).standard_normal(
    (24,)).astype(np.float32))
np.testing.assert_allclose(np.asarray(ps.fused_lrn(x)),
                           np.asarray(local_response_norm(x)), atol=1e-6)
g1 = jax.grad(lambda v: ps.fused_lrn(v).sum())(x)
g2 = jax.grad(lambda v: local_response_norm(v).sum())(x)
np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-5)
np.testing.assert_allclose(np.asarray(ps.fused_bias_relu(x, b)),
                           np.asarray(jnp.maximum(x + b, 0)), atol=1e-6)
np.testing.assert_allclose(
    np.asarray(ps.fused_bias_relu_pool(x, b)),
    np.asarray(ps._reference_bias_relu_pool(x, b, 3, 2)), atol=1e-6)
print("pallas stem interpret smoke OK (lrn fwd+bwd, bias_relu, pool)")
EOF

echo "== pallas probe kernel interpret smoke (ops/pallas_ivf.py) =="
# The fused IVF probe kernel (gather + score + running top-k in one
# VMEM pass) must hold interpret-mode parity against the lax.scan
# baseline AND the brute-force recall gate on every box that runs CI
# (the full scoring x geometry matrix lives in tests/test_pallas_ivf.py).
JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
from npairloss_tpu.serve import EngineConfig, GalleryIndex, QueryEngine
from npairloss_tpu.serve.ivf import IVFIndex, topk_recall
rng = np.random.default_rng(0)
cents = rng.standard_normal((8, 24)).astype(np.float32)
cents /= np.linalg.norm(cents, axis=1, keepdims=True)
emb = np.repeat(cents, 25, axis=0) + 0.1 * rng.standard_normal(
    (200, 24)).astype(np.float32)
emb /= np.linalg.norm(emb, axis=1, keepdims=True)
lab = np.repeat(np.arange(8), 25).astype(np.int32)
q = emb[rng.choice(200, 8, replace=False)]
ivf = IVFIndex.build_ivf(emb, lab, normalize=False, clusters=6,
                         train_size=None)
out = {}
for impl in ("scan", "fused"):
    eng = QueryEngine(ivf, EngineConfig(top_k=10, buckets=(8,), probes=3,
                                        probe_impl=impl))
    out[impl] = eng.query(q, normalize=False)
np.testing.assert_allclose(out["fused"]["scores"], out["scan"]["scores"],
                           rtol=1e-6, atol=1e-6)
oracle = QueryEngine(GalleryIndex.build(emb, lab, normalize=False),
                     EngineConfig(top_k=10, buckets=(8,)))
exact = oracle.query(q, normalize=False)["rows"]
for k in (1, 10):
    rf = topk_recall(out["fused"]["rows"], exact, k=k)
    rs = topk_recall(out["scan"]["rows"], exact, k=k)
    assert rf == rs, (k, rf, rs)
assert topk_recall(out["fused"]["rows"], exact, k=1) >= 0.95
print("pallas probe kernel interpret smoke OK (fused==scan to 1e-6, "
      "recall@{1,10} identical, recall@1 >= 0.95)")
EOF

echo "== precision-policy prof guard (models/precision.py) =="
# The default (mxu) flagship's compute must live in the conv/inception
# gemms, not the LRN tail: prof the default-policy flagship and assert
# the top trunk region by flops share is a conv/inception region, the
# lrn region exists (the named_scope attribution is wired), and lrn
# stays under 1% of step flops.  Catches a policy regression that
# silently reverts the trunk to an elementwise-dominated step.
pol_dir="$smoke_dir/prof_policy"
JAX_PLATFORMS=cpu python -m npairloss_tpu prof --step train \
    --model flagship --precision mxu --batch 4 --image 32 --steps 2 \
    --region-depth 2 --out "$pol_dir" > "$pol_dir.log" 2>&1 \
    || { echo "smoke: policy prof run failed"; cat "$pol_dir.log"; exit 1; }
python - "$pol_dir/perf_report.json" <<'EOF'
import json, sys
report = json.load(open(sys.argv[1]))
assert report.get("policy") == "mxu", report.get("policy")
trunk = [r for r in report["regions"]
         if r["region"].startswith("GoogLeNetEmbedding/")]
assert trunk, "no trunk regions attributed"
lrn = [r for r in trunk if r["region"].endswith("/lrn")]
assert lrn, "lrn region missing — named_scope attribution broken"
top = max(trunk, key=lambda r: r["pct_flops"])
assert not top["region"].endswith("/lrn"), \
    f"trunk's top region is the LRN tail: {top}"
assert lrn[0]["pct_flops"] < 1.0, f"lrn flops share grew: {lrn[0]}"
print(f"policy prof guard OK (top trunk region {top['region']} "
      f"{top['pct_flops']:.1f}% flops; lrn {lrn[0]['pct_flops']:.2f}%, "
      f"bound {lrn[0]['bound']})")
EOF

echo "== fleet observatory smoke (docs/OBSERVABILITY.md §Fleet) =="
# Two cooperating CPU processes train a short run under the strict sync
# guard, each writing its own rank-stamped telemetry stream into ONE
# shared run dir; then `prof --fleet` must aggregate them into a
# schema-valid npairloss-fleet-report-v1 with both ranks present, skew
# computed, and ZERO unattributed collective bytes, and bench_check
# must accept the report (it refuses per-rank step-count disagreement).
#
# Real multi-controller (jax.distributed) CPU collectives are an env
# capability — some jaxlib CPU backends form the cluster and then
# refuse to EXECUTE a cross-process computation.  Probe first
# (tests/mp_probe.py); fall back to the declared-rank harness mode
# (NPAIRLOSS_FLEET_PROCESS=<rank>/<count>) where the env can't, so the
# whole fleet observability path is smoked on every box either way.
fleet_dir="$smoke_dir/fleet"
mkdir -p "$fleet_dir"
cat > "$fleet_dir/solver.prototxt" <<EOF
net: "examples/tiny_net.prototxt"
base_lr: 0.05
lr_policy: "fixed"
momentum: 0.9
max_iter: 8
display: 4
test_interval: 0
test_iter: 0
snapshot: 0
snapshot_prefix: "$fleet_dir/f_"
EOF
probe_port=$(python -c 'import socket; s=socket.socket(); s.bind(("localhost",0)); print(s.getsockname()[1])')
probe_ok=1
for i in 0 1; do
    JAX_PLATFORMS=cpu XLA_FLAGS= PYTHONPATH=. \
        python tests/mp_probe.py "$i" 2 "$probe_port" \
        > "$fleet_dir/probe$i.log" 2>&1 &
    eval "ppid$i=\$!"
done
wait "$ppid0" || probe_ok=0
wait "$ppid1" || probe_ok=0
grep -q PROBE_OK "$fleet_dir/probe0.log" || probe_ok=0

if [[ "$probe_ok" -eq 1 ]]; then
    echo "fleet smoke: real jax.distributed 2-process mode"
    mp_port=$(python -c 'import socket; s=socket.socket(); s.bind(("localhost",0)); print(s.getsockname()[1])')
    for i in 0 1; do
        JAX_PLATFORMS=cpu XLA_FLAGS= NPAIRLOSS_PIPELINE_SYNC_GUARD=strict \
            python -m npairloss_tpu train --solver "$fleet_dir/solver.prototxt" \
            --model mlp --synthetic --engine ring --pipeline \
            --coordinator "localhost:$mp_port" --num-processes 2 --process-id "$i" \
            --telemetry-dir "$fleet_dir/run" > "$fleet_dir/train$i.log" 2>&1 &
        eval "tpid$i=\$!"
    done
else
    echo "fleet smoke: declared-rank harness mode (env cannot execute" \
         "multi-process CPU collectives: $(tail -1 "$fleet_dir/probe0.log" | cut -c1-120))"
    for i in 0 1; do
        JAX_PLATFORMS=cpu NPAIRLOSS_FLEET_PROCESS="$i/2" \
            NPAIRLOSS_PIPELINE_SYNC_GUARD=strict \
            python -m npairloss_tpu train --solver "$fleet_dir/solver.prototxt" \
            --model mlp --synthetic --engine ring --mesh 1 --pipeline \
            --telemetry-dir "$fleet_dir/run" > "$fleet_dir/train$i.log" 2>&1 &
        eval "tpid$i=\$!"
    done
fi
for i in 0 1; do
    eval "pid=\$tpid$i"
    wait "$pid" \
        || { echo "fleet smoke: rank $i training failed"; cat "$fleet_dir/train$i.log"; exit 1; }
done
for i in 0 1; do
    [[ -f "$fleet_dir/run/telemetry.r$i.jsonl" ]] \
        || { echo "fleet smoke: rank $i left no stream"; ls "$fleet_dir/run"; exit 1; }
done
JAX_PLATFORMS=cpu python -m npairloss_tpu prof --fleet "$fleet_dir/run" \
    > "$fleet_dir/prof.log" 2>&1 \
    || { echo "fleet smoke: prof --fleet failed"; cat "$fleet_dir/prof.log"; exit 1; }
python - "$fleet_dir/run/fleet_report.json" <<'EOF'
import json, sys
rep = json.load(open(sys.argv[1]))
assert rep["ranks_present"] == [0, 1], rep["ranks_present"]
assert rep["skew"]["steps_analyzed"] > 0, rep["skew"]
assert rep["skew"]["slowest"]["rank"] in (0, 1), rep["skew"]
comms = rep["comms"]
assert comms["available"], comms
assert comms["unattributed_bytes"] == 0, comms
assert all(k["claimed"] for k in comms["kinds"]), comms
counts = {r["rank"]: r["steps"] for r in rep["ranks"]}
print(f"fleet smoke OK (ranks {sorted(counts)}, {counts[0]} steps each, "
      f"dispatch skew p50 {rep['skew']['dispatch_spread_ms_p50']} ms, "
      f"slowest rank {rep['skew']['slowest']['rank']}, "
      f"0 unattributed collective bytes)")
EOF
python scripts/bench_check.py --fleet-report "$fleet_dir/run/fleet_report.json" \
    || { echo "fleet smoke: bench_check refused the fleet report"; exit 1; }

echo "== pod-scale multi-controller smoke (docs/DISTRIBUTED.md) =="
# One global batch, three ways: a single-process virtual 2-device mesh
# BASELINE, then TWO controller processes covering the same global
# mesh — real jax.distributed where the capability probe passed (each
# process owning 1 device, per-process disjoint data shards), else the
# declared-rank harness (NPAIRLOSS_FLEET_PROCESS, each process running
# the full virtual mesh on the same global batch).  The contract: the
# 2-process run produces byte-identical metric-key streams and
# bit-identical final params vs the baseline, for BOTH the dense and
# ring engines, under the strict sync guard; then `prof --fleet` over
# the shared run dir must reconcile with ZERO unattributed collective
# bytes and the DCN link, gated by bench_check --expect-link dcn.
# (Reuses $probe_ok from the fleet smoke's capability probe.)
pod_dir="$smoke_dir/pod"
mkdir -p "$pod_dir"
cat > "$pod_dir/solver.prototxt" <<EOF
net: "examples/tiny_net.prototxt"
base_lr: 0.05
lr_policy: "fixed"
momentum: 0.9
max_iter: 6
display: 3
test_interval: 0
test_iter: 0
snapshot: 6
snapshot_prefix: "$pod_dir/unused_"
EOF
for eng in dense ring; do
    # Baseline: one process, the whole 2-device virtual mesh.
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \
        NPAIRLOSS_PIPELINE_SYNC_GUARD=strict \
        python -m npairloss_tpu train --solver "$pod_dir/solver.prototxt" \
        --model mlp --synthetic --engine "$eng" --mesh 2 --pipeline \
        --snapshot_prefix "$pod_dir/base_${eng}_s_" \
        --telemetry-dir "$pod_dir/base_$eng" \
        > "$pod_dir/base_$eng.log" 2>&1 \
        || { echo "pod smoke: baseline $eng failed"; cat "$pod_dir/base_$eng.log"; exit 1; }
    if [[ "$probe_ok" -eq 1 ]]; then
        pod_mode=real
        pod_port=$(python -c 'import socket; s=socket.socket(); s.bind(("localhost",0)); print(s.getsockname()[1])')
        for i in 0 1; do
            JAX_PLATFORMS=cpu XLA_FLAGS= NPAIRLOSS_PIPELINE_SYNC_GUARD=strict \
                python -m npairloss_tpu train --solver "$pod_dir/solver.prototxt" \
                --model mlp --synthetic --engine "$eng" --pipeline \
                --coordinator "localhost:$pod_port" --num-processes 2 --process-id "$i" \
                --snapshot_prefix "$pod_dir/pod_${eng}_s_" \
                --telemetry-dir "$pod_dir/pod_$eng" \
                > "$pod_dir/pod_${eng}_$i.log" 2>&1 &
            eval "podpid$i=\$!"
        done
    else
        pod_mode=harness
        for i in 0 1; do
            JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \
                NPAIRLOSS_FLEET_PROCESS="$i/2" NPAIRLOSS_PIPELINE_SYNC_GUARD=strict \
                python -m npairloss_tpu train --solver "$pod_dir/solver.prototxt" \
                --model mlp --synthetic --engine "$eng" --mesh 2 --pipeline \
                --snapshot_prefix "$pod_dir/pod_${eng}_r${i}_s_" \
                --telemetry-dir "$pod_dir/pod_$eng" \
                > "$pod_dir/pod_${eng}_$i.log" 2>&1 &
            eval "podpid$i=\$!"
        done
    fi
    for i in 0 1; do
        eval "pid=\$podpid$i"
        wait "$pid" \
            || { echo "pod smoke: $eng rank $i failed"; cat "$pod_dir/pod_${eng}_$i.log"; exit 1; }
    done
    python - "$pod_dir" "$eng" "$pod_mode" <<'EOF'
import json, sys

d, eng, mode = sys.argv[1], sys.argv[2], sys.argv[3]

# -- params: bit-identical final snapshots, proven from the commit
# manifests' per-leaf CRC-32s (identical bits <=> identical checksums)
# — no backend, no device-mesh coupling to how the snapshot was saved.
def arrays(path):
    m = json.load(open(path + "/manifest.json"))
    assert m["step"] == 6, m["step"]
    return m["arrays"]

base = arrays(f"{d}/base_{eng}_s_iter_6.ckpt")
assert base, "baseline snapshot manifest empty"
pods = ([f"{d}/pod_{eng}_s_iter_6.ckpt"] if mode == "real" else
        [f"{d}/pod_{eng}_r{i}_s_iter_6.ckpt" for i in (0, 1)])
for p in pods:
    got = arrays(p)
    assert got == base, (
        f"{eng}: params differ vs {p}: "
        + str([k for k in base if got.get(k) != base[k]][:4]))

# -- streams: byte-identical metric-key streams -------------------------
DROP = {"run_id", "wall_time", "process_index", "process_count",
        "local_device_ids"}

def rows(path):
    out = []
    for ln in open(path):
        if not ln.strip():
            continue
        r = json.loads(ln)
        out.append((r.get("phase"), r.get("step"),
                    tuple(sorted((k, v) for k, v in r.items()
                                 if k not in DROP and k not in
                                 ("phase", "step")))))
    return out

want = rows(f"{d}/base_{eng}/metrics.jsonl")
assert want, "baseline stream empty"
for i in (0, 1):
    got = rows(f"{d}/pod_{eng}/telemetry.r{i}.jsonl")
    assert got == want, (
        f"{eng}: rank {i} stream diverges from the single-process "
        f"baseline ({len(got)} vs {len(want)} rows)")
print(f"pod smoke [{mode}] {eng}: params bit-identical, "
      f"{len(want)}-row metric streams byte-identical across "
      "baseline + both ranks")
EOF
done
# The shared run dir of the LAST engine (ring) feeds the fleet gate:
# both ranks present, zero unattributed bytes, DCN link selected.
JAX_PLATFORMS=cpu python -m npairloss_tpu prof --fleet "$pod_dir/pod_ring" \
    > "$pod_dir/prof.log" 2>&1 \
    || { echo "pod smoke: prof --fleet failed"; cat "$pod_dir/prof.log"; exit 1; }
python scripts/bench_check.py --fleet-report "$pod_dir/pod_ring/fleet_report.json" \
    --expect-link dcn \
    || { echo "pod smoke: fleet report not valid/DCN"; exit 1; }
python - "$pod_dir/pod_ring" <<'EOF'
import glob, json, sys
d = sys.argv[1]
man = json.load(open(sorted(glob.glob(d + "/manifest.r0.json"))[0]))
plan = man["config"]["engine_plan"]
assert plan and plan["link"] == "dcn" and plan["hosts"] == 2, plan
part = man["config"]["partition"]
assert part["unmatched"] == 0 and part["noop_rules"] == [], part
print(f"pod smoke manifest OK (engine_plan link={plan['link']}, "
      f"hosts={plan['hosts']}, partition {part['leaves']} leaves / "
      f"{part['sharded_leaves']} sharded)")
EOF
# --engine auto + --dump-partitions preflight: the resolved table must
# print (no zero-match rules on the default table) and the manifest
# must stamp the auto plan.
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \
    python -m npairloss_tpu train --solver "$pod_dir/solver.prototxt" \
    --model mlp --synthetic --engine auto --mesh 2 --max_iter 0 \
    --dump-partitions --telemetry-dir "$pod_dir/auto" \
    > "$pod_dir/auto.log" 2>&1 \
    || { echo "pod smoke: --engine auto preflight failed"; cat "$pod_dir/auto.log"; exit 1; }
grep -q "partition rules (first match wins):" "$pod_dir/auto.log" \
    || { echo "pod smoke: --dump-partitions printed no table"; cat "$pod_dir/auto.log"; exit 1; }
python - "$pod_dir/auto/manifest.json" <<'EOF'
import json, sys
cfg = json.load(open(sys.argv[1]))["config"]
plan = cfg["engine_plan"]
assert plan["requested"] == "auto" and plan["engine"] in ("dense", "ring")
assert cfg["engine"] == plan["engine"], (cfg["engine"], plan["engine"])
print(f"pod smoke auto OK (auto -> {plan['engine']}: "
      + plan["reason"][:70] + "...)")
EOF

echo "== live observatory smoke (docs/OBSERVABILITY.md §Live) =="
# The alert lifecycle end-to-end: a CLEAN serve run under an SLO config
# fires ZERO alerts; a run with the serve.latency failpoint armed fires
# the p99 alert and RESOLVES it once the injected fault clears; the
# jax-free bench_check --alerts gate accepts that log and refuses one
# holding an unresolved critical alert (and a schema violation).
live_dir="$smoke_dir/live"
mkdir -p "$live_dir"
python - "$live_dir" <<'EOF'
import json, sys
import numpy as np
d = sys.argv[1]
rng = np.random.default_rng(0)
emb = rng.standard_normal((256, 32)).astype(np.float32)
emb /= np.linalg.norm(emb, axis=1, keepdims=True)
np.save(d + "/g.emb.npy", emb)
np.save(d + "/g.labels.npy", (np.arange(256) % 16).astype(np.int32))
with open(d + "/queries.jsonl", "w") as f:
    for i in range(40):
        f.write(json.dumps({"id": i, "embedding": emb[i].tolist()}) + "\n")
json.dump({"slos": [{
    "name": "p99", "metric": "serve_p99_ms", "op": "<=", "target": 150.0,
    "window_s": 2.0, "burn_threshold": 0.5, "min_samples": 1,
    "severity": "critical"}]}, open(d + "/slo.json", "w"))
EOF
JAX_PLATFORMS=cpu python -m npairloss_tpu index \
    --emb "$live_dir/g.emb.npy" --labels "$live_dir/g.labels.npy" \
    --no-normalize --out "$live_dir/g.gidx" > "$live_dir/index.log" 2>&1 \
    || { echo "live smoke: index build failed"; cat "$live_dir/index.log"; exit 1; }

run_live_serve() {  # $1 = telemetry dir, $2 = extra env (failpoints or "")
    local tel="$1" fp="$2"
    mkfifo "$live_dir/in.$$"
    env JAX_PLATFORMS=cpu NPAIRLOSS_FAILPOINTS="$fp" \
        python -m npairloss_tpu serve --index "$live_dir/g.gidx" \
        --top-k 3 --buckets 1 --deadline-ms 1 --metrics-window 4 \
        --telemetry-dir "$tel" --live-obs --slo-config "$live_dir/slo.json" \
        --slo-tick 0.2 < "$live_dir/in.$$" > "$tel.answers.jsonl" \
        2> "$tel.log" &
    lpid=$!
    exec 4> "$live_dir/in.$$"
    # Throttled feed: a 40-query burst through single-query buckets
    # would queue real ~100ms tails on a loaded CPU box — the CLEAN
    # run must owe its p99 to dispatch alone, so the injected 250ms
    # fault is the ONLY thing that can cross the 150ms bar.
    head -20 "$live_dir/queries.jsonl" | while IFS= read -r ln; do
        printf '%s\n' "$ln" >&4; sleep 0.05
    done
    sleep 3   # failpoint burst (if armed) fires + the alert with it
    tail -20 "$live_dir/queries.jsonl" | while IFS= read -r ln; do
        printf '%s\n' "$ln" >&4; sleep 0.05
    done
    sleep 3   # fault cleared: fast windows age the burn out -> resolve
    kill -TERM "$lpid" 2>/dev/null || true
    exec 4>&-
    rc=0; wait "$lpid" || rc=$?
    rm -f "$live_dir/in.$$"
    [[ "$rc" -eq 75 ]] \
        || { echo "live smoke: expected exit 75, got $rc"; cat "$tel.log"; exit 1; }
}

run_live_serve "$live_dir/clean" ""
[[ ! -s "$live_dir/clean/alerts.jsonl" ]] \
    || { echo "live smoke: CLEAN run fired alerts (false positives)"; cat "$live_dir/clean/alerts.jsonl"; exit 1; }
python scripts/bench_check.py --alerts "$live_dir/clean/alerts.jsonl" \
    || { echo "live smoke: gate refused the empty clean log"; exit 1; }

run_live_serve "$live_dir/fault" "serve.latency:6"
python - "$live_dir/fault/alerts.jsonl" <<'EOF'
import json, sys
records = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
states = [r["state"] for r in records]
assert "firing" in states, f"latency failpoint never fired the p99 alert: {records}"
assert states[-1] == "resolved", f"alert did not resolve after the fault cleared: {states}"
assert all(r["slo"] == "p99" and r["severity"] == "critical" for r in records)
fired = [r for r in records if r["state"] == "firing"]
print(f"live smoke: p99 alert fired {len(fired)}x and resolved "
      f"(worst window in message: {fired[0]['message'].split('worst ')[-1]}")
EOF
python scripts/bench_check.py --alerts "$live_dir/fault/alerts.jsonl" \
    || { echo "live smoke: gate refused the resolved fire->resolve log"; exit 1; }
# gate teeth: an unresolved critical (truncate the resolve off) and a
# schema violation must both be refused
head -1 "$live_dir/fault/alerts.jsonl" > "$live_dir/unresolved.jsonl"
python scripts/bench_check.py --alerts "$live_dir/unresolved.jsonl" > /dev/null \
    && { echo "live smoke: gate ACCEPTED an unresolved critical alert"; exit 1; }
sed 's/npairloss-alerts-v1/npairloss-alerts-v0/' \
    "$live_dir/fault/alerts.jsonl" > "$live_dir/badschema.jsonl"
python scripts/bench_check.py --alerts "$live_dir/badschema.jsonl" > /dev/null \
    && { echo "live smoke: gate ACCEPTED a schema violation"; exit 1; }
# the offline feed agrees: watch over the fault run's telemetry must
# reproduce a fire->resolve sequence through the SAME engine
JAX_PLATFORMS=cpu python -m npairloss_tpu watch "$live_dir/fault" \
    --slo-config "$live_dir/slo.json" > "$live_dir/watch.log" 2>&1 \
    || { echo "live smoke: watch refused the run dir"; cat "$live_dir/watch.log"; exit 1; }
python - "$live_dir/fault/alerts.watch.jsonl" <<'EOF'
import json, sys
states = [json.loads(ln)["state"] for ln in open(sys.argv[1]) if ln.strip()]
assert "firing" in states and states[-1] == "resolved", states
print(f"watch feed agrees: {states}")
EOF
echo "live observatory smoke OK (0 false positives, fire->resolve, gate teeth, watch agreement)"

echo "== query tracing smoke (docs/OBSERVABILITY.md §Query tracing) =="
# Per-query stage attribution end-to-end: a throttled serve run with
# the serve.latency failpoint armed must retain SLO-violating
# exemplars whose dominant stage is DISPATCH (the feed is slower than
# the 0.25s stall, so each faulted query pays the stall as dispatch
# self-time and no queue builds behind it — the gameday covers the
# saturated case where the same fault shows up as queue_wait); the
# jax-free bench_check --qtrace gate accepts the real artifact and
# refuses doctored copies; the merged timeline carries the exemplar
# span trees next to the alert instants.
qt_dir="$smoke_dir/qtrace"
mkdir -p "$qt_dir"
mkfifo "$qt_dir/in.$$"
env JAX_PLATFORMS=cpu NPAIRLOSS_FAILPOINTS="serve.latency:6@4" \
    python -m npairloss_tpu serve --index "$live_dir/g.gidx" \
    --top-k 3 --buckets 1 --deadline-ms 1 --metrics-window 4 \
    --telemetry-dir "$qt_dir/tel" --live-obs \
    --slo-config "$live_dir/slo.json" --slo-tick 0.2 \
    --qtrace --qtrace-slo-ms 150 \
    < "$qt_dir/in.$$" > "$qt_dir/answers.jsonl" 2> "$qt_dir/serve.log" &
qtpid=$!
exec 8> "$qt_dir/in.$$"
# Readiness probe: the FIFO buffers lines while the server is still
# importing/warming, and a buffered backlog arrives as a BURST whose
# tail pays queue_wait, not dispatch — the very confound this smoke
# must exclude.  One query, wait for its answer, then throttle the
# rest; the @4 delay keeps the stalls clear of the probe boundary.
head -1 "$live_dir/queries.jsonl" >&8
for _ in $(seq 1 120); do
    [[ -s "$qt_dir/answers.jsonl" ]] && break
    sleep 0.5
done
[[ -s "$qt_dir/answers.jsonl" ]] \
    || { echo "qtrace smoke: server never answered the probe"; cat "$qt_dir/serve.log"; exit 1; }
sed -n '2,24p' "$live_dir/queries.jsonl" | while IFS= read -r ln; do
    printf '%s\n' "$ln" >&8; sleep 0.3
done
sleep 3   # fault long gone: fast windows age the p99 burn out -> resolve
kill -TERM "$qtpid" 2>/dev/null || true
exec 8>&-
rc=0; wait "$qtpid" || rc=$?
rm -f "$qt_dir/in.$$"
[[ "$rc" -eq 75 ]] \
    || { echo "qtrace smoke: expected exit 75, got $rc"; cat "$qt_dir/serve.log"; exit 1; }
python - "$qt_dir" <<'EOF'
import json, sys
d = sys.argv[1]
rep = json.load(open(d + "/tel/qtrace.json"))
t, b = rep["totals"], rep["budget"]
assert t["queries"] == 24 and t["errors"] == 0, t
assert t["violations"] >= 1, f"no SLO violation retained: {t}"
slo_ex = [ex for ex in rep["exemplars"] if ex["reason"] == "slo"]
assert slo_ex, "fault run retained no SLO exemplars"
assert b["dominant"] == "dispatch", \
    f"injected dispatch stall attributed to {b['dominant']!r}: {b}"
for ex in slo_ex:
    stages = {e["name"]: e["dur"] for e in ex["events"]
              if e["name"].startswith("qtrace/") and e["name"] != "qtrace/query"}
    worst = max(stages, key=stages.get)
    assert worst == "qtrace/dispatch", (ex["trace_id"], worst, stages)
drain = [json.loads(ln) for ln in open(d + "/answers.jsonl") if ln.strip()][-1]
assert drain.get("event") == "serve_drain", drain
assert drain["qtrace"]["budget"]["dominant"] == "dispatch", drain["qtrace"]
rows = [json.loads(ln) for ln in open(d + "/tel/metrics.jsonl") if ln.strip()]
doms = [r["qtrace_dominant"] for r in rows
        if r.get("phase") == "serve" and "qtrace_dominant" in r]
assert "dispatch" in doms, f"no window row pinned the stall on dispatch: {doms}"
states = [json.loads(ln)["state"] for ln in open(d + "/tel/alerts.jsonl") if ln.strip()]
assert "firing" in states and states[-1] == "resolved", states
print(f"qtrace smoke: {len(slo_ex)} SLO exemplar(s), dominant dispatch "
      f"(p99 {b['p99_ms']:.0f}ms), alert fired+resolved")
EOF
python scripts/bench_check.py --qtrace "$qt_dir/tel/qtrace.json" \
    || { echo "qtrace smoke: gate refused the real artifact"; exit 1; }
# gate teeth: a schema rename and a duplicated trace id must be refused
sed 's/npairloss-qtrace-v1/npairloss-qtrace-v0/' \
    "$qt_dir/tel/qtrace.json" > "$qt_dir/badschema.json"
python scripts/bench_check.py --qtrace "$qt_dir/badschema.json" > /dev/null \
    && { echo "qtrace smoke: gate ACCEPTED a schema violation"; exit 1; }
python - "$qt_dir" <<'EOF'
import json, sys
d = sys.argv[1]
rep = json.load(open(d + "/tel/qtrace.json"))
assert len(rep["exemplars"]) >= 2, "need two exemplars to forge a duplicate"
tid = rep["exemplars"][0]["trace_id"]
rep["exemplars"][1]["trace_id"] = tid
for ev in rep["exemplars"][1]["events"]:
    ev["args"]["trace_id"] = tid
json.dump(rep, open(d + "/dup.json", "w"))
EOF
python scripts/bench_check.py --qtrace "$qt_dir/dup.json" > /dev/null \
    && { echo "qtrace smoke: gate ACCEPTED a duplicate trace id"; exit 1; }
# the composed-system timeline: serve query spans + alert instants in
# one Perfetto file (gameday layout: the telemetry dir as serve_tel)
mkdir -p "$qt_dir/run"
cp -r "$qt_dir/tel" "$qt_dir/run/serve_tel"
JAX_PLATFORMS=cpu python -m npairloss_tpu timeline "$qt_dir/run" \
    > "$qt_dir/timeline.log" 2>&1 \
    || { echo "qtrace smoke: timeline merge failed"; cat "$qt_dir/timeline.log"; exit 1; }
python - "$qt_dir" <<'EOF'
import json, sys
d = sys.argv[1]
out = json.loads(open(d + "/timeline.log").read().strip().splitlines()[-1])
assert out["sources"]["qtrace"] is True and out["sources"]["serve_host"] is True, out
merged = json.load(open(out["timeline"]))
events = merged["traceEvents"]
spans = {e["name"] for e in events if e.get("ph") == "X" and e.get("pid", 0) >= 1000}
assert "qtrace/query" in spans and "qtrace/dispatch" in spans, spans
instants = {e["name"] for e in events if e.get("ph") == "i"}
assert any(n.startswith("alert:") and n.endswith("firing") for n in instants), instants
print(f"timeline OK ({out['events']} events; serve query spans + alert instants merged)")
EOF
echo "qtrace smoke OK (dispatch attribution, artifact gate + teeth, merged timeline)"

echo "== overload / admission-control smoke (docs/SERVING.md §Approximate index) =="
# The graceful-degradation scenario (ISSUE 11): a 2-replica IVF tier
# under a p99 SLO is rammed past capacity (deterministically — the
# serve.latency failpoint stalls every dispatch 0.25s during the ramp).
# Required behavior: the p99 alert FIRES, SLO-driven admission control
# SHEDS load (fast-rejects counted in the rejected invariant) while a
# probe trickle keeps recovery observable, answered queries keep
# flowing end to end (no stall), and once the ramp ends the alert
# RESOLVES and full admission returns — then the jax-free
# bench_check --alerts gate must accept the fire->resolve log.
ov_dir="$smoke_dir/overload"
mkdir -p "$ov_dir"
python - "$ov_dir" <<'EOF'
import json, sys
import numpy as np
d = sys.argv[1]
rng = np.random.default_rng(0)
emb = rng.standard_normal((512, 32)).astype(np.float32)
emb /= np.linalg.norm(emb, axis=1, keepdims=True)
np.save(d + "/g.emb.npy", emb)
np.save(d + "/g.labels.npy", (np.arange(512) % 32).astype(np.int32))
with open(d + "/flood.jsonl", "w") as f:
    for i in range(300):
        f.write(json.dumps({"id": i, "embedding": emb[i % 512].tolist()}) + "\n")
with open(d + "/recover.jsonl", "w") as f:
    for i in range(100):
        f.write(json.dumps({"id": 1000 + i, "embedding": emb[i].tolist()}) + "\n")
with open(d + "/tail.jsonl", "w") as f:
    for i in range(20):
        f.write(json.dumps({"id": 4000 + i, "embedding": emb[i].tolist()}) + "\n")
json.dump({"slos": [{
    "name": "serve_p99", "metric": "serve_p99_ms", "op": "<=",
    "target": 150.0, "window_s": 2.0, "burn_threshold": 0.5,
    "min_samples": 1, "severity": "critical"}]},
    open(d + "/slo.json", "w"))
EOF
JAX_PLATFORMS=cpu python -m npairloss_tpu index \
    --emb "$ov_dir/g.emb.npy" --labels "$ov_dir/g.labels.npy" \
    --no-normalize --kind ivf --clusters 16 --out "$ov_dir/g.gidx" \
    > "$ov_dir/index.log" 2>&1 \
    || { echo "overload smoke: ivf index build failed"; cat "$ov_dir/index.log"; exit 1; }
mkfifo "$ov_dir/in"
JAX_PLATFORMS=cpu NPAIRLOSS_FAILPOINTS="serve.latency:60" \
    python -m npairloss_tpu serve --index "$ov_dir/g.gidx" \
    --index-kind ivf --probes 4 --scoring bf16 --replicas 2 \
    --admission slo --admission-slos serve_p99 \
    --top-k 3 --buckets 1 --deadline-ms 1 --max-queue 64 \
    --metrics-window 4 --telemetry-dir "$ov_dir/tel" --live-obs \
    --slo-config "$ov_dir/slo.json" --slo-tick 0.2 \
    < "$ov_dir/in" > "$ov_dir/answers.jsonl" 2> "$ov_dir/serve.log" &
ovpid=$!
exec 5> "$ov_dir/in"
# Phase A — the ramp: 300 queries at ~33 qps against ~8 qps of faulted
# capacity.  The queues saturate, the p99 alert fires, shedding engages.
while IFS= read -r ln; do printf '%s\n' "$ln" >&5; sleep 0.03; done \
    < "$ov_dir/flood.jsonl"
sleep 3  # ramp over; fault budget exhausts, queues drain
# Phase B — recovery: throttled traffic; the probe trickle's fast
# answers age the burn out, the alert resolves, admission returns.
while IFS= read -r ln; do printf '%s\n' "$ln" >&5; sleep 0.04; done \
    < "$ov_dir/recover.jsonl"
sleep 2.5
# Phase C — steady state again: the tail queries must nearly all land.
while IFS= read -r ln; do printf '%s\n' "$ln" >&5; sleep 0.05; done \
    < "$ov_dir/tail.jsonl"
sleep 1.5
kill -TERM "$ovpid" 2>/dev/null || true
exec 5>&-
rc=0; wait "$ovpid" || rc=$?
[[ "$rc" -eq 75 ]] \
    || { echo "overload smoke: expected exit 75, got $rc"; cat "$ov_dir/serve.log"; exit 1; }
python - "$ov_dir" <<'EOF'
import json, sys
d = sys.argv[1]
lines = [json.loads(ln) for ln in open(d + "/answers.jsonl") if ln.strip()]
drain = lines[-1]
assert drain.get("event") == "serve_drain", drain
answers = lines[:-1]
served = [a for a in answers if "neighbors" in a]
tail_served = [a for a in served if isinstance(a.get("id"), int) and a["id"] >= 4000]
# shedding engaged: admission sheds happened and are counted in rejected
assert drain["shed"] > 0, f"admission control never shed: {drain}"
assert drain["rejected"] >= drain["shed"] > 0, drain
# no stall: answers kept flowing through and after the incident
assert drain["answered"] >= 60, drain
assert len(tail_served) >= 15, \
    f"only {len(tail_served)}/20 tail queries served — tier never readmitted"
assert drain["shedding"] is False, "still shedding at drain"
assert drain["replicas"] == 2 and drain["replicas_alive"] == 2, drain
# the invariant holds through overload: nothing dropped, nothing counted twice
assert drain["queries"] == drain["answered"] + drain["errors"] + drain["rejected"], drain
states = [json.loads(ln)["state"] for ln in open(d + "/tel/alerts.jsonl") if ln.strip()]
assert "firing" in states, "p99 alert never fired under the ramp"
assert states[-1] == "resolved", f"alert did not resolve after the ramp: {states}"
print(f"overload smoke OK (shed {drain['shed']}, rejected {drain['rejected']}, "
      f"answered {drain['answered']}, tail {len(tail_served)}/20, "
      f"alert fired+resolved)")
EOF
python scripts/bench_check.py --alerts "$ov_dir/tel/alerts.jsonl" \
    || { echo "overload smoke: gate refused the fire->resolve log"; exit 1; }

echo "== alert->actuation chaos suite (docs/RESILIENCE.md §Remediation) =="
# Four fault->alert->remedy->resolve loops, each driven by a failpoint,
# proven end to end, and gated by BOTH jax-free validators:
# `bench_check --alerts` on the alert log and `bench_check
# --remediation` on the npairloss-remediation-v1 audit log.
chaos_dir="$smoke_dir/chaos"
mkdir -p "$chaos_dir"
python - "$chaos_dir" <<'EOF'
import json, sys
import numpy as np
d = sys.argv[1]
rng = np.random.default_rng(0)
emb = rng.standard_normal((256, 64)).astype(np.float32)
emb /= np.linalg.norm(emb, axis=1, keepdims=True)
np.save(d + "/g.emb.npy", emb)
np.save(d + "/g.labels.npy", (np.arange(256) % 16).astype(np.int32))
with open(d + "/queries.jsonl", "w") as f:
    for i in range(600):
        f.write(json.dumps({"id": i, "embedding": emb[i % 256].tolist()}) + "\n")
EOF
JAX_PLATFORMS=cpu python -m npairloss_tpu index \
    --emb "$chaos_dir/g.emb.npy" --labels "$chaos_dir/g.labels.npy" \
    --no-normalize --out "$chaos_dir/g.gidx" > "$chaos_dir/index.log" 2>&1 \
    || { echo "chaos: index build failed"; cat "$chaos_dir/index.log"; exit 1; }

chaos_gates() {  # $1 = telemetry dir, $2 = scenario label
    python scripts/bench_check.py --alerts "$1/alerts.jsonl" \
        || { echo "chaos $2: alert gate refused"; exit 1; }
    python scripts/bench_check.py --remediation "$1/remediation.jsonl" \
        || { echo "chaos $2: remediation gate refused"; exit 1; }
}

echo "-- chaos A: compile storm -> re-warm --"
# serve.compile_storm counts phantom post-warmup compiles; the
# post-warmup-compile alert fires, the rewarm policy re-primes the
# buckets and resets the counters, and the now-EXPLICIT zero rows
# resolve the alert.
python - "$chaos_dir" <<'EOF'
import json, sys
d = sys.argv[1]
json.dump({"slos": [{
    "name": "serve_post_warmup_compile", "metric": "serve_compiles_after_warmup",
    "op": "<=", "target": 0.0, "window_s": 3.0, "burn_threshold": 0.01,
    "min_samples": 1, "severity": "warning"}]}, open(d + "/a_slo.json", "w"))
json.dump({"policies": [{
    "name": "rewarm", "slo": "serve_post_warmup_compile", "action": "rewarm",
    "cooldown_s": 4.0, "max_attempts": 3}]}, open(d + "/a_rem.json", "w"))
EOF
mkfifo "$chaos_dir/a_in"
JAX_PLATFORMS=cpu NPAIRLOSS_FAILPOINTS="serve.compile_storm:2" \
    python -m npairloss_tpu serve --index "$chaos_dir/g.gidx" \
    --top-k 3 --buckets 1 --deadline-ms 1 --metrics-window 4 \
    --telemetry-dir "$chaos_dir/a_tel" --live-obs \
    --slo-config "$chaos_dir/a_slo.json" --slo-tick 0.2 \
    --remediate --remediation-config "$chaos_dir/a_rem.json" \
    < "$chaos_dir/a_in" > "$chaos_dir/a_answers.jsonl" \
    2> "$chaos_dir/a.log" &
apid=$!
exec 6> "$chaos_dir/a_in"
head -30 "$chaos_dir/queries.jsonl" | while IFS= read -r ln; do
    printf '%s\n' "$ln" >&6; sleep 0.05
done
sleep 2    # storm rows land, alert fires, rewarm runs
sed -n '31,90p' "$chaos_dir/queries.jsonl" | while IFS= read -r ln; do
    printf '%s\n' "$ln" >&6; sleep 0.05
done
sleep 2.5  # explicit-0 rows age the burn out -> resolve
kill -TERM "$apid" 2>/dev/null || true
exec 6>&-
rc=0; wait "$apid" || rc=$?
[[ "$rc" -eq 75 ]] \
    || { echo "chaos A: expected exit 75, got $rc"; cat "$chaos_dir/a.log"; exit 1; }
python - "$chaos_dir" <<'EOF'
import json, sys
d = sys.argv[1]
lines = [json.loads(ln) for ln in open(d + "/a_answers.jsonl") if ln.strip()]
drain = lines[-1]
assert drain.get("event") == "serve_drain", drain
assert drain["errors"] == 0 and drain["answered"] == 90, drain
assert drain["compiles_after_warmup"] == 0, drain  # re-warm reset them
states = [json.loads(ln)["state"] for ln in open(d + "/a_tel/alerts.jsonl") if ln.strip()]
assert "firing" in states and states[-1] == "resolved", states
rem = [json.loads(ln) for ln in open(d + "/a_tel/remediation.jsonl") if ln.strip()]
assert any(r["policy"] == "rewarm" and r["state"] == "succeeded" for r in rem), rem
assert drain["remediation"]["rewarm"]["outcome"] == "succeeded", drain
print(f"chaos A OK (storm counted, rewarm succeeded, alert resolved; "
      f"{len(rem)} audit event(s))")
EOF
chaos_gates "$chaos_dir/a_tel" A

echo "-- chaos B: queue saturation -> audited load-shed --"
# serve.latency wedges the dispatcher; the queue-saturation alert fires
# and the load_shed policy ENGAGES the admission throttle (an audited
# action, not an implicit behavior); the probe trickle keeps recovery
# observable, the alert resolves once the queue drains, and the
# engine's undo releases admission.
python - "$chaos_dir" <<'EOF'
import json, sys
d = sys.argv[1]
json.dump({"slos": [{
    "name": "serve_queue_saturation", "metric": "serve_queue_depth",
    "op": "<=", "target": 6.0, "window_s": 2.0, "burn_threshold": 0.5,
    "min_samples": 1, "severity": "warning"}]}, open(d + "/b_slo.json", "w"))
json.dump({"policies": [{
    "name": "load_shed", "slo": "serve_queue_saturation", "action": "load_shed",
    "cooldown_s": 8.0, "max_attempts": 4}]}, open(d + "/b_rem.json", "w"))
EOF
mkfifo "$chaos_dir/b_in"
JAX_PLATFORMS=cpu NPAIRLOSS_FAILPOINTS="serve.latency:30" \
    python -m npairloss_tpu serve --index "$chaos_dir/g.gidx" \
    --top-k 3 --buckets 1 --deadline-ms 1 --max-queue 24 \
    --metrics-window 4 --telemetry-dir "$chaos_dir/b_tel" --live-obs \
    --slo-config "$chaos_dir/b_slo.json" --slo-tick 0.2 \
    --remediate --remediation-config "$chaos_dir/b_rem.json" \
    < "$chaos_dir/b_in" > "$chaos_dir/b_answers.jsonl" \
    2> "$chaos_dir/b.log" &
bpid=$!
exec 7> "$chaos_dir/b_in"
# flood: ~100 qps against ~4 qps of faulted capacity -> queue saturates
head -150 "$chaos_dir/queries.jsonl" | while IFS= read -r ln; do
    printf '%s\n' "$ln" >&7; sleep 0.01
done
sleep 6    # fault budget exhausts, queue drains under shed
# recovery traffic: the probe trickle's answers emit the good
# queue-depth rows resolution requires
sed -n '151,250p' "$chaos_dir/queries.jsonl" | while IFS= read -r ln; do
    printf '%s\n' "$ln" >&7; sleep 0.04
done
sleep 2
kill -TERM "$bpid" 2>/dev/null || true
exec 7>&-
rc=0; wait "$bpid" || rc=$?
[[ "$rc" -eq 75 ]] \
    || { echo "chaos B: expected exit 75, got $rc"; cat "$chaos_dir/b.log"; exit 1; }
python - "$chaos_dir" <<'EOF'
import json, sys
d = sys.argv[1]
lines = [json.loads(ln) for ln in open(d + "/b_answers.jsonl") if ln.strip()]
drain = lines[-1]
assert drain.get("event") == "serve_drain", drain
assert drain["shed"] > 0, f"load_shed never engaged: {drain}"
assert drain["rejected"] >= drain["shed"], drain
assert drain["queries"] == drain["answered"] + drain["errors"] + drain["rejected"], drain
assert drain["shedding"] is False, "forced shed never released"
states = [json.loads(ln)["state"] for ln in open(d + "/b_tel/alerts.jsonl") if ln.strip()]
assert "firing" in states and states[-1] == "resolved", states
rem = [json.loads(ln) for ln in open(d + "/b_tel/remediation.jsonl") if ln.strip()]
assert any(r["policy"] == "load_shed" and r["state"] == "succeeded" for r in rem), rem
print(f"chaos B OK (shed {drain['shed']}, answered {drain['answered']}, "
      f"alert resolved, shed released)")
EOF
chaos_gates "$chaos_dir/b_tel" B

echo "-- chaos C: embedding collapse -> trainer rollback --"
# train.collapse (delay-armed: 60 healthy steps first, so pre-incident
# snapshots exist) forces the health signal degenerate; the
# embedding-collapse alert fires, the trainer_rollback policy requests
# a rollback the loop executes at its next safe point (restoring a
# snapshot COMMITTED BEFORE the alert fired), and once the injected
# collapse exhausts, the real health rows resolve the alert.
cat > "$chaos_dir/c_solver.prototxt" <<EOF
net: "examples/tiny_net.prototxt"
base_lr: 0.05
lr_policy: "fixed"
momentum: 0.9
max_iter: 800
display: 0
test_interval: 0
test_iter: 0
snapshot: 5
snapshot_prefix: "$chaos_dir/c_snap/m_"
EOF
python - "$chaos_dir" <<'EOF'
import json, sys
d = sys.argv[1]
json.dump({"slos": [{
    "name": "embedding_collapse", "metric": "train_an_threshold_mean",
    "op": "<=", "target": 0.98, "window_s": 2.0, "burn_threshold": 0.5,
    "min_samples": 3, "severity": "warning"}]}, open(d + "/c_slo.json", "w"))
json.dump({"policies": [{
    "name": "trainer_rollback", "slo": "embedding_collapse",
    "action": "trainer_rollback", "cooldown_s": 6.0, "max_attempts": 5}]},
    open(d + "/c_rem.json", "w"))
EOF
JAX_PLATFORMS=cpu NPAIRLOSS_FAILPOINTS="train.collapse:160@60" \
    python -m npairloss_tpu train --solver "$chaos_dir/c_solver.prototxt" \
    --model mlp --synthetic --health-metrics \
    --telemetry-dir "$chaos_dir/c_tel" --live-obs \
    --slo-config "$chaos_dir/c_slo.json" --slo-tick 0.2 \
    --remediate --remediation-config "$chaos_dir/c_rem.json" \
    > "$chaos_dir/c.log" 2>&1 \
    || { echo "chaos C: train run failed"; cat "$chaos_dir/c.log"; exit 1; }
python - "$chaos_dir" <<'EOF'
import json, sys
d = sys.argv[1]
rows = [json.loads(ln) for ln in open(d + "/c_tel/metrics.jsonl") if ln.strip()]
rollbacks = [r for r in rows if r.get("event") == "rollback" and r.get("requested")]
assert rollbacks, "no requested rollback executed"
assert all(r["to_iteration"] < r["step"] for r in rollbacks), rollbacks
states = [json.loads(ln)["state"] for ln in open(d + "/c_tel/alerts.jsonl") if ln.strip()]
assert "firing" in states, "collapse alert never fired"
assert states[-1] == "resolved", f"collapse alert never resolved: {states}"
rem = [json.loads(ln) for ln in open(d + "/c_tel/remediation.jsonl") if ln.strip()]
assert any(r["policy"] == "trainer_rollback" and r["state"] == "succeeded"
           for r in rem), rem
print(f"chaos C OK ({len(rollbacks)} rollback(s) to iteration "
      f"{rollbacks[0]['to_iteration']}, alert resolved, "
      f"{len(rem)} audit event(s))")
EOF
chaos_gates "$chaos_dir/c_tel" C

echo "-- chaos D (headline): model staleness -> zero-downtime hot-swap --"
# The train->serve freshness loop's actuation half, end to end: a
# trainer snapshots continuously (and is killed + resumed MID-STREAM);
# the server watches its snapshot_prefix, the model-staleness alert
# fires as the served snapshot ages past target, the hot-swap
# remediation republishes a freshly-warmed engine tier WITHOUT dropping
# a single in-flight query, and the per-answer model_age_s visibly
# drops at each swap — the staleness watchdog proving the swap.
hs="$chaos_dir/hs"
mkdir -p "$hs"
cat > "$hs/solver.prototxt" <<EOF
net: "examples/tiny_net.prototxt"
base_lr: 0.05
lr_policy: "fixed"
momentum: 0.9
max_iter: 100000
display: 0
test_interval: 0
test_iter: 0
snapshot: 40
snapshot_prefix: "$hs/snap/m_"
snapshot_max_keep: 10
EOF
python - "$hs" <<'EOF'
import json, sys
d = sys.argv[1]
json.dump({"slos": [{
    "name": "model_staleness", "metric": "serve_model_age_s", "op": "<=",
    "target": 5.0, "window_s": 2.0, "burn_threshold": 0.5,
    "min_samples": 1, "severity": "warning"}]}, open(d + "/slo.json", "w"))
json.dump({"policies": [{
    "name": "hotswap_model", "slo": "model_staleness",
    "action": "snapshot_hotswap", "cooldown_s": 4.0, "max_attempts": 4}]},
    open(d + "/rem.json", "w"))
EOF
# Phase 0: one short run commits the INITIAL snapshot the server restores.
JAX_PLATFORMS=cpu python -m npairloss_tpu train --solver "$hs/solver.prototxt" \
    --model mlp --synthetic --max_iter 40 > "$hs/seed.log" 2>&1 \
    || { echo "chaos D: seed training failed"; cat "$hs/seed.log"; exit 1; }
[[ -f "$hs/snap/m_iter_40.ckpt/manifest.json" ]] \
    || { echo "chaos D: seed snapshot missing"; exit 1; }
# The trainer, snapshotting continuously (the supervisor loop: kill ->
# relaunch same command, the docs/RESILIENCE.md recipe).
JAX_PLATFORMS=cpu python -m npairloss_tpu train --solver "$hs/solver.prototxt" \
    --model mlp --synthetic --resume auto > "$hs/train1.log" 2>&1 &
tr_pid=$!
mkfifo "$hs/in"
JAX_PLATFORMS=cpu python -m npairloss_tpu serve --index "$chaos_dir/g.gidx" \
    --snapshot "$hs/snap/m_iter_40.ckpt" --model mlp --input-size 8 \
    --watch-snapshots "$hs/snap/m_" --compile-cache "$hs/xla_cache" \
    --top-k 3 --buckets 1 --deadline-ms 1 --metrics-window 4 \
    --telemetry-dir "$hs/tel" --live-obs --slo-config "$hs/slo.json" \
    --slo-tick 0.2 --remediate --remediation-config "$hs/rem.json" \
    < "$hs/in" > "$hs/answers.jsonl" 2> "$hs/serve.log" &
sv_pid=$!
exec 8> "$hs/in"
( head -500 "$chaos_dir/queries.jsonl" | while IFS= read -r ln; do
    printf '%s\n' "$ln" >&8; sleep 0.05; done ) &
feeder=$!
sleep 10
# Kill the trainer MID-STREAM; the server must keep answering.
kill -TERM "$tr_pid" 2>/dev/null || true
rc=0; wait "$tr_pid" || rc=$?
[[ "$rc" -eq 75 ]] \
    || { echo "chaos D: trainer kill expected 75, got $rc"; cat "$hs/train1.log"; exit 1; }
# ...and resume it (same command line — the auto-resume contract).
JAX_PLATFORMS=cpu python -m npairloss_tpu train --solver "$hs/solver.prototxt" \
    --model mlp --synthetic --resume auto > "$hs/train2.log" 2>&1 &
tr_pid=$!
wait "$feeder" || true
for _ in $(seq 1 240); do  # every fed query must be answered
    n=$(grep -c '"neighbors"' "$hs/answers.jsonl" 2>/dev/null || true)
    [[ "${n:-0}" -ge 500 ]] && break
    kill -0 "$sv_pid" 2>/dev/null \
        || { echo "chaos D: server died mid-serve"; tail -30 "$hs/serve.log"; exit 1; }
    sleep 0.5
done
sleep 2  # let the last swap's resolution land before the drain
kill -TERM "$sv_pid" 2>/dev/null || true
exec 8>&-
rc=0; wait "$sv_pid" || rc=$?
[[ "$rc" -eq 75 ]] \
    || { echo "chaos D: serve expected exit 75, got $rc"; tail -30 "$hs/serve.log"; exit 1; }
kill -TERM "$tr_pid" 2>/dev/null || true
wait "$tr_pid" || true
grep -q "resuming from iteration" "$hs/train2.log" \
    || { echo "chaos D: relaunched trainer did not resume"; cat "$hs/train2.log"; exit 1; }
python - "$hs" <<'EOF'
import json, sys
d = sys.argv[1]
lines = [json.loads(ln) for ln in open(d + "/answers.jsonl") if ln.strip()]
drain = lines[-1]
assert drain.get("event") == "serve_drain", drain
served = [a for a in lines[:-1] if "neighbors" in a]
# zero downtime: EVERY fed query answered, none dropped or errored,
# through two trainer generations and every swap
assert len(served) == 500 and drain["errors"] == 0, (len(served), drain)
assert drain["queries"] == drain["answered"] + drain["errors"] + drain["rejected"], drain
assert drain["hot_swaps"] >= 2, f"expected >=2 hot swaps, got {drain.get('hot_swaps')}"
# the served model ADVANCED: the drain's snapshot_step is a later
# training iteration than the seed snapshot the server started from
assert drain["snapshot_step"] > 40, drain["snapshot_step"]
# per-answer model_age_s drops at each swap (the staleness watchdog's
# proof): count strict drops of > 2s between consecutive answers
ages = [a["model_age_s"] for a in served if "model_age_s" in a]
assert len(ages) == 500, len(ages)
drops = sum(1 for i in range(1, len(ages)) if ages[i] < ages[i - 1] - 2.0)
assert drops >= 2, f"model age dropped {drops}x, expected >= 2 swaps visible"
states = [json.loads(ln)["state"] for ln in open(d + "/tel/alerts.jsonl") if ln.strip()]
assert states.count("firing") >= 2, states
assert "resolved" in states, states
rem = [json.loads(ln) for ln in open(d + "/tel/remediation.jsonl") if ln.strip()]
swaps_ok = [r for r in rem if r["policy"] == "hotswap_model"
            and r["state"] == "succeeded"]
assert len(swaps_ok) >= 1, rem
print(f"chaos D OK ({drain['hot_swaps']} hot swap(s), {drops} visible "
      f"age drops, served snapshot_step {drain['snapshot_step']}, "
      f"500/500 answered, {states.count('firing')} staleness incident(s))")
EOF
chaos_gates "$hs/tel" D

echo "== quality observatory smoke (docs/OBSERVABILITY.md §Quality) =="
# The recall loop end to end: a clean IVF serve run under a recall@10
# SLO (shadow-scoring EVERY query against the flat oracle) fires ZERO
# alerts and the jax-free --quality gate accepts its log; a run with
# serve.recall_drop armed fires the recall alert, the probe-escalation
# remediation runs, the alert resolves, --quality and --remediation
# both accept; the watch replay reproduces firing->resolved through
# the same engine; and the gate's teeth refuse a schema violation and
# a floor breach with no fired alert.
q_dir="$smoke_dir/quality"
mkdir -p "$q_dir"
python - "$q_dir" <<'EOF'
import json, sys
import numpy as np
d = sys.argv[1]
rng = np.random.default_rng(0)
# Well-separated blobs: IVF geometry where partial probes still find
# the true neighbors, so only the INJECTED mis-probe can drop recall.
centers = rng.standard_normal((8, 32)).astype(np.float32)
centers /= np.linalg.norm(centers, axis=1, keepdims=True)
emb = np.repeat(centers, 32, axis=0) + 0.1 * rng.standard_normal(
    (256, 32)).astype(np.float32)
emb /= np.linalg.norm(emb, axis=1, keepdims=True)
np.save(d + "/g.emb.npy", emb)
np.save(d + "/g.labels.npy", np.repeat(np.arange(8), 32).astype(np.int32))
with open(d + "/queries.jsonl", "w") as f:
    for i in range(200):
        f.write(json.dumps({"id": i, "embedding": emb[i % 256].tolist()}) + "\n")
json.dump({"slos": [{
    "name": "serve_recall_floor", "metric": "serve_recall_at_10",
    "op": ">=", "target": 0.9, "window_s": 2.0, "burn_threshold": 0.5,
    "min_samples": 1, "severity": "critical"}]},
    open(d + "/slo.json", "w"))
json.dump({"policies": [{
    "name": "probe_escalation", "slo": "serve_recall_floor",
    "action": "escalate_probes", "cooldown_s": 4.0, "max_attempts": 4}]},
    open(d + "/rem.json", "w"))
EOF
JAX_PLATFORMS=cpu python -m npairloss_tpu index \
    --emb "$q_dir/g.emb.npy" --labels "$q_dir/g.labels.npy" \
    --no-normalize --kind ivf --clusters 8 --parity-sample 64 \
    --out "$q_dir/g.gidx" > "$q_dir/index.log" 2>&1 \
    || { echo "quality smoke: ivf index build failed"; cat "$q_dir/index.log"; exit 1; }
python - "$q_dir/g.gidx/manifest.json" <<'EOF'
import json, sys
par = json.load(open(sys.argv[1])).get("parity")
assert par and par["recall"]["fp32"]["at_10"] >= 0.95, par
print(f"parity birth certificate committed (fp32 recall@10 "
      f"{par['recall']['fp32']['at_10']}, probes {par['probes']})")
EOF

run_quality_serve() {  # $1 = tel dir, $2 = probes, $3 = failpoints, $4 = extra args
    local tel="$1" probes="$2" fp="$3"; shift 3
    mkfifo "$q_dir/in.$$"
    env JAX_PLATFORMS=cpu NPAIRLOSS_FAILPOINTS="$fp" \
        python -m npairloss_tpu serve --index "$q_dir/g.gidx" \
        --index-kind ivf --probes "$probes" --top-k 10 --buckets 1 \
        --deadline-ms 1 --metrics-window 4 --shadow-rate 1 \
        --shadow-window 4 --telemetry-dir "$tel" --live-obs \
        --slo-config "$q_dir/slo.json" --slo-tick 0.2 "$@" \
        < "$q_dir/in.$$" > "$tel.answers.jsonl" 2> "$tel.log" &
    qpid=$!
    exec 9> "$q_dir/in.$$"
    # phase 1: (possibly fault-poisoned) traffic
    head -40 "$q_dir/queries.jsonl" | while IFS= read -r ln; do
        printf '%s\n' "$ln" >&9; sleep 0.08
    done
    sleep 2.5  # fault (if armed) exhausts; alert fires; remediation runs
    # phase 2: clean traffic — good recall windows age the burn out
    sed -n '41,100p' "$q_dir/queries.jsonl" | while IFS= read -r ln; do
        printf '%s\n' "$ln" >&9; sleep 0.05
    done
    sleep 3    # resolution lands before the drain
    kill -TERM "$qpid" 2>/dev/null || true
    exec 9>&-
    rc=0; wait "$qpid" || rc=$?
    rm -f "$q_dir/in.$$"
    [[ "$rc" -eq 75 ]] \
        || { echo "quality smoke: expected exit 75, got $rc"; cat "$tel.log"; exit 1; }
}

echo "-- quality clean run: zero alerts, gate accepts --"
run_quality_serve "$q_dir/clean" 8 ""
[[ ! -s "$q_dir/clean/alerts.jsonl" ]] \
    || { echo "quality smoke: CLEAN run fired alerts"; cat "$q_dir/clean/alerts.jsonl"; exit 1; }
python - "$q_dir" <<'EOF'
import json, sys
d = sys.argv[1]
lines = [json.loads(ln) for ln in open(d + "/clean.answers.jsonl") if ln.strip()]
drain = lines[-1]
assert drain.get("event") == "serve_drain", drain
assert drain["errors"] == 0 and drain["answered"] == 100, drain
q = drain["quality"]
assert q["sampled"] == 100 and q["windows"] >= 20, q
assert q["last"]["recall_at_10"] == 1.0, q
assert q["baseline"]["recall"]["fp32"]["at_10"] >= 0.95, q
recs = [json.loads(ln) for ln in open(d + "/clean/quality.jsonl") if ln.strip()]
assert recs[0]["kind"] == "config" and recs[0]["recall_floor"] == 0.9, recs[0]
assert recs[-1]["kind"] == "summary", recs[-1]
print(f"quality clean OK ({q['windows']} windows, recall@10 "
      f"{q['last']['recall_at_10']}, baseline committed)")
EOF
python scripts/bench_check.py --quality "$q_dir/clean/quality.jsonl" \
    || { echo "quality smoke: gate refused the clean log"; exit 1; }
JAX_PLATFORMS=cpu python -m npairloss_tpu prof --quality "$q_dir/clean" \
    > "$q_dir/prof.log" 2>&1 \
    || { echo "quality smoke: prof --quality refused"; cat "$q_dir/prof.log"; exit 1; }

echo "-- quality fault run: recall_drop -> alert -> probe escalation -> resolve --"
run_quality_serve "$q_dir/fault" 2 "serve.recall_drop:12" \
    --remediate --remediation-config "$q_dir/rem.json"
python - "$q_dir" <<'EOF'
import json, sys
d = sys.argv[1]
lines = [json.loads(ln) for ln in open(d + "/fault.answers.jsonl") if ln.strip()]
drain = lines[-1]
assert drain.get("event") == "serve_drain", drain
assert drain["errors"] == 0 and drain["answered"] == 100, drain
states = [json.loads(ln)["state"] for ln in open(d + "/fault/alerts.jsonl") if ln.strip()]
assert "firing" in states, "recall_drop never fired the recall alert"
assert states[-1] == "resolved", f"recall alert never resolved: {states}"
rem = [json.loads(ln) for ln in open(d + "/fault/remediation.jsonl") if ln.strip()]
esc = [r for r in rem if r["policy"] == "probe_escalation"]
assert esc, "probe escalation never attempted"
ok = [r for r in esc if r["state"] == "succeeded"]
assert ok, f"probe escalation never succeeded: {esc}"
assert drain["hot_swaps"] >= 1, drain  # the escalation republished the tier
assert drain["remediation"]["probe_escalation"]["outcome"] == "succeeded", drain
qrecs = [json.loads(ln) for ln in open(d + "/fault/quality.jsonl") if ln.strip()]
bad = [r for r in qrecs if r.get("kind") == "window" and r["recall_at_10"] < 0.9]
assert bad, "no breaching window recorded — the fault never reached the shadow"
print(f"quality fault OK ({len(bad)} breaching window(s), "
      f"{len(ok)} escalation(s) succeeded, alert resolved, "
      f"{drain['hot_swaps']} hot swap(s))")
EOF
python scripts/bench_check.py --quality "$q_dir/fault/quality.jsonl" \
    || { echo "quality smoke: gate refused the remediated fault log"; exit 1; }
python scripts/bench_check.py --remediation "$q_dir/fault/remediation.jsonl" \
    || { echo "quality smoke: remediation gate refused"; exit 1; }
python scripts/bench_check.py --alerts "$q_dir/fault/alerts.jsonl" \
    || { echo "quality smoke: alert gate refused the fire->resolve log"; exit 1; }
# the offline feed agrees: watch must reproduce firing->resolved from
# the recall rows on disk, and surface a valid quality block
JAX_PLATFORMS=cpu python -m npairloss_tpu watch "$q_dir/fault" \
    --slo-config "$q_dir/slo.json" > "$q_dir/watch.log" 2>&1 \
    || { echo "quality smoke: watch refused the run dir"; cat "$q_dir/watch.log"; exit 1; }
python - "$q_dir" <<'EOF'
import json, sys
d = sys.argv[1]
states = [json.loads(ln)["state"]
          for ln in open(d + "/fault/alerts.watch.jsonl") if ln.strip()]
assert "firing" in states and states[-1] == "resolved", states
summary = json.loads(open(d + "/watch.log").read().strip().splitlines()[-1])
assert summary["quality"]["valid"] is True, summary.get("quality")
assert summary["quality"]["breaches"] >= 1, summary["quality"]
print(f"watch feed agrees: {states}; quality block valid "
      f"({summary['quality']['breaches']} breach(es) surfaced)")
EOF
# gate teeth: a schema violation and a breach with NO fired alert must
# both be refused
sed 's/npairloss-quality-v1/npairloss-quality-v0/' \
    "$q_dir/fault/quality.jsonl" > "$q_dir/badschema.jsonl"
python scripts/bench_check.py --quality "$q_dir/badschema.jsonl" > /dev/null \
    && { echo "quality smoke: gate ACCEPTED a schema violation"; exit 1; }
mkdir -p "$q_dir/ghost"
cp "$q_dir/fault/quality.jsonl" "$q_dir/ghost/quality.jsonl"
python scripts/bench_check.py --quality "$q_dir/ghost/quality.jsonl" > /dev/null \
    && { echo "quality smoke: gate ACCEPTED a breach with no alert log"; exit 1; }
echo "quality observatory smoke OK (clean zero-alert + gate, fault->alert->escalation->resolve, watch agreement, gate teeth)"

echo "== multi-tenant serving smoke (docs/SERVING.md §Multi-tenant) =="
# Three tenants (mixed flat/IVF, distinct galleries) behind ONE front
# end / ONE replica tier / ONE compile cache: routed self-match answers
# per tenant, an unknown tenant refused as an error, a MID-TRAFFIC
# hot-swap of one tenant with zero drops and bit-level proof the
# others kept serving, a noisy tenant quota-shed in isolation (its
# tenant-scoped alert fires; neighbors keep zero errors/rejects), zero
# post-warmup compiles across the shared geometry, and the jax-free
# bench_check --tenants gate accepting the evidence and refusing
# tampered copies of it.
mt_dir="$smoke_dir/mt"
mkdir -p "$mt_dir/idx" "$mt_dir/tel"
python - "$mt_dir" <<'EOF'
import json, sys
import numpy as np
from npairloss_tpu.serve import GalleryIndex
d = sys.argv[1]
for t_i, tid in enumerate(("acme", "bcorp", "ccorp")):
    rng = np.random.default_rng(11 + t_i)
    emb = rng.standard_normal((192, 32)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    labels = (np.arange(192) % 16).astype(np.int32)
    GalleryIndex.build(emb, labels, normalize=False).save(
        f"{d}/idx/{tid}-0000.gidx")
    np.save(f"{d}/{tid}.emb.npy", emb)
tenants = [
    # capacity = qps*burst_s = 10 tokens: phase A's 10 paced probes
    # fit the bucket, the 30-query flood cannot.
    {"tenant_id": "acme", "index_prefix": d + "/idx/acme-",
     "index_kind": "ivf", "quota_qps": 2.0, "quota_burst_s": 5.0},
    {"tenant_id": "bcorp", "index_prefix": d + "/idx/bcorp-"},
    {"tenant_id": "ccorp", "index_prefix": d + "/idx/ccorp-"},
]
json.dump({"schema": "npairloss-tenants-v1", "tenants": tenants},
          open(d + "/tenants.json", "w"))
with open(d + "/phase_a.jsonl", "w") as f:
    for tid in ("acme", "bcorp", "ccorp"):
        emb = np.load(f"{d}/{tid}.emb.npy")
        for i in range(10):
            f.write(json.dumps({"id": f"{tid[0]}-{i}", "tenant": tid,
                                "embedding": emb[i].tolist()}) + "\n")
    f.write(json.dumps({"id": "x-1", "tenant": "ghost",
                        "embedding": emb[0].tolist()}) + "\n")
    f.write(json.dumps({"id": "x-2",
                        "embedding": emb[0].tolist()}) + "\n")
EOF
mkfifo "$mt_dir/in"
# Strict guard: ANY post-warmup compile aborts the server — the
# cross-tenant program-sharing claim fails loudly, not just by counter.
JAX_PLATFORMS=cpu NPAIRLOSS_SERVE_COMPILE_GUARD=strict \
    python -m npairloss_tpu serve \
    --tenant-config "$mt_dir/tenants.json" \
    --top-k 5 --buckets 1,8 --deadline-ms 2 --poll-s 0.02 \
    --max-queue 64 --metrics-window 4 \
    --explicit-drops --live-obs --slo-tick 0.2 \
    --telemetry-dir "$mt_dir/tel" \
    < "$mt_dir/in" > "$mt_dir/answers.jsonl" \
    2> "$mt_dir/serve.log" &
mt_pid=$!
exec 4> "$mt_dir/in"
cat "$mt_dir/phase_a.jsonl" >&4
for _ in $(seq 1 240); do  # 32 answers: 30 routed + 2 refused
    [[ "$(wc -l < "$mt_dir/answers.jsonl")" -ge 32 ]] && break
    kill -0 "$mt_pid" 2>/dev/null \
        || { echo "mt smoke: server died in phase A"; cat "$mt_dir/serve.log"; exit 1; }
    sleep 0.5
done
# Mid-traffic hot-swap: commit a STRICTLY newer bcorp gallery; the
# per-tenant watch must republish bcorp alone within a sweep or two.
python - "$mt_dir" <<'EOF'
import sys
import numpy as np
from npairloss_tpu.serve import GalleryIndex
d = sys.argv[1]
rng = np.random.default_rng(99)
emb = rng.standard_normal((192, 32)).astype(np.float32)
emb /= np.linalg.norm(emb, axis=1, keepdims=True)
labels = (np.arange(192) % 16).astype(np.int32)
GalleryIndex.build(emb, labels, normalize=False).save(
    d + "/idx/bcorp-0001.gidx")
np.save(d + "/bcorp2.emb.npy", emb)
EOF
for _ in $(seq 1 60); do
    grep -q "tenant 'bcorp' republished" "$mt_dir/serve.log" && break
    kill -0 "$mt_pid" 2>/dev/null \
        || { echo "mt smoke: server died awaiting hot-swap"; cat "$mt_dir/serve.log"; exit 1; }
    sleep 0.5
done
grep -q "tenant 'bcorp' republished" "$mt_dir/serve.log" \
    || { echo "mt smoke: bcorp hot-swap never landed"; cat "$mt_dir/serve.log"; exit 1; }
# Phase B: bcorp answers from the NEW gallery; then the noisy-neighbor
# flood — acme's 1-token bucket sheds the burst while bcorp/ccorp ride
# along untouched.
python - "$mt_dir" <<'EOF'
import json, sys
import numpy as np
d = sys.argv[1]
with open(d + "/phase_b.jsonl", "w") as f:
    emb2 = np.load(d + "/bcorp2.emb.npy")
    for i in range(10):
        f.write(json.dumps({"id": f"b2-{i}", "tenant": "bcorp",
                            "embedding": emb2[i].tolist()}) + "\n")
    embs = {t: np.load(f"{d}/{t}.emb.npy")
            for t in ("acme", "bcorp", "ccorp")}
    for i in range(30):
        f.write(json.dumps({"id": f"hot-{i}", "tenant": "acme",
                            "embedding": embs["acme"][i % 192].tolist()})
                + "\n")
        if i % 3 == 0:
            for t in ("bcorp", "ccorp"):
                emb = embs[t] if t != "bcorp" else emb2
                f.write(json.dumps({"id": f"q-{t}-{i}", "tenant": t,
                                    "embedding": emb[i].tolist()}) + "\n")
EOF
cat "$mt_dir/phase_b.jsonl" >&4
for _ in $(seq 1 120); do  # 32 + 10 + 30 + 20 = 92 answers
    [[ "$(wc -l < "$mt_dir/answers.jsonl")" -ge 92 ]] && break
    kill -0 "$mt_pid" 2>/dev/null \
        || { echo "mt smoke: server died in phase B"; cat "$mt_dir/serve.log"; exit 1; }
    sleep 0.5
done
for _ in $(seq 1 60); do  # the tenant-scoped quota alert must page
    grep -q '"slo": "tenant_quota@acme"' "$mt_dir/tel/alerts.jsonl" 2>/dev/null && break
    sleep 0.5
done
kill -TERM "$mt_pid" 2>/dev/null || true
exec 4>&-
rc=0; wait "$mt_pid" || rc=$?
[[ "$rc" -eq 75 ]] \
    || { echo "mt smoke: expected exit 75 after SIGTERM, got $rc"; cat "$mt_dir/serve.log"; exit 1; }
python - "$mt_dir" <<'EOF'
import json, sys
d = sys.argv[1]
lines = [json.loads(ln) for ln in open(d + "/answers.jsonl") if ln.strip()]
drain = lines[-1]
assert drain.get("event") == "serve_drain", drain
answers = {a["id"]: a for a in lines[:-1]}
for tid in ("acme", "bcorp", "ccorp"):
    for i in range(10):  # phase A: routed self-match per tenant
        a = answers[f"{tid[0]}-{i}"]
        assert a.get("tenant") == tid and a["neighbors"][0]["row"] == i, a
for i in range(10):  # post-swap bcorp: NEW gallery's rows self-match
    a = answers[f"b2-{i}"]
    top1 = a["neighbors"][0]
    assert top1["row"] == i and top1["score"] > 0.99, a
for rid in ("x-1", "x-2"):  # unknown tenant: refused, never admitted
    assert "unknown tenant" in answers[rid]["error"], answers[rid]
shed = [a for a in answers.values()
        if "quota exceeded" in a.get("error", "")]
assert shed and all("'acme'" in a["error"] for a in shed), len(shed)
per = drain["tenants"]
assert per["acme"]["quota"]["sheds"] >= 15, per["acme"]
assert per["bcorp"]["errors"] == 0 and per["bcorp"]["rejected"] == 0, per["bcorp"]
assert per["ccorp"]["errors"] == 0 and per["ccorp"]["rejected"] == 0, per["ccorp"]
assert per["bcorp"]["hot_swaps"] == 1 and "hot_swaps" not in per["ccorp"], per
assert per["acme"]["index_kind"] == "ivf" and per["bcorp"]["index_kind"] == "flat"
assert drain["errors_unattributed"] == 2, drain  # the 2 unknown-tenant refusals
for key in ("queries", "answered", "errors", "rejected"):
    total = sum(row[key] for row in per.values())
    if key == "errors":
        total += drain["errors_unattributed"]
    assert total == drain[key], (key, total, drain[key])
assert drain["queries_dropped"] == 0, drain
assert drain["compiles_after_warmup"] == 0, drain
alerts = [json.loads(ln) for ln in open(d + "/tel/alerts.jsonl")]
fired = [a for a in alerts if a.get("state") == "firing"]
assert any(a["slo"] == "tenant_quota@acme" for a in fired), fired
# Noisy-neighbor isolation at the paging layer: acme's incident never
# becomes a bcorp/ccorp-scoped page.
assert not [a for a in fired
            if a["slo"].endswith(("@bcorp", "@ccorp"))], fired
print(f"mt smoke: {drain['answered']} answered across 3 tenants, "
      f"{per['acme']['quota']['sheds']} acme sheds contained, "
      f"1 bcorp hot-swap, 0 dropped, 0 post-warmup compiles")
EOF
python scripts/bench_check.py --tenants "$mt_dir/tenants.json" > /dev/null \
    || { echo "mt smoke: gate REFUSED honest tenant evidence"; exit 1; }
python - "$mt_dir" <<'EOF'
import json, sys
d = sys.argv[1]
man = json.load(open(d + "/tenants.json"))
man["tenants"][0]["quota_qps"] = -1
json.dump(man, open(d + "/tampered_manifest.json", "w"))
out = []
for ln in open(d + "/answers.jsonl"):
    rec = json.loads(ln)
    if rec.get("event") == "serve_drain":
        rec["tenants"]["acme"]["rejected"] = 0  # hide the sheds
    out.append(json.dumps(rec))
open(d + "/tampered_answers.jsonl", "w").write("\n".join(out) + "\n")
EOF
python scripts/bench_check.py --tenants "$mt_dir/tampered_manifest.json" > /dev/null \
    && { echo "mt smoke: gate ACCEPTED a tampered manifest"; exit 1; }
python scripts/bench_check.py --tenants "$mt_dir/tenants.json" \
    --answers-log "$mt_dir/tampered_answers.jsonl" > /dev/null \
    && { echo "mt smoke: gate ACCEPTED broken tenant cross-sums"; exit 1; }
echo "multi-tenant smoke OK (3 tenants one tier, routed answers, mid-traffic hot-swap, quota isolation + tenant-scoped alert, gate + teeth)"

echo "== gameday: composed-system soak (docs/RESILIENCE.md §8) =="
# The whole stack as one production-shaped group — snapshotting trainer
# (preempted mid-stream, relaunched, resumed), replicated serving tier
# (SLO admission, shadow scoring, snapshot/index hot-swap), watch
# evaluator — driven by the seeded compressed day while the chaos
# schedule arms every fault family.  The npairloss-gameday-v1 verdict
# IS the pass/fail contract: every injected fault alerted AND
# remediated, SLOs held outside declared incident windows, zero
# dropped queries across >= 3 live hot-swaps, comms fully attributed.
g_dir="$smoke_dir/gameday"
JAX_PLATFORMS=cpu python -m npairloss_tpu gameday \
    --out "$g_dir" --seed 0 --duration 75 > "$g_dir.cli.log" 2>&1 \
    || { echo "gameday: run failed"; tail -30 "$g_dir.cli.log"; \
         tail -30 "$g_dir/serve.log" 2>/dev/null; exit 1; }
python scripts/bench_check.py --gameday "$g_dir/gameday.json" \
    || { echo "gameday: gate refused a passing run"; exit 1; }
python - "$g_dir" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1] + "/gameday.json"))
assert r["verdict"] == "pass", r["failures"]
assert r["zero_drop"]["hot_swaps"] >= 3, r["zero_drop"]
assert r["zero_drop"]["queries_dropped"] == 0, r["zero_drop"]
bad = [f["name"] for f in r["faults"] if not f["ok"]]
assert not bad, bad
print(f"gameday: {len(r['faults'])} fault(s) injected+remediated, "
      f"{r['zero_drop']['hot_swaps']} hot-swap(s), 0 dropped, "
      f"{r['drain']['answered']} answered "
      f"(traffic sha {r['traffic']['sha256'][:12]})")
EOF
# gate teeth: a schema tamper and doctored evidence under a forged
# "pass" verdict must BOTH be refused (the validator recomputes every
# gate from the report's own evidence)
sed 's/npairloss-gameday-v1/npairloss-gameday-v0/' \
    "$g_dir/gameday.json" > "$g_dir/badschema.json"
python scripts/bench_check.py --gameday "$g_dir/badschema.json" > /dev/null \
    && { echo "gameday: gate ACCEPTED a schema violation"; exit 1; }
python - "$g_dir" <<'EOF'
import json, sys
d = sys.argv[1]
r = json.load(open(d + "/gameday.json"))
r["zero_drop"]["queries_dropped"] = 7  # doctored; verdict left "pass"
json.dump(r, open(d + "/tampered.json", "w"))
EOF
python scripts/bench_check.py --gameday "$g_dir/tampered.json" > /dev/null \
    && { echo "gameday: gate ACCEPTED doctored evidence under a pass verdict"; exit 1; }
echo "gameday smoke OK (compressed day, scripted chaos, verdict gate + teeth)"

echo "== tier-1 tests (ROADMAP.md) =="
rm -f /tmp/_t1.log
# `|| rc=$?` keeps set -e from aborting on test failures so the
# DOTS_PASSED diagnostic still prints; the script's exit code is the
# pytest pipeline's.
rc=0
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee /tmp/_t1.log || rc=$?
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit "$rc"
