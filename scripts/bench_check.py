#!/usr/bin/env python
"""Bench regression gate (docs/OBSERVABILITY.md §Perf observatory).

Walks the bench trajectory — ``bench_cache/bench_history.jsonl`` rows
plus the committed round artifacts (``BENCH_r*.json`` tails and
``bench_cache/last_good.json``) — and FAILS (exit != 0) when the newest
measured record regresses against the best earlier evidence, so an
emb/s or p99 regression dies in CI instead of being discovered a bench
round later.

Noise-aware thresholds, two-window-min semantics (bench round 5): every
measured row publishes ``min(ms_per_step_windows)`` and keeps both
windows; tunnel jitter is one-sided, so the spread between a row's own
windows IS its noise floor.  A row only counts as regressed when it
falls below the reference by MORE than ``max(--tol, spread_new,
spread_ref)`` — a jittery measurement widens its own gate instead of
crying wolf.

What is gated, per comparable record pair:
  * the headline ``value`` (emb/s, higher is better) — fresh
    measurements only (``headline_reused``/``degraded``/``stale``
    records carry evidence, they are not measurements);
  * every extras row with ``emb_per_sec`` (engine + batch-scaling
    rows), matched by name/path;
  * every extras row with ``p99_ms`` (serving rows; LOWER is better).
Rows present only on one side are coverage changes, not regressions.

Modes:
  * default: gate the JSONL history (``--history PATH``), newest row
    vs the best of the earlier ones;
  * ``--offline``: committed artifacts only (BENCH_r*.json +
    last_good.json) — no TPU, no history file needed; this is the
    ci.sh wiring.

Stdlib-only and jax-free by design (CI gates must never hang on a
backend import) — same contract as bench.py's parent.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HISTORY = os.path.join(REPO, "bench_cache", "bench_history.jsonl")
LAST_GOOD = os.path.join(REPO, "bench_cache", "last_good.json")
DEFAULT_TOL = 0.05
# Hard (absolute, not noise-relative) gates on the approximate-index
# bench row (ISSUE 11 / docs/SERVING.md §Approximate index): a faster-
# but-wrong index is a regression however smooth the trajectory, and an
# IVF path slower than 5x the flat scan has lost its reason to exist.
IVF_RECALL_FLOOR = 0.95
IVF_SPEEDUP_FLOOR = 5.0


def _log(msg: str) -> None:
    print(f"[bench_check] {msg}", file=sys.stderr, flush=True)


# -- record harvesting --------------------------------------------------------

def _is_measurement(rec: Dict[str, Any]) -> bool:
    """A record whose headline was measured THIS run (not reused/stale
    degraded-mode evidence) and looks like the flagship geometry."""
    return (
        isinstance(rec, dict)
        and isinstance(rec.get("value"), (int, float))
        and rec.get("value", 0) > 0
        and not rec.get("degraded")
        and not rec.get("stale")
        and not rec.get("headline_reused")
        and rec.get("mode", "full") == "full"
    )


def _json_candidates(text: str) -> List[Dict[str, Any]]:
    """Parse every JSON object found on its own line of ``text`` —
    committed BENCH_r*.json tails hold the child's stdout, where the
    record is the last JSON line (possibly truncated away)."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            continue
    return out


def load_offline_records() -> List[Tuple[str, Dict[str, Any]]]:
    """(source, record) pairs in round order from the committed
    artifacts; last_good.json (the newest full payload the bench
    committed) is appended last when it is not already represented."""
    records: List[Tuple[str, Dict[str, Any]]] = []
    rounds = sorted(
        glob.glob(os.path.join(REPO, "BENCH_r*.json")),
        key=lambda p: int(re.search(r"r(\d+)", os.path.basename(p)).group(1)),
    )
    for path in rounds:
        name = os.path.basename(path)
        try:
            with open(path) as f:
                art = json.load(f)
        except (OSError, ValueError) as e:
            _log(f"{name}: unreadable ({e}); skipped")
            continue
        cands = []
        if isinstance(art.get("parsed"), dict):
            cands.append(art["parsed"])
        cands.extend(_json_candidates(str(art.get("tail", ""))))
        measured = [c for c in cands if _is_measurement(c)]
        if measured:
            records.append((name, measured[-1]))
        else:
            _log(f"{name}: no fresh measurement (rc={art.get('rc')}); "
                 "skipped")
    try:
        with open(LAST_GOOD) as f:
            lg = json.load(f)
        payload = lg.get("payload") or {}
        if _is_measurement(payload):
            if not records or records[-1][1].get("value") != \
                    payload.get("value"):
                records.append((f"last_good ({lg.get('date')})", payload))
    except FileNotFoundError:
        pass
    except (OSError, ValueError) as e:
        _log(f"last_good.json unreadable ({e}); skipped")
    return records


def load_history_records(path: str) -> List[Tuple[str, Dict[str, Any]]]:
    records = []
    try:
        with open(path) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    _log(f"{path}:{i + 1}: bad JSON line skipped")
                    continue
                if _is_measurement(rec):
                    records.append((f"history[{i}]", rec))
    except FileNotFoundError:
        pass
    return records


# -- fleet-report gate --------------------------------------------------------

def _load_fleet_aggregate():
    """File-path-load ``obs.fleet.aggregate`` (and its ``stamp``
    dependency) WITHOUT importing the package — the jax-free contract.
    Pre-seeding the dotted names in sys.modules makes aggregate's own
    ``from npairloss_tpu.obs.fleet.stamp import ...`` resolve against
    the seeded module instead of triggering the jax-importing package
    ``__init__``."""
    import importlib.util

    base = os.path.join(REPO, "npairloss_tpu", "obs", "fleet")
    for name, fname in (
        ("npairloss_tpu.obs.fleet.stamp", "stamp.py"),
        ("npairloss_tpu.obs.fleet.aggregate", "aggregate.py"),
    ):
        if name in sys.modules:
            continue
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(base, fname))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return sys.modules["npairloss_tpu.obs.fleet.aggregate"]


def check_fleet_report(path: str,
                       expect_link: Optional[str] = None) -> List[str]:
    """Gate one fleet report artifact: schema-valid per the one
    contract (validate_fleet_report), per-rank step counts in
    agreement (ranks not training in lockstep is a broken fleet, not a
    measurement), and zero unattributed collective bytes when the
    comms join ran (an unclaimed collective kind means an exchange
    path went uninstrumented).  ``expect_link`` additionally pins the
    comms link kind — the multi-controller ci smoke demands "dcn"
    (collectives priced as crossing host processes), so a run that
    silently fell back to single-process pricing fails the gate."""
    try:
        with open(path) as f:
            report = json.load(f)
    except (OSError, ValueError) as e:
        return [f"fleet report {path} unreadable: {e}"]
    agg = _load_fleet_aggregate()
    err = agg.validate_fleet_report(report)
    if err is not None:
        return [f"fleet report schema-invalid: {err}"]
    violations: List[str] = []
    counts = {r["rank"]: r["steps"] for r in report["ranks"]}
    if len(set(counts.values())) > 1:
        violations.append(
            f"per-rank step counts disagree: {counts} — refusing the "
            "fleet report (ranks did not train in lockstep, or a "
            "stream was truncated)")
    elif not any(counts.values()):
        # All-zero counts AGREE, but a fleet that measured nothing is
        # a dead run (streams lost before the first flush), not a
        # passing one.
        violations.append(
            f"every rank reports 0 steps: {counts} — the fleet "
            "measured nothing (streams lost or training never ran)")
    comms = report.get("comms", {})
    if comms.get("available") and comms.get("unattributed_bytes", 0) > 0:
        violations.append(
            f"{comms['unattributed_bytes']:.0f} collective bytes "
            "unattributed — an exchange path is missing its comm/ "
            "instrumentation")
    if expect_link is not None:
        if not comms.get("available"):
            violations.append(
                f"comms join unavailable but --expect-link {expect_link} "
                "was demanded (no fleet_comms.json priced)")
        elif comms.get("link") != expect_link:
            violations.append(
                f"comms link is {comms.get('link')!r}, expected "
                f"{expect_link!r} — the run did not price its "
                "collectives as crossing host processes")
    if not violations:
        _log(f"fleet report OK ({len(counts)} rank(s), "
             f"{next(iter(counts.values()))} steps each)")
    return violations


# -- alert-log gate -----------------------------------------------------------

def _load_live_alerts():
    """File-path-load ``obs.live.alerts`` WITHOUT importing the package
    (the jax-free contract; same pattern as the fleet loader above —
    alerts.py is deliberately self-contained, so no pre-seeding chain
    is needed beyond its own name)."""
    import importlib.util

    name = "npairloss_tpu.obs.live.alerts"
    if name not in sys.modules:
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(REPO, "npairloss_tpu", "obs", "live",
                               "alerts.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return sys.modules[name]


def check_alert_log(path: str) -> List[str]:
    """Gate one ``npairloss-alerts-v1`` JSONL artifact: schema-valid
    per the one contract (validate_alert_log), and no CRITICAL alert
    left unresolved — a run that drained while a critical SLO was
    still burning is a failed run, not a noisy one.  Resolved alerts
    of any severity and unresolved warnings are evidence, not
    failures."""
    alerts = _load_live_alerts()
    try:
        records = alerts.load_alert_log(path)
    except OSError as e:
        return [f"alert log {path} unreadable: {e}"]
    err = alerts.validate_alert_log(records)
    if err is not None:
        return [f"alert log schema-invalid: {err}"]
    violations = []
    for alert_id, slo, severity in alerts.unresolved_alerts(records):
        if severity == "critical":
            violations.append(
                f"critical alert {alert_id!r} (SLO {slo!r}) still "
                "firing at end of log — the run drained while burning")
        else:
            _log(f"unresolved {severity} alert {alert_id!r} "
                 f"(SLO {slo!r}) — noted, not gated")
    if not violations:
        fired = sum(1 for r in records if r["state"] == "firing")
        _log(f"alert log OK ({len(records)} event(s), {fired} "
             "alert(s) fired)")
    return violations


# -- remediation-log gate -----------------------------------------------------

def _load_remediate():
    """File-path-load ``resilience.remediate`` (self-contained, stdlib
    only — the same contract as the alerts module) WITHOUT importing
    the package."""
    import importlib.util

    name = "npairloss_tpu.resilience.remediate"
    if name not in sys.modules:
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(REPO, "npairloss_tpu", "resilience",
                               "remediate.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return sys.modules[name]


def check_remediation_log(path: str,
                          alerts_path: Optional[str] = None) -> List[str]:
    """Gate one ``npairloss-remediation-v1`` audit artifact: schema +
    lifecycle valid per the one contract (validate_remediation_log),
    every action justified by an alert that actually FIRED (cross-
    checked against the paired alerts.jsonl — default: the one next to
    the audit log; an audit with actions but NO alert log is refused,
    because an unjustifiable action cannot be distinguished from a
    justified one), and no CRITICAL incident abandoned mid-budget (a
    failed attempt with attempts remaining and no retry is an actuator
    walking away from a live incident).  Outcome-less attempts (killed
    mid-action) are noted, not gated — the alert gate owns the
    unresolved-incident verdict."""
    rem = _load_remediate()
    try:
        records = rem.load_remediation_log(path)
    except OSError as e:
        return [f"remediation log {path} unreadable: {e}"]
    if alerts_path is None:
        alerts_path = os.path.join(os.path.dirname(os.path.abspath(path)),
                                   "alerts.jsonl")
    alert_records = None
    if os.path.exists(alerts_path):
        alerts = _load_live_alerts()
        try:
            alert_records = alerts.load_alert_log(alerts_path)
        except OSError as e:
            return [f"alert log {alerts_path} unreadable: {e}"]
    elif records:
        return [f"remediation log holds {len(records)} record(s) but no "
                f"alert log exists at {alerts_path} — actions cannot be "
                "justified (action-without-alert refused)"]
    err = rem.validate_remediation_log(records,
                                       alert_records=alert_records)
    if err is not None:
        return [f"remediation log invalid: {err}"]
    violations = []
    # Incidents the alert log shows RESOLVED are never abandonment —
    # an alert that healed after a failed attempt needed no retry.
    resolved = {str(r.get("alert_id")) for r in (alert_records or ())
                if isinstance(r, dict) and r.get("state") == "resolved"}
    for rec_id, policy, aid in rem.abandoned_remediations(
            records, resolved_alert_ids=resolved):
        violations.append(
            f"critical remediation {rec_id!r} (policy {policy!r}, alert "
            f"{aid!r}) failed with attempts remaining and was never "
            "retried — the actuator gave up on a live incident")
    for rec_id, policy, aid in rem.unresolved_remediations(records):
        _log(f"attempt {rec_id!r} (policy {policy!r}, alert {aid!r}) "
             "has no outcome — noted, not gated")
    if not violations:
        attempted = sum(1 for r in records if r["state"] == "attempted")
        ok = sum(1 for r in records if r["state"] == "succeeded")
        _log(f"remediation log OK ({len(records)} event(s), {attempted} "
             f"attempt(s), {ok} succeeded)")
    return violations


# -- quality-log gate ---------------------------------------------------------

def _load_quality():
    """File-path-load ``obs.quality.report`` (self-contained, stdlib
    only — the same contract as the alerts/remediate modules) WITHOUT
    importing the package."""
    import importlib.util

    name = "npairloss_tpu.obs.quality.report"
    if name not in sys.modules:
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(REPO, "npairloss_tpu", "obs", "quality",
                               "report.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return sys.modules[name]


def _load_gameday():
    """File-path-load ``gameday.verdict`` (self-contained, stdlib only
    — the same contract as the alerts/remediate/quality modules)
    WITHOUT importing the package."""
    import importlib.util

    name = "npairloss_tpu.gameday.verdict"
    if name not in sys.modules:
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(REPO, "npairloss_tpu", "gameday",
                               "verdict.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return sys.modules[name]


def _load_tenants():
    """File-path-load ``serve.tenants`` (module level stdlib-only —
    the same contract as the alerts/remediate/quality/gameday modules)
    WITHOUT importing the package.  The manifest schema id lives in
    that module alone; this gate never restates the literal."""
    import importlib.util

    name = "npairloss_tpu.serve.tenants"
    if name not in sys.modules:
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(REPO, "npairloss_tpu", "serve",
                               "tenants.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return sys.modules[name]


def check_tenants(manifest_path: str,
                  answers_path: Optional[str] = None) -> List[str]:
    """Gate one multi-tenant serving run: the tenants manifest must be
    schema-valid per the one contract (validate_tenants_manifest — a
    tampered manifest with unknown keys, a duplicate tenant id, or an
    out-of-range quota is refused with every problem listed), and —
    when an answers log sits next to it (or is named via
    ``--answers-log``) — the run's evidence must be tenant-consistent:
    no answer claiming an unregistered tenant id, per-tenant drain
    counters that cross-sum EXACTLY into the aggregates (quota
    accounting that leaks across tenants shows up as a sum mismatch),
    and recall evidence per tenant (an aggregate quality block with no
    per-tenant breakdown hides exactly the noisy-neighbor regression
    this tier exists to catch)."""
    tmod = _load_tenants()
    try:
        with open(manifest_path, "r", encoding="utf-8") as f:
            manifest = json.load(f)
    except OSError as e:
        return [f"tenants manifest {manifest_path} unreadable: {e}"]
    except ValueError as e:
        return [f"tenants manifest {manifest_path} not JSON: {e}"]
    problems = tmod.validate_tenants_manifest(manifest)
    if problems:
        return [f"tenants manifest refused: {p}" for p in problems]
    specs = {t["tenant_id"]: t for t in manifest["tenants"]}

    if answers_path is None:
        cand = os.path.join(
            os.path.dirname(os.path.abspath(manifest_path)),
            "answers.jsonl")
        answers_path = cand if os.path.exists(cand) else None
    if answers_path is None:
        _log(f"tenants manifest OK ({len(specs)} tenant(s); no "
             "answers log to cross-check)")
        return []
    answers: List[Dict[str, Any]] = []
    try:
        with open(answers_path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    answers.append(json.loads(line))
                except ValueError:
                    continue  # torn tail
    except OSError as e:
        return [f"answers log {answers_path} unreadable: {e}"]

    violations: List[str] = []
    unknown = sorted({a["tenant"] for a in answers
                      if isinstance(a, dict)
                      and isinstance(a.get("tenant"), str)
                      and a["tenant"] not in specs})
    if unknown:
        violations.append(
            f"answers claim unregistered tenant id(s) {unknown} — an "
            "unknown tenant must be refused as an error, never served")
    drain = None
    for a in answers:
        if isinstance(a, dict) and a.get("event") == "serve_drain":
            drain = a
    if drain is None:
        violations.append(
            f"{answers_path}: no serve_drain summary — the per-tenant "
            "accounting cannot be audited")
        return violations
    per = drain.get("tenants")
    if not isinstance(per, dict) or not per:
        violations.append(
            "drain summary has no per-tenant block — a multi-tenant "
            "run must leave per-tenant evidence")
        return violations
    extra = sorted(set(per) - set(specs))
    if extra:
        violations.append(
            f"drain reports unregistered tenant(s) {extra}")
    # The aggregates must be EXACTLY the per-tenant sums: a quota or
    # shed accounted against the wrong tenant cancels nowhere and
    # shows up as a sum mismatch.
    # "errors" alone admits an explicit remainder: unknown-tenant
    # refusals and bad JSON are never admitted, so no tenant row can
    # own them — the drain's errors_unattributed names that count and
    # the identity stays EXACT (a negative or unexplained remainder is
    # still refused).
    unattributed = drain.get("errors_unattributed", 0)
    if not isinstance(unattributed, int) or unattributed < 0:
        violations.append(
            f"errors_unattributed {unattributed!r} is not a "
            "non-negative count")
        unattributed = 0
    for key in ("queries", "answered", "errors", "rejected"):
        agg = drain.get(key)
        total = sum(int(row.get(key, 0)) for row in per.values()
                    if isinstance(row, dict))
        if key == "errors":
            total += unattributed
        if isinstance(agg, int) and total != agg:
            violations.append(
                f"per-tenant {key} sum {total} != aggregate {agg} — "
                "the tenant accounting does not cross-sum"
                + (" (errors_unattributed included)"
                   if key == "errors" else ""))
    if "quality" in drain:
        violations.append(
            "aggregate quality block in a multi-tenant drain — recall "
            "evidence must live inside each tenant's block (one "
            "cross-tenant average hides a single tenant's collapse)")
    for tid, spec in specs.items():
        row = per.get(tid)
        if not isinstance(row, dict):
            violations.append(
                f"tenant {tid!r} missing from the drain's per-tenant "
                "block")
            continue
        if spec.get("recall_floor") is not None \
                and "quality" not in row:
            violations.append(
                f"tenant {tid!r} declares recall_floor "
                f"{spec['recall_floor']} but its drain block carries "
                "no quality evidence (shadow scorer never armed?)")
    if not violations:
        served = sum(int(row.get("answered", 0))
                     for row in per.values() if isinstance(row, dict))
        _log(f"tenants evidence OK ({len(specs)} tenant(s), "
             f"{served} answered, per-tenant sums match the "
             "aggregates)")
    return violations


def _load_qtrace():
    """File-path-load ``obs.qtrace.report`` (self-contained, stdlib
    only — the same contract as the alerts/remediate/quality/gameday
    modules) WITHOUT importing the package."""
    import importlib.util

    name = "npairloss_tpu.obs.qtrace.report"
    if name not in sys.modules:
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(REPO, "npairloss_tpu", "obs", "qtrace",
                               "report.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return sys.modules[name]


def check_qtrace_log(path: str) -> List[str]:
    """Gate one ``npairloss-qtrace-v1`` exemplar artifact: schema-valid
    per the one contract (validate_qtrace_report — stage vocabulary,
    span nesting/ordering, trace-id uniqueness) AND internally
    consistent (qtrace_p99_consistency — an exemplar set whose worst
    span tree disagrees with the logged p99 budget by more than the
    artifact's declared ring tolerance is doctored evidence: the
    retention rule guarantees the worst query is always retained)."""
    qmod = _load_qtrace()
    try:
        report = qmod.load_qtrace_report(path)
    except OSError as e:
        return [f"qtrace artifact {path} unreadable: {e}"]
    except ValueError as e:
        return [f"qtrace artifact {path} not JSON: {e}"]
    err = qmod.validate_qtrace_report(report)
    if err is not None:
        return [f"qtrace artifact refused: {err}"]
    err = qmod.qtrace_p99_consistency(report)
    if err is not None:
        return [f"qtrace artifact inconsistent: {err}"]
    totals = report["totals"]
    budget = report["budget"]
    _log(f"qtrace artifact OK ({totals['queries']} query(ies), "
         f"{totals['exemplars']} exemplar(s), p99 "
         f"{budget['p99_ms']:.1f}ms dominated by "
         f"{budget['dominant'] or 'n/a'})")
    return []


def _load_wal():
    """File-path-load ``resilience.wal`` + its jax-free seams
    (failpoints, retrying) WITHOUT importing the package — the
    multi-module pre-seed idiom of ``_load_staticcheck``: parent
    package names are stubbed and each loaded leaf is set as an
    attribute so wal.py's guarded ``from npairloss_tpu.resilience
    import failpoints`` resolves."""
    import importlib.util
    import types

    name = "npairloss_tpu.resilience.wal"
    if name in sys.modules:
        return sys.modules[name]
    pkg = "npairloss_tpu.resilience"
    for stub in ("npairloss_tpu", pkg):
        if stub not in sys.modules:
            sys.modules[stub] = types.ModuleType(stub)
    base = os.path.join(REPO, "npairloss_tpu", "resilience")
    for leaf in ("failpoints", "retrying", "wal"):
        mod_name = f"{pkg}.{leaf}"
        if mod_name in sys.modules:
            setattr(sys.modules[pkg], leaf, sys.modules[mod_name])
            continue
        spec = importlib.util.spec_from_file_location(
            mod_name, os.path.join(base, leaf + ".py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[mod_name] = mod
        spec.loader.exec_module(mod)
        setattr(sys.modules[pkg], leaf, mod)
    return sys.modules[name]


def check_wal_dir(path: str,
                  min_last_seq: Optional[int] = None) -> List[str]:
    """Gate one ``npairloss-wal-v1`` directory: manifest schema-valid
    per the one contract (validate_wal_dir — record CRCs, sealed-
    segment seals, contiguous sequence numbers; a torn tail on the
    FINAL segment is a crash artifact and passes), and — with
    ``--wal-watermark`` — refusing a log whose last replayable record
    falls short of the externally acknowledged watermark (the
    truncated-then-patched copy the ci.sh cold-restart smoke feeds
    it)."""
    wal_mod = _load_wal()
    err = wal_mod.validate_wal_dir(path, min_last_seq=min_last_seq)
    if err is not None:
        return [f"wal artifact refused: {err}"]
    info = wal_mod.wal_info(path)
    torn = (f", torn tail: {info['torn_bytes']} byte(s) in "
            f"{info['torn_segment']}" if info.get("torn_tail") else "")
    _log(f"wal artifact OK ({info['segments']} segment(s), "
         f"{info['records']} record(s), last_seq {info['last_seq']}"
         f"{torn})")
    return []


def check_gameday_report(path: str) -> List[str]:
    """Gate one ``npairloss-gameday-v1`` verdict: schema-valid and
    PASSING per the one contract (validate_gameday_report recomputes
    every gate from the report's own evidence — schema violations,
    an unremediated injected fault, an SLO breach outside the declared
    incident windows, a dropped query, or a tampered ``verdict:
    "pass"`` are all refused).  When the run directory's serve alert
    log sits next to the report, the fault blocks are additionally
    cross-checked against it: a fault claiming its alert fired while
    the on-disk log shows no firing for that SLO is a fabricated
    report, refused."""
    gmod = _load_gameday()
    try:
        report = gmod.load_gameday_report(path)
    except OSError as e:
        return [f"gameday report {path} unreadable: {e}"]
    except ValueError as e:
        return [f"gameday report {path} not JSON: {e}"]
    err = gmod.validate_gameday_report(report)
    if err is not None:
        return [f"gameday verdict refused: {err}"]
    violations: List[str] = []
    alerts_path = os.path.join(
        os.path.dirname(os.path.abspath(path)), "serve_tel",
        "alerts.jsonl")
    if os.path.exists(alerts_path):
        alerts = _load_live_alerts()
        try:
            records = alerts.load_alert_log(alerts_path)
        except OSError as e:
            return [f"alert log {alerts_path} unreadable: {e}"]
        fired_slos = {r.get("slo") for r in records
                      if isinstance(r, dict)
                      and r.get("state") == "firing"}
        for fault in report.get("faults", []):
            if (fault.get("target") == "serve" and fault.get("alert")
                    and fault.get("alert_fired")
                    and fault["alert"] not in fired_slos):
                violations.append(
                    f"fault {fault.get('name')}: report claims alert "
                    f"{fault['alert']!r} fired but {alerts_path} shows "
                    "no firing for it — fabricated evidence")
    if not violations:
        zero = report["zero_drop"]
        _log(f"gameday verdict OK ({len(report['faults'])} fault(s) "
             f"remediated, {zero['hot_swaps']} hot-swap(s), "
             f"{zero['queries_dropped']} dropped)")
    return violations


def check_quality_log(path: str,
                      alerts_path: Optional[str] = None) -> List[str]:
    """Gate one ``npairloss-quality-v1`` shadow-recall artifact:
    schema-valid per the one contract (validate_quality_report); every
    window that breached the DECLARED recall floor must be matched by a
    recall alert that actually FIRED (cross-checked against the paired
    alerts.jsonl — a breach with no alert log at all is refused, since
    an unobserved quality regression cannot be distinguished from an
    observed one); and the shadow scorer must not have silently stopped
    sampling mid-run (the summary's stale last-sample wall time).
    Breaches WITH a fired alert are evidence the loop worked, not
    failures — the alert gate owns the unresolved-incident verdict."""
    qmod = _load_quality()
    try:
        records = qmod.load_quality_report(path)
    except OSError as e:
        return [f"quality log {path} unreadable: {e}"]
    err = qmod.validate_quality_report(records)
    if err is not None:
        return [f"quality log schema-invalid: {err}"]
    violations: List[str] = []
    breaches = qmod.quality_breaches(records)
    if breaches:
        if alerts_path is None:
            alerts_path = os.path.join(
                os.path.dirname(os.path.abspath(path)), "alerts.jsonl")
        fired_metrics = set()
        if os.path.exists(alerts_path):
            alerts = _load_live_alerts()
            try:
                alert_records = alerts.load_alert_log(alerts_path)
            except OSError as e:
                return [f"alert log {alerts_path} unreadable: {e}"]
            fired_metrics = {r.get("metric") for r in alert_records
                             if isinstance(r, dict)
                             and r.get("state") == "firing"}
        for i, metric, recall, floor in breaches:
            if metric not in fired_metrics:
                violations.append(
                    f"window record {i}: recall {recall:.4f} below the "
                    f"declared floor {floor:g} with NO fired alert on "
                    f"{metric!r} ({alerts_path}) — the quality SLO "
                    "slept through a real regression")
        matched = sum(1 for _, m, _, _ in breaches if m in fired_metrics)
        if matched:
            _log(f"{matched} floor breach(es) matched by a fired recall "
                 "alert — the loop observed them; noted, not gated")
    stale = qmod.stale_shadow(records)
    if stale is not None:
        violations.append(f"quality log: {stale}")
    if not violations:
        summary = qmod.quality_summary(records)
        _log(f"quality log OK ({summary['windows']} window(s), "
             f"{summary['sampled_total']} sample(s), "
             f"{summary['breaches']} breach(es))")
    return violations


# -- staticcheck gate ---------------------------------------------------------

def _load_staticcheck():
    """File-path-load the ``npairloss_tpu.analysis`` chain WITHOUT
    importing the package (the jax-free contract).  Unlike the
    single-file loaders above, the suite is a multi-module package
    whose driver does ``from npairloss_tpu.analysis import contracts``
    — so the parent package names are seeded as stub modules and each
    loaded submodule is set as an attribute on its parent."""
    import importlib.util
    import types

    pkg = "npairloss_tpu.analysis"
    if pkg in sys.modules:
        return sys.modules[pkg + ".runner"]
    for stub in ("npairloss_tpu", pkg):
        if stub not in sys.modules:
            sys.modules[stub] = types.ModuleType(stub)
    base = os.path.join(REPO, "npairloss_tpu", "analysis")
    # Dependency order: leaves first, the driver last.
    for leaf in ("findings", "tree", "report", "purity", "scopes",
                 "locks", "contracts", "vocab", "markers", "runner"):
        name = f"{pkg}.{leaf}"
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(base, leaf + ".py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
        setattr(sys.modules[pkg], leaf, mod)
    return sys.modules[pkg + ".runner"]


def check_static(root: str, diff_base: Optional[str] = None) -> List[str]:
    """Run the invariant linter over ``root`` (docs/STATICCHECK.md):
    every finding not in the tree's committed allowlist is a
    violation.  The ci.sh staticcheck-stage wiring — and the teeth the
    seeded fixture trees under tests/fixtures/staticcheck are held
    to."""
    runner = _load_staticcheck()
    try:
        report = runner.run_suite(root, diff_base=diff_base)
    except ValueError as e:
        return [f"staticcheck could not run: {e}"]
    violations = [
        f"staticcheck [{rec['pass']}] {rec['path']}:{rec['line']}: "
        f"{rec['message']}"
        for rec in report["findings"]
    ]
    if not violations:
        ran = [p["name"] for p in report["passes"] if not p["skipped"]]
        skipped = [p["name"] for p in report["passes"] if p["skipped"]]
        _log(f"staticcheck OK ({', '.join(ran)}"
             + (f"; skipped: {', '.join(skipped)}" if skipped else "")
             + f"; {report['summary']['allowlisted']} allowlisted)")
    return violations


# -- the gate -----------------------------------------------------------------

def _ivf_hard_gates(new_rows: Dict[str, Dict]) -> List[str]:
    """Absolute gates on the newest record's ``ivf_qps_1m`` row: the
    recall@1 floor against the flat oracle, and the minimum qps speedup
    over the ``flat_qps_1m`` twin measured in the same pass.  Rows
    absent = coverage unchanged, nothing to gate."""
    out: List[str] = []
    ivf = new_rows.get("ivf_qps_1m")
    if not isinstance(ivf, dict):
        return out
    r1 = ivf.get("recall_at_1")
    if isinstance(r1, (int, float)):
        if r1 < IVF_RECALL_FLOOR:
            out.append(
                f"ivf_qps_1m: recall@1 {r1:.4f} < hard floor "
                f"{IVF_RECALL_FLOOR} (approximate answers drifted from "
                "the brute-force oracle)")
        else:
            _log(f"ivf recall@1 {r1:.4f} >= floor {IVF_RECALL_FLOOR}")
    flat = new_rows.get("flat_qps_1m")
    ivf_qps, flat_qps = ivf.get("qps"), (flat or {}).get("qps")
    if isinstance(ivf_qps, (int, float)) and \
            isinstance(flat_qps, (int, float)) and flat_qps > 0:
        speedup = ivf_qps / flat_qps
        if speedup < IVF_SPEEDUP_FLOOR:
            out.append(
                f"ivf_qps_1m: {speedup:.1f}x flat qps < hard floor "
                f"{IVF_SPEEDUP_FLOOR}x ({ivf_qps:.1f} vs {flat_qps:.1f} "
                "qps at the 1M gallery)")
        else:
            _log(f"ivf speedup {speedup:.1f}x flat "
                 f">= floor {IVF_SPEEDUP_FLOOR}x")
    return out


def _fused_probe_gates(new: Dict[str, Any]) -> List[str]:
    """Shape + hard gates on the fused-Pallas probe rows (ISSUE 19),
    jax-free off the record dict alone: a measured ``ivf_fused_qps_1m``
    must carry its RESOLVED impl, a declared pipeline dispatch count
    <= 2 (the 4 -> 2 claim the row exists to stamp), and the same
    recall@1 floor as the scan row; ``ivf_probe_kernel_micro`` must
    declare both impls' dispatch counts and a measured scan clock.
    Skipped/error/absent rows = coverage unchanged, nothing to gate."""
    out: List[str] = []
    extras = new.get("extras")
    extras = extras if isinstance(extras, dict) else {}

    def measured(name):
        row = extras.get(name)
        if isinstance(row, dict) and "error" not in row \
                and not row.get("skipped"):
            return row
        return None

    fused = measured("ivf_fused_qps_1m")
    if fused is not None:
        if fused.get("probe_impl") != "fused":
            out.append(
                f"ivf_fused_qps_1m: probe_impl {fused.get('probe_impl')!r}"
                " != 'fused' (the row must stamp the RESOLVED impl it "
                "measured)")
        dc = fused.get("dispatch_count")
        if not isinstance(dc, int) or isinstance(dc, bool) or dc > 2:
            out.append(
                f"ivf_fused_qps_1m: dispatch_count {dc!r} is not an "
                "int <= 2 (the fused probe path's whole claim)")
        r1 = fused.get("recall_at_1")
        if isinstance(r1, (int, float)) and r1 < IVF_RECALL_FLOOR:
            out.append(
                f"ivf_fused_qps_1m: recall@1 {r1:.4f} < hard floor "
                f"{IVF_RECALL_FLOOR} (the kernel drifted from the "
                "brute-force oracle)")
        elif isinstance(r1, (int, float)):
            _log(f"fused recall@1 {r1:.4f} >= floor {IVF_RECALL_FLOOR}")
    micro = measured("ivf_probe_kernel_micro")
    if micro is not None:
        fd = micro.get("fused_dispatches")
        if not isinstance(fd, int) or isinstance(fd, bool) or fd > 2:
            out.append(
                f"ivf_probe_kernel_micro: fused_dispatches {fd!r} is "
                "not an int <= 2")
        sd = micro.get("scan_dispatches")
        if not isinstance(sd, int) or isinstance(sd, bool) \
                or (isinstance(fd, int) and fd >= sd):
            out.append(
                f"ivf_probe_kernel_micro: scan_dispatches {sd!r} must "
                "be an int above fused_dispatches — the row records the "
                "dispatch-count DROP")
        if not isinstance(micro.get("scan_ms"), (int, float)):
            out.append(
                "ivf_probe_kernel_micro: scan_ms missing/non-numeric "
                "(the baseline clock the fused claim compares against)")
    return out


def _spread(rec: Dict[str, Any]) -> float:
    """Relative window spread = the record's own measured noise floor
    (two-window-min semantics: the min is published, the spread is the
    jitter evidence)."""
    w = rec.get("ms_per_step_windows")
    if isinstance(w, list) and len(w) >= 2:
        ws = [float(x) for x in w if isinstance(x, (int, float)) and x > 0]
        if len(ws) >= 2 and min(ws) > 0:
            return (max(ws) - min(ws)) / min(ws)
    return 0.0


def _walk_rows(rec: Dict[str, Any], prefix: str = "") -> Dict[str, Dict]:
    """Flatten extras into {path: row} for every dict carrying a
    gateable metric; error/skipped rows are not measurements."""
    out: Dict[str, Dict] = {}
    extras = rec.get("extras") if not prefix else rec
    if not isinstance(extras, dict):
        return out
    for name, row in extras.items():
        if not isinstance(row, dict):
            continue
        path = f"{prefix}{name}"
        if "error" in row or row.get("skipped"):
            continue
        if any(isinstance(row.get(k), (int, float))
               for k in ("emb_per_sec", "p99_ms")):
            out[path] = row
        else:
            out.update(_walk_rows(row, prefix=path + "/"))
    return out


def check(
    records: List[Tuple[str, Dict[str, Any]]],
    tol: float = DEFAULT_TOL,
) -> List[str]:
    """Newest record vs the best earlier evidence; returns the list of
    violations (empty = gate passes)."""
    if len(records) < 2:
        _log(f"{len(records)} measured record(s) — nothing to gate")
        return []
    new_src, new = records[-1]
    violations: List[str] = []

    # Headline: higher is better; reference = best earlier value, with
    # its own windows' spread as that reference's noise contribution.
    best_src, best = max(records[:-1], key=lambda r: r[1]["value"])
    best_value, best_spread = best["value"], _spread(best)
    # A POLICY headline (the precision-policy flagship, ISSUE 7) must
    # additionally clear the best earlier measured googlenet_mxu bar —
    # the mxu trunk's own throughput (21.91 ms / 5477.5 emb/s at r05)
    # is the floor the policy default exists to beat, so a policy
    # flagship slower than the plain mxu row is a regression even when
    # it beats the old prototxt-trunk headlines.  Pre-policy records
    # are never gated against the bar (their headline IS the plain
    # trunk); the r01–r05 trajectory stays comparable untouched.
    if new.get("policy"):
        for src, rec in records[:-1]:
            row = _walk_rows(rec).get("batch_scaling/120_mxu")
            if row and isinstance(row.get("emb_per_sec"), (int, float)) \
                    and row["emb_per_sec"] > best_value:
                best_src = f"{src} (120_mxu bar)"
                best_value, best_spread = row["emb_per_sec"], _spread(row)
    eff = max(tol, _spread(new), best_spread)
    floor = best_value * (1.0 - eff)
    verdict = "OK" if new["value"] >= floor else "REGRESSED"
    _log(f"headline: {new['value']:.1f} ({new_src}) vs best "
         f"{best_value:.1f} ({best_src}), tol {eff:.1%} -> {verdict}")
    if verdict != "OK":
        violations.append(
            f"headline emb/s {new['value']:.1f} < {floor:.1f} "
            f"(best {best_value:.1f} from {best_src}, tol {eff:.1%})")

    # Per-row gates against the most recent earlier record carrying the
    # same row (engine rows are re-measured selectively; the freshest
    # prior evidence is the comparison that means something).
    new_rows = _walk_rows(new)
    for path, row in sorted(new_rows.items()):
        ref_row, ref_src = None, None
        for src, rec in reversed(records[:-1]):
            cand = _walk_rows(rec).get(path)
            if cand is not None:
                ref_row, ref_src = cand, src
                break
        if ref_row is None:
            continue
        eff = max(tol, _spread(row), _spread(ref_row))
        if isinstance(row.get("emb_per_sec"), (int, float)) and \
                isinstance(ref_row.get("emb_per_sec"), (int, float)):
            floor = ref_row["emb_per_sec"] * (1.0 - eff)
            if row["emb_per_sec"] < floor:
                violations.append(
                    f"{path}: emb/s {row['emb_per_sec']:.1f} < "
                    f"{floor:.1f} (ref {ref_row['emb_per_sec']:.1f} from "
                    f"{ref_src}, tol {eff:.1%})")
        if isinstance(row.get("p99_ms"), (int, float)) and \
                isinstance(ref_row.get("p99_ms"), (int, float)) and \
                ref_row["p99_ms"] > 0:
            ceil = ref_row["p99_ms"] * (1.0 + eff)
            if row["p99_ms"] > ceil:
                violations.append(
                    f"{path}: p99 {row['p99_ms']:.2f} ms > {ceil:.2f} ms "
                    f"(ref {ref_row['p99_ms']:.2f} from {ref_src}, "
                    f"tol {eff:.1%})")
    violations.extend(_ivf_hard_gates(new_rows))
    violations.extend(_fused_probe_gates(new))
    return violations


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="noise-aware bench regression gate")
    ap.add_argument(
        "--offline", action="store_true",
        help="gate the committed BENCH_r*.json + last_good.json only "
        "(no history file, no TPU) — the ci.sh mode",
    )
    ap.add_argument(
        "--history", default=HISTORY,
        help="bench trajectory JSONL (default bench_cache/"
        "bench_history.jsonl); offline records are appended before it "
        "unless --offline",
    )
    ap.add_argument(
        "--tol", type=float, default=DEFAULT_TOL,
        help="base relative tolerance before the per-record window "
        "spread widens it (default 0.05)",
    )
    ap.add_argument(
        "--fleet-report", dest="fleet_report", metavar="PATH",
        help="gate a fleet report artifact instead of the bench "
        "trajectory: schema-valid (npairloss-fleet-report-v1), "
        "per-rank step counts agree, zero unattributed collective "
        "bytes — the ci.sh fleet-smoke wiring",
    )
    ap.add_argument(
        "--expect-link", dest="expect_link", choices=["ici", "dcn"],
        help="with --fleet-report: additionally require the comms "
        "block's link kind (the multi-controller smoke pins 'dcn')",
    )
    ap.add_argument(
        "--alerts", metavar="PATH",
        help="gate a live-observatory alert log instead of the bench "
        "trajectory: schema-valid (npairloss-alerts-v1) and no "
        "unresolved critical alert — the ci.sh live-obs-smoke wiring",
    )
    ap.add_argument(
        "--remediation", metavar="PATH",
        help="gate a remediation audit log instead of the bench "
        "trajectory: schema-valid (npairloss-remediation-v1), every "
        "action justified by a fired alert, no abandoned critical "
        "remediation — the ci.sh chaos-suite wiring",
    )
    ap.add_argument(
        "--alerts-log", dest="alerts_log", metavar="PATH",
        help="with --remediation/--quality: the paired alerts.jsonl "
        "for the cross-checks (default: alerts.jsonl next to the "
        "gated log)",
    )
    ap.add_argument(
        "--quality", metavar="PATH",
        help="gate a shadow-recall quality log instead of the bench "
        "trajectory: schema-valid (npairloss-quality-v1), every "
        "recall-floor breach matched by a fired alert, no silently-"
        "stalled shadow scorer — the ci.sh quality-smoke wiring",
    )
    ap.add_argument(
        "--gameday", metavar="PATH",
        help="gate a gameday verdict instead of the bench trajectory: "
        "schema-valid (npairloss-gameday-v1) and PASSING — every "
        "injected fault remediated, SLOs held outside incident "
        "windows, zero dropped queries across the hot-swaps — with "
        "the fault blocks cross-checked against the run's serve "
        "alert log when present — the ci.sh gameday-stage wiring",
    )
    ap.add_argument(
        "--qtrace", metavar="PATH",
        help="gate a query-trace exemplar artifact instead of the "
        "bench trajectory: schema-valid (npairloss-qtrace-v1), stage "
        "vocabulary and span nesting intact, trace ids unique, and "
        "the exemplar worst case consistent with the logged p99 "
        "budget within the ring tolerance — the ci.sh qtrace-smoke "
        "wiring",
    )
    ap.add_argument(
        "--wal", metavar="PATH",
        help="gate a durable-ingest WAL directory instead of the "
        "bench trajectory: schema-valid (npairloss-wal-v1), record "
        "CRCs and sealed-segment seals intact, sequence numbers "
        "contiguous — the ci.sh cold-restart-smoke wiring",
    )
    ap.add_argument(
        "--wal-watermark", dest="wal_watermark", type=int,
        metavar="SEQ",
        help="with --wal: additionally refuse a log whose last "
        "replayable record falls short of this acknowledged sequence "
        "number (a truncated-then-patched copy)",
    )
    ap.add_argument(
        "--static", nargs="?", const=REPO, default=None, metavar="ROOT",
        help="run the invariant linter (docs/STATICCHECK.md) over ROOT "
        "(default: this repo) instead of the bench trajectory and fail "
        "on any finding outside the committed allowlist — the ci.sh "
        "staticcheck-stage wiring",
    )
    ap.add_argument(
        "--static-diff", dest="static_diff", metavar="BASE",
        help="with --static: restrict findings to files changed since "
        "the git ref (the fast incremental hook)",
    )
    ap.add_argument(
        "--tenants", metavar="MANIFEST",
        help="gate a multi-tenant serving run: refuse a tampered "
        "tenants manifest (schema, duplicate ids, out-of-range "
        "quotas) and, against the answers log next to it (or "
        "--answers-log), refuse answers claiming unregistered "
        "tenants, drain counters that do not cross-sum, and recall "
        "evidence hidden in an aggregate block",
    )
    ap.add_argument(
        "--answers-log", dest="answers_log", metavar="PATH",
        help="with --tenants: the serve answers JSONL to cross-check "
        "(default: answers.jsonl beside the manifest, when present)",
    )
    args = ap.parse_args(argv)

    if args.static:
        violations = check_static(args.static, diff_base=args.static_diff)
        if violations:
            for v in violations:
                print(f"REGRESSION: {v}")
            return 1
        print(f"bench_check OK (staticcheck over {args.static})")
        return 0

    if args.tenants:
        violations = check_tenants(args.tenants,
                                   answers_path=args.answers_log)
        if violations:
            for v in violations:
                print(f"REGRESSION: {v}")
            return 1
        print(f"bench_check OK (tenants manifest {args.tenants})")
        return 0

    if args.wal:
        violations = check_wal_dir(args.wal,
                                   min_last_seq=args.wal_watermark)
        if violations:
            for v in violations:
                print(f"REGRESSION: {v}")
            return 1
        print(f"bench_check OK (wal artifact {args.wal})")
        return 0

    if args.gameday:
        violations = check_gameday_report(args.gameday)
        if violations:
            for v in violations:
                print(f"REGRESSION: {v}")
            return 1
        print(f"bench_check OK (gameday verdict {args.gameday})")
        return 0

    if args.qtrace:
        violations = check_qtrace_log(args.qtrace)
        if violations:
            for v in violations:
                print(f"REGRESSION: {v}")
            return 1
        print(f"bench_check OK (qtrace artifact {args.qtrace})")
        return 0

    if args.quality:
        violations = check_quality_log(args.quality,
                                       alerts_path=args.alerts_log)
        if violations:
            for v in violations:
                print(f"REGRESSION: {v}")
            return 1
        print(f"bench_check OK (quality log {args.quality})")
        return 0

    if args.remediation:
        violations = check_remediation_log(args.remediation,
                                           alerts_path=args.alerts_log)
        if violations:
            for v in violations:
                print(f"REGRESSION: {v}")
            return 1
        print(f"bench_check OK (remediation log {args.remediation})")
        return 0

    if args.alerts:
        violations = check_alert_log(args.alerts)
        if violations:
            for v in violations:
                print(f"REGRESSION: {v}")
            return 1
        print(f"bench_check OK (alert log {args.alerts})")
        return 0

    if args.fleet_report:
        violations = check_fleet_report(args.fleet_report,
                                        expect_link=args.expect_link)
        if violations:
            for v in violations:
                print(f"REGRESSION: {v}")
            return 1
        print(f"bench_check OK (fleet report {args.fleet_report})")
        return 0

    records = load_offline_records()
    if not args.offline:
        records.extend(load_history_records(args.history))
    _log(f"{len(records)} measured record(s): "
         + ", ".join(src for src, _ in records))
    violations = check(records, tol=args.tol)
    if violations:
        for v in violations:
            print(f"REGRESSION: {v}")
        return 1
    print(f"bench_check OK ({len(records)} records, no regressions)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
