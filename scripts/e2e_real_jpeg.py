"""End-to-end real-image training proof (VERDICT r3 missing #4).

Exercises the FULL reference workflow — the MultibatchData path of
usage/def.prototxt:2-29 — on actual JPEG files, with nothing mocked:

    on-disk JPEG dataset -> tools/make_list.py list files
      -> net/solver prototxts -> `python -m npairloss_tpu train
         --native require` (C++ runtime decodes the JPEGs,
         identity-balanced sampling, crop/mirror augmentation)
      -> MLP trunk -> L2 normalize -> mined N-pair loss -> Caffe SGD
      -> display/TEST cadence -> Orbax snapshot
      -> a SECOND CLI run resuming from the snapshot (iteration-resume
         proof through the same entrypoint).

The datasets the reference trains on (CUB / SOP) are unfetchable here,
so the images are generated: each identity is a distinct smooth random
pattern, each instance a photometric/geometric jitter of it.  The split
is the reference datasets' ZERO-SHOT protocol (first classes train,
remaining classes test — ``tools/make_list.py --split-classes``): the
TEST metrics and the final full-gallery eval are over classes the model
NEVER saw, while every byte still flows through the real JPEG decode +
list-file + augmentation pipeline.

Writes accuracy/e2e_real_jpeg.json and exits nonzero on any failed
assertion.  CPU-runnable (~2-4 min); pass --steps to shorten.

Usage: python scripts/e2e_real_jpeg.py [--workdir /tmp/e2e_jpeg]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

IDS = 20           # total classes on disk
TRAIN_CLASSES = 16  # first 16 train; last 4 are ZERO-SHOT test classes
PER_ID = 8
SIDE = 64


def make_dataset(root: str, rng: np.random.Generator):
    """IDS identities x PER_ID JPEGs in one class-per-directory tree
    (the --split-classes zero-shot split is made by tools/make_list.py).

    Identity signal: a smooth low-frequency RGB pattern (upsampled 4x4
    noise) — robust under JPEG quantization; instances add brightness
    jitter, pixel noise, and a large translation.  Heavy jitter on
    purpose: a random-init trunk must NOT nearly solve the task (that
    would make the rising curve vacuous)."""
    from PIL import Image

    for cid in range(IDS):
        base_rng = np.random.default_rng(1000 + cid)
        coarse = base_rng.uniform(40, 215, size=(4, 4, 3))
        base = np.kron(coarse, np.ones((SIDE // 4, SIDE // 4, 1)))
        cdir = os.path.join(root, f"id_{cid:03d}")
        os.makedirs(cdir, exist_ok=True)
        for k in range(PER_ID):
            inst = base + rng.normal(0, 45, size=base.shape)
            inst = (inst - 128) * rng.uniform(0.6, 1.4) + 128
            inst = inst + rng.uniform(-30, 30)
            dx, dy = rng.integers(-8, 9, size=2)
            inst = np.roll(inst, (dy, dx), axis=(0, 1))
            img = np.clip(inst, 0, 255).astype(np.uint8)
            Image.fromarray(img).save(
                os.path.join(cdir, f"img_{k:02d}.jpg"), quality=92,
            )


def make_dataset_structural(root: str, rng: np.random.Generator):
    """Conv-trunk variant of the dataset: the color-blob identities of
    :func:`make_dataset` are nearly solved by a RANDOM conv init
    (global pooling of random conv features ~ a color histogram, and
    the identity IS a color pattern: first zero-shot R@1 0.875 —
    measured), which would make the rising-curve requirement vacuous.

    Here identity lives in SPATIAL STRUCTURE only: a fixed binary blob
    mask per class, rendered per-instance with a random hue pair
    (foreground guaranteed brighter, but both hues re-drawn every
    instance) — so color statistics carry ~no class signal and the
    trunk must learn the shape.  Same jitter family as the mlp dataset
    (noise, brightness, large translation roll)."""
    from PIL import Image

    for cid in range(IDS):
        base_rng = np.random.default_rng(2000 + cid)
        coarse = base_rng.standard_normal((6, 6))
        up = np.kron(coarse, np.ones((SIDE // 6 + 1, SIDE // 6 + 1)))
        mask = (up[:SIDE, :SIDE] > 0).astype(np.float64)[..., None]
        cdir = os.path.join(root, f"id_{cid:03d}")
        os.makedirs(cdir, exist_ok=True)
        for k in range(PER_ID):
            bg = rng.uniform(30, 120, size=3)
            fg = bg + rng.uniform(60, 110, size=3)  # brighter, random hue
            inst = mask * fg + (1 - mask) * bg
            inst = inst + rng.normal(0, 25, size=inst.shape)
            inst = inst + rng.uniform(-20, 20)
            dx, dy = rng.integers(-8, 9, size=2)
            inst = np.roll(inst, (dy, dx), axis=(0, 1))
            img = np.clip(inst, 0, 255).astype(np.uint8)
            Image.fromarray(img).save(
                os.path.join(cdir, f"img_{k:02d}.jpg"), quality=92,
            )


NET_TPL = """\
name: "MLP_E2E"
layer {{
    name: "data_mb"
    type: "MultibatchData"
    top: "data_mb"
    top: "label_mb"
    include {{ phase: TRAIN }}
    transform_param {{
        mirror: true
        crop_size: 56
        mean_value: 128
        mean_value: 128
        mean_value: 128
    }}
    multi_batch_data_param {{
        root_folder: "{ws}/images/"
        source: "{ws}/train.txt"
        batch_size: 16
        shuffle: true
        new_height: {side}
        new_width: {side}
        identity_num_per_batch: 8
        img_num_per_identity: 2
        rand_identity: true
    }}
}}
layer {{
    name: "data_mb"
    type: "MultibatchData"
    top: "data_mb"
    top: "label_mb"
    include {{ phase: TEST }}
    transform_param {{
        crop_size: 56
        mean_value: 128
        mean_value: 128
        mean_value: 128
    }}
    multi_batch_data_param {{
        root_folder: "{ws}/images/"
        source: "{ws}/test.txt"
        batch_size: 16
        new_height: {side}
        new_width: {side}
        identity_num_per_batch: 4
        img_num_per_identity: 4
    }}
}}
layer {{
    name: "norm"
    type: "L2Normalize"
    bottom: "emb"
    top: "emb_norm"
}}
layer {{
    name: "loss"
    type: "NPairMultiClassLoss"
    bottom: "emb_norm"
    bottom: "label_mb"
    top: "loss"
    top: "retrieve_top1"
    npair_loss_param {{
        margin_diff: -0.05
        an_mining_method: HARD
    }}
}}
"""

SOLVER_TPL = """\
net: "{ws}/net.prototxt"
base_lr: {base_lr}
lr_policy: "fixed"
momentum: 0.9
weight_decay: 0.0001
max_iter: {max_iter}
display: {display}
average_loss: {display}
test_iter: 4
test_interval: {test_interval}
test_initialization: true
snapshot: {snapshot}
snapshot_prefix: "{ws}/snap_"
"""

ITER_RE = re.compile(
    r"^iter (\d+) lr=\S+ loss=(\S+) \(avg over \d+\)(.*)$"
)
TEST_RE = re.compile(r"^iter (\d+) TEST (.*)$")


def _kv(rest: str):
    return {
        k: float(v) for k, v in (
            kv.split("=") for kv in rest.split() if "=" in kv
        )
    }


def run_cli(args_list, log_path):
    # --platform cpu goes through jax.config (the env var cannot unhang
    # the axon plugin's tunnel discovery); pass E2E_JAX_PLATFORM=default
    # to run on the real accelerator (the TPU accuracy smoke).
    platform = os.environ.get("E2E_JAX_PLATFORM", "cpu")
    cmd = [sys.executable, "-m", "npairloss_tpu",
           "--platform", platform] + args_list
    proc = subprocess.run(
        cmd, cwd=REPO, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, timeout=3600,
    )
    with open(log_path, "w") as f:
        f.write(proc.stdout)
    if proc.returncode != 0:
        print(proc.stdout[-4000:], file=sys.stderr)
        raise SystemExit(f"CLI failed rc={proc.returncode}: {' '.join(cmd)}")
    return proc.stdout


def parse_curve(stdout: str):
    train, test, resumed_from = [], [], None
    for line in stdout.splitlines():
        m = ITER_RE.match(line.strip())
        if m:
            row = {"iter": int(m.group(1)), "loss": float(m.group(2))}
            row.update(_kv(m.group(3)))
            train.append(row)
            continue
        m = TEST_RE.match(line.strip())
        if m:
            row = {"iter": int(m.group(1))}
            row.update(_kv(m.group(2)))
            test.append(row)
            continue
        m = re.match(r"^resuming from iteration (\d+)", line.strip())
        if m:
            resumed_from = int(m.group(1))
    return train, test, resumed_from


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default="/tmp/e2e_jpeg")
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument(
        "--model", default="mlp",
        help="trunk for the CLI runs; 'googlenet_bn' is the conv-trunk "
        "variant of the proof (VERDICT r4 missing #3: JPEG pipeline + "
        "conv trunk + mined loss in ONE artifact)")
    ap.add_argument(
        "--base-lr", type=float, default=None,
        help="solver base_lr (default: 0.03 for mlp, 0.05 for conv "
        "trunks — the accuracy-baseline conv recipe)")
    ap.add_argument("--r1-bar", type=float, default=0.9,
                    help="train-batch retrieve_top1 the final model must "
                    "reach (seen classes)")
    ap.add_argument("--unseen-bar", type=float, default=None,
                    help="zero-shot bar: TEST retrieve_top1 / full-gallery "
                    "R@1 over classes never seen in training (default 0.7 "
                    "for mlp; 0.4 for conv trunks, whose structural "
                    "dataset is much harder — calibrated zero-shot "
                    "plateau ~0.5-0.6 with 16-image TEST batches)")
    ap.add_argument(
        "--artifact", default=None,
        help="default accuracy/e2e_real_jpeg.json, or "
        "accuracy/e2e_real_jpeg_<model>.json for non-mlp trunks",
    )
    args = ap.parse_args()
    if args.base_lr is None:
        args.base_lr = 0.03 if args.model == "mlp" else 0.05
    if args.unseen_bar is None:
        args.unseen_bar = 0.7 if args.model == "mlp" else 0.4
    if args.artifact is None:
        suffix = "" if args.model == "mlp" else f"_{args.model}"
        args.artifact = os.path.join(
            REPO, "accuracy", f"e2e_real_jpeg{suffix}.json")

    ws = os.path.abspath(args.workdir)
    shutil.rmtree(ws, ignore_errors=True)
    os.makedirs(ws, exist_ok=True)
    rng = np.random.default_rng(7)

    structural = args.model != "mlp"
    print(f"[e2e] generating {IDS} ids x "
          f"{PER_ID} JPEGs under {ws}/images "
          f"({TRAIN_CLASSES} train / {IDS - TRAIN_CLASSES} zero-shot, "
          f"{'structural' if structural else 'color-blob'} identities)")
    (make_dataset_structural if structural else make_dataset)(
        os.path.join(ws, "images"), rng)

    # Zero-shot split through the real tool (the reference datasets'
    # protocol: first classes train, remaining classes test).
    subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "make_list.py"),
         os.path.join(ws, "images"),
         "--out-train", os.path.join(ws, "train.txt"),
         "--out-test", os.path.join(ws, "test.txt"),
         "--split-classes", str(TRAIN_CLASSES)],
        check=True, cwd=REPO,
    )
    n_train = sum(1 for _ in open(os.path.join(ws, "train.txt")))
    assert n_train == TRAIN_CLASSES * PER_ID, n_train

    snapshot_at = args.steps // 2
    display = max(args.steps // 20, 1)
    with open(os.path.join(ws, "net.prototxt"), "w") as f:
        f.write(NET_TPL.format(ws=ws, side=SIDE))
    with open(os.path.join(ws, "solver.prototxt"), "w") as f:
        f.write(SOLVER_TPL.format(
            ws=ws, max_iter=args.steps, display=display, base_lr=args.base_lr,
            test_interval=max(args.steps // 4, 1), snapshot=snapshot_at,
        ))

    # Full run: JPEGs decoded by the REQUIRED native C++ runtime.
    print(f"[e2e] training {args.steps} iters via CLI (--native require)")
    out1 = run_cli(
        ["train", "--solver", os.path.join(ws, "solver.prototxt"),
         "--model", args.model, "--native", "require"],
        os.path.join(ws, "train.log"),
    )
    train_curve, test_curve, _ = parse_curve(out1)
    assert train_curve, "no display lines parsed from the training log"
    assert test_curve, "no TEST lines parsed from the training log"

    # Resume run: restore the mid-training snapshot through the same CLI
    # and continue to max_iter; cadence must pick up AFTER the snapshot.
    snap = os.path.join(ws, f"snap_iter_{snapshot_at}.ckpt")
    assert os.path.isdir(snap), f"snapshot missing: {snap}"
    print(f"[e2e] resuming from {snap} via CLI")
    out2 = run_cli(
        ["train", "--solver", os.path.join(ws, "solver.prototxt"),
         "--model", args.model, "--native", "require", "--resume", snap],
        os.path.join(ws, "resume.log"),
    )
    r_train, r_test, resumed_from = parse_curve(out2)
    assert resumed_from == snapshot_at, (
        f"resume started at {resumed_from}, wanted {snapshot_at}"
    )
    # First display after resume: the first multiple of `display`
    # strictly greater than the snapshot iteration (the cadence is
    # step_num % display == 0, not "display steps since restore").
    first_display = (snapshot_at // display + 1) * display
    assert r_train and r_train[0]["iter"] == first_display, (
        f"first resumed display at {r_train[0]['iter'] if r_train else None},"
        f" wanted {first_display} (cadence must continue, not restart)"
    )

    # Deployment loop: extract embeddings from the final snapshot via the
    # CLI, then full-gallery Recall@K over them (the reporting protocol
    # papers use — every test image queries the whole extracted set).
    final_snap = os.path.join(ws, f"snap_iter_{args.steps}.ckpt")
    gallery = None
    if os.path.isdir(final_snap):
        n_test = (IDS - TRAIN_CLASSES) * PER_ID
        out3 = run_cli(
            ["extract", "--solver", os.path.join(ws, "solver.prototxt"),
             "--model", args.model, "--native", "require", "--phase", "TEST",
             "--batches", str(n_test // 16),
             "--resume", final_snap, "--out", os.path.join(ws, "feats")],
            os.path.join(ws, "extract.log"),
        )
        out4 = run_cli(
            ["eval", "--prefix", os.path.join(ws, "feats"),
             "--ks", "1", "2", "4", "--nmi"],
            os.path.join(ws, "eval.log"),
        )
        gallery = json.loads(out4.strip().splitlines()[-1])

    # TEST rows and the gallery eval are ZERO-SHOT (classes 16..19 never
    # appear in training); the train display rows carry the seen-class
    # in-batch monitor.
    first_unseen_r1 = test_curve[0].get("retrieve_top1", 0.0)
    final_unseen_r1 = test_curve[-1].get("retrieve_top1", 0.0)
    resumed_unseen_r1 = (
        r_test[-1].get("retrieve_top1", 0.0) if r_test else None
    )
    final_train_r1 = train_curve[-1].get("retrieve_top1", 0.0)
    first_loss = train_curve[0]["loss"]
    final_loss = train_curve[-1]["loss"]
    gallery_r1 = gallery.get("recall_at_1", 0.0) if gallery else None
    ok = (
        final_train_r1 >= args.r1_bar
        and final_loss < first_loss
        and final_unseen_r1 >= args.unseen_bar
        and final_unseen_r1 > first_unseen_r1
        and (resumed_unseen_r1 is None
             or resumed_unseen_r1 >= args.unseen_bar)
        and (gallery_r1 is None or gallery_r1 >= args.unseen_bar)
    )

    artifact = {
        "what": ("end-to-end real-JPEG training through the native C++ "
                 "loader (MultibatchData path, usage/def.prototxt:2-29): "
                 "on-disk JPEGs -> make_list -> prototxt -> CLI train "
                 "-> snapshot -> CLI resume"),
        "dataset": {
            "identities": IDS, "train_classes": TRAIN_CLASSES,
            "zero_shot_test_classes": IDS - TRAIN_CLASSES,
            "images_per_id": PER_ID, "side": SIDE,
            "format": "jpeg q92", "train_images": n_train,
            "protocol": ("zero-shot class split (make_list "
                         "--split-classes): TEST metrics + gallery eval "
                         "are over classes never seen in training"),
        },
        "pipeline": {
            "loader": "native (--native require; C++ runtime, libjpeg)",
            "augmentation": "resize 64 -> random crop 56 + mirror "
                            "(train), center crop (test)",
            "model": args.model,
            "mining": "GLOBAL/HARD margin_diff=-0.05",
        },
        "command": (f"python -m npairloss_tpu train --solver <ws>/"
                    f"solver.prototxt --model {args.model} "
                    "--native require"),
        "train_curve": train_curve,
        "test_curve": test_curve,
        "resume": {
            "snapshot_iter": snapshot_at,
            "resumed_from": resumed_from,
            "first_resumed_display_iter": r_train[0]["iter"],
            "resumed_test_curve": r_test,
        },
        "deployment": {
            "extract": "CLI extract --native require from the final "
                       "snapshot (TEST split)",
            "full_gallery_eval": gallery,
        },
        "summary": {
            "first_avg_loss": first_loss, "final_avg_loss": final_loss,
            "final_train_r1": final_train_r1,
            "first_unseen_test_r1": first_unseen_r1,
            "final_unseen_test_r1": final_unseen_r1,
            "resumed_final_unseen_test_r1": resumed_unseen_r1,
            "unseen_gallery_r1": gallery_r1,
            "r1_bar": args.r1_bar, "unseen_bar": args.unseen_bar,
        },
        "ok": ok,
    }
    os.makedirs(os.path.dirname(args.artifact), exist_ok=True)
    with open(args.artifact, "w") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")
    print(f"[e2e] {'OK' if ok else 'FAIL'}: loss {first_loss:.3f} -> "
          f"{final_loss:.3f}, zero-shot R@1 {first_unseen_r1:.3f} -> "
          f"{final_unseen_r1:.3f} (resumed {resumed_unseen_r1}, gallery "
          f"{gallery_r1}), artifact {args.artifact}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
