"""Split tpu_pallas_check output into PALLAS_CHECK.json + STRETCH.json.

Refuses to stamp hardware artifacts from a CPU/interpret run: the engine
string and device field are derived from (and asserted against) the
record itself (ADVICE r3).  Runs unattended from the revalidation queue,
so the refusal paths are unit-tested (tests/test_bench_outage.py).
"""
import argparse
import datetime
import json
import os
import sys

ROUND = 5
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def split(rec, out_dir, date=None):
    """Build (pallas, stretch) artifact dicts; SystemExit on a record
    that must not be stamped as a hardware measurement."""
    date = date or datetime.date.today().isoformat()
    if not rec.get("mosaic_compiled"):
        raise SystemExit(
            "refusing to stamp artifacts: "
            f"mosaic_compiled={rec.get('mosaic_compiled')!r}"
        )
    device = rec.get("device", "")
    if "tpu" not in device.lower():
        raise SystemExit(
            f"refusing to stamp artifacts: device={device!r} is not a TPU"
        )

    sim_cached = bool(
        rec.get("stretch", {}).get("flagship", {}).get("sim_cache"))
    engine = "pallas_blockwise (Mosaic-compiled"
    if sim_cached:
        engine += ", fp32 sim-cache; _nocache rows stream uncached"
    engine += ")"

    stretch_pool = (rec.get("stretch", {}).get("flagship_nocache", {})
                    .get("pool", 32768))
    cached_pool = rec.get("cached_pool")  # absent on legacy records
    cmd = f"python scripts/tpu_pallas_check.py --pool 4096 --stretch {stretch_pool}"
    if cached_pool and cached_pool != stretch_pool:
        cmd += f" --stretch-cached {cached_pool}"
    cache_gib = (cached_pool or stretch_pool) ** 2 * 4 / 2**30
    pallas = {
        "round": ROUND, "date": date, "device": device, "pool": rec["pool"],
        "parity": rec["parity"], "ok": rec["ok"],
        "mosaic_compiled": rec["mosaic_compiled"],
        "command": cmd,
    }
    stretch = {
        "round": ROUND, "date": date, "device": device, "pool": stretch_pool,
        "dim": 512, "block": 512,
        "engine": engine,
        "sim_cache": sim_cached,
        "note": ("fwd+bwd per step; every row carries its own 'pool'. "
                 "When enabled, the similarity cache materializes the "
                 f"{cache_gib:.2f} GiB fp32 sim matrix once in the stats "
                 "sweep and streams it back in the radix/loss/backward "
                 "sweeps (see docs/DESIGN.md); cached rows run at "
                 "'cached_pool' (a 4.0 GiB cache dispatch wedges the "
                 "tunneled v5e backend — round-4 finding). Timed as 3 "
                 "perturbed steps inside one jitted lax.scan, host-fetch "
                 "synced, dispatch floor subtracted (bench.py timing "
                 "discipline)."),
        "stretch": rec["stretch"],
        **{k: rec[k] for k in (
            "peak_bytes_in_use", "peak_bytes_in_use_cached",
            "peak_bytes_in_use_nocache", "cached_pool",
            "sim_cache_auto_at_stretch") if k in rec},
        "command": cmd,
    }
    return pallas, stretch


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--src", default="/tmp/tpu_check_out.json")
    ap.add_argument("--out-dir", default=REPO)
    args = ap.parse_args()

    rec = json.loads(open(args.src).read().strip().splitlines()[-1])
    pallas, stretch = split(rec, args.out_dir)
    with open(os.path.join(args.out_dir, "PALLAS_CHECK.json"), "w") as f:
        f.write(json.dumps(pallas) + "\n")
    with open(os.path.join(args.out_dir, "STRETCH.json"), "w") as f:
        f.write(json.dumps(stretch) + "\n")
    print("split ok:", rec["ok"], rec.get("stretch"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
