"""Split tpu_pallas_check output into PALLAS_CHECK.json + STRETCH.json.

Refuses to stamp hardware artifacts from a CPU/interpret run: the engine
string and device field are derived from (and asserted against) the
record itself (ADVICE r3).
"""
import json, sys, datetime

ROUND = 4
src = "/tmp/tpu_check_out.json"
rec = json.loads(open(src).read().strip().splitlines()[-1])
date = datetime.date.today().isoformat()

# Hardware gate: only a Mosaic-compiled run on a real TPU device may be
# recorded as a hardware measurement.
if not rec.get("mosaic_compiled"):
    sys.exit(f"refusing to stamp artifacts: mosaic_compiled={rec.get('mosaic_compiled')!r}")
device = rec.get("device", "")
if "tpu" not in device.lower():
    sys.exit(f"refusing to stamp artifacts: device={device!r} is not a TPU")

sim_cached = bool(
    rec.get("stretch", {}).get("flagship", {}).get("sim_cache"))
engine = "pallas_blockwise (Mosaic-compiled"
if sim_cached:
    engine += ", fp32 sim-cache; _nocache rows stream uncached"
engine += ")"

pallas = {
    "round": ROUND, "date": date, "device": device, "pool": rec["pool"],
    "parity": rec["parity"], "ok": rec["ok"],
    "mosaic_compiled": rec["mosaic_compiled"],
    "command": "python scripts/tpu_pallas_check.py --pool 4096 --stretch 32768",
}
stretch = {
    "round": ROUND, "date": date, "device": device, "pool": 32768,
    "dim": 512, "block": 512,
    "engine": engine,
    "sim_cache": sim_cached,
    "note": ("fwd+bwd per step; the similarity cache materializes the 4.3 GB "
             "fp32 sim matrix once in the stats sweep and streams it back in "
             "the radix/loss/backward sweeps (see docs/DESIGN.md). Timed as 3 "
             "perturbed steps inside one jitted lax.scan, host-fetch synced, "
             "dispatch floor subtracted (bench.py timing discipline)."),
    "stretch": rec["stretch"],
    **{k: rec[k] for k in (
        "peak_bytes_in_use", "peak_bytes_in_use_cached",
        "peak_bytes_in_use_nocache") if k in rec},
    "command": "python scripts/tpu_pallas_check.py --pool 4096 --stretch 32768",
}
open("/root/repo/PALLAS_CHECK.json", "w").write(json.dumps(pallas) + "\n")
open("/root/repo/STRETCH.json", "w").write(json.dumps(stretch) + "\n")
print("split ok:", rec["ok"], rec.get("stretch"))
