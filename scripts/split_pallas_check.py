"""Split tpu_pallas_check output into PALLAS_CHECK.json + STRETCH.json."""
import json, sys, datetime

src = "/tmp/tpu_check_out.json"
rec = json.loads(open(src).read().strip().splitlines()[-1])
date = datetime.date.today().isoformat()

pallas = {
    "round": 3, "date": date, "device": rec["device"], "pool": rec["pool"],
    "parity": rec["parity"], "ok": rec["ok"],
    "mosaic_compiled": rec["mosaic_compiled"],
    "command": "python scripts/tpu_pallas_check.py --pool 4096 --stretch 32768",
}
stretch = {
    "round": 3, "date": date, "device": rec["device"], "pool": 32768,
    "dim": 512, "block": 512,
    "engine": "pallas_blockwise (Mosaic-compiled, fp32 sim-cache)",
    "note": ("fwd+bwd per step; the similarity cache materializes the 4.3 GB "
             "fp32 sim matrix once in the stats sweep and streams it back in "
             "the radix/loss/backward sweeps (see docs/DESIGN.md). Timed as 3 "
             "perturbed steps inside one jitted lax.scan, host-fetch synced, "
             "dispatch floor subtracted (bench.py timing discipline)."),
    "stretch": rec["stretch"],
    **({"peak_bytes_in_use": rec["peak_bytes_in_use"]}
       if "peak_bytes_in_use" in rec else {}),
    "command": "python scripts/tpu_pallas_check.py --pool 4096 --stretch 32768",
}
open("/root/repo/PALLAS_CHECK.json", "w").write(json.dumps(pallas) + "\n")
open("/root/repo/STRETCH.json", "w").write(json.dumps(stretch) + "\n")
print("split ok:", rec["ok"], rec.get("stretch"))
