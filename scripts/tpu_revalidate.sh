#!/bin/bash
# Round-3 hardware revalidation queue (written during the 2026-07-30
# axon-tunnel outage; the sim-cache + s2d work landed with CPU-parity
# coverage only).  Waits for the tunnel, then:
#   1. scripts/tpu_pallas_check.py  -> PALLAS_CHECK.json + STRETCH.json
#      (via scripts/split_pallas_check.py)
#   2. scripts/profile_flagship.py  -> profile/flagship.{json,md}
#      (incl. the s2d ablation row)
#   3. bench.py                     -> engine extras with the sim-cache
# Run detached:  setsid nohup scripts/tpu_revalidate.sh &
# Log: /tmp/tpu_queue.log
cd "$(dirname "$0")/.."
exec > /tmp/tpu_queue.log 2>&1

echo "=== $(date) waiting for tunnel ==="
for i in $(seq 1 600); do
  # Platform check: the gate must reject a silent CPU fallback — only a
  # real TPU device counts as "tunnel up" (ADVICE r3).
  if timeout 100 python -c 'import jax,sys; sys.exit(jax.devices()[0].platform != "tpu")' >/dev/null 2>&1; then
    echo "tunnel up (platform=tpu) after probe $i ($(date))"
    break
  fi
  echo "probe $i failed ($(date)); sleeping 300s"
  sleep 300
  if [ "$i" = 600 ]; then echo "GAVE UP"; exit 1; fi
done

echo "=== $(date) 1/3 tpu_pallas_check (parity + 32k stretch, sim-cache) ==="
timeout 2400 python scripts/tpu_pallas_check.py --pool 4096 --stretch 32768 \
  > /tmp/tpu_check_out.json
rc=$?
echo "tpu_pallas_check rc=$rc"
tail -c 2000 /tmp/tpu_check_out.json
if [ "$rc" = 0 ]; then python scripts/split_pallas_check.py; fi

echo "=== $(date) 2/3 profile_flagship (incl. s2d variant) ==="
timeout 3600 python scripts/profile_flagship.py --steps 10
echo "profile rc=$?"

echo "=== $(date) 3/4 bench.py full ==="
# Budget > bench.py's worst case (~3270s: probes 270 + full
# 2400 + smoke fallbacks 600) — see tpu_queue_v3.sh.
timeout 4200 python bench.py > /tmp/bench_out.json
echo "bench rc=$?"
tail -c 1000 /tmp/bench_out.json

echo "=== $(date) 4/4 TPU accuracy smoke (e2e real-JPEG on the chip) ==="
timeout 2400 env E2E_JAX_PLATFORM=default python scripts/e2e_real_jpeg.py \
  --steps 200 --workdir /tmp/e2e_jpeg_tpu \
  --artifact accuracy/e2e_real_jpeg_tpu.json
echo "e2e tpu rc=$?"

echo "=== $(date) QUEUE DONE ==="
