"""Differential profile of the flagship training step (VERDICT r2 item 4).

``jax.profiler`` traces cannot be collected through the tunneled axon
backend (the trace RPC wedges the tunnel — observed round 3), so the
bottleneck attribution is DIFFERENTIAL: time carefully-chosen ablations
of the flagship step (GoogLeNet bf16 + mined N-pair loss + analytic
backward + Caffe-SGD update, batch 120 @ 224x224) and attribute the
deltas.  Every measurement is N perturbed steps inside one jitted
``lax.scan``, host-fetch synced, dispatch-floor subtracted — see
bench.py's timing discipline.

Variants:
  full           the flagship solver step (dense engine)
  fwd_only       model forward only
  fwd_bwd        model fwd+bwd with loss = sum(emb) (no npair machinery)
  npair_only     mined loss+VJP on precomputed (120, 1024) embeddings
  no_lrn         full minus LRN (use_lrn=False)
  fp32           full at fp32 activations
  bn             full with the Inception-BN trunk (BN instead of LRN)
  s2d            full with the space-to-depth stem (exact conv1 rewrite)

Writes profile/flagship.json + profile/flagship.md (the
generated ablation table; PROFILE.md stays hand-curated and cites it).

Usage: python scripts/profile_flagship.py [--steps 10] [--batch 120]
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BATCH = 120
IMAGE = 224


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=BATCH)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--image", type=int, default=IMAGE)
    args = ap.parse_args()

    image = args.image

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass

    import jax.numpy as jnp
    import numpy as np

    from npairloss_tpu import REFERENCE_CONFIG, npair_loss
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import caffe_sgd, lr_schedule

    dev = jax.devices()[0]
    print(f"[profile] backend={dev.platform} kind={dev.device_kind}",
          file=sys.stderr, flush=True)

    batch, steps = args.batch, args.steps
    rng = np.random.default_rng(0)
    images = jax.device_put(jnp.asarray(
        rng.standard_normal((batch, image, image, 3)).astype(np.float32)))
    labels = jax.device_put(jnp.asarray(
        np.repeat(np.arange(batch // 2), 2).astype(np.int32)))
    emb_fixed = rng.standard_normal((batch, 1024)).astype(np.float32)
    emb_fixed /= np.linalg.norm(emb_fixed, axis=1, keepdims=True)
    emb_fixed = jax.device_put(jnp.asarray(emb_fixed))

    @jax.jit
    def tiny(x):
        return x.sum()

    float(np.asarray(tiny(jnp.full((8, 8), 1.0))))
    t0 = time.perf_counter()
    float(np.asarray(tiny(jnp.full((8, 8), 2.0))))
    floor = time.perf_counter() - t0
    print(f"[profile] fetch floor {floor * 1e3:.1f} ms",
          file=sys.stderr, flush=True)

    rate_fn = lr_schedule("step", 0.001, 0.5, 10000)
    tx = caffe_sgd(rate_fn, 0.9, 2e-5)

    results = {}

    def timed(name, make_step, x):
        """make_step() -> (carry, step_fn(carry, x, s) -> (carry, loss));
        the carry holds params AND optimizer state so momentum-buffer
        HBM traffic and schedule progression are inside the timing."""
        carry0, step_fn = make_step()

        @jax.jit
        def many(carry, x, round_id):
            def body(c, s):
                c2, loss = step_fn(c, x, round_id * steps + s)
                return c2, loss

            c, losses = jax.lax.scan(
                body, carry, jnp.arange(steps, dtype=jnp.float32))
            return jax.tree_util.tree_reduce(
                lambda a, l: a + l.astype(jnp.float32).sum(), c,
                jnp.float32(0.0),
            ), losses[-1]

        print(f"[profile] compiling {name}...", file=sys.stderr, flush=True)
        acc, _ = many(carry0, x, jnp.float32(0))
        float(np.asarray(acc))
        acc, _ = many(carry0, x, jnp.float32(1))
        float(np.asarray(acc))
        t0 = time.perf_counter()
        acc, loss = many(carry0, x, jnp.float32(2))
        float(np.asarray(acc))
        dt = max(time.perf_counter() - t0 - floor, 1e-9) / steps
        results[name] = {
            "ms_per_step": round(dt * 1e3, 2),
            "emb_per_sec": round(batch / dt, 1),
        }
        print(f"[profile] {name}: {dt * 1e3:.2f} ms/step",
              file=sys.stderr, flush=True)

    def model_step(model_name, with_loss=True, **model_kw):
        def make():
            model = get_model(model_name, **model_kw)
            variables = model.init(
                jax.random.PRNGKey(0), np.zeros((2, image, image, 3),
                                                np.float32), train=False)
            params = variables["params"]
            bstats = variables.get("batch_stats", {})

            def step(carry, x, s):
                p, opt = carry

                def loss_fn(pp):
                    xin = x * (1.0 + s * 1e-6)
                    if bstats:
                        emb, _ = model.apply(
                            {"params": pp, "batch_stats": bstats}, xin,
                            train=True, mutable=["batch_stats"])
                    else:
                        emb = model.apply({"params": pp}, xin, train=True)
                    if with_loss:
                        return npair_loss(emb, labels, REFERENCE_CONFIG)
                    return emb.astype(jnp.float32).sum()

                loss, grads = jax.value_and_grad(loss_fn)(p)
                upd, opt = tx.update(grads, opt, p)
                p2 = jax.tree_util.tree_map(
                    lambda a, u: (a.astype(jnp.float32) + u).astype(a.dtype),
                    p, upd)
                return (p2, opt), loss

            return (params, tx.init(params)), step

        return make

    # -- variants ---------------------------------------------------------
    def fwd_only():
        model = get_model("googlenet", dtype=jnp.bfloat16)
        variables = model.init(
            jax.random.PRNGKey(0),
            np.zeros((2, image, image, 3), np.float32), train=False)

        def step(p, x, s):
            emb = model.apply({"params": p}, x * (1.0 + s * 1e-6),
                              train=True)
            return p, emb.astype(jnp.float32).sum()

        return variables["params"], step

    def npair_only():
        def step(p, e, s):
            loss, g = jax.value_and_grad(
                lambda ee: npair_loss(ee, labels, REFERENCE_CONFIG)
            )(e * (1.0 + s * 1e-6))
            return jax.tree_util.tree_map(lambda a: a + g[0, 0] * 0, p), loss

        return {"w": jnp.zeros(())}, step

    timed("full", model_step("googlenet", dtype=jnp.bfloat16), images)
    timed("fwd_only", fwd_only, images)
    timed("fwd_bwd", model_step("googlenet", with_loss=False,
                                dtype=jnp.bfloat16), images)
    timed("npair_only", npair_only, emb_fixed)
    timed("no_lrn", model_step("googlenet", dtype=jnp.bfloat16,
                               use_lrn=False), images)
    timed("fp32", model_step("googlenet", dtype=jnp.float32), images)
    timed("bn", model_step("googlenet_bn", dtype=jnp.bfloat16), images)
    # Space-to-depth stem (models/googlenet.py stem_s2d): algebraically
    # identical trunk, MXU-friendlier conv1 tiling — the delta vs "full"
    # is pure framework-side headroom within prototxt parity.
    timed("s2d", model_step("googlenet_s2d", dtype=jnp.bfloat16), images)
    # Fused inception 1x1s (models/googlenet.py fuse_1x1): the three
    # input-reading 1x1 convs per block become one full-lane gemm —
    # exact algebra; the delta vs "full" prices the thin-branch MXU
    # underutilization PROFILE.md attributes headroom to.
    timed("fused", model_step("googlenet_fused", dtype=jnp.bfloat16),
          images)
    # Both parity-preserving MXU rewrites stacked (s2d stem + fused).
    timed("mxu", model_step("googlenet_mxu", dtype=jnp.bfloat16), images)
    # Block remat (models/googlenet.py remat): recompute-in-backward —
    # the delta vs "full" prices the recompute FLOPs at this batch; the
    # batch-480 HBM-pressure effect is bench.py's 480_remat row.
    timed("remat", model_step("googlenet", dtype=jnp.bfloat16, remat=True),
          images)

    payload = {
        "device": dev.device_kind,
        "batch": batch,
        "image": image,
        "steps_per_timing": steps,
        "fetch_floor_ms": round(floor * 1e3, 1),
        "results": results,
    }
    os.makedirs(os.path.join(REPO, "profile"), exist_ok=True)
    with open(os.path.join(REPO, "profile", "flagship.json"), "w") as f:
        json.dump(payload, f, indent=1)
    _write_profile_md(payload)
    print(json.dumps(payload))
    return 0


def _write_profile_md(payload):
    """profile/flagship.md: the generated ablation table (PROFILE.md
    itself is hand-curated — it cites this artifact)."""
    r = {k: v["ms_per_step"] for k, v in payload["results"].items()}
    full = r.get("full", 0.0)

    def pct(ms):
        return f"{ms:.1f} ms ({100 * ms / full:.0f}%)" if full else f"{ms:.1f} ms"

    lines = [
        "# Flagship step profile (differential)",
        "",
        f"Device: `{payload['device']}` — GoogLeNet bf16 + mined N-pair "
        f"loss (def.prototxt config) + analytic VJP + Caffe-SGD, batch "
        f"{payload['batch']} @ {payload['image']}x{payload['image']}.",
        "",
        "`jax.profiler` traces wedge the tunneled backend, so attribution",
        "is by ablation (scripts/profile_flagship.py): each variant is",
        f"{payload['steps_per_timing']} perturbed steps inside one jitted",
        "lax.scan, host-fetch synced, dispatch floor",
        f"({payload['fetch_floor_ms']} ms) subtracted.",
        "",
        "| variant | ms/step | emb/s |",
        "|---|---|---|",
    ]
    for k, v in payload["results"].items():
        lines.append(
            f"| {k} | {v['ms_per_step']} | {v['emb_per_sec']} |"
        )
    lines += ["", "## Attribution", ""]
    if all(k in r for k in ("full", "fwd_only", "fwd_bwd", "npair_only")):
        lines += [
            f"- model forward: {pct(r['fwd_only'])}",
            f"- model backward + update: "
            f"{pct(max(r['fwd_bwd'] - r['fwd_only'], 0.0))}",
            f"- N-pair loss machinery (mining + custom VJP): "
            f"{pct(r['npair_only'])} standalone; in-graph cost "
            f"{pct(max(r['full'] - r['fwd_bwd'], 0.0))}",
        ]
    if "no_lrn" in r and full:
        lines.append(
            f"- LRN (both layers): {pct(max(full - r['no_lrn'], 0.0))} — "
            "VPU-bound across-channel window"
        )
    if "fp32" in r and full:
        lines.append(
            f"- bf16 vs fp32 activations: fp32 costs "
            f"{pct(max(r['fp32'] - full, 0.0))} extra"
        )
    if "bn" in r and full:
        lines.append(
            f"- Inception-BN trunk (BN instead of LRN): {pct(r['bn'])} total"
        )
    lines.append("")
    with open(os.path.join(REPO, "profile", "flagship.md"), "w") as f:
        f.write("\n".join(lines))


if __name__ == "__main__":
    sys.exit(main())
