"""Differential profile of the flagship training step (VERDICT r2 item 4).

``jax.profiler`` traces cannot be collected through the tunneled axon
backend (the trace RPC wedges the tunnel — observed round 3), so the
bottleneck attribution is DIFFERENTIAL: time carefully-chosen ablations
of the flagship step (GoogLeNet bf16 + mined N-pair loss + analytic
backward + Caffe-SGD update, batch 120 @ 224x224) and attribute the
deltas.  Every measurement is N perturbed steps inside one jitted
``lax.scan``, host-fetch synced, dispatch-floor subtracted — see
bench.py's timing discipline.

Variants:
  full           the flagship solver step (dense engine)
  fwd_only       model forward only
  fwd_bwd        model fwd+bwd with loss = sum(emb) (no npair machinery)
  npair_only     mined loss+VJP on precomputed (120, 1024) embeddings
  no_lrn         full minus LRN (use_lrn=False)
  fp32           full at fp32 activations
  bn             full with the Inception-BN trunk (BN instead of LRN)
  s2d            full with the space-to-depth stem (exact conv1 rewrite)

Writes profile/flagship.json + profile/flagship.md (the
generated ablation table; PROFILE.md stays hand-curated and cites it).

Usage: python scripts/profile_flagship.py [--steps 10] [--batch 120]
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BATCH = 120
IMAGE = 224


# Ordered by evidence value: if the tunnel dies mid-run, the variants
# that anchor the attribution story have already been captured.  bn runs
# LAST: its dispatch started the round-4 tunnel wedge, and a re-wedge
# must not cost the LRN-pricing rows (no_lrn/fp32) that decide the
# flagship trunk (VERDICT r4 item 2).
VARIANT_ORDER = [
    "full", "fwd_only", "fwd_bwd", "npair_only", "s2d", "fused", "mxu",
    "remat", "no_lrn", "fp32", "bn",
]

ARTIFACT = os.path.join(REPO, "profile", "flagship.json")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=BATCH)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--image", type=int, default=IMAGE)
    ap.add_argument(
        "--variant", choices=VARIANT_ORDER,
        help="run ONE variant in this process (child mode; prints the "
        "payload JSON on the last stdout line)",
    )
    ap.add_argument(
        "--inline", action="store_true",
        help="run all variants in this process (no per-variant child "
        "isolation; the pre-round-4 behavior)",
    )
    ap.add_argument(
        "--variant-timeout", type=int, default=480,
        help="seconds per child variant before it is recorded as a "
        "timeout (orchestrator mode)",
    )
    ap.add_argument(
        "--artifact", default=ARTIFACT,
        help="orchestrator artifact path (default profile/flagship.json)",
    )
    ap.add_argument(
        "--recover-wait", type=int, default=1800,
        help="max seconds to wait for tunnel recovery between variants "
        "(orchestrator mode)",
    )
    args = ap.parse_args()

    # A wedged tunnel used to void the whole run: one process measured
    # all variants and wrote the artifact only at the end (round 4: six
    # measured variants lost when googlenet_bn's dispatch hung).  Default
    # mode is now an orchestrator that never touches the backend itself:
    # one child process per variant with a hard timeout, artifact
    # re-written after EVERY variant, completed variants skipped on
    # resume, tunnel health probed between variants.
    if args.variant or args.inline or args.cpu:
        return run_inline(args)
    return orchestrate(args)


def _tpu_ready(timeout: int = 100) -> bool:
    """Probe (in a throwaway child) that the backend is a real TPU; a
    wedged tunnel hangs the probe, which counts as not ready."""
    import subprocess

    code = ("import jax, sys; "
            "sys.exit(0 if jax.devices()[0].platform == 'tpu' else 1)")
    try:
        return subprocess.run(
            [sys.executable, "-c", code], timeout=timeout,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        ).returncode == 0
    except Exception:
        return False


def _write_artifacts(payload, artifact: str = ARTIFACT) -> None:
    # Never destroy measurement history.  Two rules, applied to the file
    # on disk before it is replaced:
    #   1. prior_runs (dated, superseded measurement sets) carry forward
    #      when this payload doesn't already have them;
    #   2. any MEASURED rows (ms_per_step present) the new payload does
    #      not itself carry are demoted into prior_runs — so a
    #      different-geometry orchestrator run, an --inline/--cpu run,
    #      or the CPU-vs-TPU resume rejection all preserve evidence
    #      instead of overwriting it.  (Resumed runs adopt the previous
    #      results dict wholesale, so nothing is demoted there.)
    # All prev access stays inside one try/except: a malformed artifact
    # (hand-edited, legacy shape) must degrade to "no history carried",
    # never crash this function — it runs after every measured variant,
    # and an exception here would lose the row it was called to save.
    try:
        with open(artifact) as f:
            prev = json.load(f)
        if "prior_runs" not in payload and prev.get("prior_runs"):
            payload["prior_runs"] = prev["prior_runs"]
        new_results = payload.get("results") or {}
        lost = {k: v for k, v in (prev.get("results") or {}).items()
                if isinstance(v, dict) and "ms_per_step" in v
                and new_results.get(k) != v}
        already = [r.get("results") for r in payload.get("prior_runs", [])]
        if lost and lost not in already:
            payload.setdefault("prior_runs", []).append({
                "date": time.strftime("%Y-%m-%d"),
                "note": (
                    f"superseded: rows measured on {prev.get('device')!r}"
                    f" (batch {prev.get('batch')}, image"
                    f" {prev.get('image')}) not carried forward by a"
                    " later run — geometry/device mismatch or fresh"
                    " start"),
                "results": lost,
            })
    except FileNotFoundError:
        pass
    except Exception as e:
        # Degrade (don't crash — this runs after every measured variant)
        # but say so: silent history loss is the failure mode this
        # function exists to prevent.
        print(f"[profile] WARNING: could not carry history from "
              f"{artifact}: {e!r}", file=sys.stderr, flush=True)
    os.makedirs(os.path.dirname(artifact) or ".", exist_ok=True)
    tmp = artifact + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, artifact)
    if artifact == ARTIFACT:
        _write_profile_md(payload)


def orchestrate(args) -> int:
    import subprocess

    # Full meta skeleton so artifact writes survive a first-variant
    # failure on a fresh run (the md writer reads these keys).
    payload = {
        "device": None,
        "batch": args.batch,
        "image": args.image,
        "steps_per_timing": args.steps,
        "fetch_floor_ms": None,
        "results": {},
    }
    artifact = getattr(args, "artifact", ARTIFACT)
    if os.path.exists(artifact):
        try:
            with open(artifact) as f:
                prev = json.load(f)
            # Resume only against the same workload geometry AND device
            # class: the orchestrator's children run on the default (TPU)
            # backend, so rows measured by a --cpu/--inline run on a CPU
            # backend must not be skipped as "completed" — that would
            # silently publish CPU timings as the flagship TPU profile.
            # prev["device"] is None until the first child reports in
            # (skeleton from an all-down run), which is safe to resume;
            # the rejection keys on recognizably-CPU device kinds so
            # non-CPU kinds (TPU v5 lite, test doubles) still resume.
            prev_dev = prev.get("device")
            dev_ok = prev_dev is None or "cpu" not in str(prev_dev).lower()
            if (prev.get("batch") == args.batch
                    and prev.get("image") == args.image
                    and prev.get("steps_per_timing") == args.steps
                    and dev_ok):
                payload = prev
                payload.setdefault("results", {})
            # else: start fresh — _write_artifacts demotes the old
            # measured rows into prior_runs (never-destroy-history).
        except Exception:
            pass

    def log(msg):
        print(f"[profile/orchestrator] {msg}", file=sys.stderr, flush=True)

    pending = [n for n in VARIANT_ORDER
               if "ms_per_step" not in payload["results"].get(n, {})
               and not payload["results"].get(n, {}).get("wedged")]
    log(f"pending variants: {pending or 'none'}")
    gate_ok = False  # set when a just-run probe already said "up"
    for name in pending:
        deadline = time.monotonic() + args.recover_wait
        while not (gate_ok or _tpu_ready()):
            if time.monotonic() >= deadline:
                log(f"tunnel did not recover within {args.recover_wait}s; "
                    f"stopping before {name}")
                payload["results"].setdefault(
                    name, {"error": "tunnel down, recover-wait exhausted"})
                _write_artifacts(payload, artifact)
                return 3
            log("tunnel not ready; sleeping 120s")
            time.sleep(120)
        gate_ok = False  # one gate only; the next variant re-probes
        cmd = [
            sys.executable, os.path.abspath(__file__),
            "--variant", name, "--steps", str(args.steps),
            "--batch", str(args.batch), "--image", str(args.image),
        ]
        log(f"running {name} (timeout {args.variant_timeout}s)")
        try:
            proc = subprocess.run(
                cmd, timeout=args.variant_timeout, capture_output=True,
                text=True,
            )
            sys.stderr.write(proc.stderr)
            if proc.returncode != 0:
                raise RuntimeError(f"rc={proc.returncode}")
            child = json.loads(proc.stdout.strip().splitlines()[-1])
            for key in ("device", "batch", "image", "steps_per_timing",
                        "fetch_floor_ms"):
                if payload.get(key) is None:
                    payload[key] = child.get(key)
            payload["results"].update(child["results"])
            log(f"{name}: {child['results'][name]}")
        except subprocess.TimeoutExpired:
            entry = {"error": f"timeout after {args.variant_timeout}s"}
            # Discriminate wedge from slow-compile: if the tunnel no
            # longer answers after the kill, this variant wedged it — a
            # resumed run must NOT retry it (a deterministic wedge would
            # otherwise re-wedge every supervisor attempt).  Three
            # probes over ~2 min before the permanent marker: a single
            # failed probe can be transient saturation or the killed
            # child's dispatch still draining, and a false wedge mark
            # bans a variant forever; a real wedge lasts hours.
            for _ in range(3):
                if _tpu_ready():
                    gate_ok = True  # reuse: skip the next gate's probe
                    break
                time.sleep(45)
            else:
                entry["wedged"] = True
                log(f"{name}: TIMED OUT and the tunnel stayed down "
                    "(wedge shape); resume will skip this variant")
            if not entry.get("wedged"):
                log(f"{name}: TIMED OUT but the tunnel still answers "
                    "(slow variant); resume may retry it")
            payload["results"][name] = entry
        except Exception as e:
            payload["results"][name] = {"error": str(e)[:300]}
            log(f"{name}: FAILED: {e}")
        _write_artifacts(payload, artifact)
    wedged = [n for n in VARIANT_ORDER
              if payload["results"].get(n, {}).get("wedged")]
    missing = [n for n in VARIANT_ORDER
               if "ms_per_step" not in payload["results"].get(n, {})
               and n not in wedged]
    # Wedged variants are terminal (only a hand-edit un-bans them), so
    # they must not keep the exit code at 4 — a supervisor keyed on
    # rc!=0 would otherwise retry forever with no progress possible.
    log(f"done; missing: {missing or 'none'}"
        + (f"; permanently skipped (wedged): {wedged}" if wedged else ""))
    print(json.dumps(payload))
    return 0 if not missing else 4


def run_inline(args):
    image = args.image

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass

    import jax.numpy as jnp
    import numpy as np

    from npairloss_tpu import REFERENCE_CONFIG, npair_loss
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import caffe_sgd, lr_schedule

    dev = jax.devices()[0]
    print(f"[profile] backend={dev.platform} kind={dev.device_kind}",
          file=sys.stderr, flush=True)

    batch, steps = args.batch, args.steps
    rng = np.random.default_rng(0)
    images = jax.device_put(jnp.asarray(
        rng.standard_normal((batch, image, image, 3)).astype(np.float32)))
    labels = jax.device_put(jnp.asarray(
        np.repeat(np.arange(batch // 2), 2).astype(np.int32)))
    emb_fixed = rng.standard_normal((batch, 1024)).astype(np.float32)
    emb_fixed /= np.linalg.norm(emb_fixed, axis=1, keepdims=True)
    emb_fixed = jax.device_put(jnp.asarray(emb_fixed))

    # Shared salted probe (utils.profiling): every child process issues
    # DISTINCT probe dispatches (PID-offset counter), so a server-side
    # memo cache cannot hand later children a ~0 floor.
    from npairloss_tpu.utils.profiling import (
        dispatch_floor,
        next_timing_salt,
    )

    floor = dispatch_floor()
    print(f"[profile] fetch floor {floor * 1e3:.1f} ms",
          file=sys.stderr, flush=True)

    rate_fn = lr_schedule("step", 0.001, 0.5, 10000)
    tx = caffe_sgd(rate_fn, 0.9, 2e-5)

    results = {}

    def timed(name, make_step, x):
        """make_step() -> (carry, step_fn(carry, x, s) -> (carry, loss));
        the carry holds params AND optimizer state so momentum-buffer
        HBM traffic and schedule progression are inside the timing."""
        carry0, step_fn = make_step()

        @jax.jit
        def many(carry, x, round_id):
            def body(c, s):
                c2, loss = step_fn(c, x, round_id * steps + s)
                return c2, loss

            c, losses = jax.lax.scan(
                body, carry, jnp.arange(steps, dtype=jnp.float32))
            return jax.tree_util.tree_reduce(
                lambda a, l: a + l.astype(jnp.float32).sum(), c,
                jnp.float32(0.0),
            ), losses[-1]

        print(f"[profile] compiling {name}...", file=sys.stderr, flush=True)
        # Fresh salt per dispatch: a resumed/re-run variant must not be
        # served from a server-side memo cache of its previous attempt
        # (same rng seeds -> otherwise byte-identical dispatches).
        acc, _ = many(carry0, x, jnp.float32(next_timing_salt()))
        float(np.asarray(acc))
        acc, _ = many(carry0, x, jnp.float32(next_timing_salt()))
        float(np.asarray(acc))
        # Two timed windows, min published with both recorded: tunnel
        # latency jitter is one-sided (bench.py's 08:04 UTC 2026-08-01
        # dense_abs anomaly) and these rows decide the flagship trunk.
        dts = []
        for _ in range(2):
            salt = jnp.float32(next_timing_salt())
            t0 = time.perf_counter()
            acc, loss = many(carry0, x, salt)
            float(np.asarray(acc))
            dts.append(max(time.perf_counter() - t0 - floor, 1e-9) / steps)
        dt = min(dts)
        results[name] = {
            "ms_per_step": round(dt * 1e3, 2),
            "ms_per_step_windows": [round(d * 1e3, 2) for d in dts],
            "emb_per_sec": round(batch / dt, 1),
        }
        print(f"[profile] {name}: {dt * 1e3:.2f} ms/step",
              file=sys.stderr, flush=True)

    from npairloss_tpu.models import jit_init as _jit_init

    def jit_init(model):
        # ONE compiled program for init (shared helper; the round-4
        # googlenet_bn wedge started in an init-adjacent dispatch).
        return _jit_init(model, jax.random.PRNGKey(0),
                         np.zeros((2, image, image, 3), np.float32))

    def model_step(model_name, with_loss=True, **model_kw):
        def make():
            model = get_model(model_name, **model_kw)
            variables = jit_init(model)
            params = variables["params"]
            bstats = variables.get("batch_stats", {})

            def step(carry, x, s):
                p, opt = carry

                def loss_fn(pp):
                    xin = x * (1.0 + s * 1e-6)
                    if bstats:
                        emb, _ = model.apply(
                            {"params": pp, "batch_stats": bstats}, xin,
                            train=True, mutable=["batch_stats"])
                    else:
                        emb = model.apply({"params": pp}, xin, train=True)
                    if with_loss:
                        return npair_loss(emb, labels, REFERENCE_CONFIG)
                    return emb.astype(jnp.float32).sum()

                loss, grads = jax.value_and_grad(loss_fn)(p)
                upd, opt = tx.update(grads, opt, p)
                p2 = jax.tree_util.tree_map(
                    lambda a, u: (a.astype(jnp.float32) + u).astype(a.dtype),
                    p, upd)
                return (p2, opt), loss

            return (params, tx.init(params)), step

        return make

    # -- variants ---------------------------------------------------------
    def fwd_only():
        model = get_model("googlenet", dtype=jnp.bfloat16)
        variables = jit_init(model)

        def step(p, x, s):
            emb = model.apply({"params": p}, x * (1.0 + s * 1e-6),
                              train=True)
            return p, emb.astype(jnp.float32).sum()

        return variables["params"], step

    def npair_only():
        def step(p, e, s):
            loss, g = jax.value_and_grad(
                lambda ee: npair_loss(ee, labels, REFERENCE_CONFIG)
            )(e * (1.0 + s * 1e-6))
            return jax.tree_util.tree_map(lambda a: a + g[0, 0] * 0, p), loss

        return {"w": jnp.zeros(())}, step

    # Deferred thunks so a --variant child builds/compiles only its own.
    # s2d: space-to-depth stem (models/googlenet.py stem_s2d) —
    # algebraically identical trunk, MXU-friendlier conv1 tiling.
    # fused: the three input-reading 1x1 convs per inception block become
    # one full-lane gemm (exact algebra) — prices the thin-branch MXU
    # underutilization PROFILE.md attributes headroom to.
    # mxu: both parity-preserving rewrites stacked.
    # remat: recompute-in-backward; the delta vs "full" prices the
    # recompute FLOPs at this batch (batch-480 HBM-pressure effect is
    # bench.py's 480_remat row).
    variants = {
        "full": lambda: timed(
            "full", model_step("googlenet", dtype=jnp.bfloat16), images),
        "fwd_only": lambda: timed("fwd_only", fwd_only, images),
        "fwd_bwd": lambda: timed(
            "fwd_bwd",
            model_step("googlenet", with_loss=False, dtype=jnp.bfloat16),
            images),
        "npair_only": lambda: timed("npair_only", npair_only, emb_fixed),
        "no_lrn": lambda: timed(
            "no_lrn",
            model_step("googlenet", dtype=jnp.bfloat16, use_lrn=False),
            images),
        "fp32": lambda: timed(
            "fp32", model_step("googlenet", dtype=jnp.float32), images),
        "bn": lambda: timed(
            "bn", model_step("googlenet_bn", dtype=jnp.bfloat16), images),
        "s2d": lambda: timed(
            "s2d", model_step("googlenet_s2d", dtype=jnp.bfloat16),
            images),
        "fused": lambda: timed(
            "fused", model_step("googlenet_fused", dtype=jnp.bfloat16),
            images),
        "mxu": lambda: timed(
            "mxu", model_step("googlenet_mxu", dtype=jnp.bfloat16),
            images),
        "remat": lambda: timed(
            "remat",
            model_step("googlenet", dtype=jnp.bfloat16, remat=True),
            images),
    }
    for name in ([args.variant] if args.variant else VARIANT_ORDER):
        variants[name]()

    payload = {
        "device": dev.device_kind,
        "batch": batch,
        "image": image,
        "steps_per_timing": steps,
        "fetch_floor_ms": round(floor * 1e3, 1),
        "results": results,
    }
    if not args.variant:
        # Child mode never writes the artifact — the orchestrator owns
        # the merged file; a one-variant payload must not replace it.
        _write_artifacts(payload, getattr(args, "artifact", ARTIFACT))
    print(json.dumps(payload))
    return 0


def _load_perf_report_module():
    """File-path import of the stdlib-only obs/perf/report module — the
    orchestrator parent is jax-free by design (a hung backend import
    must never kill the resumable per-variant loop), so it must not
    import the npairloss_tpu package (same trick as bench.py's parent
    loading obs.sinks)."""
    import importlib.util

    path = os.path.join(REPO, "npairloss_tpu", "obs", "perf", "report.py")
    spec = importlib.util.spec_from_file_location("_npair_perf_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_profile_md(payload):
    """profile/flagship.md via the shared ablation renderer
    (obs.perf.report.ablation_markdown — PROFILE.md stays hand-curated
    and cites the artifact).  The hand-rolled table/attribution writer
    this script used to carry lives there now, so the ablation view and
    the `prof` reports evolve together."""
    md = _load_perf_report_module().ablation_markdown(payload)
    with open(os.path.join(REPO, "profile", "flagship.md"), "w") as f:
        f.write(md)


if __name__ == "__main__":
    sys.exit(main())
