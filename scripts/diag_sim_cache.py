"""Diagnose the 32k cached-stretch hang (round 4).

scripts/tpu_pallas_check.py timed out on the real chip at
``stretch 32768: absolute (sim_cache=on)`` after finishing every
uncached measurement.  Hypotheses: (a) the 4.3 GB fp32 cache held as a
VJP residual plus lax.scan double-buffering exceeds the 16 GB v5e HBM
and the tunnel stalls instead of raising; (b) the cached sweeps' HBM
traffic is pathologically slow; (c) Mosaic compile blowup for the cached
kernel family at that operand size.

This script bisects: for each pool size it times fwd-only and fwd+bwd,
scan-of-1 and scan-of-3, cached only, and prints peak HBM after each
phase — with a watchdog print before every phase so the log shows
exactly where a hang begins.  Output lines are flushed immediately; run
under ``timeout`` and read the tail.

Usage: python scripts/diag_sim_cache.py [--pools 8192,16384,32768]
"""

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pools", default="8192,16384,32768")
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--block", type=int, default=512)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass

    import jax.numpy as jnp
    import numpy as np

    from npairloss_tpu.ops.npair_loss import MiningMethod, NPairLossConfig
    from npairloss_tpu.ops.pallas_npair import blockwise_npair_loss

    dev = jax.devices()[0]

    def say(msg):
        print(f"[diag t={time.perf_counter() - T0:7.1f}s] {msg}",
              file=sys.stderr, flush=True)

    def hbm(tag):
        try:
            st = dev.memory_stats() or {}
            say(f"{tag}: in_use={st.get('bytes_in_use', 0) / 2**30:.2f} GiB "
                f"peak={st.get('peak_bytes_in_use', 0) / 2**30:.2f} GiB "
                f"limit={st.get('bytes_limit', 0) / 2**30:.2f} GiB")
        except Exception as e:
            say(f"{tag}: memory_stats unavailable ({e})")

    T0 = time.perf_counter()
    say(f"backend={dev.platform} kind={dev.device_kind}")
    hbm("start")

    cfg = NPairLossConfig(margin_diff=-0.05,
                          an_mining_method=MiningMethod.HARD)
    rng = np.random.default_rng(0)

    for pool in [int(p) for p in args.pools.split(",")]:
        f = rng.standard_normal((pool, args.dim)).astype(np.float32)
        f /= np.linalg.norm(f, axis=1, keepdims=True)
        feats = jax.device_put(jnp.asarray(f))
        labels = jax.device_put(jnp.asarray(
            np.repeat(np.arange(pool // 2), 2).astype(np.int32)))
        cache_gib = pool * pool * 4 / 2**30
        say(f"=== pool {pool} (cache {cache_gib:.2f} GiB) ===")

        def loss_fn(x):
            return blockwise_npair_loss(
                x, labels, cfg, block_size=args.block, sim_cache=True)

        # Phase 1: fwd only, single call (cache is transient).  The
        # perturbation scale rides INSIDE the jitted fn — eager device
        # ops on the axon tunnel are themselves a hang hazard and would
        # confound the bisect (.claude/skills/verify/SKILL.md).
        say("phase fwd-1: compile+run")
        fwd = jax.jit(lambda x, s: loss_fn(x * (1.0 + s * 1e-6)))
        t0 = time.perf_counter()
        l0 = float(np.asarray(fwd(feats, jnp.float32(0))))
        say(f"phase fwd-1 done: loss={l0:.6f} "
            f"wall={time.perf_counter() - t0:.1f}s")
        hbm("after fwd-1")
        t0 = time.perf_counter()
        float(np.asarray(fwd(feats, jnp.float32(1))))
        say(f"phase fwd-1 rerun: wall={time.perf_counter() - t0:.2f}s")

        # Phase 2: fwd+bwd, single call (cache lives fwd->bwd as residual).
        say("phase vg-1: compile+run")
        vg = jax.jit(lambda x, s: jax.value_and_grad(
            lambda y: loss_fn(y * (1.0 + s * 1e-6)))(x))
        t0 = time.perf_counter()
        l0, g = vg(feats, jnp.float32(0))
        l0 = float(np.asarray(l0))
        g00 = float(np.asarray(g[0, 0]))
        say(f"phase vg-1 done: loss={l0:.6f} g00={g00:.2e} "
            f"wall={time.perf_counter() - t0:.1f}s")
        hbm("after vg-1")
        t0 = time.perf_counter()
        l1, g = vg(feats, jnp.float32(1))
        float(np.asarray(l1))
        say(f"phase vg-1 rerun: wall={time.perf_counter() - t0:.2f}s")

        # Phase 3: fwd+bwd inside scan-of-3 (tpu_pallas_check's shape —
        # adds scan double-buffering on top of the residual).
        say("phase vg-scan3: compile+run")

        @jax.jit
        def many(x, round_id):
            def body(acc, s):
                loss, grad = jax.value_and_grad(loss_fn)(
                    x * (1.0 + (round_id * 3 + s) * 1e-6))
                return acc + loss + grad[0, 0], loss

            acc, losses = jax.lax.scan(
                body, jnp.float32(0.0), jnp.arange(3, dtype=jnp.float32))
            return acc, losses[0]

        t0 = time.perf_counter()
        acc, _ = many(feats, jnp.float32(0))
        float(np.asarray(acc))
        say(f"phase vg-scan3 done: wall={time.perf_counter() - t0:.1f}s")
        hbm("after vg-scan3")
        t0 = time.perf_counter()
        acc, _ = many(feats, jnp.float32(1))
        float(np.asarray(acc))
        dt = time.perf_counter() - t0
        say(f"phase vg-scan3 rerun: wall={dt:.2f}s "
            f"({dt / 3 * 1e3:.1f} ms/step)")

    say("ALL DONE")
    return 0


if __name__ == "__main__":
    sys.exit(main())
