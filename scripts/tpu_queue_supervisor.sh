#!/bin/bash
# Keeps tpu_queue_v3.sh alive until it completes: the queue gives up
# after 30 failed probes (~2.3h) so one long outage doesn't leave a
# zombie prober, and this supervisor simply starts the next attempt —
# logs rotated per attempt.  Run detached:
#   setsid nohup scripts/tpu_queue_supervisor.sh &
# Supervisor log: /tmp/tpu_queue_supervisor.log
cd "$(dirname "$0")/.."
exec >> /tmp/tpu_queue_supervisor.log 2>&1

for attempt in $(seq 1 48); do
  # Never run two queues at once.
  while pgrep -f "bash scripts/tpu_queue_v3.sh" > /dev/null; do
    sleep 60
  done
  if grep -q "QUEUE V3 DONE" /tmp/tpu_queue_v3.log 2>/dev/null; then
    echo "$(date) queue completed; supervisor exiting"
    exit 0
  fi
  if [ -f /tmp/tpu_queue_v3.log ]; then
    cp /tmp/tpu_queue_v3.log "/tmp/tpu_queue_v3.attempt${attempt}.log"
  fi
  echo "$(date) starting queue attempt ${attempt}"
  scripts/tpu_queue_v3.sh
  echo "$(date) queue attempt ${attempt} exited rc=$?"
  sleep 30
done
echo "$(date) supervisor budget exhausted"
