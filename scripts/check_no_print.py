#!/usr/bin/env python
"""Lint: no bare ``print()`` in npairloss_tpu/ library code.

Library modules must emit through the package loggers or the obs metric
sinks (docs/OBSERVABILITY.md) — a print() in library code bypasses both
the embedder's logging configuration and the structured telemetry
pipeline.  The user-facing surfaces are exempt: ``cli.py`` and
``__main__.py`` (their printed JSON lines ARE the product), plus
everything outside the package (scripts/, tests/, bench.py).

Exit 0 when clean; exit 1 listing every offending file:line.

Usage: check_no_print.py [ROOT]   (default: the repo's npairloss_tpu/)
"""

from __future__ import annotations

import ast
import os
import sys

EXEMPT_BASENAMES = {"cli.py", "__main__.py"}
# Root-relative exemptions for user-facing surfaces that are not
# top-level: the staticcheck driver's printed findings ARE the product
# (it doubles as `python -m npairloss_tpu staticcheck`).
EXEMPT_RELPATHS = {os.path.join("analysis", "runner.py")}


def find_prints(path: str):
    """Yield (lineno, source_line) for every print() call in the file."""
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        # A file the linter cannot parse is its own failure mode — the
        # test suite will say more; don't mask it as "no prints".
        yield (e.lineno or 0, f"SYNTAX ERROR: {e.msg}")
        return
    lines = source.splitlines()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            text = lines[node.lineno - 1].strip() if node.lineno <= len(
                lines) else ""
            yield (node.lineno, text)


def main(argv) -> int:
    if len(argv) > 1:
        root = argv[1]
    else:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        root = os.path.join(repo, "npairloss_tpu")
    failures = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py") or name in EXEMPT_BASENAMES:
                continue
            path = os.path.join(dirpath, name)
            if os.path.relpath(path, root) in EXEMPT_RELPATHS:
                continue
            for lineno, text in find_prints(path):
                failures.append(f"{path}:{lineno}: {text}")
    if failures:
        sys.stderr.write(
            "bare print() in library code (use logging or obs sinks):\n"
        )
        for f in failures:
            sys.stderr.write(f"  {f}\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
