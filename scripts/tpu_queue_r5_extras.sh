#!/bin/bash
# Round-5 follow-on hardware captures.  Runs AFTER tpu_queue_v3.sh
# completes (it polls the v3 log for the DONE marker) so it can never
# steal tunnel bandwidth from the primary evidence sweep — concurrent
# dispatches pollute the timings (docs/DESIGN.md §6).
#
#   1. conv-trunk e2e JPEG proof ON THE CHIP (the TPU counterpart of
#      accuracy/e2e_real_jpeg_googlenet_bn.json): native C++ loader +
#      on-device augmentation + googlenet_bn + mined loss + snapshot/
#      resume against the real backend.
#
# Run detached:  setsid nohup scripts/tpu_queue_r5_extras.sh &
# Log: /tmp/tpu_queue_r5_extras.log
cd "$(dirname "$0")/.."
exec > /tmp/tpu_queue_r5_extras.log 2>&1

probe() {
  timeout 100 python -c \
    'import jax,sys; sys.exit(jax.devices()[0].platform != "tpu")' \
    >/dev/null 2>&1
}

wait_tunnel() {
  for i in $(seq 1 30); do
    probe && { echo "tunnel up after probe $i ($(date))"; return 0; }
    echo "probe $i failed ($(date)); sleeping 180s"
    sleep 180
  done
  echo "tunnel still down after 30 probes"
  return 1
}

echo "=== $(date) waiting for primary queue (tpu_queue_v3) to finish ==="
for i in $(seq 1 2880); do  # up to ~48h of polling, zero TPU traffic
  if grep -q "QUEUE V3 DONE" /tmp/tpu_queue_v3.log 2>/dev/null; then
    echo "primary queue done ($(date))"
    break
  fi
  sleep 60
done
grep -q "QUEUE V3 DONE" /tmp/tpu_queue_v3.log 2>/dev/null || {
  echo "primary queue never finished; exiting"; exit 1; }

echo "=== $(date) 1/1 conv-trunk e2e JPEG on TPU ==="
# 4 CLI invocations (train/resume/extract/eval) behind a tunnel where
# first compiles take minutes: budget well past the script's own
# per-subprocess 3600s so the outer timeout can't kill it mid-train.
rc=1
wait_tunnel && { timeout 7200 env E2E_JAX_PLATFORM=default \
  python scripts/e2e_real_jpeg.py \
  --model googlenet_bn --steps 600 --workdir /tmp/e2e_conv_tpu \
  --artifact accuracy/e2e_real_jpeg_googlenet_bn_tpu.json; rc=$?; }
echo "conv e2e tpu rc=$rc"

if [ "$rc" = 0 ] && [ -f accuracy/e2e_real_jpeg_googlenet_bn_tpu.json ]; then
  echo "=== $(date) R5 EXTRAS DONE ==="
else
  echo "=== $(date) R5 EXTRAS FAILED (rc=$rc; artifact $( [ -f accuracy/e2e_real_jpeg_googlenet_bn_tpu.json ] && echo present || echo MISSING )) ==="
fi
