#!/bin/bash
# Round-5 follow-on hardware captures.  Runs AFTER tpu_queue_v3.sh
# completes (it polls the v3 log for the DONE marker) so it can never
# steal tunnel bandwidth from the primary evidence sweep — concurrent
# dispatches pollute the timings (docs/DESIGN.md §6).
#
#   1. bench re-pass IF the first pass lost batch-scaling rows to a
#      wedge (2026-08-02: the batch-480 compile wedged the tunnel at
#      16:05 UTC, costing the vit_b16/s2d/fused/remat rows; 480 is
#      quarantined so the re-pass cannot re-wedge on it).  Runs FIRST:
#      the timed ViT-B/16 row is a named VERDICT item with no other
#      coverage, and the conv e2e below needs 2h of healthy tunnel.
#   2. conv-trunk e2e JPEG proof ON THE CHIP (the TPU counterpart of
#      accuracy/e2e_real_jpeg_googlenet_bn.json): native C++ loader +
#      on-device augmentation + googlenet_bn + mined loss + snapshot/
#      resume against the real backend.
#
# Run detached:  setsid nohup scripts/tpu_queue_r5_extras.sh &
# Log: /tmp/tpu_queue_r5_extras.log
cd "$(dirname "$0")/.."
exec > /tmp/tpu_queue_r5_extras.log 2>&1

probe() {
  timeout 100 python -c \
    'import jax,sys; sys.exit(jax.devices()[0].platform != "tpu")' \
    >/dev/null 2>&1
}

wait_tunnel() {
  for i in $(seq 1 30); do
    probe && { echo "tunnel up after probe $i ($(date))"; return 0; }
    echo "probe $i failed ($(date)); sleeping 180s"
    sleep 180
  done
  echo "tunnel still down after 30 probes"
  return 1
}

echo "=== $(date) waiting for primary queue (tpu_queue_v3) to finish ==="
for i in $(seq 1 2880); do  # up to ~48h of polling, zero TPU traffic
  if grep -q "QUEUE V3 DONE" /tmp/tpu_queue_v3.log 2>/dev/null; then
    echo "primary queue done ($(date))"
    break
  fi
  sleep 60
done
grep -q "QUEUE V3 DONE" /tmp/tpu_queue_v3.log 2>/dev/null || {
  echo "primary queue never finished; exiting"; exit 1; }

echo "=== $(date) 1/2 bench re-pass for wedge-lost batch rows ==="
# bench_rows_missing.py also seeds the 480/480_remat quarantine so the
# re-pass cannot re-wedge on the compile that killed the first pass.
need_repass=$(python scripts/bench_rows_missing.py)
echo "bench re-pass needed: ${need_repass:-checker crashed (fail-open)}"
if [ "$need_repass" != "no" ]; then  # fail-open: crash/empty => re-pass
  # Belt-and-braces backup: bench.py --rows MERGES into last_good.json
  # (a selective record can no longer clobber measured rows), but an
  # operator re-run without --rows still replaces — keep the pass-1
  # payload either way.  -n: never clobber an existing backup.
  if [ -f bench_cache/last_good.json ]; then
    cp -n bench_cache/last_good.json bench_cache/last_good_pass1.json
    [ -f bench_cache/last_good_pass1.json ] \
      || echo "WARNING: pass-1 backup failed; re-pass may clobber rows"
  fi
  # Selective re-measure (bench.py --rows, ADVICE #2): only the wanted
  # rows still missing are dispatched — the re-pass no longer spends
  # ~70 min re-measuring the headline + eleven engine rows before
  # reaching the batch rows it exists to recover.  Empty list with a
  # fail-open "yes" above means the checker couldn't read last_good:
  # fall back to the full sweep.
  rows=$(python scripts/bench_rows_missing.py --print-rows)
  echo "re-pass rows: ${rows:-<full sweep>}"
  if wait_tunnel; then
    if [ -n "$rows" ]; then
      timeout 4200 python bench.py --rows "$rows" > /tmp/bench_out_repass.json
    else
      timeout 4200 python bench.py > /tmp/bench_out_repass.json
    fi
    echo "bench re-pass rc=$?"
    tail -c 600 /tmp/bench_out_repass.json 2>/dev/null; echo
  fi
fi
# Coverage, not exit code or dispatch decisions, decides success: the
# strict check runs UNCONDITIONALLY so DONE means every wanted row is
# measured — not skipped, errored, quarantined, or given-up-on (a
# wedge's auto-quarantine must not flip a later run to DONE).
still=$(python scripts/bench_rows_missing.py --strict)
echo "wanted rows still missing (strict): ${still:-unknown}"
if [ "$still" = "no" ]; then repass_ok=1; else repass_ok=0; fi

echo "=== $(date) 2/2 conv-trunk e2e JPEG on TPU ==="
# 4 CLI invocations (train/resume/extract/eval) behind a tunnel where
# first compiles take minutes: budget well past the script's own
# per-subprocess 3600s so the outer timeout can't kill it mid-train.
rc=1
wait_tunnel && { timeout 7200 env E2E_JAX_PLATFORM=default \
  python scripts/e2e_real_jpeg.py \
  --model googlenet_bn --steps 600 --workdir /tmp/e2e_conv_tpu \
  --artifact accuracy/e2e_real_jpeg_googlenet_bn_tpu.json; rc=$?; }
echo "conv e2e tpu rc=$rc"

if [ "$rc" = 0 ] && [ "$repass_ok" = 1 ] \
  && [ -f accuracy/e2e_real_jpeg_googlenet_bn_tpu.json ]; then
  echo "=== $(date) R5 EXTRAS DONE ==="
else
  echo "=== $(date) R5 EXTRAS FAILED (e2e rc=$rc; repass_ok=$repass_ok; artifact $( [ -f accuracy/e2e_real_jpeg_googlenet_bn_tpu.json ] && echo present || echo MISSING )) ==="
fi
