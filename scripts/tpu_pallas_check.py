"""On-TPU Pallas validation: Mosaic-compile the blockwise kernels and
assert parity vs the dense XLA path on the real chip.

The CPU test suite runs the kernels in Pallas interpreter mode
(tests/test_pallas.py); Mosaic tiling/SMEM constraints only bite on real
hardware, so this script is the one-command hardware check (VERDICT r1
item 2): forward+backward at pool >= 4096 for both an absolute config
and the flagship GLOBAL/RELATIVE_HARD config, on-device parity against
the dense path, then a 32k blockwise-only run (whose dense pair matrix
cannot exist) with throughput numbers.

Usage:  python scripts/tpu_pallas_check.py [--pool 4096] [--stretch 32768]
Writes one JSON line to stdout; nonzero exit on any parity failure.

Everything is jitted (eager ops on the axon tunnel are hazardous — see
.claude/skills/verify/SKILL.md).
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pool", type=int, default=4096)
    ap.add_argument("--stretch", type=int, default=32768)
    ap.add_argument(
        "--stretch-cached", type=int, default=None,
        help="pool for the sim_cache=on stretch rows (default: --stretch). "
        "Round 4 found that dispatching the cached program with the 32k "
        "pool's exactly-4.0-GiB cache WEDGES the tunneled v5e backend — "
        "every later client gets UNAVAILABLE until the tunnel resets — so "
        "the revalidation queue measures the cached rows at a pool the "
        "auto-gate accepts and records the 32k auto verdict separately.")
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--block", type=int, default=512)
    ap.add_argument("--cpu", action="store_true",
                    help="debug on CPU (interpret mode)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass

    import jax.numpy as jnp
    import numpy as np

    from npairloss_tpu import REFERENCE_CONFIG, NPairLossConfig
    from npairloss_tpu.ops.npair_loss import MiningMethod, npair_loss
    from npairloss_tpu.ops.npair_loss import resolve_sim_cache_auto
    from npairloss_tpu.ops.pallas_npair import blockwise_npair_loss

    dev = jax.devices()[0]
    print(f"[tpu-check] backend={dev.platform} kind={dev.device_kind}",
          file=sys.stderr, flush=True)
    on_tpu = dev.platform == "tpu"

    abs_cfg = NPairLossConfig(
        margin_diff=-0.05,
        an_mining_method=MiningMethod.HARD,
    )
    configs = [("absolute", abs_cfg), ("flagship", REFERENCE_CONFIG)]

    rng = np.random.default_rng(0)
    record = {"device": dev.device_kind, "pool": args.pool,
              "parity": {}, "stretch": {}}
    ok = True

    # Incremental spill (bench.py's wedge lesson, 08:04 UTC 2026-08-01):
    # a heavy dispatch can wedge the tunnel mid-run and this process
    # never prints — the spill keeps everything measured so far
    # recoverable from disk.
    spill_path = os.environ.get(
        "TPU_CHECK_SPILL_PATH", f"/tmp/tpu_check_spill.{os.getuid()}.json")
    try:  # a stale spill from a previous run must never be salvageable
        os.remove(spill_path)
    except OSError:
        pass

    def spill():
        try:
            tmp = spill_path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(record, fh)
            os.replace(tmp, spill_path)
        except Exception:
            pass

    n = args.pool
    f = rng.standard_normal((n, args.dim)).astype(np.float32)
    f /= np.linalg.norm(f, axis=1, keepdims=True)
    feats = jax.device_put(jnp.asarray(f))
    labels = jax.device_put(
        jnp.asarray(np.repeat(np.arange(n // 2), 2).astype(np.int32)))

    for name, cfg in configs:
        print(f"[tpu-check] parity: {name} (pool {n})...",
              file=sys.stderr, flush=True)
        dense = jax.jit(jax.value_and_grad(
            lambda x: npair_loss(x, labels, cfg)))
        block = jax.jit(jax.value_and_grad(
            lambda x: blockwise_npair_loss(
                x, labels, cfg, block_size=args.block)))
        ld, gd = dense(feats)
        lb, gb = block(feats)
        dl = abs(float(ld) - float(lb))
        # jitted delta: eager reductions on the axon tunnel are hazardous
        dg = float(jax.jit(lambda a, b: jnp.max(jnp.abs(a - b)))(gd, gb))
        rel_ok = dl <= 1e-4 * max(1.0, abs(float(ld))) and dg <= 1e-5
        record["parity"][name] = {
            "loss_dense": float(ld), "loss_blockwise": float(lb),
            "loss_delta": dl, "grad_max_delta": dg, "ok": rel_ok,
        }
        ok = ok and rel_ok
        print(f"[tpu-check]   loss {float(ld):.6f} vs {float(lb):.6f} "
              f"(d={dl:.2e}), grad max d={dg:.2e} -> "
              f"{'OK' if rel_ok else 'FAIL'}", file=sys.stderr, flush=True)
        spill()

    # Stretch: blockwise-only at a pool whose dense matrix cannot exist.
    ns = args.stretch

    def stretch_arrays(n_):
        fs = rng.standard_normal((n_, args.dim)).astype(np.float32)
        fs /= np.linalg.norm(fs, axis=1, keepdims=True)
        return (
            jax.device_put(jnp.asarray(fs)),
            jax.device_put(jnp.asarray(
                np.repeat(np.arange(n_ // 2), 2).astype(np.int32))),
        )

    feats_s, labels_s = stretch_arrays(ns)
    # Timing discipline (see bench.py): the tunneled backend neither
    # blocks in block_until_ready nor re-executes identical dispatches,
    # so time `reps` perturbed fwd+bwd steps inside ONE jitted lax.scan,
    # sync via host fetch, and subtract the measured dispatch floor.
    @jax.jit
    def _tiny(x):
        return x.sum()

    float(np.asarray(_tiny(jnp.full((8, 8), 1.0))))
    t0 = time.perf_counter()
    float(np.asarray(_tiny(jnp.full((8, 8), 2.0))))
    floor = time.perf_counter() - t0

    reps = 3

    def time_stretch(cfg, use_cache: bool, feats_t=None, labels_t=None,
                     pos_topk=None):
        feats_t = feats_s if feats_t is None else feats_t
        labels_t = labels_s if labels_t is None else labels_t
        n_t = int(feats_t.shape[0])
        vg = jax.value_and_grad(
            lambda x: blockwise_npair_loss(
                x, labels_t, cfg, block_size=args.block,
                sim_cache=use_cache, pos_topk=pos_topk))

        @jax.jit
        def many(x, round_id):
            def body(acc, s):
                # round_id makes every call a distinct computation (the
                # tunnel dedupes identical dispatches) without any eager
                # array op leaking into the timed window.
                loss, grad = vg(x * (1.0 + (round_id * reps + s) * 1e-6))
                return acc + loss + grad[0, 0], loss

            acc, losses = jax.lax.scan(
                body, jnp.float32(0.0), jnp.arange(reps, dtype=jnp.float32))
            return acc, losses[0]

        acc, l0 = many(feats_t, jnp.float32(0))
        float(np.asarray(acc))  # compile + warm
        acc, l0 = many(feats_t, jnp.float32(1))
        float(np.asarray(acc))  # second warm (first-program phantom cost)
        t0 = time.perf_counter()
        acc, l0 = many(feats_t, jnp.float32(2))
        float(np.asarray(acc))
        dt = max(time.perf_counter() - t0 - floor, 1e-9) / reps
        return {
            "loss": float(np.asarray(l0)),
            "ms_per_step": round(dt * 1e3, 2),
            "embeddings_per_sec": round(n_t / dt, 1),
            "sim_cache": use_cache,
            "pool": n_t,
        }

    def peak_bytes():
        try:
            stats = dev.memory_stats() or {}
            return int(stats.get("peak_bytes_in_use", 0))
        except Exception as e:
            print(f"[tpu-check] memory stats unavailable: {e}",
                  file=sys.stderr, flush=True)
            return None

    # Measure BOTH cache states (VERDICT r3 item 3: the cache's effect at
    # the stretch must be an artifact, not a hypothesis).
    # peak_bytes_in_use is a process-lifetime high-water mark (never
    # reset), so the UNCACHED runs go first: their snapshot is a true
    # uncached peak, and the post-cached snapshot minus it attributes the
    # n*n*4-byte fp32 tile allocation to the cache.
    # resolve_sim_cache_auto is what sim_cache=None actually does
    # (device-memory-capped budget), so the artifact records its verdict
    # at the FULL stretch pool even when the cached rows run smaller.
    cache_auto = resolve_sim_cache_auto(ns * ns * 4, "blockwise")
    record["sim_cache_auto_at_stretch"] = cache_auto
    for name, cfg in configs:
        print(f"[tpu-check] stretch {ns}: {name} (sim_cache=off)...",
              file=sys.stderr, flush=True)
        rec_n = time_stretch(cfg, False)
        record["stretch"][name + "_nocache"] = rec_n
        spill()
        print(f"[tpu-check]   {rec_n['ms_per_step']:.1f} ms/step, "
              f"{rec_n['embeddings_per_sec']:.0f} emb/s",
              file=sys.stderr, flush=True)
    pk = peak_bytes()
    if pk is not None:
        record["peak_bytes_in_use_nocache"] = pk
    nc = args.stretch_cached or ns
    record["cached_pool"] = nc
    if nc != ns:
        feats_c, labels_c = stretch_arrays(nc)
        # Paired uncached rows at the cached pool so the cache delta is
        # apples-to-apples even when nc != ns.
        for name, cfg in configs:
            print(f"[tpu-check] stretch {nc}: {name} (sim_cache=off)...",
                  file=sys.stderr, flush=True)
            rec_n = time_stretch(cfg, False, feats_c, labels_c)
            record["stretch"][name + "_nocache_cachedpool"] = rec_n
            spill()
            print(f"[tpu-check]   {rec_n['ms_per_step']:.1f} ms/step, "
                  f"{rec_n['embeddings_per_sec']:.0f} emb/s",
                  file=sys.stderr, flush=True)
    else:
        feats_c, labels_c = feats_s, labels_s
    cache_auto_nc = (cache_auto if nc == ns
                     else resolve_sim_cache_auto(nc * nc * 4, "blockwise"))
    for name, cfg in configs:
        print(f"[tpu-check] stretch {nc}: {name} (sim_cache=on)...",
              file=sys.stderr, flush=True)
        rec_c = time_stretch(cfg, True, feats_c, labels_c)
        rec_c["sim_cache_auto"] = cache_auto_nc
        record["stretch"][name] = rec_c
        spill()
        key = (name + "_nocache" if nc == ns
               else name + "_nocache_cachedpool")
        rec_n = record["stretch"][key]
        if abs(rec_c["loss"] - rec_n["loss"]) > 1e-4 * max(1.0, abs(rec_n["loss"])):
            print(f"[tpu-check]   CACHE PARITY FAIL: {rec_c['loss']} vs "
                  f"{rec_n['loss']}", file=sys.stderr, flush=True)
            ok = False
        print(f"[tpu-check]   {rec_c['ms_per_step']:.1f} ms/step, "
              f"{rec_c['embeddings_per_sec']:.0f} emb/s "
              f"(uncached was {rec_n['ms_per_step']:.1f})",
              file=sys.stderr, flush=True)
    pk = peak_bytes()
    if pk is not None:
        record["peak_bytes_in_use_cached"] = pk
        record["peak_bytes_in_use"] = pk

    # Radix-forced flagship row (pos_topk=0): the delta against
    # flagship_nocache — whose AP threshold now rides the
    # sparse-positive fast path — records the round-4 fast path's gain
    # on hardware, and parity between the two is the strongest on-chip
    # check of the fast path (identical population, different selection
    # machinery).  Runs LAST and behind the shared quarantine: the
    # pos_topk=0 streamed-radix compile is the dispatch that wedged the
    # tunnel at 08:06 UTC 2026-08-01 (bench_cache/quarantine.json), and
    # a re-wedge must not cost the cached-stretch rows above.
    try:  # one quarantine protocol, owned by bench.py
        import bench as _bench
        q_note = _bench._quarantined("blockwise_flagship_radix")
    except Exception:
        q_note = None
    if q_note:
        record["stretch"]["flagship_radix_nocache"] = {
            "skipped": f"quarantined: {q_note}"}
        print("[tpu-check] stretch radix row SKIPPED (quarantined)",
              file=sys.stderr, flush=True)
        spill()
    else:
        print(f"[tpu-check] stretch {ns}: flagship (radix, sim_cache=off)...",
              file=sys.stderr, flush=True)
        rec_r = time_stretch(REFERENCE_CONFIG, False, pos_topk=0)
        record["stretch"]["flagship_radix_nocache"] = rec_r
        spill()
        rec_f = record["stretch"]["flagship_nocache"]
        print(f"[tpu-check]   {rec_r['ms_per_step']:.1f} ms/step, "
              f"{rec_r['embeddings_per_sec']:.0f} emb/s "
              f"(fast path was {rec_f['ms_per_step']:.1f})",
              file=sys.stderr, flush=True)
        if abs(rec_r["loss"] - rec_f["loss"]) > 1e-4 * max(
                1.0, abs(rec_f["loss"])):
            print(f"[tpu-check]   FAST-PATH PARITY FAIL: {rec_f['loss']} vs "
                  f"{rec_r['loss']}", file=sys.stderr, flush=True)
            ok = False
        pk = peak_bytes()
        if pk is not None:
            # the radix program may be the true process peak now that it
            # runs after the cached snapshot
            record["peak_bytes_in_use_radix"] = pk
            record["peak_bytes_in_use"] = pk

    record["ok"] = ok
    record["mosaic_compiled"] = on_tpu
    print(json.dumps(record))
    try:  # the record reached stdout; the spill is no longer needed
        os.remove(spill_path)
    except OSError:
        pass
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
