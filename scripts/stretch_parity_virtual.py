"""Stretch-pool parity oracle on the virtual CPU mesh (no TPU needed).

STRETCH.json times the 32k-pool blockwise engine on hardware, but no
artifact pins CORRECTNESS at that scale: the CPU test suite tops out at
a few hundred rows, and the hardware stretch has no dense oracle to
compare against (the whole point of the streaming engines is that the
dense pair matrix is HBM-impossible on-chip).  On the host, 125 GB of
RAM makes the dense 32k graph possible — so this script computes, at
the full stretch pool:

    dense  : ``npair_loss`` value+grad on all N rows, single device
    ring   : ``parallel.ring`` over the 8-shard virtual mesh
             (N/8 rows per shard, ppermute streaming, grad rotation)

with the FLAGSHIP mining config (GLOBAL/RELATIVE_HARD AP + LOCAL/HARD
AN, usage/def.prototxt:137-146) — at N=32k the RELATIVE rank population
is ~1e9 pairs, exercising the radix-selection count arithmetic at a
scale no unit test reaches — and asserts loss + gradient parity.

Writes STRETCH_PARITY.json.  Runtime: tens of minutes on one CPU core
(three ~1.1-TFLOP gemms plus full-matrix sweeps); pass --pool to
shrink.

Usage: python scripts/stretch_parity_virtual.py [--pool 32768]
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(msg):
    print(f"[stretch-parity t={time.time() - T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


T0 = time.time()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pool", type=int, default=32768)
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument(
        "--blockwise-pool", type=int, default=0,
        help="also check the Pallas blockwise engine (interpret mode on "
        "CPU) against the single-rank dense oracle at this pool — "
        "interpret is slow, so this uses a smaller pool than the ring "
        "check (8192 is ~4x the hardware parity pool)",
    )
    ap.add_argument(
        "--skip-ring", action="store_true",
        help="only run the blockwise section (merge into existing out)",
    )
    ap.add_argument(
        "--out", default=os.path.join(REPO, "STRETCH_PARITY.json")
    )
    args = ap.parse_args()

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.shards}"
    ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from npairloss_tpu import REFERENCE_CONFIG
    from npairloss_tpu.ops.npair_loss import npair_loss
    from npairloss_tpu.parallel._compat import shard_map
    from npairloss_tpu.parallel.mesh import data_parallel_mesh
    from npairloss_tpu.parallel.ring import ring_npair_loss_and_metrics

    n, d, g = args.pool, args.dim, args.shards
    assert n % g == 0
    rng = np.random.default_rng(0)
    f = rng.standard_normal((n, d)).astype(np.float32)
    f /= np.linalg.norm(f, axis=1, keepdims=True)
    labels_np = np.repeat(np.arange(n // 2), 2).astype(np.int32)
    mesh = data_parallel_mesh(jax.devices()[:g])
    shard = NamedSharding(mesh, P("dp"))
    feats = jax.device_put(jnp.asarray(f), shard)
    labels = jax.device_put(jnp.asarray(labels_np), shard)
    cfg = REFERENCE_CONFIG

    log(f"pool {n} x dim {d}, {g} virtual shards, flagship config")

    # Both engines run per-rank semantics on the SAME mesh (the
    # reference is per-MPI-rank: GLOBAL thresholds are per-rank
    # N x N*G block statistics, cu:327-334 — a G=1 dense run would be a
    # DIFFERENT math, not an oracle).  Composition mirrors
    # tests/test_ring.py::_dense_fns/_ring_fns, scaled to the full pool.
    def ring_shard(pos_topk):
        def fn(xs, ls):
            loss = ring_npair_loss_and_metrics(
                xs, ls, cfg, "dp", top_ks=(), pos_topk=pos_topk)[0]
            grad = jax.grad(
                lambda x_: ring_npair_loss_and_metrics(
                    x_, ls, cfg, "dp", top_ks=(), pos_topk=pos_topk
                )[0]
            )(xs)
            return loss[None], grad
        return fn

    def dense_shard(xs, ls):
        # npair_loss(axis_name=...) all-gathers the pool in-graph and
        # materializes this rank's (N/g x N) pair matrix — the full
        # dense-path oracle at stretch scale (~0.5 GB per shard).
        loss = npair_loss(xs, ls, cfg, axis_name="dp")
        grad = jax.grad(
            lambda x_: npair_loss(x_, ls, cfg, axis_name="dp")
        )(xs)
        return loss[None], grad

    def run(name, shard_fn):
        fn = jax.jit(shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P("dp"), P("dp")), out_specs=(P("dp"), P("dp")),
        ))
        log(f"compiling + running {name}...")
        loss, grad = fn(feats, labels)
        loss = np.asarray(loss)
        grad = np.asarray(grad)
        log(f"{name} per-rank loss mean {loss.mean():.6f}")
        return loss, grad

    def parity(name_a, name_b, la, ga, lb, gb):
        """(delta summary, ok) at the test_ring elementwise bar."""
        loss_delta = float(np.max(np.abs(la - lb)))
        grad_max_delta = float(np.max(np.abs(gb - ga)))
        grad_scale = float(np.max(np.abs(gb)))
        grad_ok = bool(np.allclose(ga, gb, rtol=3e-5, atol=1e-6))
        sec_ok = (
            loss_delta <= 1e-4 * max(1.0, abs(float(np.mean(lb))))
            and grad_ok
            and bool(np.isfinite(ga).all())
        )
        return {
            f"loss_{name_a}": float(np.mean(la)),
            f"loss_{name_b}": float(np.mean(lb)),
            "loss_delta": loss_delta,
            "grad_max_delta": grad_max_delta,
            "grad_scale": grad_scale,
            "ok": bool(sec_ok),
        }, sec_ok

    record = {
        "what": ("dense-oracle parity for the streaming engines at "
                 "stretch-scale pools on the virtual CPU mesh — "
                 "correctness at the scale STRETCH.json only times "
                 "(radix RELATIVE selection over ~1e9 pairs included)"),
        "config": "flagship (usage/def.prototxt:137-146)",
        "backend": "cpu (virtual mesh)",
        "command": f"python scripts/stretch_parity_virtual.py --pool {n}"
                   + (f" --blockwise-pool {args.blockwise_pool}"
                      if args.blockwise_pool else ""),
    }
    if os.path.exists(args.out):
        try:
            with open(args.out) as fo:
                prev = json.load(fo)
            for key in ("ring", "ring_radix", "blockwise",
                        "blockwise_radix"):
                if key in prev:
                    record[key] = prev[key]
        except Exception:
            pass

    ok = True
    if not args.skip_ring:
        dense_losses, gd = run(
            "dense oracle (per-rank pair matrices)", dense_shard)
        # Both AP-threshold machineries at the full stretch pool: the
        # sparse-positive fast path (default, round 4) and the radix
        # selection it falls back to (pos_topk=0; rank population ~1e9
        # pairs — the count-arithmetic scale no unit test reaches).
        for key, pos_topk, label in (
            ("ring", None, "ring (sparse-positive fast path)"),
            ("ring_radix", 0, "ring (radix selection, pos_topk=0)"),
        ):
            ring_losses, gr = run(label, ring_shard(pos_topk))
            sec, sec_ok = parity(
                "ring", "dense", ring_losses, gr, dense_losses, gd)
            ok = ok and sec_ok
            record[key] = {
                "pool": n, "dim": d, "shards": g, "pos_topk": pos_topk,
                **sec,
                "note": "per-rank semantics on the 8-shard mesh, both sides",
            }
            log(f"{key} section {'OK' if sec_ok else 'FAIL'}: "
                f"loss d={sec['loss_delta']:.2e}, "
                f"grad max d={sec['grad_max_delta']:.2e}")

    if args.blockwise_pool:
        from npairloss_tpu.ops.pallas_npair import blockwise_npair_loss

        nb = args.blockwise_pool
        fb = rng.standard_normal((nb, d)).astype(np.float32)
        fb /= np.linalg.norm(fb, axis=1, keepdims=True)
        feats_b = jnp.asarray(fb)
        labels_b = jnp.asarray(
            np.repeat(np.arange(nb // 2), 2).astype(np.int32))
        log(f"blockwise section: pool {nb} (interpret mode on CPU)...")
        ld_, gd_ = jax.jit(jax.value_and_grad(
            lambda x: npair_loss(x, labels_b, cfg)))(feats_b)
        ld_, gd_ = np.asarray(ld_), np.asarray(gd_)
        for key, pos_topk in (("blockwise", None), ("blockwise_radix", 0)):
            t0 = time.time()
            lb_, gb_ = jax.jit(jax.value_and_grad(
                lambda x: blockwise_npair_loss(
                    x, labels_b, cfg, pos_topk=pos_topk)))(feats_b)
            lb_, gb_ = np.asarray(lb_), np.asarray(gb_)
            log(f"{key} loss {float(lb_):.6f} ({time.time() - t0:.0f}s)")
            sec, sec_ok = parity(
                "blockwise", "dense",
                np.asarray([lb_]), gb_, np.asarray([ld_]), gd_)
            ok = ok and sec_ok
            record[key] = {
                "pool": nb, "dim": d, "block": 512,
                "interpret": True, "pos_topk": pos_topk, **sec,
                "note": ("single-rank semantics (the blockwise engine is "
                         "the single-chip path); Pallas interpret mode — "
                         "the Mosaic-compiled twin is PALLAS_CHECK.json"),
            }
            log(f"{key} section {'OK' if sec_ok else 'FAIL'}: "
                f"loss d={sec['loss_delta']:.2e}, "
                f"grad max d={sec['grad_max_delta']:.2e}")

    record["ok"] = bool(ok)
    record["elapsed_s"] = round(time.time() - T0, 1)
    with open(args.out, "w") as fo:
        json.dump(record, fo, indent=1)
        fo.write("\n")
    log(f"{'OK' if ok else 'FAIL'} -> {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
