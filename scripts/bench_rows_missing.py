"""Print "yes" if wedge-recoverable batch-scaling rows are missing.

Consulted by scripts/tpu_queue_r5_extras.sh before AND after its bench
re-pass: before to decide whether a re-pass is worth ~70 min of tunnel,
after to decide whether the re-pass actually recovered the rows
(bench.py exits 0 even when a row ends as an {'error'}/{'skipped'}
record, so the exit code proves nothing about row coverage).

Also seeds quarantine entries for the batch-480 rows when 480 is
unmeasured: the 2026-08-02 16:05 UTC wedge happened during the plain
480 compile, and bench.py only auto-quarantines a wedged row if the
child died while it had been in flight >= 15 min — this makes the
"the re-pass cannot re-wedge on 480" premise true by construction
rather than hoping the salvage path wrote the entry.  480_remat is
quarantined alongside it: the remat ablation is only interpretable
against the plain-480 baseline row, and its equally-large first
compile would put the higher-value ViT rows at wedge risk for an
uninterpretable datapoint.

Fail-open: an unexpected condition prints "yes" (the caller treats a
crash/empty output as "yes" too) — with ONE deliberate exception: an
unparseable quarantine.json prints "no" in the before-call, because
bench.py would read the same corrupt file as an empty quarantine and a
green-lit re-pass would dispatch the known tunnel-wedgers the file
exists to block.  --strict is unaffected (it reports coverage, not
dispatch decisions).
"""

import datetime
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAST_GOOD = os.path.join(REPO, "bench_cache", "last_good.json")
QUARANTINE = os.path.join(REPO, "bench_cache", "quarantine.json")

# Rows worth a re-pass, by evidence value: the timed ViT-B/16 rows are
# VERDICT r4 item 5 with no other coverage; s2d/fused price the MXU
# stem/branch rewrites PROFILE.md argues from.
WANT = ["vit_b16_128", "120_s2d", "120_fused", "vit_b16_256"]


def _measured(row) -> bool:
    return isinstance(row, dict) and "emb_per_sec" in row


def main() -> None:
    try:
        rows = json.load(open(LAST_GOOD))["payload"]["extras"][
            "batch_scaling"]
        if not isinstance(rows, dict):
            rows = {}
    except Exception:
        rows = {}  # no usable payload: every wanted row is missing
    quarantine_ok = True
    try:
        quarantine = json.load(open(QUARANTINE))
        assert isinstance(quarantine, dict)
    except FileNotFoundError:
        quarantine = {}
    except Exception:
        # Unparseable file: NEVER rewrite it (that would drop existing
        # entries like the radix wedge row), and NEVER green-light a
        # dispatch — bench.py's _load_quarantine also reads a corrupt
        # file as {}, so a re-pass would dispatch the very rows the
        # quarantine exists to block (the known tunnel-wedgers).
        quarantine, quarantine_ok = {}, False

    # Seed only on EVIDENCE of the incident: last_good's 480 row holds
    # the error record bench.py wrote when the 2026-08-02 dispatch
    # failed.  "480 merely unmeasured" must not seed — that would
    # re-add entries an operator deliberately cleared for a retry, and
    # would fire in fresh environments where 480 never wedged.
    # Deliberate clears are recorded as NULL tombstones in
    # quarantine.json ("480": null): bench.py's _quarantined treats a
    # null entry as not-quarantined, while the `key not in quarantine`
    # guard below sees the key and refuses to re-seed — the round-6
    # un-quarantine (AOT warmup recipe: bench.py --warmup-rows) stays
    # cleared even though last_good still carries the old error
    # evidence.  A REAL re-wedge overwrites the tombstone via
    # bench.py's _quarantine_add.
    row_480 = rows.get("480")
    evidence_480 = isinstance(row_480, dict) and "error" in row_480
    changed = False
    if quarantine_ok and evidence_480:
        today = datetime.date.today().isoformat()
        for key, why in (
            ("480", "batch-480 first compile wedged the tunnel at "
             "16:05 UTC 2026-08-02 (client killed mid-dispatch); seeded "
             "by bench_rows_missing.py so a re-pass cannot re-wedge on "
             "it even if the salvage-side auto-quarantine never fired"),
            ("480_remat", "same-size batch-480 compile as the row that "
             "wedged 2026-08-02, and the remat ablation is only "
             "interpretable against the plain-480 baseline (also "
             "quarantined) — not worth putting the ViT rows at risk"),
        ):
            if key not in quarantine:
                quarantine[key] = {"date": today, "note": why}
                changed = True
    if changed:
        try:
            tmp = QUARANTINE + ".tmp"
            with open(tmp, "w") as f:
                json.dump(quarantine, f, indent=1)
                f.write("\n")  # match bench.py _quarantine_add format
            os.replace(tmp, QUARANTINE)
        except Exception:
            pass  # seeding is protection; never block the check

    # --strict (the after-re-pass call): a wanted row only counts as
    # covered if it was MEASURED.  Quarantine exclusion is correct for
    # the before-call ("don't re-pass for a row bench.py will skip")
    # but wrong as a success criterion — a re-pass that wedged and
    # auto-quarantined a VERDICT row must not read as DONE.
    # --print-rows: the bench.py --rows argument for a selective
    # re-pass — the missing dispatchable rows, comma-separated (empty
    # output = nothing to re-measure).
    strict = "--strict" in sys.argv[1:]
    print_rows = "--print-rows" in sys.argv[1:]
    if not quarantine_ok and print_rows:
        # Same refusal as the before-call: without quarantine protection
        # a green-lit dispatch could hit known tunnel-wedgers.
        print("")
        print("quarantine.json unparseable — refusing to emit a --rows "
              "list; fix or delete the file first", file=sys.stderr)
        return
    if not quarantine_ok and not strict:
        # Before-call with no quarantine protection: do NOT dispatch.
        print("no")
        print("quarantine.json unparseable — refusing to green-light "
              "a re-pass that could dispatch known tunnel-wedgers; "
              "fix or delete the file first", file=sys.stderr)
        return
    # Truthy-entry test, NOT key presence: a null deliberate-clear
    # tombstone means "dispatchable" to bench.py's _quarantined, so it
    # must mean the same to the --rows list this emits.
    missing = [
        k for k in WANT
        if not _measured(rows.get(k))
        and (strict or not quarantine.get(k))
    ]
    if print_rows:
        print(",".join(missing))
        return
    print("yes" if missing else "no")
    if missing:
        print(f"missing rows: {missing}", file=sys.stderr)


if __name__ == "__main__":
    main()
