"""Generate the accuracy baseline artifact (ACCURACY.md + curves JSON).

The reference publishes no accuracy numbers (SURVEY.md §6) and its
datasets (CUB-200-2011 / Stanford Online Products) are not fetchable in
this environment, so the baseline the framework is judged against is
generated: for each BASELINE.json mining configuration, train an
embedding model on synthetic separable identity clusters at a realistic
batch shape and record the loss / Recall@k curves until Recall@1
converges to ~1.0.  The reference's own convergence criterion is its
retrieve_top1 top (npair_multi_class_loss.cu:390-398); a correct
implementation of the loss + mining + gradient must drive that metric to
1.0 on separable data — a broken gradient, mis-mined pairs, or wrong
metric semantics all show up as a flat curve.

Engines covered: dense XLA graph, ring-ppermute over the 8-device mesh,
and the Pallas blockwise kernels (single chip) — the same config trains
through all three, pinning training-level engine parity, not just
per-step numerics.

Usage: python scripts/accuracy_baseline.py [--steps N] [--out DIR]
Writes <repo>/accuracy/curves.json and <repo>/ACCURACY.md.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def run_config(name, loss_cfg, model_name, model_kw, input_shape, num_ids,
               ids_per_batch, steps, lr, use_ring=False, use_blockwise=False,
               record_every=10, seed=0, noise=0.6, param_mults=None,
               weight_decay=0.0):
    import jax
    import numpy as np

    from npairloss_tpu.data import synthetic_identity_batches
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    mesh = None
    if use_ring:
        from npairloss_tpu.parallel import data_parallel_mesh

        mesh = data_parallel_mesh(jax.devices()[:8])

    solver = Solver(
        get_model(model_name, **model_kw),
        loss_cfg,
        SolverConfig(
            base_lr=lr, lr_policy="fixed", momentum=0.9,
            weight_decay=weight_decay,
            display=0, test_interval=0, snapshot=0, random_seed=seed,
        ),
        mesh=mesh,
        input_shape=input_shape,
        use_ring=use_ring,
        param_mults=param_mults,
    )
    if use_blockwise:
        # Swap the dense loss for the Pallas blockwise engine inside the
        # solver's step (single-chip self-pool).
        from npairloss_tpu.ops.pallas_npair import (
            blockwise_npair_loss_with_aux,
            blockwise_retrieval_metrics,
        )

        def loss_and_metrics(emb, labels):
            loss, _ = blockwise_npair_loss_with_aux(
                emb, labels, loss_cfg, block_size=64
            )
            metrics = blockwise_retrieval_metrics(
                jax.lax.stop_gradient(emb), labels, solver.top_ks,
                block_size=64,
            )
            return loss, metrics

        solver._loss_and_metrics = loss_and_metrics

    batches = synthetic_identity_batches(
        num_ids, ids_per_batch, 2, input_shape, noise=noise, seed=seed
    )
    curve = []
    t0 = time.time()
    for it in range(steps):
        x, lab = next(batches)
        m = solver.step(x, lab)
        if it % record_every == 0 or it == steps - 1:
            curve.append({
                "step": it,
                "loss": round(float(m["loss"]), 6),
                "retrieve_top1": round(float(m["retrieve_top1"]), 4),
                "retrieve_top5": round(float(m.get("retrieve_top5", 0.0)), 4),
            })
    final = curve[-1]
    print(
        f"  {name}: loss {curve[0]['loss']:.3f} -> {final['loss']:.3f}, "
        f"R@1 {curve[0]['retrieve_top1']:.3f} -> "
        f"{final['retrieve_top1']:.3f} ({time.time() - t0:.1f}s)",
        flush=True,
    )
    return {
        "name": name,
        "engine": "ring" if use_ring else (
            "blockwise" if use_blockwise else "dense"),
        "steps": steps,
        "final_loss": final["loss"],
        "final_recall_at_1": final["retrieve_top1"],
        "curve": curve,
    }


def run_band_config(name, loss_cfg, expected_band, seeds=(0, 1, 2),
                    tail_points=8, **kw):
    """A config whose expected Recall@1 is a BAND below 1.0, not ~1.0.

    The separable-cluster rows catch broken gradients/mining/metrics but
    a mining regression that merely *slows* convergence on hard data
    would still reach R@1=1.0 there.  This row trains on OVERLAPPING
    clusters where final accuracy is mining-limited: the flagship mining
    config lands inside ``expected_band`` while unmined (RAND=ALL)
    training falls below its lower edge at the same geometry/steps —
    calibrated on CPU, seeds 0-2 (flagship tail-avgs 0.65-0.77, mean
    0.728; unmined 0.55-0.62, mean 0.590; noise 1.4, 600 steps).

    Per-batch R@1 over 32 queries is quantized (1/32 steps), so the
    score is the mean of the last ``tail_points`` recorded points,
    averaged over ``seeds``.
    """
    import numpy as np

    per_seed = []
    curves = {}
    for seed in seeds:
        r = run_config(f"{name}_seed{seed}", loss_cfg, seed=seed, **kw)
        tail = float(np.mean(
            [p["retrieve_top1"] for p in r["curve"][-tail_points:]]))
        per_seed.append(round(tail, 4))
        curves[f"seed{seed}"] = r["curve"]
    score = round(sum(per_seed) / len(per_seed), 4)
    lo, hi = expected_band
    print(f"  {name}: tail-avg R@1 per seed {per_seed} -> mean {score} "
          f"(expected band [{lo}, {hi}])", flush=True)
    return {
        "name": name,
        "engine": "dense",
        "steps": kw.get("steps"),
        "final_loss": None,
        "final_recall_at_1": score,
        "expected_band": [lo, hi],
        "per_seed_tail_recall": per_seed,
        # Every seed's raw trajectory — a band miss on seed 1 or 2 must
        # be diagnosable from the artifact, not just seed 0's curve.
        "curve": curves[f"seed{seeds[0]}"],
        "curves_per_seed": curves,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--out", default=os.path.join(REPO, "accuracy"))
    ap.add_argument(
        "--only", nargs="*", default=None,
        help="run only configs whose name contains any of these substrings",
    )
    ap.add_argument(
        "--tpu", action="store_true",
        help="run on the default (TPU) backend; without this flag the CPU "
        "platform is forced BEFORE any backend query — even probing the "
        "default backend hangs when the TPU tunnel is wedged",
    )
    args = ap.parse_args()

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from npairloss_tpu import NPairLossConfig, REFERENCE_CONFIG
    from npairloss_tpu.ops.npair_loss import MiningMethod, MiningRegion

    s = args.steps
    mlp = dict(model_name="mlp", model_kw=dict(hidden=(64,), embedding_dim=32),
               input_shape=(32,), num_ids=32, ids_per_batch=16, lr=0.5)
    wide = dict(model_name="mlp", model_kw=dict(hidden=(64,), embedding_dim=32),
                input_shape=(32,), num_ids=64, ids_per_batch=32, lr=0.5)
    runs = [
        # usage/def.prototxt flagship mining config (BASELINE.json cfg 1).
        ("flagship_def_prototxt",
         lambda: run_config("flagship_def_prototxt", REFERENCE_CONFIG,
                            steps=s, **mlp)),
        # Flagship config WITH the reference template's per-param
        # recipe (bias lr x2, no bias decay — def.prototxt:90-97, now
        # honored by caffe_sgd param_mults) AND the reference solver's
        # weight_decay 2e-5 (solver.prototxt:11), so BOTH halves of the
        # recipe (lr_mult and decay_mult) are live in this trajectory.
        ("flagship_caffe_param_mults",
         lambda: run_config(
             "flagship_caffe_param_mults", REFERENCE_CONFIG, steps=s,
             param_mults=((1.0, 1.0), (2.0, 0.0)), weight_decay=2e-5,
             **mlp)),
        # Paper-baseline LOCAL/RAND (BASELINE.json cfg 2: CUB).
        ("local_rand_cub",
         lambda: run_config("local_rand_cub", NPairLossConfig(),
                            steps=s, **mlp)),
        # LOCAL/HARD both sides (BASELINE.json cfg 3: SOP).
        ("local_hard_sop",
         lambda: run_config(
             "local_hard_sop",
             NPairLossConfig(
                 margin_ident=0.1, margin_diff=-0.05,
                 ap_mining_method=MiningMethod.HARD,
                 an_mining_method=MiningMethod.HARD,
             ),
             steps=s, **mlp)),
        # GLOBAL/RELATIVE_HARD with cross-chip gathered negatives
        # (BASELINE.json cfg 4) — dense engine on the 8-device mesh.
        ("global_relhard_mesh_dense",
         lambda: run_config("global_relhard_mesh_dense", REFERENCE_CONFIG,
                            steps=s, **wide)),
        # Same config, ring-ppermute engine (streamed radix RELATIVE).
        ("global_relhard_mesh_ring",
         lambda: run_config("global_relhard_mesh_ring", REFERENCE_CONFIG,
                            steps=s, use_ring=True, **wide)),
        # Same config, Pallas blockwise engine (the 32k-stretch path,
        # BASELINE.json cfg 5's engine) at test scale.
        ("global_relhard_blockwise",
         lambda: run_config("global_relhard_blockwise", REFERENCE_CONFIG,
                            steps=s, use_blockwise=True, **mlp)),
        # FLAGSHIP TRUNK end-to-end: Inception-BN GoogLeNet (the
        # from-scratch-trainable variant — the BN-free v1 trunk collapses
        # at random init, see models/googlenet.py) with the shipped
        # def.prototxt mining config.  f32 on CPU (bf16 conv emulation is
        # pathologically slow there), bf16 under --tpu; ~18 min CPU /
        # ~1 min TPU for the 200-step curve.
        ("flagship_googlenet_bn",
         lambda: run_config(
             "flagship_googlenet_bn", REFERENCE_CONFIG,
             steps=max(200, s // 2),
             model_name="googlenet_bn",
             model_kw=dict(
                 dtype=jnp.bfloat16 if args.tpu else jnp.float32),
             input_shape=(96, 96, 3),
             num_ids=16, ids_per_batch=16, lr=0.05, record_every=10,
             noise=0.6)),
        # The SAME BN trunk + flagship mining through the ring and
        # blockwise engines at the same steps/bar (VERDICT r3 weak #6):
        # engine choice must not change what the real conv trunk learns.
        ("flagship_googlenet_bn_ring",
         lambda: run_config(
             "flagship_googlenet_bn_ring", REFERENCE_CONFIG,
             steps=max(200, s // 2),
             model_name="googlenet_bn",
             model_kw=dict(
                 dtype=jnp.bfloat16 if args.tpu else jnp.float32),
             input_shape=(96, 96, 3),
             num_ids=16, ids_per_batch=16, lr=0.05, record_every=10,
             noise=0.6, use_ring=True)),
        ("flagship_googlenet_bn_blockwise",
         lambda: run_config(
             "flagship_googlenet_bn_blockwise", REFERENCE_CONFIG,
             steps=max(200, s // 2),
             model_name="googlenet_bn",
             model_kw=dict(
                 dtype=jnp.bfloat16 if args.tpu else jnp.float32),
             input_shape=(96, 96, 3),
             num_ids=16, ids_per_batch=16, lr=0.05, record_every=10,
             noise=0.6, use_blockwise=True)),
        # The full MXU-rewrite stack (BN trunk + space-to-depth stem +
        # fused inception 1x1s) training end-to-end: the rewrites are
        # algebraically exact by test, and this row shows the variant
        # LEARNS at the same bar — the trainability evidence for the
        # performance trunk.
        ("flagship_googlenet_bn_mxu",
         lambda: run_config(
             "flagship_googlenet_bn_mxu", REFERENCE_CONFIG,
             steps=max(200, s // 2),
             model_name="googlenet_bn_s2d",
             model_kw=dict(
                 fuse_1x1=True,
                 dtype=jnp.bfloat16 if args.tpu else jnp.float32),
             input_shape=(96, 96, 3),
             num_ids=16, ids_per_batch=16, lr=0.05, record_every=10,
             noise=0.6)),
        # ViT trunk (reduced proxy of BASELINE.json cfg 5's ViT-B/16
        # stretch) with the flagship mining config — every model family
        # in the zoo demonstrates a learning curve.
        ("vit_small_flagship",
         lambda: run_config(
             "vit_small_flagship", REFERENCE_CONFIG,
             steps=max(200, s // 2),
             model_name="vit_b16",
             model_kw=dict(patch=8, hidden=64, depth=2, num_heads=4,
                           mlp_dim=128,
                           dtype=jnp.bfloat16 if args.tpu else jnp.float32),
             input_shape=(32, 32, 3),
             num_ids=16, ids_per_batch=16, lr=0.05, record_every=10,
             noise=0.6)),
        # OVERLAPPING clusters: final R@1 is mining-limited (expected
        # band, NOT 1.0) — the convergence-RATE regression detector the
        # separable rows cannot provide (VERDICT r4 weak #7).  Unmined
        # training at this geometry falls below the band's lower edge.
        # Steps pinned at the calibrated 600 (NOT scaled by --steps):
        # the two-sided band is calibrated at this exact budget, and
        # more steps would drift the tail recall past the upper edge.
        ("overlap_mined_band",
         lambda: run_band_config(
             "overlap_mined_band", REFERENCE_CONFIG,
             expected_band=(0.63, 0.92),
             steps=600, noise=1.4, record_every=10, **mlp)),
        # Conv trunk: ResNet-18 (the reduced proxy of BASELINE.json
        # cfg 3's ResNet-50/SOP run) with LOCAL/HARD mining.
        ("resnet18_small",
         lambda: run_config(
             "resnet18_small",
             NPairLossConfig(
                 margin_ident=0.1, margin_diff=-0.05,
                 ap_mining_method=MiningMethod.HARD,
                 an_mining_method=MiningMethod.HARD,
             ),
             steps=max(60, s // 5),
             model_name="resnet18",
             model_kw=dict(dtype=jnp.float32),
             input_shape=(32, 32, 3),
             num_ids=8, ids_per_batch=8, lr=0.1, record_every=5,
             noise=0.5)),
    ]
    if args.only:
        runs = [(n, t) for n, t in runs
                if any(sub in n for sub in args.only)]

    print("accuracy baseline runs:", flush=True)
    results = [thunk() for _, thunk in runs]

    # Merge with prior partial runs so --only invocations compose.
    os.makedirs(args.out, exist_ok=True)
    curves_path = os.path.join(args.out, "curves.json")
    merged = {}
    if os.path.exists(curves_path):
        with open(curves_path) as f:
            for r in json.load(f).get("results", []):
                merged[r["name"]] = r
    for r in results:
        merged[r["name"]] = r
    results = list(merged.values())

    payload = {
        "generated_by": "scripts/accuracy_baseline.py",
        "backend": jax.default_backend(),
        "steps": s,
        "results": results,
    }
    with open(curves_path, "w") as f:
        json.dump(payload, f, indent=1)

    lines = [
        "# Accuracy baseline (generated)",
        "",
        "The reference publishes no accuracy numbers and its datasets are",
        "not fetchable here (SURVEY.md §6), so the baseline is generated:",
        "each BASELINE.json mining config trains on synthetic separable",
        "identity clusters until Recall@1 converges.  A broken gradient,",
        "mis-mined pairs or wrong metric semantics would flatten these",
        "curves.  Reproduce with `python scripts/accuracy_baseline.py`;",
        "raw curves in `accuracy/curves.json`.",
        "",
        "| config | engine | steps | final loss | final Recall@1 |",
        "|---|---|---|---|---|",
    ]
    for r in results:
        loss_cell = ("—" if r.get("final_loss") is None
                     else f"{r['final_loss']:.4f}")
        recall_cell = f"{r['final_recall_at_1']:.3f}"
        if r.get("expected_band"):
            lo, hi = r["expected_band"]
            recall_cell += f" (band [{lo}, {hi}])"
        lines.append(
            f"| {r['name']} | {r['engine']} | {r['steps']} | "
            f"{loss_cell} | {recall_cell} |"
        )
    lines += [
        "",
        f"Backend: `{jax.default_backend()}`.  All configs must reach "
        "Recall@1 >= 0.95 (conv trunks at the same bar), EXCEPT rows "
        "with an expected band: those train on overlapping clusters "
        "where final R@1 is mining-limited, and the seed-averaged "
        "tail recall must land INSIDE the band — below means a "
        "convergence-rate regression (unmined training falls below "
        "the lower edge by construction), above means the data "
        "stopped being hard.  `tests/test_accuracy_baseline.py` "
        "replays short runs (incl. the band row and its unmined "
        "counterexample) in CI.",
        "",
        "The flagship def.prototxt config trains END-TO-END on the real",
        "GoogLeNet trunk via the Inception-BN variant",
        "(`get_model('googlenet_bn')`): a randomly-initialized BN-free",
        "Inception-v1 collapses at init (all pairwise sims ≈ 0.9999; the",
        "original relied on aux classifiers and ImageNet-scale",
        "schedules), so BatchNorm-after-every-conv is the honest",
        "from-scratch recipe.  The prototxt-parity BN-free trunk",
        "(`googlenet`) remains the bench/compile-check model.",
        "",
    ]
    with open(os.path.join(REPO, "ACCURACY.md"), "w") as f:
        f.write("\n".join(lines))

    # One bar for every row, conv trunks included (the round-3 0.85
    # conv concession is obsolete: every trunk converges to ~1.0).
    # Band rows gate BOTH directions: below = convergence regression,
    # above = the data stopped being hard (a test-bug signal).
    def _ok(r):
        if r.get("expected_band"):
            lo, hi = r["expected_band"]
            return lo <= r["final_recall_at_1"] <= hi
        return r["final_recall_at_1"] >= 0.95

    bad = [r for r in results if not _ok(r)]
    if bad:
        print(f"FAILED configs: {[r['name'] for r in bad]}", file=sys.stderr)
        return 1
    print(f"wrote {args.out}/curves.json and ACCURACY.md")
    return 0


if __name__ == "__main__":
    sys.exit(main())
