#!/bin/bash
# Round-4 hardware queue, second pass — ORDERED BY HAZARD.
#
# The first pass (tpu_revalidate.sh) dispatched the 32k cached-stretch
# program early; its 4.0 GiB-cache dispatch wedged the tunneled v5e
# backend server-side (every later client got UNAVAILABLE), which
# zeroed the profile artifact and degraded bench.py to its CPU-smoke
# fallback.  This queue runs every SAFE workload first so one wedge
# cannot void the round's evidence, and probes the cache boundary from
# small pools upward, LAST.
#   1. profile_flagship        -> profile/flagship.{json,md}
#   2. bench.py full           -> /tmp/bench_out.json (+ last_good cache)
#   3. tpu_pallas_check        -> parity + 32k uncached stretch + cached
#                                 rows at 16384 (1 GiB cache, in-budget)
#   4. e2e real-JPEG on chip   -> accuracy/e2e_real_jpeg_tpu.json
#   5. diag_sim_cache 8k,16k   -> phase timings + HBM peaks (log only)
#   6. (LAST, wedge-risk) diag 24576 — pins the boundary; a wedge here
#      costs nothing already captured.
# Run detached:  setsid nohup scripts/tpu_queue_v2.sh &
# Log: /tmp/tpu_queue_v2.log
cd "$(dirname "$0")/.."
exec > /tmp/tpu_queue_v2.log 2>&1

echo "=== $(date) waiting for tunnel ==="
for i in $(seq 1 600); do
  if timeout 100 python -c 'import jax,sys; sys.exit(jax.devices()[0].platform != "tpu")' >/dev/null 2>&1; then
    echo "tunnel up (platform=tpu) after probe $i ($(date))"
    break
  fi
  echo "probe $i failed ($(date)); sleeping 180s"
  sleep 180
  if [ "$i" = 600 ]; then echo "GAVE UP"; exit 1; fi
done

echo "=== $(date) 1/6 profile_flagship (incl. s2d + remat ablations) ==="
timeout 3600 python scripts/profile_flagship.py --steps 10
echo "profile rc=$?"

echo "=== $(date) 2/6 bench.py full ==="
# Budget > bench.py's worst case (~3270s: probes 270 + full
# 2400 + smoke fallbacks 600) — see tpu_queue_v3.sh.
timeout 4200 python bench.py > /tmp/bench_out.json
echo "bench rc=$?"
tail -c 1000 /tmp/bench_out.json

echo "=== $(date) 3/6 tpu_pallas_check (parity + stretch, cached@16k) ==="
timeout 3300 python scripts/tpu_pallas_check.py --pool 4096 \
  --stretch 32768 --stretch-cached 16384 > /tmp/tpu_check_out.json
rc=$?
echo "tpu_pallas_check rc=$rc"
tail -c 2000 /tmp/tpu_check_out.json
if [ "$rc" = 0 ]; then python scripts/split_pallas_check.py; fi

echo "=== $(date) 4/6 TPU accuracy smoke (e2e real-JPEG on the chip) ==="
timeout 2400 env E2E_JAX_PLATFORM=default python scripts/e2e_real_jpeg.py \
  --steps 200 --workdir /tmp/e2e_jpeg_tpu2 \
  --artifact accuracy/e2e_real_jpeg_tpu.json
echo "e2e tpu rc=$?"

echo "=== $(date) 5/6 diag_sim_cache 8192,16384 (safe pools) ==="
timeout 1800 python scripts/diag_sim_cache.py --pools 8192,16384
echo "diag safe rc=$?"

echo "=== $(date) 6/6 diag_sim_cache 24576 (WEDGE-RISK, runs last) ==="
timeout 1200 python scripts/diag_sim_cache.py --pools 24576
echo "diag 24576 rc=$?"

echo "=== $(date) QUEUE V2 DONE ==="
