"""Parity tests: Pallas blockwise kernels vs the dense path.

The blockwise path (ops.pallas_npair) must reproduce the dense
``npair_loss_with_aux`` loss, gradient, counts and metrics exactly (up to
fp32 reduction-order noise) for every absolute mining configuration,
including pool sizes that do not divide the block size (padding path).
Kernels run in Pallas interpreter mode on the CPU test backend.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import make_identity_batch
from npairloss_tpu.ops.npair_loss import (
    MiningMethod,
    MiningRegion,
    NPairLossConfig,
    REFERENCE_CONFIG,
    npair_loss_with_aux,
)
from npairloss_tpu.ops.metrics import retrieval_metrics
from npairloss_tpu.ops.pallas_npair import (
    blockwise_npair_loss_with_aux,
    blockwise_retrieval_metrics,
    blockwise_supported,
)

ABS_CONFIGS = [
    NPairLossConfig(),  # proto defaults: LOCAL/RAND both sides
    NPairLossConfig(
        ap_mining_method=MiningMethod.HARD,
        an_mining_method=MiningMethod.HARD,
        margin_ident=0.1,
        margin_diff=-0.05,
    ),
    NPairLossConfig(
        ap_mining_method=MiningMethod.EASY,
        an_mining_method=MiningMethod.EASY,
        margin_ident=-0.02,
    ),
    NPairLossConfig(
        ap_mining_region=MiningRegion.GLOBAL,
        ap_mining_method=MiningMethod.HARD,
        an_mining_region=MiningRegion.GLOBAL,
        an_mining_method=MiningMethod.EASY,
        margin_diff=0.03,
    ),
    NPairLossConfig(
        ap_mining_method=MiningMethod.EASY,
        an_mining_method=MiningMethod.HARD,
        grad_mode="true",
    ),
]


@pytest.mark.parametrize("cfg", ABS_CONFIGS)
@pytest.mark.parametrize("block", [4, 5, 64])
def test_blockwise_matches_dense(rng, cfg, block):
    (f,), (l,) = make_identity_batch(rng, num_ids=6, imgs_per_id=2, dim=16)
    loss_d, aux_d = npair_loss_with_aux(jnp.asarray(f), jnp.asarray(l), cfg)
    loss_b, aux_b = blockwise_npair_loss_with_aux(
        jnp.asarray(f), jnp.asarray(l), cfg, block_size=block
    )
    np.testing.assert_allclose(loss_b, loss_d, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(aux_b["ident_num"], aux_d["ident_num"])
    np.testing.assert_allclose(aux_b["diff_num"], aux_d["diff_num"])
    np.testing.assert_allclose(
        aux_b["pos_threshold"], aux_d["pos_threshold"], rtol=1e-6
    )
    np.testing.assert_allclose(
        aux_b["neg_threshold"], aux_d["neg_threshold"], rtol=1e-6
    )


@pytest.mark.parametrize("cfg", ABS_CONFIGS)
def test_blockwise_grad_matches_dense(rng, cfg):
    (f,), (l,) = make_identity_batch(rng, num_ids=6, imgs_per_id=2, dim=16)
    f, l = jnp.asarray(f), jnp.asarray(l)

    gd = jax.grad(lambda x: npair_loss_with_aux(x, l, cfg)[0])(f)
    gb = jax.grad(
        lambda x: blockwise_npair_loss_with_aux(x, l, cfg, block_size=5)[0]
    )(f)
    np.testing.assert_allclose(gb, gd, rtol=1e-5, atol=1e-7)


REL_CONFIGS = [
    # The shipped def.prototxt mining config — the flagship workload
    # (GLOBAL/RELATIVE_HARD AP): previously dense-only on one chip, now
    # streamed via radix selection so the 32k stretch runs blockwise.
    REFERENCE_CONFIG,
    # LOCAL relative on both sides, fraction-valued sn.
    NPairLossConfig(
        ap_mining_method=MiningMethod.RELATIVE_EASY, identsn=-0.5,
        an_mining_method=MiningMethod.RELATIVE_HARD, diffsn=-0.3,
    ),
    # Positive sn = absolute rank from the sorted top (cu:285-287).
    NPairLossConfig(
        ap_mining_method=MiningMethod.RELATIVE_HARD, identsn=1.0,
        an_mining_method=MiningMethod.RELATIVE_EASY, diffsn=2.0,
        margin_diff=0.02,
    ),
    # GLOBAL relative on the AN side (block-wide rank, cu:327-334).
    NPairLossConfig(
        an_mining_region=MiningRegion.GLOBAL,
        an_mining_method=MiningMethod.RELATIVE_HARD, diffsn=-0.25,
    ),
]


# Every config runs at block 5 (a non-divisor of N=18 — exercises the
# padding path); the exact-tiling shape (block 6 divides N=18 — no
# padded rows anywhere) is pinned once rather than per-config:
# interpret-mode Pallas executes each grid cell in Python, so the full
# cfg x block product costs minutes for no added coverage (the block
# size only affects tiling, not mining semantics).
@pytest.mark.parametrize(
    "cfg_idx,block",
    [(i, 5) for i in range(len(REL_CONFIGS))] + [(0, 6)],
)
def test_blockwise_relative_matches_dense(rng, cfg_idx, block):  # slow-ok: the blockwise-vs-dense mining-grid parity oracle — tier-1's core contract
    """RELATIVE_* thresholds via streamed radix selection must equal the
    dense path's host-sort semantics exactly — loss, aux and grads."""
    cfg = REL_CONFIGS[cfg_idx]
    assert blockwise_supported(cfg)
    (f,), (l,) = make_identity_batch(rng, num_ids=6, imgs_per_id=3, dim=16)
    f, l = jnp.asarray(f), jnp.asarray(l)
    loss_d, aux_d = npair_loss_with_aux(f, l, cfg)
    loss_b, aux_b = blockwise_npair_loss_with_aux(f, l, cfg, block_size=block)
    np.testing.assert_allclose(loss_b, loss_d, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(aux_b["ident_num"], aux_d["ident_num"])
    np.testing.assert_allclose(aux_b["diff_num"], aux_d["diff_num"])
    # Radix selection is bit-exact on the streamed population, but the
    # streamed sim tiles themselves can differ from the one big dense
    # matmul by 1 ULP (different XLA kernel shapes accumulate in a
    # different order) — hence rtol, not equality.
    np.testing.assert_allclose(
        aux_b["pos_threshold"], aux_d["pos_threshold"], rtol=1e-6
    )
    np.testing.assert_allclose(
        aux_b["neg_threshold"], aux_d["neg_threshold"], rtol=1e-6
    )
    gd = jax.grad(lambda x: npair_loss_with_aux(x, l, cfg)[0])(f)
    gb = jax.grad(
        lambda x: blockwise_npair_loss_with_aux(x, l, cfg, block_size=block)[0]
    )(f)
    np.testing.assert_allclose(gb, gd, rtol=1e-5, atol=1e-7)


def test_blockwise_sim_cache_bit_identical(rng):  # slow-ok: sim-cache bit-identity is the streaming engine's correctness bar
    """The similarity cache (ops.pallas_npair sim_cache) stores exactly
    the fp32 values the recompute path produces, so cached and uncached
    runs must agree BIT-FOR-BIT — loss, aux monitors and gradients — on
    the flagship relative config (which exercises stats, radix-digit,
    loss and both backward sweeps).  Auto mode enables the cache at test
    shapes, so this test is also what keeps the recompute path covered."""
    (f,), (l,) = make_identity_batch(rng, num_ids=6, imgs_per_id=3, dim=16)
    f, l = jnp.asarray(f), jnp.asarray(l)

    outs = {}
    for cache in (True, False):
        def fn(x, cache=cache):
            return blockwise_npair_loss_with_aux(
                x, l, REFERENCE_CONFIG, block_size=5, sim_cache=cache
            )
        (loss, aux), grad = jax.value_and_grad(fn, has_aux=True)(f)
        outs[cache] = (np.asarray(loss), aux, np.asarray(grad))

    loss_on, aux_on, grad_on = outs[True]
    loss_off, aux_off, grad_off = outs[False]
    assert loss_on == loss_off
    assert np.array_equal(grad_on, grad_off)
    for k in aux_on:
        assert np.array_equal(
            np.asarray(aux_on[k]), np.asarray(aux_off[k])
        ), k


@pytest.mark.parametrize("bn,bm", [(4, 7), (7, 4)])
def test_blockwise_sim_cache_asymmetric_tiles(rng, bn, bm):  # slow-ok: ragged-tile cache parity guards the production block shapes
    """Cached sweeps with q_block != block exercise the _simblock index
    maps on a non-square tile grid (incl. padding on both axes); must
    still match the dense path on the flagship config."""
    (f,), (l,) = make_identity_batch(rng, num_ids=6, imgs_per_id=3, dim=16)
    f, l = jnp.asarray(f), jnp.asarray(l)

    def fn(x):
        return blockwise_npair_loss_with_aux(
            x, l, REFERENCE_CONFIG, block_size=bm, q_block_size=bn,
            sim_cache=True,
        )[0]

    loss_d, _ = npair_loss_with_aux(f, l, REFERENCE_CONFIG)
    gd = jax.grad(lambda x: npair_loss_with_aux(x, l, REFERENCE_CONFIG)[0])(f)
    np.testing.assert_allclose(fn(f), loss_d, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(jax.grad(fn)(f), gd, rtol=1e-5, atol=1e-7)


def test_blockwise_global_relative_int32_overflow_guard():
    """GLOBAL RELATIVE rank targets sum pair counts over the whole block:
    beyond 2^31 pairs int32 wraps and would silently mis-rank (caught in
    review) — without x64 the trace must fail loudly instead."""
    cfg = NPairLossConfig(
        an_mining_region=MiningRegion.GLOBAL,
        an_mining_method=MiningMethod.RELATIVE_HARD,
        diffsn=-0.3,
    )
    n = 50_000  # n*n > 2^31 - 1
    f = jax.ShapeDtypeStruct((n, 8), jnp.float32)
    l = jax.ShapeDtypeStruct((n,), jnp.int32)
    with pytest.raises(NotImplementedError, match="2\\^31|64-bit"):
        jax.eval_shape(
            lambda f_, l_: blockwise_npair_loss_with_aux(
                f_, l_, cfg, block_size=512
            )[0],
            f, l,
        )
    # Under the bound the same config traces fine.
    small = jax.ShapeDtypeStruct((64, 8), jnp.float32)
    small_l = jax.ShapeDtypeStruct((64,), jnp.int32)
    jax.eval_shape(
        lambda f_, l_: blockwise_npair_loss_with_aux(
            f_, l_, cfg, block_size=32
        )[0],
        small, small_l,
    )


def test_blockwise_relative_clamp_quirk(rng):  # slow-ok: pins the reference's -FLT_MAX clamp quirk bit-exactly
    """A negative-valued relative threshold clamps to -FLT_MAX (cu:288
    etc.); all-negative features force the quirk on the blockwise path."""
    cfg = NPairLossConfig(
        ap_mining_method=MiningMethod.RELATIVE_HARD, identsn=-0.9,
        an_mining_method=MiningMethod.RELATIVE_HARD, diffsn=-0.9,
    )
    (f,), (l,) = make_identity_batch(rng, num_ids=5, imgs_per_id=2, dim=8)
    f = -np.abs(f)
    f, l = jnp.asarray(f), jnp.asarray(l)
    loss_d, aux_d = npair_loss_with_aux(f, l, cfg)
    loss_b, aux_b = blockwise_npair_loss_with_aux(f, l, cfg, block_size=4)
    np.testing.assert_allclose(loss_b, loss_d, rtol=1e-6)
    # The clamp replaces the looked-up value with -FLT_MAX exactly.
    np.testing.assert_allclose(
        aux_b["pos_threshold"], aux_d["pos_threshold"], rtol=1e-6
    )


@pytest.mark.slow  # ~115s over 4 params; tier-1 budget, run with -m slow
@pytest.mark.parametrize("region", [MiningRegion.LOCAL, MiningRegion.GLOBAL])
@pytest.mark.parametrize("imgs_per_id", [9, 11])
def test_blockwise_pos_topk_fallback_boundary(rng, region, imgs_per_id):
    """The sparse-positive fast path guards on cnt_s <= K: a group of 9
    (cnt_s = 8) fits the 8-slot buffer exactly, a group of 11 overflows
    and the lax.cond must fall back to radix selection — parity with the
    dense path must hold on BOTH sides of the boundary."""
    cfg = NPairLossConfig(
        ap_mining_region=region,
        ap_mining_method=MiningMethod.RELATIVE_HARD, identsn=-0.3,
        an_mining_method=MiningMethod.HARD, margin_diff=-0.05,
    )
    (f,), (l,) = make_identity_batch(
        rng, num_ids=3, imgs_per_id=imgs_per_id, dim=16)
    f, l = jnp.asarray(f), jnp.asarray(l)
    loss_d, aux_d = npair_loss_with_aux(f, l, cfg)
    loss_b, aux_b = blockwise_npair_loss_with_aux(
        f, l, cfg, block_size=5, pos_topk=8)
    np.testing.assert_allclose(loss_b, loss_d, rtol=1e-5, atol=1e-6)
    # rtol covers the tile-vs-dense matmul's few-ULP reduction noise
    # (see test_blockwise_relative_matches_dense); the selection itself
    # is exact on the streamed sims.
    np.testing.assert_allclose(
        aux_b["pos_threshold"], aux_d["pos_threshold"], rtol=1e-5)
    np.testing.assert_allclose(aux_b["ident_num"], aux_d["ident_num"])
    gd = jax.grad(lambda x: npair_loss_with_aux(x, l, cfg)[0])(f)
    gb = jax.grad(lambda x: blockwise_npair_loss_with_aux(
        x, l, cfg, block_size=5, pos_topk=8)[0])(f)
    np.testing.assert_allclose(gb, gd, rtol=1e-5, atol=1e-7)


def test_blockwise_pos_topk_disabled_matches(rng):
    """pos_topk=0 forces the pure radix path (no K-slot buffer in the
    stats sweep) — it must stay exact, it is the fallback's substrate."""
    cfg = REFERENCE_CONFIG
    (f,), (l,) = make_identity_batch(rng, num_ids=6, imgs_per_id=2, dim=16)
    f, l = jnp.asarray(f), jnp.asarray(l)
    loss_d, aux_d = npair_loss_with_aux(f, l, cfg)
    loss_b, aux_b = blockwise_npair_loss_with_aux(
        f, l, cfg, block_size=5, pos_topk=0)
    np.testing.assert_allclose(loss_b, loss_d, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        aux_b["pos_threshold"], aux_d["pos_threshold"], rtol=1e-6)


@pytest.mark.slow  # ~28s; tier-1 budget, run with -m slow
def test_blockwise_pos_topk_with_sim_cache(rng):
    """Fast path + fp32 sim cache together (the 32k stretch shape):
    cached and uncached must agree bit-for-bit, and both must match the
    dense oracle."""
    cfg = REFERENCE_CONFIG
    (f,), (l,) = make_identity_batch(rng, num_ids=8, imgs_per_id=2, dim=12)
    f, l = jnp.asarray(f), jnp.asarray(l)
    loss_d, _ = npair_loss_with_aux(f, l, cfg)
    loss_c, aux_c = blockwise_npair_loss_with_aux(
        f, l, cfg, block_size=4, sim_cache=True)
    loss_n, aux_n = blockwise_npair_loss_with_aux(
        f, l, cfg, block_size=4, sim_cache=False)
    assert float(loss_c) == float(loss_n)
    np.testing.assert_array_equal(
        aux_c["pos_threshold"], aux_n["pos_threshold"])
    np.testing.assert_allclose(loss_c, loss_d, rtol=1e-5, atol=1e-6)
    gc = jax.grad(lambda x: blockwise_npair_loss_with_aux(
        x, l, cfg, block_size=4, sim_cache=True)[0])(f)
    gn = jax.grad(lambda x: blockwise_npair_loss_with_aux(
        x, l, cfg, block_size=4, sim_cache=False)[0])(f)
    np.testing.assert_array_equal(gc, gn)


def test_blockwise_zero_count_queries(rng):
    """Unique labels -> no positives anywhere -> loss must be exactly 0
    (the reference's zero-count guard, cu:133-154, cu:162-169)."""
    f = rng.standard_normal((8, 16)).astype(np.float32)
    l = np.arange(8, dtype=np.int32)
    loss, aux = blockwise_npair_loss_with_aux(
        jnp.asarray(f), jnp.asarray(l), NPairLossConfig(), block_size=4
    )
    assert float(loss) == 0.0
    np.testing.assert_array_equal(aux["ident_num"], np.zeros(8))
    # "reference" grad mode: p3 keeps diff-type entries alive for
    # identNum==0 queries (cu:133-146) — the gradient is NONZERO and must
    # match the dense path exactly.
    g_block = jax.grad(
        lambda x: blockwise_npair_loss_with_aux(
            x, jnp.asarray(l), NPairLossConfig(), block_size=4
        )[0]
    )(jnp.asarray(f))
    g_dense = jax.grad(
        lambda x: npair_loss_with_aux(x, jnp.asarray(l), NPairLossConfig())[0]
    )(jnp.asarray(f))
    np.testing.assert_allclose(g_block, g_dense, rtol=1e-5, atol=1e-7)
    # "true" grad mode: autodiff of the guarded log gives exactly 0 for
    # zero-loss queries.
    cfg_true = NPairLossConfig(grad_mode="true")
    g_true = jax.grad(
        lambda x: blockwise_npair_loss_with_aux(
            x, jnp.asarray(l), cfg_true, block_size=4
        )[0]
    )(jnp.asarray(f))
    np.testing.assert_array_equal(np.asarray(g_true), np.zeros_like(f))


def test_blockwise_float_labels_match_dense(rng):
    """Float labels are legal (Caffe labels are Dtype); distinct float
    values like 0.2 vs 0.7 must stay distinct identities — an int cast
    would merge them (caught by review)."""
    f = jnp.asarray(rng.standard_normal((6, 8)).astype(np.float32))
    l = jnp.asarray(np.array([0.2, 0.2, 0.7, 0.7, 1.2, 1.2], np.float32))
    cfg = NPairLossConfig()
    loss_d, _ = npair_loss_with_aux(f, l, cfg)
    loss_b, _ = blockwise_npair_loss_with_aux(f, l, cfg, block_size=4)
    np.testing.assert_allclose(loss_b, loss_d, rtol=1e-6)
    gd = jax.grad(lambda x: npair_loss_with_aux(x, l, cfg)[0])(f)
    gb = jax.grad(
        lambda x: blockwise_npair_loss_with_aux(x, l, cfg, block_size=4)[0]
    )(f)
    np.testing.assert_allclose(gb, gd, rtol=1e-5, atol=1e-7)
    m = blockwise_retrieval_metrics(f, l, (1,), block_size=4)
    _, aux = npair_loss_with_aux(f, l, cfg)
    dense_m = retrieval_metrics(aux, l, f, (1,))
    np.testing.assert_allclose(m["retrieve_top1"], dense_m["retrieve_top1"])


def test_blockwise_batch_of_one_grad_finite(rng):
    """Batch of 1: only the (excluded) self pair exists, so max_all is
    -FLT_MAX and sim_exp overflows to +inf — the backward weight tile
    must mask where-based or inf * 0 poisons the gemms with NaN (the
    dense path's cu:152-154 hazard; caught live on this kernel)."""
    f = jnp.asarray(rng.standard_normal((1, 8)).astype(np.float32))
    l = jnp.asarray(np.array([3], np.int32))
    for cfg in (NPairLossConfig(), NPairLossConfig(grad_mode="true")):
        loss, _ = blockwise_npair_loss_with_aux(f, l, cfg, block_size=4)
        assert float(loss) == 0.0
        g = jax.grad(
            lambda x: blockwise_npair_loss_with_aux(x, l, cfg, block_size=4)[0]
        )(f)
        np.testing.assert_array_equal(np.asarray(g), np.zeros_like(g))


@pytest.mark.parametrize("block", [4, 7, 64])
def test_blockwise_metrics_match_dense(rng, block):
    (f,), (l,) = make_identity_batch(rng, num_ids=8, imgs_per_id=3, dim=16)
    f, l = jnp.asarray(f), jnp.asarray(l)
    _, aux = npair_loss_with_aux(f, l, NPairLossConfig())
    dense = retrieval_metrics(aux, l, f, (1, 5, 10))
    streamed = blockwise_retrieval_metrics(f, l, (1, 5, 10), block_size=block)
    for k, v in dense.items():
        np.testing.assert_allclose(streamed[k], v, rtol=1e-6, err_msg=k)


def test_blockwise_under_jit(rng):
    (f,), (l,) = make_identity_batch(rng, num_ids=6, imgs_per_id=2, dim=16)
    f, l = jnp.asarray(f), jnp.asarray(l)
    cfg = NPairLossConfig(
        ap_mining_method=MiningMethod.HARD, an_mining_method=MiningMethod.HARD
    )

    @jax.jit
    def step(x):
        return jax.value_and_grad(
            lambda y: blockwise_npair_loss_with_aux(y, l, cfg, block_size=4)[0]
        )(x)

    loss, g = step(f)
    loss_d, g_d = jax.value_and_grad(
        lambda y: npair_loss_with_aux(y, l, cfg)[0]
    )(f)
    np.testing.assert_allclose(loss, loss_d, rtol=1e-5)
    np.testing.assert_allclose(g, g_d, rtol=1e-5, atol=1e-7)
