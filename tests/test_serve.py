"""serve/: index commit/restore, engine parity, batcher, drain contract.

The load-bearing pins (docs/SERVING.md):
  * served top-K answers are EXACTLY consistent with the offline
    protocol (``ops.eval_retrieval.gallery_recall_at_k``) on identical
    embeddings — streamed blocks and mesh shards included;
  * the index commit is atomic and a torn index is skipped, never
    served (the resilience.snapshot contract applied to galleries);
  * the micro-batcher honors deadline/bucket/backpressure bounds;
  * a drain (the SIGTERM path) answers every admitted query — zero
    drops — and steady-state serving performs zero XLA compiles after
    warmup (counted via the engine's compile accounting, not eyeballed).
"""

import io
import json
import os
import threading
import time

import numpy as np
import pytest

from npairloss_tpu.resilience.snapshot import SnapshotValidationError
from npairloss_tpu.serve import (
    BatcherConfig,
    EngineConfig,
    GalleryIndex,
    MicroBatcher,
    QueryEngine,
    QueueFullError,
    RetrievalServer,
    ServerConfig,
)
from npairloss_tpu.serve.index import load_newest


def make_gallery(rng, ids=12, per_id=6, dim=16, noise=0.3):
    centers = rng.standard_normal((ids, dim))
    labels = np.repeat(np.arange(ids), per_id).astype(np.int32)
    emb = centers[labels] + noise * rng.standard_normal(
        (ids * per_id, dim)
    )
    return emb.astype(np.float32), labels


# -- index ------------------------------------------------------------------


def test_index_build_persist_restore_roundtrip(rng, tmp_path):
    emb, lab = make_gallery(rng)
    idx = GalleryIndex.build(emb, lab)
    path = str(tmp_path / "g-0001.gidx")
    idx.save(path)
    idx2 = GalleryIndex.load(path)
    np.testing.assert_array_equal(idx2._host_labels, idx._host_labels)
    np.testing.assert_array_equal(idx2.ids, idx.ids)
    # build() normalized once; the round-tripped rows are bit-identical
    np.testing.assert_array_equal(idx2._host_emb, idx._host_emb)
    assert idx2.size == idx.size and idx2.dim == idx.dim


def test_index_torn_commit_is_skipped(rng, tmp_path):
    emb, lab = make_gallery(rng)
    idx = GalleryIndex.build(emb, lab)
    good = str(tmp_path / "g-0001.gidx")
    bad = str(tmp_path / "g-0002.gidx")
    idx.save(good)
    idx.save(bad)
    # Bit-rot the newer index's embedding bytes: load must refuse it...
    with open(os.path.join(bad, "emb.npy"), "r+b") as f:
        f.seek(256)
        f.write(b"\xff\xff\xff\xff")
    with pytest.raises(SnapshotValidationError):
        GalleryIndex.load(bad)
    # ...and the newest-first scan must fall back to the older valid one.
    found = load_newest(str(tmp_path / "g-"))
    assert found is not None and found[0] == good
    # A tmp dir (crash mid-commit) is invisible to the scan entirely.
    os.rename(bad, str(tmp_path / "g-0003.gidx.tmp-123-ab"))
    found = load_newest(str(tmp_path / "g-"))
    assert found is not None and found[0] == good


def test_index_add_appends_and_pads(rng):
    from npairloss_tpu.parallel import data_parallel_mesh

    emb, lab = make_gallery(rng, ids=5, per_id=3)
    mesh = data_parallel_mesh()
    idx = GalleryIndex.build(emb, lab, mesh=mesh)
    assert idx.padded_size % mesh.size == 0
    n0 = idx.size
    add_emb = rng.standard_normal((7, emb.shape[1])).astype(np.float32)
    idx.add(add_emb, np.arange(7).astype(np.int32))
    assert idx.size == n0 + 7
    assert idx.padded_size % mesh.size == 0
    assert idx.ids.shape[0] == idx.size
    # validity mask exactly covers the true rows
    assert int(np.asarray(idx.valid).sum()) == idx.size


# -- engine parity ----------------------------------------------------------


def _served_recall(engine, emb, labels, ks):
    """Recall@K from served answers under the offline protocol: query
    each gallery row, drop the self row, membership-in-top-K."""
    out = engine.query(emb)
    n = emb.shape[0]
    recalls = {}
    for k in ks:
        hits = 0
        for i in range(n):
            rows = [r for r in out["rows"][i] if r != i][:k]
            hits += bool(np.any(labels[np.asarray(rows)] == labels[i]))
        recalls[k] = hits / n
    return recalls


@pytest.mark.parametrize("use_mesh", [False, True])
def test_served_topk_matches_gallery_recall(rng, use_mesh):
    """The acceptance pin: served answers reproduce
    ``gallery_recall_at_k`` EXACTLY on the same embeddings — through
    streamed gallery blocks and (parametrized) the sharded merge."""
    from npairloss_tpu.ops.eval_retrieval import evaluate_embeddings

    emb, lab = make_gallery(rng, ids=10, per_id=5, dim=16, noise=0.8)
    ks = (1, 2, 4, 8)
    mesh = None
    if use_mesh:
        from npairloss_tpu.parallel import data_parallel_mesh

        mesh = data_parallel_mesh()
    idx = GalleryIndex.build(emb, lab, mesh=mesh)
    engine = QueryEngine(
        idx,
        EngineConfig(top_k=max(ks) + 1, buckets=(8, 64),
                     gallery_block=13),
    )
    want = evaluate_embeddings(emb, lab, ks=ks)
    got = _served_recall(engine, emb, lab, ks)
    n = emb.shape[0]
    for k in ks:
        # Exact consistency = identical HIT COUNTS (the offline number
        # is an fp32 mean of 0/1s; the count is its exact content).
        assert round(got[k] * n) == round(want[f"recall_at_{k}"] * n), k
        assert got[k] == pytest.approx(want[f"recall_at_{k}"], abs=1e-6)


def test_streamed_blocks_and_shards_are_bit_identical(rng):
    """Gallery-block size and mesh sharding are implementation details:
    every combination returns the same rows AND bit-identical scores."""
    from npairloss_tpu.parallel import data_parallel_mesh

    emb, lab = make_gallery(rng, ids=8, per_id=5, dim=8, noise=1.0)
    ref = None
    mesh = data_parallel_mesh()
    for m, block in ((None, 64), (None, 7), (None, 13), (mesh, 7)):
        idx = GalleryIndex.build(emb, lab, mesh=m)
        engine = QueryEngine(
            idx, EngineConfig(top_k=5, buckets=(16, 64),
                              gallery_block=block)
        )
        out = engine.query(emb[:11])
        if ref is None:
            ref = out
        else:
            np.testing.assert_array_equal(out["rows"], ref["rows"])
            np.testing.assert_array_equal(out["scores"], ref["scores"])


def test_query_validates_and_chunks(rng):
    emb, lab = make_gallery(rng, ids=4, per_id=4, dim=8)
    idx = GalleryIndex.build(emb, lab)
    engine = QueryEngine(idx, EngineConfig(top_k=3, buckets=(2, 4)))
    with pytest.raises(ValueError, match="dim"):
        engine.query(np.zeros((2, 5), np.float32))
    # 11 queries chunk through max-bucket 4 dispatches (4+4+3->pad 4)
    out = engine.query(emb[:11])
    assert out["rows"].shape == (11, 3)
    with pytest.raises(ValueError, match="exceeds gallery size"):
        QueryEngine(idx, EngineConfig(top_k=100))


# -- batcher ----------------------------------------------------------------


def test_batcher_deadline_flushes_partial_batch():
    batches = []
    b = MicroBatcher(
        lambda items: [i * 10 for i in items],
        BatcherConfig(max_batch=8, max_delay_ms=30.0, max_queue=16),
    ).start()
    try:
        t0 = time.perf_counter()
        fut = b.submit(3)
        assert fut.result(timeout=5.0) == 30  # alone, under deadline
        waited = time.perf_counter() - t0
        assert waited < 2.0  # deadline (30ms) + dispatch, not the 5s cap
    finally:
        b.close()


def test_batcher_coalesces_to_bucket_and_pads(rng):
    """Queries submitted together ride one dispatch, padded to the
    smallest engine bucket that fits (the padded shape is what the
    jitted program sees — pinned via the engine's signature set)."""
    emb, lab = make_gallery(rng, ids=4, per_id=4, dim=8)
    idx = GalleryIndex.build(emb, lab)
    engine = QueryEngine(idx, EngineConfig(top_k=2, buckets=(1, 4, 8)))
    engine.warmup()
    stats = []
    server = RetrievalServer(
        engine,
        BatcherConfig(max_batch=8, max_delay_ms=50.0, max_queue=32),
        ServerConfig(metrics_window=0),
    )
    server.batcher._on_batch = stats.append
    server.batcher.start()
    try:
        futs = [server.batcher.submit({"embedding": emb[i].tolist()})
                for i in range(3)]
        answers = [f.result(timeout=10.0) for f in futs]
    finally:
        server.batcher.close()
    assert [a["neighbors"][0]["row"] for a in answers] == [0, 1, 2]
    # 3 queries coalesced into one batch...
    assert server.batcher.batches == 1 and stats[0]["size"] == 3
    # ...dispatched at the padded bucket-4 signature (warmup saw it).
    assert ("topk", 4, idx.padded_size, idx.dim) in engine._seen_sigs
    assert engine.compiles_after_warmup == 0


def test_batcher_backpressure_rejects_not_queues():
    release = threading.Event()

    def slow_dispatch(items):
        release.wait(timeout=10.0)
        return items

    b = MicroBatcher(
        slow_dispatch,
        BatcherConfig(max_batch=1, max_delay_ms=0.0, max_queue=2),
    ).start()
    try:
        futs = [b.submit(i) for i in range(2)]  # fills dispatcher + queue
        time.sleep(0.2)  # let the dispatcher pick work up
        with pytest.raises(QueueFullError):
            for i in range(8):  # queue bound, not unbounded growth
                futs.append(b.submit(100 + i))
        assert b.rejected >= 1
        release.set()
        for f in futs:
            f.result(timeout=10.0)  # everything admitted still answers
    finally:
        release.set()
        b.close()


# -- server: drain + zero-recompile steady state ----------------------------


def _jsonl_server(rng, metrics_window=0, telemetry=None):
    from npairloss_tpu.resilience import PreemptionSignal

    emb, lab = make_gallery(rng, ids=6, per_id=4, dim=8)
    idx = GalleryIndex.build(emb, lab)
    engine = QueryEngine(idx, EngineConfig(top_k=3, buckets=(1, 4, 8)),
                         telemetry=telemetry)
    engine.warmup()
    preempt = PreemptionSignal()  # driven via .request(), no handlers
    server = RetrievalServer(
        engine,
        BatcherConfig(max_batch=8, max_delay_ms=5.0, max_queue=64),
        ServerConfig(metrics_window=metrics_window),
        telemetry=telemetry, preempt=preempt,
    )
    return emb, server, preempt


def test_jsonl_roundtrip_order_and_summary(rng):
    emb, server, _ = _jsonl_server(rng)
    lines = "".join(
        json.dumps({"id": i, "embedding": emb[i].tolist()}) + "\n"
        for i in range(17)
    )
    out = io.StringIO()
    rc = server.run_jsonl(io.StringIO(lines), out)
    assert rc == 0
    recs = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert [r["id"] for r in recs[:-1]] == list(range(17))  # in order
    for r in recs[:-1]:
        assert r["neighbors"][0]["row"] == r["id"]  # self is top-1
    summary = recs[-1]
    assert summary["event"] == "serve_drain"
    assert summary["answered"] == 17 and summary["errors"] == 0
    assert summary["compiles_after_warmup"] == 0


def test_sigterm_drain_answers_every_admitted_query(rng):
    """The preemption contract: requesting a drain mid-stream stops
    ADMISSION but answers every already-admitted query (zero drops),
    emits the summary, and returns EXIT_PREEMPTED."""
    from npairloss_tpu.resilience import EXIT_PREEMPTED

    emb, server, preempt = _jsonl_server(rng)
    r_fd, w_fd = os.pipe()
    in_stream = os.fdopen(r_fd, "r")
    writer = os.fdopen(w_fd, "w")
    out = io.StringIO()
    result = {}

    def run():
        result["rc"] = server.run_jsonl(in_stream, out)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    for i in range(25):
        writer.write(
            json.dumps({"id": i, "embedding": emb[i % len(emb)].tolist()})
            + "\n"
        )
    writer.flush()
    # Let some queries into flight, then preempt WITHOUT closing stdin —
    # exactly the SIGTERM timing (the handler only sets the flag).
    time.sleep(0.3)
    preempt.request()
    t.join(timeout=30.0)
    assert not t.is_alive()
    writer.close()
    in_stream.close()
    assert result["rc"] == EXIT_PREEMPTED
    recs = [json.loads(ln) for ln in out.getvalue().splitlines()]
    summary = recs[-1]
    assert summary["event"] == "serve_drain"
    answers = recs[:-1]
    # Zero drops: every admitted query has exactly one answer, in order.
    assert [a["id"] for a in answers] == list(range(len(answers)))
    assert summary["answered"] == len(answers) == summary["queries"]
    assert all("neighbors" in a for a in answers)


def test_zero_recompile_steady_state_strict_guard(rng, monkeypatch):
    """100 mixed-size queries after warmup under the strict compile
    guard: a single post-warmup XLA compile would raise.  The counters
    (signature set + executable cache size) are the proof — the
    ``NPAIRLOSS_PIPELINE_SYNC_GUARD``-style counted assertion."""
    monkeypatch.setenv("NPAIRLOSS_SERVE_COMPILE_GUARD", "strict")
    emb, lab = make_gallery(rng, ids=6, per_id=4, dim=8)
    idx = GalleryIndex.build(emb, lab)
    engine = QueryEngine(idx, EngineConfig(top_k=3, buckets=(1, 4, 8)))
    engine.warmup()
    warm = engine.compile_stats()
    assert warm["warmed"] and warm["compiles_after_warmup"] == 0
    rng2 = np.random.default_rng(1)
    served = 0
    while served < 100:
        n = int(rng2.integers(1, 9))
        out = engine.query(
            rng2.standard_normal((n, emb.shape[1])).astype(np.float32)
        )
        assert out["rows"].shape == (n, 3)
        served += n
    stats = engine.compile_stats()
    assert stats["compiles_after_warmup"] == 0
    # and the cache holds exactly the warmed buckets, nothing more
    assert stats["executable_cache_size"] in (None, 3)


def test_unwarmed_bucket_trips_strict_guard(rng, monkeypatch):
    """The guard has teeth: serving a bucket warmup never compiled
    raises instead of silently eating a hot-path compile."""
    from npairloss_tpu.serve.engine import ServeCompileError

    monkeypatch.setenv("NPAIRLOSS_SERVE_COMPILE_GUARD", "strict")
    emb, lab = make_gallery(rng, ids=4, per_id=4, dim=8)
    idx = GalleryIndex.build(emb, lab)
    engine = QueryEngine(idx, EngineConfig(top_k=2, buckets=(1, 4)))
    engine.warmup()
    engine.cfg = EngineConfig(top_k=2, buckets=(1, 2, 4))  # sneak a bucket
    with pytest.raises(ServeCompileError):
        engine.query(emb[:2])


def test_serve_metrics_rows_and_spans(rng, tmp_path):
    """Per-window serve metrics rows + serve/* spans land through the
    run-telemetry pipeline (docs/OBSERVABILITY.md)."""
    from npairloss_tpu.obs import RunTelemetry

    with RunTelemetry(str(tmp_path / "run"), metrics=True) as tel:
        emb, server, _ = _jsonl_server(rng, metrics_window=5,
                                       telemetry=tel)
        lines = "".join(
            json.dumps({"id": i, "embedding": emb[i % len(emb)].tolist()})
            + "\n" for i in range(12)
        )
        rc = server.run_jsonl(io.StringIO(lines), io.StringIO())
        assert rc == 0
        names = {e["name"]
                 for e in tel.tracer.to_chrome_trace()["traceEvents"]}
        assert {"serve/admit", "serve/dispatch", "serve/topk",
                "serve/warmup"} <= names
    rows = [json.loads(ln) for ln in
            open(tmp_path / "run" / "metrics.jsonl")]
    serve_rows = [r for r in rows if r["phase"] == "serve"
                  and "qps" in r]
    assert serve_rows, rows
    assert {"qps", "p50_ms", "p99_ms", "queue_depth"} <= set(serve_rows[0])


def test_backpressure_surfaces_as_error_answer(rng):
    """A rejected query is ANSWERED with an error record, not dropped."""
    emb, server, _ = _jsonl_server(rng)
    server.batcher.cfg = BatcherConfig(max_batch=1, max_delay_ms=0.0,
                                       max_queue=1)
    server.batcher._q.maxsize = 1
    release = threading.Event()
    orig = server._dispatch

    def slow(items):
        release.wait(timeout=10.0)
        return orig(items)

    server.batcher._dispatch_fn = slow
    server.batcher.start()
    try:
        futs, errors = [], 0
        for i in range(12):
            try:
                futs.append(server.batcher.submit(
                    {"id": i, "embedding": emb[0].tolist()}
                ))
            except QueueFullError:
                errors += 1
        assert errors > 0
        release.set()
        for f in futs:
            assert "neighbors" in f.result(timeout=10.0)
    finally:
        release.set()
        server.batcher.close()


# -- snapshot -> answers (restore_for_inference + encode path) --------------


def test_restore_for_inference_and_encode_path(rng, tmp_path):
    """The online path end-to-end in-process: train a tiny model,
    snapshot it, restore WITHOUT a Solver, serve raw-'input' queries
    whose encodings match the solver's own eval-mode forward."""
    import jax.numpy as jnp

    from npairloss_tpu.models import get_model
    from npairloss_tpu.ops.npair_loss import NPairLossConfig
    from npairloss_tpu.train import (
        Solver,
        SolverConfig,
        restore_for_inference,
    )
    from conftest import make_identity_batch

    solver = Solver(
        get_model("mlp", hidden=(16,), embedding_dim=8),
        NPairLossConfig(),
        SolverConfig(base_lr=0.1, lr_policy="fixed", display=0,
                     snapshot=0,
                     snapshot_prefix=str(tmp_path / "m_")),
        input_shape=(8,),
    )
    (f,), (l,) = make_identity_batch(rng, 4, 2, 8)
    solver.step(f, l)
    path = solver.save_snapshot(1)
    state = restore_for_inference(path)
    assert set(state) == {"params", "batch_stats"}
    # build a gallery from the solver's own embeddings and serve it
    emb, _ = solver.apply_model(
        solver.state["params"], solver.state["batch_stats"],
        jnp.asarray(f), train=False,
    )
    emb = np.asarray(emb)
    idx = GalleryIndex.build(emb, l)
    engine = QueryEngine(
        idx, EngineConfig(top_k=3, buckets=(1, 4)),
        model=solver.model, state=state,
    )
    engine.warmup(input_shape=(8,))
    out_io = io.StringIO()
    server = RetrievalServer(engine, BatcherConfig(max_batch=4),
                             ServerConfig(metrics_window=0))
    lines = "".join(
        json.dumps({"id": i, "input": f[i].tolist()}) + "\n"
        for i in range(4)
    )
    rc = server.run_jsonl(io.StringIO(lines), out_io)
    assert rc == 0
    recs = [json.loads(ln) for ln in out_io.getvalue().splitlines()]
    for r in recs[:-1]:
        # the encoded query's nearest gallery row is itself
        assert r["neighbors"][0]["row"] == r["id"]
        assert r["neighbors"][0]["score"] == pytest.approx(1.0, abs=1e-5)
    assert recs[-1]["compiles_after_warmup"] == 0


def test_restore_for_inference_rejects_corrupt_snapshot(rng, tmp_path):
    from npairloss_tpu.models import get_model
    from npairloss_tpu.ops.npair_loss import NPairLossConfig
    from npairloss_tpu.train import (
        Solver,
        SolverConfig,
        restore_for_inference,
    )
    from conftest import make_identity_batch

    solver = Solver(
        get_model("mlp", hidden=(8,), embedding_dim=4),
        NPairLossConfig(),
        SolverConfig(base_lr=0.1, lr_policy="fixed", display=0,
                     snapshot=0,
                     snapshot_prefix=str(tmp_path / "m_")),
        input_shape=(8,),
    )
    (f,), (l,) = make_identity_batch(rng, 4, 2, 8)
    solver.step(f, l)
    path = solver.save_snapshot(1)
    # poison the manifest's params checksums -> verification must refuse
    import json as _json

    mpath = os.path.join(path, "manifest.json")
    manifest = _json.load(open(mpath))
    for k, rec in manifest["arrays"].items():
        if k.startswith("['params']"):
            rec["crc32"] = (rec["crc32"] + 1) & 0xFFFFFFFF
    _json.dump(manifest, open(mpath, "w"))
    with pytest.raises(SnapshotValidationError):
        restore_for_inference(path)


# -- review regressions -----------------------------------------------------


def test_engine_add_on_mesh_reoffsets_shards(rng):
    """add() that grows padded_size changes every shard's row extent;
    the retraced sharded top-k must compute offsets from the NEW local
    shard shape, not the one captured at engine build (stale offsets
    serve wrong rows/labels/ids)."""
    from npairloss_tpu.parallel import data_parallel_mesh

    mesh = data_parallel_mesh()
    emb, lab = make_gallery(rng, ids=5, per_id=2, dim=8)  # 10 -> pad 16
    idx = GalleryIndex.build(emb, lab, mesh=mesh)
    engine = QueryEngine(idx, EngineConfig(top_k=4, buckets=(4,)))
    q = np.asarray(idx._host_emb[:4])
    engine.query(q)  # trace the original layout first
    add_emb, add_lab = make_gallery(rng, ids=7, per_id=1, dim=8)
    idx.add(add_emb, add_lab)  # 17 rows -> pad 24: shard extent 2 -> 3
    out = engine.query(np.asarray(idx._host_emb), normalize=False)
    sims = idx._host_emb @ idx._host_emb.T
    for i in range(idx.size):
        want = np.argsort(-sims[i], kind="stable")[:4]
        np.testing.assert_array_equal(out["rows"][i], want, str(i))
        np.testing.assert_array_equal(
            out["labels"][i], idx._host_labels[want], str(i)
        )


def test_index_save_overwrite_never_destroys_committed_data(rng, tmp_path):
    """Re-committing onto an existing index renames the old dir aside
    and deletes it only AFTER the new commit: a crash at the commit
    point must leave the original arrays intact on disk, never an empty
    prefix (the --add-to re-commit is the production path here)."""
    from npairloss_tpu.resilience import failpoints
    from npairloss_tpu.resilience.failpoints import InjectedFault

    emb, lab = make_gallery(rng, ids=4, per_id=2)
    idx = GalleryIndex.build(emb, lab)
    path = str(tmp_path / "g.gidx")
    idx.save(path)
    original = np.load(os.path.join(path, "emb.npy"))
    idx.add(rng.standard_normal((3, emb.shape[1])).astype(np.float32),
            np.arange(3).astype(np.int32))
    with failpoints.armed("index.commit.crash"):
        with pytest.raises(InjectedFault):
            idx.save(path)
    # the committed name is mid-swap, but the old data survives aside
    aside = [d for d in os.listdir(tmp_path)
             if "-prev" in d and d.startswith("g.gidx")]
    assert len(aside) == 1, aside
    kept = np.load(str(tmp_path / aside[0] / "emb.npy"))
    np.testing.assert_array_equal(kept, original)
    # a clean retry commits the new index and clears the debris
    idx.save(path)
    reloaded = GalleryIndex.load(path)
    assert reloaded.size == idx.size
    assert not [d for d in os.listdir(tmp_path) if "-prev" in d]


def test_bad_record_answers_alone_coriders_served(rng):
    """One malformed record in a coalesced micro-batch answers with an
    error WITHOUT failing its co-riders, and the drain summary counts
    it as an error, not an answered query."""
    emb, lab = make_gallery(rng)
    idx = GalleryIndex.build(emb, lab)
    engine = QueryEngine(idx, EngineConfig(top_k=3, buckets=(8,)))
    server = RetrievalServer(
        engine, BatcherConfig(max_batch=8, max_delay_ms=50.0),
        ServerConfig(metrics_window=0),
    )
    recs = [
        {"id": 0, "embedding": emb[0].tolist()},
        {"id": 1},  # missing field
        {"id": 2, "embedding": emb[1][:5].tolist()},  # wrong dim
        {"id": 3, "embedding": emb[2].tolist()},
    ]
    out_io = io.StringIO()
    rc = server.run_jsonl(
        io.StringIO("".join(json.dumps(r) + "\n" for r in recs)), out_io
    )
    assert rc == 0
    lines = [json.loads(ln) for ln in out_io.getvalue().splitlines()]
    by_id = {a["id"]: a for a in lines[:-1]}
    assert by_id[0]["neighbors"] and by_id[3]["neighbors"]
    assert "error" in by_id[1] and "field" in by_id[1]["error"]
    assert "error" in by_id[2] and "shape" in by_id[2]["error"]
    drain = lines[-1]
    assert drain["answered"] == 2 and drain["errors"] == 2, drain


def test_submit_close_race_leaves_no_hung_future():
    """A submit racing with close() must never land its item behind the
    _STOP sentinel (a hung future = a dropped admitted query).  Stress
    the window: every future a submitter got back must resolve."""
    batcher = MicroBatcher(
        lambda items: [x for x in items],
        BatcherConfig(max_batch=4, max_delay_ms=1.0, max_queue=512),
    ).start()
    futures, stop = [], threading.Event()
    flock = threading.Lock()

    def pound():
        while not stop.is_set():
            try:
                fut = batcher.submit("x")
            except QueueFullError:
                continue
            with flock:
                futures.append(fut)

    threads = [threading.Thread(target=pound) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.15)
    batcher.close(drain=True)
    stop.set()
    for t in threads:
        t.join(timeout=5.0)
    assert futures
    for fut in futures:  # resolved == dispatched (drain) — none hung
        assert fut.result(timeout=5.0) == "x"


def test_dispatch_encodes_raw_inputs_as_one_batch(rng):
    """Raw-'input' co-riders encode as ONE stacked dispatch — per-record
    encodes would serialize device round-trips inside the batch and
    defeat the micro-batcher entirely."""
    emb, lab = make_gallery(rng)
    idx = GalleryIndex.build(emb, lab)
    inner = QueryEngine(idx, EngineConfig(top_k=3, buckets=(8,)))

    class CountingEngine:
        index = idx
        encode_calls = 0

        def encode(self, x):
            CountingEngine.encode_calls += 1
            return x / np.maximum(
                np.linalg.norm(x, axis=1, keepdims=True), 1e-12
            )

        def query(self, q, normalize=True):
            return inner.query(q, normalize=normalize)

        def compile_stats(self):
            return inner.compile_stats()

    server = RetrievalServer(CountingEngine(),
                             cfg=ServerConfig(metrics_window=0))
    answers = server._dispatch([
        {"id": i, "input": emb[i].tolist()} for i in range(3)
    ] + [{"id": 3, "embedding": emb[3].tolist()}])
    assert CountingEngine.encode_calls == 1
    for i, a in enumerate(answers):
        assert a["id"] == i and a["neighbors"][0]["row"] == i

def test_jsonl_burst_then_idle_answers_every_line(rng):
    """A burst of lines followed by quiet must all answer WITHOUT
    waiting for EOF: lines read ahead into the stream buffer may never
    make the fd readable again, so the reader must not gate line
    consumption on fd-level readiness."""
    emb, lab = make_gallery(rng)
    idx = GalleryIndex.build(emb, lab)
    engine = QueryEngine(idx, EngineConfig(top_k=3, buckets=(8,)))
    engine.warmup()
    server = RetrievalServer(
        engine, BatcherConfig(max_batch=8, max_delay_ms=5.0),
        ServerConfig(metrics_window=0, poll_s=0.02),
    )
    r_fd, w_fd = os.pipe()
    reader = os.fdopen(r_fd, "r")
    out_io = io.StringIO()
    rc = [None]
    t = threading.Thread(
        target=lambda: rc.__setitem__(0, server.run_jsonl(reader, out_io))
    )
    t.start()
    try:
        burst = "".join(
            json.dumps({"id": i, "embedding": emb[i].tolist()}) + "\n"
            for i in range(20)
        ).encode()
        os.write(w_fd, burst)  # one burst, writer stays open (no EOF)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if out_io.getvalue().count("\n") >= 20:
                break
            time.sleep(0.05)
        answered = [json.loads(ln) for ln in out_io.getvalue().splitlines()]
        assert len(answered) == 20, (
            f"only {len(answered)} answers while idle (writer still open)"
        )
        assert {a["id"] for a in answered} == set(range(20))
    finally:
        os.close(w_fd)  # EOF ends the run
        t.join(timeout=10.0)
    assert rc[0] == 0


def test_handle_many_coalesces_one_request_body(rng):
    """handle_many admits every record before waiting on any, so an
    N-record HTTP body coalesces into shared micro-batches instead of
    N sequential batches-of-1 each paying the deadline."""
    emb, lab = make_gallery(rng)
    idx = GalleryIndex.build(emb, lab)
    engine = QueryEngine(idx, EngineConfig(top_k=3, buckets=(4,)))
    engine.warmup()
    server = RetrievalServer(
        engine, BatcherConfig(max_batch=4, max_delay_ms=500.0),
        ServerConfig(metrics_window=0),
    )
    server.batcher.start()
    try:
        recs = [{"id": i, "embedding": emb[i].tolist()} for i in range(4)]
        t0 = time.monotonic()
        answers = server.handle_many(recs)
        dt = time.monotonic() - t0
    finally:
        server.batcher.close(drain=True)
    for i, a in enumerate(answers):
        assert a["id"] == i and a["neighbors"][0]["row"] == i
    # all 4 filled the bucket and dispatched as ONE batch immediately —
    # sequential handling would pay the 500ms deadline per record
    assert server.batcher.batches == 1, server.batcher.batches
    assert dt < 2.0, f"coalesced body took {dt:.2f}s"


def test_warmup_compiles_each_bucket_exactly_once(rng):
    """warmup must pay ONE XLA compile per bucket program — an AOT
    lower().compile() whose executable jit's dispatch cache ignores
    would silently double every bucket's compile cost (counted via
    jax.monitoring backend-compile events, not eyeballed)."""
    import jax.monitoring

    emb, lab = make_gallery(rng)
    idx = GalleryIndex.build(emb, lab)
    engine = QueryEngine(idx, EngineConfig(top_k=3, buckets=(1, 4)))
    compiles = []

    def _listener(name, dur, **kw):
        if name == "/jax/core/compile/backend_compile_duration":
            compiles.append(name)

    jax.monitoring.register_event_duration_secs_listener(_listener)
    try:
        engine.warmup()
    finally:
        from jax._src import monitoring as _mon

        _mon._unregister_event_duration_listener_by_callback(_listener)
    assert len(compiles) == len(engine.cfg.buckets), (
        f"{len(compiles)} backend compiles for "
        f"{len(engine.cfg.buckets)} buckets"
    )
    assert engine.compile_stats()["compiles_after_warmup"] == 0


def test_submit_counter_exact_under_concurrency(rng):
    """self.queries increments under the lock: concurrent HTTP request
    threads must never lose an increment (the drain summary invariant
    queries == answered + errors + rejected depends on it)."""
    emb, lab = make_gallery(rng)
    idx = GalleryIndex.build(emb, lab)
    engine = QueryEngine(idx, EngineConfig(top_k=3, buckets=(8,)))
    engine.warmup()
    server = RetrievalServer(
        engine, BatcherConfig(max_batch=8, max_delay_ms=1.0,
                              max_queue=4096),
        ServerConfig(metrics_window=0),
    )
    server.batcher.start()
    n_threads, per = 8, 50

    def _hammer(t):
        for i in range(per):
            server.handle({"id": t * per + i,
                           "embedding": emb[i % emb.shape[0]].tolist()})

    threads = [threading.Thread(target=_hammer, args=(t,))
               for t in range(n_threads)]
    try:
        for t in threads:
            t.start()
    finally:
        for t in threads:
            t.join(timeout=60.0)
        server.batcher.close(drain=True)
    s = server.summary()
    assert s["queries"] == n_threads * per
    assert s["queries"] == s["answered"] + s["errors"] + s["rejected"]


def test_add_rejects_mismatched_ids(rng):
    emb, lab = make_gallery(rng, ids=4, per_id=2)
    idx = GalleryIndex.build(emb, lab)
    with pytest.raises(ValueError, match="ids"):
        idx.add(rng.standard_normal((3, emb.shape[1])).astype(np.float32),
                np.arange(3).astype(np.int32),
                ids=np.arange(7, dtype=np.int64))

def test_rejected_queries_counted_once_in_summary(rng):
    """A backpressure rejection counts ONCE — in ``rejected``, never
    also in ``errors`` — so the drain invariant queries == answered +
    errors + rejected holds with rejections actually occurring."""
    emb, server, _ = _jsonl_server(rng)
    server.batcher.cfg = BatcherConfig(max_batch=1, max_delay_ms=0.0,
                                       max_queue=1)
    server.batcher._q.maxsize = 1
    release = threading.Event()
    orig = server._dispatch

    def slow(items):
        release.wait(timeout=10.0)
        return orig(items)

    server.batcher._dispatch_fn = slow
    server.batcher.start()
    try:
        threading.Timer(0.3, release.set).start()
        answers = server.handle_many(
            [{"id": i, "embedding": emb[0].tolist()} for i in range(12)]
        )
    finally:
        release.set()
        server.batcher.close(drain=True)
    s = server.summary()
    assert s["rejected"] > 0, s
    assert sum(1 for a in answers if "error" in a) == s["rejected"]
    assert s["errors"] == 0, s
    assert s["queries"] == s["answered"] + s["errors"] + s["rejected"], s
