"""Partition-rule system + pod-scale planning (docs/DISTRIBUTED.md).

Covers the declarative sharding table (``parallel.partition``): first
match wins, unmatched leaves loud, the replicated fallback, 2-D mesh
specs, and parity-by-construction with the hand-placed shardings it
replaced; the DCN-aware engine plan (``parallel.plan``); the
multi-controller data shards (``data.shard_batches``); the declared-rank
topology probe fix; and the multi-host resume manifest-wait.
"""

import json
import shutil
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from npairloss_tpu.data import shard_batches
from npairloss_tpu.parallel import (
    build_mesh,
    data_parallel_mesh,
    mesh_topology,
    plan_for_mesh,
)
from npairloss_tpu.parallel import partition as pt
from npairloss_tpu.parallel.plan import (
    host_counts,
    plan_engine,
    ring_device_order,
)

G = 8


def small_tree():
    return {
        "params": {
            "dense0": {"kernel": np.zeros((16, 32), np.float32),
                       "bias": np.zeros((32,), np.float32)},
        },
        "opt": {
            "momentum_buf": {
                "dense0": {"kernel": np.zeros((16, 32), np.float32),
                           "bias": np.zeros((32,), np.float32)},
            },
            "step": np.zeros((), np.int32),
        },
    }


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= G
    return data_parallel_mesh(jax.devices()[:G])


@pytest.fixture(scope="module")
def mesh2d():
    return build_mesh(jax.devices()[:G], mp=2)


# -- match_partition_rules -------------------------------------------------


class TestMatchRules:
    def test_first_match_wins(self):
        rules = (
            (r"dense0/kernel$", P(None, "mp")),
            (r"kernel$", P("dp")),
            (".*", P()),
        )
        specs = pt.match_partition_rules(rules, small_tree())
        assert specs["params"]["dense0"]["kernel"] == P(None, "mp")
        assert specs["params"]["dense0"]["bias"] == P()
        # The broader kernel$ rule never sees dense0 (already taken).
        assert specs["opt"]["momentum_buf"]["dense0"]["kernel"] == \
            P(None, "mp")

    def test_scalar_leaves_never_partition(self):
        specs = pt.match_partition_rules(
            ((".*", P("dp")),), {"step": np.zeros(()),
                                 "one": np.zeros((1,))})
        assert specs["step"] == P()
        assert specs["one"] == P()

    def test_unmatched_leaf_is_loud(self):
        with pytest.raises(pt.PartitionRuleError, match="dense0/bias"):
            pt.match_partition_rules(((r"kernel$", P()),), small_tree())

    def test_replicated_fallback_rule(self):
        specs = pt.match_partition_rules(
            ((r"kernel$", P(None, "mp")), (".*", P())), small_tree())
        assert specs["opt"]["momentum_buf"]["dense0"]["bias"] == P()

    def test_default_ruleset_is_all_replicated(self):
        specs = pt.match_partition_rules(pt.replicated_rules(), small_tree())
        flat = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P))
        assert flat and all(s == P() for s in flat)

    def test_bad_regex_is_loud(self):
        with pytest.raises(pt.PartitionRuleError, match="valid regex"):
            pt.compile_rules((("(unclosed", P()),))

    def test_bad_spec_is_loud(self):
        with pytest.raises(pt.PartitionRuleError, match="spec"):
            pt.compile_rules(((".*", 42),))

    def test_empty_ruleset_is_loud(self):
        with pytest.raises(pt.PartitionRuleError, match="empty"):
            pt.compile_rules(())

    def test_shard_last_dim_is_rank_aware(self):
        # The shipped kernel$ rule must shard OUTPUT channels of a 2-D
        # Dense kernel AND a 4-D conv kernel — a positional
        # P(None, "mp") would hit the conv's spatial width.
        from npairloss_tpu.parallel import model_parallel_rules

        tree = {"params": {
            "conv1": {"kernel": np.zeros((3, 3, 3, 64), np.float32)},
            "head": {"kernel": np.zeros((16, 64), np.float32),
                     "bias": np.zeros((64,), np.float32)},
        }}
        specs = pt.match_partition_rules(model_parallel_rules(), tree)
        assert specs["params"]["conv1"]["kernel"] == \
            P(None, None, None, "mp")
        assert specs["params"]["head"]["kernel"] == P(None, "mp")
        assert specs["params"]["head"]["bias"] == P()

    def test_opt_paths_use_field_names(self):
        # NamedTuple opt states flatten by FIELD name, so one kernel$
        # rule covers a param and its momentum twin.
        import optax

        from npairloss_tpu.train.optim import CaffeSGDState

        tree = {"opt": CaffeSGDState(
            momentum_buf={"d": {"kernel": np.zeros((4, 4))}},
            step=np.zeros((), np.int32))}
        paths = [pt.tree_path_str(p) for p, _ in
                 jax.tree_util.tree_flatten_with_path(tree)[0]]
        assert "opt/momentum_buf/d/kernel" in paths
        assert "opt/step" in paths
        del optax


# -- shardings on a mesh ---------------------------------------------------


class TestShardings:
    def test_replicated_matches_hand_placed(self, mesh):
        # Parity by construction with the NamedSharding(mesh, P()) the
        # table replaced: every resolved sharding IS that sharding.
        sh = pt.match_partition_shardings(
            pt.replicated_rules(), small_tree(), mesh)
        want = NamedSharding(mesh, P())
        assert all(s == want for s in jax.tree_util.tree_leaves(sh))

    def test_2d_mesh_specs(self, mesh2d):
        sh = pt.match_partition_shardings(
            pt.model_parallel_rules(), small_tree(), mesh2d)
        assert sh["params"]["dense0"]["kernel"].spec == P(None, "mp")
        assert sh["params"]["dense0"]["bias"].spec == P()

    def test_unknown_axis_is_loud(self, mesh):
        with pytest.raises(pt.PartitionRuleError, match="axes"):
            pt.match_partition_shardings(
                ((".*", P("model")),), small_tree(), mesh)

    def test_indivisible_dim_is_loud(self, mesh):
        # 16 rows over an 8-way axis divide; (6, x) does not.
        tree = {"w": np.zeros((6, 4), np.float32)}
        with pytest.raises(pt.PartitionRuleError, match="divide"):
            pt.match_partition_shardings(((".*", P("dp")),), tree, mesh)

    def test_place_tree_places_per_spec(self, mesh2d):
        tree = small_tree()
        sh = pt.match_partition_shardings(
            pt.model_parallel_rules(), tree, mesh2d)
        placed = pt.place_tree(tree, sh)
        assert placed["params"]["dense0"]["kernel"].sharding.spec == \
            P(None, "mp")
        np.testing.assert_array_equal(
            np.asarray(placed["params"]["dense0"]["kernel"]),
            tree["params"]["dense0"]["kernel"])


# -- the diagnostic table --------------------------------------------------


class TestTable:
    def test_counts_and_noop_flagging(self, mesh):
        rules = ((r"kernel$", P("dp")), (r"nevermatches", P()), (".*", P()))
        table = pt.partition_table(rules, small_tree(), mesh=mesh)
        by_pat = {r["pattern"]: r["matches"] for r in table["rules"]}
        assert by_pat[r"kernel$"] == 2
        assert by_pat[r"nevermatches"] == 0
        assert table["unmatched"] == []
        assert table["sharded_leaves"] == 2
        summary = pt.partition_summary(rules, small_tree(), mesh=mesh)
        assert summary["noop_rules"] == [r"nevermatches"]
        rendered = pt.render_partition_table(table)
        assert "matches NOTHING" in rendered
        assert "params/dense0/kernel" in rendered

    def test_unmatched_reported_not_raised(self):
        table = pt.partition_table(((r"kernel$", P()),), small_tree())
        assert "params/dense0/bias" in table["unmatched"]
        assert "UNMATCHED" in pt.render_partition_table(table)

    def test_scalar_rows_tagged(self):
        table = pt.partition_table(pt.replicated_rules(), small_tree())
        row = next(r for r in table["rows"] if r["path"] == "opt/step")
        assert row["scalar"] and row["spec"] == "P()"


class TestLoadRules:
    def test_json_round_trip(self, tmp_path):
        f = tmp_path / "rules.json"
        f.write_text(json.dumps({"rules": [
            ["kernel$", [None, "mp"]],
            [".*", []],
        ]}))
        rules = pt.load_partition_rules(str(f))
        assert rules[0] == ("kernel$", P(None, "mp"))
        assert rules[1] == (".*", P())

    def test_bare_list_and_multi_axis_dim(self, tmp_path):
        f = tmp_path / "rules.json"
        f.write_text(json.dumps([["big$", [["dp", "mp"]]], [".*", None]]))
        rules = pt.load_partition_rules(str(f))
        assert rules[0] == ("big$", P(("dp", "mp")))
        assert rules[1] == (".*", P())

    def test_last_dim_json_spelling(self, tmp_path):
        f = tmp_path / "rules.json"
        f.write_text(json.dumps({"rules": [
            ["kernel$", {"last": "mp"}],
            [".*", []],
        ]}))
        rules = pt.load_partition_rules(str(f))
        specs = pt.match_partition_rules(
            rules, {"conv": {"kernel": np.zeros((3, 3, 3, 64))}})
        assert specs["conv"]["kernel"] == P(None, None, None, "mp")
        f.write_text(json.dumps([["kernel$", {"wrong": "mp"}]]))
        with pytest.raises(pt.PartitionRuleError, match="last"):
            pt.load_partition_rules(str(f))

    def test_non_list_is_loud(self, tmp_path):
        f = tmp_path / "rules.json"
        f.write_text(json.dumps({"not_rules": 1}))
        with pytest.raises(pt.PartitionRuleError):
            pt.load_partition_rules(str(f))


# -- the DCN-aware engine plan ---------------------------------------------


class _FakeDev:
    def __init__(self, id, process_index):
        self.id = id
        self.process_index = process_index
        self.device_kind = "fake"


class TestPlan:
    def test_single_shard_is_dense(self):
        plan = plan_engine(1, 1, 120, 512, "TPU v4")
        assert plan.engine == "dense" and plan.link == "ici"

    def test_single_host_small_pool_is_dense(self):
        plan = plan_engine(8, 1, 120, 512, "TPU v4")
        assert plan.engine == "dense"
        assert plan.cross_host_hops == 0
        assert "all_gather" in plan.reason

    def test_memory_budget_routes_to_ring_on_any_link(self):
        # Per-shard sim block: 10240 * (10240*8) * 4B = 3.4 GB > 2 GB.
        for hosts in (1, 2):
            plan = plan_engine(8, hosts, 10240, 512, "TPU v4")
            assert plan.engine == "ring", plan.reason
            assert "GB budget" in plan.reason

    def test_cross_host_hidden_hop_is_ring(self):
        # Widen the memory budget so the bandwidth branch decides:
        # 32768-row shards make the per-hop matmul dwarf the DCN hop.
        plan = plan_engine(8, 2, 32768, 512, "TPU v4",
                           dense_sim_budget=1 << 50)
        assert plan.link == "dcn"
        assert plan.comm_hidden and plan.engine == "ring", plan.reason
        assert plan.cross_host_hops == 2

    def test_cross_host_exposed_hop_is_dense(self):
        plan = plan_engine(8, 2, 120, 512, "TPU v4")
        assert plan.link == "dcn"
        assert not plan.comm_hidden and plan.engine == "dense", plan.reason

    def test_explicit_engine_honored_and_recorded(self):
        plan = plan_engine(8, 2, 120, 512, "TPU v4", requested="ring")
        assert plan.engine == "ring"
        assert "explicit" in plan.reason and "dense" in plan.reason

    def test_bad_topology_is_loud(self):
        with pytest.raises(ValueError):
            plan_engine(2, 4, 120, 512)
        with pytest.raises(ValueError):
            plan_engine(2, 1, 120, 512, requested="warp")

    def test_to_dict_is_json_able(self):
        d = plan_engine(8, 2, 120, 512, "TPU v4").to_dict()
        json.dumps(d)
        assert d["requested"] == "auto" and d["hosts"] == 2

    def test_ring_order_is_process_major(self):
        devs = [_FakeDev(0, 1), _FakeDev(1, 0), _FakeDev(2, 1),
                _FakeDev(3, 0)]
        ordered = ring_device_order(devs)
        assert [(d.process_index, d.id) for d in ordered] == \
            [(0, 1), (0, 3), (1, 0), (1, 2)]
        assert host_counts(devs) == {0: 2, 1: 2}

    def test_plan_for_mesh_declared_process_count(self, mesh):
        # The declared-rank harness: every device attr claims process
        # 0, but the fleet spans 2 controllers — the plan must consult
        # the declared count and select the DCN link.
        plan = plan_for_mesh(mesh, 240, 512, process_count=2)
        assert plan.hosts == 2 and plan.link == "dcn"
        plan1 = plan_for_mesh(mesh, 240, 512)
        assert plan1.hosts == 1 and plan1.link == "ici"

    def test_plan_for_mesh_declared_count_clamps_to_devices(self):
        # The fleet-smoke harness shape: a 1-device local mesh under a
        # declared 2-process fleet plans THAT mesh — single shard,
        # nothing to exchange — not a 2-host/1-device contradiction.
        mesh1 = data_parallel_mesh(jax.devices()[:1])
        plan = plan_for_mesh(mesh1, 240, 512, process_count=2)
        assert plan.devices == 1 and plan.hosts == 1
        assert plan.engine == "dense"


# -- mesh building + topology probe ----------------------------------------


class TestMesh:
    def test_build_mesh_1d_matches_data_parallel_mesh(self):
        a = build_mesh(jax.devices()[:G])
        b = data_parallel_mesh(jax.devices()[:G])
        assert a.axis_names == b.axis_names == ("dp",)
        assert [d.id for d in a.devices.flatten()] == \
            [d.id for d in b.devices.flatten()]

    def test_build_mesh_2d_shape(self, mesh2d):
        assert mesh2d.axis_names == ("dp", "mp")
        assert mesh2d.devices.shape == (4, 2)

    def test_build_mesh_indivisible_is_loud(self):
        with pytest.raises(ValueError, match="--mp"):
            build_mesh(jax.devices()[:G], mp=3)

    def test_topology_uses_declared_rank(self, mesh, monkeypatch):
        monkeypatch.setenv("NPAIRLOSS_FLEET_PROCESS", "1/2")
        topo = mesh_topology(mesh)
        assert topo["process_count"] == 2
        assert topo["process_index"] == 1

    def test_topology_without_declaration(self, mesh, monkeypatch):
        monkeypatch.delenv("NPAIRLOSS_FLEET_PROCESS", raising=False)
        topo = mesh_topology(mesh)
        assert topo["process_count"] == 1
        assert topo["axes"] == {"dp": G}
        assert len(topo["device_ids"]) == G


# -- data shards -----------------------------------------------------------


class TestShardBatches:
    def _stream(self):
        rng = np.random.default_rng(3)
        while True:
            yield (rng.standard_normal((8, 4)).astype(np.float32),
                   np.arange(8, dtype=np.int32))

    def test_disjoint_shards_reassemble_to_global(self):
        want_x, want_l = next(self._stream())
        parts = [next(shard_batches(self._stream(), r, 4)) for r in range(4)]
        np.testing.assert_array_equal(
            np.concatenate([p[0] for p in parts]), want_x)
        np.testing.assert_array_equal(
            np.concatenate([p[1] for p in parts]), want_l)
        for p in parts:
            assert p[0].shape[0] == 2

    def test_indivisible_batch_is_loud(self):
        it = shard_batches(self._stream(), 0, 3)
        with pytest.raises(ValueError, match="divide"):
            next(it)

    def test_rank_bounds_are_loud(self):
        with pytest.raises(ValueError, match="rank"):
            shard_batches(self._stream(), 4, 4)


# -- solver integration ----------------------------------------------------


def _mlp_solver(mesh, rules=None, **cfg_kw):
    from npairloss_tpu import REFERENCE_CONFIG
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    cfg = SolverConfig(base_lr=0.1, lr_policy="fixed", display=0,
                       snapshot=0, test_interval=0, **cfg_kw)
    return Solver(
        get_model("mlp", hidden=(32,), embedding_dim=16),
        REFERENCE_CONFIG, cfg, mesh=mesh, input_shape=(16,),
        partition_rules=rules,
    )


def _batch(rows=16):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((rows, 16)).astype(np.float32)
    lab = np.repeat(np.arange(rows // 2), 2).astype(np.int32)
    return x, lab


class TestSolverPartition:
    def test_default_rules_place_replicated(self, mesh):
        s = _mlp_solver(mesh)
        x, lab = _batch()
        s.step(x, lab)
        want = NamedSharding(mesh, P())
        for leaf in jax.tree_util.tree_leaves(s.state):
            assert leaf.sharding == want

    def test_explicit_replicated_rules_bit_identical_to_default(self, mesh):
        # The parity-by-construction satellite: the rule table's
        # replicated default trains bit-identically to an explicitly
        # spelled fallback table (same resolved shardings in, same
        # program out) — metric streams equal to the last bit.
        x, lab = _batch()
        a = _mlp_solver(mesh)
        b = _mlp_solver(mesh, rules=((".*", P()),))
        for _ in range(3):
            ma = a.step(x, lab)
            mb = b.step(x, lab)
        assert sorted(ma) == sorted(mb)
        for k in ma:
            assert float(ma[k]) == float(mb[k]), k
        for la, lb in zip(jax.tree_util.tree_leaves(a.state),
                          jax.tree_util.tree_leaves(b.state)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_2d_mesh_mp_rules_match_1d_dp_run(self):
        # dp=4 both ways; sharding kernels over the extra mp axis must
        # not change the math (the mp gemm partition splits output
        # columns — no reduction reorder).
        from npairloss_tpu.parallel import model_parallel_rules

        x, lab = _batch()
        s1 = _mlp_solver(data_parallel_mesh(jax.devices()[:4]))
        s2 = _mlp_solver(build_mesh(jax.devices()[:G], mp=2),
                         rules=model_parallel_rules())
        # MULTIPLE steps: step 1's output state must stay ON the rule
        # table (out_shardings pin) or step 2's input contract breaks —
        # XLA propagating the sharded kernel's layout onto the bias
        # output was a live bug caught by the convergence drive.
        for _ in range(3):
            m1 = s1.step(x, lab)
            m2 = s2.step(x, lab)
        assert s2.state["params"]["dense0"]["kernel"].sharding.spec == \
            P(None, "mp")
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                                   rtol=1e-6)

    def test_unmatched_rule_fails_before_training(self, mesh):
        s = _mlp_solver(mesh, rules=((r"kernel$", P()),))
        with pytest.raises(pt.PartitionRuleError, match="bias"):
            s.step(*_batch())

    def test_partition_table_before_init_uses_abstract_state(self, mesh):
        s = _mlp_solver(mesh)
        assert s.state is None
        table = s.partition_table()
        assert s.state is None  # eval_shape only — nothing materialized
        paths = {r["path"] for r in table["rows"]}
        assert "params/dense0/kernel" in paths
        assert "opt/momentum_buf/dense0/kernel" in paths
        assert table["mesh"]["axes"] == {"dp": G}

    def test_engine_plan_attribute_default(self, mesh):
        assert _mlp_solver(mesh).engine_plan is None


# -- multi-host resume: the manifest race ----------------------------------


class TestResumeManifestWait:
    def _snapshotted_solver(self, tmp_path):
        s = _mlp_solver(None,
                        snapshot_prefix=str(tmp_path / "s_"))
        s.init(np.zeros((2, 16), np.float32))
        s.save_snapshot(3)
        return s, s.snapshot_path(3)

    def _fast_retry(self):
        from npairloss_tpu.resilience import RetryPolicy

        return RetryPolicy(max_attempts=8, base_delay=0.05,
                           max_delay=0.05, jitter=0.0)

    def test_nonzero_rank_waits_out_the_race(self, tmp_path, monkeypatch):
        s, path = self._snapshotted_solver(tmp_path)
        manifest = f"{path}/manifest.json"
        aside = f"{path}/manifest.aside"
        shutil.move(manifest, aside)
        monkeypatch.setenv("NPAIRLOSS_FLEET_PROCESS", "1/2")
        s.snapshot_retry = self._fast_retry()
        t = threading.Timer(0.12, lambda: shutil.move(aside, manifest))
        t.start()
        try:
            restored = s.restore_auto()
        finally:
            t.join()
        assert restored == path  # waited, not skipped-as-torn

    def test_rank_zero_still_skips_torn(self, tmp_path, monkeypatch):
        s, path = self._snapshotted_solver(tmp_path)
        shutil.move(f"{path}/manifest.json", f"{path}/manifest.aside")
        monkeypatch.setenv("NPAIRLOSS_FLEET_PROCESS", "0/2")
        s.snapshot_retry = self._fast_retry()
        assert s.restore_auto() is None  # rank 0: missing manifest IS torn

    def test_wait_gives_up_after_budget(self, tmp_path, monkeypatch):
        from npairloss_tpu.resilience import (
            RetryPolicy,
            SnapshotValidationError,
            validate_snapshot_wait,
        )

        s, path = self._snapshotted_solver(tmp_path)
        shutil.move(f"{path}/manifest.json", f"{path}/manifest.aside")
        with pytest.raises(SnapshotValidationError):
            validate_snapshot_wait(
                path, RetryPolicy(max_attempts=2, base_delay=0.01,
                                  jitter=0.0))
