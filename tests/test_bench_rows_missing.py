"""Unit tests for scripts/bench_rows_missing.py — the coverage checker
that gates tpu_queue_r5_extras.sh's bench re-pass.

The checker decides (a) whether a ~70-minute bench re-pass is worth
dispatching (before-call), (b) whether the run may claim DONE
(--strict after-call), and (c) seeds the batch-480 quarantine from the
recorded evidence of the 2026-08-02 incident.  Each behavior guards
real tunnel time, so each is pinned here.
"""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "bench_rows_missing.py")


@pytest.fixture()
def checker(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_rows_missing", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "LAST_GOOD", str(tmp_path / "last_good.json"))
    monkeypatch.setattr(mod, "QUARANTINE", str(tmp_path / "quarantine.json"))
    monkeypatch.setattr(sys, "argv", ["bench_rows_missing.py"])
    return mod


def _write_last_good(mod, rows):
    with open(mod.LAST_GOOD, "w") as f:
        json.dump({"payload": {"extras": {"batch_scaling": rows}}}, f)


def _run(mod, capsys, *argv):
    import sys as _sys
    _sys.argv = ["bench_rows_missing.py", *argv]
    mod.main()
    return capsys.readouterr().out.strip().splitlines()[0]


MEASURED = {"emb_per_sec": 1000.0, "ms_per_step": 1.0}


def test_all_measured_prints_no(checker, capsys):
    _write_last_good(checker, {k: dict(MEASURED) for k in checker.WANT})
    assert _run(checker, capsys) == "no"


def test_missing_row_prints_yes(checker, capsys):
    rows = {k: dict(MEASURED) for k in checker.WANT}
    del rows["vit_b16_128"]
    _write_last_good(checker, rows)
    assert _run(checker, capsys) == "yes"


def test_error_row_counts_missing(checker, capsys):
    rows = {k: dict(MEASURED) for k in checker.WANT}
    rows["120_s2d"] = {"error": "in flight when the child died (wedge?)"}
    _write_last_good(checker, rows)
    assert _run(checker, capsys) == "yes"


def test_unreadable_last_good_prints_yes(checker, capsys):
    # No last_good at all: every wanted row is missing -> re-pass.
    assert _run(checker, capsys) == "yes"


def test_quarantined_row_skips_repass_but_fails_strict(checker, capsys):
    """Before-call: don't dispatch for a row bench.py will skip.
    After-call (--strict): that row still blocks the DONE marker."""
    rows = {k: dict(MEASURED) for k in checker.WANT}
    rows["vit_b16_256"] = {"error": "wedge"}
    _write_last_good(checker, rows)
    with open(checker.QUARANTINE, "w") as f:
        json.dump({"vit_b16_256": {"note": "wedged"}}, f)
    assert _run(checker, capsys) == "no"
    assert _run(checker, capsys, "--strict") == "yes"


def test_seeds_480_quarantine_only_on_error_evidence(checker, capsys):
    rows = {k: dict(MEASURED) for k in checker.WANT}
    rows["480"] = {"error": "UNAVAILABLE: TPU backend setup/compile error"}
    _write_last_good(checker, rows)
    _run(checker, capsys)
    q = json.load(open(checker.QUARANTINE))
    assert set(q) == {"480", "480_remat"}
    # Wedge-shaped compiles are environment incidents, not code bugs:
    # the note must tell the operator how to retry.
    assert "note" in q["480"] and "date" in q["480"]


def test_no_seeding_without_evidence(checker, capsys):
    """'480 merely unmeasured' must NOT seed: that would re-add entries
    an operator deliberately cleared for a retry, and would fire in
    fresh environments where 480 never failed."""
    rows = {k: dict(MEASURED) for k in checker.WANT}
    _write_last_good(checker, rows)  # no 480 row at all
    _run(checker, capsys)
    assert not os.path.exists(checker.QUARANTINE)


def test_measured_480_does_not_seed(checker, capsys):
    rows = {k: dict(MEASURED) for k in checker.WANT}
    rows["480"] = dict(MEASURED)
    _write_last_good(checker, rows)
    _run(checker, capsys)
    assert not os.path.exists(checker.QUARANTINE)


def test_seeding_is_idempotent_and_preserves_entries(checker, capsys):
    rows = {k: dict(MEASURED) for k in checker.WANT}
    rows["480"] = {"error": "UNAVAILABLE"}
    _write_last_good(checker, rows)
    with open(checker.QUARANTINE, "w") as f:
        json.dump({"blockwise_flagship_radix": {"note": "kept"}}, f)
    _run(checker, capsys)
    first = json.load(open(checker.QUARANTINE))
    _run(checker, capsys)
    second = json.load(open(checker.QUARANTINE))
    assert first == second
    assert second["blockwise_flagship_radix"]["note"] == "kept"
    assert "480" in second and "480_remat" in second


def test_corrupt_quarantine_never_rewritten_and_blocks_dispatch(
        checker, capsys):
    """A corrupt quarantine file must not be clobbered (that would drop
    the radix wedge entry) and must not green-light a re-pass —
    bench.py reads the same corrupt file as {} and would dispatch the
    known tunnel-wedgers."""
    rows = {}  # everything missing: normally a clear 'yes'
    _write_last_good(checker, rows)
    with open(checker.QUARANTINE, "w") as f:
        f.write("{not json")
    assert _run(checker, capsys) == "no"
    assert open(checker.QUARANTINE).read() == "{not json"
    # --strict (after-call) still reports coverage honestly.
    assert _run(checker, capsys, "--strict") == "yes"
