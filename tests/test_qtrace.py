"""obs/qtrace: per-query stage tracing, exemplar sampling, the v1
artifact contract, and the composed-system timeline merge.

The load-bearing pins (docs/OBSERVABILITY.md §Query tracing):
  * one trace id per query, assigned at ingestion and propagated with
    the record across the admission/batcher/replica THREADS — every
    span in an exemplar tree carries that id, and the tree shows work
    from more than one thread;
  * span ordering and nesting obey the contract the validator checks
    (root covers everything; score/topk_merge nest inside dispatch);
  * the exemplar store is bounded — fastest evicted first, so the
    worst span tree is never lost — and retention is deterministic
    under a seeded clock;
  * the validator refuses doctored artifacts (≥6 distinct refusals
    pinned here) and the p99/exemplar cross-check refuses aggregation
    the exemplars can't explain;
  * qtrace OFF keeps every emitted stream byte-identical to a
    qtrace-free build, and the two latency populations (smoothed ring
    vs per-window list) admit exactly the same samples — dropped and
    errored queries enter NEITHER, and windows-off keeps the window
    list empty rather than growing an unbounded divergent copy;
  * the timeline merge gives exemplar trees their own per-replica
    lanes and renders alerts/remediation/chaos as instants.
"""

import json
import threading
import types

import numpy as np
import pytest

from npairloss_tpu.obs.qtrace import (
    MARKER_NAMES,
    QTraceConfig,
    QueryTracer,
    STAGES,
    qtrace_p99_consistency,
    validate_qtrace_report,
)
from npairloss_tpu.obs.qtrace.report import ROOT_SPAN
from npairloss_tpu.serve.batcher import BatcherConfig
from npairloss_tpu.serve.server import RetrievalServer, ServerConfig


class SeededClock:
    """Deterministic monotonic clock: time moves only when told to."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def _tracer(clk, **cfg):
    return QueryTracer(QTraceConfig(**cfg), clock=clk,
                       wall=lambda: 1000.0 + clk.t)


def _run_query(tracer, clk, qid, admit_s=0.001, queue_s=0.002,
               assemble_s=0.003, dispatch_s=0.010, score_us=4000.0,
               merge_us=1000.0, replica="r0"):
    """Drive one query through every stage hook with seeded timing."""
    qt = tracer.begin(qid)
    clk.advance(admit_s)
    tracer.admitted(qt)
    clk.advance(queue_s)
    tracer.picked(qt)
    clk.advance(assemble_s)
    tracer.dispatch_begin([qt], replica=replica)
    clk.advance(dispatch_s)
    tracer.dispatch_end([qt], score_us=score_us, merge_us=merge_us)
    tracer.finish(qt)
    return qt


# -- seeded-clock determinism: spans, ordering, nesting ---------------------


def test_seeded_clock_stage_decomposition_exact():
    clk = SeededClock()
    tr = _tracer(clk, exemplars=4, slo_ms=100.0)
    _run_query(tr, clk, "q1")
    rep = tr.report()
    assert validate_qtrace_report(rep) is None
    assert qtrace_p99_consistency(rep) is None
    assert rep["totals"] == {
        "queries": 1, "errors": 0, "dropped": 0, "violations": 0,
        "exemplars": 1, "evicted": 0, "reroutes": 0, "hotswap_flips": 0,
    }
    # 1+2+3+10 ms of seeded time; dispatch splits 10 into 5/4/1.
    b = rep["budget"]
    assert b["p99_ms"] == pytest.approx(16.0)
    assert b["dominant"] == "dispatch"
    assert b["worst_mean_ms"]["admit_wait"] == pytest.approx(1.0)
    assert b["worst_mean_ms"]["queue_wait"] == pytest.approx(2.0)
    assert b["worst_mean_ms"]["batch_assemble"] == pytest.approx(3.0)
    assert b["worst_mean_ms"]["dispatch"] == pytest.approx(5.0)
    assert b["worst_mean_ms"]["score"] == pytest.approx(4.0)
    assert b["worst_mean_ms"]["topk_merge"] == pytest.approx(1.0)


def test_exemplar_tree_ordering_and_nesting():
    clk = SeededClock()
    tr = _tracer(clk, exemplars=4, slo_ms=100.0)
    _run_query(tr, clk, "q1")
    (ex,) = tr.report()["exemplars"]
    names = [e["name"] for e in ex["events"]]
    # Every stage span plus exactly one root, sorted by start ts.
    assert names.count(ROOT_SPAN) == 1
    for stage in STAGES:
        assert f"qtrace/{stage}" in names
    ts = [e["ts"] for e in ex["events"]]
    assert ts == sorted(ts)
    # Root covers the whole tree; score/topk_merge nest inside dispatch.
    root = next(e for e in ex["events"] if e["name"] == ROOT_SPAN)
    disp = next(e for e in ex["events"] if e["name"] == "qtrace/dispatch")
    for e in ex["events"]:
        assert e["ts"] >= root["ts"] - 2.0
        assert e["ts"] + e.get("dur", 0.0) <= \
            root["ts"] + root["dur"] + 2.0
    for name in ("qtrace/score", "qtrace/topk_merge"):
        e = next(ev for ev in ex["events"] if ev["name"] == name)
        assert e["ts"] >= disp["ts"] - 2.0
        assert e["ts"] + e["dur"] <= disp["ts"] + disp["dur"] + 2.0
    # Every span carries the exemplar's trace id and replica is stamped.
    assert all(e["args"]["trace_id"] == ex["trace_id"]
               for e in ex["events"])
    assert ex["replica"] == "r0"


# -- exemplar retention: ring bounds, determinism ---------------------------


def test_exemplar_ring_bounded_fastest_evicted_first():
    clk = SeededClock()
    tr = _tracer(clk, exemplars=2, slo_ms=0.0)  # tail rule only
    for qid, disp_s in (("a", 0.010), ("b", 0.020), ("c", 0.030)):
        _run_query(tr, clk, qid, admit_s=0.0, queue_s=0.0,
                   assemble_s=0.0, dispatch_s=disp_s, score_us=0.0,
                   merge_us=0.0)
    # "a" (ring-empty retain) was evicted when "c" arrived; the two
    # slowest survive, so the worst span tree is never lost.
    rep = tr.report()
    assert validate_qtrace_report(rep) is None
    kept = sorted(ex["total_ms"] for ex in rep["exemplars"])
    assert kept == pytest.approx([20.0, 30.0])
    assert rep["totals"]["evicted"] == 1
    assert rep["totals"]["exemplars"] == 2
    # A below-tail query is NOT retained (never a flight recorder).
    _run_query(tr, clk, "d", admit_s=0.0, queue_s=0.0, assemble_s=0.0,
               dispatch_s=0.005, score_us=0.0, merge_us=0.0)
    rep = tr.report()
    assert sorted(ex["total_ms"] for ex in rep["exemplars"]) == \
        pytest.approx([20.0, 30.0])


def test_slo_violation_retained_even_when_store_prefers_it_not():
    clk = SeededClock()
    tr = _tracer(clk, exemplars=1, slo_ms=1.0)
    _run_query(tr, clk, "slow", admit_s=0.0, queue_s=0.0,
               assemble_s=0.0, dispatch_s=0.050, score_us=0.0,
               merge_us=0.0)
    _run_query(tr, clk, "violating-but-faster", admit_s=0.0,
               queue_s=0.0, assemble_s=0.0, dispatch_s=0.010,
               score_us=0.0, merge_us=0.0)
    rep = tr.report()
    # Both violated; the store is full of a slower tree, so the second
    # counts as evicted rather than displacing the worst exemplar.
    assert rep["totals"]["violations"] == 2
    assert rep["totals"]["evicted"] == 1
    (ex,) = rep["exemplars"]
    assert ex["reason"] == "slo"
    assert ex["total_ms"] == pytest.approx(50.0)


def test_dropped_and_errored_enter_no_population():
    clk = SeededClock()
    tr = _tracer(clk, exemplars=4, slo_ms=100.0)
    tr.drop(tr.begin("shed"))
    tr.drop(tr.begin("boom"), error=True)
    rep = tr.report()
    assert validate_qtrace_report(rep) is None
    assert rep["totals"]["queries"] == 2
    assert rep["totals"]["dropped"] == 1
    assert rep["totals"]["errors"] == 1
    # Neither the budget ring nor the exemplar store saw them.
    assert rep["budget"]["p99_ms"] == 0.0
    assert rep["budget"]["dominant"] == ""
    assert rep["exemplars"] == []
    assert tr.window_row() == {"qtrace_dominant": "",
                               "qtrace_dominant_ms": 0.0}


def test_window_row_drains_its_accumulator():
    clk = SeededClock()
    tr = _tracer(clk, exemplars=4, slo_ms=100.0)
    _run_query(tr, clk, "q1", dispatch_s=0.030)
    row = tr.window_row()
    assert row["qtrace_dominant"] == "dispatch"
    assert row["qtrace_dominant_ms"] > 0
    # The accumulator is per-window: a second read starts empty, while
    # the smoothed budget ring still remembers the query.
    assert tr.window_row() == {"qtrace_dominant": "",
                               "qtrace_dominant_ms": 0.0}
    assert tr.budget()["p99_ms"] > 0


def test_marker_vocabulary_and_counts():
    clk = SeededClock()
    tr = _tracer(clk, exemplars=4, slo_ms=100.0)
    tr.marker("hotswap_flip", generation=1)
    tr.marker("crash_reroute", dead="r0", target="r1", queries=3)
    with pytest.raises(ValueError):
        tr.marker("made_up_marker")
    rep = tr.report()
    assert rep["totals"]["hotswap_flips"] == 1
    assert rep["totals"]["reroutes"] == 1
    assert [m["name"] for m in rep["markers"]] == list(MARKER_NAMES)


# -- validator refusals -----------------------------------------------------


def _valid_report():
    clk = SeededClock()
    tr = _tracer(clk, exemplars=4, slo_ms=0.0)
    _run_query(tr, clk, "q1", dispatch_s=0.010)
    _run_query(tr, clk, "q2", dispatch_s=0.020)
    tr.marker("hotswap_flip", generation=1)
    rep = tr.report()
    assert validate_qtrace_report(rep) is None, "fixture must start valid"
    return json.loads(json.dumps(rep))


def _doctor_schema(rep):
    rep["schema"] = "npairloss-qtrace-v2"


def _doctor_missing_key(rep):
    del rep["budget"]


def _doctor_stage_vocab(rep):
    rep["stages"][3] = "disptach"


def _doctor_duplicate_trace_id(rep):
    src, dst = rep["exemplars"][0], rep["exemplars"][1]
    dst["trace_id"] = src["trace_id"]
    for ev in dst["events"]:
        ev["args"]["trace_id"] = src["trace_id"]


def _doctor_event_order(rep):
    rep["exemplars"][0]["events"].reverse()


def _doctor_nesting(rep):
    ex = rep["exemplars"][0]
    span = next(e for e in ex["events"]
                if e["name"] == "qtrace/queue_wait")
    span["dur"] = 1e9  # escapes the root span — broken nesting


def _doctor_totals_mismatch(rep):
    rep["totals"]["exemplars"] += 1


def _doctor_marker_name(rep):
    rep["markers"][0]["name"] = "surprise_party"


def _doctor_foreign_span(rep):
    ex = rep["exemplars"][0]
    ex["events"][0]["name"] = "qtrace/gpu_melt"


def _doctor_reason(rep):
    rep["exemplars"][0]["reason"] = "vibes"


@pytest.mark.parametrize(
    "doctor, expect",
    [
        (_doctor_schema, "foreign artifact"),
        (_doctor_missing_key, "missing key"),
        (_doctor_stage_vocab, "do not match the contract"),
        (_doctor_duplicate_trace_id, "duplicate trace_id"),
        (_doctor_event_order, "out of ts order"),
        (_doctor_nesting, "broken nesting"),
        (_doctor_totals_mismatch, "retained exemplars"),
        (_doctor_marker_name, "instant named one of"),
        (_doctor_foreign_span, "outside the qtrace vocabulary"),
        (_doctor_reason, "reason"),
    ],
    ids=["schema", "missing-key", "stage-vocab", "dup-trace-id",
         "event-order", "nesting", "totals-mismatch", "marker-name",
         "foreign-span", "reason"],
)
def test_validator_refuses_doctored_artifacts(doctor, expect):
    rep = _valid_report()
    doctor(rep)
    err = validate_qtrace_report(rep)
    assert err is not None and expect in err


def test_p99_consistency_cross_check():
    rep = _valid_report()
    assert qtrace_p99_consistency(rep) is None
    # Aggregation the exemplar set cannot explain: a logged p99 beyond
    # the worst retained tree by more than the ring tolerance.
    rep["budget"]["p99_ms"] = max(
        ex["total_ms"] for ex in rep["exemplars"]
    ) * (1.0 + rep["ring_tolerance"]) * 1.5
    err = qtrace_p99_consistency(rep)
    assert err is not None and "ring tolerance" in err


# -- server integration: propagation, byte-identity, populations ------------


class FakeEngine:
    """Engine-shaped stand-in: answers instantly, reports measured
    score/merge time through the per-call stage accumulator exactly
    like QueryEngine.query does — no device, no compiles."""

    def __init__(self, dim=4, k=2):
        self.index = types.SimpleNamespace(dim=dim)
        self.k = k
        self.compiles_total = 0
        self.compiles_after_warmup = 0

    def query(self, emb, normalize=True, stages=None):
        n = emb.shape[0]
        if stages is not None:
            stages["score_us"] = stages.get("score_us", 0.0) + 120.0
            stages["merge_us"] = stages.get("merge_us", 0.0) + 40.0
        rows = np.tile(np.arange(self.k), (n, 1)).astype(np.int64)
        return {"rows": rows, "ids": rows, "labels": rows,
                "scores": np.ones((n, self.k), np.float32)}

    def compile_stats(self):
        return {"compiles": 0}


class CapturingTelemetry:
    """Telemetry-shaped sink recording every emitted row verbatim."""

    metrics_enabled = True

    def __init__(self):
        self.rows = []

    def log(self, kind, step, row):
        self.rows.append((kind, json.dumps(row, sort_keys=True)))

    def flush(self):
        pass

    def span(self, name, **args):
        import contextlib

        return contextlib.nullcontext()


def _fake_server(qtrace=None, replicas=2, metrics_window=4,
                 telemetry=None):
    return RetrievalServer(
        [FakeEngine() for _ in range(replicas)],
        BatcherConfig(max_batch=4, max_delay_ms=2.0, max_queue=64),
        ServerConfig(metrics_window=metrics_window),
        telemetry=telemetry,
        qtrace=qtrace,
    )


def _records(prefix, n, dim=4):
    return [{"id": f"{prefix}{i}", "embedding": [0.1] * dim}
            for i in range(n)]


def test_trace_propagation_across_threads():
    tracer = QueryTracer(QTraceConfig(exemplars=64, slo_ms=0.0))
    srv = _fake_server(qtrace=tracer)
    srv.replicaset.start()
    errors = []

    def client(prefix):
        try:
            answers = srv.handle_many(_records(prefix, 6))
            assert all("error" not in a for a in answers)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    try:
        threads = [threading.Thread(target=client, args=(f"c{i}-",))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        srv.replicaset.close(drain=True)
    assert not errors
    rep = tracer.report()
    assert validate_qtrace_report(rep) is None
    assert rep["totals"]["queries"] == 24
    assert rep["totals"]["errors"] == 0
    assert rep["exemplars"], "tail rule must retain at least one tree"
    for ex in rep["exemplars"]:
        names = {e["name"] for e in ex["events"]}
        # The pipeline stages all made it into one tree, across the
        # client thread (admit/root) and the dispatcher (pick/dispatch)
        # — same trace id end to end, at least two distinct threads.
        for want in (ROOT_SPAN, "qtrace/admit_wait", "qtrace/queue_wait",
                     "qtrace/batch_assemble", "qtrace/dispatch"):
            assert want in names
        assert len({e["tid"] for e in ex["events"]}) >= 2
    # The summary carries the budget decomposition.
    s = srv.summary()
    assert s["qtrace"]["queries"] == 24
    assert s["qtrace"]["budget"]["dominant"] in STAGES


def test_qtrace_off_streams_byte_identical():
    tel = CapturingTelemetry()
    srv = _fake_server(qtrace=None, telemetry=tel)
    srv.replicaset.start()
    try:
        srv.handle_many(_records("q", 8))
    finally:
        srv.replicaset.close(drain=True)
    summary = srv.summary()
    rows = [row for kind, row in tel.rows if kind == "serve"]
    assert rows, "windows must have emitted"
    # The OFF posture: no qtrace key anywhere in any emitted byte.
    for row in rows:
        assert "qtrace" not in row
    assert "qtrace" not in json.dumps(summary)

    # Turning tracing ON adds ONLY the qtrace keys to the same stream.
    tel2 = CapturingTelemetry()
    tracer = QueryTracer(QTraceConfig(exemplars=8, slo_ms=0.0))
    srv2 = _fake_server(qtrace=tracer, telemetry=tel2)
    srv2.replicaset.start()
    try:
        srv2.handle_many(_records("q", 8))
    finally:
        srv2.replicaset.close(drain=True)
    on_rows = [json.loads(row) for kind, row in tel2.rows
               if kind == "serve"]
    assert any("qtrace_dominant" in r for r in on_rows)
    off_keys = {k for row in rows for k in json.loads(row)}
    on_keys = {k for r in on_rows for k in r}
    assert on_keys - off_keys <= {"qtrace_dominant",
                                  "qtrace_dominant_ms"}
    assert "qtrace" in srv2.summary()


def test_latency_populations_admit_identical_samples():
    # Satellite pin: the smoothed ring and the per-window list are two
    # views of ONE population.  With windows off the per-window list
    # must stay EMPTY (not an unbounded divergent copy of the ring),
    # and errored queries enter neither view.
    srv = _fake_server(qtrace=None, metrics_window=0)
    srv.replicaset.start()
    try:
        srv.handle_many(_records("ok", 5))
        answers = srv.handle_many([{"id": "bad"}])  # no embedding/input
        assert "error" in answers[0]
    finally:
        srv.replicaset.close(drain=True)
    assert srv.answered == 5 and srv.errors == 1
    assert len(srv._lat) == 5
    assert srv._window_lat == []

    # With windows ON both views admit exactly the answered samples.
    tracer = QueryTracer(QTraceConfig(exemplars=8, slo_ms=0.0))
    srv2 = _fake_server(qtrace=tracer, metrics_window=100)
    srv2.replicaset.start()
    try:
        srv2.handle_many(_records("ok", 5))
        srv2.handle_many([{"id": "bad"}])
    finally:
        srv2.replicaset.close(drain=True)
    assert len(srv2._lat) == 5
    assert len(srv2._window_lat) == 5  # window never filled: no emit
    rep = tracer.report()
    assert rep["totals"]["queries"] == 6
    assert rep["totals"]["errors"] == 1
    # The errored query is in no aggregation population.
    assert all(ex["qid"] != "bad" for ex in rep["exemplars"])


# -- the composed-system timeline -------------------------------------------


def test_merge_timeline_lanes_and_instants(tmp_path):
    from npairloss_tpu.obs.fleet.merge_traces import (
        OPS_PID,
        QTRACE_PID_BASE,
        SERVE_EVENTS_PID,
        merge_timeline,
    )

    run = tmp_path / "run"
    serve_tel = run / "serve_tel"
    serve_tel.mkdir(parents=True)

    clk = SeededClock()
    tr = _tracer(clk, exemplars=4, slo_ms=0.0)
    _run_query(tr, clk, "q1", dispatch_s=0.020, replica="r0")
    _run_query(tr, clk, "q2", dispatch_s=0.030, replica="r1")
    tr.marker("hotswap_flip", generation=1)
    tr.write(str(serve_tel / "qtrace.json"))

    with open(serve_tel / "alerts.jsonl", "w") as f:
        f.write(json.dumps({"slo": "serve_p99", "state": "firing",
                            "ts": 1000.5, "severity": "page"}) + "\n")
        f.write(json.dumps({"slo": "serve_p99", "state": "resolved",
                            "ts": 1001.5, "severity": "page"}) + "\n")
    with open(serve_tel / "remediation.jsonl", "w") as f:
        f.write(json.dumps({"policy": "load_shed", "state": "succeeded",
                            "ts": 1001.0, "attempt": 1}) + "\n")
    with open(run / "gameday.json", "w") as f:
        json.dump({"faults": [{"name": "serve.latency", "target":
                               "serve", "kind": "failpoint",
                               "at_s": 5.0}]}, f)

    path, merged = merge_timeline(str(run))
    assert path is not None
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk["otherData"]["sources"]["qtrace"] is True
    assert on_disk["otherData"]["sources"]["alerts"] == 2
    events = merged["traceEvents"]

    # One lane (pid) per replica, one row (tid) per exemplar tree.
    lane_names = {e["args"]["name"] for e in events
                  if e.get("ph") == "M" and e["name"] == "process_name"}
    assert "serve queries r0" in lane_names
    assert "serve queries r1" in lane_names
    qtrace_spans = [e for e in events if e.get("ph") == "X"
                    and e["pid"] >= QTRACE_PID_BASE
                    and e["pid"] < SERVE_EVENTS_PID]
    assert {e["name"] for e in qtrace_spans} >= {ROOT_SPAN,
                                                 "qtrace/dispatch"}

    # Markers land on the serve-events lane; ops land as instants.
    assert any(e["pid"] == SERVE_EVENTS_PID
               and e["name"] == "hotswap_flip" for e in events)
    instants = {e["name"] for e in events
                if e.get("ph") == "i" and e["pid"] == OPS_PID}
    assert "alert:serve_p99 firing" in instants
    assert "alert:serve_p99 resolved" in instants
    assert "remediation:load_shed succeeded" in instants
    assert "chaos:serve.latency" in instants

    # Alignment: the alert fired 0.5 s after the tracer's origin, on
    # the merged timeline's shared clock (µs since base origin).
    fired = next(e for e in events
                 if e["name"] == "alert:serve_p99 firing")
    base = merged["otherData"]["wall_time_origin"]
    assert fired["ts"] == pytest.approx((1000.5 - base) * 1e6)
