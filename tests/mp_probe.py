"""Capability probe: can THIS box execute a multi-process CPU
collective?  (test_multiprocess.py's skip fixture.)

Forming the jax.distributed cluster is not the hard part — some jaxlib
CPU backends form it fine and then refuse to RUN a cross-process
computation ("Multiprocess computations aren't implemented on the CPU
backend").  The probe does the minimal end-to-end thing: join the
cluster, build the global mesh, and run one jitted psum across it.  It
uses only jax + the repo's version-compat shims (no loss code), so a
probe failure is an environment limit, never a framework bug — exactly
the distinction the skip fixture needs.

Usage: mp_probe.py <process_id> <num_processes> <port>
"""

import os
import sys


def main() -> int:
    proc_id, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from jax.sharding import PartitionSpec as P

    from npairloss_tpu.parallel import (
        data_parallel_mesh,
        initialize_distributed,
        process_local_batch,
        shard_map,
    )

    initialize_distributed(f"localhost:{port}", nproc, proc_id)
    assert jax.process_count() == nproc, jax.process_count()
    mesh = data_parallel_mesh()
    x = np.full((jax.local_device_count(),), float(proc_id + 1), np.float32)
    (gx,) = process_local_batch(mesh, (x,))
    out = jax.jit(
        shard_map(
            lambda v: jax.lax.psum(v.sum(), "dp")[None],
            mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"),
        )
    )(gx)
    got = float(np.asarray(out.addressable_shards[0].data)[0])
    want = sum(
        (p + 1) * jax.local_device_count() for p in range(nproc)
    )
    assert got == want, (got, want)
    sys.stdout.write("PROBE_OK\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
