"""Fleet observatory (npairloss_tpu/obs/fleet/ — docs/OBSERVABILITY.md
§Fleet observatory): rank-stamped telemetry, the rank-aware path
scheme, straggler/skew aggregation, the fleet-report validator's teeth,
merged cross-rank timelines, and the collective/comms reconciliation.

The synthetic 4-rank fixtures hand-craft streams (skew, a missing rank,
a torn tail line, a clock offset, dropped spans) so the OFFLINE reader
contract is pinned independently of any live run; the live write path
is covered by the single-host-mesh solver test here and the real
2-process run in test_multiprocess.py (capability-gated).
"""

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from npairloss_tpu.obs import (
    FLEET_KEYS,
    REQUIRED_KEYS,
    FleetStamp,
    RunTelemetry,
    SpanTracer,
    validate_chrome_trace,
)
from npairloss_tpu.obs.fleet import (
    build_fleet_report,
    merge_run_traces,
    validate_fleet_report,
)
from npairloss_tpu.obs.fleet import aggregate as agg
from npairloss_tpu.obs.fleet import comms as comms_mod
from npairloss_tpu.obs.fleet import stamp as stamp_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- stamp + path scheme ------------------------------------------------------


def test_stamp_keys_pin():
    # obs.sinks.FLEET_KEYS is the jax-free duplicate of STAMP_KEYS
    # (file-path loaders cannot import the package); this pin is what
    # lets them stay two literals.
    assert FLEET_KEYS == stamp_mod.STAMP_KEYS


def test_stamp_env_override_and_validation(monkeypatch):
    monkeypatch.setenv(stamp_mod.FLEET_PROCESS_ENV, "1/3")
    s = stamp_mod.fleet_stamp()
    assert (s.process_index, s.process_count) == (1, 3)
    monkeypatch.setenv(stamp_mod.FLEET_PROCESS_ENV, "junk")
    with pytest.raises(ValueError):
        stamp_mod.fleet_stamp()
    with pytest.raises(ValueError):
        FleetStamp(3, 3)  # rank out of range
    assert stamp_mod.resolve_fleet(None) is None
    assert stamp_mod.resolve_fleet(False) is None
    monkeypatch.delenv(stamp_mod.FLEET_PROCESS_ENV)
    # jax is imported under conftest: resolve_fleet(True) reads it.
    s = stamp_mod.resolve_fleet(True)
    assert s.process_count >= 1 and s.process_index == 0


def test_rank_path_scheme(tmp_path):
    assert stamp_mod.rank_metrics_name(3) == "telemetry.r3.jsonl"
    assert stamp_mod.rank_trace_name(0) == "trace.r0.json"
    assert stamp_mod.rank_of_file("telemetry.r12.jsonl") == 12
    assert stamp_mod.rank_of_file("metrics.jsonl") is None
    assert stamp_mod.rank_of_file("trace.json") is None
    for name in ("telemetry.r0.jsonl", "trace.r2.json", "manifest.r1.json",
                 "metrics.jsonl"):
        (tmp_path / name).write_text("{}\n")
    assert stamp_mod.discover_ranks(str(tmp_path)) == [0, 1, 2]


# -- RunTelemetry: fleet layout vs byte-identical parity ----------------------


def test_runtelemetry_fleet_layout_and_stamping(tmp_path):
    run = tmp_path / "run"
    for k in range(2):
        tel = RunTelemetry(str(run), fleet=FleetStamp(k, 2, (k,)))
        tel.write_manifest(config={"k": k})
        tel.log("train", 1, {"loss": 0.5})
        with tel.span("step/dispatch", batch=4, step=1):
            pass
        tel.close()
    names = sorted(os.listdir(run))
    assert names == [
        "manifest.r0.json", "manifest.r1.json",
        "telemetry.r0.jsonl", "telemetry.r1.jsonl",
        "trace.r0.json", "trace.r1.json",
    ]
    for k in range(2):
        rows = [json.loads(ln) for ln in
                (run / f"telemetry.r{k}.jsonl").read_text().splitlines()]
        for row in rows:
            for key in REQUIRED_KEYS + FLEET_KEYS:
                assert key in row, key
            assert row["process_index"] == k
            assert row["process_count"] == 2
            assert row["local_device_ids"] == [k]
        man = json.load(open(run / f"manifest.r{k}.json"))
        assert man["fleet"]["process_index"] == k
        trace = json.load(open(run / f"trace.r{k}.json"))
        assert trace["otherData"]["fleet"]["process_index"] == k


def test_runtelemetry_parity_without_fleet(tmp_path):
    """fleet=None keeps the pre-fleet contract bit-for-bit: legacy file
    names, rows carrying EXACTLY the envelope + metric keys (no rank
    stamps), no fleet block anywhere."""
    run = tmp_path / "run"
    tel = RunTelemetry(str(run))
    assert tel.fleet is None
    tel.write_manifest(config={})
    tel.log("train", 1, {"loss": 0.5})
    tel.close()
    assert sorted(os.listdir(run)) == [
        "manifest.json", "metrics.jsonl", "trace.json"]
    (row,) = [json.loads(ln) for ln in
              (run / "metrics.jsonl").read_text().splitlines()]
    assert sorted(row) == sorted(REQUIRED_KEYS + ("loss",))
    assert "fleet" not in json.load(open(run / "trace.json"))["otherData"]


# -- synthetic 4-rank fixture -------------------------------------------------

T0 = 1_700_000_000.0
STEP_S = 0.100
STRAGGLER = 2
LATE_S = 0.030
OFFSET_RANK = 3
OFFSET_S = 5.0  # rank 3's tracer origin is 5 s earlier (clock offset)


def _make_fleet_run(tmp_path, ranks=4, steps=6):
    """Hand-crafted fleet run dir: rank STRAGGLER dispatches LATE_S
    late every step; rank OFFSET_RANK's trace clock is OFFSET_S off
    (its ts values compensate, so ABSOLUTE times agree)."""
    run = tmp_path / "fleet"
    run.mkdir(exist_ok=True)
    for k in range(ranks):
        origin = T0 - (OFFSET_S if k == OFFSET_RANK else 0.0)
        late = LATE_S if k == STRAGGLER else 0.0
        events = []
        rows = []
        for s in range(1, steps + 1):
            abs_t = T0 + s * STEP_S + late
            events.append({
                "name": "step/dispatch", "ph": "X",
                "ts": (abs_t - origin) * 1e6, "dur": 500.0,
                "pid": 1000 + k, "tid": 1,
                "args": {"batch": 8, "step": s},
            })
            rows.append({
                "loss": 0.5 / s, "run_id": "fix", "step": s,
                "wall_time": abs_t + 0.001, "phase": "train",
                "process_index": k, "process_count": ranks,
                "local_device_ids": [k],
            })
        (run / f"telemetry.r{k}.jsonl").write_text(
            "".join(json.dumps(r) + "\n" for r in rows))
        (run / f"trace.r{k}.json").write_text(json.dumps({
            "traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"wall_time_origin": origin},
        }))
        (run / f"manifest.r{k}.json").write_text(json.dumps({
            "run_id": "fix", "created": origin,
            "fleet": {"process_index": k, "process_count": ranks,
                      "local_device_ids": [k]},
        }))
    return run


def test_fleet_report_skew_and_straggler(tmp_path):
    run = _make_fleet_run(tmp_path)
    report = build_fleet_report(str(run))
    assert validate_fleet_report(report) is None, report
    assert report["process_count"] == 4
    assert report["ranks_present"] == [0, 1, 2, 3]
    # Dispatch-start spread = the straggler's lateness.
    skew = report["skew"]
    assert skew["source"] == "dispatch_spans"
    assert skew["dispatch_spread_ms_p50"] == pytest.approx(
        LATE_S * 1e3, rel=1e-6)
    # Slowest-rank identity with full persistence.
    assert skew["slowest"]["rank"] == STRAGGLER
    assert skew["slowest"]["share"] == 1.0
    assert skew["slowest"]["persistence"] == skew["steps_analyzed"]
    # Victims wait for the straggler; the straggler itself does not.
    by_rank = {r["rank"]: r for r in report["ranks"]}
    assert by_rank[STRAGGLER]["barrier_wait_share"] == 0.0
    assert by_rank[0]["barrier_wait_share"] > 0.0
    assert by_rank[0]["ms_per_step_p50"] == pytest.approx(
        STEP_S * 1e3, rel=1e-6)
    # Per-rank step counts agree -> no disagreement note.
    assert not any("disagree" in n for n in report["notes"])


def test_fleet_report_missing_rank_fails_validator(tmp_path):
    run = _make_fleet_run(tmp_path)
    for name in os.listdir(run):
        if ".r3." in name:
            os.unlink(run / name)
    report = build_fleet_report(str(run))
    # Manifests/rows still declare a 4-process fleet: the validator
    # must refuse a 3-rank report claiming to cover it.
    assert report["process_count"] == 4
    err = validate_fleet_report(report)
    assert err is not None and "missing" in err
    assert any("missing rank" in n for n in report["notes"])


def test_fleet_report_torn_tail_counted_not_fatal(tmp_path):
    run = _make_fleet_run(tmp_path)
    with open(run / "telemetry.r1.jsonl", "a") as f:
        f.write('{"loss": 0.1, "step": 7, "phase": "tr')  # killed mid-write
    report = build_fleet_report(str(run))
    assert validate_fleet_report(report) is None
    by_rank = {r["rank"]: r for r in report["ranks"]}
    assert by_rank[1]["torn_lines"] == 1
    assert by_rank[1]["flagged"]
    assert by_rank[0]["torn_lines"] == 0


def test_fleet_report_dropped_spans_flagged_not_averaged(tmp_path):
    run = _make_fleet_run(tmp_path)
    trace = json.load(open(run / "trace.r0.json"))
    trace["otherData"]["dropped_events"] = 7
    (run / "trace.r0.json").write_text(json.dumps(trace))
    report = build_fleet_report(str(run))
    assert validate_fleet_report(report) is None
    by_rank = {r["rank"]: r for r in report["ranks"]}
    assert by_rank[0]["spans_dropped"] == 7
    assert by_rank[0]["flagged"]
    assert any("dropped spans" in n for n in report["notes"])
    # Validator teeth: a dropped-spans rank that is NOT flagged must be
    # rejected — that is the 'flagged, not averaged' contract.
    for r in report["ranks"]:
        r["flagged"] = False
    err = validate_fleet_report(report)
    assert err is not None and "flagged" in err


def test_fleet_report_step_count_disagreement_noted(tmp_path):
    run = _make_fleet_run(tmp_path)
    lines = (run / "telemetry.r2.jsonl").read_text().splitlines()
    (run / "telemetry.r2.jsonl").write_text(
        "\n".join(lines[:-2]) + "\n")  # rank 2 lost its last 2 steps
    report = build_fleet_report(str(run))
    assert any("disagree" in n for n in report["notes"])


def test_validator_teeth(tmp_path):
    run = _make_fleet_run(tmp_path)
    good = build_fleet_report(str(run))
    assert validate_fleet_report(good) is None
    assert validate_fleet_report([]) is not None
    bad = dict(good, schema="nope")
    assert "schema" in validate_fleet_report(bad)
    bad = dict(good, ranks=[])
    assert validate_fleet_report(bad) is not None
    bad = dict(good, ranks=[{k: v for k, v in good["ranks"][0].items()
                             if k != "spans_dropped"}])
    assert "spans_dropped" in validate_fleet_report(bad)
    bad = dict(good, skew={})
    assert validate_fleet_report(bad) is not None
    bad = dict(good)
    bad.pop("comms")
    assert "comms" in validate_fleet_report(bad)


# -- merged timelines ---------------------------------------------------------


def test_merge_traces_lanes_and_clock_offsets(tmp_path):
    run = _make_fleet_run(tmp_path)
    path, merged = merge_run_traces(str(run))
    assert path == str(run / "fleet_trace.json")
    assert validate_chrome_trace(merged) is None
    lanes = {e["pid"] for e in merged["traceEvents"]}
    assert lanes == {0, 1, 2, 3}
    # One process_name metadata event per rank lane.
    names = {e["pid"]: e["args"]["name"]
             for e in merged["traceEvents"] if e["name"] == "process_name"}
    assert names == {k: f"rank {k}" for k in range(4)}
    # Clock alignment: rank 3's origin was OFFSET_S earlier; after the
    # offset re-base, its step-1 dispatch lands at the same merged ts
    # as rank 1's (both dispatch on time).
    meta = merged["otherData"]
    assert meta["clock_offsets_us"]["3"] == 0.0
    assert meta["clock_offsets_us"]["0"] == pytest.approx(OFFSET_S * 1e6)
    t_of = {
        (e["pid"], e["args"]["step"]): e["ts"]
        for e in merged["traceEvents"]
        if e.get("name") == "step/dispatch"
    }
    assert t_of[(3, 1)] == pytest.approx(t_of[(1, 1)], abs=1.0)
    assert t_of[(STRAGGLER, 1)] - t_of[(1, 1)] == pytest.approx(
        LATE_S * 1e6, rel=1e-6)


def test_merge_traces_missing_trace_noted(tmp_path):
    run = _make_fleet_run(tmp_path)
    os.unlink(run / "trace.r2.json")
    path, merged = merge_run_traces(str(run))
    assert path is not None
    assert {e["pid"] for e in merged["traceEvents"]} == {0, 1, 3}
    assert any("rank 2" in n for n in merged["otherData"]["notes"])


# -- comms reconciliation -----------------------------------------------------


def _per_opcode_fixture():
    return {
        "all-gather": {"bytes": 4096.0, "count": 2.0,
                       "regions": {"npair/gather/comm/all_gather": 4096.0}},
        "all-reduce": {"bytes": 1024.0, "count": 1.0,
                       "regions": {"MLPEmbedding/dense0": 1024.0}},
    }


def test_comm_rows_claimed_vs_unattributed():
    # No claim for the unscoped all-reduce -> its bytes are unattributed.
    out = comms_mod.comm_rows_from_hlo(_per_opcode_fixture())
    kinds = {k["kind"]: k for k in out["kinds"]}
    assert kinds["all_gather"]["claimed"]
    assert kinds["all_gather"]["scope_coverage"] == 1.0
    assert not kinds["allreduce"]["claimed"]
    assert out["unattributed_bytes"] == 1024.0
    # The solver's grad-sync claim covers it -> zero unattributed.
    out = comms_mod.comm_rows_from_hlo(
        _per_opcode_fixture(),
        extra_claims=comms_mod.grad_sync_claim_bytes(1024.0, 2))
    kinds = {k["kind"]: k for k in out["kinds"]}
    assert kinds["allreduce"]["claimed"]
    assert kinds["allreduce"]["scope_coverage"] == 0.0
    assert out["unattributed_bytes"] == 0.0


def test_effective_bandwidth_ici_vs_dcn():
    rows = comms_mod.comm_rows_from_hlo(
        _per_opcode_fixture(),
        extra_claims={"allreduce": 1024.0})
    ici = comms_mod.effective_bandwidth(rows, 10.0, "TPU v4", "ici")
    dcn = comms_mod.effective_bandwidth(rows, 10.0, "TPU v4", "dcn")
    assert ici["peak_bytes_per_s"] == 300e9
    assert dcn["peak_bytes_per_s"] == 25e9
    k = {r["kind"]: r for r in ici["kinds"]}["all_gather"]
    assert k["effective_bytes_per_s"] == pytest.approx(4096.0 / 0.010)
    u_ici = {r["kind"]: r for r in ici["kinds"]}["all_gather"][
        "link_utilization"]
    u_dcn = {r["kind"]: r for r in dcn["kinds"]}["all_gather"][
        "link_utilization"]
    assert u_dcn == pytest.approx(u_ici * 12.0, rel=1e-6)
    # No step time -> no bandwidth fabricated.
    none = comms_mod.effective_bandwidth(rows, None, "cpu", "ici")
    assert all(r["effective_bytes_per_s"] is None for r in none["kinds"])


def test_interconnect_peak_specs():
    from npairloss_tpu.obs.perf.roofline import chip_peaks, interconnect_peak

    spec = chip_peaks("TPU v4")
    assert interconnect_peak(spec, "ici") == 300e9
    assert interconnect_peak(spec, "dcn") == 25e9
    with pytest.raises(ValueError):
        interconnect_peak(spec, "pcie")
    # Unknown kinds keep the flagged fallback with a DCN column too.
    fb = chip_peaks("cpu")
    assert not fb.known and fb.dcn_bytes_per_s > 0


_SYNTHETIC_HLO = """\
HloModule toy

%body (p: (s32[], f32[4,8], f32[4,8], f32[4,8], f32[4,8], f32[4,8], f32[4,8])) -> (s32[], f32[4,8], f32[4,8], f32[4,8], f32[4,8], f32[4,8], f32[4,8]) {
  %p = (s32[], f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, /*index=5*/f32[4,8]{1,0}, f32[4,8]{1,0}) parameter(0)
  %gte = f32[4,8]{1,0} get-tuple-element((s32[], f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, /*index=5*/f32[4,8]{1,0}, f32[4,8]{1,0}) %p), index=1
  %cp = f32[4,8]{1,0} collective-permute(f32[4,8]{1,0} %gte), source_target_pairs={{0,1},{1,0}}, metadata={op_name="jit(f)/comm/ppermute/ppermute"}
  ROOT %t = (s32[], f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, /*index=5*/f32[4,8]{1,0}, f32[4,8]{1,0}) tuple(%p)
}

%cond (p: (s32[], f32[4,8], f32[4,8], f32[4,8], f32[4,8], f32[4,8], f32[4,8])) -> pred[] {
  %p = (s32[], f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, /*index=5*/f32[4,8]{1,0}, f32[4,8]{1,0}) parameter(0)
  %iv = s32[] get-tuple-element((s32[], f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, /*index=5*/f32[4,8]{1,0}, f32[4,8]{1,0}) %p), index=0
  %n = s32[] constant(3)
  ROOT %lt = pred[] compare(s32[] %iv, s32[] %n), direction=LT
}

ENTRY %main (a: f32[4,8]) -> f32[8,8] {
  %a = f32[4,8]{1,0} parameter(0)
  %ag = f32[8,8]{1,0} all-gather(f32[4,8]{1,0} %a), dimensions={0}, metadata={op_name="jit(f)/npair/gather/comm/all_gather/all_gather"}
  %init = (s32[], f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, /*index=5*/f32[4,8]{1,0}, f32[4,8]{1,0}) tuple(%a)
  %w = (s32[], f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, /*index=5*/f32[4,8]{1,0}, f32[4,8]{1,0}) while((s32[], f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, f32[4,8]{1,0}, /*index=5*/f32[4,8]{1,0}, f32[4,8]{1,0}) %init), condition=%cond, body=%body
  ROOT %out = f32[8,8]{1,0} add(f32[8,8]{1,0} %ag, f32[8,8]{1,0} %ag)
}
"""


def test_collective_bytes_by_opcode_trips_and_big_tuple_while():
    """Pins the large-carry ``while`` parse: XLA comments tuple element
    indices past 4 (``/*index=5*/``), which the old =-excluding type
    charset failed on — the whole ring scan body then went unwalked
    and every collective-permute byte silently vanished."""
    from npairloss_tpu.obs.perf.hlo import collective_bytes_by_opcode

    out = collective_bytes_by_opcode(_SYNTHETIC_HLO)
    assert out["all-gather"]["bytes"] == 8 * 8 * 4
    assert out["all-gather"]["count"] == 1
    assert "comm/all_gather" in next(iter(out["all-gather"]["regions"]))
    # collective-permute inside the 3-trip while body: x3.
    assert out["collective-permute"]["count"] == 3
    assert out["collective-permute"]["bytes"] == 3 * 4 * 8 * 4
    assert all("comm/ppermute" in r
               for r in out["collective-permute"]["regions"])


# -- solver integration: spans_dropped + the single-host fleet path ----------


def _tiny_solver(**kw):
    from npairloss_tpu import MiningMethod, NPairLossConfig
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    cfg = kw.pop("cfg", None) or SolverConfig(
        base_lr=0.1, lr_policy="fixed", momentum=0.9, weight_decay=0.0,
        display=0, test_interval=0, snapshot=0,
    )
    loss_cfg = NPairLossConfig(
        margin_diff=-0.05,
        an_mining_method=MiningMethod.HARD,
        ap_mining_method=MiningMethod.RAND,
    )
    return Solver(get_model("mlp", hidden=(32,), embedding_dim=16),
                  loss_cfg, cfg, input_shape=(8,), **kw)


def test_solver_spans_dropped_in_window_rows(tmp_path):
    """Satellite: the tracer-cap drop counter must surface in the
    solver's display-window rows (the serve window rows' contract,
    uniform for training) — and stay ABSENT when nothing dropped."""
    from npairloss_tpu.data import synthetic_identity_batches
    from npairloss_tpu.train import SolverConfig

    run = tmp_path / "run"
    tel = RunTelemetry(str(run))
    tel.tracer = SpanTracer(max_events=2)  # force the cap immediately
    solver = _tiny_solver(telemetry=tel, cfg=SolverConfig(
        base_lr=0.1, lr_policy="fixed", momentum=0.9, weight_decay=0.0,
        display=2, test_interval=0, snapshot=0,
    ))
    batches = synthetic_identity_batches(8, 8, 2, (8,), noise=0.5)
    solver.train(batches, num_iters=4)
    tel.close()
    rows = [json.loads(ln) for ln in
            (run / "metrics.jsonl").read_text().splitlines()]
    display = [r for r in rows if r["phase"] == "train"
               and r["step"] % 2 == 0]
    off = [r for r in rows if r["phase"] == "train" and r["step"] % 2]
    assert all(r.get("spans_dropped", 0) > 0 for r in display), display
    assert all("spans_dropped" not in r for r in off)


@pytest.mark.parametrize("engine", ["dense"])
def test_solver_single_host_fleet_path(tmp_path, engine):
    """The whole fleet path exercisable today on the single-host mesh
    (the ISSUE's core promise): forced fleet stamping on a 2-device
    mesh leaves rank-stamped rows, step-numbered dispatch spans,
    per-step comm marks, and fleet_comms.json — and `build_fleet_report`
    over the run dir reconciles every collective byte."""
    import jax

    from npairloss_tpu.data import synthetic_identity_batches
    from npairloss_tpu.parallel import data_parallel_mesh

    run = tmp_path / "run"
    tel = RunTelemetry(str(run), fleet=True)
    assert tel.fleet is not None and tel.fleet.process_count == 1
    mesh = data_parallel_mesh(jax.devices()[:2])
    solver = _tiny_solver(telemetry=tel, mesh=mesh, engine=engine)
    batches = synthetic_identity_batches(8, 8, 2, (8,), noise=0.5)
    solver.train(batches, num_iters=3)
    tel.close()

    rows = [json.loads(ln) for ln in
            (run / "telemetry.r0.jsonl").read_text().splitlines()]
    assert all(r["process_index"] == 0 for r in rows)
    assert os.path.exists(run / "fleet_comms.json")
    trace = json.load(open(run / "trace.r0.json"))
    dispatches = [e for e in trace["traceEvents"]
                  if e["name"].startswith(("step/dispatch", "step/compile"))
                  and e.get("ph") == "X"]
    assert sorted(e["args"]["step"] for e in dispatches) == [1, 2, 3]
    marks = [e for e in trace["traceEvents"]
             if e["name"].startswith("comm/") and e.get("ph") == "i"]
    assert marks and all("bytes" in e["args"] for e in marks)

    report = build_fleet_report(str(run))
    assert validate_fleet_report(report) is None, report
    comms = report["comms"]
    assert comms["available"]
    assert comms["unattributed_bytes"] == 0, comms
    kinds = {k["kind"]: k for k in comms["kinds"]}
    assert kinds["all_gather"]["scope_coverage"] == 1.0
    assert all(k["claimed"] for k in comms["kinds"])
    assert report["skew"]["source"] == "dispatch_spans"


def test_solver_fleet_comms_captured_on_late_telemetry_attach(tmp_path):
    """Review-round pin: attaching fleet telemetry AFTER the step
    already compiled (a warmed solver, the mp harness) must still
    capture the collective pricing at the next dispatch — the capture
    is gated on first-dispatch-under-fleet, not on a recompile that
    will never come."""
    import jax

    from npairloss_tpu.data import synthetic_identity_batches
    from npairloss_tpu.parallel import data_parallel_mesh

    mesh = data_parallel_mesh(jax.devices()[:2])
    solver = _tiny_solver(mesh=mesh)
    batches = synthetic_identity_batches(8, 8, 2, (8,), noise=0.5)
    x, lab = next(batches)
    solver.step(x, lab)  # compiles WITHOUT telemetry

    run = tmp_path / "run"
    tel = RunTelemetry(str(run), fleet=True)
    solver.telemetry = tel
    solver.train(batches, num_iters=3, log_fn=lambda s: None)
    tel.close()
    assert os.path.exists(run / "fleet_comms.json")
    report = build_fleet_report(str(run))
    assert report["comms"]["available"]
    assert report["comms"]["unattributed_bytes"] == 0


def test_solver_fleet_comms_repriced_on_recompile(tmp_path):
    """Review-round pin: a new batch signature is a NEW program with
    new collective payloads — the comm marks after the recompile must
    carry the new program's bytes, not the first signature's."""
    import jax

    from npairloss_tpu.parallel import data_parallel_mesh

    run = tmp_path / "run"
    tel = RunTelemetry(str(run), fleet=True)
    mesh = data_parallel_mesh(jax.devices()[:2])
    solver = _tiny_solver(telemetry=tel, mesh=mesh)
    rng = np.random.default_rng(0)

    def batch(n):
        f = rng.standard_normal((n, 8)).astype(np.float32)
        l = np.repeat(np.arange(n // 2), 2).astype(np.int32)
        return f, l

    solver.step(*batch(16))
    big = list(solver._comm_kinds)
    solver.step(*batch(8))  # dynamic-batch tail: recompiles
    small = list(solver._comm_kinds)
    tel.close()
    big_b = {k: b for k, b, _ in big}
    small_b = {k: b for k, b, _ in small}
    assert big_b.keys() == small_b.keys()
    assert all(small_b[k] < big_b[k] for k in big_b), (big_b, small_b)
    # And the emitted marks follow: the last comm marks carry the
    # small program's bytes.
    trace = tel.tracer.to_chrome_trace()
    marks = [e for e in trace["traceEvents"]
             if e["name"].startswith("comm/") and e.get("ph") == "i"]
    last_by_kind = {e["name"]: e["args"]["bytes"] for e in marks}
    for kind, b in small_b.items():
        assert last_by_kind[f"comm/{kind}"] == b


def test_merge_traces_drops_malformed_events(tmp_path):
    """One rank's damaged trace (an 'X' event without dur) must not
    invalidate the merged fleet timeline — malformed events are
    dropped at merge, per the never-fatal contract."""
    run = _make_fleet_run(tmp_path)
    trace = json.load(open(run / "trace.r1.json"))
    trace["traceEvents"].append({"name": "broken", "ph": "X",
                                 "ts": 1.0, "pid": 9, "tid": 1})
    trace["traceEvents"].append({"ph": "i", "ts": 2.0})  # no name
    (run / "trace.r1.json").write_text(json.dumps(trace))
    _, merged = merge_run_traces(str(run))
    assert validate_chrome_trace(merged) is None
    assert not any(e.get("name") == "broken"
                   for e in merged["traceEvents"])


def test_solver_without_fleet_keeps_trace_and_stream_shape(tmp_path):
    """Parity pin: a non-fleet solver run must emit NO comm marks, NO
    step args on dispatch spans, NO fleet_comms.json — the pre-fleet
    artifacts exactly."""
    from npairloss_tpu.data import synthetic_identity_batches

    run = tmp_path / "run"
    tel = RunTelemetry(str(run))
    solver = _tiny_solver(telemetry=tel)
    batches = synthetic_identity_batches(8, 8, 2, (8,), noise=0.5)
    solver.train(batches, num_iters=2)
    tel.close()
    assert not os.path.exists(run / "fleet_comms.json")
    trace = json.load(open(run / "trace.json"))
    assert not any(e["name"].startswith("comm/")
                   for e in trace["traceEvents"])
    for e in trace["traceEvents"]:
        if e["name"] in ("step/dispatch", "step/compile"):
            assert "step" not in (e.get("args") or {}), e


# -- bench_check --fleet-report gate ------------------------------------------


def _load_bench_check():
    spec = importlib.util.spec_from_file_location(
        "_bench_check_fleet", os.path.join(REPO, "scripts",
                                           "bench_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_check_fleet_report_gate(tmp_path):
    bc = _load_bench_check()
    run = _make_fleet_run(tmp_path)
    report = build_fleet_report(str(run))
    good = tmp_path / "good.json"
    good.write_text(json.dumps(report))
    assert bc.check_fleet_report(str(good)) == []
    assert bc.main(["--fleet-report", str(good)]) == 0

    # Per-rank step counts disagreeing must be refused.
    bad = json.loads(good.read_text())
    bad["ranks"][2]["steps"] -= 2
    p = tmp_path / "bad_steps.json"
    p.write_text(json.dumps(bad))
    vio = bc.check_fleet_report(str(p))
    assert vio and "disagree" in vio[0]
    assert bc.main(["--fleet-report", str(p)]) == 1

    # Unattributed collective bytes must be refused.
    bad = json.loads(good.read_text())
    bad["comms"] = {"available": True, "kinds": [
        {"kind": "all_to_all", "bytes_per_step": 9.0, "claimed": False,
         "effective_bytes_per_s": None, "link_utilization": None}],
        "unattributed_bytes": 9.0}
    p = tmp_path / "bad_comms.json"
    p.write_text(json.dumps(bad))
    vio = bc.check_fleet_report(str(p))
    assert vio and "unattributed" in vio[0]

    # Schema-invalid is refused via the ONE contract.
    bad = json.loads(good.read_text())
    bad["schema"] = "nope"
    p = tmp_path / "bad_schema.json"
    p.write_text(json.dumps(bad))
    vio = bc.check_fleet_report(str(p))
    assert vio and "schema" in vio[0]

    # All-zero step counts AGREE but measured nothing — refused.
    bad = json.loads(good.read_text())
    for r in bad["ranks"]:
        r["steps"] = 0
    p = tmp_path / "bad_zero.json"
    p.write_text(json.dumps(bad))
    vio = bc.check_fleet_report(str(p))
    assert vio and "0 steps" in vio[0]
