"""Fault-tolerance tests (npairloss_tpu.resilience, docs/RESILIENCE.md),
driven through named failpoints so every fault is deterministic: atomic
snapshot commit + torn-snapshot validation, ``--resume auto`` skipping
corrupt snapshots, SIGTERM -> emergency snapshot -> resume at k+1,
retry/backoff schedule on a fake clock, divergence rollback, and
bounded prefetch-worker respawn.  All tier-1 fast (CPU, tiny MLPs)."""

import dataclasses
import json
import os
import random
import signal

import numpy as np
import pytest

from npairloss_tpu import NPairLossConfig
from npairloss_tpu.data import synthetic_identity_batches
from npairloss_tpu.models import get_model
from npairloss_tpu.resilience import (
    DivergenceConfig,
    DivergenceError,
    InjectedFault,
    PreemptionSignal,
    RetryPolicy,
    TrainingPreempted,
    call_with_retry,
    failpoints,
    list_snapshots,
    read_manifest,
    validate_snapshot,
)
from npairloss_tpu.resilience.snapshot import (
    SnapshotValidationError,
    TMP_MARKER,
)
from npairloss_tpu.train import Solver, SolverConfig


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


def _make_solver(tmp_path, snapshot=0, max_keep=0, **kw):
    cfg = SolverConfig(
        base_lr=0.5, lr_policy="fixed", momentum=0.9, weight_decay=0.0,
        display=0, test_interval=0, average_loss=10,
        snapshot=snapshot, snapshot_prefix=str(tmp_path / "snap" / "m_"),
        snapshot_max_keep=max_keep,
    )
    solver = Solver(
        get_model("mlp", hidden=(32,), embedding_dim=16),
        NPairLossConfig(), cfg, input_shape=(16,),
        snapshot_retry=RetryPolicy(base_delay=0.001, jitter=0.0),
        **kw,
    )
    return solver, synthetic_identity_batches(8, 8, 2, (16,), noise=0.5)


# -- retry/backoff schedule (fake clock) ---------------------------------


def test_retry_backoff_schedule_fake_clock():
    sleeps, events = [], []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise OSError(f"transient {calls['n']}")
        return "ok"

    policy = RetryPolicy(max_attempts=5, base_delay=1.0, max_delay=3.0,
                         multiplier=2.0, jitter=0.0)
    out = call_with_retry(
        flaky, policy, sleep=sleeps.append,
        on_retry=lambda a, d, e: events.append((a, d, str(e))),
    )
    assert out == "ok" and calls["n"] == 4
    # Exponential growth capped at max_delay: 1, 2, min(4, 3) = 3.
    assert sleeps == [1.0, 2.0, 3.0]
    assert [a for a, _, _ in events] == [1, 2, 3]


def test_retry_jitter_bounded_and_seeded():
    policy = RetryPolicy(max_attempts=2, base_delay=1.0, jitter=0.5)
    rng = random.Random(0)
    delays = [policy.delay(1, rng) for _ in range(100)]
    assert all(0.5 <= d <= 1.5 for d in delays)
    assert delays == [policy.delay(1, random.Random(0))
                      for _ in range(1)] + delays[1:]  # seeded = reproducible


def test_retry_exhausts_and_raises():
    sleeps = []
    with pytest.raises(OSError, match="always"):
        call_with_retry(
            lambda: (_ for _ in ()).throw(OSError("always")),
            RetryPolicy(max_attempts=3, base_delay=0.1, jitter=0.0),
            sleep=sleeps.append,
        )
    assert len(sleeps) == 2  # 3 attempts = 2 backoffs


def test_retry_does_not_catch_non_transient():
    with pytest.raises(ValueError):
        call_with_retry(
            lambda: (_ for _ in ()).throw(ValueError("logic bug")),
            RetryPolicy(max_attempts=5, base_delay=0.1),
            sleep=lambda d: pytest.fail("must not retry a ValueError"),
        )


# -- failpoints ----------------------------------------------------------


def test_failpoint_counts_and_context():
    assert not failpoints.should_fire("x")  # unarmed
    with failpoints.armed("x", times=2):
        assert failpoints.should_fire("x")
        assert failpoints.should_fire("x")
        assert not failpoints.should_fire("x")  # exhausted
    failpoints.arm("y", times=1)
    with pytest.raises(InjectedFault, match="failpoint 'y'"):
        failpoints.fire("y")
    failpoints.fire("y")  # disarmed after the count: no-op


def test_failpoints_env_parsing(monkeypatch):
    failpoints.reset()
    monkeypatch.setenv(failpoints.ENV_VAR, "a.b:2, c ,bad:oops")
    assert failpoints.should_fire("a.b")
    assert failpoints.should_fire("a.b")
    assert not failpoints.should_fire("a.b")
    assert failpoints.should_fire("c")  # bare name = once
    assert not failpoints.should_fire("bad")  # unparseable count ignored
    failpoints.reset()


# -- atomic snapshot commit + validation ---------------------------------


def test_atomic_commit_writes_manifest_and_no_tmp(tmp_path):
    solver, batches = _make_solver(tmp_path)
    x, lab = next(batches)
    solver.step(x, lab)
    path = solver.save_snapshot(1)
    manifest = validate_snapshot(path)
    assert manifest["step"] == 1
    assert manifest["arrays"]  # one record per state leaf
    rec = next(iter(manifest["arrays"].values()))
    assert set(rec) == {"crc32", "shape", "dtype"}
    # The commit renamed the tmp dir away — nothing uncommitted remains.
    assert not [n for n in os.listdir(tmp_path / "snap") if TMP_MARKER in n]


@pytest.mark.slow
def test_commit_crash_before_rename_is_invisible_to_resume(tmp_path):
    solver, batches = _make_solver(tmp_path)
    x, lab = next(batches)
    solver.step(x, lab)
    failpoints.arm("snapshot.commit.crash", times=1)
    with pytest.raises(InjectedFault):
        solver.save_snapshot(1)
    # Arrays hit disk but the rename never happened: no committed
    # snapshot exists, only a tmp dir the resume scan must ignore.
    assert not os.path.exists(solver.snapshot_path(1))
    assert [n for n in os.listdir(tmp_path / "snap") if TMP_MARKER in n]
    assert list_snapshots(solver.cfg.snapshot_prefix) == []
    solver2, _ = _make_solver(tmp_path)
    assert solver2.restore_auto() is None  # fresh start, no crash


@pytest.mark.slow
def test_transient_save_error_is_retried(tmp_path, caplog):
    solver, batches = _make_solver(tmp_path, snapshot=2)
    failpoints.arm("snapshot.save.io", times=1)
    with caplog.at_level("WARNING", logger="npairloss_tpu.resilience"):
        solver.train(batches, num_iters=3)
    # The injected fault was retried, the run completed, the snapshot
    # is valid.
    assert any("retrying" in r.message for r in caplog.records)
    assert validate_snapshot(solver.snapshot_path(2))["step"] == 2


def test_resume_auto_skips_torn_snapshot_with_reason(tmp_path, caplog):
    solver, batches = _make_solver(tmp_path)
    for k in (1, 2):
        x, lab = next(batches)
        solver.step(x, lab)
        solver.save_snapshot(k)
    # Corrupt the NEWEST snapshot's checksums (the injected torn commit
    # path produces exactly this shape of damage).
    newest = solver.snapshot_path(2)
    manifest = read_manifest(newest)
    next(iter(manifest["arrays"].values()))["crc32"] ^= 1
    with open(os.path.join(newest, "manifest.json"), "w") as f:
        json.dump(manifest, f)

    solver2, _ = _make_solver(tmp_path)
    with caplog.at_level("WARNING", logger="npairloss_tpu.solver"):
        restored = solver2.restore_auto()
    assert restored == solver.snapshot_path(1)
    assert solver2.iteration == 1
    skip = [r for r in caplog.records if "skipping snapshot" in r.message]
    assert skip and "checksum mismatch" in skip[0].message


@pytest.mark.slow
def test_injected_torn_commit_is_caught_by_validator(tmp_path):
    solver, batches = _make_solver(tmp_path)
    x, lab = next(batches)
    solver.step(x, lab)
    failpoints.arm("snapshot.commit.torn", times=1)
    path = solver.save_snapshot(1)
    # Structurally fine...
    validate_snapshot(path)
    # ...but the deep (restore-time) check must reject it.
    solver2, _ = _make_solver(tmp_path)
    with pytest.raises(SnapshotValidationError, match="checksum"):
        solver2.restore_snapshot(path)
    assert solver2.restore_auto() is None


@pytest.mark.slow
def test_manifest_less_snapshot_skipped_on_auto_but_loads_explicitly(
        tmp_path, caplog):
    """Pre-resilience snapshots (no manifest) are skipped by the
    validated auto scan but still restorable by explicit path — the
    migration contract."""
    solver, batches = _make_solver(tmp_path)
    x, lab = next(batches)
    solver.step(x, lab)
    path = solver.save_snapshot(1)
    os.remove(os.path.join(path, "manifest.json"))
    solver2, _ = _make_solver(tmp_path)
    with caplog.at_level("WARNING", logger="npairloss_tpu.solver"):
        assert solver2.restore_auto() is None
    assert any("no manifest" in r.message for r in caplog.records)
    solver3, _ = _make_solver(tmp_path)
    solver3.restore_snapshot(path)
    assert solver3.iteration == 1


@pytest.mark.slow
def test_explicit_restore_rejects_corrupt_manifest(tmp_path):
    """A manifest that EXISTS but is unparseable is corruption, not a
    legacy snapshot — explicit restore must refuse, not silently skip
    verification."""
    solver, batches = _make_solver(tmp_path)
    x, lab = next(batches)
    solver.step(x, lab)
    path = solver.save_snapshot(1)
    with open(os.path.join(path, "manifest.json"), "w") as f:
        f.write('{"format": "npairloss-snapsho')  # truncated mid-write
    solver2, _ = _make_solver(tmp_path)
    with pytest.raises(SnapshotValidationError, match="unreadable manifest"):
        solver2.restore_snapshot(path)


@pytest.mark.slow
def test_snapshot_retention_gc(tmp_path):
    solver, batches = _make_solver(tmp_path, snapshot=1, max_keep=2)
    solver.train(batches, num_iters=5)
    snaps = list_snapshots(solver.cfg.snapshot_prefix)
    assert [s for s, _ in snaps] == [4, 5]
    for _, p in snaps:
        validate_snapshot(p)


# -- graceful preemption -------------------------------------------------


class _SignalAt:
    """Batch iterator that SIGTERMs this process while producing batch
    ``fire_at`` — the in-process counterpart of `kill -TERM $pid` during
    a smoke train (the handler runs in the main thread before the next
    preemption poll)."""

    def __init__(self, batches, fire_at: int):
        self.batches = batches
        self.fire_at = fire_at
        self.count = 0

    def __iter__(self):
        return self

    def __next__(self):
        self.count += 1
        if self.count == self.fire_at:
            os.kill(os.getpid(), signal.SIGTERM)
        return next(self.batches)


def test_sigterm_emergency_snapshot_then_resume_at_k_plus_1(tmp_path):
    # Uninterrupted reference run: 6 iters, same seeds.
    ref, ref_batches = _make_solver(tmp_path / "ref")
    ref_final = ref.train(ref_batches, num_iters=6)

    solver, batches = _make_solver(tmp_path)
    with PreemptionSignal() as sig:
        solver.preempt = sig
        with pytest.raises(TrainingPreempted) as ei:
            solver.train(_SignalAt(batches, 4), num_iters=6)
    k = ei.value.step
    assert k == 4
    # The emergency snapshot is committed and manifest-valid at k.
    assert validate_snapshot(ei.value.snapshot_path)["step"] == k

    # Relaunch with --resume auto semantics: restore, continue at k+1.
    solver2, batches2 = _make_solver(tmp_path)
    assert solver2.restore_auto() == ei.value.snapshot_path
    assert solver2.iteration == k
    logs = []
    final = solver2.train(batches2, num_iters=6, log_fn=logs.append)
    assert any("resuming from iteration 4" in line for line in logs)
    assert solver2.iteration == 6
    # Metric keys byte-identical to the uninterrupted run's.
    assert sorted(final) == sorted(ref_final)


def test_preemption_signal_programmatic_and_exit_code():
    from npairloss_tpu.resilience import EXIT_PREEMPTED

    assert EXIT_PREEMPTED == 75  # the documented supervisor contract
    sig = PreemptionSignal()
    assert not sig.requested
    sig.request(signal.SIGTERM)
    assert sig.requested and sig.signum == signal.SIGTERM


# -- divergence guard ----------------------------------------------------


def test_divergence_rollback_restores_and_scales_lr(tmp_path):
    solver, batches = _make_solver(
        tmp_path, snapshot=2,
        divergence=DivergenceConfig(patience=2, action="rollback",
                                    lr_scale=0.5, max_rollbacks=1),
    )
    # Snapshots land at 2 and 4; NaNs at steps 5 and 6 trip the guard.
    # The rollback window excludes snapshot@4 (the step-4 update is
    # implicated by the first NaN at 5), so the target is 2.
    def arm_after(batches):
        for i, b in enumerate(batches):
            if i == 4:
                failpoints.arm("step.nan_loss", times=2)
            yield b

    logs = []
    final = solver.train(arm_after(batches), num_iters=8, log_fn=logs.append)
    assert any("rolled back to iteration 2" in line for line in logs)
    assert solver.iteration == 8  # recovered and finished
    assert solver.cfg.base_lr == pytest.approx(0.25)  # 0.5 * lr_scale
    assert np.isfinite(final["loss"])


@pytest.mark.slow
def test_divergence_rollback_skips_snapshots_inside_nan_streak(tmp_path):
    """A snapshot committed while the loss was already non-finite (or by
    the update that produced the first NaN) is a poisoned rollback
    target — the guard must restore an older one even though the newer
    ones are checksum-valid."""
    solver, batches = _make_solver(
        tmp_path, snapshot=1,
        divergence=DivergenceConfig(patience=3, action="rollback",
                                    max_rollbacks=1),
    )
    def arm_after(batches):
        for i, b in enumerate(batches):
            if i == 2:
                failpoints.arm("step.nan_loss", times=3)
            yield b

    logs = []
    solver.train(arm_after(batches), num_iters=6, log_fn=logs.append)
    # NaNs at 3,4,5; snapshots 3 and 4 were committed mid-streak and 2
    # is implicated by the first NaN — rollback landed on 1, and the
    # suspect snapshots were quarantined out of the resume namespace
    # (then swept by GC as retraining re-committed those steps).
    assert any("rolled back to iteration 1" in line for line in logs)
    assert solver.iteration == 6
    assert [s for s, _ in list_snapshots(solver.cfg.snapshot_prefix)] == \
        [1, 2, 3, 4, 5, 6]  # all re-committed post-rollback


@pytest.mark.slow
def test_quarantine_hides_suspect_snapshots_and_gc_sweeps(tmp_path):
    """Quarantined snapshots leave the resume namespace immediately (a
    later --resume auto must not restore NaN-era params) and are
    reclaimed by GC regardless of the retention setting."""
    from npairloss_tpu.resilience import gc_snapshots, quarantine_snapshots
    from npairloss_tpu.resilience.snapshot import QUARANTINE_SUFFIX

    solver, batches = _make_solver(tmp_path, snapshot=1)
    solver.train(batches, num_iters=3)
    prefix = solver.cfg.snapshot_prefix
    assert [s for s, _ in list_snapshots(prefix)] == [1, 2, 3]
    moved = quarantine_snapshots(prefix, min_step=1)
    assert len(moved) == 2 and all(
        p.endswith(QUARANTINE_SUFFIX) for p in moved)
    assert [s for s, _ in list_snapshots(prefix)] == [1]
    solver2, _ = _make_solver(tmp_path)
    assert solver2.restore_auto() == solver.snapshot_path(1)
    # GC sweeps quarantined dirs even with max_keep=0 (keep-all).
    swept = gc_snapshots(prefix, 0)
    assert sorted(swept) == sorted(moved)
    assert not [n for n in os.listdir(tmp_path / "snap")
                if n.endswith(QUARANTINE_SUFFIX)]


def test_divergence_halt_raises(tmp_path):
    solver, batches = _make_solver(
        tmp_path,
        divergence=DivergenceConfig(patience=2, action="halt"),
    )
    failpoints.arm("step.nan_loss", times=2)
    with pytest.raises(DivergenceError, match="2 consecutive non-finite"):
        solver.train(batches, num_iters=6)


@pytest.mark.slow
def test_divergence_rollback_budget_exhausted_halts(tmp_path):
    solver, batches = _make_solver(
        tmp_path, snapshot=1,
        divergence=DivergenceConfig(patience=1, action="rollback",
                                    max_rollbacks=1),
    )
    def arm_after(batches):
        for i, b in enumerate(batches):
            if i == 2:
                failpoints.arm("step.nan_loss", times=None)  # forever
            yield b

    with pytest.raises(DivergenceError, match="budget"):
        solver.train(arm_after(batches), num_iters=6)


@pytest.mark.slow
def test_divergence_without_snapshot_halts_with_reason(tmp_path):
    solver, batches = _make_solver(
        tmp_path,
        divergence=DivergenceConfig(patience=1, action="rollback"),
    )
    failpoints.arm("step.nan_loss", times=1)
    with pytest.raises(DivergenceError, match="no valid snapshot"):
        solver.train(batches, num_iters=4)


def test_divergence_config_validation():
    with pytest.raises(ValueError):
        DivergenceConfig(patience=0)
    with pytest.raises(ValueError):
        DivergenceConfig(action="panic")
    with pytest.raises(ValueError):
        DivergenceConfig(lr_scale=0.0)


# -- prefetch-worker respawn ---------------------------------------------


def _tiny_loader(max_worker_restarts=3):
    from npairloss_tpu.config.schema import DataLayerConfig
    from npairloss_tpu.data import ArrayDataset, MultibatchLoader

    rng = np.random.default_rng(0)
    images = rng.standard_normal((32, 4, 4, 3)).astype(np.float32)
    labels = np.repeat(np.arange(8), 4)
    cfg = DataLayerConfig(identity_num_per_batch=4, img_num_per_identity=2)
    return MultibatchLoader(
        ArrayDataset(images, labels), cfg,
        max_worker_restarts=max_worker_restarts,
    )


def test_worker_crash_respawns_within_budget(caplog):
    failpoints.arm("data.worker", times=2)
    with _tiny_loader() as loader:
        with caplog.at_level("WARNING", logger="npairloss_tpu.data"):
            for _ in range(4):
                images, labels = next(loader)
        assert images.shape == (8, 4, 4, 3)
        # The budget bounds CONSECUTIVE failures: a delivered batch
        # resets it, so sparse transient errors over a long run never
        # accumulate into an abort.
        assert loader._respawns == 0
    respawn = [r for r in caplog.records if "respawning" in r.message]
    assert len(respawn) == 2 and "died at batch 0" in respawn[0].message


def test_worker_crash_beyond_budget_raises_with_context():
    from npairloss_tpu.data import PrefetchWorkerError

    failpoints.arm("data.worker", times=None)
    with _tiny_loader(max_worker_restarts=1) as loader:
        with pytest.raises(PrefetchWorkerError,
                           match=r"batch 0 after 1 respawns.*InjectedFault"):
            next(loader)


# -- solver exit paths ---------------------------------------------------


@pytest.mark.slow
def test_checkpointer_drained_on_exception_exit(tmp_path, monkeypatch):
    """wait_until_finished must run on the exception exit path too —
    the in-flight Orbax save lands even when a later step raises."""
    solver, batches = _make_solver(
        tmp_path,
        divergence=DivergenceConfig(patience=1, action="halt"),
    )
    drained = []

    def fail_after(batches):
        for i, b in enumerate(batches):
            if i == 1:
                failpoints.arm("step.nan_loss", times=1)
            yield b

    solver.init(np.zeros((2, 16), np.float32))
    ckpt = solver._ckpt()
    orig = ckpt.wait_until_finished
    monkeypatch.setattr(
        ckpt, "wait_until_finished",
        lambda: (drained.append(True), orig())[1],
    )
    with pytest.raises(DivergenceError):
        solver.train(fail_after(batches), num_iters=6)
    assert drained  # the finally block drained the checkpointer


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_solver(tmp_path, max_iter=4, snapshot=2):
    solver = tmp_path / "solver.prototxt"
    solver.write_text(
        'net: "examples/tiny_net.prototxt"\nbase_lr: 0.05\n'
        'lr_policy: "fixed"\nmomentum: 0.9\n'
        f'max_iter: {max_iter}\ndisplay: 0\ntest_interval: 0\n'
        f'test_iter: 0\nsnapshot: {snapshot}\n'
        f'snapshot_prefix: "{tmp_path}/m_"\n'
    )
    return str(solver)


@pytest.mark.slow
def test_cli_resume_auto_fresh_then_restore(tmp_path, monkeypatch):
    """The supervisor contract: the SAME command line works for the
    first launch (fresh start) and the relaunch (restore + continue),
    with an injected transient save fault retried along the way."""
    from npairloss_tpu.cli import main

    monkeypatch.chdir(_REPO)
    solver = _write_solver(tmp_path, max_iter=4, snapshot=2)
    failpoints.arm("snapshot.save.io", times=1)
    rc = main(["train", "--solver", solver, "--model", "mlp",
               "--synthetic", "--resume", "auto"])
    assert rc == 0
    snaps = list_snapshots(f"{tmp_path}/m_")
    assert [s for s, _ in snaps] == [2, 4]
    # Relaunch, same flags + a higher target: restores 4, runs to 6.
    rc = main(["train", "--solver", solver, "--model", "mlp",
               "--synthetic", "--resume", "auto", "--max_iter", "6"])
    assert rc == 0
    assert [s for s, _ in list_snapshots(f"{tmp_path}/m_")] == [2, 4, 6]


@pytest.mark.slow
def test_cli_snapshot_keep_and_divergence_flags(tmp_path, monkeypatch):
    from npairloss_tpu.cli import main

    monkeypatch.chdir(_REPO)
    solver = _write_solver(tmp_path, max_iter=6, snapshot=2)
    rc = main(["train", "--solver", solver, "--model", "mlp",
               "--synthetic", "--snapshot-keep", "2"])
    assert rc == 0
    assert [s for s, _ in list_snapshots(f"{tmp_path}/m_")] == [4, 6]
    # Divergence halt surfaces as a clean error exit, not a traceback.
    failpoints.arm("step.nan_loss", times=2)
    rc = main(["train", "--solver", solver, "--model", "mlp",
               "--synthetic", "--max_iter", "8",
               "--divergence-patience", "2",
               "--divergence-action", "halt"])
    assert rc == 1


@pytest.mark.slow
def test_telemetry_events_emitted_for_retry_and_rollback(tmp_path):
    from npairloss_tpu.obs import RunTelemetry

    tel = RunTelemetry(str(tmp_path / "run"), trace=False)
    solver, batches = _make_solver(
        tmp_path, snapshot=2,
        divergence=DivergenceConfig(patience=1, action="rollback",
                                    max_rollbacks=1),
        telemetry=tel,
    )
    def arm_after(batches):
        for i, b in enumerate(batches):
            if i == 3:
                failpoints.arm("step.nan_loss", times=1)
                failpoints.arm("snapshot.save.io", times=1)
            yield b

    solver.train(arm_after(batches), num_iters=6)
    tel.close()
    events = [r for r in tel.ring.records() if r.get("phase") == "event"]
    kinds = [r["event"] for r in events]
    assert "retry" in kinds      # the injected save fault was retried
    assert "rollback" in kinds   # the NaN step rolled back
    rb = next(r for r in events if r["event"] == "rollback")
    assert rb["to_iteration"] == 2
