"""Durable gallery ingest: WAL unit tests, the crash-point matrix, the
artifact validator's tamper suite, the retry-policy satellites, and the
slow-marked multi-SIGKILL disaster drill (docs/RESILIENCE.md §9).

The crash-point matrix abandons live ``WriteAheadLog`` instances
without ``close()`` (the SIGKILL analogue for in-process tests) or
crashes them mid-operation through the §6 failpoints, then reopens the
directory and asserts the exactly-once contract: every record whose
``wait_durable`` returned (the ack barrier) is replayed exactly once
above the watermark, torn tails are truncated loudly, and unacked
records may vanish but never corrupt.
"""

import base64
import json
import os
import shutil
import signal
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from npairloss_tpu.resilience import failpoints
from npairloss_tpu.resilience.retrying import RetryPolicy, named_policy
from npairloss_tpu.resilience.wal import (
    MANIFEST_NAME,
    WAL_FORMAT,
    WalCorruptionError,
    WalError,
    WriteAheadLog,
    load_wal_manifest,
    validate_wal_dir,
    validate_wal_manifest,
    wal_info,
)

_HEADER = struct.Struct("<II")


def _add(i, rows=2, dim=4):
    """A well-formed ``kind: "add"`` record body (seq is assigned by
    ``append``); the emb bytes are deterministic per ``i``."""
    raw = np.full(rows * dim, float(i), np.float32).tobytes()
    return {"kind": "add", "ids": [1000 + 10 * i + j for j in range(rows)],
            "labels": [7] * rows, "dim": dim,
            "emb": base64.b64encode(raw).decode("ascii")}


def _replayed(path, after_seq=0):
    wal = WriteAheadLog(str(path))
    try:
        return [rec["seq"] for rec in wal.replay(after_seq=after_seq)]
    finally:
        wal.close()


# -- unit: append / replay / rotation / GC -----------------------------------


def test_append_assigns_contiguous_seqs_and_replays(tmp_path):
    with WriteAheadLog(str(tmp_path / "wal")) as wal:
        seqs = [wal.append(_add(i)) for i in range(5)]
        assert seqs == [1, 2, 3, 4, 5]
        wal.wait_durable(5)
        assert [r["seq"] for r in wal.replay()] == [1, 2, 3, 4, 5]
        # The watermark contract: records at or below are skipped.
        assert [r["seq"] for r in wal.replay(after_seq=3)] == [4, 5]
        stats = wal.stats()
        assert stats["last_seq"] == 5 and stats["durable_seq"] == 5
        assert stats["torn_records"] == 0
    assert validate_wal_dir(str(tmp_path / "wal")) is None


def test_reopen_resumes_sequence(tmp_path):
    path = tmp_path / "wal"
    with WriteAheadLog(str(path)) as wal:
        for i in range(3):
            wal.append(_add(i))
    with WriteAheadLog(str(path)) as wal:
        assert wal.last_seq == 3
        assert wal.append(_add(3)) == 4
        assert [r["seq"] for r in wal.replay()] == [1, 2, 3, 4]


def test_rotation_seals_segments_and_gc_respects_watermark(tmp_path):
    path = tmp_path / "wal"
    with WriteAheadLog(str(path), segment_max_bytes=200) as wal:
        for i in range(8):
            wal.append(_add(i))
        stats = wal.stats()
        assert stats["segments"] > 1
        assert stats["sealed_segments"] == stats["segments"] - 1
        sealed = load_wal_manifest(str(path))["sealed"]
        assert validate_wal_manifest(load_wal_manifest(str(path))) is None
        # A watermark below every sealed last_seq removes nothing ...
        assert wal.gc(0) == 0
        # ... and one covering some sealed segments removes exactly
        # those, never the active segment.
        cover = min(s["last_seq"] for s in sealed.values())
        assert wal.gc(cover) >= 1
        assert [r["seq"] for r in wal.replay(after_seq=cover)] == \
            list(range(cover + 1, 9))
    assert validate_wal_dir(str(path)) is None
    info = wal_info(str(path))
    assert info["last_seq"] == 8 and info["first_seq"] > 1


def test_group_commit_flusher_makes_appends_durable(tmp_path):
    with WriteAheadLog(str(tmp_path / "wal"),
                       flush_interval_s=0.02) as wal:
        seq = wal.append(_add(0))
        wal.wait_durable(seq, timeout=10.0)
        assert wal.durable_seq >= seq


def test_bad_payload_and_closed_log_are_loud(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "wal"))
    with pytest.raises(WalError, match="ids/labels"):
        wal.append({"kind": "add", "ids": [], "labels": [],
                    "dim": 4, "emb": "AA=="})
    wal.close()
    with pytest.raises(WalError, match="closed"):
        wal.append(_add(0))


# -- the crash-point matrix ---------------------------------------------------


def test_crash_before_ack_loses_only_the_unacked_record(tmp_path):
    """Mid-record crash (``wal.append.torn``): the torn, never-acked
    record is truncated LOUDLY; every previously acked record replays
    exactly once and the sequence continues with no gap."""
    path = tmp_path / "wal"
    wal = WriteAheadLog(str(path))
    for i in range(3):
        wal.wait_durable(wal.append(_add(i)))
    with failpoints.armed("wal.append.torn"):
        with pytest.raises(failpoints.InjectedFault):
            wal.append(_add(3))
    # No close: the process is "gone".  Reopen recovers.
    wal2 = WriteAheadLog(str(path))
    try:
        assert wal2.torn_records == 1 and wal2.torn_bytes > 0
        assert [r["seq"] for r in wal2.replay()] == [1, 2, 3]
        # The torn seq was never burned: the next append reuses it.
        assert wal2.append(_add(3)) == 4
    finally:
        wal2.close()


def test_crash_after_ack_pre_flush_keeps_the_acked_record(tmp_path):
    """With a long group-commit window the fsync has NOT happened when
    append returns — but ``wait_durable`` (the ack barrier) forces it.
    A crash right after the ack must not lose the record."""
    path = tmp_path / "wal"
    wal = WriteAheadLog(str(path), flush_interval_s=60.0)
    seq = wal.append(_add(0))
    wal.flush()            # the covering group-commit fsync
    wal.wait_durable(seq)  # ack barrier returned => record is durable
    # SIGKILL analogue: abandon the instance without close/flush.
    assert _replayed(path) == [1]


def test_crash_during_rotation_recovers_unsealed_tail(tmp_path):
    """``wal.rotate.crash`` dies after the finished segment's fsync but
    before its seal reaches the manifest: recovery must treat it as the
    clean unsealed tail and keep appending — acked records intact."""
    path = tmp_path / "wal"
    wal = WriteAheadLog(str(path), segment_max_bytes=200)
    acked = []
    with failpoints.armed("wal.rotate.crash"):
        for i in range(12):
            try:
                seq = wal.append(_add(i))
            except failpoints.InjectedFault:
                break
            wal.wait_durable(seq)
            acked.append(seq)
        else:
            pytest.fail("segment never rotated — raise the record size")
    wal2 = WriteAheadLog(str(path), segment_max_bytes=200)
    try:
        assert [r["seq"] for r in wal2.replay()] == acked
        nxt = wal2.append(_add(99))
        assert nxt == acked[-1] + 1
        assert validate_wal_dir(str(path)) is None
    finally:
        wal2.close()


def test_crash_during_gc_drops_stale_seal_on_recovery(tmp_path):
    """``wal.gc.crash`` dies after a covered segment is unlinked but
    before the manifest rewrite: the manifest carries a seal for a
    missing segment.  Recovery drops the stale seal (it is only
    explainable as that crash) and replay above the watermark is
    unaffected."""
    path = tmp_path / "wal"
    wal = WriteAheadLog(str(path), segment_max_bytes=200)
    for i in range(8):
        wal.wait_durable(wal.append(_add(i)))
    sealed = load_wal_manifest(str(path))["sealed"]
    assert sealed, "need at least one sealed segment for GC"
    cover = min(s["last_seq"] for s in sealed.values())
    with failpoints.armed("wal.gc.crash"):
        with pytest.raises(failpoints.InjectedFault):
            wal.gc(cover)
    # The unlinked segment is gone but its seal survived the crash.
    manifest = load_wal_manifest(str(path))
    present = set(os.listdir(str(path)))
    assert any(name not in present for name in manifest["sealed"])
    wal2 = WriteAheadLog(str(path), segment_max_bytes=200)
    try:
        assert [r["seq"] for r in wal2.replay(after_seq=cover)] == \
            list(range(cover + 1, 9))
        # Recovery rewrote the manifest without the stale seal.
        survivors = load_wal_manifest(str(path))["sealed"]
        assert all(name in os.listdir(str(path)) for name in survivors)
    finally:
        wal2.close()
    assert validate_wal_dir(str(path)) is None


def test_replay_is_exactly_once_across_repeated_recoveries(tmp_path):
    """Reopen + replay is idempotent: recovering twice (crash during
    the first recovery's replay apply) never duplicates a record."""
    path = tmp_path / "wal"
    with WriteAheadLog(str(path)) as wal:
        for i in range(4):
            wal.wait_durable(wal.append(_add(i)))
    assert _replayed(path, after_seq=2) == [3, 4]
    assert _replayed(path, after_seq=2) == [3, 4]  # second cold start
    assert _replayed(path, after_seq=4) == []      # watermark caught up


# -- validator / tamper -------------------------------------------------------


def test_validate_refuses_truncated_then_patched_copy(tmp_path):
    """The ci.sh tamper: truncate the final segment at a record
    boundary (structurally valid — recovery would accept it) — the
    acknowledged watermark is what refuses it."""
    path = tmp_path / "wal"
    with WriteAheadLog(str(path)) as wal:
        for i in range(3):
            wal.wait_durable(wal.append(_add(i)))
    copy = tmp_path / "tampered"
    shutil.copytree(str(path), str(copy))
    seg = [n for n in os.listdir(str(copy)) if n.endswith(".seg")]
    assert len(seg) == 1
    seg_path = os.path.join(str(copy), seg[0])
    blob = open(seg_path, "rb").read()
    off = 0
    for _ in range(2):  # keep 2 of 3 records
        length, _crc = _HEADER.unpack_from(blob, off)
        off += _HEADER.size + length
    with open(seg_path, "r+b") as f:
        f.truncate(off)
    # Structurally the copy is a fine WAL ...
    assert validate_wal_dir(str(copy)) is None
    # ... but the operator acked seq 3: refused.
    err = validate_wal_dir(str(copy), min_last_seq=3)
    assert err is not None and "acknowledged watermark" in err
    assert validate_wal_dir(str(path), min_last_seq=3) is None


def test_validate_refuses_doctored_manifest_and_content(tmp_path):
    path = tmp_path / "wal"
    with WriteAheadLog(str(path), segment_max_bytes=200) as wal:
        for i in range(8):
            wal.append(_add(i))
    manifest = load_wal_manifest(str(path))
    assert manifest["format"] == WAL_FORMAT
    sealed_name = sorted(manifest["sealed"])[0]

    # Wrong format tag.
    doctored = dict(manifest, format="npairloss-wal-v0")
    mpath = os.path.join(str(path), MANIFEST_NAME)
    open(mpath, "w").write(json.dumps(doctored))
    assert "format" in validate_wal_dir(str(path))

    # Sealed CRC that disagrees with the bytes.
    doctored = json.loads(json.dumps(manifest))
    doctored["sealed"][sealed_name]["crc32"] ^= 1
    open(mpath, "w").write(json.dumps(doctored))
    assert "CRC" in validate_wal_dir(str(path))

    # Flipped byte inside a SEALED segment: corruption, not a torn
    # tail — refused by the validator AND by recovery.
    open(mpath, "w").write(json.dumps(manifest))
    assert validate_wal_dir(str(path)) is None
    seg_path = os.path.join(str(path), sealed_name)
    blob = bytearray(open(seg_path, "rb").read())
    blob[_HEADER.size + 1] ^= 0xFF
    open(seg_path, "wb").write(bytes(blob))
    err = validate_wal_dir(str(path))
    assert err is not None and sealed_name in err
    with pytest.raises(WalCorruptionError):
        WriteAheadLog(str(path), segment_max_bytes=200)


def test_wal_info_reports_torn_tail_without_mutating(tmp_path):
    path = tmp_path / "wal"
    with WriteAheadLog(str(path)) as wal:
        for i in range(3):
            wal.append(_add(i))
    seg = [n for n in os.listdir(str(path)) if n.endswith(".seg")][0]
    seg_path = os.path.join(str(path), seg)
    size = os.path.getsize(seg_path)
    with open(seg_path, "r+b") as f:
        f.truncate(size - 3)  # torn mid-payload
    info = wal_info(str(path))
    assert info["torn_tail"] and info["torn_bytes"] > 0
    assert info["last_seq"] == 2
    # A torn tail is a crash artifact: the validator passes ...
    assert validate_wal_dir(str(path)) is None
    # ... unless the torn record was acknowledged.
    assert "acknowledged watermark" in validate_wal_dir(
        str(path), min_last_seq=3)
    # wal_info did not repair anything.
    assert os.path.getsize(seg_path) == size - 3


# -- satellites: retry policies and the snapshot dir-fsync pin ---------------


def test_jitter_cap_bounds_absolute_jitter():
    policy = RetryPolicy(max_attempts=3, base_delay=10.0, max_delay=100.0,
                         multiplier=1.0, jitter=0.5, jitter_cap_s=0.1)

    class _Rng:
        def random(self):
            return 1.0  # worst-case draw

    # Uncapped jitter would add 5.0s; the cap bounds it to 0.1s.
    assert policy.delay(1, rng=_Rng()) == pytest.approx(10.1)
    uncapped = RetryPolicy(max_attempts=3, base_delay=10.0,
                           max_delay=100.0, multiplier=1.0, jitter=0.5)
    assert uncapped.delay(1, rng=_Rng()) == pytest.approx(15.0)
    with pytest.raises(ValueError, match="jitter_cap_s"):
        RetryPolicy(jitter_cap_s=-1.0)


def test_named_retry_policies_registered():
    for name in ("wal_replay", "wal_segment_open"):
        policy = named_policy(name)
        assert isinstance(policy, RetryPolicy)
        assert policy.jitter_cap_s is not None
    with pytest.raises(KeyError, match="wal_replay"):
        named_policy("no_such_policy")


def test_snapshot_dirsync_failpoint_sits_after_the_rename(tmp_path):
    """The §1 commit's durability hole: ``snapshot.commit.dirsync``
    fires AFTER ``os.replace`` lands the manifest but BEFORE the parent
    dir fsync — so the pin proves the rename happened (the manifest is
    at its final name) while the directory entry was never synced."""
    from npairloss_tpu.resilience import snapshot as snap
    d = tmp_path / "snap"
    d.mkdir()
    with failpoints.armed("snapshot.commit.dirsync"):
        with pytest.raises(failpoints.InjectedFault):
            snap.write_manifest(str(d), step=1, checksums={})
    final = os.path.join(str(d), snap.MANIFEST_NAME)
    assert os.path.exists(final)          # rename already landed
    assert not os.path.exists(final + ".part")
    manifest = json.load(open(final))
    assert manifest["step"] == 1


# -- the disaster drill: >= 5 SIGKILLs against a real serving process --------


def _read_acks(stream, acks, stop):
    for line in iter(stream.readline, b""):
        if stop.is_set():
            break
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and isinstance(rec.get("ingested"), int):
            acks.append(rec)


@pytest.mark.slow
def test_sigkill_drill_zero_acked_loss(tmp_path):
    """Five scripted SIGKILLs at randomized seeded offsets against a
    real ``serve --wal-dir`` subprocess: every acknowledged vector
    survives into the final artifact exactly once (docs/RESILIENCE.md
    §9; the ci.sh smoke runs the single-kill version)."""
    from npairloss_tpu.serve import GalleryIndex
    from npairloss_tpu.serve.index import load_newest

    rng = np.random.default_rng(1234)
    dim, kills = 16, 5
    base = rng.normal(size=(32, dim)).astype(np.float32)
    idx_dir = tmp_path / "idx"
    idx_dir.mkdir()
    GalleryIndex.build(base, np.arange(32, dtype=np.int32) % 4).save(
        str(idx_dir / "g_0000.gidx"))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "npairloss_tpu", "serve",
           "--index-prefix", str(idx_dir / "g_"),
           "--wal-dir", str(tmp_path / "wal"),
           "--wal-flush-ms", "2", "--wal-checkpoint-every", "3",
           "--top-k", "5", "--buckets", "1,8"]
    acked = {}     # rid -> ids sent in that batch
    sent = {}      # rid -> (ids, emb) for every batch ever sent
    batch_no = 0

    def _batch():
        nonlocal batch_no
        b = batch_no
        batch_no += 1
        ids = [100000 + 10 * b + j for j in range(2)]
        emb = rng.normal(size=(2, dim)).astype(np.float32)
        emb /= np.linalg.norm(emb, axis=1, keepdims=True)
        rid = f"drill-{b}"
        sent[rid] = (ids, emb)
        return rid, json.dumps({"id": rid, "ingest": {
            "ids": ids, "labels": [9, 9],
            "embeddings": emb.tolist()}}) + "\n"

    log_path = str(tmp_path / "serve.log")
    for k in range(kills + 1):
        acks, stop = [], threading.Event()
        proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=open(log_path, "ab"), env=env)
        reader = threading.Thread(target=_read_acks,
                                  args=(proc.stdout, acks, stop),
                                  daemon=True)
        reader.start()
        try:
            # Randomized seeded offset: how many batches to ack before
            # this kill lands.
            want = int(rng.integers(1, 4))
            deadline = time.monotonic() + 180.0
            sent_here = 0
            while len(acks) < want and time.monotonic() < deadline:
                if sent_here <= len(acks):
                    rid, line = _batch()
                    proc.stdin.write(line.encode())
                    proc.stdin.flush()
                    sent_here += 1
                time.sleep(0.05)
            assert len(acks) >= want, \
                f"kill {k}: only {len(acks)} acks before deadline"
            for rec in list(acks):
                acked[rec["id"]] = sent[rec["id"]][0]
            if k < kills:
                # Race one more unacked batch into the pipe, then kill.
                rid, line = _batch()
                try:
                    proc.stdin.write(line.encode())
                    proc.stdin.flush()
                except OSError:
                    pass
                proc.send_signal(signal.SIGKILL)
                assert proc.wait(timeout=60) == -signal.SIGKILL
            else:
                # Final segment: graceful drain publishes the last
                # checkpoint.
                proc.send_signal(signal.SIGTERM)
                assert proc.wait(timeout=120) == 75
                # Late acks may land during the drain.
                reader.join(timeout=10)
                for rec in list(acks):
                    acked[rec["id"]] = sent[rec["id"]][0]
        finally:
            stop.set()
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=60)

    found = load_newest(str(idx_dir / "g_"))
    assert found is not None
    final_path, final = found
    assert "g_w" in os.path.basename(final_path)  # watermark checkpoint
    final_ids = np.asarray(final.ids).astype(np.int64)
    id_set = set(final_ids.tolist())
    # Zero duplicate applies (exactly-once replay) ...
    assert final_ids.shape[0] == len(id_set)
    # ... and zero acked-vector loss across all five kills.
    lost = [i for ids in acked.values() for i in ids if i not in id_set]
    assert lost == [], f"acked ids missing after {kills} kills: {lost}"
    assert len(acked) >= kills  # at least one acked batch per segment
    log_text = open(log_path, "rb").read().decode("utf-8", "replace")
    assert log_text.count("wal: recovered") >= kills
