"""Model zoo shape/norm tests (small spatial sizes for CPU speed)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from npairloss_tpu.models import available_models, get_model


def _init_and_run(model, x, train=False):
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    if "batch_stats" in variables:
        out, _ = model.apply(
            variables, x, train=train, mutable=["batch_stats"] if train else []
        )
    else:
        out = model.apply(variables, x, train=train)
    return out


def test_registry_lists_reference_models():
    names = available_models()
    for required in ("googlenet", "resnet50", "vit_b16", "mlp"):
        assert required in names


def test_googlenet_embedding_shape_and_norm():
    m = get_model("googlenet", dtype=jnp.float32)
    x = jnp.ones((2, 64, 64, 3), jnp.float32)
    out = _init_and_run(m, x)
    assert out.shape == (2, 1024)  # pool5/7x7_s1 width, def.prototxt:116
    np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, rtol=1e-5)


def test_resnet50_embedding_shape():
    m = get_model("resnet50", dtype=jnp.float32)
    x = jnp.ones((2, 64, 64, 3), jnp.float32)
    out = _init_and_run(m, x, train=True)
    assert out.shape == (2, 2048)
    np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, rtol=1e-5)


def test_vit_embedding_shape():
    m = get_model("vit_b16", depth=2, hidden=64, num_heads=4, mlp_dim=128, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    out = _init_and_run(m, x)
    assert out.shape == (2, 64)


def test_mlp_embedding_unnormalized_option():
    m = get_model("mlp", normalize=False, embedding_dim=8)
    x = jnp.ones((4, 16), jnp.float32)
    out = _init_and_run(m, x)
    assert out.shape == (4, 8)
    assert not np.allclose(np.linalg.norm(out, axis=1), 1.0)
