"""Model zoo shape/norm tests (small spatial sizes for CPU speed)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from npairloss_tpu.models import available_models, get_model


def _init_and_run(model, x, train=False):
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    if "batch_stats" in variables:
        out, _ = model.apply(
            variables, x, train=train, mutable=["batch_stats"] if train else []
        )
    else:
        out = model.apply(variables, x, train=train)
    return out


def test_registry_lists_reference_models():
    names = available_models()
    for required in ("googlenet", "googlenet_bn", "googlenet_s2d",
                     "resnet50", "vit_b16", "mlp"):
        assert required in names


def test_space_to_depth_rejects_odd_dims():
    from npairloss_tpu.models.layers import space_to_depth

    with pytest.raises(ValueError, match="divisible"):
        space_to_depth(jnp.zeros((1, 227, 227, 3)), 2)


def test_googlenet_embedding_shape_and_norm():
    m = get_model("googlenet", dtype=jnp.float32)
    x = jnp.ones((2, 64, 64, 3), jnp.float32)
    out = _init_and_run(m, x)
    assert out.shape == (2, 1024)  # pool5/7x7_s1 width, def.prototxt:116
    np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, rtol=1e-5)


def test_googlenet_trunk_topology_matches_def_prototxt():
    """Pin the Inception-v1 trunk to the reference net's topology
    (usage/def.prototxt:85-120): conv1 is 64x7x7 stride 2 (the one conv
    the template spells out, def.prototxt:85-111), the inception stages
    produce the canonical GoogLeNet channel widths at the canonical
    strides on a 224 input, and pool5/7x7_s1 pools 7x7x1024 -> 1024
    (the embedding fed to L2Normalize, def.prototxt:115-120)."""
    m = get_model("googlenet", dtype=jnp.float32)
    x = jnp.zeros((1, 224, 224, 3), jnp.float32)
    variables = jax.eval_shape(
        lambda: m.init(jax.random.PRNGKey(0), x, train=False)
    )
    params = variables["params"]

    # conv1/7x7_s2: num_output 64, kernel 7, stride 2 (def.prototxt:98-101).
    assert params["conv1"]["Conv_0"]["kernel"].shape == (7, 7, 3, 64)

    # Stage output shapes on the canonical 224 input: spatial halvings at
    # conv1 / pool1 / pool2 / pool3 / pool4, channel widths from the
    # Inception-v1 plan the prototxt's "..." elides.
    _, inter = jax.eval_shape(
        lambda v: m.apply(
            v, x, train=False, capture_intermediates=True, mutable=["intermediates"]
        ),
        variables,
    )
    outs = {
        name: shapes["__call__"][0]
        for name, shapes in inter["intermediates"].items()
        if name.startswith("inception_")
    }
    want = {
        "inception_3a": (1, 28, 28, 256),
        "inception_3b": (1, 28, 28, 480),
        "inception_4a": (1, 14, 14, 512),
        "inception_4b": (1, 14, 14, 512),
        "inception_4c": (1, 14, 14, 512),
        "inception_4d": (1, 14, 14, 528),
        "inception_4e": (1, 14, 14, 832),
        "inception_5a": (1, 7, 7, 832),
        "inception_5b": (1, 7, 7, 1024),
    }
    for name, shape in want.items():
        assert outs[name].shape == shape, (name, outs[name].shape)

    # 9 inception blocks, each with the 6-conv plan (1x1, 3x3red, 3x3,
    # 5x5red, 5x5, pool_proj) — 2 stem conv blocks + conv2_reduce.
    assert len(outs) == 9
    for blk in ("b1x1", "b3x3_reduce", "b3x3", "b5x5_reduce", "b5x5",
                "pool_proj"):
        assert blk in params["inception_3a"], blk


def test_resnet50_embedding_shape():
    m = get_model("resnet50", dtype=jnp.float32)
    x = jnp.ones((2, 64, 64, 3), jnp.float32)
    out = _init_and_run(m, x, train=True)
    assert out.shape == (2, 2048)
    np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, rtol=1e-5)


def test_vit_embedding_shape():
    m = get_model("vit_b16", depth=2, hidden=64, num_heads=4, mlp_dim=128, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    out = _init_and_run(m, x)
    assert out.shape == (2, 64)


def test_mlp_embedding_unnormalized_option():
    m = get_model("mlp", normalize=False, embedding_dim=8)
    x = jnp.ones((4, 16), jnp.float32)
    out = _init_and_run(m, x)
    assert out.shape == (4, 8)
    assert not np.allclose(np.linalg.norm(out, axis=1), 1.0)


def test_googlenet_s2d_stem_exact_equivalence():
    """The space-to-depth stem (models/googlenet.py stem_s2d) is an
    algebraic rewrite of conv1, not an approximation: converting the
    7x7/s2 kernel with conv1_kernel_to_s2d and running the s2d trunk
    must reproduce the plain trunk's embeddings to float rounding."""
    from npairloss_tpu.models.layers import conv1_kernel_to_s2d

    m_std = get_model("googlenet", dtype=jnp.float32)
    m_s2d = get_model("googlenet_s2d", dtype=jnp.float32)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((3, 64, 64, 3)).astype(np.float32))

    v_std = m_std.init(jax.random.PRNGKey(0), x[:1], train=False)
    params = jax.tree_util.tree_map(lambda a: a, v_std["params"])
    k7 = np.asarray(params["conv1"]["Conv_0"]["kernel"])
    params["conv1"]["Conv_0"]["kernel"] = jnp.asarray(conv1_kernel_to_s2d(k7))
    # every 7x7 tap lands somewhere (only the p=7 slots are zero)
    assert np.count_nonzero(params["conv1"]["Conv_0"]["kernel"]) >= np.count_nonzero(k7)

    out_std = np.asarray(m_std.apply(v_std, x, train=False))
    out_s2d = np.asarray(m_s2d.apply({"params": params}, x, train=False))
    np.testing.assert_allclose(out_s2d, out_std, rtol=1e-4, atol=1e-5)


def test_resnet_s2d_stem_exact_equivalence():
    """The ResNet stem_s2d variant (registry: resnet50_s2d) is the same
    algebraic rewrite as the GoogLeNet one: converting the 7x7/s2 stem
    kernel with conv1_kernel_to_s2d must reproduce the plain trunk's
    embeddings to float rounding.  (Equivalence runs on resnet18 — same
    shared stem code — for CPU speed.)"""
    m50 = get_model("resnet50_s2d", dtype=jnp.float32)
    assert m50.stem_s2d and m50.stage_sizes == (3, 4, 6, 3)
    from npairloss_tpu.models.layers import conv1_kernel_to_s2d

    m_std = get_model("resnet18", dtype=jnp.float32)
    m_s2d = get_model("resnet18", dtype=jnp.float32, stem_s2d=True)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 64, 64, 3)).astype(np.float32))

    v_std = m_std.init(jax.random.PRNGKey(0), x, train=False)
    params = jax.tree_util.tree_map(lambda a: a, v_std["params"])
    k7 = np.asarray(params["conv_stem"]["kernel"])
    params["conv_stem"]["kernel"] = jnp.asarray(conv1_kernel_to_s2d(k7))
    variables = {"params": params,
                 "batch_stats": v_std.get("batch_stats", {})}

    out_std = np.asarray(m_std.apply(v_std, x, train=False))
    out_s2d = np.asarray(m_s2d.apply(variables, x, train=False))
    np.testing.assert_allclose(out_s2d, out_std, rtol=1e-4, atol=1e-5)


def test_googlenet_bn_trains_from_scratch_spread():  # slow-ok: the only from-scratch GoogLeNet-BN convergence probe in tier-1
    """Inception-BN variant: BatchNorm after every conv keeps the
    embedding batch SPREAD at random init (the BN-free v1 trunk collapses
    to pairwise sims ~0.9999, which kills mining-based training from
    scratch — see models/googlenet.py).  Also pins: batch_stats exist,
    LRN is dropped when BN is on, eval mode runs."""
    m = get_model("googlenet_bn", dtype=jnp.float32)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 64, 64, 3)).astype(np.float32))
    variables = m.init(jax.random.PRNGKey(0), x[:2], train=False)
    assert "batch_stats" in variables  # BN params present

    emb, _ = m.apply(variables, x, train=True, mutable=["batch_stats"])
    emb = np.asarray(emb)
    assert emb.shape == (8, 1024)
    np.testing.assert_allclose(np.linalg.norm(emb, axis=1), 1.0, rtol=1e-5)
    sims = emb @ emb.T
    off = sims[~np.eye(8, dtype=bool)]
    assert off.mean() < 0.9, f"BN trunk collapsed at init: mean sim {off.mean()}"

    # the BN-free trunk DOES collapse — the contrast this variant exists for
    m0 = get_model("googlenet", dtype=jnp.float32)
    v0 = m0.init(jax.random.PRNGKey(0), x[:2], train=False)
    emb0 = np.asarray(m0.apply(v0, x, train=False))
    off0 = (emb0 @ emb0.T)[~np.eye(8, dtype=bool)]
    assert off0.mean() > 0.99

    # eval mode (running stats) produces finite normalized embeddings
    emb_eval = np.asarray(m.apply(variables, x, train=False))
    assert np.isfinite(emb_eval).all()


@pytest.mark.slow  # ~46s; tier-1 budget (ROADMAP.md), run with -m slow
def test_googlenet_remat_is_numerically_identical():
    """remat=True checkpoints each inception block (recompute in the
    backward) — outputs AND gradients must match remat=False exactly;
    only the memory/FLOPs tradeoff changes."""
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal((2, 64, 64, 3)).astype(np.float32))

    m_plain = get_model("googlenet", dtype=jnp.float32)
    m_remat = get_model("googlenet", dtype=jnp.float32, remat=True)
    variables = m_plain.init(jax.random.PRNGKey(0), x, train=False)

    def loss(model, params):
        return model.apply({"params": params}, x, train=False).sum()

    out_p = np.asarray(m_plain.apply(variables, x, train=False))
    out_r = np.asarray(m_remat.apply(variables, x, train=False))
    np.testing.assert_array_equal(out_r, out_p)

    g_p = jax.grad(lambda p: loss(m_plain, p))(variables["params"])
    g_r = jax.grad(lambda p: loss(m_remat, p))(variables["params"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=1e-6, atol=1e-7
        ),
        g_p, g_r,
    )


def test_googlenet_fused_1x1_exact_equivalence():
    """fuse_1x1 merges the three input-reading 1x1 convs into one wider
    conv + slices (MXU lane occupancy); with weights converted by
    fuse_inception_1x1_params the outputs must match the plain trunk."""
    from npairloss_tpu.models import fuse_inception_1x1_params

    rng = np.random.default_rng(13)
    x = jnp.asarray(rng.standard_normal((2, 64, 64, 3)).astype(np.float32))

    m_plain = get_model("googlenet", dtype=jnp.float32)
    m_fused = get_model("googlenet_fused", dtype=jnp.float32)
    variables = m_plain.init(jax.random.PRNGKey(0), x, train=False)
    fp, _ = fuse_inception_1x1_params(variables["params"])
    out_plain = np.asarray(m_plain.apply(variables, x, train=False))
    out_fused = np.asarray(m_fused.apply({"params": fp}, x, train=False))
    np.testing.assert_allclose(out_fused, out_plain, rtol=1e-5, atol=1e-6)

    # Param count is identical — fusion is a layout change, not a model
    # change.
    count = lambda t: sum(
        int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(t))
    assert count(fp) == count(variables["params"])


def test_googlenet_bn_fused_1x1_exact_equivalence():
    """Same check for the BN trunk: BN scale/bias/mean/var are
    per-channel, so channel-concat conversion is exact (batch_stats
    tree converts too)."""
    from npairloss_tpu.models import fuse_inception_1x1_params

    rng = np.random.default_rng(14)
    x = jnp.asarray(rng.standard_normal((2, 64, 64, 3)).astype(np.float32))

    m_plain = get_model("googlenet_bn", dtype=jnp.float32)
    m_fused = get_model("googlenet_bn", dtype=jnp.float32, fuse_1x1=True)
    variables = m_plain.init(jax.random.PRNGKey(1), x, train=False)
    fp, fbs = fuse_inception_1x1_params(
        variables["params"], variables["batch_stats"]
    )
    out_plain = np.asarray(m_plain.apply(variables, x, train=False))
    out_fused = np.asarray(
        m_fused.apply({"params": fp, "batch_stats": fbs}, x, train=False)
    )
    np.testing.assert_allclose(out_fused, out_plain, rtol=1e-5, atol=1e-6)


def test_googlenet_mxu_variant_runs():
    """googlenet_mxu stacks both parity-preserving rewrites (s2d stem +
    fused 1x1s) — shape/norm contract must hold."""
    m = get_model("googlenet_mxu", dtype=jnp.float32)
    assert m.stem_s2d and m.fuse_1x1
    rng = np.random.default_rng(15)
    x = jnp.asarray(rng.standard_normal((2, 64, 64, 3)).astype(np.float32))
    out = _init_and_run(m, x)
    assert out.shape == (2, 1024)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=1), 1.0, rtol=1e-5)


def test_lrn_matches_caffe_formula():
    """local_response_norm == the Caffe LRN formula computed in plain
    NumPy (denominator (k + alpha/size * window_sum(x^2))^beta over the
    across-channel window), including the rsqrt-based beta=0.75 fast
    path, to float32 round-off."""
    from npairloss_tpu.models.layers import local_response_norm

    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 5, 5, 16)).astype(np.float32) * 3.0
    size, alpha, beta, k = 5, 1e-4, 0.75, 1.0

    sq = x * x
    pad = np.zeros((2, 5, 5, 16 + size - 1), np.float32)
    pad[..., size // 2:size // 2 + 16] = sq
    win = np.zeros_like(sq)
    for i in range(16):
        win[..., i] = pad[..., i:i + size].sum(-1)
    expect = x / np.power(k + (alpha / size) * win, beta)

    got = np.asarray(local_response_norm(jnp.asarray(x), size, alpha,
                                         beta, k))
    np.testing.assert_allclose(got, expect, rtol=2e-6, atol=2e-6)

    # Non-0.75 beta exercises the generic pow branch.
    expect_b = x / np.power(k + (alpha / size) * win, 0.5)
    got_b = np.asarray(local_response_norm(jnp.asarray(x), size, alpha,
                                           0.5, k))
    np.testing.assert_allclose(got_b, expect_b, rtol=2e-6, atol=2e-6)

    # Gradients stay finite through the fast path (it feeds the trunk
    # backward on the prototxt-parity path).
    g = jax.grad(lambda a: local_response_norm(a, size, alpha, beta,
                                               k).sum())(jnp.asarray(x))
    assert np.isfinite(np.asarray(g)).all()
