"""Test configuration: run on a virtual 8-device CPU mesh.

Multi-chip semantics (all_gather negative pooling, psum gradient exchange)
are validated without TPU pods by forcing 8 host-platform devices, per
SURVEY.md §4 ("Distributed without a cluster").  Must run before jax imports.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# jax may already be imported (e.g. by the jaxtyping pytest plugin) with
# JAX_PLATFORMS captured from the shell env — override via config too.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def make_identity_batch(rng, num_ids, imgs_per_id, dim, num_shards=1, scale=1.0):
    """Identity-balanced batches (the MultibatchData contract,
    def.prototxt:25-27): every query has >= imgs_per_id - 1 local positives.

    Returns (features_per_shard, labels_per_shard) lists of length num_shards,
    with L2-normalized rows so similarities live in [-1, 1] like the
    reference's post-L2Normalize embeddings.
    """
    feats, labs = [], []
    for _ in range(num_shards):
        ids = rng.choice(10 * num_ids, size=num_ids, replace=False)
        lab = np.repeat(ids, imgs_per_id).astype(np.int32)
        f = rng.standard_normal((num_ids * imgs_per_id, dim)).astype(np.float32)
        f = scale * f / np.linalg.norm(f, axis=1, keepdims=True)
        perm = rng.permutation(len(lab))
        feats.append(f[perm])
        labs.append(lab[perm])
    return feats, labs
