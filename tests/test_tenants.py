"""Multi-tenant serving (docs/SERVING.md §Multi-tenant).

Load-bearing pins:
  * the ``npairloss-tenants-v1`` manifest validates TOTALLY and
    loudly: unknown keys, duplicate ids, malformed ids and
    out-of-range quotas are refused with every problem listed (the
    same validator bench_check's ``--tenants`` gate file-path-loads);
  * one front end, one replica tier, MANY galleries: a query routes on
    its ``tenant`` key to that tenant's engine set, answers come back
    tenant-stamped, and an unknown tenant is a malformed request
    (error), never an admitted query;
  * hot-swapping ONE tenant republished exactly that tenant — every
    other tenant's engines are untouched by identity and its answers
    stay bit-identical;
  * same-geometry tenants share compiled programs through the
    :class:`ProgramCache` — tenant count must not multiply compiles;
  * a noisy tenant's quota sheds land on THAT tenant's counters only,
    the per-tenant counters cross-sum EXACTLY into the aggregates,
    and the quota gauge stream is tenant-labeled (the samples its
    tenant-scoped SLO burns on);
  * the tenant_skew gameday verdict refuses a run whose hot tenant
    was never shed or paged, and any neighbor that saw errors, leaked
    sheds, a p99 breach, or a recall dip.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

from npairloss_tpu.gameday import schedule as chaos
from npairloss_tpu.gameday import traffic as tg
from npairloss_tpu.gameday.verdict import (
    build_gameday_report,
    validate_gameday_report,
)
from npairloss_tpu.obs.live.export import prometheus_text
from npairloss_tpu.obs.live.registry import MetricRegistry
from npairloss_tpu.serve import (
    BatcherConfig,
    EngineConfig,
    GalleryIndex,
    QueryEngine,
    RetrievalServer,
    ServerConfig,
)
from npairloss_tpu.serve.tenants import (
    TENANTS_SCHEMA,
    ProgramCache,
    QuotaGate,
    TenantEntry,
    TenantRegistry,
    TenantSpec,
    tenant_of_slo,
    tenant_slo_specs,
    validate_tenants_manifest,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bench_check():
    spec = importlib.util.spec_from_file_location(
        "bench_check_mod", os.path.join(REPO, "scripts",
                                        "bench_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _entry(tid="acme", **kw):
    d = {"tenant_id": tid, "index_prefix": f"/tmp/idx/{tid}-"}
    d.update(kw)
    return d


def _manifest(*entries):
    return {"schema": TENANTS_SCHEMA,
            "tenants": list(entries) or [_entry()]}


# -- manifest validation ------------------------------------------------------


def test_manifest_valid_and_registry_roundtrip():
    man = _manifest(_entry("acme", index_kind="ivf", probe_impl="fused",
                           quota_qps=5.0, recall_floor=0.9,
                           p99_ms=150.0),
                    _entry("b-corp_2"))
    assert validate_tenants_manifest(man) == []
    reg = TenantRegistry.from_manifest(man)
    assert reg.ids() == ["acme", "b-corp_2"]
    assert "acme" in reg and len(reg) == 2
    assert reg.get("acme").index_kind == "ivf"
    with pytest.raises(KeyError, match="unknown tenant"):
        reg.get("nope")


def test_manifest_refusals_are_total_and_loud():
    # Every problem listed in ONE pass, not first-error-wins.
    man = {"schema": "wrong-schema",
           "tenants": [_entry("acme", quota_qps=-1),
                       _entry("acme"),
                       _entry("bad id!"),
                       dict(_entry("c"), mystery_key=1)]}
    problems = validate_tenants_manifest(man)
    text = "\n".join(problems)
    assert "schema" in text
    assert "quota_qps" in text
    assert "duplicate" in text
    assert "bad id!" in text
    assert "mystery_key" in text
    with pytest.raises(ValueError, match="invalid tenants manifest"):
        TenantRegistry.from_manifest(man)


def test_manifest_shape_refusals():
    assert validate_tenants_manifest(None)
    assert validate_tenants_manifest({"schema": TENANTS_SCHEMA})
    assert validate_tenants_manifest(
        {"schema": TENANTS_SCHEMA, "tenants": []})
    assert validate_tenants_manifest(
        {"schema": TENANTS_SCHEMA, "tenants": [17]})
    assert validate_tenants_manifest(_manifest(
        _entry("a", index_kind="hnsw")))      # unknown kind
    assert validate_tenants_manifest(_manifest(
        _entry("a", probe_impl="magic")))     # unknown probe impl
    assert validate_tenants_manifest(_manifest(
        {"tenant_id": "a"}))                  # index_prefix missing


def test_tenant_spec_validates_through_the_one_contract():
    with pytest.raises(ValueError, match="quota_qps"):
        TenantSpec(tenant_id="a", index_prefix="/p/a-", quota_qps=-2)
    spec = TenantSpec.from_dict(
        dict(_entry("a"), tenant="ignored-unknown-key"))
    assert spec.tenant_id == "a"


def test_registry_load_refuses_bad_json(tmp_path):
    path = tmp_path / "tenants.json"
    path.write_text("{not json")
    with pytest.raises(ValueError, match="bad JSON"):
        TenantRegistry.load(str(path))
    path.write_text(json.dumps(_manifest()))
    assert TenantRegistry.load(str(path)).ids() == ["acme"]


# -- tenant-scoped SLO naming -------------------------------------------------


def test_tenant_slo_specs_and_name_roundtrip():
    spec = TenantSpec(tenant_id="acme", index_prefix="/p/a-",
                      quota_qps=5.0, p99_ms=150.0, recall_floor=0.9,
                      recall_k=10)
    specs = tenant_slo_specs(spec)
    names = {s.name for s in specs}
    assert names == {"tenant_p99@acme", "tenant_quota@acme",
                     "tenant_recall@acme"}
    for s in specs:
        assert tenant_of_slo(s.name) == "acme"
        # Each spec burns on a tenant-labeled sample stream.
        assert 'tenant="acme"' in s.metric
    assert tenant_of_slo("serve_p99") is None
    # A tenant with no declared contracts arms no SLOs.
    bare = TenantSpec(tenant_id="b", index_prefix="/p/b-")
    assert tenant_slo_specs(bare) == []


# -- quota gate ---------------------------------------------------------------


def test_quota_gate_token_bucket_deterministic():
    now = [0.0]
    gate = QuotaGate(qps=2.0, burst_s=1.0, clock=lambda: now[0])
    assert gate.admit() and gate.admit()   # capacity 2*1
    assert not gate.admit()                # bucket dry
    now[0] = 1.0                           # refill 2 tokens
    assert gate.admit() and gate.admit()
    assert not gate.admit()
    s = gate.stats()
    assert s["sheds"] == 2 and s["qps"] == 2.0 and s["burst_s"] == 1.0


def test_quota_gate_zero_qps_disarms():
    gate = QuotaGate(qps=0.0)
    assert all(gate.admit() for _ in range(50))
    assert gate.stats()["sheds"] == 0
    with pytest.raises(ValueError, match="qps"):
        QuotaGate(qps=-1)
    with pytest.raises(ValueError, match="burst_s"):
        QuotaGate(qps=1, burst_s=0)


def test_quota_gauge_stream_is_tenant_labeled():
    reg = MetricRegistry()
    now = [0.0]
    gate = QuotaGate(qps=1.0, burst_s=1.0, clock=lambda: now[0],
                     registry=reg.view(tenant="acme"))
    assert gate.admit()
    assert not gate.admit()
    snap = reg.snapshot()
    assert snap['serve_quota_exhausted{tenant="acme"}']["value"] == 1.0
    assert snap['serve_quota_shed{tenant="acme"}']["value"] == 1.0
    # The exporter renders the label as a REAL Prometheus label.
    assert 'serve_quota_exhausted{tenant="acme"} 1' in \
        prometheus_text(reg)


# -- one tier, many galleries -------------------------------------------------


def _tenant_gallery(seed, n=24, dim=16, id_base=0):
    r = np.random.default_rng(seed)
    emb = r.standard_normal((n, dim)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    labels = (np.arange(n) % 6).astype(np.int32)
    ids = (np.arange(n) + id_base).astype(np.int64)
    return emb, GalleryIndex.build(emb, labels, ids=ids,
                                   normalize=False)


def _tenant_server(tenant_ids, *, quotas=None, replicas=1,
                   max_queue=64, programs=None):
    """One replica tier serving one distinct gallery per tenant, all
    engines sharing programs through one cache (the cli wiring in
    miniature).  Returns (server, {tid: query embeddings})."""
    programs = programs if programs is not None else ProgramCache()
    cfg = EngineConfig(top_k=3, buckets=(1, 4))
    entries, embs = {}, {}
    anchor = None
    for t_i, tid in enumerate(tenant_ids):
        emb, index = _tenant_gallery(7 + t_i, id_base=1000 * t_i)
        embs[tid] = emb
        primary = programs.engine_for(index, cfg)
        if anchor is None:
            primary.warmup()
        else:
            primary.warmed = True  # shares the anchor's programs
        engines = [primary] + [
            QueryEngine(index, cfg, share_compiled_with=primary)
            for _ in range(replicas - 1)]
        for e in engines[1:]:
            e.warmed = True
        if anchor is None:
            anchor = engines
        spec = TenantSpec(
            tenant_id=tid, index_prefix=f"/tmp/idx/{tid}-",
            quota_qps=(quotas or {}).get(tid, 0.0), quota_burst_s=1.0)
        quota = None
        if spec.quota_qps:
            quota = QuotaGate(spec.quota_qps, spec.quota_burst_s,
                              clock=lambda: 0.0)  # frozen: no refill
        entries[tid] = TenantEntry(spec, engines, quota=quota)
    server = RetrievalServer(
        anchor,
        BatcherConfig(max_batch=4, max_delay_ms=1.0,
                      max_queue=max_queue),
        ServerConfig(metrics_window=0, explicit_drops=True),
    )
    server.enable_tenants(entries)
    return server, embs


def _q(tid, emb, i, qid=None):
    return {"id": qid if qid is not None else i, "tenant": tid,
            "embedding": emb[i].tolist()}


def test_tenant_routing_answers_from_own_gallery(rng):
    server, embs = _tenant_server(["acme", "bcorp"])
    server.replicaset.start()
    try:
        for tid in ("acme", "bcorp"):
            a = server.handle(_q(tid, embs[tid], 3))
            assert a["tenant"] == tid
            # The query IS gallery row 3 of its own tenant: top-1
            # must be the exact match — proof it scored against the
            # right gallery, not a neighbor's.
            assert a["neighbors"][0]["row"] == 3
            assert a["neighbors"][0]["score"] == pytest.approx(
                1.0, abs=1e-5)
    finally:
        server.replicaset.close(drain=True)


def test_unknown_tenant_is_an_error_not_a_query(rng):
    server, embs = _tenant_server(["acme"])
    server.replicaset.start()
    try:
        a = server.handle(_q("ghost", embs["acme"], 0, qid="x"))
        assert "unknown tenant" in a["error"]
        b = server.handle({"id": "y", "embedding": embs["acme"][0].tolist()})
        assert "unknown tenant" in b["error"]  # missing key too
        assert server.errors == 2
        # Never admitted: a malformed request must not dilute the
        # drain invariant's admitted-query population.
        assert server.queries == 0
        # No tenant row owns a refusal — the drain names the remainder
        # so the error audit stays exact (Σ per-tenant + unattributed
        # == aggregate, the bench_check --tenants identity).
        summ = server.summary()
        assert summ["errors_unattributed"] == 2
        per = summ["tenants"]
        assert sum(row["errors"] for row in per.values()) == 0
        assert (sum(row["errors"] for row in per.values())
                + summ["errors_unattributed"] == summ["errors"])
        # A refusal never entered ``queries``, so it must not read
        # back as a negative drop count.
        assert summ["queries_dropped"] == 0
    finally:
        server.replicaset.close(drain=True)


def test_swap_one_tenant_leaves_neighbors_bit_identical(rng):
    server, embs = _tenant_server(["acme", "bcorp"])
    server.replicaset.start()
    try:
        before = server.handle(_q("bcorp", embs["bcorp"], 5))
        b_engines = server.tenants["bcorp"].engines
        # Republish acme on a brand-new gallery (new ids namespace).
        emb2, index2 = _tenant_gallery(99, id_base=5000)
        old = server.tenants["acme"].engines[0]
        fresh = QueryEngine(index2, old.cfg, share_programs_with=old)
        fresh.warmed = True
        server.swap_tenant_engines("acme", [fresh])
        assert server.tenants["acme"].swaps == 1
        assert server.tenants["bcorp"].swaps == 0
        # bcorp's engine OBJECTS are untouched...
        assert server.tenants["bcorp"].engines is b_engines
        # ...and its answers bit-identical across the neighbor swap.
        after = server.handle(_q("bcorp", embs["bcorp"], 5))
        assert after["neighbors"] == before["neighbors"]
        # acme now answers from the new gallery's id namespace.
        a = server.handle(_q("acme", emb2, 2))
        assert a["neighbors"][0]["row"] == 2
        with pytest.raises(Exception, match="unknown tenant"):
            server.swap_tenant_engines("ghost", [fresh])
        with pytest.raises(ValueError, match="replica count"):
            server.swap_tenant_engines("acme", [fresh, fresh])
    finally:
        server.replicaset.close(drain=True)


def test_same_geometry_tenants_share_compiles(rng):
    programs = ProgramCache()
    server, embs = _tenant_server(["acme", "bcorp", "ccorp"],
                                  programs=programs)
    server.replicaset.start()
    try:
        for tid in ("acme", "bcorp", "ccorp"):
            server.handle(_q(tid, embs[tid], 0))
        # One program family serves every tenant: ONLY the anchor's
        # warmup compiled; the other tenants' first dispatches found
        # every program hot (tenant count must not multiply compiles).
        assert programs.stats() == {"families": 1}
        assert server._compiles_after_warmup() == 0
    finally:
        server.replicaset.close(drain=True)


def test_quota_shed_isolation_and_cross_sums(rng):
    # acme's frozen-clock bucket admits exactly 2 (capacity 2*1);
    # everything beyond sheds on acme alone.
    server, embs = _tenant_server(["acme", "bcorp"],
                                  quotas={"acme": 2.0})
    server.replicaset.start()
    try:
        records = [_q("acme", embs["acme"], i, qid=f"a{i}")
                   for i in range(6)]
        records += [_q("bcorp", embs["bcorp"], i, qid=f"b{i}")
                    for i in range(3)]
        answers = server.handle_many(records)
        shed = [a for a in answers if "error" in a
                and "quota exceeded" in a["error"]]
        assert len(shed) == 4
        summ = server.summary()
        per = summ["tenants"]
        assert per["acme"]["answered"] == 2
        assert per["acme"]["rejected"] == 4
        assert per["acme"]["quota"]["sheds"] == 4
        # The noisy neighbor's sheds never leak onto bcorp.
        assert per["bcorp"]["answered"] == 3
        assert per["bcorp"]["rejected"] == 0
        assert per["bcorp"]["errors"] == 0
        # Per-tenant counters cross-sum EXACTLY into the aggregates
        # (the bench_check --tenants gate's accounting invariant).
        for key in ("queries", "answered", "errors", "rejected"):
            assert sum(row[key] for row in per.values()) == summ[key], key
        assert summ["queries_dropped"] == 0
    finally:
        server.replicaset.close(drain=True)


def test_enable_tenants_is_loud(rng):
    server, _ = _tenant_server(["acme"])
    with pytest.raises(ValueError, match="already installed"):
        server.enable_tenants(dict(server.tenants))
    emb, index = _tenant_gallery(1)
    cfg = EngineConfig(top_k=3, buckets=(1,))
    eng = QueryEngine(index, cfg)
    bad = TenantEntry(
        TenantSpec(tenant_id="x", index_prefix="/p/x-"), [eng, eng])
    fresh = RetrievalServer(
        [eng], BatcherConfig(max_batch=1, max_delay_ms=1.0,
                             max_queue=4),
        ServerConfig(metrics_window=0))
    with pytest.raises(ValueError, match="replica tier"):
        fresh.enable_tenants({"x": bad})  # 2 engines vs 1 replica
    with pytest.raises(ValueError, match=">= 1 tenant"):
        fresh.enable_tenants({})


# -- tenant-aware traffic plans ----------------------------------------------


def _skew_cfg(**over):
    kw = dict(seed=0, duration_s=30.0, base_qps=4.0, peak_qps=8.0,
              burst_qps=30.0, bursts=1, burst_s=6.0, catalog=64,
              zipf_s=1.1,
              tenants=(("acme", 1.0), ("bcorp", 1.0), ("ccorp", 1.0)),
              hot_tenant="acme", hot_burst_factor=8.0)
    kw.update(over)
    return tg.TrafficConfig(**kw)


def test_traffic_tenant_draws_and_burst_skew():
    plan = tg.generate(_skew_cfg())
    tids = {q.tenant for q in plan.queries}
    assert tids == {"acme", "bcorp", "ccorp"}
    # Inside the burst window ([12, 18] — one burst centered at 15)
    # the hot tenant's weight is multiplied 8x, so its arrival share
    # must dominate there and stay ~fair outside.
    assert plan.burst_windows == ((12.0, 18.0),)
    burst = [q for q in plan.queries if plan.in_burst(q.t)]
    steady = [q for q in plan.queries if not plan.in_burst(q.t)]
    hot_burst = sum(q.tenant == "acme" for q in burst) / len(burst)
    hot_steady = sum(q.tenant == "acme" for q in steady) / len(steady)
    assert hot_burst > 0.6 > hot_steady
    assert hot_steady == pytest.approx(1 / 3, abs=0.12)
    assert plan_stats_hot_share(plan) == pytest.approx(hot_burst)


def plan_stats_hot_share(plan):
    stats = tg.plan_stats(plan)
    row = stats["tenants"]["acme"]
    return row["burst"] / stats["burst_queries"]


def test_traffic_tenant_plans_are_deterministic_and_serializable():
    a, b = tg.generate(_skew_cfg()), tg.generate(_skew_cfg())
    assert tg.plan_lines(a) == tg.plan_lines(b)
    assert tg.plan_digest(a) == tg.plan_digest(b)
    assert tg.plan_digest(tg.generate(_skew_cfg(seed=1))) != \
        tg.plan_digest(a)
    rec = json.loads(tg.plan_lines(a)[1])  # line 0 is the cfg header
    assert rec["tenant"] in ("acme", "bcorp", "ccorp")
    # Tenant-free configs keep the old single-tenant line shape (and
    # so the recorded single-tenant days' digests).
    plain = tg.generate(tg.TrafficConfig(seed=0, duration_s=10.0))
    assert "tenant" not in json.loads(tg.plan_lines(plain)[1])
    assert "tenants" not in json.loads(tg.plan_lines(plain)[0])["cfg"]


def test_traffic_tenant_config_is_loud():
    with pytest.raises(ValueError, match="hot_tenant"):
        tg.TrafficConfig(seed=0, duration_s=10.0,
                         tenants=(("a", 1.0),), hot_tenant="ghost")
    with pytest.raises(ValueError, match="weight"):
        tg.TrafficConfig(seed=0, duration_s=10.0,
                         tenants=(("a", -1.0),))
    with pytest.raises(ValueError, match="hot_burst_factor"):
        tg.TrafficConfig(seed=0, duration_s=10.0,
                         tenants=(("a", 1.0),), hot_tenant="a",
                         hot_burst_factor=0.0)


def test_tenant_skew_schedule_declares_the_alert_pair():
    entries = chaos.tenant_skew_schedule("acme", 75.0)
    [e] = entries
    assert e.kind == "traffic" and e.target == "serve"
    assert e.alert == "tenant_quota@acme"
    assert tenant_of_slo(e.alert) == "acme"
    with pytest.raises(ValueError, match="hot tenant"):
        chaos.tenant_skew_schedule("", 75.0)
    with pytest.raises(ValueError, match="alert pair"):
        chaos.ChaosEntry(name="x", target="serve", kind="traffic")


# -- tenant_skew verdict ------------------------------------------------------


def _alert_pair(aid, slo, t0, t1):
    base = {"schema": "alerts-v1", "alert_id": aid, "slo": slo,
            "metric": "m", "severity": "warning", "ts": t0,
            "fired_at": t0, "bad_fraction": 1.0, "samples": 4,
            "target": 1.0, "op": "<=", "message": "x"}
    return [dict(base, state="firing"),
            dict(base, state="resolved", ts=t1, bad_fraction=0.0)]


def _tenant_row(queries=100, answered=100, errors=0, rejected=0,
                sheds=0, p99=30.0):
    return {"queries": queries, "answered": answered, "errors": errors,
            "rejected": rejected, "p99_ms": p99, "index_kind": "flat",
            "quota": {"qps": 6.0, "burst_s": 1.0, "sheds": sheds,
                      "tokens": 0.0}}


def _skew_report(**over):
    entries = chaos.entry_dicts(chaos.tenant_skew_schedule("acme", 75.0))
    tenants = {
        "acme": _tenant_row(queries=300, answered=100, rejected=200,
                            sheds=200),
        "bcorp": _tenant_row(),
        "ccorp": _tenant_row(),
    }
    kw = dict(
        traffic={"planned": 500, "fed": 500, "answered": 300,
                 "errors": 0, "rejected": 200, "sha256": "d" * 64},
        serve_alerts=_alert_pair("a1", "tenant_quota@acme", 36.0, 66.0),
        train_alerts=[], serve_remediation=[], train_remediation=[],
        serve_rows=[{"p99_ms": 35.0, "wall_time": float(t)}
                    for t in range(0, 76, 5)],
        quality_windows=[],
        drain={"queries": 500, "answered": 300, "errors": 0,
               "rejected": 200, "queries_dropped": 0, "hot_swaps": 0,
               "tenants": tenants},
        comms={"available": False, "reason": "no trainer"},
        trainer={"segments": 0, "exit_codes": [], "resumed": False},
        observed_fires={}, client_errors=0, window_s=75.0, seed=0,
        p99_target_ms=150.0, recall_floor=0.9, min_hot_swaps=0,
        tenant_hot="acme",
        tenant_quality={tid: [{"recall_at_10": 0.97,
                               "wall_time": float(t)}
                              for t in range(0, 76, 10)]
                        for tid in tenants})
    kw.update(over)
    return build_gameday_report(entries, **kw)


def test_tenant_skew_report_passes_and_validates():
    rep = _skew_report()
    assert rep["verdict"] == "pass", rep["failures"]
    assert validate_gameday_report(rep) is None
    tb = rep["tenants"]
    assert tb["available"] and tb["hot"] == "acme"
    assert tb["tenants"]["acme"]["shed"] == 200  # the quota sheds
    assert tb["tenants"]["acme"]["alerted"] is True
    assert tb["tenants"]["bcorp"]["alerted"] is False
    assert tb["tenants"]["bcorp"]["recall_worst"] == pytest.approx(0.97)


def test_tenant_skew_verdict_demands_shed_and_page():
    # Hot tenant never shed -> isolation unproven.
    quiet = {"acme": _tenant_row(), "bcorp": _tenant_row(),
             "ccorp": _tenant_row()}
    rep = _skew_report(drain={"queries": 500, "answered": 500,
                              "errors": 0, "rejected": 0,
                              "queries_dropped": 0, "hot_swaps": 0,
                              "tenants": quiet})
    assert rep["verdict"] == "fail"
    assert any("never shed" in f for f in rep["failures"])
    # Shed but never paged: the alert pair is the declared evidence.
    rep = _skew_report(serve_alerts=[])
    assert rep["verdict"] == "fail"
    assert any("tenant-scoped alert" in f for f in rep["failures"])
    assert any("unremediated injected fault" in f or
               "fired=False" in f for f in rep["failures"])


def test_tenant_skew_verdict_protects_the_neighbors():
    base = {
        "acme": _tenant_row(queries=300, answered=100, rejected=200,
                            sheds=200),
        "bcorp": _tenant_row(errors=2),
        "ccorp": _tenant_row(),
    }
    rep = _skew_report(drain={"queries": 500, "answered": 298,
                              "errors": 2, "rejected": 200,
                              "queries_dropped": 0, "hot_swaps": 0,
                              "tenants": base})
    assert rep["verdict"] == "fail"
    assert any("'bcorp' saw 2 error(s)" in f for f in rep["failures"])
    # A neighbor p99 breach fails even with the hot tenant shed.
    slow = dict(base, bcorp=_tenant_row(p99=400.0))
    rep = _skew_report(drain={"queries": 500, "answered": 300,
                              "errors": 0, "rejected": 200,
                              "queries_dropped": 0, "hot_swaps": 0,
                              "tenants": slow})
    assert any("p99" in f and "bcorp" in f for f in rep["failures"])
    # A neighbor recall dip outside incident windows fails.
    rep = _skew_report(tenant_quality={
        "acme": [], "ccorp": [],
        "bcorp": [{"recall_at_10": 0.5, "wall_time": 5.0}]})
    assert any("recall" in f and "bcorp" in f for f in rep["failures"])


def test_tenant_block_shape_is_validated():
    rep = _skew_report()
    broken = json.loads(json.dumps(rep))
    del broken["tenants"]["tenants"]["acme"]["shed"]
    assert "shed" in validate_gameday_report(broken)
    broken = json.loads(json.dumps(rep))
    broken["tenants"] = "yes"
    assert validate_gameday_report(broken)
    # Pre-multi-tenant reports (no "tenants" key) must keep validating.
    legacy = json.loads(json.dumps(rep))
    del legacy["tenants"]
    assert validate_gameday_report(legacy) is None


# -- bench_check --tenants gate ----------------------------------------------


def _run_dir(tmp_path, manifest=None, drain=None, answers=None):
    man = manifest if manifest is not None else _manifest(
        _entry("acme", quota_qps=6.0), _entry("bcorp"))
    (tmp_path / "tenants.json").write_text(json.dumps(man))
    if answers is None:
        answers = [{"id": 1, "tenant": "acme", "neighbors": []},
                   {"id": 2, "tenant": "bcorp", "neighbors": []}]
        if drain is None:
            drain = {"event": "serve_drain", "queries": 2,
                     "answered": 2, "errors": 0, "rejected": 0,
                     "tenants": {
                         "acme": _tenant_row(queries=1, answered=1),
                         "bcorp": _tenant_row(queries=1, answered=1)}}
        answers = answers + [drain]
    (tmp_path / "answers.jsonl").write_text(
        "\n".join(json.dumps(a) for a in answers) + "\n")
    return str(tmp_path / "tenants.json")


def test_check_tenants_accepts_consistent_run(bench_check, tmp_path):
    assert bench_check.check_tenants(_run_dir(tmp_path)) == []


def test_check_tenants_refuses_tampered_manifest(bench_check, tmp_path):
    man = _manifest(_entry("acme", quota_qps=-5))
    path = _run_dir(tmp_path, manifest=man)
    out = bench_check.check_tenants(path)
    assert out and all("manifest refused" in v for v in out)


def test_check_tenants_refuses_broken_cross_sums(bench_check, tmp_path):
    drain = {"event": "serve_drain", "queries": 2, "answered": 7,
             "errors": 0, "rejected": 0,
             "tenants": {"acme": _tenant_row(queries=1, answered=1),
                         "bcorp": _tenant_row(queries=1, answered=1)}}
    path = _run_dir(tmp_path, drain=drain)
    out = bench_check.check_tenants(path)
    assert any("cross-sum" in v for v in out)


def test_check_tenants_accounts_unattributed_errors(bench_check,
                                                    tmp_path):
    # An unknown-tenant refusal belongs to NO tenant row; the drain's
    # errors_unattributed remainder keeps the error identity exact —
    # omit it (or fake a negative one) and the gate refuses.
    drain = {"event": "serve_drain", "queries": 2, "answered": 2,
             "errors": 2, "rejected": 0, "errors_unattributed": 2,
             "tenants": {"acme": _tenant_row(queries=1, answered=1),
                         "bcorp": _tenant_row(queries=1, answered=1)}}
    path = _run_dir(tmp_path, drain=drain)
    assert bench_check.check_tenants(path) == []
    no_rem = dict(drain)
    del no_rem["errors_unattributed"]
    path = _run_dir(tmp_path, drain=no_rem)
    assert any("cross-sum" in v
               for v in bench_check.check_tenants(path))
    bad_rem = dict(drain, errors_unattributed=-2)
    path = _run_dir(tmp_path, drain=bad_rem)
    assert any("non-negative" in v
               for v in bench_check.check_tenants(path))


def test_check_tenants_refuses_unregistered_and_aggregate_quality(
        bench_check, tmp_path):
    answers = [
        {"id": 1, "tenant": "ghost", "neighbors": []},
        {"event": "serve_drain", "queries": 1, "answered": 1,
         "errors": 0, "rejected": 0, "quality": {"recall_at_10": 1.0},
         "tenants": {"acme": _tenant_row(queries=1, answered=1),
                     "bcorp": _tenant_row(queries=0, answered=0)}},
    ]
    path = _run_dir(tmp_path, answers=answers)
    out = bench_check.check_tenants(path)
    assert any("unknown tenant" in v for v in out)
    assert any("aggregate quality" in v for v in out)


def test_check_tenants_manifest_only_when_no_answers(bench_check,
                                                     tmp_path):
    man = _manifest(_entry("acme"))
    (tmp_path / "tenants.json").write_text(json.dumps(man))
    assert bench_check.check_tenants(
        str(tmp_path / "tenants.json")) == []
