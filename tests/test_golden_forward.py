"""Golden forward tests: JAX implementation vs the NumPy oracle.

The oracle (npairloss_tpu.testing.oracle) is a loop-level transliteration of
the reference semantics (npair_multi_class_loss.cu:207-402); these tests
sweep the full (region x method) mining grid per SURVEY.md §4.
"""

import itertools

import jax
import numpy as np
import pytest

from conftest import make_identity_batch
from npairloss_tpu import MiningMethod, MiningRegion, NPairLossConfig
from npairloss_tpu.ops.npair_loss import npair_loss_with_aux
from npairloss_tpu.testing import oracle

REGIONS = [MiningRegion.GLOBAL, MiningRegion.LOCAL]
METHODS = list(MiningMethod)
AP_CELLS = list(itertools.product(REGIONS, METHODS))


def _run_jax(feats, labs, cfg):
    loss, aux = jax.jit(
        lambda f, l: npair_loss_with_aux(f, l, cfg), static_argnums=()
    )(feats, labs)
    return float(loss), jax.tree_util.tree_map(np.asarray, aux)


def _check_cell(rng, cfg, num_ids=4, imgs_per_id=3, dim=8):
    feats, labs = make_identity_batch(rng, num_ids, imgs_per_id, dim)
    want = oracle.forward(feats, labs, cfg)[0]
    got_loss, aux = _run_jax(feats[0], labs[0], cfg)
    np.testing.assert_allclose(aux["pos_threshold"], want.pos_thr, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(aux["neg_threshold"], want.neg_thr, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(aux["ident_num"], (want.same & want.select).sum(1))
    np.testing.assert_allclose(aux["diff_num"], (want.diff & want.select).sum(1))
    np.testing.assert_allclose(got_loss, want.loss, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(aux["sim_exp"], want.sim_exp, rtol=1e-5)


@pytest.mark.parametrize("ap_region,ap_method", AP_CELLS)
def test_ap_grid(rng, ap_region, ap_method):
    """Every AP (region, method) cell against the oracle (cu:277-306)."""
    cfg = NPairLossConfig(
        margin_ident=0.02,
        identsn=-0.4,
        ap_mining_region=ap_region,
        ap_mining_method=ap_method,
        an_mining_region=MiningRegion.LOCAL,
        an_mining_method=MiningMethod.RAND,
    )
    _check_cell(rng, cfg)


@pytest.mark.parametrize("an_region,an_method", AP_CELLS)
def test_an_grid(rng, an_region, an_method):
    """Every AN (region, method) cell against the oracle (cu:307-337)."""
    cfg = NPairLossConfig(
        margin_diff=-0.05,
        diffsn=-0.3,
        an_mining_region=an_region,
        an_mining_method=an_method,
        ap_mining_region=MiningRegion.LOCAL,
        ap_mining_method=MiningMethod.RAND,
    )
    _check_cell(rng, cfg)


@pytest.mark.parametrize("identsn,diffsn", [(0.0, 0.0), (1.0, 2.0), (-0.0, -0.3),
                                            (-0.5, -0.9), (3.0, 1.0)])
def test_relative_sn_values(rng, identsn, diffsn):
    """sn >= 0 rank-from-top vs sn < 0 top-fraction semantics (cu:285-287)."""
    cfg = NPairLossConfig(
        identsn=identsn,
        diffsn=diffsn,
        ap_mining_region=MiningRegion.LOCAL,
        ap_mining_method=MiningMethod.RELATIVE_HARD,
        an_mining_region=MiningRegion.GLOBAL,
        an_mining_method=MiningMethod.RELATIVE_EASY,
    )
    _check_cell(rng, cfg, num_ids=5, imgs_per_id=3)


def test_reference_def_prototxt_config(rng):
    """The exact shipped mining config (usage/def.prototxt:137-146)."""
    cfg = NPairLossConfig(
        margin_ident=0.0,
        margin_diff=-0.05,
        identsn=-0.0,
        diffsn=-0.3,
        ap_mining_region=MiningRegion.GLOBAL,
        ap_mining_method=MiningMethod.RELATIVE_HARD,
        an_mining_region=MiningRegion.LOCAL,
        an_mining_method=MiningMethod.HARD,
    )
    _check_cell(rng, cfg, num_ids=8, imgs_per_id=2, dim=16)


def test_negative_threshold_clamps_to_flt_max(rng):
    """Relative thresholds < 0 become -FLT_MAX (cu:288,303,319,334).

    Antipodal within-class features make every within-class similarity -1,
    so the AP relative lookup lands on a negative value and the clamp fires.
    """
    num_ids, dim = 4, 8
    f = np.zeros((num_ids * 2, dim), dtype=np.float32)
    lab = np.repeat(np.arange(num_ids), 2).astype(np.int32)
    for i in range(num_ids):
        f[2 * i, i] = 1.0
        f[2 * i + 1, i] = -1.0
    feats, labs = [f], [lab]
    cfg = NPairLossConfig(
        identsn=-0.5,
        diffsn=-0.5,
        ap_mining_region=MiningRegion.LOCAL,
        ap_mining_method=MiningMethod.RELATIVE_EASY,
        an_mining_region=MiningRegion.LOCAL,
        an_mining_method=MiningMethod.RELATIVE_HARD,
    )
    want = oracle.forward(feats, labs, cfg)[0]
    assert (want.pos_thr < -1e30).all(), "clamp should have fired"
    got_loss, aux = _run_jax(feats[0], labs[0], cfg)
    np.testing.assert_allclose(aux["pos_threshold"], want.pos_thr, rtol=1e-6)
    np.testing.assert_allclose(aux["neg_threshold"], want.neg_thr, rtol=1e-6)
    np.testing.assert_allclose(got_loss, want.loss, rtol=1e-5, atol=1e-7)


def test_rand_selects_all(rng):
    """RAND has no randomness — it selects every pair (cu:88-89, 109-110)."""
    feats, labs = make_identity_batch(rng, 4, 2, 8)
    cfg = NPairLossConfig(
        ap_mining_method=MiningMethod.RAND, an_mining_method=MiningMethod.RAND
    )
    want = oracle.forward(feats, labs, cfg)[0]
    assert (want.select == (want.same | want.diff)).all()
    _, aux = _run_jax(feats[0], labs[0], cfg)
    np.testing.assert_allclose(aux["ident_num"], want.same.sum(1))
    np.testing.assert_allclose(aux["diff_num"], want.diff.sum(1))


def test_zero_count_queries_contribute_zero(rng):
    """A query whose selection is empty adds exactly 0 loss (cu:162-169).

    HARD positive mining with a hugely negative margin deselects every
    positive; the loss must equal 0 (all queries invalid), not NaN.
    """
    feats, labs = make_identity_batch(rng, 4, 2, 8)
    cfg = NPairLossConfig(
        margin_ident=-100.0,
        ap_mining_method=MiningMethod.HARD,
        an_mining_method=MiningMethod.RAND,
    )
    want = oracle.forward(feats, labs, cfg)[0]
    got_loss, aux = _run_jax(feats[0], labs[0], cfg)
    assert want.loss == 0.0
    assert got_loss == 0.0
    assert np.isfinite(got_loss)


def test_self_pair_excluded(rng):
    """The diagonal (self) pair is in neither mask (cu:54)."""
    feats, labs = make_identity_batch(rng, 4, 2, 8)
    want = oracle.forward(feats, labs, NPairLossConfig())[0]
    n = feats[0].shape[0]
    for q in range(n):
        assert not want.same[q, q] and not want.diff[q, q]
    _, aux = _run_jax(feats[0], labs[0], NPairLossConfig())
    # ident_num for query q excludes itself: == (#same-label items) - 1.
    lab = labs[0]
    expect = np.array([(lab == lab[q]).sum() - 1 for q in range(n)])
    np.testing.assert_allclose(aux["ident_num"], expect)
