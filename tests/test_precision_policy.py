"""Mixed-precision policy tests (models.precision, ISSUE 7).

Covers: registry + regex-rule resolution semantics, the CLI choice pin,
policy-vs-legacy-constructor bit-identity (fp32_parity / bf16), the
bf16-convolution HLO pin on the default (mxu) policy, the flagship
policy-vs-fp32 loss-delta bound, and the Solver's policy->loss-engine
precision threading.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from npairloss_tpu.models.precision import (
    DEFAULT_POLICY,
    ModulePrecision,
    PrecisionPolicy,
    available_policies,
    get_policy,
    module_precision,
)

# ---------------------------------------------------------------------------
# Registry + resolution (pure, no jit)
# ---------------------------------------------------------------------------


def test_registry_vocabulary():
    assert available_policies() == ["bf16", "fp32_parity", "mxu"]
    assert DEFAULT_POLICY == "mxu"
    with pytest.raises(KeyError, match="unknown precision policy"):
        get_policy("fp16")  # not a thing here; must list the vocabulary
    pol = get_policy("mxu")
    assert get_policy(pol) is pol  # objects pass through


def test_shipped_policy_contents():
    mxu = get_policy("mxu")
    assert mxu.compute_dtype == jnp.bfloat16
    assert mxu.param_dtype == jnp.float32
    assert mxu.output_dtype == jnp.float32
    assert mxu.matmul_precision == "default"
    assert mxu.loss_matmul_precision == "default"
    par = get_policy("fp32_parity")
    assert par.compute_dtype == jnp.float32
    assert par.matmul_precision is None
    assert par.loss_matmul_precision is None
    bf16 = get_policy("bf16")
    assert bf16.compute_dtype == jnp.bfloat16
    assert bf16.loss_matmul_precision is None


def test_rule_resolution_first_match_wins():
    pol = PrecisionPolicy(
        name="t",
        compute_dtype=jnp.bfloat16,
        matmul_precision="default",
        rules=(
            (r"(^|/)conv1(/|$)", {"compute_dtype": jnp.float32,
                                  "matmul_precision": "highest"}),
            (r"conv", {"matmul_precision": None}),
        ),
    )
    # First rule wins for conv1 (both patterns match).
    mp = pol.resolve(("conv1",))
    assert mp.compute_dtype == jnp.float32
    assert mp.matmul_precision == "highest"
    assert mp.precision == jax.lax.Precision.HIGHEST
    # Second rule for other convs; overrides only what it names.
    mp = pol.resolve("inception_3a/b3x3_reduce/conv2")
    assert mp.compute_dtype == jnp.bfloat16
    assert mp.matmul_precision is None and mp.precision is None
    # No rule -> policy-wide defaults.
    mp = pol.resolve(("head",))
    assert mp.matmul_precision == "default"
    assert mp.precision == jax.lax.Precision.DEFAULT
    # Tuple and string paths resolve identically.
    assert pol.resolve(("a", "conv1")) == pol.resolve("a/conv1")


def test_rule_validation_is_loud():
    with pytest.raises(ValueError, match="unknown field"):
        PrecisionPolicy(name="bad", rules=(("x", {"dtype": jnp.float32}),))
    with pytest.raises(ValueError, match="matmul_precision"):
        PrecisionPolicy(name="bad", rules=(("x", {"matmul_precision":
                                                  "fast"}),))
    with pytest.raises(re.error):
        PrecisionPolicy(name="bad", rules=(("(", {}),))
    with pytest.raises(ValueError, match="matmul_precision must be"):
        PrecisionPolicy(name="bad", matmul_precision="fastest")


def test_module_precision_fallback_matches_prepolicy_defaults():
    mp = module_precision(None, ("anything",), jnp.bfloat16)
    assert mp == ModulePrecision(param_dtype=jnp.float32,
                                 compute_dtype=jnp.bfloat16,
                                 matmul_precision=None)
    assert mp.precision is None


def test_describe_is_jsonable():
    import json

    d = get_policy("mxu").describe()
    json.dumps(d)
    assert d["name"] == "mxu" and d["compute_dtype"] == "bfloat16"


def test_cli_choices_pinned_to_registry():
    """cli._PRECISION_CHOICES is hardcoded (argparse must stay jax-free
    for the bench parent contract); this pin makes drift a failure."""
    from npairloss_tpu.cli import _PRECISION_CHOICES

    assert sorted(_PRECISION_CHOICES) == available_policies()


# ---------------------------------------------------------------------------
# Model threading (small trunks: cheap jits)
# ---------------------------------------------------------------------------


def _tiny_vit(**kw):
    from npairloss_tpu.models import get_model

    return get_model("vit_b16", patch=8, hidden=32, depth=1, num_heads=2,
                     mlp_dim=64, **kw)


def test_policy_equals_legacy_dtype_constructors_tiny():
    """fp32_parity == dtype=fp32 and bf16 == dtype=bf16, bit for bit,
    on the ViT trunk (policy threaded through Dense/attention/patchify)
    and the MLP (compute-dtype-only threading)."""
    from npairloss_tpu.models import get_model, jit_init

    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (4, 16, 16, 3)).astype(np.float32))
    key = jax.random.PRNGKey(0)
    for name, kw in (("vit", {}), ("mlp", {})):
        mk = _tiny_vit if name == "vit" else (
            lambda **k: get_model("mlp", hidden=(32,), embedding_dim=16,
                                  **k))
        v = jit_init(mk(dtype=jnp.float32), key, x)
        for policy, dtype in (("fp32_parity", jnp.float32),
                              ("bf16", jnp.bfloat16)):
            legacy = mk(dtype=dtype)
            poliy = mk(policy=policy)
            o1 = jax.jit(
                lambda v_, x_: legacy.apply(v_, x_, train=False))(v, x)
            o2 = jax.jit(
                lambda v_, x_: poliy.apply(v_, x_, train=False))(v, x)
            np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))


def test_vit_rules_resolve_at_named_submodule_paths():
    """A rule targeting "patchify" or "attn" must actually match: the
    ViT modules resolve at the NAMED submodule's path, not their own
    (a root-path resolution silently no-ops such rules)."""
    pol = PrecisionPolicy(
        name="pin",
        compute_dtype=jnp.bfloat16,
        rules=(
            (r"(^|/)patchify(/|$)", {"param_dtype": jnp.bfloat16}),
            (r"(^|/)attn(/|$)", {"param_dtype": jnp.float16}),
        ),
    )
    x = jax.ShapeDtypeStruct((2, 16, 16, 3), jnp.float32)
    v = jax.eval_shape(
        lambda k, xx: _tiny_vit(policy=pol).init(k, xx, train=False),
        jax.random.PRNGKey(0), x)
    params = v["params"]
    assert params["patchify"]["kernel"].dtype == jnp.bfloat16
    assert params["block0"]["attn"]["query"]["kernel"].dtype == jnp.float16
    assert params["block0"]["mlp"]["Dense_0"]["kernel"].dtype == jnp.float32


def test_get_model_policy_sets_compute_dtype_everywhere():
    from npairloss_tpu.models import get_model

    m = get_model("mlp", policy="mxu")
    assert m.dtype == jnp.bfloat16  # compute dtype honored sans threading
    m = _tiny_vit(policy="mxu")
    assert m.policy is not None and m.policy.name == "mxu"


def test_default_policy_hlo_contains_bf16_convolutions():
    """THE pin of the tentpole's point: the flagship trunk under the
    default (mxu) policy lowers to bf16 convolutions (bf16 operands
    feeding conv ops), while fp32_parity lowers none.  Lowering only —
    no XLA compile — so this stays cheap."""
    from npairloss_tpu.models import FLAGSHIP_POLICY, flagship_model
    from npairloss_tpu.parallel._compat import lowered_text

    assert FLAGSHIP_POLICY == DEFAULT_POLICY
    x_sds = jax.ShapeDtypeStruct((2, 32, 32, 3), jnp.float32)
    key = jax.random.PRNGKey(0)

    def conv_lines(model):
        vars_sds = jax.eval_shape(
            lambda k, xx: model.init(k, xx, train=False), key, x_sds)
        low = jax.jit(
            lambda v_, x_: model.apply(v_, x_, train=False)
        ).lower(vars_sds, x_sds)
        # Op lines only ("stablehlo.convolution"/HLO "convolution(") —
        # NOT MLIR #loc debug lines, which quote Python names (this
        # test's own name contains both "convolution" and "bf16"...).
        lines = [ln for ln in lowered_text(low).splitlines()
                 if re.search(r"\bconvolution\b\s*\(|stablehlo\."
                              r"convolution", ln)]
        assert lines, "no convolutions in the lowered trunk?"
        return lines

    bf16_re = re.compile(r"\bbf16\b|xbf16>")
    bf16_lines = [ln for ln in conv_lines(flagship_model())
                  if bf16_re.search(ln)]
    assert bf16_lines, "default policy lowered no bf16 convolutions"
    fp32_lines = [ln for ln in
                  conv_lines(flagship_model(policy="fp32_parity"))
                  if bf16_re.search(ln)]
    assert not fp32_lines, "fp32_parity policy lowered bf16 convolutions"


@pytest.mark.slow
def test_flagship_policy_loss_delta_bounded():
    """Same flagship trunk, same params, same batch: |loss(mxu) -
    loss(fp32_parity)| stays small (the acceptance bound bench.py
    reports at full scale as policy_fp32_loss_delta).  Slow-marked:
    two GoogLeNet jits (~12s); every bench headline record re-reports
    the delta at full scale and the tier-1 HLO pin covers the policy
    threading itself."""
    from npairloss_tpu import REFERENCE_CONFIG
    from npairloss_tpu.models import flagship_model, jit_init
    from npairloss_tpu.ops.npair_loss import npair_loss

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 32, 32, 3)).astype(np.float32))
    lab = jnp.asarray(np.repeat(np.arange(4), 2).astype(np.int32))
    key = jax.random.PRNGKey(0)
    m_pol = flagship_model()
    m_32 = flagship_model(policy="fp32_parity")
    v = jit_init(m_pol, key, x)  # fp32 master params: shared verbatim

    def loss_of(model, precision):
        def f(v_, x_, l_):
            emb = model.apply(v_, x_, train=False)
            return npair_loss(emb, l_, REFERENCE_CONFIG,
                              matmul_precision=precision)

        return float(jax.jit(f)(v, x, lab))

    l_pol = loss_of(m_pol, get_policy("mxu").loss_matmul_precision)
    l_32 = loss_of(m_32, None)
    assert np.isfinite(l_pol) and np.isfinite(l_32)
    # bf16 trunk rounding at 1024-d embeddings: the observed delta is
    # ~1e-3-level; 5e-2 is the "policies agree on the objective" bound,
    # far below any mining-decision flip at flagship margins.
    assert abs(l_pol - l_32) < 5e-2, (l_pol, l_32)


# ---------------------------------------------------------------------------
# Solver threading
# ---------------------------------------------------------------------------


def test_solver_precision_supplies_loss_matmul_precision():
    from npairloss_tpu import REFERENCE_CONFIG
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver

    mk = lambda: get_model("mlp", hidden=(32,), embedding_dim=16,
                           policy="mxu")
    s = Solver(mk(), REFERENCE_CONFIG, precision="mxu",
               input_shape=(16, 16, 3))
    assert s.matmul_precision == "default"
    assert s.precision_policy.name == "mxu"
    # An explicit matmul_precision outranks the policy's default.
    s = Solver(mk(), REFERENCE_CONFIG, precision="mxu",
               matmul_precision="highest", input_shape=(16, 16, 3))
    assert s.matmul_precision == "highest"
    # No policy: everything stays None (oracle-parity engines).
    s = Solver(mk(), REFERENCE_CONFIG, input_shape=(16, 16, 3))
    assert s.precision_policy is None and s.matmul_precision is None


def test_solver_precision_trains_a_step():
    """End-to-end: a policy-built MLP + precision="mxu" Solver takes a
    finite step (the loss engines trace under the policy's single-pass
    precision)."""
    from npairloss_tpu import REFERENCE_CONFIG
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 8, 8, 3)).astype(np.float32)
    lab = np.repeat(np.arange(4), 2).astype(np.int32)
    s = Solver(
        get_model("mlp", hidden=(16,), embedding_dim=8, policy="mxu"),
        REFERENCE_CONFIG,
        SolverConfig(display=0, snapshot=0),
        input_shape=(8, 8, 3),
        precision="mxu",
    )
    m = s.step(x, lab)
    assert np.isfinite(float(m["loss"]))
