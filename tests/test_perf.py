"""Perf observatory (npairloss_tpu/obs/perf/ + scripts/bench_check.py —
docs/OBSERVABILITY.md §Perf observatory).

Pins: the one shared cost/MFU helper (list-vs-dict cost_analysis,
missing keys), named-scope -> region aggregation on a toy 2-scope
jitted fn, roofline bound-class classification on synthetic fixtures,
span-stream step-time decomposition with the exact reconciliation
invariant, serve-span latency splits, the versioned report schema, and
the bench_check regression gate's pass/fail/noise-widening semantics.
All tier-1-fast: no device profiler, tiny jitted programs only.
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- costs: the one shared helper ---------------------------------------------

class _Stage:
    def __init__(self, ret=None, raise_=False):
        self._ret, self._raise = ret, raise_

    def cost_analysis(self):
        if self._raise:
            raise RuntimeError("no analysis on this backend")
        return self._ret


def test_cost_helper_list_vs_dict_and_missing():
    """The cross-version return shapes and missing keys are handled in
    ONE place (the dedup satellite's whole point)."""
    from npairloss_tpu.obs.perf.costs import (
        cost_analysis_dict,
        cost_flops,
        mfu_from_timing,
    )

    assert cost_flops(_Stage({"flops": 10.0})) == 10.0
    assert cost_flops(_Stage([{"flops": 7.0}])) == 7.0  # older jax: [dict]
    assert cost_flops(_Stage({})) is None               # missing key
    assert cost_flops(_Stage({"flops": 0.0})) is None   # non-positive
    assert cost_flops(_Stage(raise_=True)) is None      # degrade, not raise
    assert cost_analysis_dict(_Stage([])) == {}
    assert cost_analysis_dict(
        _Stage({"flops": 1.0, "bad": "x"})) == {"flops": 1.0}

    est = mfu_from_timing(_Stage({"flops": 275e12}), seconds=1.0,
                          steps=1, device_kind="TPU v4")
    assert est["step_flops"] == 275e12
    assert est["mfu"] == pytest.approx(1.0)
    # Unknown chip / no analysis: keys present, values None.
    est = mfu_from_timing(_Stage(raise_=True), seconds=1.0,
                          device_kind="quantum abacus")
    assert est == {"step_flops": None, "mfu": None}


def test_exactly_one_mfu_helper_home():
    """utils.profiling re-exports the SAME objects — no second
    implementation survives anywhere."""
    from npairloss_tpu.obs.perf import costs
    from npairloss_tpu.utils import profiling

    assert profiling.cost_flops is costs.cost_flops
    assert profiling.peak_flops is costs.peak_flops
    assert profiling.PEAK_FLOPS is costs.PEAK_FLOPS
    assert profiling.mfu_from_timing is costs.mfu_from_timing


# -- hlo: region aggregation --------------------------------------------------

def test_region_of_paths():
    from npairloss_tpu.obs.perf.hlo import UNSCOPED, region_of

    assert region_of(
        "jit(step)/jit(main)/jvp(npair/sim)/dot_general") == "npair/sim"
    assert region_of(
        "jit(step)/jit(main)/transpose(jvp(MLPEmbedding))/head/dot_general"
    ) == "MLPEmbedding/head"
    # scan/while structural segments vanish; the scope survives.
    assert region_of(
        "jit(topk)/jit(main)/while/body/serve/score/dot") == "serve/score"
    assert region_of("jit(f)/jit(main)/add") == UNSCOPED
    assert region_of("x") == UNSCOPED
    assert region_of("") == UNSCOPED
    # depth truncation
    assert region_of(
        "jit(s)/jit(main)/jvp(A)/b/c/prim", depth=1) == "A"
    assert region_of(
        "jit(s)/jit(main)/jvp(A)/b/c/prim", depth=0) == "A/b/c"


def test_named_scope_region_aggregation_toy():
    """A 2-scope jitted fn attributes its gemm EXACTLY to its scope
    (2*M*N*K) with bytes and a nonzero elementwise share in the other,
    reconciling against XLA's own total."""
    import jax
    import jax.numpy as jnp

    from npairloss_tpu.obs.perf import (
        attribute_regions,
        cost_flops,
        stage_hlo_text,
    )

    n = 64

    def f(x):
        with jax.named_scope("regA"):
            y = x @ x
        with jax.named_scope("regB"):
            return jnp.sum(jnp.tanh(y))

    comp = jax.jit(f).lower(
        jax.ShapeDtypeStruct((n, n), jnp.float32)).compile()
    regions = attribute_regions(stage_hlo_text(comp))
    regions.pop("_notes", None)
    assert "regA" in regions and "regB" in regions
    assert regions["regA"]["flops"] == 2.0 * n * n * n
    assert regions["regA"]["bytes"] > 0
    assert regions["regB"]["flops"] >= n * n  # tanh at least
    total = sum(r["flops"] for r in regions.values())
    xla = cost_flops(comp)
    assert xla is not None
    assert total == pytest.approx(xla, rel=0.2)


def test_instr_regex_matches_tpu_tiled_layouts():
    """TPU-optimized HLO stamps tiled layouts on result types
    (``f32[8,16]{1,0:T(8,128)}``, conv tiles like ``T(8,128)(2,1)``);
    the instruction regex must still match them.  CPU HLO carries no
    tiling, so only this pin catches the chip-only parse miss (which
    would silently empty the region table exactly on the platform the
    observatory targets)."""
    from npairloss_tpu.obs.perf.hlo import _INSTR_RE, _shapes_in

    m = _INSTR_RE.match(
        "  %fusion.1 = f32[8,16]{1,0:T(8,128)} fusion(%p0), kind=kLoop")
    assert m and m.group("opcode") == "fusion"
    assert _shapes_in(m.group("type")) == [("f32", (8, 16))]
    m = _INSTR_RE.match(
        "  ROOT %conv.2 = f32[4,14,14,32]{3,2,1,0:T(8,128)(2,1)} "
        "convolution(%a, %b), window={size=3x3}")
    assert m and m.group("opcode") == "convolution"
    assert _shapes_in(m.group("type")) == [("f32", (4, 14, 14, 32))]
    m = _INSTR_RE.match(
        "  %dot.3 = bf16[128,256]{1,0:T(8,128)(2,1)S(1)} dot(%x, %y)")
    assert m and m.group("opcode") == "dot"


def test_scan_body_multiplied_by_trip_count():
    """A lax.scan body's flops count once per trip (XLA's
    known_trip_count backend_config, else the condition-compare
    heuristic — found via the ``condition=`` attribute, not by call
    order: HLO prints condition before body).  Scan-based programs
    (ring/blockwise engines, the serve gallery stream) would otherwise
    undercount by the trip factor."""
    import jax
    import jax.numpy as jnp

    from npairloss_tpu.obs.perf import attribute_regions, stage_hlo_text

    n, trips = 8, 7

    def f(x):
        def body(c, _):
            with jax.named_scope("scanreg"):
                return c @ c, None
        y, _ = jax.lax.scan(body, x, None, length=trips)
        return jnp.sum(y)

    comp = jax.jit(f).lower(
        jax.ShapeDtypeStruct((n, n), jnp.float32)).compile()
    regions = attribute_regions(stage_hlo_text(comp))
    notes = regions.pop("_notes", [])
    assert not any("trip_count_unknown" in str(x) for x in notes)
    assert regions["scanreg"]["flops"] == trips * 2.0 * n * n * n


# -- roofline -----------------------------------------------------------------

def test_roofline_classification_fixtures():
    from npairloss_tpu.obs.perf.roofline import (
        BOUND_CLASSES,
        chip_peaks,
        classify,
    )

    spec = chip_peaks("TPU v4")
    assert spec.known
    # High arithmetic intensity: way right of the ridge -> compute.
    c = classify(flops=spec.flops, bytes_accessed=1.0, spec=spec)
    assert c["bound"] == "compute"
    assert c["ai"] == pytest.approx(spec.flops)
    assert c["est_ms_at_roofline"] == pytest.approx(1e3)
    # One byte per flop: far left of the ridge -> memory.
    m = classify(flops=1e9, bytes_accessed=1e9, spec=spec)
    assert m["bound"] == "memory"
    # Interconnect-dominated -> collective.
    i = classify(flops=1.0, bytes_accessed=1.0,
                 collective_bytes=spec.ici_bytes_per_s, spec=spec)
    assert i["bound"] == "collective"
    assert i["est_ms_at_roofline"] == pytest.approx(1e3)
    # Nothing at all -> unknown.
    assert classify(0.0, 0.0, 0.0, spec)["bound"] == "unknown"
    assert all(x in BOUND_CLASSES
               for x in ("compute", "memory", "collective", "unknown"))
    # Unknown device kinds fall back, flagged.
    assert not chip_peaks("cpu").known
    assert not chip_peaks("").known


# -- decompose ----------------------------------------------------------------

def _ev(name, ts, dur, tid=1):
    return {"name": name, "ph": "X", "ts": ts, "dur": dur, "tid": tid}


def test_decompose_reconciles_and_nests():
    from npairloss_tpu.obs.perf.decompose import decompose_step_time

    events = [
        _ev("data/next_batch", 0, 1000),
        _ev("step/dispatch", 1000, 2000),
        _ev("step/device_wait", 3000, 4000),
        # eval contains eval/compile: self-time split, no double count.
        _ev("eval", 7000, 3000),
        _ev("eval/compile", 7500, 1000),
        # A staging-thread span must NOT be summed into the loop wall.
        _ev("pipeline/stage", 0, 9000, tid=2),
    ]
    dec = decompose_step_time(events, wall_ms=12.0)
    parts = dec["parts"]
    assert parts["data_wait"] == pytest.approx(1.0)
    assert parts["dispatch"] == pytest.approx(2.0)
    assert parts["device_compute"] == pytest.approx(4.0)
    assert parts["compile"] == pytest.approx(1.0)   # nested slice only
    assert parts["eval"] == pytest.approx(2.0)      # self time
    assert "h2d" not in parts                       # other thread
    # THE invariant: sum(parts) + unattributed == wall, exactly.
    assert sum(parts.values()) + dec["unattributed_ms"] == pytest.approx(
        dec["wall_ms"], abs=1e-6)
    assert dec["unattributed_ms"] == pytest.approx(2.0)


def test_decompose_unattributed_never_silently_absorbed():
    from npairloss_tpu.obs.perf.decompose import decompose_step_time

    dec = decompose_step_time([], wall_ms=5.0)
    assert dec["parts"] == {}
    assert dec["unattributed_ms"] == pytest.approx(5.0)


def test_decompose_serve_mode_admits_stage_categories():
    """A serve-step decomposition carries the serving stages as
    first-class parts (train mode still buries them in other_span — its
    category vocabulary is pinned); reconciliation holds either way."""
    from npairloss_tpu.obs.perf.decompose import decompose_step_time

    events = [_ev("serve/topk", 0, 2000), _ev("serve/encode", 3000, 1000)]
    dec = decompose_step_time(events, wall_ms=5.0, serve=True)
    assert dec["parts"]["topk"] == pytest.approx(2.0)
    assert dec["parts"]["encode"] == pytest.approx(1.0)
    assert sum(dec["parts"].values()) + dec["unattributed_ms"] == \
        pytest.approx(dec["wall_ms"], abs=1e-6)
    train = decompose_step_time(events, wall_ms=5.0)
    assert "topk" not in train["parts"]
    assert train["parts"]["other_span"] == pytest.approx(3.0)


def test_serve_span_decomposition_from_recorded_stream():
    from npairloss_tpu.obs.perf.decompose import (
        serve_latency_decomposition,
    )

    events = []
    # 100 topk spans of 1..100 ms, a few encode spans, on mixed tids.
    for i in range(100):
        events.append(_ev("serve/topk", i * 2000, (i + 1) * 1000,
                          tid=i % 3))
    for i in range(4):
        events.append(_ev("serve/encode", i * 500, 2000))
    events.append(_ev("serve/batch", 0, 3000))
    events.append(_ev("step/dispatch", 0, 1000))  # not a serve stage
    split = serve_latency_decomposition(events)
    assert set(split) == {"topk", "encode", "batch"}
    assert split["topk"]["count"] == 100
    assert split["topk"]["p50_ms"] == pytest.approx(50.0, abs=2.0)
    assert split["topk"]["p99_ms"] == pytest.approx(99.0, abs=2.0)
    assert split["encode"]["p50_ms"] == pytest.approx(2.0)
    # since_us cuts the window.
    late = serve_latency_decomposition(events, since_us=150_000)
    assert late["topk"]["count"] < 100


def test_serve_window_counts_boundary_straddling_spans():
    """The window cursor filters on span END: a long span in flight
    across the boundary belongs to the window it finished in — start-
    time filtering would drop exactly the longest (tail) spans and
    bias p99 low."""
    from npairloss_tpu.obs.perf.decompose import (
        serve_latency_decomposition,
    )

    straddler = _ev("serve/dispatch", 900, 5000)   # ends at 5900
    done_early = _ev("serve/dispatch", 0, 500)     # ends at 500
    split = serve_latency_decomposition(
        [straddler, done_early], since_us=1000)
    assert split["dispatch"]["count"] == 1
    assert split["dispatch"]["p99_ms"] == pytest.approx(5.0)


def test_tracer_events_since_incremental():
    """The serve windows' incremental read: each call returns only the
    spans FINISHED since the last cursor, O(window) not O(buffer), and
    surfaces the max_events drop count."""
    from npairloss_tpu.obs.tracing import SpanTracer

    tracer = SpanTracer(max_events=3)
    with tracer.span("serve/topk"):
        pass
    evs, idx, dropped = tracer.events_since(0)
    assert [e["name"] for e in evs] == ["serve/topk"] and dropped == 0
    with tracer.span("serve/encode"):
        with tracer.span("serve/dispatch"):
            pass
    evs, idx, dropped = tracer.events_since(idx)
    # Appends happen at span END — the nested span closed first.
    assert [e["name"] for e in evs] == ["serve/dispatch", "serve/encode"]
    with tracer.span("serve/topk"):  # over the cap: dropped, reported
        pass
    evs, idx, dropped = tracer.events_since(idx)
    assert evs == [] and dropped == 1


# -- report schema ------------------------------------------------------------

def test_report_schema_pinned_and_validator():
    import jax
    import jax.numpy as jnp

    from npairloss_tpu.obs.perf import (
        REPORT_SCHEMA,
        build_report,
        render_table,
        validate_report,
    )
    from npairloss_tpu.obs.perf.report import REGION_KEYS

    def f(x):
        with jax.named_scope("regA"):
            return jnp.sum(x @ x)

    comp = jax.jit(f).lower(
        jax.ShapeDtypeStruct((32, 32), jnp.float32)).compile()
    events = [_ev("step/dispatch", 0, 4000),
              _ev("step/device_wait", 4000, 5000)]
    report = build_report(
        step="train", device_kind="TPU v4", batch=32, stage=comp,
        span_events=events, wall_ms=10.0, ms_per_step=10.0, steps=1,
    )
    assert validate_report(report) is None
    assert report["schema"] == REPORT_SCHEMA
    names = {r["region"] for r in report["regions"]}
    assert "regA" in names
    for row in report["regions"]:
        for key in REGION_KEYS:
            assert key in row, key
    # Round-trips through JSON (the on-disk artifact).
    assert validate_report(json.loads(json.dumps(report))) is None
    assert "regA" in render_table(report)

    # Validator teeth: bad bound, missing key, broken reconciliation.
    bad = json.loads(json.dumps(report))
    bad["regions"][0]["bound"] = "quantum"
    assert "bound" in validate_report(bad)
    bad = json.loads(json.dumps(report))
    del bad["regions"][0]["ai"]
    assert "ai" in validate_report(bad)
    bad = json.loads(json.dumps(report))
    bad["decomposition"]["unattributed_ms"] += 5.0
    assert "reconcile" in validate_report(bad)
    assert validate_report({"schema": "nope"}) is not None


# -- solver perf rows ---------------------------------------------------------

def test_solver_perf_rows_opt_in(tmp_path):
    """perf_metrics=True emits one phase="perf" row per display window
    (ms_per_step + emb_per_sec, MFU only when the chip is known); the
    default emits NONE (the sync-vs-pipelined byte-parity contract
    covers perf rows only when both runs opt in)."""
    from conftest import make_identity_batch

    from npairloss_tpu import NPairLossConfig
    from npairloss_tpu.models import get_model
    from npairloss_tpu.obs import RunTelemetry
    from npairloss_tpu.train import Solver, SolverConfig

    def run(tag, perf):
        rng = np.random.default_rng(0)

        def batches():
            while True:
                (f,), (l,) = make_identity_batch(rng, 4, 2, 8)
                yield f, l

        solver = Solver(
            get_model("mlp", hidden=(8,), embedding_dim=4),
            NPairLossConfig(),
            SolverConfig(base_lr=0.01, lr_policy="fixed", display=2,
                         snapshot=0, test_interval=0),
            input_shape=(8,), perf_metrics=perf,
        )
        tel = RunTelemetry(str(tmp_path / tag), trace=False)
        solver.telemetry = tel
        try:
            solver.train(batches(), num_iters=4, log_fn=lambda s: None)
        finally:
            tel.close()
        rows = [json.loads(line)
                for line in open(tmp_path / tag / "metrics.jsonl")]
        return [r for r in rows if r["phase"] == "perf"]

    perf_rows = run("on", True)
    # display=2 over 4 steps -> boundaries at 2 and 4; the first arms
    # the window, the second emits.
    assert len(perf_rows) == 1
    row = perf_rows[0]
    assert row["step"] == 4
    assert row["ms_per_step"] > 0
    assert row["emb_per_sec"] > 0
    assert row["step_flops"] > 0
    assert "mfu" not in row  # CPU: unknown peak -> no made-up MFU
    assert run("off", False) == []


# -- serve window breakdown ---------------------------------------------------

def test_serve_summary_latency_split(tmp_path):
    """The drain summary (and window rows) carry the per-stage p50/p99
    split read from the serve/* spans."""
    from npairloss_tpu.obs import RunTelemetry
    from npairloss_tpu.serve import (
        EngineConfig,
        GalleryIndex,
        QueryEngine,
        RetrievalServer,
    )
    from npairloss_tpu.serve.batcher import BatcherConfig

    rng = np.random.default_rng(0)
    emb = rng.standard_normal((64, 16)).astype(np.float32)
    index = GalleryIndex.build(emb, np.arange(64).astype(np.int32) % 8)
    tel = RunTelemetry(str(tmp_path / "serve"), metrics=True)
    engine = QueryEngine(index, EngineConfig(top_k=3, buckets=(1, 4)),
                         telemetry=tel)
    engine.warmup()
    server = RetrievalServer(
        engine, BatcherConfig(max_batch=4, max_delay_ms=10.0),
        telemetry=tel,
    )
    server.batcher.start()
    try:
        answers = server.handle_many([
            {"id": i, "embedding": emb[i].tolist()} for i in range(6)
        ])
    finally:
        server.batcher.close(drain=True)
    assert all("neighbors" in a for a in answers)
    s = server.summary()
    assert "topk_p50_ms" in s and "topk_p99_ms" in s
    assert s["topk_p50_ms"] > 0
    tel.close()


def test_server_latency_split_excludes_warmup_spans(tmp_path):
    """Pre-construction serve/* spans (warmup's XLA compiles — cmd_serve
    warms the engine BEFORE building the server) never enter the window
    rows or the drain summary: both cursors baseline at construction,
    so seconds-long compile spans can't masquerade as serving p99."""
    from npairloss_tpu.obs import RunTelemetry
    from npairloss_tpu.serve import (
        EngineConfig,
        GalleryIndex,
        QueryEngine,
        RetrievalServer,
    )
    from npairloss_tpu.serve.batcher import BatcherConfig

    rng = np.random.default_rng(0)
    emb = rng.standard_normal((16, 8)).astype(np.float32)
    index = GalleryIndex.build(emb, np.arange(16).astype(np.int32) % 4)
    tel = RunTelemetry(str(tmp_path / "serve"), metrics=True)
    with tel.tracer.span("serve/topk"):  # the "warmup compile" span
        pass
    engine = QueryEngine(index, EngineConfig(top_k=3, buckets=(1,)),
                         telemetry=tel)
    server = RetrievalServer(engine, BatcherConfig(max_batch=1),
                             telemetry=tel)
    s = server.summary()  # zero queries served -> zero split keys
    assert not any(k.startswith("topk_") for k in s)
    assert not server._window_latency_split()
    tel.close()


# -- bench_check gate ---------------------------------------------------------

def _load_bench_check():
    spec = importlib.util.spec_from_file_location(
        "_bench_check", os.path.join(REPO, "scripts", "bench_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _rec(value, windows=None, extras=None):
    rec = {"metric": "m", "unit": "u", "mode": "full", "value": value}
    if windows is not None:
        rec["ms_per_step_windows"] = windows
    if extras is not None:
        rec["extras"] = extras
    return rec


def test_bench_check_pass_and_fail():
    bc = _load_bench_check()
    # Improving trajectory: clean.
    assert bc.check([("r1", _rec(4000.0)), ("r2", _rec(4300.0))]) == []
    # Regressed headline: violation.
    v = bc.check([("r1", _rec(4300.0)), ("r2", _rec(3000.0))])
    assert len(v) == 1 and "headline" in v[0]
    # Within base tolerance: clean.
    assert bc.check([("r1", _rec(4300.0)), ("r2", _rec(4200.0))]) == []
    # Single record: nothing to gate.
    assert bc.check([("r1", _rec(4300.0))]) == []


def test_bench_check_noise_widens_gate():
    """Two-window-min semantics: a reference whose own windows spread
    20% cannot condemn a 15% drop — its min is not trustworthy to 5%."""
    bc = _load_bench_check()
    noisy_ref = _rec(4300.0, windows=[25.0, 30.0])  # 20% spread
    assert bc.check([("r1", noisy_ref), ("r2", _rec(3700.0))]) == []
    tight_ref = _rec(4300.0, windows=[25.0, 25.2])
    assert len(bc.check([("r1", tight_ref), ("r2", _rec(3700.0))])) == 1


def test_bench_check_rows_and_p99():
    bc = _load_bench_check()
    base = _rec(4300.0, extras={
        "ring_abs": {"emb_per_sec": 2.0e6,
                     "ms_per_step_windows": [2.0, 2.05]},
        "serve_qps": {"p99_ms": 10.0},
        "batch_scaling": {"240": {"emb_per_sec": 4500.0}},
    })
    good = _rec(4310.0, extras={
        "ring_abs": {"emb_per_sec": 1.99e6,
                     "ms_per_step_windows": [2.0, 2.1]},
        "serve_qps": {"p99_ms": 10.2},
        "batch_scaling": {"240": {"emb_per_sec": 4490.0}},
    })
    assert bc.check([("r1", base), ("r2", good)]) == []
    bad = _rec(4310.0, extras={
        "ring_abs": {"emb_per_sec": 1.2e6},          # -40%
        "serve_qps": {"p99_ms": 30.0},               # 3x p99
        "batch_scaling": {"240": {"error": "wedged"}},  # not a row
    })
    v = bc.check([("r1", base), ("r2", bad)])
    assert any("ring_abs" in x for x in v)
    assert any("serve_qps" in x and "p99" in x for x in v)
    assert not any("batch_scaling" in x for x in v)


def test_bench_check_offline_on_committed_artifacts():
    """The ci.sh wiring: the committed BENCH_r01..r05 trajectory must
    pass the gate (it improved every measured round)."""
    bc = _load_bench_check()
    records = bc.load_offline_records()
    assert len(records) >= 2  # r02 + last_good at minimum
    assert bc.check(records) == []
    # And main() agrees end to end.
    assert bc.main(["--offline"]) == 0


def test_bench_check_ivf_hard_gates():
    """The approximate-index row's ABSOLUTE gates (ISSUE 11): recall@1
    below the hard floor or an IVF/flat qps ratio under the speedup
    floor is a violation regardless of trajectory noise; clean rows
    and absent rows gate nothing."""
    bc = _load_bench_check()
    base = _rec(4300.0, extras={"serve_qps": {"p99_ms": 10.0}})

    def scale_rec(recall, ivf_qps, flat_qps):
        return _rec(4310.0, extras={
            "serve_qps": {"p99_ms": 10.0},
            "flat_qps_1m": {"p99_ms": 800.0, "qps": flat_qps},
            "ivf_qps_1m": {"p99_ms": 70.0, "qps": ivf_qps,
                           "recall_at_1": recall},
        })

    # Healthy: 8x speedup at recall 1.0 — clean.
    assert bc.check([("r1", base), ("r2", scale_rec(1.0, 130.0, 16.0))]) \
        == []
    # Recall under the floor: hard violation.
    v = bc.check([("r1", base), ("r2", scale_rec(0.80, 130.0, 16.0))])
    assert any("recall@1" in x for x in v), v
    # Speedup under the floor: hard violation.
    v = bc.check([("r1", base), ("r2", scale_rec(1.0, 40.0, 16.0))])
    assert any("flat qps" in x for x in v), v
    # IVF row absent: coverage unchanged, nothing to gate.
    assert bc.check([("r1", base), ("r2", base)]) == []
    # The committed BENCH_r07 evidence must clear both hard gates.
    records = bc.load_offline_records()
    rows = bc._walk_rows(records[-1][1])
    assert "ivf_qps_1m" in rows, "committed ivf_qps_1m row missing"
    assert bc._ivf_hard_gates(rows) == []


def test_bench_check_skips_degraded_and_reused():
    bc = _load_bench_check()
    assert not bc._is_measurement(
        {"value": 4000.0, "degraded": True, "stale": True})
    assert not bc._is_measurement({"value": 4000.0,
                                   "headline_reused": True})
    assert not bc._is_measurement({"value": 0.0})
    assert not bc._is_measurement({"value": 100.0, "mode": "smoke"})
    assert bc._is_measurement({"value": 4000.0})
