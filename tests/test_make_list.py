"""tools/make_list.py: list-file generation for the MultibatchData
``source`` contract (class-per-directory trees, zero-shot class split,
singleton dropping)."""

import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_tree(root, classes):
    ppm = b"P6\n4 4\n255\n" + bytes(4 * 4 * 3)
    for name, n in classes:
        d = root / name
        d.mkdir(parents=True)
        for i in range(n):
            (d / f"img_{i}.ppm").write_bytes(ppm)


def _run(*argv):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "make_list.py"), *argv],
        capture_output=True, text=True,
    )


def test_single_list_and_labels(tmp_path):
    _make_tree(tmp_path, [("b_class", 3), ("a_class", 2), ("single", 1)])
    out = tmp_path / "all.txt"
    r = _run(str(tmp_path), "--out", str(out))
    assert r.returncode == 0, r.stderr
    lines = [l.split() for l in out.read_text().splitlines()]
    # classes sorted by name -> a_class=0, b_class=1; singleton dropped
    assert len(lines) == 5
    labels = sorted({int(l[-1]) for l in lines})
    assert labels == [0, 1]
    assert "dropping" in r.stderr and "single" in r.stderr
    # paths resolve under root and load through ListFileDataset
    from npairloss_tpu.data.dataset import ListFileDataset

    ds = ListFileDataset(str(tmp_path), str(out))
    assert len(ds.labels) == 5
    img = ds.load(0)
    assert img.shape[-1] == 3


def test_zero_shot_split(tmp_path):
    _make_tree(tmp_path, [(f"c{i:02d}", 2) for i in range(6)])
    tr, te = tmp_path / "train.txt", tmp_path / "test.txt"
    r = _run(str(tmp_path), "--split-classes", "4",
             "--out-train", str(tr), "--out-test", str(te))
    assert r.returncode == 0, r.stderr
    tr_labels = {int(l.split()[-1]) for l in tr.read_text().splitlines()}
    te_labels = {int(l.split()[-1]) for l in te.read_text().splitlines()}
    assert tr_labels == {0, 1, 2, 3}
    assert te_labels == {4, 5}
