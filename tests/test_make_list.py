"""tools/make_list.py: list-file generation for the MultibatchData
``source`` contract (class-per-directory trees, zero-shot class split,
singleton dropping)."""

import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_tree(root, classes):
    ppm = b"P6\n4 4\n255\n" + bytes(4 * 4 * 3)
    for name, n in classes:
        d = root / name
        d.mkdir(parents=True)
        for i in range(n):
            (d / f"img_{i}.ppm").write_bytes(ppm)


def _run(*argv):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "make_list.py"), *argv],
        capture_output=True, text=True,
    )


def test_single_list_and_labels(tmp_path):
    _make_tree(tmp_path, [("b_class", 3), ("a_class", 2), ("single", 1)])
    out = tmp_path / "all.txt"
    r = _run(str(tmp_path), "--out", str(out))
    assert r.returncode == 0, r.stderr
    lines = [l.split() for l in out.read_text().splitlines()]
    # classes sorted by name -> a_class=0, b_class=1; singleton dropped
    assert len(lines) == 5
    labels = sorted({int(l[-1]) for l in lines})
    assert labels == [0, 1]
    assert "dropping" in r.stderr and "single" in r.stderr
    # paths resolve under root and load through ListFileDataset
    from npairloss_tpu.data.dataset import ListFileDataset

    ds = ListFileDataset(str(tmp_path), str(out))
    assert len(ds.labels) == 5
    img = ds.load(0)
    assert img.shape[-1] == 3


def test_zero_shot_split(tmp_path):
    _make_tree(tmp_path, [(f"c{i:02d}", 2) for i in range(6)])
    tr, te = tmp_path / "train.txt", tmp_path / "test.txt"
    r = _run(str(tmp_path), "--split-classes", "4",
             "--out-train", str(tr), "--out-test", str(te))
    assert r.returncode == 0, r.stderr
    tr_labels = {int(l.split()[-1]) for l in tr.read_text().splitlines()}
    te_labels = {int(l.split()[-1]) for l in te.read_text().splitlines()}
    assert tr_labels == {0, 1, 2, 3}
    assert te_labels == {4, 5}


def test_e2e_structural_dataset_signal_is_shape_not_color(tmp_path):
    """The conv-trunk e2e proof (accuracy/e2e_real_jpeg_googlenet_bn.json)
    rests on make_dataset_structural's contract: identity must live in
    the SPATIAL mask, not color statistics — otherwise a random conv
    init nearly solves the task and the rising zero-shot curve is
    vacuous (measured: 0.875 first-test R@1 on the color-blob set).

    Pinned here: (a) per-image mean color carries ~no class signal
    (between-class variance of per-class mean colors is small vs the
    within-class instance variance), and (b) binarized spatial masks
    agree within a class and differ across classes."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "e2e_real_jpeg", os.path.join(REPO, "scripts", "e2e_real_jpeg.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    from PIL import Image

    root = str(tmp_path / "imgs")
    mod.make_dataset_structural(root, np.random.default_rng(7))

    means = {}   # class -> [per-image mean color]
    masks = {}   # class -> [binarized luminance mask, roll-normalized]
    for cid in range(4):
        cdir = os.path.join(root, f"id_{cid:03d}")
        means[cid], masks[cid] = [], []
        for fn in sorted(os.listdir(cdir))[:4]:
            a = np.asarray(Image.open(os.path.join(cdir, fn)), np.float64)
            means[cid].append(a.mean(axis=(0, 1)))
            lum = a.mean(axis=2)
            m = (lum > np.median(lum)).astype(np.float64)
            masks[cid].append(m)

    # (a) color: between-class spread of class-mean colors must be small
    # relative to within-class spread (colors are re-drawn per instance).
    class_means = np.array([np.mean(means[c], axis=0) for c in means])
    between = class_means.std(axis=0).mean()
    within = np.mean([np.std(means[c], axis=0).mean() for c in means])
    assert between < within, (between, within)

    # (b) shape: the binary mask is the class signal.  Each instance is
    # rolled independently by +/-8px, so the RELATIVE offset between
    # two instances spans +/-16px — search that full window.
    def best_iou(a, b):
        best = 0.0
        for dy in range(-16, 17, 2):
            for dx in range(-16, 17, 2):
                bb = np.roll(b, (dy, dx), axis=(0, 1))
                inter = (a * bb).sum()
                union = ((a + bb) > 0).sum()
                best = max(best, inter / union)
        return best

    same = np.mean([best_iou(masks[c][0], masks[c][1]) for c in masks])
    cross = np.mean([best_iou(masks[a][0], masks[b][0])
                     for a in masks for b in masks if a < b])
    assert same > cross + 0.1, (same, cross)
