"""CLI contract tests: the ``caffe train`` counterpart must never train
on data the user did not ask for — a missing/absent data source is a hard
error unless synthetic data was explicitly opted into (--synthetic)."""

import os

import pytest

from npairloss_tpu.cli import main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _repo_cwd(monkeypatch):
    # The tiny solver references its net relative to the repo root, as
    # Caffe resolves net paths relative to the CWD.
    monkeypatch.chdir(REPO)


def test_train_without_source_fails_loudly():
    """The tiny net's MultibatchData has no `source`: training it without
    --synthetic must exit with an error, not silently fabricate data."""
    with pytest.raises(SystemExit, match="source|synthetic"):
        main([
            "train", "--solver", "examples/tiny_solver.prototxt",
            "--model", "mlp", "--max_iter", "2",
        ])


def test_train_missing_source_path_fails_loudly(tmp_path):
    """A typo'd source path is a hard error (VERDICT r1: the CLI used to
    silently 'succeed' on random clusters)."""
    net = tmp_path / "net.prototxt"
    net.write_text("""
name: "TinyMLP"
layer {
  name: "d" type: "MultibatchData" top: "d" top: "l"
  include { phase: TRAIN }
  transform_param { crop_size: 8 }
  multi_batch_data_param {
    batch_size: 16 identity_num_per_batch: 8 img_num_per_identity: 2
    source: "/nonexistent/list.txt"
  }
}
""")
    solver = tmp_path / "solver.prototxt"
    solver.write_text(
        f'net: "{net}"\nbase_lr: 0.01\nlr_policy: "fixed"\nmax_iter: 2\n'
        "display: 0\nsnapshot: 0\ntest_interval: 0\ntest_iter: 0\n"
    )
    with pytest.raises(SystemExit, match="does not exist"):
        main(["train", "--solver", str(solver), "--model", "mlp",
              "--max_iter", "2"])


def test_train_synthetic_opt_in_runs():
    rc = main([
        "train", "--solver", "examples/tiny_solver.prototxt",
        "--model", "mlp", "--max_iter", "2", "--synthetic",
    ])
    assert rc == 0


def test_train_blockwise_engine_runs():
    rc = main([
        "train", "--solver", "examples/tiny_solver.prototxt",
        "--model", "mlp", "--max_iter", "2", "--synthetic",
        "--engine", "blockwise",
    ])
    assert rc == 0


@pytest.mark.slow
def test_train_ring_engine_runs_single_device_mesh():
    rc = main([
        "train", "--solver", "examples/tiny_solver.prototxt",
        "--model", "mlp", "--max_iter", "2", "--synthetic",
        "--engine", "ring", "--mesh", "1",
    ])
    assert rc == 0


def test_cli_test_command_blockwise_engine(capsys):
    rc = main([
        "test", "--solver", "examples/tiny_solver.prototxt",
        "--model", "mlp", "--synthetic", "--iterations", "1",
        "--engine", "blockwise",
    ])
    assert rc == 0
    import json

    m = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "retrieve_top1" in m


def test_cli_test_command(tmp_path, capsys):
    """`test` = caffe test counterpart: TEST phase metrics from a
    (fresh or restored) model, no training."""
    rc = main([
        "test", "--solver", "examples/tiny_solver.prototxt",
        "--model", "mlp", "--synthetic", "--iterations", "2",
    ])
    assert rc == 0
    import json

    out = capsys.readouterr().out.strip().splitlines()[-1]
    m = json.loads(out)
    assert "loss" in m and "retrieve_top1" in m
    assert all(abs(v) < 1e9 for v in m.values())


def test_cli_extract_command(tmp_path, capsys):
    """`extract` dumps eval-mode embeddings + labels as .npy."""
    out_prefix = str(tmp_path / "feat")
    rc = main([
        "extract", "--solver", "examples/tiny_solver.prototxt",
        "--model", "mlp", "--synthetic", "--batches", "2",
        "--phase", "TEST", "--out", out_prefix,
    ])
    assert rc == 0
    import json

    import numpy as np

    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    emb = np.load(rec["embeddings"])
    lab = np.load(rec["labels"])
    assert emb.shape[0] == lab.shape[0] > 0
    # L2Normalize head: unit-norm rows (the deployment contract)
    np.testing.assert_allclose(
        np.linalg.norm(emb, axis=1), 1.0, rtol=1e-4
    )


def test_train_weights_finetune_start(tmp_path):
    """--weights starts training from an externally-supplied params
    file (the caffemodel-migration finetune workflow).  The load is
    structure-enforced by Solver.load_params — a tree mismatch fails
    loudly, so rc 0 here means the marked params were accepted and
    loaded."""
    import flax.serialization
    import jax
    import jax.numpy as jnp
    import numpy as np

    from npairloss_tpu import NPairLossConfig
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    solver = Solver(
        get_model("mlp"),
        NPairLossConfig(),
        SolverConfig(base_lr=0.0, lr_policy="fixed", display=0, snapshot=0),
        input_shape=(8, 8, 3),
    )
    solver.init()
    rng = np.random.default_rng(9)
    marked = jax.tree_util.tree_map(
        lambda a: rng.standard_normal(a.shape).astype(np.float32),
        solver.state["params"],
    )
    wfile = tmp_path / "pre.msgpack"
    wfile.write_bytes(flax.serialization.msgpack_serialize(
        {"params": marked, "batch_stats": {}}
    ))

    rc = main([
        "train", "--solver", "examples/tiny_solver.prototxt",
        "--model", "mlp", "--max_iter", "1", "--synthetic",
        "--weights", str(wfile),
    ])
    assert rc == 0


def test_bench_subcommand_forwards_args(monkeypatch):
    """`npairloss_tpu bench --smoke` must forward --smoke to bench.py
    instead of dying on argv re-parsing (argparse REMAINDER cannot
    capture leading optionals in a subparser)."""
    import npairloss_tpu.cli as cli

    seen = {}

    def fake_bench(args):
        seen["bench_args"] = args.bench_args
        return 0

    # main() builds its parser per call and resolves cmd_bench from
    # module globals, so the patch takes effect.
    monkeypatch.setattr(cli, "cmd_bench", fake_bench)
    rc = cli.main(["bench", "--smoke", "--steps", "3"])
    assert rc == 0
    assert seen["bench_args"] == ["--smoke", "--steps", "3"]


def test_cli_time_command(capsys):
    """`npairloss_tpu time --net X` — the `caffe time -model X` surface:
    no solver prototxt required, stage timings + derived deltas emitted
    as one JSON record."""
    import json

    rc = main([
        "time", "--net", "examples/tiny_net.prototxt", "--model", "mlp",
        "--iterations", "2",
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    for key in ("trunk_forward_ms", "forward_ms", "loss_forward_ms",
                "forward_backward_ms", "backward_ms", "emb_per_sec"):
        assert key in rec, key
        assert rec[key] >= 0
    assert rec["batch"] == 16  # tiny_net.prototxt: 8 ids x 2 imgs
    assert rec["iterations"] == 2


@pytest.mark.slow
def test_cli_time_forward_only_engines(capsys):
    """--forward-only skips the backward stage; the streaming engines
    must both time through the same entrypoint, and the emitted record
    must prove which engine/mesh actually ran (ring on an explicit
    2-device mesh — the multi-chip shard_map timing path; blockwise
    single-device by contract)."""
    import json

    for engine, extra, mesh_devices in (
        ("ring", ["--mesh", "2"], 2),
        ("blockwise", [], 1),
    ):
        rc = main([
            "time", "--net", "examples/tiny_net.prototxt", "--model",
            "mlp", "--iterations", "2", "--forward-only",
            "--engine", engine, *extra,
        ])
        assert rc == 0
        rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert "forward_backward_ms" not in rec
        assert rec["forward_ms"] >= 0
        assert rec["engine"] == engine
        assert rec["mesh_devices"] == mesh_devices


def test_cli_device_query(capsys):
    """`device-query` — the `caffe device_query` surface: topology plus
    one record per device."""
    import json

    rc = main(["device-query"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["device_count"] >= 1
    assert len(rec["devices"]) == rec["device_count"]
    for d in rec["devices"]:
        assert "platform" in d and "device_kind" in d


def test_cli_train_log_json(tmp_path, capsys):
    """--log-json appends structured display/test events the Caffe text
    log only renders as prose."""
    import json

    path = tmp_path / "metrics.jsonl"
    rc = main([
        "train", "--solver", "examples/tiny_solver.prototxt",
        "--model", "mlp", "--max_iter", "10", "--synthetic",
        "--log-json", str(path),
    ])
    assert rc == 0
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    events = {r["event"] for r in recs}
    assert "display" in events
    displays = [r for r in recs if r["event"] == "display"]
    assert all("loss_avg" in r and "iteration" in r for r in displays)
    assert displays[-1]["iteration"] == 10


def test_time_stage_bodies_resist_dce():
    """The timed stage programs must contain the work they claim to time:
    forward+backward FLOPs well above forward FLOPs (grad leaves all
    consumed), forward above trunk (loss+metrics consumed).  If an
    anchor regresses, XLA dead-code-eliminates the missing subgraph and
    these ratios collapse toward 1."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from npairloss_tpu.cli import _time_stage_bodies
    from npairloss_tpu.data import synthetic_identity_batches
    from npairloss_tpu.models import get_model
    from npairloss_tpu.ops.npair_loss import NPairLossConfig
    from npairloss_tpu.train import Solver, SolverConfig
    from npairloss_tpu.utils.profiling import cost_flops

    # Tiny trunk + larger batch/embedding so the loss+metrics subgraph
    # (O(N^2 D)) is a visible share of forward FLOPs.
    solver = Solver(
        get_model("mlp", hidden=(8,), embedding_dim=64),
        NPairLossConfig(),
        SolverConfig(display=0, snapshot=0),
        input_shape=(16,),
    )
    images, labels = next(synthetic_identity_batches(32, 16, 2, (16,)))
    solver.init(np.asarray(images[:2]))
    trunk, fwd, fb, init = _time_stage_bodies(solver, images, labels)

    def flops(body):
        lowered = jax.jit(
            lambda c: body(c, jnp.float32(0.0))
        ).lower(init)
        return cost_flops(lowered)

    f_trunk, f_fwd, f_fb = flops(trunk), flops(fwd), flops(fb)
    assert f_trunk and f_fwd and f_fb
    assert f_fwd > f_trunk * 1.2, (f_trunk, f_fwd)  # loss+metrics present
    assert f_fb > f_fwd * 1.7, (f_fwd, f_fb)        # full backward present


def test_train_caffe_solverstate_resume_conflict(tmp_path):
    """--caffe-solverstate and --resume are mutually exclusive snapshot
    sources; the conflict errors out before any restore runs."""
    f = tmp_path / "x.solverstate"
    f.write_bytes(b"")
    rc = main([
        "train", "--solver", "examples/tiny_solver.prototxt",
        "--model", "mlp", "--max_iter", "1", "--synthetic",
        "--caffe-solverstate", str(f), "--resume", "/nonexistent",
    ])
    assert rc == 2


def test_train_caffe_solverstate_requires_weights(tmp_path):
    """A solverstate resume over random-init weights is a corrupt
    trajectory; the CLI demands the paired .caffemodel via --weights."""
    f = tmp_path / "x.solverstate"
    f.write_bytes(b"")
    rc = main([
        "train", "--solver", "examples/tiny_solver.prototxt",
        "--model", "mlp", "--max_iter", "1", "--synthetic",
        "--caffe-solverstate", str(f),
    ])
    assert rc == 2
