"""Distributed parity on a virtual 8-device CPU mesh.

Validates the TPU-native replacements for the reference's MPI collectives
(SURVEY.md §2.3): all_gather negative pooling (cu:17-43), the per-rank loss
over the pod-wide pool (cu:218-388), and the allreduced 0.5/0.5-merged
gradient (cu:462-497) — against the G-rank NumPy oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import make_identity_batch
from npairloss_tpu import MiningMethod, MiningRegion, NPairLossConfig
from npairloss_tpu.ops.npair_loss import npair_loss, npair_loss_with_aux
from npairloss_tpu.parallel import (
    DEFAULT_AXIS,
    data_parallel_mesh,
    shard_batch,
    shard_map,
    sharded_npair_loss_fn,
)
from npairloss_tpu.testing import oracle

G = 8

CFG = NPairLossConfig(  # the shipped config, def.prototxt:137-146
    margin_diff=-0.05,
    identsn=-0.0,
    diffsn=-0.3,
    ap_mining_region=MiningRegion.GLOBAL,
    ap_mining_method=MiningMethod.RELATIVE_HARD,
    an_mining_region=MiningRegion.LOCAL,
    an_mining_method=MiningMethod.HARD,
)


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= G, "conftest must force 8 CPU devices"
    return data_parallel_mesh(jax.devices()[:G])


def _global_batch(rng, num_ids=3, imgs_per_id=2, dim=8):
    feats, labs = make_identity_batch(rng, num_ids, imgs_per_id, dim, num_shards=G)
    return feats, labs, np.concatenate(feats), np.concatenate(labs)


@pytest.mark.slow
def test_forward_parity_vs_oracle(mesh, rng):
    feats, labs, gf, gl = _global_batch(rng)
    want = oracle.forward(feats, labs, CFG)
    fn = jax.jit(sharded_npair_loss_fn(mesh, CFG))
    losses, aux = fn(*shard_batch(mesh, (gf, gl)))
    losses = np.asarray(losses)
    for r in range(G):
        np.testing.assert_allclose(losses[r], want[r].loss, rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(
            np.asarray(aux["sim_exp"])[r], want[r].sim_exp, rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(aux["pos_threshold"])[r], want[r].pos_thr, rtol=1e-6
        )


@pytest.mark.slow
def test_grad_parity_vs_oracle(mesh, rng):
    """Mean-of-rank-losses gradient == per-rank oracle grads / G.

    The reference optimizes each rank's own loss with allreduced db-side
    grads; the JAX equivalent differentiates mean_r(loss_r), whose cotangent
    to each rank's loss is 1/G — so oracle grads (loss_weight=1) divided by G.
    """
    feats, labs, gf, gl = _global_batch(rng)
    res = oracle.forward(feats, labs, CFG)
    # Each rank's loss gets cotangent 1/G; the oracle's allreduce already
    # sums every rank's db-side contribution.
    want = oracle.backward(feats, res, loss_weight=1.0 / G)

    def mean_loss(features, labels):
        loss = npair_loss(features, labels, CFG, axis_name=DEFAULT_AXIS)
        return jax.lax.pmean(loss, DEFAULT_AXIS)

    grad_fn = shard_map(
        jax.grad(mean_loss),
        mesh=mesh,
        in_specs=(P(DEFAULT_AXIS), P(DEFAULT_AXIS)),
        out_specs=P(DEFAULT_AXIS),
    )
    got = np.asarray(jax.jit(grad_fn)(*shard_batch(mesh, (gf, gl))))
    for r in range(G):
        np.testing.assert_allclose(
            got[r * len(labs[0]) : (r + 1) * len(labs[0])],
            want[r],
            rtol=1e-5,
            atol=1e-8,
        )


@pytest.mark.slow
def test_local_mining_sharded_equals_oracle_not_single_device(mesh, rng):
    """G shards != one shard on the concat batch for the *loss* (each rank
    mines per its own query rows), but LOCAL/RAND absolute mining with a
    shared pool means the gathered sim matrix rows must agree with a
    single-device run on the concatenated batch."""
    feats, labs, gf, gl = _global_batch(rng)
    cfg = NPairLossConfig()  # LOCAL/RAND: selection = all non-self pairs
    fn = jax.jit(sharded_npair_loss_fn(mesh, cfg))
    losses, aux = fn(*shard_batch(mesh, (gf, gl)))
    # Single device on the concatenated batch:
    loss1, aux1 = jax.jit(lambda f, l: npair_loss_with_aux(f, l, cfg))(gf, gl)
    # Row blocks of the gathered sim matrix line up rank-by-rank:
    sims = np.concatenate([np.asarray(aux["sim"])[r] for r in range(G)])
    np.testing.assert_allclose(sims, np.asarray(aux1["sim"]), rtol=1e-6)
    # And with selection == all pairs, mean of rank losses == concat loss.
    np.testing.assert_allclose(
        np.asarray(losses).mean(), float(loss1), rtol=1e-5, atol=1e-7
    )


def test_rank_blocks_ordered_like_mpi_allgather(mesh):
    """Gathered rows land at [r*N, (r+1)*N) exactly as MPI_Allgather's
    recvbuf ordering (cu:31-38) — pinned via per-rank labels."""
    n, d = 4, 8
    gf = np.tile(np.eye(d, dtype=np.float32)[:1], (G * n, 1))
    gl = np.arange(G * n, dtype=np.int32)  # all distinct

    def get_total(features, labels):
        tl = jax.lax.all_gather(labels, DEFAULT_AXIS, axis=0, tiled=True)
        return tl[None]

    fn = shard_map(
        get_total, mesh=mesh, in_specs=(P(DEFAULT_AXIS), P(DEFAULT_AXIS)),
        out_specs=P(DEFAULT_AXIS),
    )
    total = np.asarray(jax.jit(fn)(*shard_batch(mesh, (gf, gl))))
    for r in range(G):
        np.testing.assert_array_equal(total[r], gl)
