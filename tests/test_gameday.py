"""Gameday harness: traffic determinism, chaos grammar, verdict teeth.

Load-bearing pins (docs/RESILIENCE.md §8):
  * the traffic plan is a pure function of the seed — same seed, same
    compressed day BYTE FOR BYTE (``plan_lines``/``plan_digest``), and
    the day's statistics (Zipf hot-key share, burst amplitude) are
    pinned so a silent generator regression cannot flatten the load
    shape the chaos schedule was timed against;
  * the chaos schedule speaks the existing ``name:count@delay``
    failpoint grammar exactly, and its validation is loud — a typo'd
    target or an evidence-free remediation declaration fails at load;
  * the ``npairloss-gameday-v1`` validator IS the pass/fail contract:
    it recomputes every gate from the report's own evidence, so a
    tampered ``verdict: "pass"`` over failing blocks is refused —
    unremediated faults, SLO breaches outside incident windows,
    missing/nonzero ``queries_dropped``, too few hot-swaps, and
    unattributed comms bytes all have teeth.

Everything here is jax-free and fast (tier-1): the gameday's stdlib
modules must stay importable in gate processes.
"""

import json

import pytest

from npairloss_tpu.gameday import schedule as chaos
from npairloss_tpu.gameday import traffic as tg
from npairloss_tpu.gameday.verdict import (
    GAMEDAY_SCHEMA,
    build_gameday_report,
    incident_windows,
    validate_gameday_report,
)


# -- traffic: determinism ----------------------------------------------------


def _cfg(**kw):
    base = dict(seed=0, duration_s=60.0, base_qps=4.0, peak_qps=16.0,
                burst_qps=60.0, bursts=2, burst_s=2.0, catalog=256,
                zipf_s=1.1, ingest_every_s=10.0, ingest_rows=16)
    base.update(kw)
    return tg.TrafficConfig(**base)


def test_same_seed_same_day_byte_for_byte():
    a = tg.generate(_cfg(seed=7))
    b = tg.generate(_cfg(seed=7))
    assert tg.plan_lines(a) == tg.plan_lines(b)
    assert tg.plan_digest(a) == tg.plan_digest(b)


def test_different_seed_different_day():
    assert (tg.plan_digest(tg.generate(_cfg(seed=0)))
            != tg.plan_digest(tg.generate(_cfg(seed=1))))


def test_plan_lines_round_trip_canonical_json():
    plan = tg.generate(_cfg())
    lines = tg.plan_lines(plan)
    # Header carries the full config; every line parses; keys sorted.
    head = json.loads(lines[0])
    assert head["cfg"]["seed"] == 0 and len(head["bursts"]) == 2
    for line in lines:
        obj = json.loads(line)
        assert line == json.dumps(obj, sort_keys=True)


def test_ingest_stream_schedule():
    plan = tg.generate(_cfg(duration_s=60.0, ingest_every_s=10.0))
    assert [i.commit_id for i in plan.ingest] == [0, 1, 2, 3, 4]
    assert all(i.rows == 16 for i in plan.ingest)
    assert tg.generate(_cfg(ingest_every_s=0.0)).ingest == ()


# -- traffic: statistical pins -----------------------------------------------


def test_zipf_hot_key_skew_pinned():
    stats = tg.plan_stats(tg.generate(_cfg(duration_s=120.0)))
    # Zipf(s=1.1, catalog=256): key 0 carries ~13% of mass — order of
    # magnitude above uniform (1/256 ~ 0.4%).  A flattened sampler
    # (uniform draw) cannot clear the 0.05 floor.
    assert stats["top_key"] == 0
    assert stats["top_key_share"] > 0.05
    assert stats["distinct_keys"] > 30  # and the tail is long


def test_burst_amplitude_pinned():
    plan = tg.generate(_cfg(duration_s=120.0, bursts=3, burst_s=3.0))
    stats = tg.plan_stats(plan)
    # Inside burst windows the rate is burst_qps (60): the realized
    # windowed rate must sit far above the diurnal peak (16) and in
    # the neighborhood of the configured amplitude.
    assert stats["burst_queries"] > 0
    assert 30.0 < stats["burst_rate_qps"] < 100.0
    # And the diurnal remainder stays well below burst amplitude.
    span = 120.0 - 9.0
    off_rate = (stats["queries"] - stats["burst_queries"]) / span
    assert off_rate < 20.0


def test_traffic_config_validation_is_loud():
    with pytest.raises(ValueError, match="burst_qps must exceed"):
        _cfg(burst_qps=10.0)
    with pytest.raises(ValueError, match="cover the whole window"):
        _cfg(bursts=30, burst_s=2.0)
    with pytest.raises(ValueError, match="catalog"):
        _cfg(catalog=1)
    with pytest.raises(ValueError, match="base_qps"):
        _cfg(base_qps=0.0)


# -- chaos schedule ----------------------------------------------------------


def test_env_spec_speaks_the_failpoint_grammar():
    entries = chaos.default_schedule(75.0)
    assert chaos.env_spec(entries, "serve") == (
        "serve.stale_model:6@10,serve.latency:40@200,"
        "serve.replica_crash:1@120")
    assert chaos.env_spec(entries, "train") == "train.collapse:160@60"
    # Canonical spec drops redundant suffixes.
    assert chaos.ChaosEntry(name="x.y").spec() == "x.y"
    assert chaos.ChaosEntry(name="x.y", count=3).spec() == "x.y:3"


def test_signals_sorted_and_separated():
    entries = chaos.default_schedule(75.0)
    sigs = chaos.signals(entries, "train")
    assert [s.name for s in sigs] == ["SIGTERM"]
    assert sigs[0].expect == ("preempt_exit", "resume")
    serve_sigs = chaos.signals(entries, "serve")
    assert [s.name for s in serve_sigs] == ["SIGKILL"]
    assert serve_sigs[0].expect == ("ingest_durable",
                                    "ingest_no_duplicates")
    assert serve_sigs[0].at_s == pytest.approx(0.55 * 75.0)


def test_chaos_entry_validation_is_loud():
    with pytest.raises(ValueError, match="target"):
        chaos.ChaosEntry(name="x", target="db")
    with pytest.raises(ValueError, match="needs the"):
        chaos.ChaosEntry(name="x", remediation="p")  # no alert
    with pytest.raises(ValueError, match="unknown expect"):
        chaos.ChaosEntry(name="x", expect=("warp_drive",))
    with pytest.raises(ValueError, match="signal entries"):
        chaos.ChaosEntry(name="SIGTERM", kind="signal", alert="a")
    with pytest.raises(ValueError):
        chaos.ChaosEntry(name="x", kind="signal").spec()


def test_load_schedule_round_trip(tmp_path):
    entries = chaos.default_schedule(75.0)
    path = tmp_path / "sched.json"
    path.write_text(json.dumps({"entries": chaos.entry_dicts(entries)}))
    loaded = chaos.load_schedule(str(path))
    assert loaded == entries
    path.write_text(json.dumps({"entries": [{"name": "x", "target": "db"}]}))
    with pytest.raises(ValueError, match="target"):
        chaos.load_schedule(str(path))


# -- verdict -----------------------------------------------------------------


def _alert_pair(aid, slo, t0, t1):
    base = {"schema": "alerts-v1", "alert_id": aid, "slo": slo,
            "metric": "m", "severity": "warning", "ts": t0,
            "fired_at": t0, "bad_fraction": 1.0, "samples": 4,
            "target": 1.0, "op": "<=", "message": "x"}
    return [dict(base, state="firing"),
            dict(base, state="resolved", ts=t1, bad_fraction=0.0)]


def _rem(aid, slo, policy, state, t):
    return {"schema": "remediation-v1", "id": f"r-{aid}", "policy": policy,
            "action": "act", "alert_id": aid, "slo": slo,
            "severity": "warning", "state": state, "ts": t, "attempt": 1,
            "max_attempts": 5, "dry_run": False, "message": "x"}


def _passing_report(**over):
    entries = chaos.entry_dicts(chaos.default_schedule(75.0))
    serve_alerts = (_alert_pair("a1", "model_staleness", 12.0, 18.0)
                    + _alert_pair("a2", "serve_p99", 40.0, 46.0))
    train_alerts = _alert_pair("a3", "embedding_collapse", 25.0, 35.0)
    kw = dict(
        traffic={"planned": 400, "fed": 400, "answered": 390,
                 "errors": 0, "rejected": 10, "sha256": "d" * 64},
        serve_alerts=serve_alerts, train_alerts=train_alerts,
        serve_remediation=[
            _rem("a1", "model_staleness", "hotswap_model", "succeeded",
                 16.0),
            _rem("a2", "serve_p99", "load_shed", "succeeded", 44.0)],
        train_remediation=[
            _rem("a3", "embedding_collapse", "trainer_rollback",
                 "succeeded", 30.0)],
        # Healthy rows attribute to dispatch; the rows inside the
        # serve_p99 incident window show the queue_wait signature the
        # serve.latency entry declares (worst decomposed row wins).
        serve_rows=[{"p99_ms": 40.0, "wall_time": float(t),
                     "qtrace_dominant": ("queue_wait" if 35 <= t <= 50
                                         else "dispatch"),
                     "qtrace_dominant_ms": (220.0 if 35 <= t <= 50
                                            else 6.0)}
                    for t in range(0, 76, 5)],
        quality_windows=[{"recall_at_10": 0.97, "wall_time": float(t)}
                         for t in range(0, 76, 10)],
        drain={"queries": 400, "answered": 390, "errors": 0,
               "rejected": 10, "queries_dropped": 0, "hot_swaps": 4},
        comms={"available": True, "unattributed_bytes": 0},
        trainer={"segments": 2, "exit_codes": [75, 75], "resumed": True},
        observed_fires={"serve.stale_model": 6, "serve.latency": 40,
                        "serve.replica_crash": 1, "train.collapse": 160,
                        "SIGTERM": 1, "SIGKILL": 1},
        host_crash={"available": True, "kills": 1, "acked_batches": 3,
                    "acked_vectors": 24, "lost": 0, "duplicates": 0,
                    "torn_records": 0, "self_recall": 1.0},
        client_errors=0, window_s=75.0, seed=0,
        p99_target_ms=150.0, recall_floor=0.9, min_hot_swaps=3,
        qtrace={"available": True,
                "totals": {"queries": 400, "reroutes": 1,
                           "hotswap_flips": 4},
                "budget": {"p99_ms": 42.0, "dominant": "dispatch"}})
    kw.update(over)
    return build_gameday_report(entries, **kw)


def test_passing_report_validates():
    report = _passing_report()
    assert report["verdict"] == "pass" and report["failures"] == []
    assert report["schema"] == GAMEDAY_SCHEMA
    assert validate_gameday_report(report) is None


def test_unfired_fault_fails():
    report = _passing_report(observed_fires={
        "serve.stale_model": 6, "serve.latency": 40,
        "train.collapse": 160})  # replica_crash never fired
    assert report["verdict"] == "fail"
    assert any("never fired" in f for f in report["failures"])
    assert "replica_crash" in validate_gameday_report(report)


def test_unremediated_fault_fails():
    report = _passing_report(serve_remediation=[
        _rem("a1", "model_staleness", "hotswap_model", "failed", 16.0),
        _rem("a2", "serve_p99", "load_shed", "succeeded", 44.0)])
    assert any("unremediated" in f for f in report["failures"])
    err = validate_gameday_report(report)
    assert err is not None and "unremediated" in err


def test_breach_inside_incident_window_excused():
    # The p99 spike lands inside the serve_p99 alert's window
    # [40 - 30, 46 + 10]: excused, verdict still passes.  The spike
    # row carries the queue_wait decomposition the serve.latency entry
    # declares (it IS the window's worst decomposed row).
    rows = [{"p99_ms": 40.0, "wall_time": float(t)}
            for t in range(0, 76, 5)]
    rows.append({"p99_ms": 900.0, "wall_time": 42.0,
                 "qtrace_dominant": "queue_wait",
                 "qtrace_dominant_ms": 870.0})
    report = _passing_report(serve_rows=rows)
    assert report["verdict"] == "pass"
    assert report["slo"]["p99"]["in_incident"] > 0


def test_breach_outside_incident_window_fails():
    rows = [{"p99_ms": 40.0, "wall_time": float(t)}
            for t in range(0, 76, 5)]
    rows.append({"p99_ms": 900.0, "wall_time": 74.5})  # outside pads
    report = _passing_report(serve_rows=rows)
    assert report["verdict"] == "fail"
    assert any("p99 breached outside" in f for f in report["failures"])


def test_zero_drop_gate_demands_explicit_evidence():
    report = _passing_report(drain={
        "queries": 400, "answered": 390, "errors": 0, "rejected": 10,
        "hot_swaps": 4})  # queries_dropped absent
    assert any("queries_dropped missing" in f
               for f in report["failures"])
    report = _passing_report(drain={
        "queries": 400, "answered": 383, "errors": 0, "rejected": 10,
        "queries_dropped": 7, "hot_swaps": 4})
    assert any("dropped queries: 7" in f for f in report["failures"])


def test_too_few_hot_swaps_fails():
    report = _passing_report(drain={
        "queries": 400, "answered": 390, "errors": 0, "rejected": 10,
        "queries_dropped": 0, "hot_swaps": 2})
    assert any("too few hot-swaps" in f for f in report["failures"])


def test_unattributed_comms_bytes_fail_only_when_available():
    report = _passing_report(comms={"available": True,
                                    "unattributed_bytes": 12})
    assert any("unattributed comms" in f for f in report["failures"])
    report = _passing_report(comms={"available": False,
                                    "reason": "no fleet_comms.json"})
    assert report["verdict"] == "pass"


def test_tampered_pass_verdict_refused():
    report = _passing_report(drain={
        "queries": 400, "answered": 383, "errors": 0, "rejected": 10,
        "queries_dropped": 7, "hot_swaps": 4})
    tampered = dict(report, verdict="pass", failures=[])
    err = validate_gameday_report(tampered)
    assert err is not None and "dropped queries" in err


def test_wrong_schema_tag_refused():
    report = dict(_passing_report(), schema="npairloss-gameday-v0")
    assert "schema" in validate_gameday_report(report)


def test_missing_block_key_refused():
    report = _passing_report()
    bad = dict(report, zero_drop={
        k: v for k, v in report["zero_drop"].items()
        if k != "queries_dropped"})
    assert "zero_drop missing key" in validate_gameday_report(bad)
    assert "non-empty" in validate_gameday_report(
        dict(report, faults=[]))


def test_host_crash_lost_vector_fails():
    report = _passing_report(host_crash={
        "available": True, "kills": 1, "acked_batches": 3,
        "acked_vectors": 24, "lost": 2, "duplicates": 0,
        "torn_records": 0, "self_recall": 1.0})
    assert report["verdict"] == "fail"
    assert any("ingest_durable recomputed false" in f
               for f in report["failures"])
    # A kill that leaves duplicates fails the exactly-once half.
    report = _passing_report(host_crash={
        "available": True, "kills": 1, "acked_batches": 3,
        "acked_vectors": 24, "lost": 0, "duplicates": 1,
        "torn_records": 0, "self_recall": 1.0})
    assert any("ingest_no_duplicates recomputed false" in f
               for f in report["failures"])


def test_host_crash_evidence_required():
    # No evidence block at all: the SIGKILL fault's checks cannot pass.
    report = _passing_report(host_crash=None)
    assert report["verdict"] == "fail"
    assert report["host_crash"] == {"available": False}
    assert any("host-crash evidence refutes" in f
               for f in report["failures"])
    # Recall parity below the floor is a loss in disguise.
    report = _passing_report(host_crash={
        "available": True, "kills": 1, "acked_batches": 3,
        "acked_vectors": 24, "lost": 0, "duplicates": 0,
        "torn_records": 0, "self_recall": 0.5})
    assert any("ingest_durable" in f for f in report["failures"])


def test_host_crash_tampered_pass_refused():
    # Flip the stored verdict AND the fault row's checks to true over
    # refuting evidence: the validator recomputes from host_crash and
    # refuses — the durable-ingest judgement is never trusted.
    report = _passing_report(host_crash={
        "available": True, "kills": 1, "acked_batches": 3,
        "acked_vectors": 24, "lost": 5, "duplicates": 0,
        "torn_records": 0, "self_recall": 1.0})
    tampered = dict(report, verdict="pass", failures=[])
    tampered["faults"] = [
        dict(f, ok=True, checks={c: True for c in f["checks"]})
        for f in report["faults"]]
    err = validate_gameday_report(tampered)
    assert err is not None and "host-crash evidence refutes" in err


def test_host_crash_available_demands_full_evidence():
    report = _passing_report()
    hc = {k: v for k, v in report["host_crash"].items()
          if k != "torn_records"}
    err = validate_gameday_report(dict(report, host_crash=hc))
    assert err is not None and "host_crash missing key" in err


def test_incident_windows_pads_and_horizon():
    wins = incident_windows(
        _alert_pair("a1", "s", 100.0, 110.0), pad_before_s=30.0,
        pad_after_s=10.0)
    assert wins == [{"slo": "s", "alert_id": "a1", "start": 70.0,
                     "end": 120.0}]
    # Never-resolved alert stays open to the horizon.
    firing_only = _alert_pair("a2", "s", 100.0, 110.0)[:1]
    wins = incident_windows(firing_only, horizon=200.0)
    assert wins[0]["end"] == 210.0
    # Torn tail lines are ignored, not fatal.
    assert incident_windows([{"_bad_line": "x"}]) == []
