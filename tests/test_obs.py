"""obs: structured metric sinks, span tracing, run telemetry, and the
in-graph training-health signals (docs/OBSERVABILITY.md)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from npairloss_tpu.obs import (
    REQUIRED_KEYS,
    CsvSink,
    HealthConfig,
    JsonlSink,
    MultiSink,
    RingBufferSink,
    RunTelemetry,
    SpanTracer,
    validate_chrome_trace,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- sinks ----------------------------------------------------------------


def test_jsonl_sink_roundtrip(tmp_path):
    path = tmp_path / "m.jsonl"
    sink = JsonlSink(str(path))
    sink.log({"run_id": "r1", "step": 1, "wall_time": 1.5,
              "phase": "train", "loss": 0.25})
    sink.log({"run_id": "r1", "step": 2, "wall_time": 2.5,
              "phase": "train", "loss": 0.125})
    sink.close()
    rows = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(rows) == 2
    assert rows[1]["loss"] == 0.125
    for row in rows:
        for key in REQUIRED_KEYS:
            assert key in row, key


def test_jsonl_sink_appends_across_instances(tmp_path):
    path = str(tmp_path / "m.jsonl")
    for i in range(2):
        s = JsonlSink(path)
        s.log({"i": i})
        s.close()
    assert len(open(path).read().splitlines()) == 2


def test_csv_sink_fixed_header(tmp_path):
    path = tmp_path / "m.csv"
    sink = CsvSink(str(path))
    sink.log({"step": 1, "loss": 0.5})
    # Extra keys are dropped (CSV cannot grow columns), missing -> "".
    sink.log({"step": 2, "loss": 0.25, "extra": 9})
    sink.log({"step": 3})
    sink.close()
    lines = path.read_text().splitlines()
    assert lines[0] == "step,loss"
    assert lines[2] == "2,0.25"
    assert lines[3] == "3,"


def test_ring_buffer_eviction():
    ring = RingBufferSink(capacity=4)
    for i in range(10):
        ring.log({"step": i})
    recs = ring.records()
    assert [r["step"] for r in recs] == [6, 7, 8, 9]
    assert ring.latest()["step"] == 9
    assert ring.total_logged == 10
    with pytest.raises(ValueError):
        RingBufferSink(capacity=0)


def test_multiplex_fan_out():
    a, b = RingBufferSink(8), RingBufferSink(8)
    multi = MultiSink([a, b])
    multi.log({"step": 1})
    assert a.latest() == {"step": 1}
    assert b.latest() == {"step": 1}

    class Boom:
        def log(self, rec):
            raise RuntimeError("boom")

        def flush(self):
            pass

        def close(self):
            pass

    # A failing child must not starve its siblings of the record.
    multi = MultiSink([Boom(), a])
    with pytest.raises(RuntimeError):
        multi.log({"step": 2})
    assert a.latest() == {"step": 2}


# -- tracing --------------------------------------------------------------


def test_tracer_chrome_trace_schema(tmp_path):
    tr = SpanTracer()
    with tr.span("outer", kind="test"):
        with tr.span("inner"):
            pass
    tr.instant("marker", note="x")
    path = tr.write(str(tmp_path / "trace.json"))
    obj = json.load(open(path))
    assert validate_chrome_trace(obj) is None
    events = obj["traceEvents"]
    names = [e["name"] for e in events]
    assert {"outer", "inner", "marker"} <= set(names)
    outer = next(e for e in events if e["name"] == "outer")
    inner = next(e for e in events if e["name"] == "inner")
    # "X" complete events; the inner span nests inside the outer by
    # timestamp containment (the Chrome/Perfetto stacking rule).
    assert outer["ph"] == inner["ph"] == "X"
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert outer["args"] == {"kind": "test"}


def test_tracer_event_cap_is_recorded():
    tr = SpanTracer(max_events=2)
    for i in range(5):
        tr.instant(f"e{i}")
    obj = tr.to_chrome_trace()
    assert len(obj["traceEvents"]) == 2
    assert obj["otherData"]["dropped_events"] == 3
    assert validate_chrome_trace(obj) is None


def test_validate_chrome_trace_rejects_bad_shapes():
    assert validate_chrome_trace([]) is not None
    assert validate_chrome_trace({"traceEvents": [{}]}) is not None
    assert validate_chrome_trace(
        {"traceEvents": [{"name": "a", "ph": "X", "ts": 0}]}
    ) is not None  # X without dur


# -- run telemetry --------------------------------------------------------


def test_run_telemetry_dir_contract(tmp_path):
    run_dir = tmp_path / "run"
    with RunTelemetry(str(run_dir)) as tel:
        tel.write_manifest(config={"model": "mlp"}, extra={"note": "t"})
        with tel.span("step/dispatch", batch=4):
            pass
        tel.log("train", 1, {"loss": 0.5})
        tel.log("eval", 1, {"loss": 0.4}, eval_batches=2)
    manifest = json.load(open(run_dir / "manifest.json"))
    assert manifest["run_id"] == tel.run_id
    assert manifest["config"] == {"model": "mlp"}
    assert manifest["package_version"]
    # conftest imports jax, so topology must be captured.
    assert manifest["topology"]["device_count"] >= 1
    rows = [json.loads(l)
            for l in open(run_dir / "metrics.jsonl").read().splitlines()]
    assert [r["phase"] for r in rows] == ["train", "eval"]
    for row in rows:
        for key in REQUIRED_KEYS:
            assert key in row, key
        assert row["run_id"] == tel.run_id
    assert rows[1]["eval_batches"] == 2
    trace = json.load(open(run_dir / "trace.json"))
    assert validate_chrome_trace(trace) is None
    assert tel.ring.latest()["phase"] == "eval"


def test_run_telemetry_envelope_wins_over_metric_collision(tmp_path):
    tel = RunTelemetry(str(tmp_path / "r"), metrics=False, trace=False)
    rec = tel.log("train", 7, {"step": 999, "loss": 1.0})
    assert rec["step"] == 7  # a metric named "step" must not corrupt rows
    tel.close()


# -- solver integration ---------------------------------------------------


def _tiny_solver(**kw):
    from npairloss_tpu import MiningMethod, NPairLossConfig
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    cfg = SolverConfig(
        base_lr=0.1, lr_policy="fixed", momentum=0.9, weight_decay=0.0,
        display=0, test_interval=0, snapshot=0,
    )
    loss_cfg = NPairLossConfig(
        margin_diff=-0.05,
        an_mining_method=MiningMethod.HARD,
        ap_mining_method=MiningMethod.RAND,
    )
    return Solver(get_model("mlp", hidden=(32,), embedding_dim=16),
                  loss_cfg, cfg, input_shape=(8,), **kw)


def _batch(rng, n=16):
    from npairloss_tpu.data import synthetic_identity_batches

    return next(synthetic_identity_batches(n // 2, n // 2, 2, (8,),
                                           noise=0.5))


BASELINE_KEYS = sorted(
    ["loss", "lr", "retrieve_top1", "retrieve_top5", "retrieve_top10",
     "feature_asum"]
)

# The solver integration tests below each compile jitted steps (~1-2 s
# on CPU); they are consolidated — one no-health solver, one health
# solver — because the tier-1 run's 870 s budget has ~10 s of headroom
# over the rest of the suite (ROADMAP.md).


def test_solver_no_health_telemetry_and_keys(tmp_path, rng):
    """One no-health solver covers three pins: (a) the hot path exposes
    EXACTLY the pre-obs metric keys (the acceptance pin for 'identical
    HLO when disabled'), (b) train/evaluate emit enveloped rows through
    the sink, (c) compile/recompile capture shows in the span trace."""
    from npairloss_tpu.data import synthetic_identity_batches

    run_dir = tmp_path / "run"
    tel = RunTelemetry(str(run_dir))
    solver = _tiny_solver(telemetry=tel)
    batches = synthetic_identity_batches(8, 8, 2, (8,), noise=0.5)
    solver.train(batches, num_iters=2)

    x2, lab2 = _batch(rng, n=8)  # dynamic-batch path: new shape
    m = solver.step(x2, lab2)
    assert sorted(m.keys()) == BASELINE_KEYS

    ev = solver.evaluate(batches, 1)
    tel.close()

    rows = [json.loads(l)
            for l in open(run_dir / "metrics.jsonl").read().splitlines()]
    train_rows = [r for r in rows if r["phase"] == "train"]
    assert [r["step"] for r in train_rows] == [1, 2]
    for row in train_rows:
        for key in REQUIRED_KEYS + ("loss",):
            assert key in row, key
        assert sorted(set(row) - set(REQUIRED_KEYS)) == BASELINE_KEYS
    eval_rows = [r for r in rows if r["phase"] == "eval"]
    assert len(eval_rows) == 1 and eval_rows[0]["eval_batches"] == 1
    np.testing.assert_allclose(eval_rows[0]["loss"], ev["loss"], rtol=1e-6)

    trace = json.load(open(run_dir / "trace.json"))
    assert validate_chrome_trace(trace) is None
    names = [e["name"] for e in trace["traceEvents"]]
    # First dispatch per batch signature is the compile; repeat
    # signatures are plain dispatches; a signature after the first also
    # drops the step/recompile instant marker.
    assert names.count("step/compile") == 2
    assert names.count("step/dispatch") == 1
    assert names.count("step/recompile") == 1
    assert "data/next_batch" in names and "eval" in names


def test_solver_health_metrics_appear_when_enabled(rng):
    solver = _tiny_solver(health=HealthConfig())
    x, lab = _batch(rng)
    m = solver.step(x, lab)
    expected = {
        "grad_norm", "param_norm", "update_norm", "update_ratio",
        "emb_mag_mean", "emb_mag_max",
        "mined_pos_per_query", "mined_neg_per_query",
        "ap_threshold_mean", "an_threshold_mean",
    }
    assert expected <= set(m.keys())
    assert float(m["grad_norm"]) > 0
    assert 0 < float(m["update_ratio"]) < 1
    # update_ratio must be ||update||/||params|| of THIS step.
    ratio = float(m["update_norm"]) / (float(m["param_norm"]) + 1e-12)
    np.testing.assert_allclose(float(m["update_ratio"]), ratio, rtol=1e-4)
    # L2-normalized embeddings: magnitude pins to 1 (the reference's
    # feature-monitor invariant, cu:400-401 generalized).
    np.testing.assert_allclose(float(m["emb_mag_mean"]), 1.0, rtol=1e-5)
    np.testing.assert_allclose(float(m["emb_mag_max"]), 1.0, rtol=1e-5)
    assert float(m["mined_pos_per_query"]) >= 1.0
    # baseline retrieval metrics still present alongside
    assert "retrieve_top1" in m and "loss" in m

    # Edge regression (caught live): an all-same-label batch has no
    # negatives, so the AP mining threshold is a -inf/FLT_MAX sentinel
    # for every query — the hardness summary must skip sentinels and
    # stay FINITE (health rows feed assert_all_finite under
    # --debug-checks).
    x0 = np.random.default_rng(1).standard_normal((4, 8)).astype(np.float32)
    m0 = solver.step(x0, np.zeros(4, np.int32))
    vals = {k: float(v) for k, v in m0.items()}
    assert all(np.isfinite(v) for v in vals.values()), vals
    assert vals["loss"] == 0.0 and vals["mined_neg_per_query"] == 0.0


# -- CLI flags ------------------------------------------------------------
# marked slow: each spawns a full 2-iteration CLI training run (~1.5 s);
# the flag plumbing they cover is also exercised solver-level above, so
# the tier-1 budgeted run (-m 'not slow', ROADMAP.md) skips them and the
# unfiltered suite keeps them.


@pytest.mark.slow
def test_cli_telemetry_and_health_flags(tmp_path, monkeypatch):
    from npairloss_tpu.cli import main
    from npairloss_tpu.utils.debug import (
        debug_checks_enabled,
        enable_debug_checks,
    )

    monkeypatch.chdir(REPO)
    run_dir = tmp_path / "run"
    enable_debug_checks(False)
    try:
        rc = main([
            "train", "--solver", "examples/tiny_solver.prototxt",
            "--model", "mlp", "--max_iter", "2", "--synthetic",
            "--mesh", "1",
            "--telemetry-dir", str(run_dir), "--health-metrics",
            "--debug-checks",
        ])
    finally:
        was_enabled = debug_checks_enabled()
        enable_debug_checks(False)
    assert rc == 0
    assert was_enabled  # --debug-checks flipped the process-wide switch
    manifest = json.load(open(run_dir / "manifest.json"))
    assert manifest["config"]["health_metrics"] is True
    assert manifest["config"]["solver"]["max_iter"] == 2
    rows = [json.loads(l)
            for l in open(run_dir / "metrics.jsonl").read().splitlines()]
    train_rows = [r for r in rows if r["phase"] == "train"]
    assert len(train_rows) == 2
    assert "grad_norm" in train_rows[0]
    assert validate_chrome_trace(
        json.load(open(run_dir / "trace.json"))) is None


@pytest.mark.slow
def test_cli_trace_dir_only(tmp_path, monkeypatch):
    from npairloss_tpu.cli import main

    monkeypatch.chdir(REPO)
    trace_dir = tmp_path / "tr"
    rc = main([
        "train", "--solver", "examples/tiny_solver.prototxt",
        "--model", "mlp", "--max_iter", "2", "--synthetic",
        "--mesh", "1",
        "--trace-dir", str(trace_dir),
    ])
    assert rc == 0
    assert validate_chrome_trace(
        json.load(open(trace_dir / "trace.json"))) is None
    # trace-only mode: no metric rows on disk
    assert not os.path.exists(trace_dir / "metrics.jsonl")


# -- tooling --------------------------------------------------------------


def test_check_no_print_clean_on_repo():
    rc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "check_no_print.py")],
        capture_output=True,
    )
    assert rc.returncode == 0, rc.stderr.decode()


def test_check_no_print_flags_offender(tmp_path):
    bad = tmp_path / "lib.py"
    bad.write_text("def f():\n    print('leak')\n")
    exempt = tmp_path / "cli.py"
    exempt.write_text("print('fine')\n")
    rc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "check_no_print.py"),
         str(tmp_path)],
        capture_output=True,
    )
    assert rc.returncode == 1
    err = rc.stderr.decode()
    assert "lib.py:2" in err and "cli.py" not in err


def test_bench_parent_sinks_load_without_package():
    """bench.py's parent loads obs/sinks.py by file path — that module
    must import cleanly WITHOUT jax or the npairloss_tpu package."""
    code = (
        "import importlib.util, sys\n"
        f"spec = importlib.util.spec_from_file_location('s', "
        f"{os.path.join(REPO, 'npairloss_tpu', 'obs', 'sinks.py')!r})\n"
        "mod = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(mod)\n"
        "assert 'jax' not in sys.modules\n"
        "assert 'npairloss_tpu' not in sys.modules\n"
        "ring = mod.RingBufferSink(2)\n"
        "ring.log({'a': 1})\n"
        "assert ring.latest() == {'a': 1}\n"
    )
    rc = subprocess.run([sys.executable, "-c", code], capture_output=True)
    assert rc.returncode == 0, rc.stderr.decode()
