"""Ring-blockwise loss parity: ring path ≡ dense gather path, per shard,
for loss, metrics, and gradients in both grad modes (SURVEY.md §5.7)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from npairloss_tpu.ops.metrics import retrieval_metrics
from npairloss_tpu.ops.npair_loss import (
    REFERENCE_CONFIG,
    MiningMethod,
    MiningRegion,
    NPairLossConfig,
    npair_loss_with_aux,
)
from npairloss_tpu.parallel import data_parallel_mesh, ring_supported, shard_map
from npairloss_tpu.parallel.ring import ring_npair_loss_and_metrics

from conftest import make_identity_batch

AXIS = "dp"


def _mesh():
    return data_parallel_mesh()


def _make_inputs(rng, num_shards, num_ids=4, imgs=2, dim=16):
    feats, labs = make_identity_batch(rng, num_ids, imgs, dim, num_shards)
    return np.concatenate(feats), np.concatenate(labs)


def _dense_fns(mesh, cfg, top_ks=(1, 5, 10)):
    def per_shard(f, l):
        loss, aux = npair_loss_with_aux(f, l, cfg, axis_name=AXIS)
        m = retrieval_metrics(
            jax.lax.stop_gradient(aux), l, jax.lax.stop_gradient(f), top_ks
        )
        return loss, m

    def value(f, l):
        loss, m = per_shard(f, l)
        stack = lambda x: jnp.asarray(x)[None]
        return stack(loss), jax.tree_util.tree_map(stack, m)

    def grad(f, l):
        g = jax.grad(lambda f_: per_shard(f_, l)[0])(f)
        return g

    value_sh = jax.jit(
        shard_map(
            value, mesh=mesh, in_specs=(P(AXIS), P(AXIS)),
            out_specs=(P(AXIS), P(AXIS)),
        )
    )
    grad_sh = jax.jit(
        shard_map(
            grad, mesh=mesh, in_specs=(P(AXIS), P(AXIS)), out_specs=P(AXIS)
        )
    )
    return value_sh, grad_sh


def _ring_fns(mesh, cfg, top_ks=(1, 5, 10)):
    def per_shard(f, l):
        loss, m = ring_npair_loss_and_metrics(f, l, cfg, AXIS, top_ks)
        stack = lambda x: jnp.asarray(x)[None]
        return stack(loss), jax.tree_util.tree_map(stack, m)

    def grad(f, l):
        g = jax.grad(
            lambda f_: ring_npair_loss_and_metrics(f_, l, cfg, AXIS, top_ks)[0]
        )(f)
        return g

    value_sh = jax.jit(
        shard_map(
            per_shard, mesh=mesh, in_specs=(P(AXIS), P(AXIS)),
            out_specs=(P(AXIS), P(AXIS)),
        )
    )
    grad_sh = jax.jit(
        shard_map(
            grad, mesh=mesh, in_specs=(P(AXIS), P(AXIS)), out_specs=P(AXIS)
        )
    )
    return value_sh, grad_sh


ABS_CONFIGS = [
    NPairLossConfig(),  # proto defaults: LOCAL/RAND both sides
    NPairLossConfig(
        an_mining_method=MiningMethod.HARD, margin_diff=-0.05
    ),  # def.prototxt AN side
    NPairLossConfig(
        ap_mining_method=MiningMethod.HARD,
        ap_mining_region=MiningRegion.GLOBAL,
        an_mining_method=MiningMethod.EASY,
        margin_ident=0.1,
    ),
    NPairLossConfig(
        ap_mining_method=MiningMethod.EASY,
        an_mining_region=MiningRegion.GLOBAL,
        an_mining_method=MiningMethod.HARD,
    ),
]


@pytest.mark.parametrize("cfg_idx", range(len(ABS_CONFIGS)))
@pytest.mark.slow
def test_ring_matches_dense_loss_and_metrics(rng, cfg_idx):
    cfg = ABS_CONFIGS[cfg_idx]
    mesh = _mesh()
    g = len(mesh.devices)
    f, l = _make_inputs(rng, g)
    dense_v, _ = _dense_fns(mesh, cfg)
    ring_v, _ = _ring_fns(mesh, cfg)
    dl, dm = dense_v(jnp.asarray(f), jnp.asarray(l))
    rl, rm = ring_v(jnp.asarray(f), jnp.asarray(l))
    np.testing.assert_allclose(np.asarray(rl), np.asarray(dl), rtol=2e-5, atol=1e-6)
    for k in ("retrieve_top1", "retrieve_top5", "retrieve_top10", "feature_asum"):
        np.testing.assert_allclose(
            np.asarray(rm[k]), np.asarray(dm[k]), rtol=2e-5, atol=1e-6,
            err_msg=k,
        )


@pytest.mark.parametrize("grad_mode", ["reference", "true"])
@pytest.mark.slow
def test_ring_matches_dense_grad(rng, grad_mode):
    import dataclasses

    cfg = dataclasses.replace(
        NPairLossConfig(an_mining_method=MiningMethod.HARD, margin_diff=-0.05),
        grad_mode=grad_mode,
    )
    mesh = _mesh()
    g = len(mesh.devices)
    f, l = _make_inputs(rng, g)
    _, dense_g = _dense_fns(mesh, cfg)
    _, ring_g = _ring_fns(mesh, cfg)
    dg = np.asarray(dense_g(jnp.asarray(f), jnp.asarray(l)))
    rg = np.asarray(ring_g(jnp.asarray(f), jnp.asarray(l)))
    assert np.isfinite(rg).all()
    np.testing.assert_allclose(rg, dg, rtol=3e-5, atol=1e-6)


REL_CONFIGS = [
    # The shipped def.prototxt mining config — the flagship workload.
    REFERENCE_CONFIG,
    # LOCAL relative on both sides, fraction-valued sn.
    NPairLossConfig(
        ap_mining_method=MiningMethod.RELATIVE_EASY, identsn=-0.5,
        an_mining_method=MiningMethod.RELATIVE_HARD, diffsn=-0.3,
    ),
    # Positive sn = absolute rank from the sorted top (cu:285-287).
    NPairLossConfig(
        ap_mining_method=MiningMethod.RELATIVE_HARD, identsn=1.0,
        an_mining_method=MiningMethod.RELATIVE_EASY, diffsn=2.0,
        margin_diff=0.02,
    ),
    # GLOBAL relative on the AN side (block-wide rank, cu:327-334).
    NPairLossConfig(
        an_mining_region=MiningRegion.GLOBAL,
        an_mining_method=MiningMethod.RELATIVE_HARD, diffsn=-0.25,
    ),
]


@pytest.mark.parametrize("cfg_idx", range(len(REL_CONFIGS)))
@pytest.mark.slow
def test_ring_relative_matches_dense(rng, cfg_idx):
    """RELATIVE_* thresholds via streamed radix selection must equal the
    dense path's host-sort semantics exactly — loss, metrics and grads."""
    cfg = REL_CONFIGS[cfg_idx]
    assert ring_supported(cfg)
    mesh = _mesh()
    g = len(mesh.devices)
    f, l = _make_inputs(rng, g)
    dense_v, dense_g = _dense_fns(mesh, cfg)
    ring_v, ring_g = _ring_fns(mesh, cfg)
    fj, lj = jnp.asarray(f), jnp.asarray(l)
    dl, dm = dense_v(fj, lj)
    rl, rm = ring_v(fj, lj)
    np.testing.assert_allclose(
        np.asarray(rl), np.asarray(dl), rtol=2e-5, atol=1e-6
    )
    for k in ("retrieve_top1", "retrieve_top5", "retrieve_top10"):
        np.testing.assert_allclose(
            np.asarray(rm[k]), np.asarray(dm[k]), rtol=2e-5, err_msg=k
        )
    np.testing.assert_allclose(
        np.asarray(ring_g(fj, lj)), np.asarray(dense_g(fj, lj)),
        rtol=3e-5, atol=1e-6,
    )


@pytest.mark.parametrize("num_ids,imgs", [(9, 8), (9, 16)])
@pytest.mark.slow
def test_ring_pos_topk_fallback_boundary(rng, num_ids, imgs):
    """The ring's sparse-positive fast path guards on a pmax-agreed
    cnt_s <= K: 8 imgs per identity (cnt_s=7) fits the 8-slot buffer,
    16 overflows and every shard must take the radix fallback branch
    together (a split vote would deadlock the ppermute collectives).
    9 ids x {8,16} imgs over 8 shards puts 9 (resp. 18) rows per shard,
    so label groups SPAN shard boundaries — the buffer must merge
    positives arriving on different ring hops.  Parity with dense must
    hold on both sides of the boundary."""
    cfg = NPairLossConfig(
        ap_mining_region=MiningRegion.GLOBAL,
        ap_mining_method=MiningMethod.RELATIVE_HARD, identsn=-0.3,
        an_mining_method=MiningMethod.HARD, margin_diff=-0.05,
    )
    mesh = _mesh()
    g = len(mesh.devices)
    per_shard = num_ids * imgs // g
    assert num_ids * imgs == per_shard * g and per_shard % imgs != 0
    feats, labs = make_identity_batch(rng, num_ids=num_ids,
                                      imgs_per_id=imgs,
                                      dim=16, num_shards=1)
    f, l = np.concatenate(feats), np.concatenate(labs)
    dense_v, dense_g = _dense_fns(mesh, cfg)
    ring_v, ring_g = _ring_fns(mesh, cfg)
    fj, lj = jnp.asarray(f), jnp.asarray(l)
    dl, _ = dense_v(fj, lj)
    rl, _ = ring_v(fj, lj)
    np.testing.assert_allclose(
        np.asarray(rl), np.asarray(dl), rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(ring_g(fj, lj)), np.asarray(dense_g(fj, lj)),
        rtol=3e-5, atol=1e-6)


@pytest.mark.slow
@pytest.mark.skip(reason=(
    "gradient bit-identity between the cached and recompute backward is "
    "not achievable on the CPU backend: XLA fuses the fp32 weight-tile "
    "chain and the small per-hop gemms of the FUSED forward+backward "
    "program differently depending on whether the sim tiles are "
    "cached residuals or recomputed in-loop, perturbing reduction "
    "order by 1-2 ulp (~1% of grad entries, max |delta| ~2e-9 at grad "
    "scale ~1e-3); see the root-cause note in "
    "test_ring_sim_cache_near_identical, which pins the math instead"))
def test_ring_sim_cache_bit_identical(rng):
    """The per-shard similarity cache (parallel.ring sim_cache) replays
    exactly the tiles the recompute path produces, so cached and
    uncached runs must agree BIT-FOR-BIT — loss, metrics and gradients —
    on the flagship relative config across the 8-shard mesh (stats,
    radix-digit, loss and backward passes all exercised).  Auto mode
    enables the cache at test shapes, so this also keeps the recompute
    path covered.

    SKIPPED (pre-existing failure, root-caused at PR 10): the FORWARD
    outputs (loss + every metric) and the extracted residuals (pos/neg
    thresholds, max_all, ident/all sums) ARE bit-identical between the
    two modes — only the gradients differ, by 1-2 ulp in ~1% of
    entries.  The divergence is an XLA CPU fusion artifact, not a math
    bug: when any of the backward intermediates (the sim tile or the
    weight tile w) is materialized — returned as an output, or routed
    through a scan-carry slot — the gradients become bit-identical
    again, proving the replayed tiles equal the recomputed ones.  In
    the fully-fused grad program, XLA chooses different
    fusion/emission (and hence fp32 reduction order) for the
    weight-tile chain and the small per-hop grad gemms depending on
    whether ``sims`` is a cached-residual gather or an in-loop dot;
    ``jax.lax.optimization_barrier`` does not pin CPU fusion here, and
    pinning via materialization would cost the streaming path exactly
    the memory it exists to avoid.  The contract the cache can honestly
    promise — identical math, ulp-level gradients — is pinned by
    test_ring_sim_cache_near_identical below."""
    mesh = _mesh()
    g = len(mesh.devices)
    f, l = _make_inputs(rng, g, num_ids=6, imgs=3)
    f, l = jnp.asarray(f), jnp.asarray(l)

    outs = {}
    for cache in (True, False):
        def per_shard(f_, l_, cache=cache):
            loss, m = ring_npair_loss_and_metrics(
                f_, l_, REFERENCE_CONFIG, AXIS, (1,), sim_cache=cache
            )
            return jnp.asarray(loss)[None], jax.tree_util.tree_map(
                lambda x: jnp.asarray(x)[None], m
            )

        value = jax.jit(shard_map(
            per_shard, mesh=mesh, in_specs=(P(AXIS), P(AXIS)),
            out_specs=(P(AXIS), P(AXIS)),
        ))
        grad = jax.jit(shard_map(
            lambda f_, l_, cache=cache: jax.grad(
                lambda x: ring_npair_loss_and_metrics(
                    x, l_, REFERENCE_CONFIG, AXIS, (1,), sim_cache=cache
                )[0]
            )(f_),
            mesh=mesh, in_specs=(P(AXIS), P(AXIS)), out_specs=P(AXIS),
        ))
        loss, m = value(f, l)
        outs[cache] = (np.asarray(loss), m, np.asarray(grad(f, l)))

    loss_on, m_on, g_on = outs[True]
    loss_off, m_off, g_off = outs[False]
    assert np.array_equal(loss_on, loss_off)
    assert np.array_equal(g_on, g_off)
    for k in m_on:
        assert np.array_equal(np.asarray(m_on[k]), np.asarray(m_off[k])), k


@pytest.mark.slow
def test_ring_sim_cache_near_identical(rng):
    """The honest sim-cache parity contract (see the skip note on
    test_ring_sim_cache_bit_identical): cached and recompute runs agree
    BIT-FOR-BIT on the forward (loss + every metric) and to ulp-level
    tolerance on the gradients — the residual 1-2 ulp grad spread is
    XLA CPU fusion reordering the fp32 reductions, bounded here so a
    real replay bug (wrong tile, wrong hop order) still fails loudly:
    such a bug produces O(grad)-scale differences, ~6 orders of
    magnitude above this gate."""
    mesh = _mesh()
    g = len(mesh.devices)
    f, l = _make_inputs(rng, g, num_ids=6, imgs=3)
    f, l = jnp.asarray(f), jnp.asarray(l)

    outs = {}
    for cache in (True, False):
        def per_shard(f_, l_, cache=cache):
            loss, m = ring_npair_loss_and_metrics(
                f_, l_, REFERENCE_CONFIG, AXIS, (1,), sim_cache=cache
            )
            return jnp.asarray(loss)[None], jax.tree_util.tree_map(
                lambda x: jnp.asarray(x)[None], m
            )

        value = jax.jit(shard_map(
            per_shard, mesh=mesh, in_specs=(P(AXIS), P(AXIS)),
            out_specs=(P(AXIS), P(AXIS)),
        ))
        grad = jax.jit(shard_map(
            lambda f_, l_, cache=cache: jax.grad(
                lambda x: ring_npair_loss_and_metrics(
                    x, l_, REFERENCE_CONFIG, AXIS, (1,), sim_cache=cache
                )[0]
            )(f_),
            mesh=mesh, in_specs=(P(AXIS), P(AXIS)), out_specs=P(AXIS),
        ))
        loss, m = value(f, l)
        outs[cache] = (np.asarray(loss), m, np.asarray(grad(f, l)))

    loss_on, m_on, g_on = outs[True]
    loss_off, m_off, g_off = outs[False]
    # Forward IS bit-identical — the cached tiles replay exactly.
    assert np.array_equal(loss_on, loss_off)
    for k in m_on:
        assert np.array_equal(np.asarray(m_on[k]), np.asarray(m_off[k])), k
    # Gradients: ulp-level only (the documented fusion artifact).
    np.testing.assert_allclose(g_on, g_off, rtol=0.0, atol=1e-8)


@pytest.mark.slow
def test_ring_relative_clamp_quirk(rng):
    """A negative-valued relative threshold clamps to -FLT_MAX (cu:288
    etc.); scaled-down features make every similarity negative-capable."""
    cfg = NPairLossConfig(
        ap_mining_method=MiningMethod.RELATIVE_HARD, identsn=-0.9,
        an_mining_method=MiningMethod.RELATIVE_HARD, diffsn=-0.9,
    )
    mesh = _mesh()
    g = len(mesh.devices)
    f, l = _make_inputs(rng, g)
    f = -np.abs(f)  # all-negative features -> negative thresholds
    dense_v, _ = _dense_fns(mesh, cfg)
    ring_v, _ = _ring_fns(mesh, cfg)
    dl, _ = dense_v(jnp.asarray(f), jnp.asarray(l))
    rl, _ = ring_v(jnp.asarray(f), jnp.asarray(l))
    np.testing.assert_allclose(
        np.asarray(rl), np.asarray(dl), rtol=2e-5, atol=1e-6
    )


@pytest.mark.slow
def test_ring_ident_counts_match_dense(rng):
    """Selected-pair counts stream correctly (identNum/diffNum parity)."""
    cfg = NPairLossConfig(
        an_mining_method=MiningMethod.HARD, margin_diff=-0.05
    )
    mesh = _mesh()
    g = len(mesh.devices)
    f, l = _make_inputs(rng, g)

    def dense_counts(f_, l_):
        _, aux = npair_loss_with_aux(f_, l_, cfg, axis_name=AXIS)
        return aux["ident_num"].sum()[None], aux["diff_num"].sum()[None]

    dc = jax.jit(
        shard_map(
            dense_counts, mesh=mesh, in_specs=(P(AXIS), P(AXIS)),
            out_specs=(P(AXIS), P(AXIS)),
        )
    )
    ring_v, _ = _ring_fns(mesh, cfg)
    di, dd = dc(jnp.asarray(f), jnp.asarray(l))
    _, rm = ring_v(jnp.asarray(f), jnp.asarray(l))
    np.testing.assert_allclose(np.asarray(rm["ident_num"]), np.asarray(di))
    np.testing.assert_allclose(np.asarray(rm["diff_num"]), np.asarray(dd))


@pytest.mark.slow
def test_ring_all_same_label_is_zero_loss(rng):
    """No negatives anywhere -> D=0 -> log(I/I)=0 (zero-guard parity)."""
    mesh = _mesh()
    g = len(mesh.devices)
    n, d = 4, 8
    f = rng.standard_normal((g * n, d)).astype(np.float32)
    f /= np.linalg.norm(f, axis=1, keepdims=True)
    l = np.zeros((g * n,), np.int32)
    ring_v, ring_g = _ring_fns(mesh, NPairLossConfig())
    loss, _ = ring_v(jnp.asarray(f), jnp.asarray(l))
    grads = np.asarray(ring_g(jnp.asarray(f), jnp.asarray(l)))
    np.testing.assert_allclose(np.asarray(loss), 0.0, atol=1e-7)
    assert np.isfinite(grads).all()


@pytest.mark.slow
def test_solver_ring_step_trains(rng):
    """Full jitted training step with ring pooling over the 8-device mesh."""
    import jax.numpy as jnp

    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    mesh = _mesh()
    g = len(mesh.devices)
    solver = Solver(
        get_model("mlp", hidden=(16,), embedding_dim=8),
        NPairLossConfig(),
        SolverConfig(base_lr=0.1, lr_policy="fixed", display=0, snapshot=0),
        mesh=mesh,
        input_shape=(12,),
        use_ring=True,
    )
    from npairloss_tpu.data import synthetic_identity_batches

    batches = synthetic_identity_batches(4 * g, 2 * g, 2, (12,), noise=0.6)
    losses = []
    for _ in range(12):
        x, lab = next(batches)
        m = solver.step(x, lab)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert min(losses[-4:]) <= max(losses[:4])


@pytest.mark.slow
def test_solver_ring_reference_config_trains(rng):
    """The flagship GLOBAL/RELATIVE_HARD config runs end-to-end in ring
    mode (previously dense-only)."""
    from npairloss_tpu.data import synthetic_identity_batches
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    mesh = _mesh()
    g = len(mesh.devices)
    solver = Solver(
        get_model("mlp", hidden=(16,), embedding_dim=8),
        REFERENCE_CONFIG,
        SolverConfig(base_lr=0.1, lr_policy="fixed", display=0, snapshot=0),
        mesh=mesh,
        input_shape=(12,),
        use_ring=True,
    )
    batches = synthetic_identity_batches(4 * g, 2 * g, 2, (12,), noise=0.6)
    for _ in range(4):
        x, lab = next(batches)
        m = solver.step(x, lab)
    assert np.isfinite(float(m["loss"]))
