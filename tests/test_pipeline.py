"""Sync-free stepping tests (docs/PIPELINE.md): parity against the
synchronous loop (bit-identical params, byte-identical metric-key
streams), prefetcher drain/crash/resume, dispatch-depth bounding, the
no-mid-window-host-sync contract, and the compile-cache warmup path."""

import json
import os
import threading

import numpy as np
import jax
import pytest

from npairloss_tpu import MiningMethod, NPairLossConfig
from npairloss_tpu.data import synthetic_identity_batches
from npairloss_tpu.models import get_model
from npairloss_tpu.parallel import data_parallel_mesh
from npairloss_tpu.pipeline import (
    DevicePrefetcher,
    DispatchController,
    HostSyncMonitor,
    MetricWindow,
    PrefetchStageError,
    disable_compile_cache,
    enable_compile_cache,
)
from npairloss_tpu.resilience import DivergenceConfig, failpoints
from npairloss_tpu.train import Solver, SolverConfig


def _make_solver(pipeline, mesh=None, **cfg_kw):
    kw = dict(
        base_lr=0.5, lr_policy="fixed", momentum=0.9, weight_decay=0.0,
        display=5, test_interval=0, snapshot=0, average_loss=10,
        pipeline=pipeline,
    )
    kw.update(cfg_kw)
    loss_cfg = NPairLossConfig(
        margin_diff=-0.05,
        an_mining_method=MiningMethod.HARD,
        ap_mining_method=MiningMethod.RAND,
    )
    model = get_model("mlp", hidden=(32,), embedding_dim=16)
    solver = Solver(model, loss_cfg, SolverConfig(**kw), mesh=mesh,
                    input_shape=(16,))
    batches = synthetic_identity_batches(8, 8, 2, (16,), noise=0.6)
    return solver, batches


def _params_equal(a, b):
    eq = jax.tree_util.tree_map(
        lambda x, y: bool(np.array_equal(np.asarray(x), np.asarray(y))),
        a, b,
    )
    return all(jax.tree_util.tree_leaves(eq))


# -- unit pieces -----------------------------------------------------------


class _FakeToken:
    def __init__(self, log, name):
        self.log, self.name = log, name

    def block_until_ready(self):
        self.log.append(self.name)


def test_dispatch_controller_bounds():
    log = []
    ctl = DispatchController(max_in_flight=2)
    for i in range(5):
        ctl.reserve()
        # The bound holds BEFORE each dispatch, and waits happen on the
        # OLDEST token, in order.
        assert ctl.in_flight <= 1
        ctl.admit(_FakeToken(log, i))
    assert log == [0, 1, 2]  # 5 dispatches, depth 2 -> blocked on 0,1,2
    ctl.drain()
    assert log == [0, 1, 2, 3, 4]
    assert ctl.blocked == 3
    with pytest.raises(ValueError):
        DispatchController(0)


def test_metric_window_roundtrip_and_streak():
    win = MetricWindow(["loss", "top1"], capacity=4)
    ring = win.init_ring()
    for loss, top1 in ((1.0, 0.5), (float("nan"), 0.25)):
        ring = win.update(
            ring, {"loss": np.float32(loss), "top1": np.float32(top1)}
        )
    host = jax.device_get(ring)
    rows = win.read(host)
    assert [list(r) for r in rows] == [["loss", "top1"]] * 2
    assert rows[0]["loss"] == np.float32(1.0)
    assert np.isnan(rows[1]["loss"])
    assert int(host["streak"]) == 1 and int(host["max_streak"]) == 1
    # Reset rewinds the buffer but carries the in-flight streak.
    ring = win.reset(ring)
    assert int(jax.device_get(ring["pos"])) == 0
    assert int(jax.device_get(ring["streak"])) == 1
    with pytest.raises(ValueError):
        MetricWindow(["top1"], 4)  # loss is mandatory


def test_prefetcher_stages_ahead_and_closes():
    placed = []

    def place(x, lab):
        placed.append(threading.get_ident())
        return jax.device_put((x, lab))

    def gen():
        for i in range(100):
            yield np.full((2, 4), i, np.float32), \
                np.arange(2, dtype=np.int32)

    with DevicePrefetcher(gen(), place, depth=2) as pf:
        for i in range(5):
            x, lab = pf.get()
            assert float(np.asarray(x)[0, 0]) == i
        assert pf.consumed == 5 and pf.staged >= 5
    # Staging ran off the consumer thread, and close() joined it.
    assert set(placed) != {threading.get_ident()}
    assert not pf._thread.is_alive()
    with pytest.raises(RuntimeError):
        pf.get()


def test_prefetcher_end_of_data_and_failure():
    place = lambda x, lab: (x, lab)  # noqa: E731
    pf = DevicePrefetcher(iter([(1, 2)]), place, depth=2)
    assert pf.get() == (1, 2)
    with pytest.raises(StopIteration):
        pf.get()
    pf.close()

    def gen():
        yield np.zeros(1), np.zeros(1)
        yield np.zeros(1), np.zeros(1)

    failpoints.reset()
    failpoints.arm("pipeline.stage", times=1)
    try:
        pf = DevicePrefetcher(gen(), place, depth=2)
        with pytest.raises(PrefetchStageError) as ei:
            pf.get()
        assert ei.value.batch_index == 0
        pf.close()
        assert not pf._thread.is_alive()
    finally:
        failpoints.reset()


# -- parity (the acceptance pin) ------------------------------------------


def _run_with_telemetry(solver, batches, num_iters, tmp_path, tag,
                        test_batches=None):
    from npairloss_tpu.obs import RunTelemetry

    logs = []
    tel = RunTelemetry(str(tmp_path / tag), trace=False)
    solver.telemetry = tel
    try:
        last = solver.train(batches, num_iters=num_iters,
                            test_batches=test_batches, log_fn=logs.append)
    finally:
        tel.close()
    rows = [json.loads(line) for line in
            open(tmp_path / tag / "metrics.jsonl")]
    return last, logs, rows


def test_pipelined_parity_single_device(tmp_path):
    """Sync vs pipelined: byte-identical metric-key streams (telemetry
    rows AND display lines) and bit-identical params, eval included."""
    outs = {}
    for tag, pipeline in (("sync", False), ("pipe", True)):
        solver, batches = _make_solver(
            pipeline, test_interval=6, test_iter=1,
            test_initialization=False,
        )
        outs[tag] = (solver,) + _run_with_telemetry(
            solver, batches, 12, tmp_path, tag,
            test_batches=synthetic_identity_batches(8, 8, 2, (16,),
                                                    noise=0.6, seed=1),
        )
    s_sync, last_s, logs_s, rows_s = outs["sync"]
    s_pipe, last_p, logs_p, rows_p = outs["pipe"]
    assert logs_s == logs_p  # display + TEST lines, values included
    assert last_s == last_p
    # Byte-identical metric-KEY streams: same rows, same key order.
    keys_s = [list(r) for r in rows_s]
    keys_p = [list(r) for r in rows_p]
    assert keys_s == keys_p
    # And the step/phase/value payloads match (envelope wall_time/run_id
    # legitimately differ).
    for rs, rp in zip(rows_s, rows_p):
        for k in rs:
            if k not in ("wall_time", "run_id"):
                assert rs[k] == rp[k], k
    assert _params_equal(s_sync.state["params"], s_pipe.state["params"])


def test_pipelined_parity_mesh_8dev(tmp_path):
    """The acceptance pin: >= 10 steps on the virtual 8-device CPU mesh,
    bit-identical params + identical metric-key streams."""
    outs = {}
    for tag, pipeline in (("sync", False), ("pipe", True)):
        mesh = data_parallel_mesh(jax.devices()[:8])
        solver, batches = _make_solver(pipeline, mesh=mesh, display=4)
        outs[tag] = (solver,) + _run_with_telemetry(
            solver, batches, 11, tmp_path, tag
        )
    s_sync, last_s, logs_s, rows_s = outs["sync"]
    s_pipe, last_p, logs_p, rows_p = outs["pipe"]
    assert logs_s == logs_p
    assert last_s == last_p
    assert [list(r) for r in rows_s] == [list(r) for r in rows_p]
    assert _params_equal(s_sync.state["params"], s_pipe.state["params"])


# -- the sync-free contract ------------------------------------------------


def test_pipelined_no_midwindow_host_syncs():
    solver, batches = _make_solver(True)
    mon = HostSyncMonitor(strict=True)  # a violation raises immediately
    solver.sync_monitor = mon
    solver.train(batches, num_iters=20, log_fn=lambda s: None)
    c = mon.counts()
    # Every batch put happened on the staging thread...
    assert c["put_guarded"] == 0 and c["put"] >= 20
    # ...and the step-loop thread read back exactly once per window
    # (display=5 -> boundaries at 5/10/15/20).
    assert c["get_guarded"] == 4
    assert mon.violations() == []


def test_pipeline_window_capacity_rules():
    solver, _ = _make_solver(True, display=100, snapshot=30)
    assert solver._pipeline_window_capacity(test_active=False) == 30
    solver.cfg.display = 0
    solver.cfg.snapshot = 0
    assert solver._pipeline_window_capacity(test_active=False) == 64
    solver.cfg.pipeline_window = 7
    assert solver._pipeline_window_capacity(test_active=False) == 7
    solver.cfg.display = 5
    assert solver._pipeline_window_capacity(test_active=False) == 5


def test_pipelined_exhaustion_flushes_window_tail(tmp_path):
    """A stream that exhausts mid-window must not drop the tail's
    telemetry: the pending rows are flushed on the way out, matching
    what the synchronous loop had already emitted step-by-step."""
    from npairloss_tpu.obs import RunTelemetry

    def seven():
        g = synthetic_identity_batches(8, 8, 2, (16,), noise=0.6)
        for _ in range(7):
            yield next(g)

    rows = {}
    for tag, pipeline in (("sync", False), ("pipe", True)):
        solver, _ = _make_solver(pipeline, display=0, pipeline_window=10)
        tel = RunTelemetry(str(tmp_path / tag), trace=False)
        solver.telemetry = tel
        try:
            with pytest.raises(StopIteration):
                solver.train(seven(), num_iters=50, log_fn=lambda s: None)
        finally:
            tel.close()
        rows[tag] = [json.loads(line) for line in
                     open(tmp_path / tag / "metrics.jsonl")]
    assert [r["step"] for r in rows["pipe"]] == [1, 2, 3, 4, 5, 6, 7]
    assert [list(r) for r in rows["sync"]] == [list(r) for r in
                                               rows["pipe"]]
    for rs, rp in zip(rows["sync"], rows["pipe"]):
        for k in rs:
            if k not in ("wall_time", "run_id"):
                assert rs[k] == rp[k], k


def test_pipelined_step_rebuild_relabels_compile():
    """A rebuilt pipelined step (cfg replaced, e.g. a rollback's
    lr_scale) is a NEW program: the shape-tracking must reset so the
    recompile is labeled step/compile and the expected-donation-warning
    filter is reinstalled — not a mislabeled step/dispatch leaking
    XLA's 'donated buffers were not usable' warning."""
    import warnings as _w

    solver, batches = _make_solver(True, display=0, pipeline_window=2)
    solver.train(batches, num_iters=2, log_fn=lambda s: None)
    assert solver._seen_step_shapes
    solver.cfg = solver.cfg  # the setter drops every jitted step
    assert solver._pipe_step_fn is None
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        solver.train(batches, num_iters=4, log_fn=lambda s: None)
    assert not [w for w in rec if "donated buffers" in str(w.message)]
    # The rebuild re-registered exactly the live signature.
    assert len(solver._seen_step_shapes) == 1


# -- resilience interop ----------------------------------------------------


@pytest.mark.slow  # snapshot commit + rollback restore: ~6s (tier-1 budget)
def test_pipelined_guard_rollback_windowed(tmp_path):
    """step.nan_loss mid-window: the guard trips at the boundary read,
    rolls back to a pre-streak snapshot, and training continues —
    identical recovery semantics, detection deferred to the window."""
    solver, batches = _make_solver(
        True, display=0, snapshot=4, pipeline_window=4,
        snapshot_prefix=str(tmp_path / "g_"),
    )
    solver.divergence = DivergenceConfig(patience=2, action="rollback",
                                         max_rollbacks=1)
    failpoints.reset()
    logs = []
    solver.train(batches, num_iters=6, log_fn=logs.append)
    failpoints.arm("step.nan_loss", times=2)
    try:
        solver.train(batches, num_iters=10, log_fn=logs.append)
    finally:
        failpoints.reset()
    rolled = [s for s in logs if "rolled back to iteration 4" in s]
    assert rolled, logs
    assert "2 consecutive non-finite losses at iteration 8" in rolled[0]
    assert solver.iteration == 10


def test_pipelined_guard_streak_resets_after_poisoned_window(monkeypatch):
    """A sub-patience poison streak at a window TAIL must be RESET by a
    later all-finite window: host-side poison is invisible to the
    device counter, so the replay must also run whenever the guard
    carries a streak — otherwise a lone NaN windows later completes a
    phantom streak and trips the guard where the sync loop would not."""
    calls = {"n": 0}
    real = failpoints.should_fire

    def fake(name):
        # Poison exact STEP numbers (one check per step), immune to the
        # prefetch-depth offset generator-side arming would have: 3-4
        # end window 1 with streak 2 (< patience 3); window 2 (5-8) is
        # all finite; the lone NaN at 9 must see streak 1, not 3.
        if name == "step.nan_loss":
            calls["n"] += 1
            return calls["n"] in (3, 4, 9)
        return real(name)

    monkeypatch.setattr(failpoints, "should_fire", fake)
    solver, batches = _make_solver(True, display=0, snapshot=0,
                                   pipeline_window=4)
    solver.divergence = DivergenceConfig(patience=3, action="halt")
    solver.train(batches, num_iters=12, log_fn=lambda s: None)
    assert solver.iteration == 12  # no phantom DivergenceError


@pytest.mark.slow  # 3 solvers + snapshot/restore: ~20s (tier-1 budget)
def test_pipelined_crash_resume_replays_batch_index(tmp_path):
    """A pipeline.stage crash mid-window surfaces, drains cleanly, and
    --resume auto + replaying the consumed batch stream yields params
    bit-identical to an uninterrupted synchronous run."""

    def indexed_batches(start=0):
        # Deterministic stream keyed by batch index so a resumed run can
        # replay from exactly the right position.
        gens = synthetic_identity_batches(8, 8, 2, (16,), noise=0.6)
        stream = [next(gens) for _ in range(32)]
        for i in range(start, len(stream)):
            yield stream[i]

    cfg = dict(display=0, snapshot=4, pipeline_window=4,
               snapshot_prefix=str(tmp_path / "c_"))

    # Reference: uninterrupted SYNC run to 8 steps (consumes batches
    # 0..7 — the parity anchor for the resumed pipelined run), with its
    # OWN snapshot prefix so its iter-8 snapshot cannot shadow the
    # crashed run's newest-valid candidate.
    ref, _ = _make_solver(False, **{**cfg,
                                    "snapshot_prefix": str(tmp_path / "r_")})
    ref.train(indexed_batches(), num_iters=8, log_fn=lambda s: None)

    # Pipelined run crashes mid-window-2: the 7th host batch arms the
    # pipeline.stage failpoint, so the staging thread dies while steps
    # 5-6 are in flight (window 2 never reaches its boundary).
    crashed, _ = _make_solver(True, **cfg)

    class _ArmAtBatch6:
        def __init__(self):
            self.it = indexed_batches()
            self.n = 0

        def __iter__(self):
            return self

        def __next__(self):
            if self.n == 6:
                failpoints.arm("pipeline.stage", times=1)
            self.n += 1
            return next(self.it)

    failpoints.reset()
    try:
        with pytest.raises(PrefetchStageError):
            crashed.train(_ArmAtBatch6(), num_iters=16,
                          log_fn=lambda s: None)
    finally:
        failpoints.reset()
    # Clean drain: no staging thread left alive behind the raise.
    assert not [t for t in threading.enumerate()
                if t.name == "npairloss-pipeline-stage" and t.is_alive()]
    # The snapshot cadence committed iteration 4 before the crash.
    resumed, _ = _make_solver(True, **cfg)
    restored = resumed.restore_auto()
    assert restored and resumed.iteration == 4
    # Replay from the correct batch index: iteration k consumed batch
    # k-1, so the resumed run continues with batch index 4.
    resumed.train(indexed_batches(start=resumed.iteration),
                  num_iters=8, log_fn=lambda s: None)
    assert _params_equal(ref.state["params"], resumed.state["params"])


def test_pipelined_preempt_flushes_partial_window(tmp_path):
    from npairloss_tpu.resilience import PreemptionSignal, TrainingPreempted

    solver, batches = _make_solver(
        True, display=0, snapshot=0, pipeline_window=10,
        snapshot_prefix=str(tmp_path / "p_"),
    )
    solver.preempt = PreemptionSignal()
    solver.preempt.request()
    with pytest.raises(TrainingPreempted) as ei:
        solver.train(batches, num_iters=50, log_fn=lambda s: None)
    # Preempt is polled per step: the boundary fired at step 1, flushed
    # the one-step window, and committed the emergency snapshot.
    assert ei.value.step == 1
    assert os.path.isdir(ei.value.snapshot_path)


# -- compile cache / warmup ------------------------------------------------


@pytest.fixture
def compile_cache_off_after():
    """The cache is process-global jax config; a test must not leak it
    into the rest of the suite (a cache-HIT executable enforces
    donations a fresh CPU compile prunes — zero-copy np views of
    donated state then mutate, see disable_compile_cache's docstring)."""
    yield
    disable_compile_cache()


def test_warmup_populates_compile_cache(tmp_path, compile_cache_off_after):
    cache = tmp_path / "xla_cache"
    solver, _ = _make_solver(False, compile_cache=str(cache))
    dt = solver.warmup(4)
    assert dt > 0
    entries = [f for f in os.listdir(cache) if f.endswith("-cache")]
    assert entries, "warmup did not populate the compilation cache"
    # warmup is AOT: nothing dispatched, no training state consumed.
    assert solver.iteration == 0


def test_enable_compile_cache_idempotent(tmp_path, compile_cache_off_after):
    p1 = enable_compile_cache(str(tmp_path / "cc"))
    p2 = enable_compile_cache(str(tmp_path / "cc"))
    assert p1 == p2 and os.path.isdir(p1)


def test_cache_hit_executable_enforces_donation(tmp_path,
                                                compile_cache_off_after):
    """Pin the sharp edge disable_compile_cache documents: a cache-HIT
    executable donates where a fresh CPU compile pruned, so zero-copy
    views of donated inputs mutate.  If a jax upgrade changes this,
    the docstring should be updated too."""
    import jax.numpy as jnp

    enable_compile_cache(str(tmp_path / "cc"))

    def probe():
        f = jax.jit(lambda s: s * 2.0, donate_argnums=0)
        s = f(jnp.arange(4, dtype=jnp.float32))
        view = np.asarray(s)
        ref = view.copy()
        jax.block_until_ready(f(s))  # donates s's buffer
        return bool(np.array_equal(view, ref))

    probe()  # miss: compiles + writes the entry
    stable_on_hit = probe()
    # Whichever way jax behaves, the FRAMEWORK contract holds: nothing
    # in Solver retains zero-copy views across steps.  Record the
    # current jax behavior so a silent change is visible.
    assert stable_on_hit is False
