"""A new exchange path that forgot its comm marker."""
import jax


def grad_sync(grads, axis_name):
    with jax.named_scope("optim/sync"):
        return jax.lax.psum(grads, axis_name)
