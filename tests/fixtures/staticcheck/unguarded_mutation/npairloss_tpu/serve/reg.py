"""A guarded attribute mutated off-lock — the race staticcheck exists
to catch before a thread does."""
import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}  # guarded-by: _lock

    def put(self, key, value):
        with self._lock:
            self._items[key] = value

    def reset(self):
        self._items = {}
