import argparse


def main(argv=None):
    p = argparse.ArgumentParser()
    sub = p.add_subparsers(dest="cmd")
    s = sub.add_parser("serve")
    s.add_argument("--port", type=int, default=0)
    args = p.parse_args(argv)
    return 0
