from npairloss_tpu.resilience import failpoints


def poke():
    failpoints.fire("other.fault")
