"""Fires a failpoint the runbook never heard of."""
from npairloss_tpu.resilience import failpoints


def dispatch():
    if failpoints.should_fire("serve.bogus"):
        raise OSError("injected")
