"""An innocent-looking helper that drags the backend in."""
import jax


def device_count():
    return jax.device_count()
