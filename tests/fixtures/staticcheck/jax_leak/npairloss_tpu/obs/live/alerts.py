"""A declared contract module that BREAKS its jax-free contract:
the helper import below transitively reaches jax at module level."""
import json

from npairloss_tpu.obs.live.helper import device_count


def load_alert_log(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]
