"""A versioned contract nobody can hold anything to."""
ORPHAN_SCHEMA = "npairloss-orphan-v1"


def build_orphan(value):
    return {"schema": ORPHAN_SCHEMA, "value": value}
