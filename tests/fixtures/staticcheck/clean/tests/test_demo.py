import pytest


@pytest.mark.slow
def test_big_thing():
    assert True


def test_small_thing():  # slow-ok: deliberately kept in tier-1 (fixture)
    assert True
