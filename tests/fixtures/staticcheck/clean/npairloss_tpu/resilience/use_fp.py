"""A documented failpoint call site."""
from npairloss_tpu.resilience import failpoints


def risky_save():
    failpoints.fire("demo.save.crash")
