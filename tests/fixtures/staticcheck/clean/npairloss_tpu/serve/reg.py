"""Clean lock discipline: every mutation under the declared lock."""
import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}  # guarded-by: _lock
        self.count = 0  # guarded-by: _lock

    def put(self, key, value):
        with self._lock:
            self._items[key] = value
            self.count += 1

    def _drop_locked(self, key):  # holds-lock: _lock
        self._items.pop(key, None)
        self.count -= 1

    def drop(self, key):
        with self._lock:
            self._drop_locked(key)

    def debug_reset(self):
        # unguarded-ok: test-only helper, single-threaded by contract
        self._items = {}
