"""Mini CLI whose documented flags exist."""
import argparse


def main(argv=None):
    p = argparse.ArgumentParser()
    sub = p.add_subparsers(dest="cmd")
    d = sub.add_parser("demo")
    d.add_argument("--rounds", type=int, default=1)
    args = p.parse_args(argv)
    return 0
