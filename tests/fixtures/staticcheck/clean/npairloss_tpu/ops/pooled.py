"""Clean collective usage: every exchange carries its comm marker."""
import jax


def pooled_mean(x, axis_name):
    with jax.named_scope("pool/gather"), \
            jax.named_scope("comm/all_gather"):
        everyone = jax.lax.all_gather(x, axis_name)
    with jax.named_scope("comm/allreduce"):
        total = jax.lax.psum(x, axis_name)
    return everyone, total
