"""A versioned contract done right: schema constant + validator."""
FOO_SCHEMA = "npairloss-foo-v1"

FOO_KEYS = ("schema", "value")


def build_foo(value):
    return {"schema": FOO_SCHEMA, "value": value}


def validate_foo_report(rec):
    if not isinstance(rec, dict):
        return "not an object"
    if rec.get("schema") != FOO_SCHEMA:
        return "bad schema"
    for key in FOO_KEYS:
        if key not in rec:
            return f"missing {key!r}"
    return None
