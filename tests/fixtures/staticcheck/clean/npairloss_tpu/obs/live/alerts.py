"""A declared contract module that honors its jax-free contract."""
import json
import os


def load_alert_log(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def where():
    return os.path.abspath(__file__)
