def test_giant_compile():
    assert True
