"""The reference layer is templated over Dtype and its MPI dispatch
handles double (npair_multi_class_loss.cu:38-41, cu:471-487).  The TPU
engines are fp32-by-design (fp64 is software-emulated on TPU; see
PARITY.md "Dtype=double"), so the double instantiation lives in the
ORACLE: ``oracle.forward/backward(dtype=np.float64)`` renders the exact
double semantics — including the (Dtype)-FLT_MAX clamps the reference
keeps even at double precision (cu:230-236, cu:288).

These tests pin (a) that the fp64 oracle is self-consistent with the
fp32 oracle to fp32 tolerance (so fp32 loses nothing on flagship-shaped
inputs), and (b) that the fp32 JAX engine matches the fp64 oracle as
closely as it matches the fp32 one — the evidence behind the fp32-only
decision.
"""

import jax
import numpy as np
import pytest

from conftest import make_identity_batch
from npairloss_tpu import MiningMethod, MiningRegion, NPairLossConfig
from npairloss_tpu.ops.npair_loss import REFERENCE_CONFIG, npair_loss_with_aux
from npairloss_tpu.testing import oracle

GRID = [
    REFERENCE_CONFIG,
    NPairLossConfig(
        margin_ident=0.02, identsn=-0.4,
        ap_mining_region=MiningRegion.GLOBAL,
        ap_mining_method=MiningMethod.RELATIVE_HARD,
        an_mining_region=MiningRegion.LOCAL,
        an_mining_method=MiningMethod.HARD,
    ),
    NPairLossConfig(
        margin_diff=-0.05, diffsn=-0.3,
        ap_mining_region=MiningRegion.LOCAL,
        ap_mining_method=MiningMethod.EASY,
        an_mining_region=MiningRegion.GLOBAL,
        an_mining_method=MiningMethod.RELATIVE_EASY,
    ),
]


@pytest.mark.parametrize("cfg", GRID)
def test_fp64_oracle_matches_fp32_oracle(rng, cfg):
    feats, labs = make_identity_batch(rng, 4, 3, 8)
    r32 = oracle.forward(feats, labs, cfg)
    r64 = oracle.forward(feats, labs, cfg, dtype=np.float64)
    assert r64[0].sims.dtype == np.float64
    # Mining SELECTIONS must be identical — thresholds are order
    # statistics of the similarity list, and fp32 rounding must not
    # flip any on these well-separated inputs.
    np.testing.assert_array_equal(r32[0].select, r64[0].select)
    np.testing.assert_allclose(r32[0].loss, r64[0].loss, rtol=1e-5)
    g32 = oracle.backward(feats, r32)
    g64 = oracle.backward(feats, r64, dtype=np.float64)
    assert g64[0].dtype == np.float64
    np.testing.assert_allclose(g32[0], g64[0], rtol=1e-4, atol=1e-7)


@pytest.mark.parametrize("cfg", GRID)
def test_fp32_engine_matches_fp64_oracle(rng, cfg):
    """The fp32 JAX engine agrees with the DOUBLE instantiation's
    semantics to fp32 tolerance — fp64 would add precision the flagship
    workload cannot observe."""
    feats, labs = make_identity_batch(rng, 4, 3, 8)
    want = oracle.forward(feats, labs, cfg, dtype=np.float64)[0]
    loss, aux = jax.jit(
        lambda f, l: npair_loss_with_aux(f, l, cfg)
    )(feats[0], labs[0])
    np.testing.assert_allclose(float(loss), want.loss, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(
        np.asarray(aux["pos_threshold"], np.float64), want.pos_thr,
        rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(
        np.asarray(aux["neg_threshold"], np.float64), want.neg_thr,
        rtol=1e-5, atol=1e-7)


def test_fp64_keeps_flt_max_clamps():
    """cu:230-236/cu:288 write (Dtype)-FLT_MAX even for double: the
    empty-list fill and the <0 clamp must be FLT_MAX-magnitude in the
    fp64 oracle, NOT DBL_MAX."""
    # One identity, one image: no positives and no negatives anywhere
    # -> every mining statistic keeps its fill value.
    feats = [np.ones((1, 4), np.float64)]
    labs = [np.zeros((1,), np.float64)]
    cfg = NPairLossConfig(
        ap_mining_region=MiningRegion.LOCAL,
        ap_mining_method=MiningMethod.RELATIVE_HARD,
        an_mining_region=MiningRegion.LOCAL,
        an_mining_method=MiningMethod.RELATIVE_HARD,
    )
    res = oracle.forward(feats, labs, cfg, top_ks=(), dtype=np.float64)[0]
    flt_max = float(np.finfo(np.float32).max)
    assert res.max_all[0] == -flt_max
    assert res.pos_thr[0] == flt_max  # empty ident list -> +FLT_MAX fill
    # (loss is nan here in BOTH precisions: exp(s + FLT_MAX) overflows
    # and inf*0 = nan — the reference's own batch-of-1 hazard, which the
    # oracle reproduces faithfully and the JAX engine guards to 0;
    # tests/test_pallas.py::test_blockwise_batch_of_one_grad_finite.)
    assert np.isnan(res.loss)
