"""Live observatory tests (docs/OBSERVABILITY.md §Live observatory).

Covers the whole chain the ci.sh smoke drives end-to-end: registry /
histogram semantics, the sink adapter's zero-footprint contract
(byte-parity pin), SLO burn-rate math and hysteresis on hand-crafted
fixtures, the npairloss-alerts-v1 validator's teeth, the
watch-vs-in-process evaluator agreement, /metrics exposition format,
freshness ages, the serve failpoints, and the bench_check --alerts
gate.  Most tests are stdlib-only and sub-millisecond; the few that
build a QueryEngine use tiny galleries.
"""

import json
import math
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from npairloss_tpu.obs.live import (
    ALERTS_SCHEMA,
    AlertEngine,
    LiveObservatory,
    MetricRegistry,
    RegistrySink,
    SLOEvaluator,
    SLOSpec,
    default_watchdogs,
    load_alert_log,
    load_slo_config,
    prometheus_text,
    replay_records,
    start_http_exporter,
    unresolved_alerts,
    validate_alert_log,
    watch_run_dir,
)
from npairloss_tpu.obs.live.registry import DEFAULT_BOUNDS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spec(**kw):
    base = dict(name="s", metric="m", op="<=", target=10.0,
                window_s=10.0, burn_threshold=0.5, min_samples=1)
    base.update(kw)
    return SLOSpec(**base)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


def test_registry_counter_gauge_semantics():
    reg = MetricRegistry()
    reg.inc("c")
    reg.inc("c", 2.5)
    assert reg.get("c").value == 3.5
    with pytest.raises(ValueError):
        reg.counter("c").inc(-1)
    reg.set("g", 1.0, t=100.0)
    reg.set("g", 2.0, t=101.0)
    assert reg.get("g").value == 2.0
    assert reg.samples_since("g", 100.5) == [(101.0, 2.0)]
    assert reg.samples_since("g", 0.0) == [(100.0, 1.0), (101.0, 2.0)]
    # counters have no sample window
    assert reg.samples_since("c", 0.0) == []
    # kind collision is a programming error, loudly
    with pytest.raises(ValueError):
        reg.gauge("c")
    with pytest.raises(ValueError):
        reg.counter("g")


def test_registry_histogram_semantics():
    reg = MetricRegistry()
    h = reg.histogram("h", bounds=(1.0, 5.0, 10.0))
    # boundary value lands IN its bucket (le semantics), overflow in +Inf
    for v in (0.5, 1.0, 3.0, 10.0, 11.0):
        h.observe(v, t=50.0)
    assert h.bucket_counts == [2, 1, 1, 1]
    assert h.cumulative_counts() == [2, 3, 4, 5]
    assert h.count == 5
    assert h.sum == pytest.approx(25.5)
    # histograms feed the SLO sample window like gauges
    assert len(reg.samples_since("h", 0.0)) == 5
    # re-registration with different bounds is loud
    with pytest.raises(ValueError):
        reg.histogram("h", bounds=(1.0, 2.0))
    with pytest.raises(ValueError):
        MetricRegistry().histogram("bad", bounds=(5.0, 1.0))


# ---------------------------------------------------------------------------
# sink adapter
# ---------------------------------------------------------------------------


def test_sink_maps_rows_to_metrics():
    sink = RegistrySink()
    reg = sink.registry
    sink.log({"phase": "train", "step": 3, "wall_time": 100.0,
              "loss": 1.5, "lr": 0.01, "run_id": "r"})
    assert reg.get("train_rows").value == 1
    assert reg.get("train_loss").value == 1.5  # generic gauge mapping
    assert reg.get("train_loss_hist").count == 1  # histogram observation
    assert reg.get("train_lr").value == 0.01
    assert reg.get("train_step").value == 3
    # strings / bools / envelope keys never become gauges
    assert reg.get("train_run_id") is None
    sink.log({"phase": "serve", "wall_time": 101.0, "step": 0,
              "p99_ms": 42.0, "qps": 10.0})
    assert reg.get("serve_p99_ms").value == 42.0
    assert reg.get("serve_latency_ms").count == 1


def test_sink_nonfinite_streak_and_spread():
    sink = RegistrySink()
    reg = sink.registry
    for loss in (1.0, float("nan"), float("inf"), 2.0):
        sink.log({"phase": "train", "step": 1, "wall_time": 1.0,
                  "loss": loss})
    assert reg.get("train_nonfinite_loss").value == 2
    # streak reset by the final finite loss
    assert reg.get("train_nonfinite_streak").value == 0.0
    # mid-stream the streak reached 2 (sample history shows it)
    vals = [v for _, v in reg.get("train_nonfinite_streak").samples]
    assert vals == [0.0, 1.0, 2.0, 0.0]
    # NaN never lands in a gauge or a histogram
    assert all(math.isfinite(v)
               for _, v in reg.get("train_loss").samples)
    assert reg.get("train_loss_hist").count == 2  # the finite two
    sink.log({"phase": "train", "step": 2, "wall_time": 2.0,
              "emb_mag_mean": 1.0, "emb_mag_max": 1.5})
    assert reg.get("train_emb_mag_spread").value == pytest.approx(1.5)


def test_sink_fleet_step_lag():
    sink = RegistrySink()
    reg = sink.registry
    sink.log({"phase": "train", "step": 10, "wall_time": 1.0,
              "loss": 1.0, "process_index": 0, "process_count": 2})
    assert reg.get("fleet_step_lag") is None  # one rank = no lag yet
    sink.log({"phase": "train", "step": 7, "wall_time": 1.1,
              "loss": 1.0, "process_index": 1, "process_count": 2})
    assert reg.get("fleet_step_lag").value == 3.0


def test_sink_event_rows_count_but_never_gauge():
    """The drain summary carries WHOLE-RUN percentiles under the same
    keys as window rows — ingesting it as samples would re-fire a
    resolved p99 alert at the final tick (regression pin)."""
    sink = RegistrySink()
    reg = sink.registry
    sink.log({"phase": "serve", "step": 0, "wall_time": 5.0,
              "event": "serve_drain", "p99_ms": 5000.0, "answered": 10})
    assert reg.get("serve_event_serve_drain").value == 1
    assert reg.get("serve_p99_ms") is None
    assert reg.get("serve_answered") is None


def test_sink_never_mutates_never_raises():
    sink = RegistrySink()
    rec = {"phase": "train", "step": 1, "wall_time": 1.0, "loss": 1.0,
           "nested": {"x": 1}}
    snapshot = dict(rec)
    sink.log(rec)
    assert rec == snapshot
    # a poisoned registry must not propagate out of log()
    class Boom:
        def __getattr__(self, name):
            raise RuntimeError("boom")

    sink.registry = Boom()
    sink.log({"phase": "train", "step": 1, "wall_time": 1.0})  # no raise


def test_sink_byte_parity_of_jsonl_stream(tmp_path, monkeypatch):
    """Attaching the RegistrySink as an extra sink must not change ONE
    byte of the on-disk telemetry stream — the zero-footprint half of
    the live-obs parity contract (the other half is that no sink is
    attached at all when --live-obs is off)."""
    from npairloss_tpu.obs import run as obs_run

    rows = [
        ("train", 1, {"loss": 1.25, "lr": 0.01}),
        ("train", 2, {"loss": float("nan"), "lr": 0.01}),
        ("serve", 0, {"qps": 10.0, "p99_ms": 3.25}),
        ("eval", 2, {"loss": 0.5}),
    ]
    monkeypatch.setattr(obs_run.time, "time", lambda: 1234.5)
    streams = {}
    for variant in ("plain", "with_sink"):
        d = tmp_path / variant
        extra = (RegistrySink(),) if variant == "with_sink" else ()
        tel = obs_run.RunTelemetry(str(d), run_id="fixed", trace=False,
                                   extra_sinks=extra)
        for phase, step, metrics in rows:
            tel.log(phase, step, metrics)
        tel.close()
        streams[variant] = (d / "metrics.jsonl").read_bytes()
    assert streams["plain"] == streams["with_sink"]
    assert len(streams["plain"].splitlines()) == len(rows)


# ---------------------------------------------------------------------------
# SLO math
# ---------------------------------------------------------------------------


def test_slo_spec_validation_loud():
    with pytest.raises(ValueError):
        _spec(op="<")
    with pytest.raises(ValueError):
        _spec(severity="page")
    with pytest.raises(ValueError):
        _spec(burn_threshold=0.0)
    with pytest.raises(ValueError):
        _spec(burn_threshold=1.5)
    with pytest.raises(ValueError):
        _spec(window_s=0)
    with pytest.raises(ValueError):
        _spec(min_samples=0)
    with pytest.raises(ValueError):
        _spec(clear_threshold=0.9, burn_threshold=0.5)  # clears above fire
    assert _spec().resolved_clear_threshold() == 0.25


def test_slo_burn_rate_math():
    reg = MetricRegistry()
    spec = _spec(window_s=10.0, burn_threshold=0.5, min_samples=2)
    ev = SLOEvaluator([spec], reg)
    # 4 bad of 10 -> 0.4 < 0.5: ok
    for i in range(10):
        reg.set("m", 20.0 if i < 4 else 5.0, t=100.0 + i)
    st = ev.evaluate(now=110.0)[0]
    assert not st.burning and st.bad_fraction == pytest.approx(0.4)
    assert st.samples == 10
    # one more bad sample -> 5/11 ~ 0.45: still ok; then window slides
    # past the good prefix and the fraction crosses the threshold
    reg.set("m", 30.0, t=110.0)
    assert not ev.evaluate(now=110.0)[0].burning
    st = ev.evaluate(now=114.5)[0]  # window [104.5, 114.5]: bad 1 of 6...
    assert st.samples == 6
    # worst violator is reported for the alert message
    assert st.worst == 30.0


def test_slo_min_samples_and_ops():
    reg = MetricRegistry()
    lo = _spec(name="lo", op=">=", target=100.0, min_samples=3)
    ev = SLOEvaluator([lo], reg)
    reg.set("m", 1.0, t=10.0)
    st = ev.evaluate(now=11.0)[0]
    assert not st.burning and st.samples == 1  # below min_samples: ok
    reg.set("m", 2.0, t=10.5)
    reg.set("m", 3.0, t=10.6)
    st = ev.evaluate(now=11.0)[0]
    assert st.burning and st.bad_fraction == 1.0
    assert st.worst == 1.0  # op=">=": the SMALLEST violator is worst


def test_slo_hysteresis_no_flap():
    """bad_fraction dancing between clear (0.25) and burn (0.5) must
    not flap: it fires crossing 0.5, then stays firing until the
    fraction drops BELOW 0.25."""
    reg = MetricRegistry()
    spec = _spec(window_s=4.0, burn_threshold=0.5, clear_threshold=0.25,
                 min_samples=1)
    ev = SLOEvaluator([spec], reg)

    def window(t0, n_bad, n_total):
        for i in range(n_total):
            reg.set("m", 99.0 if i < n_bad else 1.0,
                    t=t0 + i / n_total)

    states = []
    for k, (bad, total) in enumerate(
            [(3, 6), (2, 6), (1, 6), (2, 6), (3, 6)]):
        t0 = 100.0 + 10.0 * k  # windows far apart: each eval sees one
        window(t0, bad, total)
        states.append(ev.evaluate(now=t0 + 1.0)[0].burning)
    # 0.5 fires; 0.33 sits INSIDE the hysteresis band (above clear,
    # below burn) so the alert neither clears nor re-fires; 0.17
    # clears; 0.33 now stays CLEAR (below burn); 0.5 fires again.
    assert states == [True, True, False, False, True]


# ---------------------------------------------------------------------------
# alert engine + contract
# ---------------------------------------------------------------------------


def _status(spec, burning, frac=1.0, samples=4):
    from npairloss_tpu.obs.live.slo import SLOStatus

    return SLOStatus(spec, burning, frac, samples, worst=99.0)


def test_slo_scrape_never_advances_hysteresis():
    """A /healthz poll (evaluate commit=False / status_dict) must not
    open or close hysteresis state a tick-driven evaluation alone
    would not have (review-round regression pin)."""
    reg = MetricRegistry()
    spec = _spec(window_s=10.0, burn_threshold=0.5, clear_threshold=0.25)
    ev = SLOEvaluator([spec], reg)
    for i in range(2):
        reg.set("m", 99.0, t=100.0 + i)  # 100% bad: would fire
    # scrapes see it burning but never commit
    assert ev.status_dict(now=102.0)["s"]["burning"] is True
    assert ev._burning["s"] is False
    # now good samples dilute to 0.4 — inside the band: a committed
    # tick from the NON-burning state must stay ok (the scrape above
    # must not have latched burning=True, which would hold at 0.4)
    for i in range(3):
        reg.set("m", 1.0, t=103.0 + i)
    assert not ev.evaluate(now=106.0)[0].burning


def test_slo_burning_holds_through_silence():
    """Silence is not recovery: a burning SLO stays burning when the
    window empties (a wedged server emitting nothing must not stand
    the pager down); resolution needs good samples."""
    reg = MetricRegistry()
    spec = _spec(window_s=5.0, min_samples=1, severity="critical")
    ev = SLOEvaluator([spec], reg)
    reg.set("m", 99.0, t=100.0)
    assert ev.evaluate(now=101.0)[0].burning
    st = ev.evaluate(now=200.0)[0]  # window long empty
    assert st.burning and st.samples == 0
    reg.set("m", 1.0, t=300.0)  # recovery evidence
    assert not ev.evaluate(now=301.0)[0].burning
    # and an SLO that never burned stays ok through silence
    st = ev.evaluate(now=400.0)[0]
    assert not st.burning


def test_alert_engine_resumes_appended_log(tmp_path):
    """A restarted process appending to an existing alerts.jsonl (the
    preempt-and-resume flow) must continue alert ids past the old
    segment and ADOPT its open alert instead of double-firing — the
    concatenated log stays validator-clean (review-round pin)."""
    spec = _spec(name="p99", severity="critical")
    path = str(tmp_path / "alerts.jsonl")
    first = AlertEngine(path)
    first.update([_status(spec, True)], now=10.0)  # left FIRING
    first.close()

    second = AlertEngine(path)  # process restart
    # the SLO recovered across the restart: resolve under the OLD id
    ev = second.update([_status(spec, False)], now=20.0)
    assert ev[0]["state"] == "resolved" and ev[0]["alert_id"] == "p99-1"
    assert ev[0]["fired_at"] == 10.0
    # a NEW incident gets a seq past everything the log ever used
    ev = second.update([_status(spec, True)], now=30.0)
    assert ev[0]["alert_id"] == "p99-2"
    second.close()
    records = load_alert_log(path)
    assert validate_alert_log(records) is None
    assert [(r["alert_id"], r["state"]) for r in records] == [
        ("p99-1", "firing"), ("p99-1", "resolved"), ("p99-2", "firing")]

    # still-burning across the restart: adopted silently, ONE firing
    third = AlertEngine(path)
    assert third.update([_status(spec, True)], now=40.0) == []
    assert third.active()["p99"]["alert_id"] == "p99-2"
    third.close()
    assert validate_alert_log(load_alert_log(path)) is None


def test_alert_lifecycle_dedup_and_debounce():
    spec = _spec(severity="critical")
    eng = AlertEngine(min_ticks=1)
    assert eng.update([_status(spec, True)], now=10.0)[0]["state"] == "firing"
    # still burning: dedup, no second event
    assert eng.update([_status(spec, True)], now=11.0) == []
    assert list(eng.active()) == ["s"]
    ev = eng.update([_status(spec, False)], now=12.0)[0]
    assert ev["state"] == "resolved" and ev["duration_s"] == 2.0
    assert eng.active() == {}
    # a later burn is a NEW alert id
    assert eng.update([_status(spec, True)], now=13.0)[0]["alert_id"] != \
        eng.history[0]["alert_id"]

    # debounce: one burning tick among quiet ones never fires
    eng2 = AlertEngine(min_ticks=2)
    assert eng2.update([_status(spec, True)], now=1.0) == []
    assert eng2.update([_status(spec, False)], now=2.0) == []
    assert eng2.update([_status(spec, True)], now=3.0) == []
    assert eng2.update([_status(spec, True)], now=4.0) != []


def test_alert_log_roundtrip_and_validator(tmp_path):
    spec = _spec(severity="warning")
    path = str(tmp_path / "alerts.jsonl")
    eng = AlertEngine(path)
    eng.update([_status(spec, True)], now=10.0)
    eng.update([_status(spec, False)], now=20.0)
    eng.close()
    records = load_alert_log(path)
    assert validate_alert_log(records) is None
    assert [r["state"] for r in records] == ["firing", "resolved"]
    assert all(r["schema"] == ALERTS_SCHEMA for r in records)
    assert unresolved_alerts(records) == []
    # torn tail line (killed writer) is tolerated by the loader
    with open(path, "a") as f:
        f.write('{"schema": "npairloss-aler')
    assert validate_alert_log(load_alert_log(path)) is None


def test_alert_validator_teeth():
    good = {
        "schema": ALERTS_SCHEMA, "alert_id": "a-1", "slo": "a",
        "metric": "m", "severity": "critical", "state": "firing",
        "ts": 1.0, "fired_at": 1.0, "bad_fraction": 1.0, "samples": 3,
        "target": 5.0, "op": "<=", "message": "x",
    }
    assert validate_alert_log([good]) is None
    assert "schema" in validate_alert_log([{**good, "schema": "v0"}])
    missing = dict(good)
    del missing["message"]
    assert "message" in validate_alert_log([missing])
    assert "state" in validate_alert_log([{**good, "state": "open"}])
    assert "severity" in validate_alert_log(
        [{**good, "severity": "fatal"}])
    # resolve without its firing
    assert "lifecycle" in validate_alert_log(
        [{**good, "state": "resolved", "resolved_at": 2.0}])
    # duplicate firing for one alert id
    assert "duplicate" in validate_alert_log([good, dict(good)])
    # second active alert for the same SLO violates dedup
    assert "dedup" in validate_alert_log(
        [good, {**good, "alert_id": "a-2"}])
    # resolved before fired
    resolved = {**good, "state": "resolved", "resolved_at": 0.5}
    assert "precedes" in validate_alert_log([good, resolved])
    # a SECOND resolve for one incident violates the lifecycle
    ok_resolve = {**good, "state": "resolved", "resolved_at": 2.0}
    assert validate_alert_log([good, ok_resolve]) is None
    assert "lifecycle" in validate_alert_log(
        [good, ok_resolve, dict(ok_resolve)])
    # unresolved report
    assert unresolved_alerts([good]) == [("a-1", "a", "critical")]


def test_bench_check_alerts_gate(tmp_path):
    """The jax-free gate: accepts a resolved log, refuses an unresolved
    CRITICAL and a schema violation (exit != 0)."""
    gate = os.path.join(REPO, "scripts", "bench_check.py")
    fire = {
        "schema": ALERTS_SCHEMA, "alert_id": "p99-1", "slo": "p99",
        "metric": "serve_p99_ms", "severity": "critical",
        "state": "firing", "ts": 1.0, "fired_at": 1.0,
        "bad_fraction": 1.0, "samples": 3, "target": 100.0, "op": "<=",
        "message": "x",
    }
    resolve = {**fire, "state": "resolved", "ts": 2.0,
               "resolved_at": 2.0, "duration_s": 1.0}

    def run(records):
        p = tmp_path / "log.jsonl"
        p.write_text("".join(json.dumps(r) + "\n" for r in records))
        return subprocess.run(
            [sys.executable, gate, "--alerts", str(p)],
            capture_output=True, text=True)

    ok = run([fire, resolve])
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = run([fire])  # unresolved critical
    assert bad.returncode == 1 and "still firing" in bad.stdout
    # unresolved WARNING is noted, not gated
    warn = run([{**fire, "alert_id": "w-1", "slo": "w",
                 "severity": "warning"}])
    assert warn.returncode == 0, warn.stdout + warn.stderr
    schema = run([{**fire, "schema": "nope"},
                  {**resolve, "schema": "nope"}])
    assert schema.returncode == 1 and "schema-invalid" in schema.stdout


# ---------------------------------------------------------------------------
# one evaluator, two feeds
# ---------------------------------------------------------------------------


def _serve_stream():
    """Synthetic serve window rows: fast, then an incident, then
    recovery — wall_times drive the replay clock."""
    rows = []
    t = 1000.0
    for p99 in [10, 12, 11, 500, 600, 550, 9, 8, 10, 11]:
        rows.append({"run_id": "r", "step": len(rows), "phase": "serve",
                     "wall_time": t, "p99_ms": float(p99), "qps": 50.0})
        t += 5.0
    return rows


def test_watch_vs_in_process_agreement():
    """The same stream through the offline replay and through a
    hand-driven in-process observatory must produce the SAME alert
    sequence — one engine, two feeds."""
    spec = _spec(name="p99", metric="serve_p99_ms", target=100.0,
                 window_s=12.0, burn_threshold=0.5, min_samples=1,
                 severity="critical")
    rows = _serve_stream()
    _, replay_events = replay_records(rows, [spec])

    inproc = LiveObservatory([spec])
    inproc_events = []
    for rec in rows:
        inproc.sink.log(rec)
        inproc_events.extend(inproc.tick(now=rec["wall_time"]))

    key = [(e["alert_id"], e["state"], e["ts"]) for e in replay_events]
    assert key == [(e["alert_id"], e["state"], e["ts"])
                   for e in inproc_events]
    assert [s for _, s, _ in key] == ["firing", "resolved"]


def test_watch_run_dir_offline(tmp_path):
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    rows = _serve_stream()
    # split across the legacy stream and a rank stream, with a torn
    # tail: watch must merge by wall_time and never die on the tear
    with open(run_dir / "metrics.jsonl", "w") as f:
        for r in rows[::2]:
            f.write(json.dumps(r) + "\n")
    with open(run_dir / "telemetry.r1.jsonl", "w") as f:
        for r in rows[1::2]:
            f.write(json.dumps(r) + "\n")
        f.write('{"torn')  # no newline: still being written
    spec = _spec(name="p99", metric="serve_p99_ms", target=100.0,
                 window_s=12.0, burn_threshold=0.5, min_samples=1)
    out = str(tmp_path / "alerts.watch.jsonl")
    summary = watch_run_dir(str(run_dir), [spec], out_path=out)
    assert summary["rows"] == len(rows)
    assert summary["events"] == 2
    assert summary["alerts_active"] == 0
    records = load_alert_log(out)
    assert validate_alert_log(records) is None
    assert [r["state"] for r in records] == ["firing", "resolved"]
    # the summary's SLO block is evaluated at the STREAM's last wall
    # time, not real now — a finished run must not read as an empty
    # (hence falsely-ok) window next to its own alert history
    assert summary["slo"]["p99"]["samples"] > 0
    with pytest.raises(FileNotFoundError):
        watch_run_dir(str(tmp_path / "empty"), [spec])


# ---------------------------------------------------------------------------
# config + watchdogs
# ---------------------------------------------------------------------------


def test_load_slo_config(tmp_path):
    cfg = tmp_path / "slo.json"
    cfg.write_text(json.dumps({
        "watchdogs": ["serve"],
        "slos": [
            {"name": "serve_p99", "metric": "serve_p99_ms",
             "op": "<=", "target": 42.0, "window_s": 5.0},
            {"name": "mine", "metric": "x", "op": ">=", "target": 1.0},
        ],
    }))
    specs = {s.name: s for s in load_slo_config(str(cfg))}
    # preset pulled in, explicit entry OVERRIDES the preset by name
    assert specs["serve_p99"].target == 42.0
    assert "serve_queue_saturation" in specs
    assert specs["mine"].op == ">="
    for bad in (
        {"slos": [{"name": "x"}]},                      # missing keys
        {"slos": [{"name": "x", "metric": "m", "op": "<=",
                   "target": 1.0, "typo_key": 2}]},     # unknown key
        {"nope": []},                                   # unknown top level
        {},                                             # no SLOs at all
        {"watchdogs": ["serve"], "unknown": 1},
    ):
        cfg.write_text(json.dumps(bad))
        with pytest.raises(ValueError):
            load_slo_config(str(cfg))


def test_default_watchdogs():
    serve = {s.name for s in default_watchdogs("serve", max_queue=64)}
    assert {"serve_p99", "serve_queue_saturation",
            "serve_post_warmup_compile", "index_staleness",
            "model_staleness", "serve_recall_floor",
            "serve_score_gap"} == serve
    train = {s.name for s in default_watchdogs("train")}
    assert "train_nonfinite_streak" in train
    assert "mining_margin_floor" in train
    assert "train_throughput_floor" not in train  # only with a real bar
    train_bar = {s.name
                 for s in default_watchdogs("train", bench_floor=100.0)}
    assert "train_throughput_floor" in train_bar
    with pytest.raises(ValueError):
        default_watchdogs("pod")
    # severity twin pin: alerts.py spells slo.SEVERITIES out (jax-free
    # file-path-load contract) — drift is a test failure
    from npairloss_tpu.obs.live.alerts import ALERT_SEVERITIES
    from npairloss_tpu.obs.live.slo import SEVERITIES

    assert ALERT_SEVERITIES == SEVERITIES


# ---------------------------------------------------------------------------
# exposition + exporter
# ---------------------------------------------------------------------------


def test_prometheus_exposition_format():
    reg = MetricRegistry()
    reg.inc("serve_rows", 7)
    reg.set("serve_p99_ms", 12.5, t=1.0)
    reg.observe("serve_latency_ms", 3.0, t=1.0)
    reg.gauge("never_set")
    text = prometheus_text(reg)
    lines = text.splitlines()
    assert "# TYPE npairloss_serve_rows_total counter" in lines
    assert "npairloss_serve_rows_total 7" in lines
    assert "# TYPE npairloss_serve_p99_ms gauge" in lines
    assert "npairloss_serve_p99_ms 12.5" in lines
    assert "# TYPE npairloss_serve_latency_ms histogram" in lines
    assert 'npairloss_serve_latency_ms_bucket{le="2.5"} 0' in lines
    assert 'npairloss_serve_latency_ms_bucket{le="5"} 1' in lines
    assert 'npairloss_serve_latency_ms_bucket{le="+Inf"} 1' in lines
    assert "npairloss_serve_latency_ms_sum 3" in lines
    assert "npairloss_serve_latency_ms_count 1" in lines
    # histogram buckets are cumulative and ordered
    buckets = [ln for ln in lines if "_bucket" in ln]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert counts == sorted(counts)
    assert len(buckets) == len(DEFAULT_BOUNDS) + 1
    # an unset gauge exposes nothing
    assert "never_set" not in text


def test_http_exporter_and_health():
    reg = MetricRegistry()
    reg.set("g", 1.25, t=1.0)
    httpd = start_http_exporter(reg, 0, health_fn=lambda: {"ok": True})
    try:
        port = httpd.server_address[1]
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        assert "npairloss_g 1.25" in text
        health = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        assert health == {"ok": True}
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_live_observatory_probe_and_final_tick(tmp_path):
    spec = _spec(name="age", metric="age_s", target=5.0,
                 window_s=60.0, severity="critical")
    obs = LiveObservatory([spec], out_dir=str(tmp_path))
    age = [0.0]
    obs.add_probe(lambda: obs.registry.set("age_s", age[0]))
    assert obs.tick(now=1.0) == []
    age[0] = 99.0
    # stop() runs one final tick: the transition that happened right
    # before shutdown still lands in alerts.jsonl
    obs.stop()
    records = load_alert_log(str(tmp_path / "alerts.jsonl"))
    assert validate_alert_log(records) is None
    assert [r["state"] for r in records] == ["firing"]
    assert obs.health()["alerts_active"] == 1


# ---------------------------------------------------------------------------
# freshness + serve integration (tiny jax work)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_serve():
    from npairloss_tpu.serve import (
        BatcherConfig,
        EngineConfig,
        Freshness,
        GalleryIndex,
        QueryEngine,
        RetrievalServer,
        ServerConfig,
    )

    rng = np.random.default_rng(0)
    emb = rng.standard_normal((32, 8)).astype(np.float32)
    index = GalleryIndex.build(emb, (np.arange(32) % 4).astype(np.int32))
    engine = QueryEngine(index, EngineConfig(top_k=3, buckets=(1, 4)))
    engine.warmup()
    freshness = Freshness.collect(index=index, index_path="/tmp/fake.gidx")
    server = RetrievalServer(
        engine, BatcherConfig(max_batch=4, max_delay_ms=1.0),
        ServerConfig(metrics_window=0), freshness=freshness,
    )
    server.batcher.start()
    yield emb, server
    server.batcher.close(drain=True)


def test_index_created_roundtrip(tmp_path):
    from npairloss_tpu.serve import GalleryIndex

    rng = np.random.default_rng(1)
    idx = GalleryIndex.build(
        rng.standard_normal((8, 4)).astype(np.float32),
        np.arange(8, dtype=np.int32))
    assert idx.created is not None and idx.created <= time.time()
    path = str(tmp_path / "g.gidx")
    idx.save(path)
    loaded = GalleryIndex.load(path)
    # load dates the gallery by its COMMIT manifest
    assert loaded.created is not None
    assert abs(loaded.created - time.time()) < 60.0
    before = loaded.created
    time.sleep(0.01)
    loaded.add(rng.standard_normal((2, 4)).astype(np.float32),
               np.array([8, 9], np.int32))
    assert loaded.created > before  # add() is a freshness event


def test_freshness_shapes_and_answer_stamp(tiny_serve):
    """The satellite's JSON-shape regression test: /healthz, the drain
    summary, and every answer report the freshness ages WITHOUT
    --live-obs."""
    emb, server = tiny_serve
    answer = server.handle({"id": 7, "embedding": emb[7].tolist()})
    assert answer["neighbors"][0]["row"] == 7
    assert "index_age_s" in answer and answer["index_age_s"] >= 0.0
    assert "model_age_s" not in answer  # embedding-only serving
    s = server.summary()
    assert s["index_path"] == "/tmp/fake.gidx"
    assert "index_age_s" in s and "snapshot_step" not in s
    h = server.healthz()
    assert h["ok"] is True and "index_age_s" in h
    assert "slo" not in h  # no live observatory attached
    # error answers carry no stale stamp confusion: still answered
    err = server.handle({"id": 8, "embedding": [1.0]})
    assert "error" in err


def test_snapshot_info_manifestless(tmp_path):
    from npairloss_tpu.train import snapshot_info

    d = tmp_path / "old.ckpt"
    d.mkdir()
    info = snapshot_info(str(d))
    assert info["step"] is None and info["created"] is None
    assert info["path"] == str(d)


def test_serve_latency_failpoint(tiny_serve):
    from npairloss_tpu.resilience import failpoints

    emb, server = tiny_serve
    before = time.perf_counter()
    with failpoints.armed("serve.latency", times=1):
        a = server.handle({"id": 1, "embedding": emb[1].tolist()})
    assert a["neighbors"][0]["row"] == 1
    assert time.perf_counter() - before >= failpoints.SERVE_LATENCY_FAULT_S
    # disarmed: fast again
    before = time.perf_counter()
    server.handle({"id": 2, "embedding": emb[2].tolist()})
    assert time.perf_counter() - before < failpoints.SERVE_LATENCY_FAULT_S


def test_serve_queue_stall_failpoint(tiny_serve):
    from npairloss_tpu.resilience import failpoints

    emb, server = tiny_serve
    with failpoints.armed("serve.queue_stall", times=1):
        t0 = time.perf_counter()
        answers = server.handle_many(
            [{"id": i, "embedding": emb[i].tolist()} for i in range(3)])
    assert all("neighbors" in a for a in answers)
    assert time.perf_counter() - t0 >= failpoints.SERVE_QUEUE_STALL_S


def test_window_rows_are_per_window_and_clean(tiny_serve):
    """Window rows describe THEIR window (a live p99 watchdog must see
    recovery), and a clean engine's rows carry NO
    compiles_after_warmup key (the absent-when-zero stream-parity
    contract).  A FRESH server around the shared warmed engine: the
    window alignment under test must not inherit another test's
    half-filled window."""
    from npairloss_tpu.obs.sinks import RingBufferSink
    from npairloss_tpu.serve import (
        BatcherConfig,
        RetrievalServer,
        ServerConfig,
    )

    emb, shared = tiny_serve
    ring = RingBufferSink(16)

    class Tel:
        metrics_enabled = True
        tracer = None

        def log(self, phase, step, metrics, **extra):
            rec = {**metrics, "phase": phase, "step": step}
            ring.log(rec)
            return rec

        def span(self, name, **args):
            import contextlib

            return contextlib.nullcontext()

    server = RetrievalServer(
        shared.engine, BatcherConfig(max_batch=4, max_delay_ms=1.0),
        ServerConfig(metrics_window=2), telemetry=Tel(),
    )
    server.batcher.start()
    try:
        # window 1: two slow answers; window 2: two fast ones
        from npairloss_tpu.resilience import failpoints

        with failpoints.armed("serve.latency", times=2):
            for i in (1, 2):
                server.handle({"id": i, "embedding": emb[i].tolist()})
        for i in (3, 4):
            server.handle({"id": i, "embedding": emb[i].tolist()})
        rows = [r for r in ring.records() if r.get("phase") == "serve"]
        assert len(rows) == 2
        slow, fast = rows
        assert slow["p99_ms"] >= 250.0
        # the fast window's p99 must NOT remember the slow window
        assert fast["p99_ms"] < 250.0
        assert all("compiles_after_warmup" not in r for r in rows)
    finally:
        server.batcher.close(drain=True)
