"""Caffemodel weight migration: wire format, GoogLeNet mapping, CLI.

The reference's users hold trained .caffemodel files (binary-protobuf
NetParameter over bvlc_googlenet layer names, usage/def.prototxt:85-111);
config.caffemodel + models.caffe_import are the migration path in and
out of this framework.  No real caffemodel is fetchable here, so the
tests pin BOTH directions against each other (export -> bytes ->
import == identity) plus hand-built wire encodings for the legacy
V1/old-shape forms.
"""

import json
import struct
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from npairloss_tpu.config.caffemodel import (
    parse_caffemodel,
    write_caffemodel,
)
from npairloss_tpu.models import get_model
from npairloss_tpu.models.caffe_import import (
    caffe_layer_map,
    caffemodel_layers_from_googlenet_params,
    googlenet_params_from_caffemodel,
)


def test_wire_roundtrip():
    rng = np.random.default_rng(0)
    layers = {
        "conv1/7x7_s2": [
            rng.standard_normal((64, 3, 7, 7)).astype(np.float32),
            rng.standard_normal((64,)).astype(np.float32),
        ],
        "odd/λ-name": [rng.standard_normal((2, 3)).astype(np.float32)],
    }
    back = parse_caffemodel(write_caffemodel(layers))
    assert sorted(back) == sorted(layers)
    for name in layers:
        assert len(back[name]) == len(layers[name])
        for a, b in zip(layers[name], back[name]):
            np.testing.assert_array_equal(a, b)


def _varint(v):
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _len_field(num, payload):
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def test_parses_legacy_v1_layers_and_old_shape():
    """Old caffemodels use `layers` (field 2, V1LayerParameter: name=4,
    blobs=6) and 4-D num/channels/height/width blob shapes."""
    data = np.arange(24, dtype=np.float32)
    blob = (
        _varint((1 << 3) | 0) + _varint(2)    # num = 2
        + _varint((2 << 3) | 0) + _varint(3)  # channels = 3
        + _varint((3 << 3) | 0) + _varint(2)  # height = 2
        + _varint((4 << 3) | 0) + _varint(2)  # width = 2
        + _len_field(5, data.tobytes())       # packed float data
    )
    v1_layer = _len_field(4, b"legacy") + _len_field(6, blob)
    net = _len_field(1, b"net") + _len_field(2, v1_layer)
    out = parse_caffemodel(net)
    assert list(out) == ["legacy"]
    assert out["legacy"][0].shape == (2, 3, 2, 2)
    np.testing.assert_array_equal(
        out["legacy"][0].reshape(-1), data
    )


def test_skips_unknown_fields_and_bloblless_layers():
    layer = (
        _len_field(1, b"data")                        # name, no blobs
        + _len_field(2, b"MultibatchData")            # type
        + _varint((33 << 3) | 0) + _varint(7)         # unknown varint
        + _len_field(44, b"\x01\x02\x03")             # unknown LEN
    )
    net = _len_field(100, layer)
    assert parse_caffemodel(net) == {}


@pytest.fixture(scope="module")
def plain_params():
    m = get_model("googlenet", dtype=jnp.float32)
    x = jnp.zeros((1, 64, 64, 3), jnp.float32)
    return m.init(jax.random.PRNGKey(0), x, train=False)["params"]


def test_googlenet_mapping_covers_trunk(plain_params):  # slow-ok: full-trunk caffemodel mapping coverage — the import contract
    mapping = caffe_layer_map()
    # 3 stem convs + 9 stages x 6 branch convs
    assert len(mapping) == 3 + 9 * 6
    for path in mapping:
        node = plain_params
        for p in path.split("/"):
            assert p in node, (path, sorted(node))
            node = node[p]
        assert "Conv_0" in node


def test_googlenet_caffemodel_roundtrip(plain_params):
    """export -> caffemodel bytes -> import reproduces every conv
    kernel/bias exactly (pins the OIHW<->HWIO transposes against each
    other — a single wrong axis breaks equality)."""
    layers = caffemodel_layers_from_googlenet_params(plain_params)
    blob = write_caffemodel(layers)
    back_blobs = parse_caffemodel(blob)
    template = jax.tree_util.tree_map(
        lambda a: np.zeros_like(np.asarray(a)), plain_params
    )
    back = googlenet_params_from_caffemodel(back_blobs, template)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        plain_params, back,
    )


def test_import_rejects_missing_and_mismatched(plain_params):
    layers = caffemodel_layers_from_googlenet_params(plain_params)
    template = jax.tree_util.tree_map(
        lambda a: np.zeros_like(np.asarray(a)), plain_params
    )
    missing = dict(parse_caffemodel(write_caffemodel(layers)))
    missing.pop("inception_4c/5x5")
    with pytest.raises(KeyError, match="inception_4c/5x5"):
        googlenet_params_from_caffemodel(missing, template)

    bad = dict(parse_caffemodel(write_caffemodel(layers)))
    bad["conv2/3x3"] = [bad["conv2/3x3"][0][:, :, :1, :1],
                        bad["conv2/3x3"][1]]
    with pytest.raises(ValueError, match="conv2/3x3"):
        googlenet_params_from_caffemodel(bad, template)


def test_solver_load_params_resets_opt_and_casts():
    from npairloss_tpu import NPairLossConfig
    from npairloss_tpu.train import Solver, SolverConfig

    solver = Solver(
        get_model("mlp", hidden=(8,), embedding_dim=4),
        NPairLossConfig(),
        SolverConfig(base_lr=0.1, lr_policy="fixed", display=0, snapshot=0),
        input_shape=(6,),
    )
    solver.init()
    rng = np.random.default_rng(3)
    new = jax.tree_util.tree_map(
        lambda a: rng.standard_normal(a.shape).astype(np.float64),
        solver.state["params"],
    )
    solver.load_params(new)
    got = solver.state["params"]
    jax.tree_util.tree_map(
        lambda g, n: np.testing.assert_allclose(
            np.asarray(g), n.astype(np.float32), rtol=1e-6
        ),
        got, new,
    )
    # structure mismatch is a loud error, not a partial load
    with pytest.raises(Exception):
        solver.load_params({"wrong": np.zeros(3)})


def test_cli_import_export_roundtrip(tmp_path, plain_params):
    """The migration workflow end-to-end through the CLI: caffemodel ->
    import-caffemodel -> msgpack -> export-caffemodel -> identical
    blobs."""
    src = tmp_path / "ref.caffemodel"
    src.write_bytes(write_caffemodel(
        caffemodel_layers_from_googlenet_params(plain_params)
    ))

    def cli(*args):
        proc = subprocess.run(
            [sys.executable, "-m", "npairloss_tpu", "--platform", "cpu",
             *args],
            capture_output=True, text=True, timeout=600,
        )
        assert proc.returncode == 0, proc.stderr[-3000:]
        return json.loads(proc.stdout.strip().splitlines()[-1])

    imported = tmp_path / "pre.msgpack"
    rec = cli("import-caffemodel", "--weights", str(src),
              "--out", str(imported))
    assert rec["mapped_convs"] == 57 and imported.exists()

    exported = tmp_path / "back.caffemodel"
    rec2 = cli("export-caffemodel", "--weights", str(imported),
               "--out", str(exported))
    assert rec2["layers"] == 57

    a = parse_caffemodel(src.read_bytes())
    b = parse_caffemodel(exported.read_bytes())
    assert sorted(a) == sorted(b)
    for name in a:
        for x, y in zip(a[name], b[name]):
            np.testing.assert_array_equal(x, y)


@pytest.fixture(scope="module")
def resnet_variables():
    # resnet18 shares the block/naming code with resnet50 but inits in
    # seconds on CPU; the mapping is parameterized by stage_sizes.
    m = get_model("resnet18", dtype=jnp.float32)
    x = jnp.zeros((1, 64, 64, 3), jnp.float32)
    return m.init(jax.random.PRNGKey(2), x, train=False)


def test_resnet_caffemodel_roundtrip(resnet_variables):
    """Caffe ResNet encodes BN as BatchNorm (mean,var,scale_factor) +
    Scale (gamma,beta) layer pairs; export -> bytes -> import must
    reproduce params AND batch_stats exactly.  resnet18's stage_sizes
    exercise the same mapping code as resnet50."""
    from npairloss_tpu.models.caffe_import import (
        caffemodel_layers_from_resnet50_params,
        resnet50_params_from_caffemodel,
    )

    params = resnet_variables["params"]
    stats = resnet_variables["batch_stats"]
    import npairloss_tpu.models.caffe_import as ci

    orig = ci._resnet_block_names

    def block_names(stage_sizes=(2, 2, 2, 2)):
        return orig(stage_sizes)

    ci._resnet_block_names = block_names
    try:
        layers = caffemodel_layers_from_resnet50_params(params, stats)
        blobs = parse_caffemodel(write_caffemodel(layers))
        zeros = lambda t: jax.tree_util.tree_map(
            lambda a: np.zeros_like(np.asarray(a)), t)
        back_p, back_s = resnet50_params_from_caffemodel(
            blobs, zeros(params), zeros(stats))
    finally:
        ci._resnet_block_names = orig
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), params, back_p)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), stats, back_s)


def test_resnet_import_applies_caffe_bn_scale_factor(resnet_variables):
    """Caffe BatchNorm blobs are running SUMS times a scale_factor —
    the import must divide it out."""
    from npairloss_tpu.models.caffe_import import _caffe_bn

    gamma = np.arange(4, dtype=np.float32) + 1
    beta = np.arange(4, dtype=np.float32)
    mean = np.asarray([1.0, 2.0, 3.0, 4.0], np.float32)
    var = np.asarray([0.5, 0.5, 0.5, 0.5], np.float32)
    factor = 5.0
    blobs = {
        "bn_x": [mean * factor, var * factor,
                 np.asarray([factor], np.float32)],
        "scale_x": [gamma, beta],
    }
    g, b, m, v = _caffe_bn(blobs, "bn_x", "scale_x", 4)
    np.testing.assert_allclose(m, mean)
    np.testing.assert_allclose(v, var)
    np.testing.assert_array_equal(g, gamma)
    np.testing.assert_array_equal(b, beta)


def test_cli_export_from_snapshot(tmp_path, plain_params):  # slow-ok: end-to-end snapshot->caffemodel export through the real CLI
    """train -> snapshot -> export-caffemodel --snapshot: the deploy
    path for a trunk trained HERE, no msgpack intermediary."""
    from npairloss_tpu import NPairLossConfig
    from npairloss_tpu.train import Solver, SolverConfig

    solver = Solver(
        get_model("googlenet", dtype=jnp.float32),
        NPairLossConfig(),
        SolverConfig(
            base_lr=0.0, lr_policy="fixed", display=0, snapshot=0,
            snapshot_prefix=str(tmp_path / "snap_"),
        ),
        input_shape=(64, 64, 3),
    )
    solver.init()
    solver.load_params(plain_params)
    snap = solver.save_snapshot(1)
    solver._ckpt().wait_until_finished()

    out = tmp_path / "deploy.caffemodel"
    ss_out = tmp_path / "deploy.solverstate"
    proc = subprocess.run(
        [sys.executable, "-m", "npairloss_tpu", "--platform", "cpu",
         "export-caffemodel", "--snapshot", snap, "--out", str(out),
         "--solverstate-out", str(ss_out)],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    blobs = parse_caffemodel(out.read_bytes())
    assert len(blobs) == 57
    np.testing.assert_array_equal(
        blobs["conv1/7x7_s2"][0].transpose(2, 3, 1, 0),
        np.asarray(plain_params["conv1"]["Conv_0"]["kernel"]),
    )
    # The paired optimizer snapshot rode along: momentum history (one
    # blob per learnable param) + the snapshot's iteration.
    from npairloss_tpu.config.caffemodel import parse_solverstate

    st = parse_solverstate(ss_out.read_bytes())
    # iter comes from the optimizer's own step counter (the solver's
    # single source of truth) — 0 here, since no training step ran;
    # save_snapshot(1) only names the file.
    assert st["iter"] == 0
    assert st["learned_net"] == "deploy.caffemodel"
    assert len(st["history"]) == sum(len(b) for b in blobs.values())


def test_cli_export_solverstate_rejects_variant_trunks(tmp_path):
    """--solverstate-out with a variant GoogLeNet trunk (googlenet_bn/
    s2d/fused/mxu) must fail in the upfront validation block, BEFORE
    the .caffemodel is written: the variant momentum trees don't map
    onto the plain-trunk layer order, and the old gate ('resnet' only)
    let them through to raise AFTER the weights file landed on disk."""
    from npairloss_tpu.cli import main

    out = tmp_path / "deploy.caffemodel"
    ss_out = tmp_path / "deploy.solverstate"
    rc = main([
        "export-caffemodel", "--model", "googlenet_bn",
        "--snapshot", str(tmp_path / "never_loaded"),
        "--out", str(out), "--solverstate-out", str(ss_out),
    ])
    assert rc == 2
    assert not out.exists() and not ss_out.exists()


def test_caffe_pad_stem_matches_explicit_pad3_conv():
    """caffe_pad=True must evaluate conv1 at Caffe's geometry: stride-2
    windows over symmetric pad 3 (usage/def.prototxt:100).  With stride
    2, SAME's (2,3) pad samples a DIFFERENT input phase — the two are
    not equal anywhere — so the option is pinned against a direct lax
    conv with explicit pad 3, and shape equality with SAME is asserted
    (same 2x downsampling)."""
    m_same = get_model("googlenet", dtype=jnp.float32)
    m_caffe = get_model("googlenet", dtype=jnp.float32, caffe_pad=True)
    rng = np.random.default_rng(21)
    x = jnp.asarray(rng.standard_normal((1, 64, 64, 3)).astype(np.float32))
    v = m_same.init(jax.random.PRNGKey(0), x, train=False)

    def stem_out(model):
        _, inter = model.apply(
            v, x, train=False, capture_intermediates=True,
            mutable=["intermediates"],
        )
        return np.asarray(inter["intermediates"]["conv1"]["__call__"][0])

    a, b = stem_out(m_same), stem_out(m_caffe)
    assert a.shape == b.shape  # both 32x32 on a 64 input

    k = v["params"]["conv1"]["Conv_0"]["kernel"]
    bias = v["params"]["conv1"]["Conv_0"]["bias"]
    want = jax.lax.conv_general_dilated(
        x, k, (2, 2), ((3, 3), (3, 3)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    ) + bias
    want = np.maximum(np.asarray(want), 0.0)
    np.testing.assert_allclose(b, want, rtol=1e-5, atol=1e-5)
    # and SAME genuinely differs (different sampling phase)
    assert not np.allclose(a, want, atol=1e-3)


# -- SolverState (optimizer-state migration) --------------------------------


def test_solverstate_wire_and_history_roundtrip(plain_params):
    """momentum tree -> history blobs (net order) -> .solverstate bytes
    -> parse -> momentum tree: exact, with iter/current_step/learned_net
    preserved (the `caffe train --snapshot` resume surface)."""
    from npairloss_tpu.config.caffemodel import (
        parse_solverstate,
        write_solverstate,
    )
    from npairloss_tpu.models.caffe_import import (
        googlenet_history_from_momentum,
        googlenet_momentum_from_history,
    )

    rng = np.random.default_rng(5)
    momentum = jax.tree_util.tree_map(
        lambda a: rng.standard_normal(a.shape).astype(np.float32),
        plain_params,
    )
    hist = googlenet_history_from_momentum(momentum)
    data = write_solverstate(
        1234, hist, current_step=7, learned_net="net.caffemodel"
    )
    st = parse_solverstate(data)
    assert st["iter"] == 1234
    assert st["current_step"] == 7
    assert st["learned_net"] == "net.caffemodel"
    assert len(st["history"]) == len(hist)
    back, skipped = googlenet_momentum_from_history(
        st["history"],
        jax.tree_util.tree_map(np.zeros_like, momentum),
        strict=True,
    )
    assert skipped == 0
    jax.tree_util.tree_map(
        np.testing.assert_array_equal, back, momentum
    )


def test_solverstate_history_mismatch_fails_loudly(plain_params):
    from npairloss_tpu.models.caffe_import import (
        googlenet_history_from_momentum,
        googlenet_momentum_from_history,
    )

    momentum = jax.tree_util.tree_map(np.zeros_like, plain_params)
    hist = googlenet_history_from_momentum(momentum)
    # Truncated history: the last expected blob is missing — error in
    # both modes.
    with pytest.raises(ValueError, match="history"):
        googlenet_momentum_from_history(hist[:-1], momentum)
    with pytest.raises(ValueError, match="history"):
        googlenet_momentum_from_history(hist[:-1], momentum, strict=True)
    # Trailing extra blob: strict refuses; default counts it as skipped.
    with pytest.raises(ValueError, match="history"):
        googlenet_momentum_from_history(hist + [hist[0]], momentum,
                                        strict=True)
    _, skipped = googlenet_momentum_from_history(
        hist + [hist[0]], momentum)
    assert skipped == 1


def test_solverstate_skips_aux_classifier_blobs(plain_params):
    """A genuine reference .solverstate interleaves aux-classifier
    momentum (loss1/*, loss2/* — learnable params of the FULL training
    net) with the trunk's; the shape-guided alignment must skip them and
    still recover the trunk momentum exactly."""
    from npairloss_tpu.models.caffe_import import (
        googlenet_history_from_momentum,
        googlenet_momentum_from_history,
    )

    rng = np.random.default_rng(3)
    momentum = jax.tree_util.tree_map(
        lambda a: rng.standard_normal(a.shape).astype(np.float32),
        plain_params,
    )
    hist = googlenet_history_from_momentum(momentum)
    # Splice aux-head-shaped blobs mid-sequence (after an arbitrary
    # trunk layer boundary) + a classifier pair at the end — shapes no
    # trunk blob position expects at those points.
    aux = [
        np.zeros((128, 512, 1, 1), np.float32),  # loss1/conv kernel
        np.zeros((128,), np.float32),            # loss1/conv bias
        np.zeros((1024, 2048), np.float32),      # loss1/fc (InnerProduct)
        np.zeros((1024,), np.float32),
    ]
    # Splice at a layer boundary (kernel+bias pairs -> even index) where
    # the next expected kernel shape differs from the aux kernel's, as
    # in the real net order (the aux heads attach between inception
    # stages whose neighbors have different channel counts).
    pos = next(
        i for i in range(20, len(hist), 2)
        if tuple(hist[i].shape) != tuple(aux[0].shape)
    )
    spliced = (hist[:pos] + aux + hist[pos:]
               + [np.zeros((1000, 1024), np.float32),   # classifier
                  np.zeros((1000,), np.float32)])
    back, skipped = googlenet_momentum_from_history(
        spliced, jax.tree_util.tree_map(np.zeros_like, momentum))
    assert skipped == len(aux) + 2
    jax.tree_util.tree_map(
        np.testing.assert_array_equal, back, momentum
    )


def test_solver_resumes_from_caffe_solverstate(tmp_path, plain_params):  # slow-ok: the solverstate resume path has no ci.sh smoke twin
    """Solver.load_caffe_solverstate restores momentum + iteration —
    display/test/snapshot cadence and the lr schedule continue from the
    Caffe run's step."""
    from npairloss_tpu import NPairLossConfig
    from npairloss_tpu.config.caffemodel import write_solverstate
    from npairloss_tpu.models.caffe_import import (
        googlenet_history_from_momentum,
    )
    from npairloss_tpu.train import Solver, SolverConfig

    rng = np.random.default_rng(11)
    momentum = jax.tree_util.tree_map(
        lambda a: rng.standard_normal(a.shape).astype(np.float32),
        plain_params,
    )
    path = tmp_path / "iter_500.solverstate"
    path.write_bytes(write_solverstate(
        500, googlenet_history_from_momentum(momentum)
    ))

    solver = Solver(
        get_model("googlenet", dtype=jnp.float32),
        NPairLossConfig(),
        SolverConfig(base_lr=0.001, lr_policy="fixed", display=0,
                     snapshot=0),
        input_shape=(64, 64, 3),
    )
    it = solver.load_caffe_solverstate(str(path))
    assert it == 500
    assert solver.iteration == 500
    jax.tree_util.tree_map(
        lambda got, want: np.testing.assert_allclose(
            np.asarray(got), want, rtol=1e-6),
        solver.state["opt"].momentum_buf,
        momentum,
    )
    with pytest.raises(NotImplementedError, match="GoogLeNet"):
        solver.load_caffe_solverstate(str(path), model_name="resnet50")


def test_solverstate_accepts_legacy_4d_bias_blobs(plain_params):
    """Old-Caffe forks store bias blobs with the legacy 4-D
    (1,1,1,N) shape (the weight path normalizes them with reshape(-1));
    the history alignment must accept that storage too."""
    from npairloss_tpu.models.caffe_import import (
        googlenet_history_from_momentum,
        googlenet_momentum_from_history,
    )

    rng = np.random.default_rng(7)
    momentum = jax.tree_util.tree_map(
        lambda a: rng.standard_normal(a.shape).astype(np.float32),
        plain_params,
    )
    hist = [
        b if b.ndim == 4 else b.reshape(1, 1, 1, -1)
        for b in googlenet_history_from_momentum(momentum)
    ]
    back, skipped = googlenet_momentum_from_history(
        hist, jax.tree_util.tree_map(np.zeros_like, momentum))
    assert skipped == 0
    jax.tree_util.tree_map(
        np.testing.assert_array_equal, back, momentum
    )
