"""Replay a short accuracy-baseline run (scripts/accuracy_baseline.py).

The committed ACCURACY.md / accuracy/curves.json artifact is generated
by the script; this test replays its flagship configuration at reduced
step count so CI pins the convergence behavior the artifact documents:
Recall@1 must rise from chance to ~1.0 on separable synthetic clusters.
"""

import importlib.util
import os

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _load_script():
    spec = importlib.util.spec_from_file_location(
        "accuracy_baseline",
        os.path.join(REPO, "scripts", "accuracy_baseline.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_flagship_short_replay_converges():
    from npairloss_tpu import REFERENCE_CONFIG

    mod = _load_script()
    r = mod.run_config(
        "flagship_replay", REFERENCE_CONFIG,
        model_name="mlp", model_kw=dict(hidden=(64,), embedding_dim=32),
        input_shape=(32,), num_ids=32, ids_per_batch=16, lr=0.5,
        steps=150,
    )
    assert r["final_recall_at_1"] >= 0.9, r
    # Training moved: the loss fell and retrieval did not regress.
    assert r["curve"][-1]["loss"] < r["curve"][0]["loss"], r["curve"]
    assert r["final_recall_at_1"] >= r["curve"][0]["retrieve_top1"] - 0.05


def test_overlap_band_mined_inside_unmined_below():
    """The overlapping-clusters band row (VERDICT r4 weak #7): flagship
    mining lands inside the expected R@1 band, while unmined (RAND=ALL
    selection — the 'mining silently broke' proxy) falls below its
    lower edge at the SAME data/geometry/steps.  This is the
    convergence-rate detector the separable rows can't provide: a
    regression that merely slows mining shows up here as a band miss,
    not as a still-perfect 1.0."""
    import numpy as np

    from npairloss_tpu import NPairLossConfig, REFERENCE_CONFIG

    mod = _load_script()
    geo = dict(
        model_name="mlp", model_kw=dict(hidden=(64,), embedding_dim=32),
        input_shape=(32,), num_ids=32, ids_per_batch=16, lr=0.5,
        steps=600, noise=1.4, record_every=10,
    )
    band = (0.63, 0.92)
    r = mod.run_band_config(
        "band_replay", REFERENCE_CONFIG, expected_band=band,
        seeds=(0, 1), **geo)
    assert band[0] <= r["final_recall_at_1"] <= band[1], r

    # Counterexample: no mining (default config selects ALL pairs).
    def tail(rr):
        return float(np.mean(
            [p["retrieve_top1"] for p in rr["curve"][-8:]]))

    unmined = [
        tail(mod.run_config(f"unmined_seed{s}", NPairLossConfig(),
                            seed=s, **geo))
        for s in (0, 1)
    ]
    assert sum(unmined) / len(unmined) < band[0], unmined


def test_blockwise_engine_short_replay_converges():
    """The Pallas blockwise engine trains the flagship config end-to-end
    (training-level parity, not just per-step numerics)."""
    from npairloss_tpu import REFERENCE_CONFIG

    mod = _load_script()
    r = mod.run_config(
        "blockwise_replay", REFERENCE_CONFIG,
        model_name="mlp", model_kw=dict(hidden=(64,), embedding_dim=32),
        input_shape=(32,), num_ids=16, ids_per_batch=8, lr=0.5,
        steps=100, use_blockwise=True,
    )
    assert r["final_recall_at_1"] >= 0.9, r


def test_vit_trunk_short_replay_converges():  # slow-ok: the only ViT-trunk convergence probe in tier-1
    """The ViT trunk (reduced ViT-B/16 proxy) learns through the
    flagship mining config — the transformer family's counterpart of
    the conv-trunk rows in ACCURACY.md."""
    import jax.numpy as jnp

    from npairloss_tpu import REFERENCE_CONFIG

    mod = _load_script()
    r = mod.run_config(
        "vit_replay", REFERENCE_CONFIG,
        model_name="vit_b16",
        model_kw=dict(patch=8, hidden=64, depth=2, num_heads=4,
                      mlp_dim=128, dtype=jnp.float32),
        input_shape=(32, 32, 3), num_ids=16, ids_per_batch=16, lr=0.05,
        steps=120, record_every=10,
    )
    assert r["final_recall_at_1"] >= 0.9, r
    assert r["curve"][-1]["loss"] < r["curve"][0]["loss"], r["curve"]
