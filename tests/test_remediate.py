"""Alert→actuation tests (resilience/remediate.py + its serve/train
wiring, docs/RESILIENCE.md §Remediation): policy matching and budgets
(cooldown / max-attempts / per-incident reset), dry-run, the
npairloss-remediation-v1 audit validator's teeth, the jax-free
bench_check --remediation gate, hot-swap under concurrent queries
(zero drops, zero post-swap compiles), re-warm resetting the compile
counters, the train.collapse / serve.compile_storm failpoints, the
solver's requested-rollback path, watch's audit reconciliation, and the
forced admission shed."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from npairloss_tpu.resilience import failpoints
from npairloss_tpu.resilience.guard import RollbackRequest
from npairloss_tpu.resilience.remediate import (
    EVENT_KEYS,
    REMEDIATION_SCHEMA,
    REMEDIATION_SEVERITIES,
    RemediationEngine,
    RemediationPolicy,
    abandoned_remediations,
    default_policies,
    load_policies,
    load_remediation_log,
    unresolved_remediations,
    validate_remediation_log,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_CHECK = os.path.join(REPO, "scripts", "bench_check.py")


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


def _alert(aid, severity="critical", fired_at=0.0):
    return {"alert_id": aid, "severity": severity, "fired_at": fired_at,
            "bad_fraction": 1.0}


def _engine(policies, actions, tmp_path=None, **kw):
    log_path = (str(tmp_path / "remediation.jsonl")
                if tmp_path is not None else None)
    return RemediationEngine(policies, actions, log_path=log_path,
                             clock=lambda: 0.0, **kw)


POL = RemediationPolicy(name="p", slo="s", action="a", cooldown_s=5.0,
                        max_attempts=2)


# -- policy table -------------------------------------------------------------


def test_policy_validation_louds():
    with pytest.raises(ValueError, match="cooldown_s"):
        RemediationPolicy(name="p", slo="s", action="a", cooldown_s=-1)
    with pytest.raises(ValueError, match="max_attempts"):
        RemediationPolicy(name="p", slo="s", action="a", max_attempts=0)
    for field in ("name", "slo", "action"):
        with pytest.raises(ValueError, match=field):
            RemediationPolicy(**{"name": "p", "slo": "s", "action": "a",
                                 field: ""})


def test_load_policies_roundtrip_and_louds(tmp_path):
    path = str(tmp_path / "rem.json")
    with open(path, "w") as f:
        json.dump({"policies": [
            {"name": "x", "slo": "serve_p99", "action": "rewarm",
             "cooldown_s": 1, "max_attempts": 4},
        ]}, f)
    (pol,) = load_policies(path)
    assert (pol.name, pol.slo, pol.action) == ("x", "serve_p99", "rewarm")
    assert pol.cooldown_s == 1 and pol.max_attempts == 4

    for bad in (
        {"policies": []},
        {"policies": [{"name": "x"}]},                      # missing keys
        {"policies": [{"name": "x", "slo": "s", "action": "a",
                       "typo": 1}]},                        # unknown key
        {"nope": []},                                       # unknown top
        {"policies": [{"name": "x", "slo": "s", "action": "a"},
                      {"name": "x", "slo": "t", "action": "b"}]},
    ):
        with open(path, "w") as f:
            json.dump(bad, f)
        with pytest.raises(ValueError):
            load_policies(path)


def test_default_policies():
    serve = default_policies("serve")
    assert [p.name for p in serve] == [
        "hotswap_model", "hotswap_index", "load_shed", "rewarm",
        "probe_escalation"]
    assert {p.slo for p in serve} == {
        "model_staleness", "index_staleness", "serve_queue_saturation",
        "serve_post_warmup_compile", "serve_recall_floor"}
    (train,) = default_policies("train")
    assert (train.slo, train.action) == (
        "embedding_collapse", "trainer_rollback")
    with pytest.raises(ValueError, match="unknown policy kind"):
        default_policies("fleet")


def test_severities_twin_pin():
    from npairloss_tpu.obs.live.alerts import ALERT_SEVERITIES

    assert REMEDIATION_SEVERITIES == ALERT_SEVERITIES


# -- engine lifecycle ---------------------------------------------------------


def test_engine_success_lifecycle_with_undo(tmp_path):
    calls, undone = [], []
    eng = _engine([POL], {"a": (lambda a: calls.append(a) or {"k": 1},
                                lambda a: undone.append(a))}, tmp_path)
    ev = eng.tick({"s": _alert("s-1")}, now=10.0)
    assert [e["state"] for e in ev] == ["attempted"]
    assert calls and calls[0]["slo"] == "s"
    ev = eng.tick({}, now=11.0)  # alert resolved = the success signal
    assert [e["state"] for e in ev] == ["succeeded"]
    assert ev[0]["detail"] == {"k": 1}
    assert ev[0]["duration_s"] == 1.0
    assert len(undone) == 1
    eng.close()
    records = load_remediation_log(str(tmp_path / "remediation.jsonl"))
    assert validate_remediation_log(records) is None
    assert set(records[0]) >= set(EVENT_KEYS)
    assert records[0]["schema"] == REMEDIATION_SCHEMA


def test_engine_retry_budget_and_fresh_incident(tmp_path):
    calls = []
    eng = _engine([POL], {"a": lambda a: calls.append(a)}, tmp_path)
    a1 = _alert("s-1")
    assert [e["state"] for e in eng.tick({"s": a1}, 10.0)] == ["attempted"]
    # inside cooldown, still firing: wait for the action to take effect
    assert eng.tick({"s": a1}, 12.0) == []
    # cooldown elapsed, still firing: attempt 1 failed, attempt 2 opens
    ev = eng.tick({"s": a1}, 16.0)
    assert [e["state"] for e in ev] == ["failed", "attempted"]
    assert "still firing" in ev[0]["error"]
    assert ev[1]["attempt"] == 2
    # budget exhausted: the final attempt fails, nothing new opens
    ev = eng.tick({"s": a1}, 22.0)
    assert [e["state"] for e in ev] == ["failed"]
    assert eng.tick({"s": a1}, 40.0) == []
    # a NEW incident (new alert id) gets a fresh budget
    ev = eng.tick({"s": _alert("s-2")}, 50.0)
    assert [e["state"] for e in ev] == ["attempted"]
    assert ev[0]["attempt"] == 1
    assert len(calls) == 3


def test_engine_cooldown_rate_limits_across_incidents():
    eng = _engine([POL], {"a": lambda a: None})
    assert len(eng.tick({"s": _alert("s-1")}, 10.0)) == 1
    eng.tick({}, 11.0)  # resolve (succeeded)
    # new incident, but the policy cooled down only 3 of 5 seconds
    assert eng.tick({"s": _alert("s-2")}, 13.0) == []
    assert [e["state"] for e in eng.tick({"s": _alert("s-2")}, 16.0)] \
        == ["attempted"]


def test_engine_action_raise_is_immediate_failure(tmp_path):
    def boom(alert):
        raise RuntimeError("no newer snapshot")

    eng = _engine([POL], {"a": boom}, tmp_path)
    ev = eng.tick({"s": _alert("s-1")}, 10.0)
    assert [e["state"] for e in ev] == ["attempted", "failed"]
    assert "no newer snapshot" in ev[1]["error"]
    eng.close()
    records = load_remediation_log(str(tmp_path / "remediation.jsonl"))
    assert validate_remediation_log(records) is None


def test_engine_dry_run_logs_but_never_acts(tmp_path):
    calls = []
    eng = _engine([POL], {"a": lambda a: calls.append(a)}, tmp_path,
                  dry_run=True)
    ev = eng.tick({"s": _alert("s-1")}, 10.0)
    assert [e["state"] for e in ev] == ["attempted"]
    assert ev[0]["dry_run"] is True
    assert calls == []
    # budgets still count: a rehearsal exercises the rate limits
    assert eng.tick({"s": _alert("s-1")}, 16.0)[0]["attempt"] == 2
    assert eng.tick({"s": _alert("s-1")}, 22.0) == []  # budget spent
    # dry attempts never conclude, even on resolution
    assert eng.tick({}, 30.0) == []
    eng.close()
    records = load_remediation_log(str(tmp_path / "remediation.jsonl"))
    assert validate_remediation_log(records) is None
    assert all(r["state"] == "attempted" and r["dry_run"]
               for r in records)


def test_undo_survives_failed_and_exhausted_attempts():
    """An undo (load-shed release) must run when the incident resolves
    even when its attempt long since FAILED — an actuator that can
    engage but not disengage is worse than none."""
    engaged, released = [], []
    pol = RemediationPolicy(name="p", slo="s", action="a",
                            cooldown_s=2.0, max_attempts=1)
    eng = _engine([pol], {"a": (lambda a: engaged.append(a),
                                lambda a: released.append(a))})
    a1 = _alert("s-1")
    assert [e["state"] for e in eng.tick({"s": a1}, 10.0)] == ["attempted"]
    # budget is 1: the cooldown-elapsed tick fails the attempt...
    assert [e["state"] for e in eng.tick({"s": a1}, 13.0)] == ["failed"]
    assert eng.tick({"s": a1}, 16.0) == []
    assert released == []  # still burning: stay engaged
    # ...but resolution still releases the engaged actuator
    assert eng.tick({}, 20.0) == []
    assert len(engaged) == 1 and len(released) == 1


def test_engine_config_louds():
    with pytest.raises(ValueError, match="unregistered actions"):
        RemediationEngine([POL], {})
    with pytest.raises(ValueError, match="duplicate policy names"):
        RemediationEngine([POL, POL], {"a": lambda a: None})


def test_engine_resumes_id_sequence(tmp_path):
    eng = _engine([POL], {"a": lambda a: None}, tmp_path)
    eng.tick({"s": _alert("s-1")}, 10.0)
    eng.tick({}, 11.0)
    eng.close()
    eng2 = _engine([POL], {"a": lambda a: None}, tmp_path)
    ev = eng2.tick({"s": _alert("s-9")}, 100.0)
    assert ev[0]["id"] == "p-2"  # continues past the old segment's ids
    eng2.close()
    records = load_remediation_log(str(tmp_path / "remediation.jsonl"))
    assert validate_remediation_log(records) is None


def test_last_by_policy_shape():
    eng = _engine([POL], {"a": lambda a: None})
    assert eng.last_by_policy() == {}  # never fired = absent key
    eng.tick({"s": _alert("s-1")}, 10.0)
    last = eng.last_by_policy()
    assert last == {"p": {"action": "a", "outcome": "attempted",
                          "alert_id": "s-1", "wall_time": 10.0}}
    eng.tick({}, 11.0)
    assert eng.last_by_policy()["p"]["outcome"] == "succeeded"


# -- the audit contract (validator teeth) -------------------------------------


def _valid_pair(aid="s-1", dry=False):
    base = {
        "schema": REMEDIATION_SCHEMA, "policy": "p", "action": "a",
        "alert_id": aid, "slo": "s", "severity": "critical",
        "attempt": 1, "max_attempts": 2, "dry_run": dry, "message": "m",
    }
    att = {**base, "id": "p-1", "state": "attempted", "ts": 10.0}
    ok = {**base, "id": "p-1", "state": "succeeded", "ts": 11.0,
          "dry_run": False, "duration_s": 1.0}
    return att, ok


def test_validator_accepts_and_rejects():
    att, ok = _valid_pair()
    assert validate_remediation_log([att, ok]) is None
    assert validate_remediation_log([]) is None

    def bad(mutate, records=None):
        recs = [dict(r) for r in (records or [att, ok])]
        mutate(recs)
        err = validate_remediation_log(recs)
        assert err is not None, recs
        return err

    assert "schema" in bad(lambda r: r[0].update(schema="v0"))
    assert "missing" in bad(lambda r: r[0].pop("attempt"))
    assert "state" in bad(lambda r: r[0].update(state="skipped"))
    assert "severity" in bad(lambda r: r[0].update(severity="fatal"))
    assert "not numeric" in bad(lambda r: r[0].update(ts="now"))
    assert "not an integer" in bad(lambda r: r[0].update(attempt=1.5))
    assert "outside" in bad(lambda r: r[0].update(attempt=3))
    assert "without an attempted" in bad(lambda r: r.pop(0))
    assert "duplicate attempted" in bad(lambda r: r.__setitem__(1, r[0]))
    assert "second outcome" in bad(lambda r: r.append(dict(r[1])))
    assert "precedes" in bad(lambda r: r[1].update(ts=9.0))
    assert "duration_s" in bad(lambda r: r[1].pop("duration_s"))
    # a failed outcome must carry its error
    failed = dict(ok, state="failed")
    assert "error" in validate_remediation_log([att, failed])
    # a dry-run attempt can never have an outcome
    datt = dict(att, dry_run=True)
    assert "DRY-RUN" in validate_remediation_log([datt, ok])
    # torn mid-log line is a violation (only the tail is tolerated)
    assert "unparseable" in validate_remediation_log(
        [{"_bad_line": 3}, att])


def test_validator_alert_crosscheck():
    att, ok = _valid_pair()
    fired = [{"state": "firing", "alert_id": "s-1", "ts": 5.0}]
    assert validate_remediation_log([att, ok], alert_records=fired) is None
    err = validate_remediation_log([att, ok], alert_records=[])
    assert "never fired" in err
    late = [{"state": "firing", "alert_id": "s-1", "ts": 50.0}]
    err = validate_remediation_log([att, ok], alert_records=late)
    assert "precedes the firing" in err


def test_unresolved_and_abandoned_helpers():
    att, ok = _valid_pair()
    assert unresolved_remediations([att]) == [("p-1", "p", "s-1")]
    assert unresolved_remediations([att, ok]) == []
    # failed mid-budget with no retry, critical: abandoned
    failed = dict(ok, state="failed", error="x")
    assert abandoned_remediations([att, failed]) == [("p-1", "p", "s-1")]
    # a later attempt for the same incident clears the verdict
    att2 = dict(att, id="p-2", attempt=2)
    assert abandoned_remediations([att, failed, att2]) == []
    # budget exhausted is not abandonment
    spent = dict(failed, attempt=2)
    assert abandoned_remediations([att, spent]) == []
    # warnings are never abandoned (the gate is critical-only)
    warn = [dict(att, severity="warning"),
            dict(failed, severity="warning")]
    assert abandoned_remediations(warn) == []
    # an incident that RESOLVED anyway needed no retry — not abandoned
    assert abandoned_remediations([att, failed],
                                  resolved_alert_ids=["s-1"]) == []


def test_torn_tail_tolerated(tmp_path):
    att, ok = _valid_pair()
    path = str(tmp_path / "remediation.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(att) + "\n" + json.dumps(ok) + "\n")
        f.write('{"schema": "npairloss-rem')  # killed mid-write
    records = load_remediation_log(path)
    assert len(records) == 2
    assert validate_remediation_log(records) is None


# -- the jax-free bench_check gate --------------------------------------------


def _gate(path, *extra):
    return subprocess.run(
        [sys.executable, BENCH_CHECK, "--remediation", path, *extra],
        capture_output=True, text=True)


def _write_logs(tmp_path, rem_records, alert_records):
    os.makedirs(str(tmp_path), exist_ok=True)
    rp = str(tmp_path / "remediation.jsonl")
    with open(rp, "w") as f:
        for r in rem_records:
            f.write(json.dumps(r) + "\n")
    if alert_records is not None:
        with open(str(tmp_path / "alerts.jsonl"), "w") as f:
            for r in alert_records:
                f.write(json.dumps(r) + "\n")
    return rp


def test_bench_check_remediation_gate(tmp_path):
    att, ok = _valid_pair()
    fired = [{"state": "firing", "alert_id": "s-1", "ts": 5.0}]
    rp = _write_logs(tmp_path / "good", rem_records=[att, ok],
                     alert_records=fired)
    out = _gate(rp)
    assert out.returncode == 0, out.stdout + out.stderr

    # schema violation refused
    bad = dict(att)
    bad["schema"] = "npairloss-remediation-v0"
    rp = _write_logs(tmp_path / "schema", [bad, ok], fired)
    out = _gate(rp)
    assert out.returncode == 1 and "invalid" in out.stdout

    # action-without-alert refused (cross-check against the paired log)
    rp = _write_logs(tmp_path / "ghost", [att, ok],
                     [{"state": "firing", "alert_id": "other", "ts": 1.0}])
    out = _gate(rp)
    assert out.returncode == 1 and "never fired" in out.stdout

    # actions with NO alert log at all: unjustifiable, refused
    rp = _write_logs(tmp_path / "nolog", [att, ok], None)
    out = _gate(rp)
    assert out.returncode == 1 and "no alert log" in out.stdout

    # abandoned critical remediation (failed mid-budget, never retried)
    failed = dict(ok, state="failed", error="gave up")
    rp = _write_logs(tmp_path / "aband", [att, failed], fired)
    out = _gate(rp)
    assert out.returncode == 1 and "attempts remaining" in out.stdout

    # ...but the same shape with the alert RESOLVED in the paired log
    # is a healed incident, not abandonment — accepted
    healed = fired + [{"state": "resolved", "alert_id": "s-1", "ts": 30.0}]
    rp = _write_logs(tmp_path / "healed", [att, failed], healed)
    out = _gate(rp)
    assert out.returncode == 0, out.stdout

    # an empty audit log next to an empty alert log is a clean run
    rp = _write_logs(tmp_path / "empty", [], [])
    out = _gate(rp)
    assert out.returncode == 0, out.stdout


# -- live-observatory attachment ----------------------------------------------


def test_live_observatory_drives_remediation(tmp_path):
    from npairloss_tpu.obs.live import LiveObservatory, SLOSpec
    from npairloss_tpu.obs.live.alerts import (
        load_alert_log,
        validate_alert_log,
    )

    spec = SLOSpec(name="s", metric="m", op="<=", target=1.0,
                   window_s=10.0, burn_threshold=0.5, min_samples=1,
                   severity="critical")
    live = LiveObservatory([spec], out_dir=str(tmp_path),
                           clock=lambda: 0.0)
    acted = []
    eng = RemediationEngine(
        [RemediationPolicy(name="fix", slo="s", action="f",
                           cooldown_s=5.0, max_attempts=3)],
        {"f": lambda a: acted.append(a)},
        log_path=str(tmp_path / "remediation.jsonl"), clock=lambda: 0.0)
    live.set_remediation(eng)
    live.registry.set("m", 9.0, t=10.0)
    live.tick(now=10.0)
    assert len(acted) == 1 and acted[0]["alert_id"] == "s-1"
    # resolution requires GOOD samples (silence holds a burning SLO);
    # by t=21 the bad sample aged out of the window and the good one
    # clears it -> resolve -> the attempt succeeds
    live.registry.set("m", 0.5, t=15.0)
    live.tick(now=21.0)
    live.stop(final_tick=False)
    arecs = load_alert_log(str(tmp_path / "alerts.jsonl"))
    rrecs = load_remediation_log(str(tmp_path / "remediation.jsonl"))
    assert validate_alert_log(arecs) is None
    assert validate_remediation_log(rrecs, alert_records=arecs) is None
    assert [r["state"] for r in rrecs] == ["attempted", "succeeded"]


# -- watch reconciliation ------------------------------------------------------


def test_watch_reconciles_audit_against_replay(tmp_path):
    from npairloss_tpu.obs.live import SLOSpec, watch_run_dir

    run = tmp_path / "run"
    run.mkdir()
    rows = []
    # incident 1: fires at t=0..2, resolves by t=20 (acted on)
    # incident 2: fires at t=35..37, resolves by t=55 (NOT acted on)
    for t, v in [(0, 500.0), (1, 500.0), (2, 500.0), (20, 10.0),
                 (21, 10.0), (35, 500.0), (36, 500.0), (37, 500.0),
                 (55, 10.0), (56, 10.0)]:
        rows.append({"phase": "serve", "step": t, "wall_time": float(t),
                     "p99_ms": v})
    with open(run / "metrics.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    att, _ = _valid_pair(aid="p99-1")
    att = dict(att, slo="p99")
    ghost, _ = _valid_pair(aid="p99-77")
    ghost = dict(ghost, id="p-9", slo="p99")
    with open(run / "remediation.jsonl", "w") as f:
        f.write(json.dumps(att) + "\n")
        f.write(json.dumps(ghost) + "\n")
    spec = SLOSpec(name="p99", metric="serve_p99_ms", op="<=",
                   target=150.0, window_s=10.0, burn_threshold=0.5,
                   min_samples=1, severity="critical")
    summary = watch_run_dir(str(run), [spec])
    rec = summary["remediation"]
    assert rec["valid"] is True
    assert rec["matched"] == ["p99-1"]
    assert rec["alert_resolved_no_action"] == ["p99-2"]
    assert rec["action_no_resolution"] == ["p99-77"]
    # a DRY-RUN attempt is a rehearsal, never an action: its resolved
    # incident reads as alert_resolved_no_action, not matched
    # (fresh watch log: the engine resumes an existing one, so a
    # leftover alerts.watch.jsonl would continue the id sequence)
    os.remove(run / "alerts.watch.jsonl")
    dry = dict(att, id="p-2", dry_run=True)
    with open(run / "remediation.jsonl", "w") as f:
        f.write(json.dumps(dry) + "\n")
    rec = watch_run_dir(str(run), [spec])["remediation"]
    assert rec["matched"] == []
    assert sorted(rec["alert_resolved_no_action"]) == ["p99-1", "p99-2"]

    # no audit log, no block (the absent-key contract)
    os.remove(run / "remediation.jsonl")
    assert "remediation" not in watch_run_dir(str(run), [spec])


# -- delayed failpoint arming -------------------------------------------------


def test_failpoint_delayed_arming(monkeypatch):
    failpoints.arm("x", times=2, delay=3)
    assert [failpoints.should_fire("x") for _ in range(6)] == \
        [False, False, False, True, True, False]
    failpoints.reset()
    monkeypatch.setenv(failpoints.ENV_VAR, "y:2@1,z,w@2")
    assert [failpoints.should_fire("y") for _ in range(4)] == \
        [False, True, True, False]
    assert failpoints.should_fire("z") is True
    # "name@delay" shorthand: default count of 1, delayed start
    assert [failpoints.should_fire("w") for _ in range(4)] == \
        [False, False, True, False]


# -- admission forced shed -----------------------------------------------------


def test_admission_engage_release_forced_shed():
    from npairloss_tpu.serve.admission import (
        AdmissionConfig,
        AdmissionController,
    )

    ctl = AdmissionController(AdmissionConfig(probe_every=3))
    assert ctl.admit() is True
    ctl.engage()
    assert ctl.stats()["shedding"] is True and ctl.stats()["forced"]
    decisions = [ctl.admit() for _ in range(6)]
    assert decisions == [False, False, True, False, False, True]
    assert ctl.sheds == 4 and ctl.probes_admitted == 2
    ctl.release(None)
    assert ctl.admit() is True
    assert ctl.stats()["shedding"] is False
    assert "forced" not in ctl.stats()


# -- serve-side actuators (tiny jax work) -------------------------------------


class _FakeTel:
    """Just enough of RunTelemetry for window-row capture."""

    metrics_enabled = True
    tracer = None

    def __init__(self):
        self.rows = []

    def span(self, name, **args):
        import contextlib

        return contextlib.nullcontext()

    def instant(self, name, **args):
        pass

    def log(self, phase, step, row):
        self.rows.append(dict(row))

    def flush(self):
        pass


def _tiny_server(metrics_window=0, telemetry=None):
    from npairloss_tpu.serve import (
        BatcherConfig,
        EngineConfig,
        Freshness,
        GalleryIndex,
        QueryEngine,
        RetrievalServer,
        ServerConfig,
    )

    rng = np.random.default_rng(0)
    emb = rng.standard_normal((32, 8)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    index = GalleryIndex.build(emb, (np.arange(32) % 4).astype(np.int32),
                               normalize=False)
    engine = QueryEngine(index, EngineConfig(top_k=3, buckets=(1, 4)))
    engine.warmup()
    server = RetrievalServer(
        engine, BatcherConfig(max_batch=4, max_delay_ms=1.0),
        ServerConfig(metrics_window=metrics_window), telemetry=telemetry,
        freshness=Freshness.collect(index=index, index_path="/tmp/f.gidx"),
    )
    server.replicaset.start()
    return emb, server


def test_hot_swap_under_concurrent_queries(tmp_path):
    from npairloss_tpu.serve import GalleryIndex
    from npairloss_tpu.serve.hotswap import (
        NothingNewerError,
        SnapshotSwapper,
    )
    from npairloss_tpu.serve.index import load_index

    rng = np.random.default_rng(0)
    emb = rng.standard_normal((48, 8)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    lab = (np.arange(48) % 6).astype(np.int32)
    prefix = str(tmp_path / "g.")
    p1 = GalleryIndex.build(emb, lab, normalize=False).save(prefix
                                                            + "000.gidx")
    from npairloss_tpu.serve import (
        BatcherConfig,
        EngineConfig,
        Freshness,
        QueryEngine,
        RetrievalServer,
        ServerConfig,
    )

    engine = QueryEngine(load_index(p1), EngineConfig(top_k=3,
                                                      buckets=(1, 4)))
    engine.warmup()
    server = RetrievalServer(
        engine, BatcherConfig(max_batch=4, max_delay_ms=1.0),
        ServerConfig(metrics_window=0),
        freshness=Freshness.collect(index=engine.index, index_path=p1),
    )
    server.replicaset.start()
    stop = threading.Event()
    errors, answered = [], [0]

    def client(k):
        i = k
        while not stop.is_set():
            a = server.handle({"id": i, "embedding": emb[i % 48].tolist()})
            (errors.append(a) if "error" in a
             else answered.__setitem__(0, answered[0] + 1))
            i += 1

    threads = [threading.Thread(target=client, args=(k,)) for k in range(3)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.2)
        # a newer commit with add()-grown rows (new padded size = the
        # re-warm matters: the swap compiles the NEW shapes off-path)
        idx2 = load_index(p1)
        idx2.add(rng.standard_normal((7, 8)).astype(np.float32),
                 (np.arange(7) % 6).astype(np.int32))
        p2 = idx2.save(prefix + "001.gidx")
        swapper = SnapshotSwapper(server, index_prefix=prefix)
        detail = swapper.swap()
        assert detail["swapped"] == ["index"]
        time.sleep(0.2)
    finally:
        stop.set()
        for t in threads:
            t.join()
        server.replicaset.close(drain=True)
    s = server.summary()
    assert not errors, errors[:3]
    assert answered[0] > 0
    # the invariant holds through the swap; nothing dropped or double-
    # counted, and steady state after the re-warm never compiled
    assert s["queries"] == s["answered"] + s["errors"] + s["rejected"]
    assert s["hot_swaps"] == 1
    assert s["compiles_after_warmup"] == 0
    assert server.freshness.index_path == p2
    assert server.engine.index.size == 55
    # healthz shape: the remediation block is absent without an engine
    assert "remediation" not in server.healthz()
    with pytest.raises(NothingNewerError):
        swapper.swap()


def test_swapper_skips_torn_newer_snapshot(tmp_path):
    """A newer snapshot whose manifest validates but whose arrays fail
    the restore-time checksum is skipped in favor of the next older
    still-newer one — the resume scan's contract, applied to swap."""
    import types

    from npairloss_tpu.resilience import read_manifest
    from npairloss_tpu.serve.hotswap import SnapshotSwapper
    from npairloss_tpu.serve.server import Freshness

    solver, batches = _make_solver(tmp_path)
    for k in (1, 2):
        x, lab = next(batches)
        solver.step(x, lab)
        solver.save_snapshot(k)
    newest = solver.snapshot_path(2)
    manifest = read_manifest(newest)
    # Corrupt a PARAMS leaf specifically: restore_for_inference only
    # checksum-verifies the params/batch_stats subset, so a damaged
    # optimizer leaf would restore fine and prove nothing.
    key = next(k for k in manifest["arrays"]
               if k.startswith("['params']"))
    manifest["arrays"][key]["crc32"] ^= 1
    with open(os.path.join(newest, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    sw = SnapshotSwapper(
        server=types.SimpleNamespace(freshness=None),
        snapshot_prefix=str(tmp_path / "snap" / "m_"), model=object())
    restored = sw._restore_newer(Freshness(snapshot_step=0))
    assert restored is not None
    path, state = restored
    assert path == solver.snapshot_path(1)
    assert "params" in state
    # nothing newer than the valid step-1 snapshot -> None
    assert sw._restore_newer(Freshness(snapshot_step=1)) is None


def test_swap_applies_index_transform(tmp_path):
    """The --index-kind reconciliation survives the swap: a flat commit
    republished into an IVF-serving tier arrives clustered."""
    from npairloss_tpu.serve import (
        BatcherConfig,
        EngineConfig,
        Freshness,
        GalleryIndex,
        QueryEngine,
        RetrievalServer,
        ServerConfig,
    )
    from npairloss_tpu.serve.hotswap import SnapshotSwapper
    from npairloss_tpu.serve.index import load_index
    from npairloss_tpu.serve.ivf import IVFIndex

    rng = np.random.default_rng(0)
    emb = rng.standard_normal((64, 8)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    lab = (np.arange(64) % 8).astype(np.int32)
    prefix = str(tmp_path / "g.")
    p1 = GalleryIndex.build(emb, lab, normalize=False).save(
        prefix + "000.gidx")
    ivf1 = IVFIndex.from_gallery(load_index(p1), clusters=4)
    engine = QueryEngine(ivf1, EngineConfig(top_k=3, buckets=(1,),
                                            probes=4))
    engine.warmup()
    server = RetrievalServer(
        engine, BatcherConfig(max_batch=1, max_delay_ms=1.0),
        ServerConfig(metrics_window=0),
        freshness=Freshness.collect(index=ivf1, index_path=p1),
    )
    server.replicaset.start()
    try:
        idx2 = load_index(p1)
        idx2.add(rng.standard_normal((4, 8)).astype(np.float32),
                 (np.arange(4) % 8).astype(np.int32))
        idx2.save(prefix + "001.gidx")
        swapper = SnapshotSwapper(
            server, index_prefix=prefix,
            index_transform=lambda i: IVFIndex.from_gallery(i, clusters=4))
        swapper.swap()
        assert isinstance(server.engine.index, IVFIndex)
        assert server.engine.index.size == 68
        a = server.handle({"id": 0, "embedding": emb[0].tolist()})
        assert a["neighbors"][0]["row"] == 0
    finally:
        server.replicaset.close(drain=True)


def test_swapper_validation_louds():
    _, server = _tiny_server()
    from npairloss_tpu.serve.hotswap import SnapshotSwapper

    try:
        with pytest.raises(ValueError, match="needs an index_prefix"):
            SnapshotSwapper(server)
        with pytest.raises(ValueError, match="needs the model"):
            SnapshotSwapper(server, snapshot_prefix="/tmp/x_")
    finally:
        server.replicaset.close(drain=True)


def test_compile_storm_and_rewarm_reset(tmp_path):
    tel = _FakeTel()
    emb, server = _tiny_server(metrics_window=2, telemetry=tel)
    try:
        failpoints.arm("serve.compile_storm", times=2)
        for i in range(4):
            server.handle({"id": i, "embedding": emb[i].tolist()})
        # two phantom post-warmup compiles counted, no real XLA work
        assert server.engine.compiles_after_warmup == 2
        storm_rows = [r for r in tel.rows
                      if r.get("compiles_after_warmup")]
        assert storm_rows and storm_rows[-1]["compiles_after_warmup"] == 2
        out = server.rewarm()
        assert out["warmup_s"] >= 0.0
        assert server.engine.compiles_after_warmup == 0
        assert server.engine.warmed
        for i in range(4):
            server.handle({"id": i, "embedding": emb[i].tolist()})
        # post-rewarm rows carry the key EXPLICITLY at 0, so the
        # watchdog can observe recovery (clean runs keep absent-at-0)
        assert tel.rows[-1]["compiles_after_warmup"] == 0
    finally:
        server.replicaset.close(drain=True)


def test_rewarm_failure_keeps_storm_evidence():
    """A re-warm that raises must reset NOTHING: the alert that
    triggered the failed remediation keeps its counter basis."""
    emb, server = _tiny_server()
    try:
        engine = server.engine
        failpoints.arm("serve.compile_storm", times=1)
        server.handle({"id": 0, "embedding": emb[0].tolist()})
        assert engine.compiles_after_warmup == 1

        def boom(input_shape=None):
            raise RuntimeError("device fell over")

        engine.warmup = boom
        with pytest.raises(RuntimeError, match="fell over"):
            server.rewarm()
        assert engine.warmed is True  # still the serving engine
        assert engine.compiles_after_warmup == 1  # evidence survives
        assert server._explicit_compile_key is False
    finally:
        server.replicaset.close(drain=True)


def test_serve_cli_remediate_arg_validation_fast_fails(tmp_path):
    """Misconfigured remediation flags exit 2 with a diagnostic BEFORE
    any index/model work (no traceback, milliseconds)."""
    bad_cfg = str(tmp_path / "bad.json")
    with open(bad_cfg, "w") as f:
        json.dump({"policies": [{"name": "x", "typo": 1}]}, f)
    for extra in (["--remediate"],  # no --live-obs
                  ["--live-obs", "--telemetry-dir", "/tmp/x",
                   "--remediate", "--watch-snapshots", "/tmp/p_"],
                  ["--live-obs", "--telemetry-dir", "/tmp/x",
                   "--remediate", "--remediation-config", bad_cfg]):
        out = subprocess.run(
            [sys.executable, "-m", "npairloss_tpu", "serve",
             "--index", "/nonexistent.gidx", *extra],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 2, (extra, out.stderr)
        assert "Traceback" not in out.stderr, out.stderr


def test_remediation_block_in_summary_and_healthz():
    emb, server = _tiny_server()
    try:
        eng = _engine([POL], {"a": lambda a: None})
        server.remediation = eng
        assert server.summary()["remediation"] == {}
        eng.tick({"s": _alert("s-1")}, 10.0)
        block = server.healthz()["remediation"]
        assert block["p"]["outcome"] == "attempted"
        assert block["p"]["action"] == "a"
        assert isinstance(block["p"]["wall_time"], float)
    finally:
        server.replicaset.close(drain=True)


# -- train-side actuators -----------------------------------------------------


def _make_solver(tmp_path, snapshot=0, display=0, **kw):
    from npairloss_tpu import NPairLossConfig
    from npairloss_tpu.data import synthetic_identity_batches
    from npairloss_tpu.models import get_model
    from npairloss_tpu.resilience import RetryPolicy
    from npairloss_tpu.train import Solver, SolverConfig

    cfg = SolverConfig(
        base_lr=0.5, lr_policy="fixed", momentum=0.9, weight_decay=0.0,
        display=display, test_interval=0, average_loss=10,
        snapshot=snapshot, snapshot_prefix=str(tmp_path / "snap" / "m_"),
        **kw,
    )
    solver = Solver(
        get_model("mlp", hidden=(32,), embedding_dim=16),
        NPairLossConfig(), cfg, input_shape=(16,),
        snapshot_retry=RetryPolicy(base_delay=0.001, jitter=0.0),
    )
    return solver, synthetic_identity_batches(8, 8, 2, (16,), noise=0.5)


def test_train_collapse_failpoint_poisons_row(tmp_path):
    solver, batches = _make_solver(tmp_path, display=1)
    events = []
    failpoints.arm("train.collapse", times=2)
    solver.train(batches, num_iters=4, record_fn=events.append)
    displays = [e for e in events if e["event"] == "display"]
    assert [e.get("an_threshold_mean") for e in displays] == \
        [1.0, 1.0, None, None]


def test_requested_rollback_executes_and_skips(tmp_path):
    solver, batches = _make_solver(tmp_path, snapshot=2)
    events = []
    fired = {"done": False}

    def record(ev):
        events.append(ev)
        if ev["event"] == "snapshot" and ev["iteration"] == 4 \
                and not fired["done"]:
            # request from inside the run (stands in for the live-obs
            # tick thread): roll back to a snapshot predating "now"
            fired["done"] = True
            solver.request_rollback(RollbackRequest(
                reason="collapse alert", before_wall_time=time.time()))

    solver.train(batches, num_iters=6, record_fn=record)
    rb = [e for e in events if e["event"] == "rollback"]
    assert len(rb) == 1 and rb[0]["requested"] is True
    assert rb[0]["iteration"] == 5  # taken at the next step
    assert rb[0]["to_iteration"] in (2, 4)
    assert solver.iteration == 6  # re-ran to the target after rollback

    # a request predating every snapshot SKIPS (training continues; the
    # remediation budget owns retries)
    solver2, batches2 = _make_solver(tmp_path / "two", snapshot=2)
    events2 = []
    armed = {"done": False}

    def record2(ev):
        events2.append(ev)
        if ev["event"] == "snapshot" and not armed["done"]:
            armed["done"] = True
            solver2.request_rollback(RollbackRequest(
                reason="too early", before_wall_time=1.0))

    solver2.train(batches2, num_iters=4, record_fn=record2)
    assert not [e for e in events2 if e["event"] == "rollback"]
    assert solver2.iteration == 4


def test_requested_rollback_pipelined_window_boundary(tmp_path):
    solver, batches = _make_solver(tmp_path, snapshot=2, display=4)
    solver.cfg = __import__("dataclasses").replace(
        solver.cfg, pipeline=True, pipeline_window=4)
    events = []
    fired = {"done": False}

    def record(ev):
        events.append(ev)
        if ev["event"] == "snapshot" and ev["iteration"] >= 2 \
                and not fired["done"]:
            fired["done"] = True
            solver.request_rollback(RollbackRequest(
                reason="collapse alert", before_wall_time=time.time()))

    solver.train(batches, num_iters=8, record_fn=record)
    rb = [e for e in events if e["event"] == "rollback"]
    assert len(rb) == 1 and rb[0]["requested"] is True
    assert solver.iteration == 8
