"""Golden gradient tests: custom_vjp vs the oracle's analytic backward.

The reference backward (npair_multi_class_loss.cu:420-499) is NOT the plain
autodiff gradient: it averages each sample's query-role and database-role
gradients 0.5/0.5 and rescales the allreduced database side by 1/G.  These
tests pin that exactly, plus the "true" autodiff mode's relationship to it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import make_identity_batch
from npairloss_tpu import MiningMethod, MiningRegion, NPairLossConfig
from npairloss_tpu.ops.npair_loss import npair_loss
from npairloss_tpu.testing import oracle

CFGS = [
    NPairLossConfig(),  # proto defaults: LOCAL/RAND both sides
    NPairLossConfig(  # shipped config, def.prototxt:137-146
        margin_diff=-0.05,
        identsn=-0.0,
        diffsn=-0.3,
        ap_mining_region=MiningRegion.GLOBAL,
        ap_mining_method=MiningMethod.RELATIVE_HARD,
        an_mining_region=MiningRegion.LOCAL,
        an_mining_method=MiningMethod.HARD,
    ),
    NPairLossConfig(
        margin_ident=0.1,
        ap_mining_method=MiningMethod.EASY,
        an_mining_region=MiningRegion.GLOBAL,
        an_mining_method=MiningMethod.RELATIVE_EASY,
        diffsn=2.0,
    ),
]


@pytest.mark.parametrize("cfg_idx", range(len(CFGS)))
def test_single_shard_grad_matches_oracle(rng, cfg_idx):
    cfg = CFGS[cfg_idx]
    feats, labs = make_identity_batch(rng, 5, 2, 12)
    res = oracle.forward(feats, labs, cfg)
    want = oracle.backward(feats, res, loss_weight=1.0)[0]
    got = jax.jit(jax.grad(lambda f, l: npair_loss(f, l, cfg)))(feats[0], labs[0])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-7)


def test_loss_weight_scaling(rng):
    """Upstream cotangent (Caffe loss_weight, cu:435) scales linearly."""
    cfg = CFGS[1]
    feats, labs = make_identity_batch(rng, 5, 2, 12)
    res = oracle.forward(feats, labs, cfg)
    want = oracle.backward(feats, res, loss_weight=2.5)[0]
    got = jax.grad(lambda f, l: 2.5 * npair_loss(f, l, cfg))(feats[0], labs[0])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-7)


def test_true_grad_mode_is_exact_autodiff(rng):
    """grad_mode="true" must equal finite differences of the loss."""
    cfg = NPairLossConfig(grad_mode="true")
    feats, labs = make_identity_batch(rng, 4, 2, 8)
    f64 = feats[0].astype(np.float64)

    def loss_fn(f):
        return npair_loss(jnp.asarray(f), jnp.asarray(labs[0]), cfg)

    g = np.asarray(jax.grad(loss_fn)(feats[0]))
    eps = 1e-3
    for idx in [(0, 0), (1, 3), (3, 5)]:
        fp = f64.copy()
        fp[idx] += eps
        fm = f64.copy()
        fm[idx] -= eps
        fd = (float(loss_fn(fp.astype(np.float32))) - float(loss_fn(fm.astype(np.float32)))) / (2 * eps)
        np.testing.assert_allclose(g[idx], fd, rtol=5e-2, atol=1e-4)


def test_reference_grad_is_half_true_grad_single_shard(rng):
    """With G=1 the reference gradient is exactly 0.5x the true gradient
    (0.5 * query-role + 0.5 * db-role vs their sum) — SURVEY.md §3.2."""
    feats, labs = make_identity_batch(rng, 4, 2, 8)
    ref = jax.grad(lambda f, l: npair_loss(f, l, NPairLossConfig()))(
        feats[0], labs[0]
    )
    true = jax.grad(
        lambda f, l: npair_loss(f, l, NPairLossConfig(grad_mode="true"))
    )(feats[0], labs[0])
    np.testing.assert_allclose(np.asarray(ref) * 2.0, np.asarray(true), rtol=1e-5, atol=1e-7)


def test_int_labels_grad_ok(rng):
    """Integer labels must not break the custom_vjp (float0 tangent)."""
    feats, labs = make_identity_batch(rng, 4, 2, 8)
    g = jax.grad(lambda f: npair_loss(f, jnp.asarray(labs[0], jnp.int32)))(feats[0])
    assert np.isfinite(np.asarray(g)).all()
