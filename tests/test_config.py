"""Config front-end tests: text-format parser + typed schema + reference
usage files parsed verbatim (SURVEY.md §7.4, north-star prototxt compat)."""

import os

import pytest

from npairloss_tpu.config import (
    PrototxtParseError,
    dumps,
    load_net,
    load_solver,
    net_from_text,
    npair_param_to_config,
    parse,
)
from npairloss_tpu.ops.npair_loss import MiningMethod, MiningRegion

REF_USAGE = "/root/reference/usage"


# ---------------------------------------------------------------------------
# Parser primitives
# ---------------------------------------------------------------------------


def test_scalars_and_types():
    msg = parse(
        """
        an_int: 42
        a_float: 0.5
        neg: -0.3
        sci: 1e-8
        flag_t: true
        flag_f: false
        s: "hello world"
        enum_val: RELATIVE_HARD
        """
    )
    assert msg["an_int"] == 42 and isinstance(msg["an_int"], int)
    assert msg["a_float"] == 0.5
    assert msg["neg"] == -0.3
    assert msg["sci"] == 1e-8
    assert msg["flag_t"] is True and msg["flag_f"] is False
    assert msg["s"] == "hello world"
    assert msg["enum_val"] == "RELATIVE_HARD"


def test_nested_and_repeated():
    msg = parse(
        """
        layer { name: "a" top: "x" top: "y" }
        layer { name: "b" inner { k: 1 } }
        loss_weight: 1
        loss_weight: 2
        """
    )
    layers = msg.getlist("layer")
    assert len(layers) == 2
    assert layers[0].getlist("top") == ["x", "y"]
    assert layers[1]["inner"]["k"] == 1
    assert msg.getlist("loss_weight") == [1, 2]
    # singular access takes the last occurrence (proto2 semantics)
    assert msg["loss_weight"] == 2


def test_comments_including_nonascii():
    msg = parse(
        """
        a: 1 # trailing comment
        # full-line comment
        b: 2 # 对于绝对选择来说该项无效
        s: "has # not a comment"
        """
    )
    assert msg["a"] == 1 and msg["b"] == 2
    assert msg["s"] == "has # not a comment"


def test_colon_before_brace_and_no_space():
    msg = parse('inc:{ phase: TEST }\nval:3')
    assert msg["inc"]["phase"] == "TEST"
    assert msg["val"] == 3


def test_template_ellipsis_tolerated():
    # def.prototxt is a truncated template with literal "." lines
    msg = parse("a: 1\n.\n.\n.\nb: 2")
    assert msg["a"] == 1 and msg["b"] == 2


def test_parse_errors():
    with pytest.raises(PrototxtParseError):
        parse("a: 1 }")
    with pytest.raises(PrototxtParseError):
        parse("layer {")
    with pytest.raises(PrototxtParseError):
        parse("a:")


def test_roundtrip():
    text = 'name: "n"\nlayer {\n    t: GLOBAL\n    v: 3\n}'
    msg = parse(text)
    again = parse(dumps(msg))
    assert again.to_dict() == msg.to_dict()


# ---------------------------------------------------------------------------
# NPairLossParameter mapping (caffe.proto:3-23)
# ---------------------------------------------------------------------------


def test_npair_param_defaults_match_proto():
    cfg = npair_param_to_config(None)
    assert cfg.margin_ident == 0.0
    assert cfg.margin_diff == 0.0
    assert cfg.identsn == -1.0
    assert cfg.diffsn == -1.0
    assert cfg.ap_mining_region == MiningRegion.LOCAL
    assert cfg.ap_mining_method == MiningMethod.RAND
    assert cfg.an_mining_region == MiningRegion.LOCAL
    assert cfg.an_mining_method == MiningMethod.RAND


def test_npair_param_numeric_enums():
    msg = parse("ap_mining_region: 0\nap_mining_method: 3")
    cfg = npair_param_to_config(msg)
    assert cfg.ap_mining_region == MiningRegion.GLOBAL
    assert cfg.ap_mining_method == MiningMethod.RELATIVE_HARD


# ---------------------------------------------------------------------------
# Reference usage files, verbatim
# ---------------------------------------------------------------------------

needs_ref = pytest.mark.skipif(
    not os.path.isdir(REF_USAGE), reason="reference usage/ not mounted"
)


@needs_ref
def test_reference_solver_prototxt():
    cfg, net = load_solver(os.path.join(REF_USAGE, "solver.prototxt"))
    assert net == "./conf_same_veri/def.prototxt"
    assert cfg.base_lr == 0.001
    assert cfg.lr_policy == "step"
    assert cfg.stepsize == 10000
    assert cfg.gamma == 0.5
    assert cfg.max_iter == 2000000
    assert cfg.momentum == 0.9
    assert cfg.weight_decay == 2e-5
    assert cfg.snapshot == 5000
    assert cfg.snapshot_prefix == "./snap/googlenet_"
    assert cfg.test_iter == 2000
    assert cfg.test_interval == 2000
    assert cfg.test_initialization is True
    assert cfg.display == 100
    assert cfg.average_loss == 100


@needs_ref
def test_reference_def_prototxt():
    net = load_net(os.path.join(REF_USAGE, "def.prototxt"))
    assert net.name == "GoogleNet"
    assert net.l2_normalize

    train = net.data["TRAIN"]
    assert train.batch_size == 120
    assert train.identity_num_per_batch == 60
    assert train.img_num_per_identity == 2
    assert train.rand_identity and train.shuffle
    assert train.new_height == train.new_width == 224
    assert train.transform.crop_size == 224
    assert train.transform.mirror is True
    assert train.transform.mean_value == (104.0, 117.0, 123.0)

    test = net.data["TEST"]
    assert test.batch_size == 30
    assert test.identity_num_per_batch == 15

    tr = net.transformer
    assert tr is not None
    assert tr.rotate_angle_scope == pytest.approx(0.349)
    assert tr.translation_w_scope == 70
    assert tr.scale_w_scope == pytest.approx(1.2)
    assert tr.h_flip is True
    assert tr.elastic_transform is False

    loss = net.loss
    assert loss is not None
    assert len(loss.tops) == 5
    assert loss.loss_weights == (1.0,) * 5
    lc = loss.loss
    assert lc.margin_ident == 0.0
    assert lc.margin_diff == pytest.approx(-0.05)
    assert lc.identsn == pytest.approx(-0.0)
    assert lc.diffsn == pytest.approx(-0.3)
    assert lc.ap_mining_region == MiningRegion.GLOBAL
    assert lc.ap_mining_method == MiningMethod.RELATIVE_HARD
    assert lc.an_mining_region == MiningRegion.LOCAL
    assert lc.an_mining_method == MiningMethod.HARD


@needs_ref
def test_reference_def_matches_shipped_reference_config():
    """The parsed def.prototxt mining config must equal REFERENCE_CONFIG."""
    import dataclasses

    from npairloss_tpu.ops.npair_loss import REFERENCE_CONFIG

    net = load_net(os.path.join(REF_USAGE, "def.prototxt"))
    parsed = dataclasses.replace(net.loss.loss, grad_mode="reference")
    assert parsed == REFERENCE_CONFIG


# ---------------------------------------------------------------------------
# Solver round-trip on our own fixture
# ---------------------------------------------------------------------------


def test_solver_from_text(tmp_path):
    p = tmp_path / "solver.prototxt"
    p.write_text(
        'net: "net.prototxt"\nbase_lr: 0.01\nlr_policy: "multistep"\n'
        "stepvalue: 10\nstepvalue: 20\nmomentum: 0.5\nmax_iter: 100\n"
        'solver_mode: GPU\n'
    )
    cfg, net = load_solver(str(p))
    assert net == "net.prototxt"
    assert cfg.base_lr == 0.01
    assert cfg.lr_policy == "multistep"
    assert cfg.stepvalues == (10, 20)
    assert cfg.momentum == 0.5
    assert cfg.max_iter == 100


def test_net_without_loss_params_uses_defaults():
    net = net_from_text(
        'name: "tiny"\nlayer { name: "l" type: "NPairMultiClassLoss" '
        'bottom: "f" bottom: "y" top: "loss" }'
    )
    assert net.loss is not None
    assert net.loss.loss == npair_param_to_config(None)


def test_example_configs_parse():
    """Every shipped example prototxt must parse into a coherent config
    (examples mirror the BASELINE.json workloads)."""
    from npairloss_tpu.ops.npair_loss import MiningMethod, REFERENCE_CONFIG

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    nets = [os.path.join(repo, "examples", n) for n in (
        "tiny_net.prototxt", "googlenet_cub.prototxt",
        "resnet50_sop.prototxt", "resnet50_global_relhard.prototxt")]
    for path in nets:
        cfg = load_net(path)
        assert cfg.data.get("TRAIN") is not None, path
        assert cfg.loss is not None, path

    cub = load_net(os.path.join(repo, "examples", "googlenet_cub.prototxt"))
    assert cub.loss.loss.ap_mining_method == MiningMethod.RAND
    sop = load_net(os.path.join(repo, "examples", "resnet50_sop.prototxt"))
    assert sop.loss.loss.an_mining_method == MiningMethod.HARD
    assert sop.loss.loss.margin_diff == -0.05
    glob_cfg = load_net(
        os.path.join(repo, "examples", "resnet50_global_relhard.prototxt"))
    # the shipped def.prototxt mining config, verbatim semantics
    assert glob_cfg.loss.loss == type(REFERENCE_CONFIG)(
        **{**REFERENCE_CONFIG.__dict__}
    )

    solver_cfg, net_path = load_solver(
        os.path.join(repo, "examples", "googlenet_cub_solver.prototxt"))
    assert solver_cfg.stepsize == 10000 and solver_cfg.gamma == 0.5
    assert net_path.endswith("googlenet_cub.prototxt")


@needs_ref
def test_net_param_mults_from_reference_template():
    """The reference net trains conv biases at 2x lr with no decay
    (param blocks, usage/def.prototxt:90-97); the schema must surface
    that recipe so the solver reproduces the trajectory.  Needs the
    mounted reference tree like every other verbatim-usage test here
    (this one hard-coded the path and was the seed's standing red on
    boxes without /root/reference)."""
    from npairloss_tpu.config import load_net

    net = load_net(os.path.join(REF_USAGE, "def.prototxt"))
    assert net.param_mults == ((1.0, 1.0), (2.0, 0.0))


def test_param_mults_template_recipe_from_text():
    """The same recipe, reference-mount-free: the def.prototxt param
    blocks verbatim (w: lr 1/decay 1, b: lr 2/decay 0) must resolve to
    the net-wide multiplier tuple the solver trains under — keeps the
    template contract covered even where /root/reference is absent."""
    from npairloss_tpu.config import net_from_text

    net = net_from_text(
        'name: "GoogleNet"\n'
        'layer {\n'
        '  name: "conv1/7x7_s2" type: "Convolution"\n'
        '  param { lr_mult: 1 decay_mult: 1 }\n'
        '  param { lr_mult: 2 decay_mult: 0 }\n'
        '}\n'
        'layer {\n'
        '  name: "conv2/3x3" type: "Convolution"\n'
        '  param { lr_mult: 1 decay_mult: 1 }\n'
        '  param { lr_mult: 2 decay_mult: 0 }\n'
        '}\n'
    )
    assert net.param_mults == ((1.0, 1.0), (2.0, 0.0))


def test_net_param_mults_absent_without_blocks():
    from npairloss_tpu.config import net_from_text

    net = net_from_text('name: "X"\nlayer { name: "d" type: "ReLU" }\n')
    assert net.param_mults is None


CONFLICTING_MULTS_NET = '''
name: "X"
layer {
  name: "frozen" type: "Convolution"
  param { lr_mult: 0 decay_mult: 0 }
  param { lr_mult: 0 decay_mult: 0 }
}
layer {
  name: "head" type: "Convolution"
  param { lr_mult: 1 decay_mult: 1 }
  param { lr_mult: 2 decay_mult: 0 }
}
'''


def test_net_param_mults_conflict_recorded_not_raised():
    """Two layers declaring DIFFERENT recipes (e.g. frozen trunk +
    trainable head) cannot be honored net-wide — but a legitimate Caffe
    net using per-layer recipes must still LOAD for inference-only
    commands (test/extract/parse/eval), where multipliers are
    irrelevant.  Parse records the conflict; only training refuses."""
    from npairloss_tpu.config import net_from_text

    net = net_from_text(CONFLICTING_MULTS_NET)
    assert net.param_mults is None
    assert "conflicting" in net.param_mults_conflict
    assert "'head'" in net.param_mults_conflict


def test_net_param_mults_conflict_refuses_training(tmp_path):
    """cmd_train must fail loudly on the recorded conflict — training
    with the multipliers silently dropped would be a different
    trajectory than the net declares.  (Inference-only commands keep
    working: test_net_param_mults_conflict_recorded_not_raised.)"""
    import os

    from npairloss_tpu.cli import main

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "examples", "tiny_net.prototxt")) as f:
        tiny = f.read()
    net = tmp_path / "net.prototxt"
    net.write_text(tiny + CONFLICTING_MULTS_NET.split("\n", 2)[2])
    solver = tmp_path / "solver.prototxt"
    solver.write_text(
        f'net: "{net}"\nbase_lr: 0.001\nmax_iter: 1\n'
        'lr_policy: "fixed"\nsnapshot: 0\n')
    rc = main(["train", "--solver", str(solver), "--model", "mlp",
               "--max_iter", "1", "--synthetic"])
    assert rc == 2
