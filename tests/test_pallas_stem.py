"""Interpret-mode parity tests for the fused stem kernels
(ops.pallas_stem vs the XLA references) — forward AND backward, ragged
tile shapes included, plus the ConvBlock/GoogLeNet wiring contracts
(parameter-tree interchange with the plain path).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from npairloss_tpu.models.layers import ConvBlock, local_response_norm
from npairloss_tpu.ops import pallas_stem as ps

# Shapes chosen to hit: full lane tiles (64->128 pad), multi-lane-tile
# channels with a ragged edge (130), sub-tile channels (24), ragged row
# counts (odd H*W products), and a row count above one block (>256).
LRN_SHAPES = [
    (2, 7, 7, 24),
    (1, 5, 3, 64),
    (2, 3, 9, 130),
    (3, 10, 10, 8),  # 300 rows > one 256-row block
]


def _rand(shape, seed=0, dtype=np.float32):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal(shape).astype(dtype))


@pytest.mark.parametrize("shape", LRN_SHAPES)
def test_fused_lrn_forward_parity(shape):
    x = _rand(shape)
    ref = local_response_norm(x)
    for cache in (True, False):
        out = ps.fused_lrn(x, cache=cache)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6, rtol=1e-6)


@pytest.mark.parametrize("shape", LRN_SHAPES)
def test_fused_lrn_backward_parity_and_cache_bitparity(shape):
    x = _rand(shape, seed=1)
    w = jnp.cos(jnp.arange(x.size, dtype=jnp.float32).reshape(x.shape))
    g_ref = jax.grad(lambda v: (local_response_norm(v) * w).sum())(x)
    g_c = jax.grad(lambda v: (ps.fused_lrn(v, cache=True) * w).sum())(x)
    g_n = jax.grad(lambda v: (ps.fused_lrn(v, cache=False) * w).sum())(x)
    np.testing.assert_allclose(np.asarray(g_c), np.asarray(g_ref),
                               atol=1e-5, rtol=1e-4)
    # Cached and recompute backward are BIT-identical (the cache stores
    # exactly the fp32 d the forward produced — the sim-cache contract).
    np.testing.assert_array_equal(np.asarray(g_c), np.asarray(g_n))


def test_fused_lrn_generic_beta_and_params():
    """The non-0.75-beta path (exp/log pow) and non-default size/k."""
    x = _rand((2, 4, 4, 24), seed=2)
    ref = local_response_norm(x, size=3, alpha=2e-3, beta=0.5, k=2.0)
    out = ps.fused_lrn(x, size=3, alpha=2e-3, beta=0.5, k=2.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6, rtol=1e-5)


def test_fused_lrn_bf16_dtype_roundtrip():
    x = _rand((2, 4, 4, 32)).astype(jnp.bfloat16)
    out = ps.fused_lrn(x)
    assert out.dtype == jnp.bfloat16
    ref = local_response_norm(x)  # fp32 internals, bf16 out — same shape
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref, np.float32),
        atol=2e-2)


def test_lrn_cache_auto_threshold():
    assert ps.resolve_lrn_cache_auto(ps.LRN_CACHE_AUTO_BYTES, None)
    assert not ps.resolve_lrn_cache_auto(ps.LRN_CACHE_AUTO_BYTES + 1, None)
    assert ps.resolve_lrn_cache_auto(1 << 40, True)  # explicit wins
    assert not ps.resolve_lrn_cache_auto(1, False)


def test_fused_bias_relu_parity():
    x = _rand((2, 5, 5, 24), seed=3)
    b = _rand((24,), seed=4)
    ref = jnp.maximum(x + b, 0)
    np.testing.assert_allclose(np.asarray(ps.fused_bias_relu(x, b)),
                               np.asarray(ref), atol=1e-6)
    got = jax.grad(
        lambda xx, bb: (ps.fused_bias_relu(xx, bb) ** 2).sum(),
        argnums=(0, 1))(x, b)
    want = jax.grad(
        lambda xx, bb: (jnp.maximum(xx + bb, 0) ** 2).sum(),
        argnums=(0, 1))(x, b)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(got[1]), np.asarray(want[1]),
                               atol=1e-4, rtol=1e-5)


@pytest.mark.parametrize("hw", [(8, 8), (7, 9), (5, 5)])
def test_fused_bias_relu_pool_parity(hw):
    """SAME 3x3/s2 pool epilogue vs bias+relu+reduce_window, fwd + bwd,
    even and odd (ragged-pad) spatial sizes."""
    x = _rand((2, *hw, 24), seed=5)
    b = _rand((24,), seed=6)
    ref = ps._reference_bias_relu_pool(x, b, 3, 2)
    np.testing.assert_allclose(np.asarray(ps.fused_bias_relu_pool(x, b)),
                               np.asarray(ref), atol=1e-6)
    got = jax.grad(
        lambda xx: (ps.fused_bias_relu_pool(xx, b) ** 2).sum())(x)
    want = jax.grad(
        lambda xx: (ps._reference_bias_relu_pool(xx, b, 3, 2)
                    .astype(jnp.float32) ** 2).sum())(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)


# ---------------------------------------------------------------------------
# Model wiring: the fused path must interchange with the plain one
# ---------------------------------------------------------------------------


def test_convblock_fused_epilogue_param_tree_and_output():
    """fused_epilogue keeps the EXACT nn.Conv parameter tree
    (Conv_0/{kernel,bias}) and computes the same function; fuse_pool
    folds the SAME max-pool the caller would otherwise apply."""
    import jax.tree_util as jtu

    from npairloss_tpu.models.layers import max_pool

    x = _rand((2, 12, 12, 3), seed=7)
    key = jax.random.PRNGKey(0)
    plain = ConvBlock(16, (3, 3), (2, 2))
    fused = ConvBlock(16, (3, 3), (2, 2), fused_epilogue=True)
    pooled = ConvBlock(16, (3, 3), (2, 2), fused_epilogue=True,
                       fuse_pool=(3, 2))
    v = plain.init(key, x)
    paths = lambda t: [jtu.keystr(k) for k, _ in
                       jtu.tree_flatten_with_path(t)[0]]
    assert paths(fused.init(key, x)) == paths(v)
    o_plain = plain.apply(v, x)
    o_fused = fused.apply(v, x)
    np.testing.assert_allclose(np.asarray(o_fused), np.asarray(o_plain),
                               atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(pooled.apply(v, x)),
        np.asarray(max_pool(o_plain, 3, 2)), atol=1e-5)


def test_fused_epilogue_nonfp32_bias_cotangent_dtype():
    """custom_vjp requires db.dtype == bias.dtype: a policy rule may
    store a fused-stem conv's params in bf16, and the epilogue VJPs
    must return the bias cotangent in that dtype (a hardcoded fp32 db
    raised at trace time on the first training step)."""
    from npairloss_tpu.models.precision import PrecisionPolicy

    pol = PrecisionPolicy(
        name="bf16params", compute_dtype=jnp.bfloat16,
        rules=((r".*", {"param_dtype": jnp.bfloat16}),),
    )
    x = _rand((2, 8, 8, 3), seed=11)
    for fuse_pool in (None, (3, 2)):
        blk = ConvBlock(8, (3, 3), policy=pol, fused_epilogue=True,
                        fuse_pool=fuse_pool)
        v = blk.init(jax.random.PRNGKey(0), x)
        assert v["params"]["Conv_0"]["bias"].dtype == jnp.bfloat16
        g = jax.grad(
            lambda vv: blk.apply(vv, x).astype(jnp.float32).sum())(v)
        assert g["params"]["Conv_0"]["bias"].dtype == jnp.bfloat16


def test_convblock_fused_epilogue_ignored_under_bn():
    """BN trunks have neither conv bias nor an epilogue to fuse — the
    flag must be a no-op there, not an error."""
    x = _rand((2, 8, 8, 3), seed=8)
    bn = ConvBlock(8, (3, 3), use_bn=True, fused_epilogue=True)
    v = bn.init(jax.random.PRNGKey(0), x)
    ref = ConvBlock(8, (3, 3), use_bn=True)
    np.testing.assert_array_equal(
        np.asarray(bn.apply(v, x)), np.asarray(ref.apply(v, x)))


def test_local_response_norm_impl_routing():
    x = _rand((2, 4, 4, 16), seed=9)
    np.testing.assert_allclose(
        np.asarray(local_response_norm(x, impl="pallas")),
        np.asarray(local_response_norm(x)), atol=1e-6)
    with pytest.raises(ValueError, match="impl"):
        local_response_norm(x, impl="cuda")


@pytest.mark.slow
def test_googlenet_pallas_registry_interchange():
    """googlenet_pallas == googlenet_mxu trunk + pallas_stem: identical
    parameter tree, near-identical function on shared params (the
    fused-kernel wiring pin at trunk level).  Slow-marked: two
    GoogLeNet jits (~13s); the ConvBlock-level interchange test above
    plus the ci.sh pallas smoke keep the wiring covered in tier-1
    time."""
    import jax.tree_util as jtu

    from npairloss_tpu.models import get_model, jit_init

    x = _rand((2, 32, 32, 3), seed=10)
    key = jax.random.PRNGKey(0)
    m_mxu = get_model("googlenet_mxu", policy="mxu")
    m_pal = get_model("googlenet_pallas", policy="mxu")
    assert m_pal.pallas_stem and m_pal.stem_s2d and m_pal.fuse_1x1
    v = jit_init(m_mxu, key, x)
    paths = lambda t: [jtu.keystr(k) for k, _ in
                       jtu.tree_flatten_with_path(t)[0]]
    assert paths(jax.eval_shape(
        lambda: m_pal.init(key, x))) == paths(v)
    o_mxu = jax.jit(lambda v_, x_: m_mxu.apply(v_, x_))(v, x)
    o_pal = jax.jit(lambda v_, x_: m_pal.apply(v_, x_))(v, x)
    assert float(jnp.abs(o_pal - o_mxu).max()) < 2e-2
