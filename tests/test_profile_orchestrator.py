"""The profile orchestrator runs unattended on flaky hardware; pin its
contract: per-variant child isolation, artifact written after EVERY
variant, resume skips completed variants, a timeout costs one variant
(not the run), and measurement history (prior_runs) survives rewrites.
(Round-4 lesson: a single-process profile run wedged at variant 7 of 11
and lost six on-chip measurements — scripts/profile_flagship.py.)"""

import importlib.util
import json
import os
import subprocess
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def pf():
    spec = importlib.util.spec_from_file_location(
        "profile_flagship", os.path.join(REPO, "scripts",
                                         "profile_flagship.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _args(pf, artifact, **kw):
    return types.SimpleNamespace(
        steps=2, batch=8, image=32, artifact=str(artifact),
        variant_timeout=5, recover_wait=0, cpu=False, variant=None,
        inline=False, **kw,
    )


def _fake_run(fail=()):
    """subprocess.run stand-in: emits a child payload for the requested
    variant; raises TimeoutExpired for names in ``fail``."""

    def run(cmd, timeout=None, capture_output=None, text=None, **kw):
        name = cmd[cmd.index("--variant") + 1]
        if name in fail:
            raise subprocess.TimeoutExpired(cmd, timeout)
        payload = {
            "device": "fake", "batch": 8, "image": 32,
            "steps_per_timing": 2, "fetch_floor_ms": 1.0,
            "results": {name: {"ms_per_step": 1.5, "emb_per_sec": 5333.3}},
        }
        return types.SimpleNamespace(
            returncode=0, stdout=json.dumps(payload) + "\n", stderr="")

    return run


def test_orchestrator_full_run(pf, tmp_path, monkeypatch):
    monkeypatch.setattr(pf, "_tpu_ready", lambda timeout=100: True)
    monkeypatch.setattr(subprocess, "run", _fake_run())
    art = tmp_path / "p.json"
    rc = pf.orchestrate(_args(pf, art))
    assert rc == 0
    rec = json.loads(art.read_text())
    assert set(rec["results"]) == set(pf.VARIANT_ORDER)
    assert rec["device"] == "fake"


def test_orchestrator_timeout_costs_one_variant(pf, tmp_path, monkeypatch):
    monkeypatch.setattr(pf, "_tpu_ready", lambda timeout=100: True)
    monkeypatch.setattr(subprocess, "run", _fake_run(fail={"s2d"}))
    art = tmp_path / "p.json"
    rc = pf.orchestrate(_args(pf, art))
    assert rc == 4  # incomplete, but not dead
    rec = json.loads(art.read_text())
    assert "error" in rec["results"]["s2d"]
    done = [n for n in pf.VARIANT_ORDER if n != "s2d"]
    assert all("ms_per_step" in rec["results"][n] for n in done)


def test_orchestrator_resume_skips_completed(pf, tmp_path, monkeypatch):
    monkeypatch.setattr(pf, "_tpu_ready", lambda timeout=100: True)
    ran = []

    def spy(cmd, **kw):
        ran.append(cmd[cmd.index("--variant") + 1])
        return _fake_run()(cmd, **kw)

    art = tmp_path / "p.json"
    art.write_text(json.dumps({
        "device": "fake", "batch": 8, "image": 32, "steps_per_timing": 2,
        "fetch_floor_ms": 1.0,
        "results": {"full": {"ms_per_step": 9.9, "emb_per_sec": 808.1},
                    "s2d": {"error": "timeout"}},
        "prior_runs": [{"date": "earlier", "results": {}}],
    }))
    monkeypatch.setattr(subprocess, "run", spy)
    rc = pf.orchestrate(_args(pf, art))
    assert rc == 0
    rec = json.loads(art.read_text())
    assert "full" not in ran              # completed -> skipped
    assert "s2d" in ran                   # errored -> retried
    assert rec["results"]["full"]["ms_per_step"] == 9.9
    assert rec["prior_runs"][0]["date"] == "earlier"  # history preserved


def test_orchestrator_cpu_artifact_not_resumed(pf, tmp_path, monkeypatch):
    """A prior --cpu run with matching geometry must NOT satisfy the
    resume check — skipping its variants would silently publish CPU
    timings as the flagship TPU profile.  The CPU rows are demoted to
    prior_runs (history preserved), and every variant re-runs."""
    monkeypatch.setattr(pf, "_tpu_ready", lambda timeout=100: True)
    ran = []

    def spy(cmd, **kw):
        ran.append(cmd[cmd.index("--variant") + 1])
        return _fake_run()(cmd, **kw)

    art = tmp_path / "p.json"
    art.write_text(json.dumps({
        "device": "cpu", "batch": 8, "image": 32, "steps_per_timing": 2,
        "fetch_floor_ms": 1.0,
        "results": {"full": {"ms_per_step": 400.0, "emb_per_sec": 20.0}},
        "prior_runs": [{"date": "earlier", "results": {}}],
    }))
    monkeypatch.setattr(subprocess, "run", spy)
    rc = pf.orchestrate(_args(pf, art))
    assert rc == 0
    rec = json.loads(art.read_text())
    assert "full" in ran                  # CPU row did not count
    assert rec["results"]["full"]["ms_per_step"] == 1.5
    dates = [r.get("date") for r in rec["prior_runs"]]
    assert "earlier" in dates             # old history carried forward
    demoted = [r for r in rec["prior_runs"]
               if "superseded" in r.get("note", "")]
    assert demoted and demoted[0]["results"]["full"]["ms_per_step"] == 400.0


def test_orchestrator_geometry_mismatch_demotes_not_destroys(
        pf, tmp_path, monkeypatch):
    """Re-running the orchestrator at a different batch must not delete
    the previous geometry's measured rows — they demote to prior_runs
    (the never-destroy-history invariant, generalized past the CPU
    special case)."""
    monkeypatch.setattr(pf, "_tpu_ready", lambda timeout=100: True)
    monkeypatch.setattr(subprocess, "run", _fake_run())
    art = tmp_path / "p.json"
    art.write_text(json.dumps({
        "device": "fake", "batch": 120, "image": 224,
        "steps_per_timing": 2, "fetch_floor_ms": 1.0,
        "results": {"full": {"ms_per_step": 27.8, "emb_per_sec": 4316.5}},
    }))
    rc = pf.orchestrate(_args(pf, art))  # batch=8 != 120
    assert rc == 0
    rec = json.loads(art.read_text())
    assert rec["batch"] == 8
    assert rec["results"]["full"]["ms_per_step"] == 1.5
    demoted = [r for r in rec["prior_runs"]
               if "superseded" in r.get("note", "")]
    assert demoted and demoted[0]["results"]["full"]["ms_per_step"] == 27.8


def test_orchestrator_tunnel_down_fails_structured(pf, tmp_path,
                                                   monkeypatch):
    monkeypatch.setattr(pf, "_tpu_ready", lambda timeout=100: False)
    art = tmp_path / "p.json"
    rc = pf.orchestrate(_args(pf, art))
    assert rc == 3
    rec = json.loads(art.read_text())
    assert any("error" in v for v in rec["results"].values())


def test_orchestrator_wedge_shaped_timeout_not_retried(pf, tmp_path,
                                                       monkeypatch):
    """A variant timeout with the tunnel dead right after the kill is
    wedge-shaped: it is recorded with wedged=true and a resumed run must
    NOT retry it (a deterministic wedge would otherwise re-wedge every
    supervisor attempt — the round-4 googlenet_bn lesson).  A timeout
    with the tunnel still answering stays retryable (covered by
    test_orchestrator_resume_skips_completed)."""
    state = {"down_probes": 0}

    def fake_ready(timeout=100):
        if state["down_probes"] > 0:
            state["down_probes"] -= 1  # all handler re-probes fail...
            return False
        return True  # ...then the tunnel "recovers" for the next gate

    def run(cmd, timeout=None, **kw):
        name = cmd[cmd.index("--variant") + 1]
        if name == "s2d":
            state["down_probes"] = 3  # the kill leaves the tunnel dead
            raise subprocess.TimeoutExpired(cmd, timeout)
        return _fake_run()(cmd, timeout=timeout, **kw)

    monkeypatch.setattr(pf, "_tpu_ready", fake_ready)
    monkeypatch.setattr(pf.time, "sleep", lambda s: None)
    monkeypatch.setattr(subprocess, "run", run)
    art = tmp_path / "p.json"
    # Wedged variants are terminal, not retryable: rc reports "nothing
    # retryable left" (0), so a rc!=0-keyed supervisor cannot spin.
    rc = pf.orchestrate(_args(pf, art))
    assert rc == 0
    rec = json.loads(art.read_text())
    assert rec["results"]["s2d"]["wedged"] is True

    # Resume: every OTHER variant is complete; the wedged one is skipped.
    ran = []

    def spy(cmd, **kw):
        ran.append(cmd[cmd.index("--variant") + 1])
        return _fake_run()(cmd, **kw)

    monkeypatch.setattr(subprocess, "run", spy)
    rc = pf.orchestrate(_args(pf, art))
    assert rc == 0
    assert ran == []  # nothing pending: completed skipped, wedged skipped
    assert json.loads(art.read_text())["results"]["s2d"]["wedged"] is True


def test_orchestrator_transient_probe_failure_stays_retryable(
        pf, tmp_path, monkeypatch):
    """A timeout whose post-kill probe fails ONCE then answers is a slow
    variant on a briefly-saturated tunnel, not a wedge — it must stay
    retryable."""
    state = {"down_probes": 0}

    def fake_ready(timeout=100):
        if state["down_probes"] > 0:
            state["down_probes"] -= 1
            return False
        return True

    def run(cmd, timeout=None, **kw):
        name = cmd[cmd.index("--variant") + 1]
        if name == "s2d":
            state["down_probes"] = 1  # only the first re-probe fails
            raise subprocess.TimeoutExpired(cmd, timeout)
        return _fake_run()(cmd, timeout=timeout, **kw)

    monkeypatch.setattr(pf, "_tpu_ready", fake_ready)
    monkeypatch.setattr(pf.time, "sleep", lambda s: None)
    monkeypatch.setattr(subprocess, "run", run)
    art = tmp_path / "p.json"
    rc = pf.orchestrate(_args(pf, art))
    assert rc == 4  # retryable work remains
    rec = json.loads(art.read_text())
    assert "wedged" not in rec["results"]["s2d"]
    assert "error" in rec["results"]["s2d"]
