"""Fused Pallas IVF probe kernel (ops/pallas_ivf.py) — ISSUE 19.

Load-bearing pins:
  * the fused gather+score+running-top-k kernel matches the lax.scan
    baseline to 1e-6 scores — across fp32/bf16/int8 (the int8 dequant
    happens INSIDE the kernel), ragged cluster tails, empty clusters,
    and ``probes > n_clusters`` — exercised in Pallas interpret mode so
    tier-1 proves the kernel without TPU hardware;
  * the probe-impl registry is the single vocabulary: the CLI flag
    choices pin to it (the staticcheck vocab pass holds the same pin),
    ``auto`` resolves per platform, and the fused/scan choice is part
    of the engine's compile signature;
  * the serving tier carries the choice end to end: /healthz stamps the
    RESOLVED impl (absent on flat tiers), ``swap_engines`` preserves it
    (hot-swap rebuilds from the old EngineConfig), a replica crash on a
    fused tier stays client-invisible, and the qtrace ``probe_fused``
    span validates under the unchanged npairloss-qtrace-v1 vocabulary.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from npairloss_tpu.ops.pallas_ivf import (
    CAP_ALIGN,
    PROBE_IMPLS,
    fused_probe_topk,
    probe_dispatch_count,
    resolve_probe_impl,
)
from npairloss_tpu.parallel.mesh import data_parallel_mesh
from npairloss_tpu.resilience import failpoints
from npairloss_tpu.serve import (
    BatcherConfig,
    EngineConfig,
    GalleryIndex,
    QueryEngine,
    RetrievalServer,
    ServerConfig,
)
from npairloss_tpu.serve.engine import _finalize_topk, _ivf_probe_topk
from npairloss_tpu.serve.ivf import (
    SCORINGS,
    IVFIndex,
    _quantize_int8,
    topk_recall,
)

ATOL = 1e-6  # the acceptance gate: fused == scan to 1e-6 scores


# -- registry / resolution ----------------------------------------------------


def test_probe_impl_registry_pins_cli_choices():
    """CLI flag vocabulary == the registry (the _PRECISION_CHOICES
    pattern; the staticcheck vocab pass holds the same pin), and the
    registry declares the 4 -> 2 dispatch-count drop the bench rows
    stamp."""
    from npairloss_tpu.cli import _PROBE_IMPL_CHOICES

    assert set(_PROBE_IMPL_CHOICES) == set(PROBE_IMPLS)
    assert PROBE_IMPLS["scan"]["dispatch_count"] == 4
    assert PROBE_IMPLS["fused"]["dispatch_count"] <= 2
    assert PROBE_IMPLS["fused"]["pallas"] is True


def test_resolve_probe_impl_per_platform():
    """Explicit choices pass through; ``auto`` picks the kernel only
    where Mosaic compiles it (interpret emulation is a parity harness,
    not a serving path)."""
    assert resolve_probe_impl("scan") == "scan"
    assert resolve_probe_impl("fused", platform="cpu") == "fused"
    assert resolve_probe_impl("auto", platform="tpu") == "fused"
    assert resolve_probe_impl("auto", platform="cpu") == "scan"
    assert resolve_probe_impl("auto", platform="gpu") == "scan"
    assert probe_dispatch_count("auto", platform="tpu") <= 2
    assert probe_dispatch_count("scan") == 4
    with pytest.raises(ValueError, match="probe_impl"):
        resolve_probe_impl("vectorized")


def test_engine_config_validates_probe_impl():
    with pytest.raises(ValueError, match="probe_impl"):
        EngineConfig(probe_impl="fast")
    assert EngineConfig(probe_impl="fused").probe_impl == "fused"


# -- kernel-level parity matrix ----------------------------------------------


def _layout(rng, kc, cap, d, empty=()):
    """Hand-built packed layout with ragged per-cluster fills and the
    given clusters forced EMPTY (cvalid False, all rows -1)."""
    packed = rng.standard_normal((kc, cap, d)).astype(np.float32)
    rows = np.arange(kc * cap, dtype=np.int32).reshape(kc, cap)
    for ci in range(kc):  # ragged tails
        fill = int(rng.integers(1, cap + 1))
        rows[ci, fill:] = -1
        packed[ci, fill:] = 0.0
    cvalid = np.ones(kc, bool)
    for ci in empty:
        rows[ci, :] = -1
        packed[ci] = 0.0
        cvalid[ci] = False
    cents = rng.standard_normal((kc, d)).astype(np.float32)
    return (jnp.asarray(packed), jnp.asarray(rows), jnp.asarray(cents),
            jnp.asarray(cvalid))


@pytest.mark.parametrize("scoring", SCORINGS)
@pytest.mark.parametrize(
    "kc,cap,d,probes,k,empty",
    [
        (7, 11, 24, 3, 5, (2,)),      # ragged + one empty cluster
        (7, 11, 24, 12, 10, (2, 5)),  # probes > n_clusters
        (4, 6, 130, 2, 40, ()),       # kl < k (probe set too small)
    ],
)
def test_fused_matches_scan_probe(rng, scoring, kc, cap, d, probes, k,
                                  empty):
    """The parity gate, kernel level: same probe set, 1e-6 scores, and
    identical finalized answers against the scan baseline — unaligned
    cap/D exercise the in-call tile re-pad."""
    packed, rows, cents, cvalid = _layout(rng, kc, cap, d, empty)
    q = jnp.asarray(rng.standard_normal((5, d)).astype(np.float32))
    scale = None
    if scoring == "bf16":
        packed = packed.astype(jnp.bfloat16)
    elif scoring == "int8":
        packed, scale = _quantize_int8(packed)
    kw = dict(k=k, probes=probes, scoring=scoring, g0=0)
    s0, r0 = _ivf_probe_topk(q, packed, rows, cents, cvalid, scale, **kw)
    s1, r1 = fused_probe_topk(q, packed, rows, cents, cvalid, scale, **kw)
    assert s1.shape == s0.shape and r1.shape == r0.shape
    # 1e-6 agreement RELATIVE to the score scale: these raw dots reach
    # O(10), so fp32 reduction-order noise scales with |score|.
    ref = np.asarray(s0)
    tol = ATOL * max(1.0, float(np.abs(ref[ref > -1e30]).max()))
    np.testing.assert_allclose(np.asarray(s1), ref, rtol=ATOL, atol=tol)
    f0s, f0r = _finalize_topk(s0, r0, k)
    f1s, f1r = _finalize_topk(s1, r1, k)
    np.testing.assert_allclose(np.asarray(f1s), np.asarray(f0s),
                               rtol=ATOL, atol=tol)
    # Identical answers wherever the scores are distinct; equal-score
    # rows must still be drawn from the same candidate multiset.
    same = np.asarray(f1r) == np.asarray(f0r)
    ties = np.isclose(np.asarray(f1s), np.asarray(f0s), atol=tol)
    assert np.all(same | ties)


# -- engine-level parity + recall --------------------------------------------


def _clustered(rng, n_clusters=12, per=30, dim=24):
    centers = rng.standard_normal((n_clusters, dim)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    emb = np.repeat(centers, per, axis=0) + 0.1 * rng.standard_normal(
        (n_clusters * per, dim)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    lab = np.repeat(np.arange(n_clusters), per).astype(np.int32)
    return emb, lab


def test_engine_fused_recall_matches_scan(rng):
    """Engine level, all three scorings on ONE index: fused and scan
    answer with 1e-6-equal scores and IDENTICAL recall@{1,10} against
    the brute-force oracle — the ISSUE 19 acceptance gate."""
    emb, lab = _clustered(rng)
    q = emb[rng.choice(emb.shape[0], 16, replace=False)]
    flat = GalleryIndex.build(emb, lab, normalize=False)
    oracle = QueryEngine(flat, EngineConfig(top_k=10, buckets=(16,)))
    exact = oracle.query(q, normalize=False)["rows"]
    ivf = IVFIndex.build_ivf(emb, lab, normalize=False, clusters=8,
                             train_size=None)
    for scoring in SCORINGS:
        outs = {}
        for impl in ("scan", "fused"):
            eng = QueryEngine(ivf, EngineConfig(
                top_k=10, buckets=(16,), probes=4, scoring=scoring,
                probe_impl=impl))
            assert eng.probe_impl == impl
            outs[impl] = eng.query(q, normalize=False)
        np.testing.assert_allclose(
            outs["fused"]["scores"], outs["scan"]["scores"],
            rtol=ATOL, atol=ATOL, err_msg=scoring)
        for k in (1, 10):
            assert topk_recall(outs["fused"]["rows"], exact, k=k) == \
                topk_recall(outs["scan"]["rows"], exact, k=k), \
                (scoring, k)


def test_cap_is_tile_aligned_after_build_and_add(rng):
    """IVFIndex._place pads cap to the kernel's sublane alignment so
    the fused path's per-dispatch re-pad is a no-op at any geometry —
    and add()'s republish keeps the property."""
    emb, lab = _clustered(rng, n_clusters=6, per=21)
    ivf = IVFIndex.build_ivf(emb, lab, normalize=False, clusters=5,
                             train_size=None)
    assert ivf.layout.cap % CAP_ALIGN == 0
    assert ivf.layout.packed.shape[1] == ivf.layout.cap
    ivf.add(emb[:3], lab[:3], normalize=False)
    assert ivf.layout.cap % CAP_ALIGN == 0


def test_probe_impl_is_part_of_the_compile_signature(rng):
    """scan and fused programs are DIFFERENT jit signatures: the
    compile accounting (and the strict guard) must see an impl flip as
    a counted compile, never a silent cache alias."""
    emb, lab = _clustered(rng, n_clusters=6, per=20)
    ivf = IVFIndex.build_ivf(emb, lab, normalize=False, clusters=4,
                             train_size=None)
    sigs = set()
    for impl in ("scan", "fused"):
        eng = QueryEngine(ivf, EngineConfig(top_k=3, buckets=(4,),
                                            probe_impl=impl))
        _, sig = eng._topk_call(4)
        sigs.add(sig)
    assert len(sigs) == 2


@pytest.mark.parametrize("scoring", ["fp32", "int8"])
def test_mesh_fused_matches_scan(rng, scoring):
    """Sharded fused probe (pallas_call inside shard_map, traced shard
    offset g0, REP_CHECK_OFF) answers exactly like the sharded scan."""
    mesh = data_parallel_mesh(jax.devices()[:4])
    emb, lab = _clustered(rng, n_clusters=10, per=32, dim=32)
    ivf = IVFIndex.build_ivf(emb, lab, mesh=mesh, normalize=False,
                             clusters=8, train_size=None)
    q = emb[rng.choice(emb.shape[0], 8, replace=False)]
    outs = {}
    for impl in ("scan", "fused"):
        eng = QueryEngine(ivf, EngineConfig(
            top_k=5, buckets=(8,), probes=4, scoring=scoring,
            probe_impl=impl))
        outs[impl] = eng.query(q, normalize=False)
    np.testing.assert_allclose(outs["fused"]["scores"],
                               outs["scan"]["scores"],
                               rtol=ATOL, atol=ATOL)


# -- serving tier: healthz / hot-swap / chaos --------------------------------


def _fused_tier(rng, n_replicas=2):
    emb, lab = _clustered(rng)
    ivf = IVFIndex.build_ivf(emb, lab, normalize=False, clusters=6,
                             train_size=None)
    cfg = EngineConfig(top_k=3, buckets=(1, 4), probes=3,
                       probe_impl="fused")
    primary = QueryEngine(ivf, cfg)
    engines = [primary] + [
        QueryEngine(ivf, cfg, share_compiled_with=primary)
        for _ in range(n_replicas - 1)
    ]
    primary.warmup()
    for e in engines[1:]:
        e.warmed = True
    server = RetrievalServer(
        engines,
        BatcherConfig(max_batch=4, max_delay_ms=1.0, max_queue=64),
        ServerConfig(metrics_window=0),
    )
    return emb, lab, server


def test_healthz_stamps_resolved_probe_impl(rng):
    """/healthz carries the RESOLVED impl on an IVF tier and stays
    shape-identical (key absent) on a flat tier — the absent-when-off
    freshness-JSON contract."""
    emb, lab, server = _fused_tier(rng, n_replicas=1)
    assert server.healthz()["probe_impl"] == "fused"
    flat = GalleryIndex.build(emb, lab, normalize=False)
    eng = QueryEngine(flat, EngineConfig(top_k=3, buckets=(1, 4)))
    eng.warmup()
    flat_server = RetrievalServer(
        [eng], BatcherConfig(max_batch=4, max_delay_ms=1.0),
        ServerConfig(metrics_window=0))
    assert "probe_impl" not in flat_server.healthz()


def test_hot_swap_preserves_probe_impl(rng):
    """swap_engines with a tier rebuilt from the OLD EngineConfig (the
    SnapshotSwapper recipe) keeps serving the fused path: /healthz
    stamps 'fused' after the flip and the swapped tier still answers."""
    from npairloss_tpu.serve.server import Freshness

    emb, lab, server = _fused_tier(rng)
    server.replicaset.start()
    try:
        assert server.healthz()["probe_impl"] == "fused"
        old = server.engine
        new_index = IVFIndex.build_ivf(emb, lab, normalize=False,
                                       clusters=6, train_size=None)
        primary = QueryEngine(new_index, old.cfg)
        warm = primary.warmup()
        assert warm >= 0.0
        replica = QueryEngine(new_index, old.cfg,
                              share_compiled_with=primary)
        replica.warmed = True
        server.swap_engines([primary, replica],
                            Freshness.collect(index=new_index))
        assert server.engine.probe_impl == "fused"
        assert server.healthz()["probe_impl"] == "fused"
        a = server.handle({"id": 1, "embedding": emb[1].tolist()})
        assert "neighbors" in a
    finally:
        server.replicaset.close(drain=True)


def test_replica_crash_on_fused_tier_zero_client_errors(rng):
    """The gameday chaos leg on the fused path: kill one of two fused
    replicas mid-burst — the tier reroutes with zero client-visible
    errors, the accounting invariant holds, and /healthz still stamps
    the fused impl on the surviving tier."""
    emb, lab, server = _fused_tier(rng, n_replicas=2)
    server.replicaset.start()
    try:
        failpoints.arm("serve.replica_crash", times=1)
        answers = server.handle_many(
            [{"id": i, "embedding": emb[i].tolist()} for i in range(16)],
            timeout=60.0,
        )
        assert server.replicaset.alive_count == 1
    finally:
        failpoints.reset()
        server.replicaset.close(drain=True)
    assert all("neighbors" in a for a in answers)
    s = server.summary()
    assert s["errors"] == 0
    assert s["queries"] == s["answered"] + s["errors"] + s["rejected"]
    assert server.healthz()["probe_impl"] == "fused"


# -- qtrace: the probe_fused span --------------------------------------------


class _SeededClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _traced_query(fused):
    from npairloss_tpu.obs.qtrace import QTraceConfig, QueryTracer

    clk = _SeededClock()
    tr = QueryTracer(QTraceConfig(exemplars=4, slo_ms=100.0),
                     clock=clk, wall=lambda: 1000.0 + clk.t)
    qt = tr.begin("q1")
    clk.advance(0.001)
    tr.admitted(qt)
    clk.advance(0.002)
    tr.picked(qt)
    clk.advance(0.003)
    tr.dispatch_begin([qt], replica="r0")
    clk.advance(0.010)
    tr.dispatch_end([qt], score_us=4000.0, merge_us=1000.0, fused=fused)
    tr.finish(qt)
    return tr.report()


@pytest.mark.parametrize("fused", [False, True])
def test_probe_fused_span_validates_and_nests(fused):
    """dispatch_end(fused=True) wraps the score/topk_merge clocks in
    ONE probe_fused span that validates under the v1 contract (stage
    vocabulary unchanged — scan artifacts carry no such span)."""
    from npairloss_tpu.obs.qtrace import STAGES, validate_qtrace_report
    from npairloss_tpu.obs.qtrace.report import PROBE_FUSED_SPAN

    rep = _traced_query(fused)
    assert validate_qtrace_report(rep) is None
    assert tuple(rep["stages"]) == STAGES  # vocabulary untouched
    (ex,) = rep["exemplars"]
    spans = {e["name"]: e for e in ex["events"]}
    if not fused:
        assert PROBE_FUSED_SPAN not in spans
        return
    pf = spans[PROBE_FUSED_SPAN]
    score = spans["qtrace/score"]
    merge = spans["qtrace/topk_merge"]
    disp = spans["qtrace/dispatch"]
    # probe_fused covers exactly score+merge and nests inside dispatch.
    assert pf["dur"] == pytest.approx(score["dur"] + merge["dur"])
    assert pf["ts"] == pytest.approx(score["ts"])
    assert pf["ts"] >= disp["ts"] - 2.0
    assert pf["ts"] + pf["dur"] <= disp["ts"] + disp["dur"] + 2.0
    # stage_us decomposition is impl-agnostic: score/topk_merge budgets
    # survive unchanged.
    assert rep["budget"]["worst_mean_ms"]["score"] == pytest.approx(4.0)
    assert rep["budget"]["worst_mean_ms"]["topk_merge"] == \
        pytest.approx(1.0)
