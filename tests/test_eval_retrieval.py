"""Full-gallery Recall@K (ops.eval_retrieval) vs a NumPy brute force.

The offline protocol is membership-in-top-K over cosine similarity with
the self excluded (what papers report for the reference's datasets) —
distinct by design from the in-training reference-quirk metric
(ops.metrics.recall_at_k); both semantics are pinned here.
"""

import json
import subprocess
import sys

import numpy as np
import pytest

from npairloss_tpu.ops.eval_retrieval import (
    evaluate_embeddings,
    gallery_recall_at_k,
)


def brute_force(emb, labels, ks):
    emb = emb / np.linalg.norm(emb, axis=1, keepdims=True)
    sims = emb @ emb.T
    np.fill_diagonal(sims, -np.inf)
    n = emb.shape[0]
    out = {}
    order = np.argsort(-sims, axis=1, kind="stable")
    for k in ks:
        kk = min(k, n - 1)
        hit = 0
        for q in range(n):
            top = order[q, :kk]
            hit += bool(np.any(labels[top] == labels[q]))
        out[f"recall_at_{kk}"] = hit / n
    return out


def make_clusters(rng, ids, per_id, dim, noise):
    centers = rng.standard_normal((ids, dim))
    labels = np.repeat(np.arange(ids), per_id)
    emb = centers[labels] + noise * rng.standard_normal(
        (ids * per_id, dim)
    )
    return emb.astype(np.float32), labels.astype(np.int32)


@pytest.mark.parametrize("noise", [0.1, 1.0, 3.0])
def test_matches_brute_force(noise):
    rng = np.random.default_rng(0)
    emb, labels = make_clusters(rng, ids=13, per_id=4, dim=16, noise=noise)
    ks = (1, 2, 4, 8)
    got = evaluate_embeddings(emb, labels, ks=ks, query_block=16)
    want = brute_force(emb, labels, ks)
    for k in ks:
        assert got[f"recall_at_{k}"] == pytest.approx(
            want[f"recall_at_{k}"], abs=1e-6
        ), k


def test_block_edges_and_overlap():
    """N not divisible by the block, block > N, and block == N must all
    agree (the clamped final block overlaps; dedup must be exact)."""
    rng = np.random.default_rng(1)
    emb, labels = make_clusters(rng, ids=9, per_id=3, dim=8, noise=0.8)
    ks = (1, 4)
    ref = evaluate_embeddings(emb, labels, ks=ks, query_block=27)
    for qb in (4, 5, 26, 27, 64):
        got = evaluate_embeddings(emb, labels, ks=ks, query_block=qb)
        assert got == pytest.approx(ref), qb


def test_k_clamped_to_gallery():
    rng = np.random.default_rng(2)
    emb, labels = make_clusters(rng, ids=3, per_id=2, dim=4, noise=0.5)
    out = evaluate_embeddings(emb, labels, ks=(100,))
    # k=100 > N-1=5 clamps to 5: every query has a same-id partner among
    # ALL other items, so recall is exactly 1.
    assert out == {"recall_at_5": 1.0}


def test_separable_clusters_reach_one_at_k1():
    rng = np.random.default_rng(3)
    emb, labels = make_clusters(rng, ids=8, per_id=4, dim=32, noise=0.05)
    out = evaluate_embeddings(emb, labels, ks=(1,))
    assert out["recall_at_1"] == 1.0


def test_prenormalized_path_matches():
    rng = np.random.default_rng(4)
    emb, labels = make_clusters(rng, ids=6, per_id=3, dim=8, noise=0.7)
    unit = emb / np.linalg.norm(emb, axis=1, keepdims=True)
    a = gallery_recall_at_k(unit, labels, ks=(1, 2), normalize=False)
    b = gallery_recall_at_k(emb, labels, ks=(1, 2), normalize=True)
    for k in ("recall_at_1", "recall_at_2"):
        assert float(a[k]) == pytest.approx(float(b[k]), abs=1e-6)


def test_cli_eval_roundtrip(tmp_path):
    rng = np.random.default_rng(5)
    emb, labels = make_clusters(rng, ids=5, per_id=3, dim=8, noise=0.3)
    np.save(tmp_path / "f.emb.npy", emb)
    np.save(tmp_path / "f.labels.npy", labels)
    proc = subprocess.run(
        [sys.executable, "-m", "npairloss_tpu", "--platform", "cpu",
         "eval", "--prefix", str(tmp_path / "f"), "--ks", "1", "4"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["gallery_size"] == 15 and rec["classes"] == 5
    want = brute_force(emb, labels, (1, 4))
    assert rec["recall_at_1"] == pytest.approx(
        want["recall_at_1"], abs=1e-4
    )
    assert rec["recall_at_4"] == pytest.approx(
        want["recall_at_4"], abs=1e-4
    )


def test_nmi_known_values():
    from npairloss_tpu.ops.eval_retrieval import nmi

    a = np.asarray([0, 0, 1, 1, 2, 2])
    # identical partitions (relabeled) -> 1
    assert nmi(a, a + 7) == pytest.approx(1.0)
    # independent partitions -> 0 for this balanced crossing
    b = np.asarray([0, 1, 0, 1, 0, 1])
    assert nmi(a, b) == pytest.approx(0.0, abs=1e-12)
    # hand-computed asymmetric case: clusters {0,0,1}, classes {0,1,1}
    # I = sum p log(p/(pa pb)); H_a = H_b = entropy([1/3, 2/3])
    pa = np.asarray([2 / 3, 1 / 3])
    h = float(-(pa * np.log(pa)).sum())
    # joint: (0,0)=1/3, (0,1)=1/3, (1,1)=1/3
    i = (
        1 / 3 * np.log((1 / 3) / (2 / 3 * 1 / 3))
        + 1 / 3 * np.log((1 / 3) / (2 / 3 * 2 / 3))
        + 1 / 3 * np.log((1 / 3) / (1 / 3 * 2 / 3))
    )
    want = 2 * i / (2 * h)
    assert nmi(np.asarray([0, 0, 1]), np.asarray([0, 1, 1])) == (
        pytest.approx(want)
    )


def test_clustering_nmi_separable_and_mixed():
    from npairloss_tpu.ops.eval_retrieval import clustering_nmi

    rng = np.random.default_rng(6)
    emb, labels = make_clusters(rng, ids=6, per_id=8, dim=16, noise=0.05)
    assert clustering_nmi(emb, labels) == pytest.approx(1.0)
    # pure noise: NMI near 0 (kmeans finds structureless clusters)
    noise_emb = rng.standard_normal((48, 16)).astype(np.float32)
    assert clustering_nmi(noise_emb, labels) < 0.45


def test_cli_eval_nmi_flag(tmp_path):
    rng = np.random.default_rng(7)
    emb, labels = make_clusters(rng, ids=5, per_id=4, dim=8, noise=0.1)
    np.save(tmp_path / "f.emb.npy", emb)
    np.save(tmp_path / "f.labels.npy", labels)
    proc = subprocess.run(
        [sys.executable, "-m", "npairloss_tpu", "--platform", "cpu",
         "eval", "--prefix", str(tmp_path / "f"), "--ks", "1", "--nmi"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["nmi"] == pytest.approx(1.0, abs=0.05)
