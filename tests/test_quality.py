"""Quality observatory tests (obs/quality + its serve/train wiring,
docs/OBSERVABILITY.md §Quality observatory): deterministic shadow
sampling, latency-invariant (never-blocking) shadow scoring, recall
math vs hand fixtures, the npairloss-quality-v1 validator's teeth, the
recall-floor watchdog's fire/clear hysteresis, the probe-escalation
remediation lifecycle incl. the budget-exhausted flat fallback, the
serve.recall_drop failpoint, the IVF parity birth certificate, the
jax-free bench_check --quality gate, the watch surfacing, and the
mining-health row-key byte-parity pin."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from npairloss_tpu.obs.quality.report import (
    QUALITY_SCHEMA,
    load_quality_report,
    quality_breaches,
    quality_summary,
    stale_shadow,
    validate_quality_report,
)
from npairloss_tpu.obs.quality.shadow import (
    ShadowConfig,
    ShadowScorer,
    recall_against,
    shadow_sampled,
)
from npairloss_tpu.resilience import failpoints

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_CHECK = os.path.join(REPO, "scripts", "bench_check.py")


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


def _unit_rows(rng, n, d):
    x = rng.standard_normal((n, d)).astype(np.float32)
    return x / np.linalg.norm(x, axis=1, keepdims=True)


@pytest.fixture(scope="module")
def tiny_ivf():
    """One 64x16 IVF index (4 clusters) shared by the jax-touching
    tests — engines built per-test, the index is immutable here."""
    from npairloss_tpu.serve.ivf import IVFIndex

    rng = np.random.default_rng(0)
    emb = _unit_rows(rng, 64, 16)
    lab = (np.arange(64) % 8).astype(np.int32)
    return emb, IVFIndex.build_ivf(emb, lab, clusters=4, seed=0)


# -- deterministic sampling ---------------------------------------------------


def test_shadow_sampling_deterministic():
    ids = list(range(500)) + ["q-%d" % i for i in range(100)] + [None]
    set_a = {i for i in ids if shadow_sampled(i, 0.3, seed=0)}
    set_b = {i for i in ids if shadow_sampled(i, 0.3, seed=0)}
    assert set_a == set_b  # same seed => same shadow set
    set_c = {i for i in ids if shadow_sampled(i, 0.3, seed=1)}
    assert set_a != set_c  # a different seed selects differently
    # the rate is roughly honored and the extremes are exact
    assert 0.15 < len(set_a) / len(ids) < 0.45
    assert not any(shadow_sampled(i, 0.0, seed=0) for i in ids)
    assert all(shadow_sampled(i, 1.0, seed=0) for i in ids)


def test_shadow_config_validates():
    with pytest.raises(ValueError, match="rate"):
        ShadowConfig(rate=0.0)
    with pytest.raises(ValueError, match="rate"):
        ShadowConfig(rate=1.5)
    with pytest.raises(ValueError, match="ks"):
        ShadowConfig(rate=0.5, ks=(5, 1))
    with pytest.raises(ValueError, match="window"):
        ShadowConfig(rate=0.5, window=0)


# -- recall math --------------------------------------------------------------


def test_recall_math_hand_fixtures():
    exact = [10, 20, 30, 40, 50]
    assert recall_against([10, 20, 30, 40, 50], exact, 5) == 1.0
    assert recall_against([10, 20, 99, 98, 97], exact, 5) == 0.4
    assert recall_against([99, 98, 97, 96, 95], exact, 5) == 0.0
    # @1 only compares the heads
    assert recall_against([10, 99], exact, 1) == 1.0
    assert recall_against([20, 10], exact, 1) == 0.0
    # order within the top-K never matters — it is set overlap
    assert recall_against([50, 40, 30, 20, 10], exact, 5) == 1.0


# -- the npairloss-quality-v1 validator ---------------------------------------


def _config(**over):
    return {"schema": QUALITY_SCHEMA, "kind": "config",
            "shadow_rate": 0.5, "seed": 0, "ks": [1, 5], "window": 4,
            "wall_time": 100.0, "stale_after_s": 30.0, **over}


def _window(t=101.0, total=4, r1=1.0, r5=1.0, **over):
    return {"schema": QUALITY_SCHEMA, "kind": "window", "wall_time": t,
            "samples": 4, "sampled_total": total, "recall_at_1": r1,
            "recall_at_5": r5, "score_gap_mean": 0.0,
            "score_gap_max": 0.01, **over}


def _summary(t=110.0, total=4, windows=1, last=101.0, **over):
    return {"schema": QUALITY_SCHEMA, "kind": "summary", "wall_time": t,
            "sampled_total": total, "windows": windows, "dropped": 0,
            "last_sample_wall_time": last, **over}


def test_quality_validator_accepts_good_stream():
    recs = [_config(), _window(), _window(t=102.0, total=8),
            _summary(total=8, windows=2, last=102.0)]
    assert validate_quality_report(recs) is None
    s = quality_summary(recs)
    assert s["windows"] == 2 and s["sampled_total"] == 8


def test_quality_validator_teeth():
    cases = [
        ([], "empty"),
        ([_window()], "record 0 must be the config"),
        ([_config(schema="npairloss-quality-v0")], "schema must be"),
        ([_config(), _config(wall_time=101.0)], "duplicate config"),
        ([_config(shadow_rate=0.0)], "shadow_rate"),
        ([_config(ks=[5, 1])], "ks must be"),
        ([_config(ks=[])], "ks must be"),
        ([_config(recall_floor=0.9)], "floor_metric"),
        ([_config(recall_floor=1.5,
                  floor_metric="serve_recall_at_5")], "recall_floor"),
        ([_config(), _window(r1=1.2)], "recall_at_1"),
        ([_config(), {k: v for k, v in _window().items()
                      if k != "recall_at_5"}], "recall_at_5"),
        ([_config(), _window(score_gap_mean=-0.1)], "score gaps"),
        ([_config(), _window(score_gap_mean=0.5,
                             score_gap_max=0.1)], "score_gap_max"),
        ([_config(), _window(total=8), _window(t=102.0, total=4)],
         "regressed"),
        ([_config(), _window(t=99.0)], "precedes"),
        ([_config(), _window(), _summary(windows=2)], "window(s)"),
        ([_config(), _window(), _summary(), _window(t=120.0)],
         "after the summary"),
        ([_config(), _window(),
          {k: v for k, v in _summary().items()
           if k != "last_sample_wall_time"}], "last_sample_wall_time"),
        ([_config(), {"_bad_line": 2}], "unparseable"),
        (["nope"], "not an object"),
    ]
    for recs, needle in cases:
        err = validate_quality_report(recs)
        assert err is not None and needle in err, (recs, err, needle)


def test_quality_breaches_and_stale():
    cfg = _config(recall_floor=0.9, floor_metric="serve_recall_at_5")
    good = [cfg, _window(), _summary()]
    assert validate_quality_report(good) is None
    assert quality_breaches(good) == []
    breach = [cfg, _window(r5=0.5), _window(t=102.0, total=8, r5=0.95),
              _summary(total=8, windows=2, last=102.0)]
    assert validate_quality_report(breach) is None
    hits = quality_breaches(breach)
    assert len(hits) == 1 and hits[0][1] == "serve_recall_at_5"
    assert hits[0][2] == 0.5 and hits[0][3] == 0.9
    # no declared floor -> nothing to breach
    assert quality_breaches([_config(), _window(r5=0.0)]) == []
    # stale: the summary drains 40s after the last sample (> 30s)
    stale = [_config(), _window(),
             _summary(t=141.0, last=101.0)]
    assert validate_quality_report(stale) is None
    assert "silent" in stale_shadow(stale)
    assert stale_shadow(good) is None
    # shadowing on but NOTHING ever sampled for longer than the bound
    empty = [_config(), {"schema": QUALITY_SCHEMA, "kind": "summary",
                         "wall_time": 140.0, "sampled_total": 0,
                         "windows": 0, "dropped": 0}]
    assert validate_quality_report(empty) is None
    assert "NOTHING" in stale_shadow(empty)
    # offer-side evidence disambiguates (the false-positive fix): a
    # drain long after the last QUERY is healthy idleness, not a wedge
    idle = [_config(), _window(),
            _summary(t=500.0, last=101.0,
                     offered_total=4, last_offer_wall_time=101.0)]
    assert validate_quality_report(idle) is None
    assert stale_shadow(idle) is None
    # ...but offers outrunning the last scored sample IS a wedge
    wedged = [_config(), _window(),
              _summary(t=500.0, last=101.0,
                       offered_total=400, last_offer_wall_time=490.0)]
    assert "stalled" in stale_shadow(wedged)
    # zero samples with zero offers: no traffic was sampled, no wedge
    quiet = [_config(), {"schema": QUALITY_SCHEMA, "kind": "summary",
                         "wall_time": 500.0, "sampled_total": 0,
                         "windows": 0, "dropped": 0,
                         "offered_total": 0}]
    assert validate_quality_report(quiet) is None
    assert stale_shadow(quiet) is None


# -- the shadow scorer --------------------------------------------------------


def test_shadow_scorer_end_to_end(tiny_ivf, tmp_path):
    """Known-good and known-garbage served answers through the real
    oracle: the window recall must equal the planted fraction, the
    quality log must validate (config/window/summary), and the gauges
    must land in a registry (registry-only mode)."""
    from npairloss_tpu.obs.live import MetricRegistry
    from npairloss_tpu.serve import EngineConfig, QueryEngine

    emb, idx = tiny_ivf
    reg = MetricRegistry()
    qp = str(tmp_path / "quality.jsonl")
    scorer = ShadowScorer(
        lambda: idx,
        ShadowConfig(rate=1.0, ks=(1, 5), window=8, oracle_batch=4),
        registry=reg, out_path=qp,
        recall_floor=0.9, floor_metric="serve_recall_at_5",
    ).start()
    # exact served answers for the first 4 queries via a full-probe
    # engine, planted garbage for the next 4
    engine = QueryEngine(idx, EngineConfig(top_k=5, buckets=(1,),
                                           probes=4))
    for i in range(4):
        out = engine.query(emb[i:i + 1], normalize=False)
        assert scorer.offer(i, emb[i], out["rows"][0], out["scores"][0])
    garbage = np.array([60, 61, 62, 63, 59], np.int32)
    for i in range(4, 8):
        assert scorer.offer(i, emb[i], garbage,
                            np.zeros(5, np.float32))
    deadline = time.time() + 30.0
    while scorer.windows < 1 and time.time() < deadline:
        time.sleep(0.05)
    scorer.close()
    assert scorer.sampled_total == 8 and scorer.dropped == 0
    recs = load_quality_report(qp)
    assert validate_quality_report(recs) is None
    window = next(r for r in recs if r["kind"] == "window")
    # 4 exact (recall 1.0) + 4 garbage (recall ~0; row 59+ could
    # overlap a true neighbor, so allow the top of the garbage band)
    assert 0.4 <= window["recall_at_5"] <= 0.65
    assert recs[0]["recall_floor"] == 0.9
    assert recs[-1]["kind"] == "summary"
    g = reg.get("serve_recall_at_5")
    assert g is not None and g.value == window["recall_at_5"]
    # the breach the garbage caused is visible to the gate helpers
    assert quality_breaches(recs)
    stats = scorer.stats()
    assert stats["sampled"] == 8 and "last" in stats


def test_shadow_oracle_follows_inplace_add(tmp_path):
    """add() republishes the SAME index object in place — the oracle
    staleness token (size, created) must force a rebuild, or served
    answers pointing at new rows would score as misses against the
    pre-add gallery (a false recall collapse)."""
    from npairloss_tpu.serve import GalleryIndex

    rng = np.random.default_rng(3)
    emb = _unit_rows(rng, 32, 8)
    idx = GalleryIndex.build(emb, (np.arange(32) % 4).astype(np.int32),
                             normalize=False)
    scorer = ShadowScorer(
        lambda: idx, ShadowConfig(rate=1.0, ks=(1,), window=1,
                                  oracle_batch=1)).start()
    scorer.offer(0, emb[0], np.array([0], np.int32),
                 np.ones(1, np.float32))
    deadline = time.time() + 30.0
    while scorer.windows < 1 and time.time() < deadline:
        time.sleep(0.05)
    assert scorer.stats()["last"]["recall_at_1"] == 1.0
    new_row = _unit_rows(rng, 1, 8)
    idx.add(new_row, np.array([9], np.int32), normalize=False)
    # the correct served answer for the new row IS the new row (32);
    # a stale oracle would still rank the old gallery and call it a
    # miss
    scorer.offer(1, new_row[0], np.array([32], np.int32),
                 np.ones(1, np.float32))
    while scorer.windows < 2 and time.time() < deadline:
        time.sleep(0.05)
    scorer.close()
    assert scorer.stats()["last"]["recall_at_1"] == 1.0


def test_shadow_offer_never_blocks(tiny_ivf):
    """The latency-invariance pin: with the scoring thread WEDGED and
    the queue bounded at 2, a thousand offers must return immediately
    (drops counted) — the serving path never waits on the oracle, and
    scoring runs on the shadow thread, never the caller's."""
    emb, idx = tiny_ivf
    wedge = threading.Event()
    scoring_threads = []

    scorer = ShadowScorer(
        lambda: idx, ShadowConfig(rate=1.0, ks=(1,), window=2,
                                  max_queue=2, oracle_batch=1))
    real = scorer._score_batch

    def wedged(batch):
        scoring_threads.append(threading.get_ident())
        wedge.wait(timeout=30.0)
        real(batch)

    scorer._score_batch = wedged
    scorer.start()
    rows = np.arange(1, dtype=np.int32)
    t0 = time.perf_counter()
    for i in range(1000):
        scorer.offer(i, emb[0], rows, np.zeros(1, np.float32))
    elapsed = time.perf_counter() - t0
    assert elapsed < 2.0, f"offers took {elapsed:.3f}s — something blocked"
    assert scorer.dropped > 900  # bounded queue shed the flood
    wedge.set()
    scorer.close()
    assert scoring_threads  # scoring happened...
    assert threading.get_ident() not in scoring_threads  # ...not here


def test_server_summary_quality_block_absent_when_off(tiny_ivf):
    """The --shadow-rate 0 parity pin: no scorer, no 'quality' key —
    summary and /healthz keep their pre-quality shape."""
    from npairloss_tpu.serve import (
        BatcherConfig,
        EngineConfig,
        QueryEngine,
        RetrievalServer,
        ServerConfig,
    )

    emb, idx = tiny_ivf
    engine = QueryEngine(idx, EngineConfig(top_k=5, buckets=(1,),
                                           probes=4))
    server = RetrievalServer(
        engine, BatcherConfig(max_batch=1, max_delay_ms=1.0),
        ServerConfig(metrics_window=0))
    server.replicaset.start()
    try:
        a = server.handle({"id": 0, "embedding": emb[0].tolist()})
        assert a["neighbors"][0]["row"] == 0
        assert "quality" not in server.summary()
        assert "quality" not in server.healthz()
    finally:
        server.replicaset.close(drain=True)


def test_server_dispatch_offers_sampled_queries(tiny_ivf):
    emb, idx = tiny_ivf
    from npairloss_tpu.serve import (
        BatcherConfig,
        EngineConfig,
        QueryEngine,
        RetrievalServer,
        ServerConfig,
    )

    engine = QueryEngine(idx, EngineConfig(top_k=5, buckets=(1,),
                                           probes=4))
    engine.warmup()
    server = RetrievalServer(
        engine, BatcherConfig(max_batch=1, max_delay_ms=1.0),
        ServerConfig(metrics_window=0))
    scorer = ShadowScorer(
        lambda: server.engine.index,
        ShadowConfig(rate=1.0, ks=(1, 5), window=3, oracle_batch=3),
    ).start()
    server.shadow = scorer
    server.replicaset.start()
    try:
        for i in range(3):
            a = server.handle({"id": i, "embedding": emb[i].tolist()})
            assert "neighbors" in a
        deadline = time.time() + 30.0
        while scorer.windows < 1 and time.time() < deadline:
            time.sleep(0.05)
    finally:
        server.replicaset.close(drain=True)
        scorer.close()
    assert scorer.sampled_total == 3
    assert scorer.stats()["last"]["recall_at_5"] == 1.0
    assert "quality" in server.summary()


# -- the recall-floor watchdog ------------------------------------------------


def test_recall_watchdog_fire_clear_hysteresis():
    from npairloss_tpu.obs.live import MetricRegistry, SLOEvaluator
    from npairloss_tpu.obs.live.watchdogs import serve_recall_floor

    spec = serve_recall_floor(k=10, floor=0.9, window_s=10.0)
    assert spec.metric == "serve_recall_at_10" and spec.op == ">="
    reg = MetricRegistry()
    ev = SLOEvaluator([spec], reg)
    # no samples: shadowing off stays ok forever
    assert not ev.evaluate(now=100.0)[0].burning
    reg.set(spec.metric, 1.0, t=100.0)
    assert not ev.evaluate(now=100.5)[0].burning
    # recall collapses: half the window bad -> fires
    for i in range(6):
        reg.set(spec.metric, 0.2, t=101.0 + i)
    st = ev.evaluate(now=107.0)
    assert st[0].burning and st[0].worst == 0.2
    # hysteresis: one good sample is not recovery...
    reg.set(spec.metric, 1.0, t=108.0)
    assert ev.evaluate(now=108.0)[0].burning
    # ...but the bad samples aging out of the window clears it
    for i in range(4):
        reg.set(spec.metric, 1.0, t=112.0 + i)
    assert not ev.evaluate(now=116.0)[0].burning


# -- probe escalation ---------------------------------------------------------


def _tiny_server(idx, probes, replicas=1, top_k=5):
    from npairloss_tpu.serve import (
        BatcherConfig,
        EngineConfig,
        QueryEngine,
        RetrievalServer,
        ServerConfig,
    )

    cfg = EngineConfig(top_k=top_k, buckets=(1,), probes=probes)
    primary = QueryEngine(idx, cfg)
    primary.warmup()
    engines = [primary] + [
        QueryEngine(idx, cfg, share_compiled_with=primary)
        for _ in range(replicas - 1)
    ]
    for e in engines[1:]:
        e.warmed = True
    server = RetrievalServer(
        engines, BatcherConfig(max_batch=1, max_delay_ms=1.0),
        ServerConfig(metrics_window=0))
    server.replicaset.start()
    return server


def test_probe_escalation_ladder_and_flat_fallback(tiny_ivf):
    from npairloss_tpu.obs.quality.escalate import (
        EscalationExhaustedError,
        ProbeEscalator,
    )
    from npairloss_tpu.serve.ivf import IVFIndex

    emb, idx = tiny_ivf
    server = _tiny_server(idx, probes=1, replicas=2)
    try:
        esc = ProbeEscalator(server)
        d = esc.escalate()
        assert d["probes"] == 2 and d["probes_before"] == 1
        assert server.engine.cfg.probes == 2 and server.engine.warmed
        assert len(server.engines) == 2  # replica count preserved
        d = esc.escalate()
        assert d["probes"] == 4  # clamped ladder top = cluster count
        # budget exhausted: the next attempt is the flat fallback
        before = server.freshness
        d = esc.escalate()
        assert d["fallback"] == "flat"
        assert not isinstance(server.engine.index, IVFIndex)
        assert server.freshness is before  # not a freshness event
        a = server.handle({"id": 0, "embedding": emb[0].tolist()})
        assert a["neighbors"][0]["row"] == 0  # flat answers are exact
        # nothing left: an honest raise, the NothingNewerError pattern
        with pytest.raises(EscalationExhaustedError):
            esc.escalate()
        assert server.swaps == 3
    finally:
        server.replicaset.close(drain=True)


def test_probe_escalation_remediation_lifecycle(tiny_ivf, tmp_path):
    """The full audited loop: firing alert -> attempted + escalation,
    resolution -> succeeded; then a sticky alert walking the ladder to
    the flat fallback, and past it the action RAISES -> failed — all
    validator-clean."""
    from npairloss_tpu.obs.quality.escalate import ProbeEscalator
    from npairloss_tpu.resilience.remediate import (
        RemediationEngine,
        RemediationPolicy,
        validate_remediation_log,
    )

    emb, idx = tiny_ivf
    server = _tiny_server(idx, probes=1)
    try:
        esc = ProbeEscalator(server)
        pol = RemediationPolicy(
            name="probe_escalation", slo="serve_recall_floor",
            action="escalate_probes", cooldown_s=10.0, max_attempts=4)
        eng = RemediationEngine(
            [pol], {"escalate_probes": esc.escalate},
            log_path=str(tmp_path / "remediation.jsonl"))
        alert = {"alert_id": "serve_recall_floor-1",
                 "severity": "critical", "fired_at": 100.0}
        active = {"serve_recall_floor": alert}
        evs = eng.tick(active, now=100.0)
        assert [e["state"] for e in evs] == ["attempted"]
        assert server.engine.cfg.probes == 2
        # alert resolves -> the attempt succeeded, detail recorded
        evs = eng.tick({}, now=105.0)
        assert evs[0]["state"] == "succeeded"
        assert evs[0]["detail"]["probes"] == 2
        # a fresh sticky incident: 4 -> flat -> exhausted(raise=failed)
        alert2 = {"alert_id": "serve_recall_floor-2",
                  "severity": "critical", "fired_at": 200.0}
        active = {"serve_recall_floor": alert2}
        eng.tick(active, now=200.0)   # probes 2 -> 4
        assert server.engine.cfg.probes == 4
        eng.tick(active, now=215.0)   # fails prior attempt, goes flat
        from npairloss_tpu.serve.ivf import IVFIndex

        assert not isinstance(server.engine.index, IVFIndex)
        evs = eng.tick(active, now=230.0)  # nothing left -> raise
        assert any(e["state"] == "failed" and "flat" in e.get(
            "error", "").lower() or e["state"] == "failed"
            for e in evs)
        eng.close()
        records = [json.loads(ln) for ln in
                   open(tmp_path / "remediation.jsonl") if ln.strip()]
        assert validate_remediation_log(records) is None
        assert any(r["state"] == "succeeded" for r in records)
        assert any(r["state"] == "failed" for r in records)
    finally:
        server.replicaset.close(drain=True)


# -- serve.recall_drop failpoint ----------------------------------------------


def test_recall_drop_failpoint(tiny_ivf):
    from npairloss_tpu.serve import EngineConfig, GalleryIndex, QueryEngine

    emb, idx = tiny_ivf
    engine = QueryEngine(idx, EngineConfig(top_k=5, buckets=(1,),
                                           probes=4))
    engine.warmup()
    before = engine.compile_stats()
    assert engine.query(emb[7:8], normalize=False)["rows"][0, 0] == 7
    failpoints.arm("serve.recall_drop", times=1)
    out = engine.query(emb[7:8], normalize=False)
    assert out["rows"][0, 0] != 7  # the probe set was poisoned
    # exhausted: the very next dispatch answers exactly again, and the
    # fault cost ZERO recompiles (same shapes, same signatures)
    assert engine.query(emb[7:8], normalize=False)["rows"][0, 0] == 7
    assert engine.compile_stats() == before
    # a flat tier has no probe to corrupt: the arming is NOT consumed
    flat = GalleryIndex.build(emb, (np.arange(64) % 8).astype(np.int32),
                              normalize=False)
    fengine = QueryEngine(flat, EngineConfig(top_k=5, buckets=(1,)))
    fengine.warmup()
    failpoints.arm("serve.recall_drop", times=1)
    assert fengine.query(emb[7:8], normalize=False)["rows"][0, 0] == 7
    assert failpoints.should_fire("serve.recall_drop")  # still armed


def test_recall_drop_visible_to_shadow(tiny_ivf):
    """The loop's first half: a poisoned dispatch's answers score ~0
    recall against the oracle — the gauge the watchdog reads."""
    emb, idx = tiny_ivf
    from npairloss_tpu.serve import EngineConfig, QueryEngine

    engine = QueryEngine(idx, EngineConfig(top_k=5, buckets=(1,),
                                           probes=4))
    engine.warmup()
    scorer = ShadowScorer(
        lambda: idx, ShadowConfig(rate=1.0, ks=(5,), window=2,
                                  oracle_batch=2)).start()
    failpoints.arm("serve.recall_drop", times=2)
    for i in range(2):
        out = engine.query(emb[i:i + 1], normalize=False)
        scorer.offer(i, emb[i], out["rows"][0], out["scores"][0])
    deadline = time.time() + 30.0
    while scorer.windows < 1 and time.time() < deadline:
        time.sleep(0.05)
    scorer.close()
    assert scorer.stats()["last"]["recall_at_5"] <= 0.2


# -- parity birth certificate -------------------------------------------------


def test_ivf_parity_stamp_roundtrip(tiny_ivf, tmp_path):
    from npairloss_tpu.serve.index import load_index, read_manifest
    from npairloss_tpu.serve.ivf import measure_parity

    emb, idx = tiny_ivf
    par = measure_parity(idx, probes=4, sample=32)
    assert par["probes"] == 4 and par["sample"] == 32
    # full probes at fp32 == the exact scan: recall is 1.0 by math
    assert par["recall"]["fp32"] == {"at_1": 1.0, "at_5": 1.0,
                                     "at_10": 1.0}
    assert set(par["recall"]) == {"fp32", "bf16", "int8"}
    idx.parity = par
    path = str(tmp_path / "g.gidx")
    idx.save(path)
    assert read_manifest(path)["parity"]["probes"] == 4
    loaded = load_index(path)
    assert loaded.parity == par  # the birth certificate survives load
    idx.parity = None  # leave the module-scoped fixture untouched


# -- mining-health ------------------------------------------------------------


def _hardness_aux(pos_thr, neg_thr):
    import jax.numpy as jnp

    n = len(pos_thr)
    return {
        "ident_num": jnp.ones(n, jnp.float32),
        "diff_num": jnp.ones(n, jnp.float32) * 3,
        "pos_threshold": jnp.asarray(pos_thr, jnp.float32),
        "neg_threshold": jnp.asarray(neg_thr, jnp.float32),
    }


def test_mining_health_keys_byte_identical_when_off():
    from npairloss_tpu.obs.health import pair_hardness_health

    aux = _hardness_aux([0.9, 0.8], [0.3, 0.4])
    # the pre-quality key set, byte-identical with the feature off
    assert list(pair_hardness_health(aux)) == [
        "mined_pos_per_query", "mined_neg_per_query",
        "ap_threshold_mean", "an_threshold_mean"]
    on = pair_hardness_health(aux, mining=True)
    assert list(on) == [
        "mined_pos_per_query", "mined_neg_per_query",
        "ap_threshold_mean", "an_threshold_mean",
        "ap_an_margin_mean", "ap_an_margin_p10", "an_saturation"]


def test_mining_health_values():
    from npairloss_tpu.obs.health import pair_hardness_health

    # healthy: wide margins, no saturation
    out = pair_hardness_health(
        _hardness_aux([0.9, 0.8, 0.7, 0.6], [0.3, 0.2, 0.1, 0.0]),
        mining=True)
    assert abs(float(out["ap_an_margin_mean"]) - 0.6) < 1e-6
    assert abs(float(out["ap_an_margin_p10"]) - 0.6) < 1e-6  # min margin
    assert float(out["an_saturation"]) == 0.0
    # collapsing: AN frontier at the AP frontier, everything saturated
    out = pair_hardness_health(
        _hardness_aux([0.99, 0.99], [0.97, 0.99]), mining=True)
    assert float(out["ap_an_margin_mean"]) < 0.02
    assert float(out["an_saturation"]) == 1.0
    # sentinel thresholds (no candidates) never poison the stats
    out = pair_hardness_health(
        _hardness_aux([1e38, 0.8], [-1e38, 0.2]), mining=True)
    assert abs(float(out["ap_an_margin_mean"]) - 0.6) < 1e-6
    assert float(out["an_saturation"]) == 0.0
    # all-sentinel: finite zeros (the assert_all_finite contract)
    out = pair_hardness_health(
        _hardness_aux([1e38], [-1e38]), mining=True)
    for key in ("ap_an_margin_mean", "ap_an_margin_p10",
                "an_saturation"):
        assert float(out[key]) == 0.0


def test_solver_rows_mining_keys_gated(tmp_path):
    """The row-schema pin at the Solver level: health rows WITHOUT
    --mining-health carry exactly the pre-quality keys; with it, the
    margin/saturation keys ride the same rows."""
    import jax.numpy as jnp

    from npairloss_tpu import REFERENCE_CONFIG
    from npairloss_tpu.models import get_model
    from npairloss_tpu.obs.health import HealthConfig
    from npairloss_tpu.train import Solver, SolverConfig

    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 16)).astype(np.float32)
    lab = np.repeat(np.arange(4), 2).astype(np.int32)

    def run(health):
        solver = Solver(
            get_model("mlp"), REFERENCE_CONFIG,
            SolverConfig(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                         display=0, snapshot=0),
            input_shape=(16,), health=health)
        solver.init(x[:2])
        return {k: float(v)
                for k, v in solver.step(x, lab).items()}

    base = run(HealthConfig())
    mined = run(HealthConfig(mining_health=True))
    new_keys = {"ap_an_margin_mean", "ap_an_margin_p10",
                "an_saturation"}
    assert not (new_keys & set(base))
    assert new_keys <= set(mined)
    assert set(mined) - set(base) == new_keys
    for k in new_keys:
        assert np.isfinite(mined[k])


# -- the jax-free bench_check gate --------------------------------------------


def _write_quality(tmp_path, records, alert_records=None):
    os.makedirs(str(tmp_path), exist_ok=True)
    qp = str(tmp_path / "quality.jsonl")
    with open(qp, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    if alert_records is not None:
        with open(str(tmp_path / "alerts.jsonl"), "w") as f:
            for r in alert_records:
                f.write(json.dumps(r) + "\n")
    return qp


def _gate(path, *extra):
    return subprocess.run(
        [sys.executable, BENCH_CHECK, "--quality", path, *extra],
        capture_output=True, text=True)


def test_bench_check_quality_gate(tmp_path):
    cfg = _config(recall_floor=0.9, floor_metric="serve_recall_at_5")
    clean = [cfg, _window(), _summary()]
    qp = _write_quality(tmp_path / "clean", clean)
    out = _gate(qp)
    assert out.returncode == 0, out.stdout + out.stderr

    # schema violation refused
    bad = [dict(cfg, schema="npairloss-quality-v0")]
    qp = _write_quality(tmp_path / "schema", bad)
    out = _gate(qp)
    assert out.returncode == 1 and "schema-invalid" in out.stdout

    # a floor breach with NO alert log at all: refused
    breach = [cfg, _window(r5=0.4), _summary()]
    qp = _write_quality(tmp_path / "noalert", breach)
    out = _gate(qp)
    assert out.returncode == 1 and "NO fired alert" in out.stdout

    # the same breach with a fired recall alert: the loop worked
    fired = [{"state": "firing", "metric": "serve_recall_at_5",
              "alert_id": "serve_recall_floor-1"}]
    qp = _write_quality(tmp_path / "alerted", breach, fired)
    out = _gate(qp)
    assert out.returncode == 0, out.stdout

    # ...but an alert on a DIFFERENT metric does not justify it
    other = [{"state": "firing", "metric": "serve_p99_ms",
              "alert_id": "p99-1"}]
    qp = _write_quality(tmp_path / "wrongmetric", breach, other)
    out = _gate(qp)
    assert out.returncode == 1 and "NO fired alert" in out.stdout

    # a silently-stalled shadow scorer: refused
    stale = [cfg, _window(), _summary(t=200.0, last=101.0)]
    qp = _write_quality(tmp_path / "stale", stale)
    out = _gate(qp)
    assert out.returncode == 1 and "silent" in out.stdout


# -- watch + prof surfacing ---------------------------------------------------


def test_watch_surfaces_quality_block(tmp_path):
    from npairloss_tpu.obs.live import watch_run_dir
    from npairloss_tpu.obs.live.watchdogs import serve_recall_floor

    run = tmp_path / "run"
    run.mkdir()
    t0 = time.time()
    rows = [{"run_id": "r", "phase": "serve", "step": i,
             "wall_time": t0 + i, "recall_at_10": 1.0,
             "shadow_score_gap": 0.0, "shadow_samples": 4}
            for i in range(3)]
    with open(run / "metrics.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    _write_quality(run, [
        _config(ks=[10], wall_time=t0),
        {"schema": QUALITY_SCHEMA, "kind": "window", "wall_time": t0 + 1,
         "samples": 4, "sampled_total": 4, "recall_at_10": 1.0,
         "score_gap_mean": 0.0, "score_gap_max": 0.0},
        _summary(t=t0 + 2, last=t0 + 1)])
    summary = watch_run_dir(str(run), [serve_recall_floor()])
    assert summary["quality"]["valid"] is True
    assert summary["quality"]["recall"]["at_10"]["min"] == 1.0
    # healthy recall rows through the replay: no alert fired
    assert summary["events"] == 0
    # an invalid log is surfaced, not hidden
    with open(run / "quality.jsonl", "a") as f:
        f.write(json.dumps({"schema": "nope", "kind": "window"}) + "\n")
        f.write("\n")
    summary = watch_run_dir(str(run), [serve_recall_floor()])
    assert summary["quality"]["valid"] is False
    assert "error" in summary["quality"]


def test_prof_quality_cli(tmp_path, capsys):
    from npairloss_tpu.cli import main

    run = tmp_path / "run"
    run.mkdir()
    _write_quality(run, [_config(), _window(), _summary()])
    rc = main(["prof", "--quality", str(run)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "quality observatory" in out
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["windows"] == 1 and tail["sampled_total"] == 4
    # schema-invalid: non-zero, the validator is the contract
    _write_quality(run, [_config(shadow_rate=2.0)])
    assert main(["prof", "--quality", str(run)]) == 1
    # no log at all
    assert main(["prof", "--quality", str(tmp_path / "none")]) == 2
