"""The invariant linter (npairloss_tpu/analysis, docs/STATICCHECK.md).

Accept/refuse fixtures per pass (tests/fixtures/staticcheck), the
npairloss-staticcheck-v1 report contract, allowlist + --diff modes,
the jax-free CLI entry, and the ``bench_check --static`` gate driven
via subprocess like the existing --alerts/--fleet-report modes.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from npairloss_tpu.analysis import (
    PASS_NAMES,
    run_suite,
    validate_staticcheck_report,
)
from npairloss_tpu.analysis.markers import parse_durations_log
from npairloss_tpu.analysis.runner import changed_files, update_timings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "staticcheck")
BENCH_CHECK = os.path.join(REPO, "scripts", "bench_check.py")


def _keys(report, pass_name=None):
    return [rec["key"] for rec in report["findings"]
            if pass_name is None or rec["pass"] == pass_name]


def _write_tree(root, files):
    for rel, content in files.items():
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(textwrap.dedent(content))
    return str(root)


# -- vocabulary pins ----------------------------------------------------------


def test_cli_pass_choices_pinned():
    """cli.py hardcodes the pass vocabulary (jax-free parser contract);
    pinned against the runner's registry so drift is a test failure —
    the same contract as _PRECISION_CHOICES."""
    from npairloss_tpu.cli import _STATICCHECK_PASSES

    assert tuple(_STATICCHECK_PASSES) == tuple(PASS_NAMES)


# -- fixtures: accept / refuse per pass ---------------------------------------


def test_clean_fixture_accepted():
    report = run_suite(os.path.join(FIXTURES, "clean"))
    assert report["findings"] == []
    assert report["allowlisted"] == []
    # Every pass actually RAN on the clean tree (markers included —
    # it ships a timing history), so acceptance is evidence, not a
    # skipped suite.
    assert all(not p["skipped"] for p in report["passes"])
    assert validate_staticcheck_report(report) is None


@pytest.mark.parametrize("tree,pass_name,detail_fragment", [
    ("jax_leak", "purity", "reaches-jax"),
    ("unscoped_collective", "scopes", "psum"),
    ("unguarded_mutation", "locks", "Registry.reset._items"),
    ("orphan_validator", "contracts", "npairloss-orphan-v1"),
    ("undocumented_flag", "vocab", "failpoint-serve.bogus"),
    ("unmarked_slow", "markers", "test_giant_compile"),
])
def test_seeded_fixture_refused(tree, pass_name, detail_fragment):
    report = run_suite(os.path.join(FIXTURES, tree))
    keys = _keys(report, pass_name)
    assert any(detail_fragment in k for k in keys), \
        f"{tree}: expected a {pass_name} finding matching " \
        f"{detail_fragment!r}, got {_keys(report)}"


def test_undocumented_flag_fixture_also_flags_doc_drift():
    report = run_suite(os.path.join(FIXTURES, "undocumented_flag"))
    assert any("flag---no-such-flag" in k for k in _keys(report, "vocab"))


def test_repo_is_clean():
    """The repo's own gate: zero non-allowlisted findings.  This IS
    the acceptance criterion — a violation introduced anywhere fails
    here first."""
    report = run_suite(REPO)
    assert report["findings"] == [], [
        r["message"] for r in report["findings"]]


# -- per-pass teeth on synthesized trees --------------------------------------


def test_purity_undeclared_file_path_load(tmp_path):
    root = _write_tree(tmp_path, {
        "scripts/gate.py": """\
            import importlib.util
            import os

            spec = importlib.util.spec_from_file_location(
                "npairloss_tpu.obs.sneaky",
                os.path.join("npairloss_tpu", "obs", "sneaky.py"))
        """,
        "npairloss_tpu/obs/sneaky.py": "VALUE = 1\n",
    })
    report = run_suite(root)
    assert any("undeclared-npairloss_tpu.obs.sneaky" in k
               for k in _keys(report, "purity"))


def test_purity_lazy_import_tolerated(tmp_path):
    root = _write_tree(tmp_path, {
        "npairloss_tpu/obs/live/alerts.py": """\
            import json


            def percentile(xs, q):
                from npairloss_tpu.heavy import jax_percentile
                return jax_percentile(xs, q)
        """,
        "npairloss_tpu/heavy.py": "import jax\n",
    })
    assert _keys(run_suite(root), "purity") == []


def test_scopes_annotation_honored(tmp_path):
    root = _write_tree(tmp_path, {
        "npairloss_tpu/ops/x.py": """\
            import jax


            def peek(x, axis_name):
                return jax.lax.pmax(x, axis_name)  # comm-scope-ok: scalar probe priced by the harness
        """,
    })
    assert _keys(run_suite(root), "scopes") == []


def test_locks_mutating_call_in_expression_context(tmp_path):
    """``x = self._d.pop(k)`` mutates exactly like the bare-statement
    form — the review-round blind spot, pinned."""
    root = _write_tree(tmp_path, {
        "npairloss_tpu/z.py": """\
            import threading


            class Engine:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._pending = {}  # guarded-by: _lock
                    self._last = {}  # guarded-by: _lock

                def take(self, k):
                    stale = self._pending.pop(k, None)
                    return stale

                def chain_store(self, p, k, v):
                    self._last[p][k] = v

                def fine(self, k):
                    with self._lock:
                        return self._pending.pop(k, None)
        """,
    })
    keys = _keys(run_suite(root), "locks")
    assert any("Engine.take._pending" in k for k in keys)
    assert any("Engine.chain_store._last" in k for k in keys)
    assert not any("Engine.fine" in k for k in keys)


def test_locks_annotation_on_continuation_line(tmp_path):
    """A '# guarded-by:' trailing the SECOND line of a backslash-
    continued assignment must still register (the SLOEvaluator._burning
    shape) — a dead annotation is worse than none."""
    root = _write_tree(tmp_path, {
        "npairloss_tpu/w.py": """\
            import threading


            class Ev:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._burning = \\
                        {}  # guarded-by: _lock

                def poke(self, k):
                    self._burning[k] = True
        """,
    })
    keys = _keys(run_suite(root), "locks")
    assert any("Ev.poke._burning" in k for k in keys)


def test_locks_real_annotations_register():
    """Every class this PR annotated actually ARMS the checker — a
    dead annotation (e.g. on a continuation line the comment scan
    misses) would claim enforcement that does not exist."""
    import ast as ast_mod

    from npairloss_tpu.analysis.locks import guarded_attrs
    from npairloss_tpu.analysis.tree import SourceTree

    tree = SourceTree(REPO)
    expected = {
        ("npairloss_tpu/obs/live/slo.py", "SLOEvaluator"):
            ({"_burning"}, {"_lock"}),
        ("npairloss_tpu/obs/live/registry.py", "MetricRegistry"):
            ({"_metrics"}, {"_lock"}),
        ("npairloss_tpu/resilience/remediate.py", "RemediationEngine"):
            ({"_seq", "_pending", "_undos", "_last", "history"},
             {"_lock"}),
        ("npairloss_tpu/serve/server.py", "RetrievalServer"):
            ({"engines", "engine", "freshness", "swaps", "queries",
              "answered", "errors", "_ingest_watermark",
              "_ckpt_watermark"},
             {"_lock", "_ingest_lock"}),
    }
    for (rel, cls_name), (attrs, locks) in expected.items():
        mod = tree.parse(rel)
        cls = next(n for n in ast_mod.walk(mod)
                   if isinstance(n, ast_mod.ClassDef)
                   and n.name == cls_name)
        guarded = guarded_attrs(cls, tree.comments(rel))
        missing = attrs - set(guarded)
        assert not missing, f"{cls_name}: {missing} never registered"
        assert set(guarded.values()) == locks, cls_name


def test_locks_missing_lock_attr_flagged(tmp_path):
    root = _write_tree(tmp_path, {
        "npairloss_tpu/y.py": """\
            class Thing:
                def __init__(self):
                    self.items = []  # guarded-by: _lock
        """,
    })
    keys = _keys(run_suite(root), "locks")
    assert any("Thing.items" in k for k in keys)


def test_contracts_key_twin_drift(tmp_path):
    root = _write_tree(tmp_path, {
        "npairloss_tpu/obs/sinks.py":
            'FLEET_KEYS = ("process_index", "process_count")\n',
        "npairloss_tpu/obs/fleet/stamp.py":
            'STAMP_KEYS = ("process_index", "process_count", '
            '"local_device_ids")\n',
    })
    assert any("twin-FLEET_KEYS" in k
               for k in _keys(run_suite(root), "contracts"))


def test_contracts_restated_literal(tmp_path):
    root = _write_tree(tmp_path, {
        "npairloss_tpu/a.py": """\
            A_SCHEMA = "npairloss-aaa-v1"


            def validate_a(rec):
                return None if rec.get("schema") == A_SCHEMA else "bad"
        """,
        "npairloss_tpu/b.py": """\
            def build():
                return {"schema": "npairloss-aaa-v1"}
        """,
    })
    assert any("restated-npairloss-aaa-v1" in k
               for k in _keys(run_suite(root), "contracts"))


def test_vocab_choice_pin_drift(tmp_path):
    root = _write_tree(tmp_path, {
        "npairloss_tpu/cli.py":
            '_PRECISION_CHOICES = ("bf16", "mxu")\n',
        "npairloss_tpu/models/precision.py": """\
            _POLICIES = {"bf16": 1, "mxu": 2, "fp32_parity": 3}
        """,
    })
    assert any("pin-_PRECISION_CHOICES" in k
               for k in _keys(run_suite(root), "vocab"))


def test_vocab_undocumented_watchdog(tmp_path):
    root = _write_tree(tmp_path, {
        "npairloss_tpu/obs/live/watchdogs.py": """\
            def ghost():
                return Spec(name="ghost_dog", metric="x")
        """,
        "docs/OBSERVABILITY.md": "# Obs\n\nNothing here.\n",
    })
    assert any("watchdog-ghost_dog" in k
               for k in _keys(run_suite(root), "vocab"))


def test_vocab_stale_failpoint_row(tmp_path):
    root = _write_tree(tmp_path, {
        "npairloss_tpu/x.py": """\
            from npairloss_tpu.resilience import failpoints


            def go():
                failpoints.fire("real.fault")
        """,
        "docs/RESILIENCE.md": """\
            | failpoint | injects |
            |---|---|
            | `real.fault` | a real one |
            | `ghost.fault` | documented but never fired |
        """,
    })
    assert any("failpoint-ghost.fault" in k
               for k in _keys(run_suite(root), "vocab"))


def test_syntax_error_is_a_finding(tmp_path):
    root = _write_tree(tmp_path, {
        "npairloss_tpu/broken.py": "def broken(:\n",
    })
    report = run_suite(root)
    assert any("parse-error" in k for k in _keys(report))


# -- allowlist + diff ---------------------------------------------------------


def test_allowlist_tolerates_named_finding(tmp_path):
    fixture = os.path.join(FIXTURES, "unguarded_mutation")
    base = run_suite(fixture)
    (key,) = _keys(base, "locks")
    allow = tmp_path / "allow.json"
    allow.write_text(json.dumps(
        {"allow": [{"key": key, "why": "fixture test"}]}))
    report = run_suite(fixture, allowlist_path=str(allow))
    assert report["findings"] == []
    assert [r["key"] for r in report["allowlisted"]] == [key]
    # The allowlisted finding still counts in its pass row (visible,
    # not vanished) and the report stays validator-clean.
    assert validate_staticcheck_report(report) is None


def test_bad_allowlist_is_loud(tmp_path):
    allow = tmp_path / "allow.json"
    allow.write_text('{"allow": [42]}')
    with pytest.raises(ValueError):
        run_suite(os.path.join(FIXTURES, "clean"),
                  allowlist_path=str(allow))


def _git(cwd, *args):
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=cwd, check=True, capture_output=True)


def test_diff_mode_restricts_to_changed_files(tmp_path):
    root = _write_tree(tmp_path, {
        "npairloss_tpu/old.py": """\
            import jax


            def old(x, a):
                return jax.lax.psum(x, a)
        """,
    })
    _git(root, "init", "-q")
    _git(root, "add", "-A")
    _git(root, "commit", "-qm", "base")
    _write_tree(tmp_path, {
        "npairloss_tpu/new.py": """\
            import jax


            def new(x, a):
                return jax.lax.pmean(x, a)
        """,
    })
    full = run_suite(root)
    assert len(_keys(full, "scopes")) == 2
    diffed = run_suite(root, diff_base="HEAD")
    keys = _keys(diffed, "scopes")
    assert keys and all("new.py" in k for k in keys)
    # And the plumbing: changed_files sees exactly the untracked file.
    assert changed_files(root, "HEAD") == ["npairloss_tpu/new.py"]


def test_diff_mode_bad_ref_is_loud(tmp_path):
    with pytest.raises(ValueError):
        run_suite(str(tmp_path), diff_base="no-such-ref")


def test_diff_mode_on_subtree_root(tmp_path):
    """--diff scanning a SUBTREE of the git repo: diff paths must be
    rebased to the tree root (git emits repo-root-relative without
    --relative), or tracked-file findings silently vanish."""
    repo = tmp_path / "repo"
    sub = repo / "sub"
    _write_tree(sub, {
        "npairloss_tpu/x.py": """\
            import jax


            def f(x, a):
                return jax.lax.psum(x, a)
        """,
    })
    _git(str(repo), "init", "-q")
    _git(str(repo), "add", "-A")
    _git(str(repo), "commit", "-qm", "base")
    # Modify the tracked file (stays a violation).
    path = sub / "npairloss_tpu" / "x.py"
    path.write_text(path.read_text() + "\n# touched\n")
    report = run_suite(str(sub), diff_base="HEAD")
    assert any("psum" in k for k in _keys(report, "scopes")), \
        "tracked-modified finding dropped on a subtree root"


def test_diff_mode_excludes_unrelated_parse_error(tmp_path):
    """A pre-existing broken file must not fail an incremental run of
    an unrelated change (the --diff contract)."""
    root = _write_tree(tmp_path, {
        "npairloss_tpu/broken.py": "def broken(:\n",
    })
    _git(root, "init", "-q")
    _git(root, "add", "-A")
    _git(root, "commit", "-qm", "base")
    _write_tree(tmp_path, {"npairloss_tpu/fine.py": "VALUE = 1\n"})
    assert _keys(run_suite(root))  # full run still reports it
    assert _keys(run_suite(root, diff_base="HEAD")) == []


def test_files_scanned_counts_every_pass(tmp_path):
    """Per-pass files_scanned reports what the pass actually looked at
    (cache hits included) — not a parse-cache delta that credits
    everything to whichever pass ran first."""
    report = run_suite(os.path.join(FIXTURES, "clean"))
    by_name = {p["name"]: p["files_scanned"] for p in report["passes"]}
    # purity and scopes both read the package sources; with the old
    # delta accounting every pass after the first reported 0.
    assert by_name["purity"] > 0
    assert by_name["scopes"] > 0
    assert by_name["locks"] > 0


def test_both_drivers_share_one_vocabulary():
    """The cli subcommand and the runner's own parser are two front
    doors to one run_from_args — their option sets and defaults are
    pinned equal so a new flag cannot land in only one."""
    import argparse

    from npairloss_tpu import cli
    from npairloss_tpu.analysis import runner

    def options(parser):
        out = {}
        for a in parser._actions:
            if isinstance(a, argparse._HelpAction):
                continue
            out[a.dest] = (tuple(a.option_strings),
                           tuple(a.choices) if a.choices else None,
                           a.default)
        return out

    runner_opts = options(runner._build_parser())
    sc = argparse.ArgumentParser()
    cli._add_staticcheck_options(sc)
    assert options(sc) == runner_opts


# -- report contract ----------------------------------------------------------


def test_report_validator_teeth():
    good = run_suite(os.path.join(FIXTURES, "unscoped_collective"))
    assert validate_staticcheck_report(good) is None

    def broken(mutate):
        rep = json.loads(json.dumps(good))
        mutate(rep)
        return validate_staticcheck_report(rep)

    assert "schema" in broken(
        lambda r: r.update(schema="npairloss-staticcheck-v2"))
    assert broken(lambda r: r.pop("summary")) is not None
    assert broken(
        lambda r: r["findings"][0].pop("message")) is not None
    assert "pass" in broken(
        lambda r: r["findings"][0].update({"pass": "ghost"}))
    assert "key" in broken(
        lambda r: r["findings"][0].update(key="wrong:format"))
    assert "claims" in broken(
        lambda r: r["passes"][1].update(findings=99))
    assert "summary.findings" in broken(
        lambda r: r["summary"].update(findings=0))
    assert "skipped" in broken(
        lambda r: r["passes"][1].update(skipped=True))
    assert "duplicate" in broken(
        lambda r: r["passes"].append(dict(r["passes"][0])))
    assert broken(lambda r: r.update(passes=[])) is not None


# -- timing history plumbing --------------------------------------------------


def test_parse_durations_log():
    text = textwrap.dedent("""\
        ============== slowest durations ===============
        12.34s call     tests/test_a.py::test_one
        0.50s setup    tests/test_a.py::test_one
        3.21s call     tests/test_b.py::TestC::test_two[case0]
        (durations < 0.005s hidden)
    """)
    d = parse_durations_log(text)
    assert d["tests/test_a.py::test_one"] == pytest.approx(12.84)
    assert d["tests/test_b.py::TestC::test_two[case0]"] == \
        pytest.approx(3.21)


def test_update_timings_roundtrip(tmp_path):
    log = tmp_path / "t1.log"
    log.write_text("55.00s call tests/test_x.py::test_slow\n")
    root = _write_tree(tmp_path, {
        "tests/test_x.py": """\
            def test_slow():
                assert True
        """,
    })
    out = update_timings(root, str(log), 10.0)
    payload = json.load(open(out))
    assert payload["threshold_s"] == 10.0
    report = run_suite(root)
    assert any("test_slow" in k for k in _keys(report, "markers"))


# -- subprocess drives: the gate + the jax-free CLI ---------------------------


def _poison_env(tmp_path):
    """An env whose ``import jax`` raises: proves the jax-free
    contract by execution, not by inspection."""
    poison = tmp_path / "poison"
    poison.mkdir()
    (poison / "jax.py").write_text(
        'raise ImportError("jax imported inside a jax-free tool")\n')
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{poison}{os.pathsep}{REPO}"
    env.pop("JAX_PLATFORMS", None)
    return env


def test_bench_check_static_gate_subprocess(tmp_path):
    env = _poison_env(tmp_path)
    ok = subprocess.run(
        [sys.executable, BENCH_CHECK, "--static",
         os.path.join(FIXTURES, "clean")],
        capture_output=True, text=True, env=env)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    for tree in ("jax_leak", "unscoped_collective",
                 "unguarded_mutation", "orphan_validator",
                 "undocumented_flag", "unmarked_slow"):
        bad = subprocess.run(
            [sys.executable, BENCH_CHECK, "--static",
             os.path.join(FIXTURES, tree)],
            capture_output=True, text=True, env=env)
        assert bad.returncode == 1, f"{tree}: {bad.stdout}{bad.stderr}"
        assert "REGRESSION: staticcheck" in bad.stdout, bad.stdout


def test_cli_staticcheck_jax_free_end_to_end(tmp_path):
    """``python -m npairloss_tpu staticcheck`` in a venv whose jax
    import RAISES: the whole entry path (package __init__, cli parser,
    analysis) must never touch it, and the emitted report must be
    validator-accepted."""
    env = _poison_env(tmp_path)
    out = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, "-m", "npairloss_tpu", "staticcheck",
         os.path.join(FIXTURES, "clean"), "--out", str(out)],
        capture_output=True, text=True, env=env, cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.load(open(out))
    assert validate_staticcheck_report(report) is None
    assert report["summary"]["findings"] == 0
