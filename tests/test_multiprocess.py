"""Multi-process runtime test: 2 real OS processes, one CPU device each,
joined via jax.distributed — the TPU-native counterpart of the
reference's one-MPI-rank-per-GPU launch (npair_multi_class_loss.cu:32).

The worker (mp_worker.py) asserts the gathered negative pool spans both
processes and that per-rank losses match the NumPy oracle on the
concatenated pod batch — plus, since the fleet observatory, that every
rank writes its own telemetry stream into one shared run dir.

Capability gate: some jaxlib CPU backends form the cluster and then
refuse to EXECUTE a cross-process computation ("Multiprocess
computations aren't implemented on the CPU backend").  That is an
environment limit, not a framework bug — the module fixture probes it
once (mp_probe.py: cluster join + one jitted psum, pure jax + compat
shims) and skips with the probe's own error when the env cannot do it,
keeping the real assertions armed everywhere the env can.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _mp_env() -> dict:
    env = dict(os.environ)
    # One CPU device per process (drop the conftest's 8-device forcing),
    # and no TPU plugin on the path — pure multi-controller CPU.
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env["PYTHONPATH"] = REPO
    return env


def _run_pair(script: str, extra_args, timeout: int):
    """Launch 2 cooperating processes of ``script``; returns
    [(returncode, output), ...]."""
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(HERE, script),
             str(i), "2", str(port), *extra_args],
            env=_mp_env(), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out.decode(errors="replace"))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return [(p.returncode, out) for p, out in zip(procs, outs)]


@pytest.fixture(scope="module")
def cpu_cluster():
    """Skip-with-reason when this box's CPU backend cannot execute a
    multi-process collective; pass through where it can (the real
    assertions stay armed there)."""
    results = _run_pair("mp_probe.py", [], timeout=120)
    if all(rc == 0 and "PROBE_OK" in out for rc, out in results):
        return
    detail = next(
        (out for rc, out in results if rc != 0), results[0][1]
    ).strip().splitlines()
    pytest.skip(
        "this environment cannot execute multi-process CPU "
        "collectives (mp_probe.py): "
        + (detail[-1] if detail else "probe produced no output")
    )


@pytest.mark.parametrize("nproc", [2])
def test_two_process_pool_spans_processes(tmp_path, nproc, cpu_cluster):
    results = _run_pair("mp_worker.py", [str(tmp_path)], timeout=240)
    for i, (rc, out) in enumerate(results):
        assert rc == 0, f"process {i} failed:\n{out[-3000:]}"
    for i in range(nproc):
        assert (tmp_path / f"ok_{i}").exists(), f"process {i} wrote no marker"

    # Fleet observatory over REAL process boundaries: the worker ran a
    # short Solver.train with fleet telemetry into one shared run dir.
    from npairloss_tpu.obs.fleet import (
        build_fleet_report,
        merge_run_traces,
        validate_fleet_report,
    )
    from npairloss_tpu.obs.tracing import validate_chrome_trace

    fleet_dir = tmp_path / "fleet_run"
    # Rank-disjoint sink files — concurrent ranks never share a stream.
    for k in range(nproc):
        stream = fleet_dir / f"telemetry.r{k}.jsonl"
        assert stream.exists(), f"rank {k} left no stream"
        rows = [json.loads(ln) for ln in stream.read_text().splitlines()]
        train = [r for r in rows if r.get("phase") == "train"]
        assert train, f"rank {k} stream has no train rows"
        assert all(r["process_index"] == k and r["process_count"] == nproc
                   for r in train)

    report = build_fleet_report(str(fleet_dir))
    assert validate_fleet_report(report) is None, report
    assert report["ranks_present"] == list(range(nproc))
    counts = {r["rank"]: r["steps"] for r in report["ranks"]}
    assert len(set(counts.values())) == 1, counts
    assert report["skew"]["steps_analyzed"] > 0
    assert report["skew"]["slowest"]["rank"] in range(nproc)
    # Collective attribution: the dense engine's all_gather + the grad
    # allreduce must be claimed, with nothing left unattributed.
    comms = report["comms"]
    assert comms["available"], comms
    assert comms["unattributed_bytes"] == 0, comms
    kinds = {k["kind"] for k in comms["kinds"]}
    assert "all_gather" in kinds, kinds

    path, merged = merge_run_traces(str(fleet_dir))
    assert path is not None
    assert validate_chrome_trace(merged) is None
    lanes = {e["pid"] for e in merged["traceEvents"]}
    assert lanes == set(range(nproc)), lanes
