"""Multi-process runtime test: 2 real OS processes, one CPU device each,
joined via jax.distributed — the TPU-native counterpart of the
reference's one-MPI-rank-per-GPU launch (npair_multi_class_loss.cu:32).

The worker (mp_worker.py) asserts the gathered negative pool spans both
processes and that per-rank losses match the NumPy oracle on the
concatenated pod batch.
"""

import os
import socket
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("nproc", [2])
def test_two_process_pool_spans_processes(tmp_path, nproc):
    port = _free_port()
    env = dict(os.environ)
    # One CPU device per process (drop the conftest's 8-device forcing),
    # and no TPU plugin on the path — pure multi-controller CPU.
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env["PYTHONPATH"] = REPO
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(HERE, "mp_worker.py"),
             str(i), str(nproc), str(port), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out.decode(errors="replace"))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
    for i in range(nproc):
        assert (tmp_path / f"ok_{i}").exists(), f"process {i} wrote no marker"
