"""Seeded randomized cross-engine sweep.

The deterministic grids (test_golden_*, test_pallas, test_ring) pin
every (region, method) cell on identity-balanced batches; this sweep
drives RANDOM points of the config space — margins, sn signs/fractions,
mixed AP/AN cells — against IRREGULAR label structure (uneven group
sizes, shuffled order) through all three engines at once:

  dense    == NumPy oracle        (loss, thresholds, counts)
  blockwise == dense              (loss + grad, non-divisor block)
  ring(2)  == dense-gather(2)     (loss + grad on a 2-shard mesh)

The quirk surface (C-truncation of relative ranks, the negative-value
-> -FLT_MAX clamp, zero-count guards — npair_multi_class_loss.cu:
277-337) is exactly where an untested parameter combination could break
silently; random points + the oracle keep the engines honest between
grid nodes.  Seeded, so failures reproduce.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from npairloss_tpu.ops.npair_loss import (
    MiningMethod,
    MiningRegion,
    NPairLossConfig,
    npair_loss_with_aux,
)
from npairloss_tpu.ops.pallas_npair import blockwise_npair_loss_with_aux
from npairloss_tpu.parallel import data_parallel_mesh, shard_map
from npairloss_tpu.parallel.ring import ring_npair_loss_and_metrics
from npairloss_tpu.testing import oracle

AXIS = "dp"
REGIONS = [MiningRegion.GLOBAL, MiningRegion.LOCAL]
METHODS = list(MiningMethod)


def _random_cfg(rng) -> NPairLossConfig:
    # sn draws cover both semantics: negative fraction-of-list and
    # positive absolute-rank-from-top (cu:285-287), plus -0.0 (the
    # flagship's identsn — rank 0 via the sn>=0 branch... the sign of
    # zero matters and the oracle pins which branch wins).
    sn_pool = [-0.7, -0.45, -0.3, -0.2, -0.0, 0.0, 1.0, 2.0, 3.0]
    return NPairLossConfig(
        margin_ident=float(rng.uniform(-0.08, 0.08)),
        margin_diff=float(rng.uniform(-0.08, 0.08)),
        identsn=float(rng.choice(sn_pool)),
        diffsn=float(rng.choice(sn_pool)),
        ap_mining_region=REGIONS[rng.integers(2)],
        ap_mining_method=METHODS[rng.integers(len(METHODS))],
        an_mining_region=REGIONS[rng.integers(2)],
        an_mining_method=METHODS[rng.integers(len(METHODS))],
    )


def _irregular_batch(rng, dim=12, max_group=4):
    """Shuffled batch with UNEVEN identity group sizes (2..max_group
    images) — the grids only ever use uniform imgs-per-id; the mining
    statistics see ragged per-query positive/negative list lengths
    here."""
    sizes = rng.integers(2, max_group + 1, size=int(rng.integers(4, 7)))
    ids = rng.choice(1000, size=len(sizes), replace=False)
    lab = np.concatenate(
        [np.full(s, i, np.int32) for s, i in zip(sizes, ids)]
    )
    f = rng.standard_normal((len(lab), dim)).astype(np.float32)
    f /= np.linalg.norm(f, axis=1, keepdims=True)
    perm = rng.permutation(len(lab))
    return f[perm], lab[perm]


@pytest.mark.parametrize("trial", range(8))
def test_fuzz_dense_oracle_blockwise(trial):  # slow-ok: the randomized three-way engine fuzz — tier-1's widest net
    rng = np.random.default_rng(20260731 + trial)
    cfg = _random_cfg(rng)
    f, l = _irregular_batch(rng)

    want = oracle.forward([f], [l], cfg)[0]
    loss_d, aux_d = jax.jit(
        lambda ff, ll: npair_loss_with_aux(ff, ll, cfg)
    )(jnp.asarray(f), jnp.asarray(l))
    np.testing.assert_allclose(
        float(loss_d), want.loss, rtol=1e-5, atol=1e-7, err_msg=str(cfg))
    np.testing.assert_allclose(
        aux_d["pos_threshold"], want.pos_thr, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(
        aux_d["neg_threshold"], want.neg_thr, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(
        aux_d["ident_num"], (want.same & want.select).sum(1))
    np.testing.assert_allclose(
        aux_d["diff_num"], (want.diff & want.select).sum(1))

    # Blockwise (interpret mode): loss + grad vs dense in ONE
    # value_and_grad compile each (interpret-mode Pallas is the slow
    # part of this sweep).
    (loss_b, _), gb = jax.value_and_grad(
        lambda x: blockwise_npair_loss_with_aux(
            x, jnp.asarray(l), cfg, block_size=5),
        has_aux=True,
    )(jnp.asarray(f))
    np.testing.assert_allclose(
        float(loss_b), float(loss_d), rtol=1e-5, atol=1e-6,
        err_msg=str(cfg))
    gd = jax.grad(
        lambda x: npair_loss_with_aux(x, jnp.asarray(l), cfg)[0]
    )(jnp.asarray(f))
    np.testing.assert_allclose(gb, gd, rtol=1e-5, atol=1e-7,
                               err_msg=str(cfg))


def _sharded_value_and_grad(fn, mesh, feats, labs):
    """One compile per engine: value_and_grad of the shard-mean loss."""

    def mean_loss(ff, ll):
        return jnp.mean(
            shard_map(
                lambda a, b: fn(a, b)[None],
                mesh=mesh, in_specs=(P(AXIS), P(AXIS)),
                out_specs=P(AXIS),
            )(ff, ll)
        )

    val, grad = jax.jit(jax.value_and_grad(mean_loss))(
        jnp.asarray(feats), jnp.asarray(labs))
    return np.asarray(val), np.asarray(grad)


@pytest.mark.parametrize("trial", range(4))
@pytest.mark.slow
def test_fuzz_ring_vs_dense_two_shards(trial):
    rng = np.random.default_rng(77310000 + trial)
    cfg = _random_cfg(rng)
    # Equal-length shards (shard_map contract); irregular groups inside.
    shards = [_irregular_batch(rng) for _ in range(2)]
    n = min(len(s[1]) for s in shards)
    n -= n % 2
    feats = np.concatenate([s[0][:n] for s in shards])
    labs = np.concatenate([s[1][:n] for s in shards])

    mesh = data_parallel_mesh(jax.devices()[:2])

    def dense_loss(ff, ll):
        return npair_loss_with_aux(ff, ll, cfg, axis_name=AXIS)[0]

    def ring_loss(ff, ll):
        return ring_npair_loss_and_metrics(ff, ll, cfg, AXIS, (1,))[0]

    vd, gd = _sharded_value_and_grad(dense_loss, mesh, feats, labs)
    vr, gr = _sharded_value_and_grad(ring_loss, mesh, feats, labs)
    np.testing.assert_allclose(vr, vd, rtol=1e-5, atol=1e-6,
                               err_msg=str(cfg))
    np.testing.assert_allclose(gr, gd, rtol=1e-5, atol=1e-7,
                               err_msg=str(cfg))


@pytest.mark.slow  # ~87s over 4 trials; tier-1 budget, run with -m slow
@pytest.mark.parametrize("trial", range(4))
def test_fuzz_pos_topk_fast_path_vs_radix(trial):
    """The sparse-positive fast path (pos_topk buffer) and forced radix
    selection (pos_topk=0) are two different machineries for the same
    RELATIVE AP threshold — both must equal the dense path at random
    config points.  The fast path is only live for RELATIVE AP + a
    NON-relative AN (its gate), so AN is pinned to an absolute method;
    and group sizes run up to 12 against the 8-slot buffer so the
    lax.cond overflow fallback genuinely fires in some groups."""
    import dataclasses

    rng = np.random.default_rng(55550000 + trial)
    cfg = dataclasses.replace(
        _random_cfg(rng),
        ap_mining_method=[MiningMethod.RELATIVE_HARD,
                          MiningMethod.RELATIVE_EASY][int(rng.integers(2))],
        an_mining_method=[MiningMethod.HARD, MiningMethod.EASY,
                          MiningMethod.RAND][int(rng.integers(3))],
    )
    f, l = _irregular_batch(rng, max_group=12)
    loss_d, _ = jax.jit(
        lambda ff, ll: npair_loss_with_aux(ff, ll, cfg)
    )(jnp.asarray(f), jnp.asarray(l))
    for pos_topk in (0, 8, None):
        loss_b, _ = blockwise_npair_loss_with_aux(
            jnp.asarray(f), jnp.asarray(l), cfg, block_size=5,
            pos_topk=pos_topk,
        )
        np.testing.assert_allclose(
            float(loss_b), float(loss_d), rtol=1e-5, atol=1e-6,
            err_msg=f"pos_topk={pos_topk} {cfg}")
