"""matmul_precision knob: "highest" (default) is the oracle-bit-parity
mode the rest of the suite pins exhaustively; "default" is the ~6x
single-pass-bf16 MXU throughput mode.  These tests pin the throughput
mode's contract: engines agree with each other at bf16-rounding
tolerance, gradients stay finite and close, training still converges,
and invalid values fail loudly.  (On the CPU test backend "default"
precision is numerically fp32, so agreement here validates plumbing and
semantics; the precision split only bites on the MXU.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import make_identity_batch
from npairloss_tpu.ops.npair_loss import (
    REFERENCE_CONFIG,
    npair_loss_with_aux,
    resolve_matmul_precision,
)
from npairloss_tpu.ops.pallas_npair import blockwise_npair_loss_with_aux
from npairloss_tpu.parallel import shard_map


def test_resolve_matmul_precision():
    assert resolve_matmul_precision(None) == jax.lax.Precision.HIGHEST
    assert resolve_matmul_precision("highest") == jax.lax.Precision.HIGHEST
    assert resolve_matmul_precision("default") == jax.lax.Precision.DEFAULT
    with pytest.raises(ValueError, match="matmul_precision"):
        resolve_matmul_precision("bf16")


def test_default_precision_engines_agree(rng):  # slow-ok: dense/blockwise/ring agreement under the default policy — the engine-trio contract
    (f,), (l,) = make_identity_batch(rng, num_ids=6, imgs_per_id=2, dim=16)
    f, l = jnp.asarray(f), jnp.asarray(l)
    loss_d, _ = npair_loss_with_aux(
        f, l, REFERENCE_CONFIG, matmul_precision="default")
    loss_b, _ = blockwise_npair_loss_with_aux(
        f, l, REFERENCE_CONFIG, block_size=5, matmul_precision="default")
    np.testing.assert_allclose(loss_b, loss_d, rtol=1e-2, atol=1e-3)
    gd = jax.grad(lambda x: npair_loss_with_aux(
        x, l, REFERENCE_CONFIG, matmul_precision="default")[0])(f)
    gb = jax.grad(lambda x: blockwise_npair_loss_with_aux(
        x, l, REFERENCE_CONFIG, block_size=5,
        matmul_precision="default")[0])(f)
    assert bool(jnp.all(jnp.isfinite(gd))) and bool(jnp.all(jnp.isfinite(gb)))
    np.testing.assert_allclose(gb, gd, rtol=1e-2, atol=1e-3)


@pytest.mark.slow
def test_default_precision_ring_agrees(rng):
    from jax.sharding import PartitionSpec as P

    from npairloss_tpu.parallel.mesh import data_parallel_mesh
    from npairloss_tpu.parallel.ring import ring_npair_loss_and_metrics

    mesh = data_parallel_mesh()
    g = len(mesh.devices)
    feats, labs = make_identity_batch(rng, num_ids=2 * g, imgs_per_id=2,
                                      dim=16, num_shards=1)
    f = jnp.asarray(np.concatenate(feats))
    l = jnp.asarray(np.concatenate(labs))

    def per_shard(e, lab):
        return ring_npair_loss_and_metrics(
            e, lab, REFERENCE_CONFIG, "dp", top_ks=(),
            matmul_precision="default")[0][None]

    ring = jax.jit(shard_map(
        per_shard, mesh=mesh, in_specs=(P("dp"), P("dp")),
        out_specs=P("dp")))

    def dense_shard(e, lab):
        return npair_loss_with_aux(
            e, lab, REFERENCE_CONFIG, axis_name="dp",
            matmul_precision="default")[0][None]

    dense = jax.jit(shard_map(
        dense_shard, mesh=mesh, in_specs=(P("dp"), P("dp")),
        out_specs=P("dp")))
    np.testing.assert_allclose(
        np.asarray(ring(f, l)), np.asarray(dense(f, l)),
        rtol=1e-2, atol=1e-3)


def test_default_precision_training_converges(rng):
    """The throughput mode must still train: a small MLP on separable
    identity clusters reaches the same near-zero loss as bit-parity
    mode within the same step budget."""
    import optax

    from npairloss_tpu.ops.metrics import recall_at_k

    num_ids, imgs, dim, emb = 8, 2, 16, 8
    centers = rng.standard_normal((num_ids, dim)).astype(np.float32)

    def batch(step):
        lab = np.repeat(np.arange(num_ids), imgs)
        r = np.random.default_rng(step)
        x = centers[lab] + 0.6 * r.standard_normal(
            (num_ids * imgs, dim)).astype(np.float32)
        return (jnp.asarray(x.astype(np.float32)),
                jnp.asarray(lab.astype(np.int32)))

    w = jnp.asarray(rng.standard_normal((dim, emb)).astype(np.float32) * 0.1)
    opt = optax.sgd(0.5, momentum=0.9)
    ost = opt.init(w)

    def emb_of(w_, x):
        e = x @ w_
        return e / jnp.linalg.norm(e, axis=1, keepdims=True)

    @jax.jit
    def step(w_, o, x, lab):
        loss, g = jax.value_and_grad(lambda ww: npair_loss_with_aux(
            emb_of(ww, x), lab, REFERENCE_CONFIG,
            matmul_precision="default")[0])(w_)
        up, o2 = opt.update(g, o, w_)
        return optax.apply_updates(w_, up), o2, loss

    for i in range(150):
        x, lab = batch(i)
        w, ost, loss = step(w, ost, x, lab)
    x, lab = batch(999)
    e = emb_of(w, x)
    sims = e @ e.T
    r1 = float(recall_at_k(sims, lab, lab, jnp.int32(0), 1))
    assert r1 >= 0.95, (r1, float(loss))
