"""Worker for the 2-process distributed test (test_multiprocess.py).

Each process joins the coordination service, builds a mesh over the
GLOBAL device set, contributes its own process-local batch (the
reference's per-rank MultibatchData model), and asserts:

  * the all-gathered negative pool spans BOTH processes' labels — the
    defining invariant of MPI_Allgather (cu:17-43) across real process
    boundaries, not just virtual devices;
  * its per-rank loss matches the NumPy oracle of the reference on the
    concatenated pod batch;
  * a full Solver training step runs and returns finite metrics.

Usage: mp_worker.py <process_id> <num_processes> <port> <out_dir>
"""

import os
import sys


def main() -> int:
    proc_id, nproc = int(sys.argv[1]), int(sys.argv[2])
    port, out_dir = sys.argv[3], sys.argv[4]

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from npairloss_tpu.parallel import (
        data_parallel_mesh,
        initialize_distributed,
        process_local_batch,
        shard_map,
    )

    initialize_distributed(f"localhost:{port}", nproc, proc_id)
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.device_count() == nproc * jax.local_device_count()

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from npairloss_tpu import REFERENCE_CONFIG, npair_loss_with_aux
    from npairloss_tpu.testing import oracle

    mesh = data_parallel_mesh()  # global devices, process-major order
    g = len(mesh.devices.flatten())
    n_local_rows = 4  # 2 ids x 2 imgs per DEVICE shard

    # Per-process data with process-disjoint labels; identical RNG tree
    # across processes would defeat the span check.
    def make(pid):
        r = np.random.default_rng(100 + pid)
        per_proc_rows = n_local_rows * jax.local_device_count()
        f = r.standard_normal((per_proc_rows, 16)).astype(np.float32)
        f /= np.linalg.norm(f, axis=1, keepdims=True)
        l = (np.repeat(np.arange(per_proc_rows // 2), 2)
             + 1000 * pid).astype(np.int32)
        return f, l

    f_mine, l_mine = make(proc_id)
    feats, labs = process_local_batch(mesh, (f_mine, l_mine))

    def per_shard(ff, ll):
        loss, aux = npair_loss_with_aux(
            ff, ll, REFERENCE_CONFIG, axis_name="dp"
        )
        return loss[None], aux["total_labels"][None]

    loss_stack, total_labels = jax.jit(
        shard_map(
            per_shard, mesh=mesh,
            in_specs=(P("dp"), P("dp")), out_specs=(P("dp"), P("dp")),
        )
    )(feats, labs)

    # Each process reads its own addressable shards.
    local_rows = sorted(
        (s.index[0].start or 0, np.asarray(s.data))
        for s in total_labels.addressable_shards
    )
    pool = np.unique(np.concatenate([d.ravel() for _, d in local_rows]))
    all_labels = np.unique(
        np.concatenate([make(p)[1] for p in range(nproc)])
    )
    assert set(all_labels).issubset(set(pool)), (
        f"gathered pool {pool} does not span all processes' labels "
        f"{all_labels}"
    )

    # Oracle parity: per-rank losses on the pod batch, process-major.
    per_dev_f, per_dev_l = [], []
    for p in range(nproc):
        fp, lp = make(p)
        for d in range(jax.local_device_count()):
            per_dev_f.append(fp[d * n_local_rows:(d + 1) * n_local_rows])
            per_dev_l.append(lp[d * n_local_rows:(d + 1) * n_local_rows])
    want = [r.loss for r in oracle.forward(per_dev_f, per_dev_l,
                                           REFERENCE_CONFIG)]
    mine = sorted(
        (s.index[0].start or 0, float(np.asarray(s.data)[0]))
        for s in loss_stack.addressable_shards
    )
    for start, got in mine:
        rank = start  # stacked axis: one row per shard
        np.testing.assert_allclose(got, want[rank], rtol=3e-5, err_msg=f"rank {rank}")

    # Ring engine across REAL process boundaries: the ppermute rotation
    # (feature blocks + the traveling database-role grad,
    # parallel/ring.py) must cross the process-spanning mesh and land on
    # the same per-rank losses the (oracle-verified) dense path produced.
    from npairloss_tpu.parallel.ring import ring_npair_loss_and_metrics

    ring_stack = jax.jit(
        shard_map(
            lambda ff, ll: ring_npair_loss_and_metrics(
                ff, ll, REFERENCE_CONFIG, "dp", top_ks=()
            )[0][None],
            mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P("dp"),
        )
    )(feats, labs)
    ring_mine = sorted(
        (s.index[0].start or 0, float(np.asarray(s.data)[0]))
        for s in ring_stack.addressable_shards
    )
    for (start, got_ring), (_, got_dense) in zip(ring_mine, mine):
        np.testing.assert_allclose(
            got_ring, got_dense, rtol=3e-5,
            err_msg=f"ring/dense divergence at rank {start}",
        )

    # Full Solver step over the process-spanning mesh.
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    solver = Solver(
        get_model("mlp", hidden=(32,), embedding_dim=16),
        REFERENCE_CONFIG,
        SolverConfig(base_lr=0.1, lr_policy="fixed", display=0, snapshot=0),
        mesh=mesh,
        input_shape=(16,),
    )
    m = solver.step(f_mine, l_mine)
    assert np.isfinite(float(m["loss"])), m

    # Per-process disjoint shards of a DETERMINISTIC global batch
    # (data.shard_batches, docs/DISTRIBUTED.md): every controller
    # computes the same global stream, rank r contributes rows
    # [r*n, (r+1)*n), and the assembled mesh array IS the global batch
    # — the data model behind the single-vs-multi-process bit-identity
    # parity contract.
    from npairloss_tpu.data import shard_batches

    def global_stream():
        r = np.random.default_rng(7)
        while True:
            gf = r.standard_normal((4 * g, 16)).astype(np.float32)
            gl = np.repeat(np.arange(2 * g), 2).astype(np.int32)
            yield gf, gl

    xs, ls = next(shard_batches(global_stream(), proc_id, nproc))
    assert xs.shape[0] == 4 * g // nproc, xs.shape
    gxs, gls = next(global_stream())
    axs, als = process_local_batch(mesh, (xs, ls))
    assert axs.shape[0] == 4 * g, axs.shape
    for s in axs.addressable_shards:
        start = s.index[0].start or 0
        np.testing.assert_array_equal(
            np.asarray(s.data), gxs[start:start + s.data.shape[0]],
            err_msg="assembled shard is not the global batch's slice")

    # Multi-host snapshot -> resume: the collective Orbax save with
    # rank 0 writing the manifest AFTER it lands; every rank then
    # resumes via --resume auto semantics.  Rank 1 reaching
    # restore_auto while rank 0 is still writing manifest.json is THE
    # race resilience.validate_snapshot_wait exists for — exercised
    # live here, not just in the mocked unit test.
    import dataclasses

    solver.cfg = dataclasses.replace(
        solver.cfg, snapshot_prefix=os.path.join(out_dir, "snap_"))
    snap = solver.save_snapshot(solver.iteration)
    restored = solver.restore_auto()
    assert restored == snap, (restored, snap)

    # Fleet observatory leg (obs.fleet): every rank opens rank-stamped
    # telemetry on the SAME shared run dir and trains a few more steps
    # — rank-disjoint streams, step-numbered dispatch spans, per-step
    # comm marks, and rank 0's fleet_comms.json all land for the
    # parent test to aggregate with `build_fleet_report`.
    from npairloss_tpu.obs import RunTelemetry

    fleet_dir = os.path.join(out_dir, "fleet_run")
    tel = RunTelemetry(fleet_dir, fleet=True)
    tel.write_manifest(config={"harness": "mp_worker"})
    assert tel.fleet is not None and tel.fleet.process_count == nproc
    solver.telemetry = tel

    def batches():
        while True:
            yield f_mine, l_mine

    solver.train(batches(), num_iters=5, log_fn=lambda s: None)
    tel.close()
    assert os.path.exists(
        os.path.join(fleet_dir, f"telemetry.r{proc_id}.jsonl")
    ), "rank stream missing"

    with open(os.path.join(out_dir, f"ok_{proc_id}"), "w") as fh:
        fh.write(f"loss={float(m['loss']):.6f} pool={len(pool)}\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
