"""bench.py outage behavior: a dead tunnel must yield a parseable,
degraded JSON record (VERDICT r3 weak #1), and the sim-cache auto-gate
must be budgeted and attributable (ADVICE r3)."""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_degraded_record_uses_last_good(bench):
    rec = bench._degraded_record("tunnel outage (test)", {"value": 1.0})
    # Driver contract: metric/value/unit/vs_baseline always present.
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
    assert rec["degraded"] is True
    assert rec["platform_status"] == "tunnel outage (test)"
    assert rec["cpu_smoke"] == {"value": 1.0}
    # The committed cache exists in-repo, so the headline value is the
    # last-good hardware payload, flagged stale.
    assert rec["stale"] is True
    assert rec["value"] == rec["last_good"]["payload"]["value"] > 0
    json.dumps(rec)  # must be serializable as the single output line


def test_degraded_record_without_cache(bench, monkeypatch):
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", "/nonexistent/x.json")
    rec = bench._degraded_record("outage", None)
    assert rec["value"] == 0.0 and rec["stale"] is False
    assert rec["cpu_smoke"] == {"error": "cpu smoke bench also failed"}


def test_last_good_cache_is_committed_and_fresh_enough(bench):
    with open(bench.LAST_GOOD_PATH) as f:
        lg = json.load(f)
    assert lg["payload"]["platform"] == "tpu"
    assert lg["payload"]["value"] > 0
    assert "provenance" in lg


def test_probe_budget_fails_fast(bench):
    """Total worst-case probe time before the CPU fallback must stay
    well inside a driver window (round 3 burned 37 min)."""
    import re

    src = open(os.path.join(REPO, "bench.py")).read()
    t = float(re.search(r'"--probe-timeout".*?default=([\d.]+)', src).group(1))
    r = int(re.search(r'"--probe-retries".*?default=(\d+)', src).group(1))
    w = float(
        re.search(r'"--probe-retry-wait".*?default=([\d.]+)', src).group(1)
    )
    worst = t * (r + 1) + w * r
    assert worst <= 330, f"probe budget {worst}s exceeds the 5.5-min cap"


def test_sim_cache_auto_is_budgeted_and_logged(caplog):
    import logging

    from npairloss_tpu.ops.npair_loss import (
        SIM_CACHE_AUTO_BYTES,
        _SIM_CACHE_LOGGED,
        resolve_sim_cache_auto,
    )

    _SIM_CACHE_LOGGED.clear()
    with caplog.at_level(logging.INFO, logger="npairloss_tpu"):
        assert resolve_sim_cache_auto(1 << 20, "testengine") is True
    assert any("auto-enabling" in r.message for r in caplog.records)
    # Beyond any budget: never auto-enables.
    assert resolve_sim_cache_auto(SIM_CACHE_AUTO_BYTES + 1, "t2") is False
    # Logged once per (engine, size): a second identical call is silent.
    caplog.clear()
    with caplog.at_level(logging.INFO, logger="npairloss_tpu"):
        resolve_sim_cache_auto(1 << 20, "testengine")
    assert not caplog.records


def test_sim_cache_auto_hbm_cap(monkeypatch):
    """The 1/5-of-HBM cap must reject the 32k pool's exactly-4.0-GiB
    slice on a full-16-GiB report (dispatching it wedges the tunneled
    v5e backend — round 4) and admit the 24k pool's 2.25 GiB; backends
    with no memory stats fail CLOSED to a 2 GiB budget."""
    import jax

    from npairloss_tpu.ops.npair_loss import resolve_sim_cache_auto

    class FakeDev:
        def __init__(self, stats):
            self._stats = stats

        def memory_stats(self):
            return self._stats

    def with_stats(stats):
        monkeypatch.setattr(jax, "devices", lambda: [FakeDev(stats)])

    gib = 1 << 30
    with_stats({"bytes_limit": 16 * gib})
    assert resolve_sim_cache_auto(32768 * 32768 * 4, "t") is False  # 4.0 GiB
    assert resolve_sim_cache_auto(24576 * 24576 * 4, "t") is True  # 2.25 GiB
    # No stats -> conservative 2 GiB budget, not the 6 GiB constant.
    with_stats(None)
    assert resolve_sim_cache_auto(3 * gib, "t") is False
    assert resolve_sim_cache_auto(1 * gib, "t") is True


def _load_split():
    spec = importlib.util.spec_from_file_location(
        "split_mod", os.path.join(REPO, "scripts", "split_pallas_check.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fake_check_record(**over):
    rec = {
        "device": "TPU v5 lite", "pool": 4096,
        "parity": {"flagship": {"ok": True}}, "ok": True,
        "mosaic_compiled": True,
        "stretch": {
            "flagship": {"ms_per_step": 300.0, "sim_cache": True},
            "flagship_nocache": {"ms_per_step": 1000.0, "sim_cache": False},
        },
        "peak_bytes_in_use_nocache": 1 << 30,
        "peak_bytes_in_use_cached": 6 << 30,
        "peak_bytes_in_use": 6 << 30,
    }
    rec.update(over)
    return rec


def test_split_refuses_non_hardware_records():
    """The queue runs unattended; a CPU/interpret run must never be
    stamped as a hardware artifact (ADVICE r3)."""
    split = _load_split().split
    with pytest.raises(SystemExit, match="mosaic_compiled"):
        split(_fake_check_record(mosaic_compiled=False), "/tmp")
    with pytest.raises(SystemExit, match="not a TPU"):
        split(_fake_check_record(device="cpu"), "/tmp")


def test_split_derives_engine_and_carries_peaks():
    split = _load_split().split
    pallas, stretch = split(_fake_check_record(), "/tmp", date="2026-07-30")
    assert pallas["ok"] is True and pallas["pool"] == 4096
    assert stretch["sim_cache"] is True
    assert "fp32 sim-cache" in stretch["engine"]
    assert stretch["peak_bytes_in_use_nocache"] == 1 << 30
    assert stretch["peak_bytes_in_use_cached"] == 6 << 30
    assert "flagship_nocache" in stretch["stretch"]
    json.dumps(pallas), json.dumps(stretch)


def test_spill_salvage_roundtrip(bench, monkeypatch, tmp_path):
    """A full child killed mid-extras must be salvageable: headline +
    completed rows survive, the wedge-shaped in-flight row is
    quarantined (the 2026-08-01 blockwise_flagship_radix tunnel wedge)."""
    monkeypatch.setattr(bench, "SPILL_PATH", str(tmp_path / "spill.json"))
    monkeypatch.setattr(bench, "QUARANTINE_PATH", str(tmp_path / "q.json"))
    monkeypatch.setattr(bench, "QUARANTINE_MIN_INFLIGHT_SECS", 0.0)
    assert bench._salvage_from_spill() is None  # no spill -> no salvage
    rec = {"value": 4000.0, "mode": "full", "platform": "tpu",
           "extras": {"dense_abs": {"ms_per_step": 60.0}}}
    bench._write_spill(rec, "wedging_row")
    out = bench._salvage_from_spill()
    assert out["salvaged"] is True and out["wedged_row"] == "wedging_row"
    assert out["extras"]["dense_abs"] == {"ms_per_step": 60.0}
    assert "error" in out["extras"]["wedging_row"]
    # the wedged row is quarantined for every later run
    assert bench._quarantined("wedging_row")
    json.dumps(out)
    # a headline-less spill (wedge during warmup) salvages nothing
    bench._write_spill({"mode": "full"}, "early_row")
    assert bench._salvage_from_spill() is None
    bench._clear_spill()
    assert bench._salvage_from_spill() is None


def test_budget_shaped_death_does_not_quarantine(bench, monkeypatch,
                                                 tmp_path):
    """A row killed shortly after starting (parent budget ran out, OOM
    kill, Ctrl-C) is recorded but NOT quarantined — only wedge-shaped
    deaths (in flight >= QUARANTINE_MIN_INFLIGHT_SECS) lose the row
    permanently."""
    monkeypatch.setattr(bench, "SPILL_PATH", str(tmp_path / "spill.json"))
    monkeypatch.setattr(bench, "QUARANTINE_PATH", str(tmp_path / "q.json"))
    rec = {"value": 4000.0, "mode": "full", "platform": "tpu"}
    bench._write_spill(rec, "slow_row")  # inflight_since = now
    out = bench._salvage_from_spill()
    assert out["wedged_row"] == "slow_row"
    assert "error" in out["extras"]["slow_row"]
    assert bench._quarantined("slow_row") is None  # not wedge-shaped


def test_salvage_namespaces_batch_rows(bench, monkeypatch, tmp_path):
    """A wedge during a batch-scaling row lands the error inside
    extras['batch_scaling'] (where its consumers read), quarantined by
    bare key."""
    monkeypatch.setattr(bench, "SPILL_PATH", str(tmp_path / "spill.json"))
    monkeypatch.setattr(bench, "QUARANTINE_PATH", str(tmp_path / "q.json"))
    monkeypatch.setattr(bench, "QUARANTINE_MIN_INFLIGHT_SECS", 0.0)
    rec = {"value": 4000.0, "mode": "full", "platform": "tpu",
           "extras": {"batch_scaling": {"120": {"ms_per_step": 29.0}}}}
    bench._write_spill(rec, "batch_scaling/240")
    out = bench._salvage_from_spill()
    assert "error" in out["extras"]["batch_scaling"]["240"]
    assert out["extras"]["batch_scaling"]["120"] == {"ms_per_step": 29.0}
    assert "240" not in out["extras"]  # not polluting the top namespace
    assert bench._quarantined("240")


def test_salvaged_partial_never_clobbers_same_day_complete(
        bench, monkeypatch, tmp_path):
    """_save_last_good: a salvaged partial must not replace a complete
    payload captured the same day, but must replace older payloads."""
    import datetime
    lg = tmp_path / "last_good.json"
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(lg))
    today = datetime.date.today().isoformat()
    complete = {"value": 4000.0, "mode": "full", "platform": "tpu"}
    bench._save_last_good(complete)
    assert json.load(open(lg))["payload"] == complete
    # same-day salvaged partial: kept out
    bench._save_last_good({"value": 1.0, "mode": "full", "salvaged": True})
    assert json.load(open(lg))["payload"] == complete
    # older complete payload: a fresh salvaged partial replaces it
    stale = {"date": "2026-07-01", "payload": complete}
    lg.write_text(json.dumps(stale))
    salv = {"value": 2.0, "mode": "full", "salvaged": True}
    bench._save_last_good(salv)
    assert json.load(open(lg))["payload"] == salv
    assert json.load(open(lg))["date"] == today


def test_committed_quarantine_parses_and_gates(bench):
    """bench_cache/quarantine.json must always parse to {row: entry}
    where an entry is either {note: ...} (gates its row) or the
    null deliberate-clear tombstone (row dispatchable, but the key's
    presence blocks bench_rows_missing.py's evidence-based re-seeding
    — the round-6 480 un-quarantine format)."""
    q = bench._load_quarantine()
    assert isinstance(q, dict)
    for row, ent in q.items():
        if ent is None:
            assert bench._quarantined(row) is None  # tombstone = cleared
            continue
        assert isinstance(ent, dict) and ent.get("note")
        assert bench._quarantined(row)
    assert bench._quarantined("definitely_not_a_row") is None


def test_measure_windows_min_and_deadline(bench):
    """_measure returns every timed window (min is published), skips
    windows past the deadline, and keeps warmup outside the windows."""
    calls = {"step": 0, "fetch": 0}

    def step():
        calls["step"] += 1
        return calls["step"]

    def fetch(_):
        calls["fetch"] += 1

    dts = bench._measure(step, [], warmup=2, steps=3, fetch=fetch,
                         floor=0.0, repeats=2)
    assert len(dts) == 2 and all(d > 0 for d in dts)
    # 2 warmup calls + 2 windows x 3 steps
    assert calls["step"] == 2 + 6
    # one sync fetch per warmup call and per window
    assert calls["fetch"] == 2 + 2
    # an already-expired deadline still times the FIRST window (a row
    # started is a row finished) but skips the second
    calls["step"] = calls["fetch"] = 0
    dts = bench._measure(step, [], warmup=0, steps=3, fetch=fetch,
                         floor=0.0, repeats=2, deadline=0.0)
    assert len(dts) == 1 and calls["step"] == 3


def test_same_day_salvage_merge_keeps_richer_base(bench, monkeypatch,
                                                  tmp_path):
    """ADVICE #1: a same-day salvaged record with strictly FEWER
    measured rows must not clobber the richer same-day salvage — its
    recovered rows merge into the existing payload instead."""
    import datetime

    lg = tmp_path / "last_good.json"
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(lg))
    today = datetime.date.today().isoformat()
    rich = {"value": 4000.0, "mode": "full", "salvaged": True,
            "extras": {"pool": 4096,
                       "dense_abs": {"emb_per_sec": 5.0},
                       "ring_abs": {"emb_per_sec": 6.0},
                       "batch_scaling": {"120": {"emb_per_sec": 7.0},
                                         "480": {"error": "wedge"}}}}
    lg.write_text(json.dumps({"date": today, "payload": rich}))
    sparse = {"value": 4100.0, "mode": "full", "salvaged": True,
              "extras": {"batch_scaling":
                         {"vit_b16_128": {"emb_per_sec": 9.0}}}}
    bench._save_last_good(sparse)
    out = json.load(open(lg))["payload"]
    # Richer base survives (headline + engine rows), recovered row lands.
    assert out["value"] == 4000.0
    assert out["extras"]["dense_abs"] == {"emb_per_sec": 5.0}
    assert out["extras"]["batch_scaling"]["120"] == {"emb_per_sec": 7.0}
    assert out["extras"]["batch_scaling"]["vit_b16_128"] == \
        {"emb_per_sec": 9.0}


def test_same_day_salvage_merge_richer_replaces_but_keeps_rows(
        bench, monkeypatch, tmp_path):
    """The other branch: a same-day salvage with MORE measured rows
    becomes the base, but the older salvage's measured rows it did not
    re-measure are folded in rather than lost."""
    import datetime

    lg = tmp_path / "last_good.json"
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(lg))
    today = datetime.date.today().isoformat()
    old = {"value": 4000.0, "mode": "full", "salvaged": True,
           "extras": {"ring_abs": {"emb_per_sec": 6.0}}}
    lg.write_text(json.dumps({"date": today, "payload": old}))
    new = {"value": 4200.0, "mode": "full", "salvaged": True,
           "extras": {"dense_abs": {"emb_per_sec": 5.0},
                      "batch_scaling": {"120": {"emb_per_sec": 7.0}}}}
    bench._save_last_good(new)
    out = json.load(open(lg))["payload"]
    assert out["value"] == 4200.0  # richer record is the base
    assert out["extras"]["dense_abs"] == {"emb_per_sec": 5.0}
    assert out["extras"]["ring_abs"] == {"emb_per_sec": 6.0}  # kept


def test_rows_filter_record_merges_into_last_good(bench, monkeypatch,
                                                  tmp_path):
    """A --rows selective re-pass record MERGES into the existing
    payload (measured rows win over skip markers) instead of wholesale
    replacement, and stamps rows_updated provenance."""
    lg = tmp_path / "last_good.json"
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(lg))
    full = {"value": 4000.0, "mode": "full",
            "extras": {"dense_abs": {"emb_per_sec": 5.0},
                       "batch_scaling": {"120": {"emb_per_sec": 7.0},
                                         "vit_b16_128": {"error": "x"}}}}
    lg.write_text(json.dumps({"date": "2026-07-01", "payload": full}))
    repass = {"value": 4000.0, "mode": "full", "headline_reused": True,
              "rows_filter": ["vit_b16_128"],
              "extras": {"dense_abs": {"skipped": "not selected (--rows)"},
                         "batch_scaling":
                         {"120": {"skipped": "not selected (--rows)"},
                          "vit_b16_128": {"emb_per_sec": 9.0}}}}
    bench._save_last_good(repass)
    out = json.load(open(lg))
    pay = out["payload"]
    assert pay["value"] == 4000.0
    assert pay["extras"]["dense_abs"] == {"emb_per_sec": 5.0}
    assert pay["extras"]["batch_scaling"]["120"] == {"emb_per_sec": 7.0}
    assert pay["extras"]["batch_scaling"]["vit_b16_128"] == \
        {"emb_per_sec": 9.0}
    assert pay["rows_updated"]["rows"] == ["vit_b16_128"]


def test_rows_repass_replaces_already_measured_row(bench, monkeypatch,
                                                   tmp_path):
    """An explicitly re-measured --rows row REPLACES the base's stale
    measured value (prefer semantics) — otherwise the re-pass is
    silently discarded while rows_updated claims it landed.  Unselected
    rows and a reused headline still never override."""
    lg = tmp_path / "last_good.json"
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(lg))
    full = {"value": 4000.0, "mode": "full",
            "extras": {"dense_abs": {"emb_per_sec": 5.0},
                       "ring_abs": {"emb_per_sec": 6.0},
                       "batch_scaling": {"120": {"emb_per_sec": 7.0}}}}
    lg.write_text(json.dumps({"date": "2026-07-01", "payload": full}))
    repass = {"value": 4000.0, "mode": "full", "headline_reused": True,
              "rows_filter": ["dense_abs", "120"],
              "extras": {"dense_abs": {"emb_per_sec": 9.5},
                         "ring_abs": {"skipped": "not selected (--rows)"},
                         "batch_scaling": {"120": {"emb_per_sec": 8.5}}}}
    bench._save_last_good(repass)
    pay = json.load(open(lg))["payload"]
    assert pay["extras"]["dense_abs"] == {"emb_per_sec": 9.5}  # replaced
    assert pay["extras"]["batch_scaling"]["120"] == {"emb_per_sec": 8.5}
    assert pay["extras"]["ring_abs"] == {"emb_per_sec": 6.0}  # untouched
    assert pay["value"] == 4000.0  # reused headline never overrides


def test_rows_merge_keeps_base_date_when_headline_not_remeasured(
        bench, monkeypatch, tmp_path):
    """A --rows merge that did not re-measure the headline keeps the
    base's date — re-stamping would let old headline evidence win the
    'same-day complete payload beats salvaged partial' rule against a
    genuinely fresh salvage."""
    lg = tmp_path / "last_good.json"
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(lg))
    full = {"value": 4000.0, "mode": "full",
            "extras": {"batch_scaling": {"vit_b16_128": {"error": "x"}}}}
    lg.write_text(json.dumps({"date": "2026-07-01", "payload": full}))
    repass = {"value": 4000.0, "mode": "full", "headline_reused": True,
              "rows_filter": ["vit_b16_128"],
              "extras": {"batch_scaling":
                         {"vit_b16_128": {"emb_per_sec": 9.0}}}}
    bench._save_last_good(repass)
    out = json.load(open(lg))
    assert out["date"] == "2026-07-01"  # headline evidence is that old
    assert out["payload"]["rows_updated"]["rows"] == ["vit_b16_128"]
    # A re-pass that DID re-measure the headline stamps today.
    import datetime

    repass2 = {"value": 4300.0, "mode": "full",
               "rows_filter": ["headline"],
               "extras": {}}
    bench._save_last_good(repass2)
    out2 = json.load(open(lg))
    assert out2["date"] == datetime.date.today().isoformat()
    assert out2["payload"]["value"] == 4300.0


def test_engine_extras_early_skip_builds_nothing(bench, monkeypatch,
                                                 tmp_path):
    """A --rows selection with no engine row returns before the 4096x512
    pool is built or device_put — jax/jnp/np are never touched (None
    stands in for all three)."""
    monkeypatch.setattr(bench, "QUARANTINE_PATH", str(tmp_path / "q.json"))
    extras = {}
    bench._engine_extras(None, None, None, 0.0, deadline=None,
                         extras=extras, flush=None,
                         selected={"headline", "vit_b16_128"})
    assert extras["pool"] == 4096
    assert all(extras[n] == {"skipped": "not selected (--rows)"}
               for n in bench.ENGINE_ROWS)


def test_rows_selection_skips_unselected_batch_rows(bench, monkeypatch,
                                                    tmp_path):
    """--rows gates every batch-scaling row before any model build or
    quarantine consult — an unselected row costs a dict write."""
    monkeypatch.setattr(bench, "QUARANTINE_PATH", str(tmp_path / "q.json"))
    rows = {}
    # jax/jnp/np/dev are never touched when nothing is selected.
    bench._batch_scaling_extras(None, None, None, None, 0.0,
                                deadline=None, rows=rows, flush=None,
                                selected={"headline"})
    assert rows and all(v == {"skipped": "not selected (--rows)"}
                        for v in rows.values())


def test_rows_unknown_name_errors_before_dispatch(bench, capsys):
    """A typo'd --rows name would match nothing downstream (a wasted
    tunnel-window child that still stamps merge provenance), so main()
    rejects it at parse time, naming the offender."""
    with pytest.raises(SystemExit) as exc:
        bench.main(["--rows", "blockwise_flagship_bf16,headline"])
    assert exc.value.code == 2
    err = capsys.readouterr().err
    assert "blockwise_flagship_bf16" in err and "unknown row name" in err


def test_known_row_names_covers_full_vocabulary(bench):
    """known_row_names() = headline + engine rows + batch-scaling keys,
    each sourced from the spec the measuring code itself iterates."""
    names = bench.known_row_names()
    assert "headline" in names
    assert set(bench.ENGINE_ROWS) <= names
    assert {s[2] for s in bench.BATCH_SCALING_SPECS} <= names
    assert len(names) == (1 + len(bench.ENGINE_ROWS)
                          + len(bench.BATCH_SCALING_SPECS))


def test_bench_rows_missing_print_rows(tmp_path, monkeypatch, capsys):
    """--print-rows emits the comma-separated bench.py --rows argument
    for the missing wanted rows (quarantined ones excluded).

    Hermetic on COPIES of the committed last_good/quarantine state: the
    old subprocess version ran the real script against the real repo
    paths, and its 480-quarantine seeding side effect MUTATED the
    committed bench_cache/quarantine.json on every tier-1 run (it
    silently re-added entries the round-6 un-quarantine had cleared,
    until null tombstones made the clear sticky)."""
    import importlib.util
    import shutil
    import sys

    spec = importlib.util.spec_from_file_location(
        "_brm_outage", os.path.join(REPO, "scripts",
                                    "bench_rows_missing.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for name, attr in (("last_good.json", "LAST_GOOD"),
                       ("quarantine.json", "QUARANTINE")):
        src = os.path.join(REPO, "bench_cache", name)
        if os.path.exists(src):
            shutil.copy(src, tmp_path / name)
        monkeypatch.setattr(mod, attr, str(tmp_path / name))
    monkeypatch.setattr(sys, "argv", ["bench_rows_missing.py",
                                      "--print-rows"])
    mod.main()
    rows = capsys.readouterr().out.strip().splitlines()
    rows = rows[0] if rows else ""
    # Against the committed last_good/quarantine state the list is a
    # (possibly empty) comma-separated subset of the WANT rows.
    want = {"vit_b16_128", "120_s2d", "120_fused", "vit_b16_256"}
    assert set(filter(None, rows.split(","))) <= want
    # The committed quarantine's deliberate-clear tombstones must
    # survive an invocation (the seeding skips present keys, null or
    # not) — on the COPY, proving the side effect cannot resurrect the
    # 480 quarantine from the stale last_good error evidence.
    q = json.load(open(tmp_path / "quarantine.json"))
    assert q.get("480", "absent") in (None, "absent")
    assert q.get("480_remat", "absent") in (None, "absent")
