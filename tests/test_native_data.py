"""Native data runtime (native/npair_data.cpp via ctypes).

Checks the C++ pipeline against the pure-Python one: decode parity
(PPM/PGM/BMP/NPY vs PIL), the documented OpenCV half-pixel resize
convention vs a NumPy oracle, the identity-balanced batch contract of
the prefetcher, and the error paths.  Skips when g++ is unavailable.
"""

import os

import numpy as np
import pytest

from npairloss_tpu.data import native as nd

pytestmark = pytest.mark.skipif(
    not nd.native_available(), reason="native runtime not buildable here"
)


def _write_ppm(path, arr):
    h, w, _ = arr.shape
    with open(path, "wb") as f:
        f.write(b"P6\n# comment\n%d %d\n255\n" % (w, h))
        f.write(arr.tobytes())


def _write_pgm(path, arr):
    h, w = arr.shape
    with open(path, "wb") as f:
        f.write(b"P5\n%d %d\n255\n" % (w, h))
        f.write(arr.tobytes())


def _write_bmp(path, arr):
    """Minimal bottom-up 24-bit BMP."""
    h, w, _ = arr.shape
    stride = (w * 3 + 3) & ~3
    size = 54 + stride * h
    hdr = bytearray(54)
    hdr[0:2] = b"BM"
    hdr[2:6] = size.to_bytes(4, "little")
    hdr[10:14] = (54).to_bytes(4, "little")
    hdr[14:18] = (40).to_bytes(4, "little")
    hdr[18:22] = w.to_bytes(4, "little")
    hdr[22:26] = h.to_bytes(4, "little")
    hdr[26:28] = (1).to_bytes(2, "little")
    hdr[28:30] = (24).to_bytes(2, "little")
    with open(path, "wb") as f:
        f.write(hdr)
        for y in range(h - 1, -1, -1):
            row = arr[y, :, ::-1].tobytes()  # RGB -> BGR
            f.write(row + b"\x00" * (stride - len(row)))


def _make_dataset(tmp_path, rng, n_ids=4, per_id=3, h=8, w=10):
    lines = []
    images = {}
    i = 0
    for ident in range(n_ids):
        for _ in range(per_id):
            arr = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
            kind = i % 3
            if kind == 0:
                name = f"img_{i}.ppm"
                _write_ppm(tmp_path / name, arr)
            elif kind == 1:
                name = f"img_{i}.bmp"
                _write_bmp(tmp_path / name, arr)
            else:
                name = f"img_{i}.npy"
                np.save(tmp_path / name, arr)
            images[name] = arr
            lines.append(f"{name} {ident}")
            i += 1
    src = tmp_path / "list.txt"
    src.write_text("\n".join(lines) + "\n")
    return str(src), lines, images


def test_decode_parity_no_resize(tmp_path, rng):
    src, lines, images = _make_dataset(tmp_path, rng)
    ds = nd.NativeListFileDataset(str(tmp_path), src, 8, 10)
    assert len(ds) == len(lines)
    for idx, line in enumerate(lines):
        name, lbl = line.rsplit(None, 1)
        np.testing.assert_array_equal(ds.load(idx), images[name], err_msg=name)
        assert ds.labels[idx] == int(lbl)
    ds.close()


def test_pgm_grayscale_replicates(tmp_path, rng):
    arr = rng.integers(0, 256, (6, 7), dtype=np.uint8)
    _write_pgm(tmp_path / "g.pgm", arr)
    (tmp_path / "l.txt").write_text("g.pgm 0\n")
    ds = nd.NativeListFileDataset(str(tmp_path), str(tmp_path / "l.txt"), 6, 7)
    out = ds.load(0)
    for c in range(3):
        np.testing.assert_array_equal(out[:, :, c], arr)


def _resize_oracle(img, dh, dw):
    """OpenCV INTER_LINEAR convention: src = (dst+0.5)*scale-0.5, clamped."""
    h, w, _ = img.shape
    fy = np.clip((np.arange(dh) + 0.5) * (h / dh) - 0.5, 0, None)
    fx = np.clip((np.arange(dw) + 0.5) * (w / dw) - 0.5, 0, None)
    y0 = np.minimum(fy.astype(int), h - 1)
    x0 = np.minimum(fx.astype(int), w - 1)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (fy - y0)[:, None, None]
    wx = (fx - x0)[None, :, None]
    img = img.astype(np.float32)
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return (top * (1 - wy) + bot * wy + 0.5).astype(np.uint8)


@pytest.mark.parametrize("dh,dw", [(4, 5), (16, 20), (8, 10)])
def test_resize_matches_convention(tmp_path, rng, dh, dw):
    arr = rng.integers(0, 256, (8, 10, 3), dtype=np.uint8)
    _write_ppm(tmp_path / "a.ppm", arr)
    (tmp_path / "l.txt").write_text("a.ppm 1\n")
    ds = nd.NativeListFileDataset(str(tmp_path), str(tmp_path / "l.txt"), dh, dw)
    got = ds.load(0)
    want = _resize_oracle(arr, dh, dw)
    # float rounding at half-ULP boundaries may differ by 1 count
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 1


def test_prefetcher_batch_contract(tmp_path, rng):
    src, lines, images = _make_dataset(tmp_path, rng, n_ids=5, per_id=4)
    ds = nd.NativeListFileDataset(str(tmp_path), src, 8, 10)
    with nd.NativePrefetcher(ds, 3, 2, seed=7, threads=3, prefetch=2) as pf:
        for _ in range(20):
            imgs, labels = next(pf)
            assert imgs.shape == (6, 8, 10, 3) and labels.shape == (6,)
            # identity-balanced: 3 distinct ids x 2 imgs each
            ids, counts = np.unique(labels, return_counts=True)
            assert len(ids) == 3 and (counts == 2).all(), labels
            # every image must be the decode of some dataset item with
            # that label (content round-trip through the C++ pipeline)
            for img, lbl in zip(imgs, labels):
                cands = [
                    images[line.rsplit(None, 1)[0]]
                    for line in lines
                    if int(line.rsplit(None, 1)[1]) == lbl
                ]
                assert any(np.array_equal(img, c) for c in cands)


def test_prefetcher_no_duplicate_images_within_group(tmp_path, rng):
    src, _, _ = _make_dataset(tmp_path, rng, n_ids=3, per_id=4)
    ds = nd.NativeListFileDataset(str(tmp_path), src, 8, 10)
    with nd.NativePrefetcher(ds, 2, 3, seed=0, threads=1) as pf:
        for _ in range(10):
            imgs, labels = next(pf)
            for lbl in np.unique(labels):
                group = imgs[labels == lbl]
                for a in range(len(group)):
                    for b in range(a + 1, len(group)):
                        assert not np.array_equal(group[a], group[b])


def test_errors(tmp_path):
    with pytest.raises(RuntimeError, match="cannot open list file"):
        nd.NativeListFileDataset(str(tmp_path), str(tmp_path / "nope.txt"))
    (tmp_path / "bad.txt").write_text("missing.ppm 0\n")
    ds = nd.NativeListFileDataset(str(tmp_path), str(tmp_path / "bad.txt"), 4, 4)
    with pytest.raises(RuntimeError, match="cannot open file"):
        ds.load(0)
    # too few identities for the batch contract
    (tmp_path / "one.txt").write_text("missing.ppm 0\n")
    ds2 = nd.NativeListFileDataset(str(tmp_path), str(tmp_path / "one.txt"), 4, 4)
    with pytest.raises(RuntimeError, match="identities"):
        nd.NativePrefetcher(ds2, 2, 2)


def test_multibatch_loader_auto_picks_native(tmp_path, rng):
    """multibatch_loader(native='auto') routes a PPM list file with fixed
    resize dims through the C++ runtime and still applies the on-device
    augmentation stack."""
    from npairloss_tpu.config.schema import DataLayerConfig, TransformParam
    from npairloss_tpu.data.loader import (
        MultibatchLoader, NativeMultibatchLoader, multibatch_loader)

    src, _, _ = _make_dataset(tmp_path, rng, n_ids=4, per_id=3, h=8, w=10)
    # mixed formats include .bmp/.npy — all native-supported
    cfg = DataLayerConfig(
        root_folder=str(tmp_path), source=src, batch_size=4,
        new_height=8, new_width=10,
        identity_num_per_batch=2, img_num_per_identity=2,
        transform=TransformParam(crop_size=6, mirror=True),
    )
    with multibatch_loader(cfg, native="auto") as ldr:
        assert isinstance(ldr, NativeMultibatchLoader)
        x, lab = next(ldr)
        assert np.asarray(x).shape == (4, 6, 6, 3)  # cropped on device
        assert lab.shape == (4,)
    with multibatch_loader(cfg, native="never") as ldr:
        assert isinstance(ldr, MultibatchLoader)
    with pytest.raises(RuntimeError, match="new_height"):
        multibatch_loader(
            DataLayerConfig(root_folder=str(tmp_path), source=src),
            native="require",
        )


def test_seeded_runs_deterministic_across_thread_counts(tmp_path, rng):
    """Batches are released in sampler draw order regardless of worker
    count, so seeded runs reproduce like the single-worker Python loader."""
    src, _, _ = _make_dataset(tmp_path, rng, n_ids=6, per_id=4)

    def run(threads):
        ds = nd.NativeListFileDataset(str(tmp_path), src, 8, 10)
        out = []
        with nd.NativePrefetcher(ds, 3, 2, seed=11, threads=threads,
                                 prefetch=3) as pf:
            for _ in range(12):
                imgs, labels = next(pf)
                out.append((imgs.copy(), labels.copy()))
        ds.close()
        return out

    a, b = run(1), run(4)
    for (ia, la), (ib, lb) in zip(a, b):
        np.testing.assert_array_equal(la, lb)
        np.testing.assert_array_equal(ia, ib)


def test_crlf_ppm_decodes_in_register(tmp_path, rng):
    """A PPM whose maxval line ends in CRLF must not shift pixels."""
    arr = rng.integers(0, 256, (5, 6, 3), dtype=np.uint8)
    with open(tmp_path / "crlf.ppm", "wb") as f:
        f.write(b"P6\r\n6 5\r\n255\r\n" + arr.tobytes())
    (tmp_path / "l.txt").write_text("crlf.ppm 0\n")
    ds = nd.NativeListFileDataset(str(tmp_path), str(tmp_path / "l.txt"), 5, 6)
    np.testing.assert_array_equal(ds.load(0), arr)


def test_use_after_close_raises(tmp_path, rng):
    """Closed handles must raise, not pass NULL into the C ABI."""
    src, _, _ = _make_dataset(tmp_path, rng)
    ds = nd.NativeListFileDataset(str(tmp_path), src, 8, 10)
    pf = nd.NativePrefetcher(ds, 2, 2)
    next(pf)
    pf.close()
    with pytest.raises(StopIteration):
        next(pf)
    ds.close()
    with pytest.raises(RuntimeError, match="closed"):
        ds.load(0)


def test_zero_dim_image_rejected(tmp_path):
    """A 0x0 PPM must fail cleanly in decode, not segfault in resize."""
    (tmp_path / "z.ppm").write_bytes(b"P6\n0 0\n255\n")
    (tmp_path / "l.txt").write_text("z.ppm 0\n")
    ds = nd.NativeListFileDataset(str(tmp_path), str(tmp_path / "l.txt"), 4, 4)
    with pytest.raises(RuntimeError, match="positive"):
        ds.load(0)


def test_jpeg_decode_matches_pil(tmp_path, rng):
    """Native JPEG decode (system libjpeg) vs PIL's decode of the same
    file: both sit on libjpeg, so pixels agree (<= 1 count of IDCT
    wiggle).  This is the CUB/SOP format (usage/def.prototxt:17-24) —
    the workload the native runtime was built for."""
    if not nd.native_jpeg_supported():
        pytest.skip("native runtime built without libjpeg")
    from PIL import Image

    arr = rng.integers(0, 256, (24, 32, 3), dtype=np.uint8)
    p = tmp_path / "x.jpg"
    Image.fromarray(arr).save(p, quality=92)
    want = np.asarray(Image.open(p).convert("RGB"))
    (tmp_path / "l.txt").write_text("x.jpg 0\n")
    ds = nd.NativeListFileDataset(str(tmp_path), str(tmp_path / "l.txt"), 24, 32)
    got = ds.load(0)
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 1
    ds.close()


def test_jpeg_grayscale_and_progressive(tmp_path, rng):
    if not nd.native_jpeg_supported():
        pytest.skip("native runtime built without libjpeg")
    from PIL import Image

    gray = rng.integers(0, 256, (16, 16), dtype=np.uint8)
    Image.fromarray(gray, mode="L").save(tmp_path / "g.jpg", quality=95)
    rgbarr = rng.integers(0, 256, (16, 16, 3), dtype=np.uint8)
    Image.fromarray(rgbarr).save(
        tmp_path / "p.jpg", quality=95, progressive=True
    )
    (tmp_path / "l.txt").write_text("g.jpg 0\np.jpg 1\n")
    ds = nd.NativeListFileDataset(str(tmp_path), str(tmp_path / "l.txt"), 16, 16)
    g = ds.load(0)
    want_g = np.asarray(Image.open(tmp_path / "g.jpg").convert("RGB"))
    assert np.abs(g.astype(int) - want_g.astype(int)).max() <= 1
    p = ds.load(1)
    want_p = np.asarray(Image.open(tmp_path / "p.jpg").convert("RGB"))
    assert np.abs(p.astype(int) - want_p.astype(int)).max() <= 1
    ds.close()


def test_jpeg_list_file_routes_native(tmp_path, rng):
    """A JPEG list file keeps the C++ runtime when libjpeg is linked
    (VERDICT r1: real datasets silently fell back to the PIL path)."""
    if not nd.native_jpeg_supported():
        pytest.skip("native runtime built without libjpeg")
    from PIL import Image

    from npairloss_tpu.config.schema import DataLayerConfig, TransformParam
    from npairloss_tpu.data.loader import (
        NativeMultibatchLoader, multibatch_loader)

    lines = []
    for ident in range(4):
        for j in range(2):
            arr = rng.integers(0, 256, (10, 12, 3), dtype=np.uint8)
            name = f"i{ident}_{j}.jpg"
            Image.fromarray(arr).save(tmp_path / name, quality=90)
            lines.append(f"{name} {ident}")
    src = tmp_path / "list.txt"
    src.write_text("\n".join(lines) + "\n")
    cfg = DataLayerConfig(
        root_folder=str(tmp_path), source=str(src), batch_size=4,
        new_height=10, new_width=12,
        identity_num_per_batch=2, img_num_per_identity=2,
        transform=TransformParam(),
    )
    with multibatch_loader(cfg, native="auto") as ldr:
        assert isinstance(ldr, NativeMultibatchLoader)
        x, lab = next(ldr)
        assert np.asarray(x).shape == (4, 10, 12, 3)


def test_corrupt_jpeg_errors_cleanly(tmp_path, rng):
    if not nd.native_jpeg_supported():
        pytest.skip("native runtime built without libjpeg")
    (tmp_path / "bad.jpg").write_bytes(
        b"\xff\xd8\xff\xe0" + bytes(rng.integers(0, 256, 64, dtype=np.uint8))
    )
    (tmp_path / "l.txt").write_text("bad.jpg 0\n")
    ds = nd.NativeListFileDataset(str(tmp_path), str(tmp_path / "l.txt"), 8, 8)
    with pytest.raises(RuntimeError, match="JPEG"):
        ds.load(0)


def test_pnm_long_comment_header(tmp_path, rng):
    """Headers with > 512 bytes of comments parse (ADVICE r1: the old
    bounded-window parser rejected them)."""
    arr = rng.integers(0, 256, (4, 5, 3), dtype=np.uint8)
    with open(tmp_path / "c.ppm", "wb") as f:
        f.write(b"P6\n" + b"# " + b"x" * 700 + b"\n5 4\n255\n" + arr.tobytes())
    (tmp_path / "l.txt").write_text("c.ppm 0\n")
    ds = nd.NativeListFileDataset(str(tmp_path), str(tmp_path / "l.txt"), 4, 5)
    np.testing.assert_array_equal(ds.load(0), arr)


def test_truncated_pnm_header_fails_cleanly(tmp_path):
    """A header that ends at EOF must error, not compute an offset from
    tellg() == -1 (ADVICE r1 UB fix)."""
    for payload in (b"P6", b"P6\n5", b"P6\n5 4\n255"):
        (tmp_path / "t.ppm").write_bytes(payload)
        (tmp_path / "l.txt").write_text("t.ppm 0\n")
        ds = nd.NativeListFileDataset(
            str(tmp_path), str(tmp_path / "l.txt"), 4, 5
        )
        with pytest.raises(RuntimeError, match="PNM"):
            ds.load(0)
        ds.close()


def test_dataset_dims_abi(tmp_path, rng):
    """nd_dataset_dims reports the output buffer shape before loading —
    fixed resize dims, or native dims when unset (ADVICE r1: the sizing
    contract used to be unsatisfiable outside Python)."""
    arr = rng.integers(0, 256, (6, 9, 3), dtype=np.uint8)
    _write_ppm(tmp_path / "d.ppm", arr)
    (tmp_path / "l.txt").write_text("d.ppm 0\n")
    fixed = nd.NativeListFileDataset(str(tmp_path), str(tmp_path / "l.txt"), 4, 5)
    assert fixed.dims(0) == (4, 5)
    fixed.close()
    free = nd.NativeListFileDataset(str(tmp_path), str(tmp_path / "l.txt"))
    assert free.dims(0) == (6, 9)
    free.close()


def test_worker_error_surfaces(tmp_path, rng):
    """A decode failure inside a worker thread must surface in __next__."""
    arr = rng.integers(0, 256, (4, 4, 3), dtype=np.uint8)
    _write_ppm(tmp_path / "ok.ppm", arr)
    (tmp_path / "mix.txt").write_text(
        "ok.ppm 0\nok.ppm 0\nmissing.ppm 1\nmissing.ppm 1\n"
    )
    ds = nd.NativeListFileDataset(str(tmp_path), str(tmp_path / "mix.txt"), 4, 4)
    pf = nd.NativePrefetcher(ds, 2, 2, seed=0, threads=1, prefetch=1)
    with pytest.raises(RuntimeError):
        for _ in range(50):
            next(pf)
    pf.close()
