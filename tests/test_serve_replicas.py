"""Replica tier + SLO-driven admission control (docs/SERVING.md).

Load-bearing pins:
  * replicas share the primary's compiled programs — warming the
    primary warms the tier, and no replica pays (or falsely counts) a
    duplicate XLA compile;
  * the ``serve.replica_crash`` failpoint kills one replica mid-burst
    and the tier REROUTES its in-flight and queued work to the
    survivors with ZERO client-visible errors (the resilience table's
    serving row; the gameday zero-drop gate), while the front end's
    ``queries == answered + errors + rejected`` invariant HOLDS;
  * admission control sheds load exactly while a watched SLO burns
    (the committed evaluator state — the same stream that drives
    alerts), counts every shed once in ``rejected``, keeps a probe
    trickle flowing so recovery stays observable, and readmits on
    clear.
"""

import threading
import time

import numpy as np
import pytest

from npairloss_tpu.obs.live import LiveObservatory
from npairloss_tpu.obs.live.registry import MetricRegistry
from npairloss_tpu.obs.live.slo import SLOSpec, SLOStatus
from npairloss_tpu.resilience import failpoints
from npairloss_tpu.serve import (
    AdmissionConfig,
    AdmissionController,
    BatcherConfig,
    EngineConfig,
    GalleryIndex,
    QueryEngine,
    QueueFullError,
    RetrievalServer,
    ServerConfig,
)
from npairloss_tpu.serve.replicas import ReplicaCrashError


def make_gallery(rng, ids=12, per_id=6, dim=16, noise=0.3):
    centers = rng.standard_normal((ids, dim))
    labels = np.repeat(np.arange(ids), per_id).astype(np.int32)
    emb = centers[labels] + noise * rng.standard_normal(
        (ids * per_id, dim)
    )
    return emb.astype(np.float32), labels


def _tier(rng, n_replicas=2, max_queue=64, admission=None,
          buckets=(1, 4)):
    emb, labels = make_gallery(rng)
    index = GalleryIndex.build(emb, labels)
    cfg = EngineConfig(top_k=3, buckets=buckets)
    primary = QueryEngine(index, cfg)
    engines = [primary] + [
        QueryEngine(index, cfg, share_compiled_with=primary)
        for _ in range(n_replicas - 1)
    ]
    primary.warmup()
    for e in engines[1:]:
        e.warmed = True
    server = RetrievalServer(
        engines,
        BatcherConfig(max_batch=buckets[-1], max_delay_ms=1.0,
                      max_queue=max_queue),
        ServerConfig(metrics_window=0),
        admission=admission,
    )
    return emb, server


# -- compile sharing ----------------------------------------------------------


def test_replicas_share_compiled_programs(rng):
    """After warming the primary ONLY, a shared replica's first real
    dispatch performs zero compiles (shared jit cache + shared
    signature set — it neither recompiles nor miscounts)."""
    emb, labels = make_gallery(rng)
    index = GalleryIndex.build(emb, labels)
    cfg = EngineConfig(top_k=3, buckets=(4,))
    primary = QueryEngine(index, cfg)
    replica = QueryEngine(index, cfg, share_compiled_with=primary)
    primary.warmup()
    replica.warmed = True
    assert replica._topk_fn is primary._topk_fn
    out = replica.query(emb[:4])
    assert out["rows"].shape == (4, 3)
    assert replica.compiles_total == 0
    assert replica.compiles_after_warmup == 0
    assert primary.compiles_after_warmup == 0


def test_share_compiled_with_validates_identity(rng):
    emb, labels = make_gallery(rng)
    index = GalleryIndex.build(emb, labels)
    other_index = GalleryIndex.build(emb, labels)
    cfg = EngineConfig(top_k=3, buckets=(4,))
    primary = QueryEngine(index, cfg)
    with pytest.raises(ValueError, match="same index"):
        QueryEngine(other_index, cfg, share_compiled_with=primary)
    with pytest.raises(ValueError, match="same index"):
        QueryEngine(index, EngineConfig(top_k=4, buckets=(4,)),
                    share_compiled_with=primary)


# -- routing ------------------------------------------------------------------


def test_routing_prefers_least_loaded_live_replica(rng):
    _, server = _tier(rng, n_replicas=3)
    reps = server.replicaset.replicas
    # fake queue depths without starting threads
    reps[0].batcher._q.put(("x", None, 0.0))
    reps[2].alive = False
    assert server.replicaset.pick() is reps[1]
    reps[1].batcher._q.put(("x", None, 0.0))
    reps[1].batcher._q.put(("x", None, 0.0))
    assert server.replicaset.pick() is reps[0]


def test_whole_tier_down_rejects_and_counts(rng):
    _, server = _tier(rng, n_replicas=2)
    for rep in server.replicaset.replicas:
        rep.alive = False
    with pytest.raises(QueueFullError, match="no live replicas"):
        server.submit({"id": 0, "embedding": [0.0] * 16})
    s = server.summary()
    assert s["rejected"] == 1 and s["queries"] == 1
    assert s["queries"] == s["answered"] + s["errors"] + s["rejected"]
    assert s["replicas_alive"] == 0


# -- crash containment --------------------------------------------------------


def test_replica_crash_reroutes_with_zero_client_errors(rng):
    """Kill one of two replicas mid-burst: the crashed replica's
    in-flight batch REROUTES to the survivor (zero client-visible
    errors — the gameday zero-drop contract), later traffic routes to
    the survivor, and the accounting invariant holds end to end."""
    emb, server = _tier(rng, n_replicas=2)
    server.replicaset.start()
    try:
        failpoints.arm("serve.replica_crash", times=1)
        answers = server.handle_many(
            [{"id": i, "embedding": emb[i].tolist()} for i in range(20)],
            timeout=30.0,
        )
        assert server.replicaset.alive_count == 1
        # the survivor keeps serving
        tail = server.handle_many(
            [{"id": 100 + i, "embedding": emb[i].tolist()}
             for i in range(8)],
            timeout=30.0,
        )
    finally:
        failpoints.reset()
        server.replicaset.close(drain=True)
    assert all("neighbors" in a for a in answers + tail), \
        "a replica crash with a survivor must stay client-invisible"
    s = server.summary()
    assert s["replicas"] == 2 and s["replicas_alive"] == 1
    assert s["queries"] == 28
    assert s["answered"] == 28 and s["errors"] == 0
    assert s["queries"] == s["answered"] + s["errors"] + s["rejected"], s


def test_replica_crash_delayed_arming_reroutes_late_batch(rng):
    """``delay`` arming (the name:count@delay grammar): the first
    dispatches pass unharmed, the crash lands mid-stream, and the
    rerouted batch still answers — zero errors end to end."""
    emb, server = _tier(rng, n_replicas=2)
    server.replicaset.start()
    try:
        failpoints.arm("serve.replica_crash", times=1, delay=2)
        answers = []
        for wave in range(4):
            answers += server.handle_many(
                [{"id": wave * 10 + i, "embedding": emb[i].tolist()}
                 for i in range(4)],
                timeout=30.0,
            )
    finally:
        failpoints.reset()
        server.replicaset.close(drain=True)
    assert server.replicaset.alive_count == 1
    assert all("neighbors" in a for a in answers), answers
    s = server.summary()
    assert s["errors"] == 0 and s["answered"] == 16
    assert s["queries"] == s["answered"] + s["errors"] + s["rejected"], s


def test_dead_replica_drains_queued_batches_to_survivor(rng):
    """Work already queued on a crashed replica reroutes to a live
    replica instead of failing — queued batches survive the crash."""
    emb, server = _tier(rng, n_replicas=2)
    rep = server.replicaset.replicas[0]
    fut = rep.batcher.submit({"id": 0, "embedding": emb[0].tolist()})
    rep.alive = False  # crashed between admission and dispatch
    server.replicaset.start()
    try:
        answer = fut.result(timeout=10.0)
        assert "neighbors" in answer, answer
    finally:
        server.replicaset.close(drain=True)


def test_dead_replica_fails_queued_batches_fast_when_tier_down(rng):
    """With NO live replica left, work queued on a crashed replica
    fails with the crash error instead of hanging the caller until
    timeout (the whole-tier-loss boundary of the reroute promise)."""
    emb, server = _tier(rng, n_replicas=1)
    rep = server.replicaset.replicas[0]
    rep.alive = False  # crashed between admission and dispatch
    server.replicaset.start()
    try:
        fut = rep.batcher.submit({"id": 0, "embedding": emb[0].tolist()})
        with pytest.raises(ReplicaCrashError):
            fut.result(timeout=10.0)
    finally:
        server.replicaset.close(drain=True)


# -- dropped-query accounting -------------------------------------------------


def test_queries_dropped_absent_by_default_at_zero(rng):
    """Default posture: ``queries_dropped`` stays absent-when-zero so
    existing drain streams keep byte parity."""
    emb, server = _tier(rng, n_replicas=1)
    server.replicaset.start()
    try:
        server.handle_many(
            [{"id": 0, "embedding": emb[0].tolist()}], timeout=30.0)
    finally:
        server.replicaset.close(drain=True)
    s = server.summary()
    assert "queries_dropped" not in s, s
    assert s["queries"] == s["answered"] + s["errors"] + s["rejected"], s


def test_queries_dropped_explicit_zero_under_gameday_posture(rng):
    """``ServerConfig(explicit_drops=True)`` (the gameday posture)
    writes ``queries_dropped: 0`` into the drain summary and /healthz —
    zero is EVIDENCE there, not a default."""
    emb, labels = make_gallery(rng)
    index = GalleryIndex.build(emb, labels)
    cfg = EngineConfig(top_k=3, buckets=(1, 4))
    primary = QueryEngine(index, cfg)
    primary.warmup()
    server = RetrievalServer(
        [primary],
        BatcherConfig(max_batch=4, max_delay_ms=1.0, max_queue=64),
        ServerConfig(metrics_window=0, explicit_drops=True),
    )
    server.replicaset.start()
    try:
        server.handle_many(
            [{"id": i, "embedding": emb[i].tolist()} for i in range(6)],
            timeout=30.0,
        )
    finally:
        server.replicaset.close(drain=True)
    s = server.summary()
    assert s["queries_dropped"] == 0, s
    assert server.healthz()["queries_dropped"] == 0


# -- admission control --------------------------------------------------------


def _status(name, burning):
    spec = SLOSpec(name=name, metric="m", op="<=", target=1.0)
    return SLOStatus(spec=spec, burning=burning, bad_fraction=1.0,
                     samples=4)


def test_admission_sheds_on_burn_probes_and_readmits():
    reg = MetricRegistry()
    ctl = AdmissionController(
        AdmissionConfig(slo_names=("p99",), probe_every=4),
        registry=reg)
    assert all(ctl.admit() for _ in range(10))  # healthy: admit all

    ctl.on_statuses([_status("p99", True), _status("other", True)])
    assert ctl.shedding
    decisions = [ctl.admit() for _ in range(8)]
    assert decisions == [False, False, False, True] * 2  # probe trickle
    assert ctl.sheds == 6 and ctl.probes_admitted == 2
    assert reg.get("serve_shedding").value == 1.0
    assert reg.get("serve_shed").value == 6

    ctl.on_statuses([_status("p99", False)])
    assert not ctl.shedding
    assert all(ctl.admit() for _ in range(10))
    assert reg.get("serve_shedding").value == 0.0


def test_admission_ignores_unwatched_slos():
    ctl = AdmissionController(AdmissionConfig(slo_names=("p99",)))
    ctl.on_statuses([_status("other", True)])
    assert not ctl.shedding and ctl.admit()


def test_admission_config_validates():
    with pytest.raises(ValueError, match="SLO name"):
        AdmissionConfig(slo_names=())
    with pytest.raises(ValueError, match="probe_every"):
        AdmissionConfig(probe_every=-1)


def test_server_sheds_into_rejected_invariant(rng):
    """A shed is a fast-reject: QueueFullError to the caller, one count
    in ``rejected`` (never errors), invariant intact, and the window/
    summary expose the shed tally."""
    ctl = AdmissionController(
        AdmissionConfig(slo_names=("p99",), probe_every=0))
    emb, server = _tier(rng, n_replicas=1, admission=ctl)
    server.replicaset.start()
    try:
        ok = server.handle_many(
            [{"id": 0, "embedding": emb[0].tolist()}], timeout=30.0)
        assert "neighbors" in ok[0]
        ctl.on_statuses([_status("p99", True)])
        shed = server.handle_many(
            [{"id": i, "embedding": emb[0].tolist()} for i in range(5)],
            timeout=30.0,
        )
        assert all("error" in a and "shed" in a["error"] for a in shed)
        ctl.on_statuses([_status("p99", False)])
        ok2 = server.handle_many(
            [{"id": 9, "embedding": emb[0].tolist()}], timeout=30.0)
        assert "neighbors" in ok2[0]
    finally:
        server.replicaset.close(drain=True)
    s = server.summary()
    assert s["shed"] == 5 and s["shedding"] is False
    assert s["rejected"] == 5 and s["errors"] == 0 and s["answered"] == 2
    assert s["queries"] == s["answered"] + s["errors"] + s["rejected"], s
    h = server.healthz()
    assert h["admission"]["shed"] == 5


def test_single_replica_summary_keeps_pre_tier_shape(rng):
    """No replicas/admission configured -> no new summary keys (the
    byte-parity posture: features off leave the stream untouched)."""
    _, server = _tier(rng, n_replicas=1)
    s = server.summary()
    for key in ("replicas", "replicas_alive", "shed", "shedding"):
        assert key not in s, key


# -- live-obs listener wiring -------------------------------------------------


def test_live_observatory_tick_feeds_listeners(tmp_path):
    """add_listener receives the COMMITTED statuses each tick — the
    admission controller's feed is the exact stream the alert engine
    reads, so shedding and the pager can never disagree."""
    spec = SLOSpec(name="p99", metric="serve_p99_ms", op="<=",
                   target=100.0, window_s=60.0, burn_threshold=0.5,
                   min_samples=1)
    live = LiveObservatory([spec], out_dir=None)
    ctl = AdmissionController(AdmissionConfig(slo_names=("p99",)))
    live.add_listener(ctl.on_statuses)
    t0 = time.time()
    live.registry.set("serve_p99_ms", 500.0, t0)
    live.tick(now=t0 + 1)
    assert ctl.shedding
    # recovery: fresh good samples age the burn out
    for i in range(8):
        live.registry.set("serve_p99_ms", 5.0, t0 + 61 + i)
    live.tick(now=t0 + 70)
    assert not ctl.shedding


def test_listener_failure_never_breaks_the_tick(tmp_path):
    spec = SLOSpec(name="p99", metric="serve_p99_ms", op="<=",
                   target=100.0, min_samples=1)
    live = LiveObservatory([spec], out_dir=None)
    seen = []
    live.add_listener(lambda statuses: 1 / 0)
    live.add_listener(lambda statuses: seen.append(len(statuses)))
    live.registry.set("serve_p99_ms", 5.0, time.time())
    live.tick()
    assert seen == [1]
