"""IVF approximate index: recall parity vs the brute-force oracle,
shared-kmeans identity, packing/degenerate cases, add-republish, and
persistence (docs/SERVING.md §Approximate index).

The load-bearing contract: with ``probes >= n_clusters`` every cluster
is scored, so the IVF answer SET must equal the flat exact scan's at
fp32 scoring — on one device and on the 8-device mesh.  Partial probes
and reduced scoring dtypes trade recall for latency; those floors are
pinned here and gated in the ``ivf_qps_1m`` bench row.
"""

import numpy as np
import pytest

import jax

from npairloss_tpu.parallel.mesh import data_parallel_mesh
from npairloss_tpu.serve import EngineConfig, GalleryIndex, QueryEngine
from npairloss_tpu.serve.ivf import IVFIndex, topk_recall


def _mesh(width):
    if width == 1:
        return None
    return data_parallel_mesh(jax.devices()[:width])


def _clustered_data(rng, n_clusters=16, per=40, dim=24, spread=0.12):
    """Well-separated gaussian blobs: the geometry IVF exists for."""
    centers = rng.standard_normal((n_clusters, dim)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    emb = np.repeat(centers, per, axis=0) + spread * rng.standard_normal(
        (n_clusters * per, dim)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    lab = np.repeat(np.arange(n_clusters), per).astype(np.int32)
    return emb, lab


def _queries(rng, emb, n=24, noise=0.05):
    q = emb[rng.choice(emb.shape[0], n, replace=False)]
    q = q + noise * rng.standard_normal(q.shape).astype(np.float32)
    return q / np.linalg.norm(q, axis=1, keepdims=True)


# -- one implementation of k-means ------------------------------------------


def test_kmeans_is_the_shared_implementation():
    """eval_retrieval's NMI k-means and the IVF builder's k-means must
    be the SAME objects (ops.kmeans) — the identity pin that keeps the
    offline clustering metric and the serving index from drifting."""
    from npairloss_tpu.ops import eval_retrieval, kmeans
    from npairloss_tpu.serve import ivf

    assert eval_retrieval.kmeans_assign is kmeans.kmeans_assign
    assert ivf.kmeans_fit is kmeans.kmeans_fit
    assert ivf.assign_to_centroids is kmeans.assign_to_centroids


def test_kmeans_fit_agrees_with_kmeans_assign(rng):
    """Unsampled kmeans_fit + streamed assignment == the one-shot
    jitted kmeans_assign (same seeding, same Lloyd steps)."""
    from npairloss_tpu.ops.kmeans import (
        assign_to_centroids,
        kmeans_assign,
        kmeans_fit,
    )

    emb, _ = _clustered_data(rng, n_clusters=8, per=25, dim=16)
    a_ref = np.asarray(kmeans_assign(emb, 8, iters=10, seed=3))
    cents = kmeans_fit(emb, 8, iters=10, seed=3, train_size=None)
    a_fit = assign_to_centroids(emb, cents, block=64)
    np.testing.assert_array_equal(a_ref, a_fit)


def test_kmeans_fit_sampled_still_covers_clusters(rng):
    """A subsampled fit must still place usable centroids: assignments
    land every point in SOME cluster and the blob structure survives
    (every true blob maps to a dominant fitted cluster)."""
    from npairloss_tpu.ops.kmeans import assign_to_centroids, kmeans_fit

    emb, lab = _clustered_data(rng, n_clusters=6, per=50, dim=16)
    cents = kmeans_fit(emb, 6, iters=10, seed=0, train_size=120)
    assign = assign_to_centroids(emb, cents, block=100)
    assert assign.shape == (300,)
    assert assign.min() >= 0 and assign.max() < 6
    for c in range(6):
        vals, counts = np.unique(assign[lab == c], return_counts=True)
        assert counts.max() / 50 >= 0.9  # blob stays together


# -- recall parity vs the flat oracle ----------------------------------------


@pytest.mark.parametrize("mesh_width", [1, 8])
def test_full_probe_matches_flat_exactly(rng, mesh_width):
    """probes >= n_clusters scores every gallery row: the IVF answer
    SET must equal the brute-force oracle's at fp32 — recall exactly
    1.0 on every mesh width."""
    mesh = _mesh(mesh_width)
    emb, lab = _clustered_data(rng)
    q = _queries(rng, emb)
    flat = GalleryIndex.build(emb, lab, mesh=mesh, normalize=False)
    oracle = QueryEngine(flat, EngineConfig(top_k=10, buckets=(24,)))
    ivf = IVFIndex.build_ivf(emb, lab, mesh=mesh, normalize=False,
                             clusters=13, train_size=None)
    eng = QueryEngine(ivf, EngineConfig(top_k=10, buckets=(24,),
                                        probes=13))
    r = topk_recall(eng.query(q)["rows"], oracle.query(q)["rows"])
    assert r == 1.0


@pytest.mark.parametrize("scoring,floor", [("bf16", 0.9), ("int8", 0.85)])
def test_reduced_scoring_recall_floor(rng, scoring, floor):
    """bf16/int8 cluster-scan scoring at FULL probe: the only error
    source is the matmul dtype, and recall vs the fp32 oracle must
    stay above the floor (the parity gate the bench row hardens)."""
    emb, lab = _clustered_data(rng)
    q = _queries(rng, emb)
    flat = GalleryIndex.build(emb, lab, normalize=False)
    oracle = QueryEngine(flat, EngineConfig(top_k=10, buckets=(24,)))
    ivf = IVFIndex.build_ivf(emb, lab, normalize=False, clusters=13,
                             train_size=None)
    eng = QueryEngine(ivf, EngineConfig(top_k=10, buckets=(24,),
                                        probes=13, scoring=scoring))
    r = topk_recall(eng.query(q)["rows"], oracle.query(q)["rows"])
    assert r >= floor, f"{scoring} recall {r}"


@pytest.mark.parametrize("mesh_width", [1, 8])
@pytest.mark.parametrize("probes", [1, 4])
def test_partial_probe_recall_on_clustered_data(rng, mesh_width, probes):
    """On separated blobs a query's true neighbors share its blob, so
    even probes=1 must find most of them; recall grows with probes and
    the mesh path agrees with single-device."""
    emb, lab = _clustered_data(rng)
    q = _queries(rng, emb)
    flat = GalleryIndex.build(emb, lab, normalize=False)
    oracle_rows = QueryEngine(
        flat, EngineConfig(top_k=10, buckets=(24,))).query(q)["rows"]
    mesh = _mesh(mesh_width)
    ivf = IVFIndex.build_ivf(emb, lab, mesh=mesh, normalize=False,
                             clusters=16, train_size=None)
    eng = QueryEngine(ivf, EngineConfig(top_k=10, buckets=(24,),
                                        probes=probes))
    r = topk_recall(eng.query(q)["rows"], oracle_rows)
    assert r >= 0.75, f"probes={probes} recall {r}"


def test_mesh_and_single_device_probe_same_clusters(rng):
    """The mesh merge is a layout detail, not a semantic one: the same
    probe set scored across 8 shards must return the same answer SET
    as one device (scores bit-compare too at fp32)."""
    emb, lab = _clustered_data(rng)
    q = _queries(rng, emb)
    outs = []
    for width in (1, 8):
        ivf = IVFIndex.build_ivf(emb, lab, mesh=_mesh(width),
                                 normalize=False, clusters=12,
                                 train_size=None)
        eng = QueryEngine(ivf, EngineConfig(top_k=8, buckets=(24,),
                                            probes=5))
        outs.append(eng.query(q))
    np.testing.assert_allclose(outs[0]["scores"], outs[1]["scores"],
                               atol=1e-6)
    assert topk_recall(outs[0]["rows"], outs[1]["rows"]) == 1.0


# -- degenerate cases ---------------------------------------------------------


def test_fewer_clusters_than_probes(rng):
    """probes clamps to the cluster count — a 3-cluster index probed
    with 8 is just a full scan, exact vs the oracle."""
    emb, lab = _clustered_data(rng, n_clusters=4, per=30)
    q = _queries(rng, emb, n=8)
    flat = GalleryIndex.build(emb, lab, normalize=False)
    oracle = QueryEngine(flat, EngineConfig(top_k=5, buckets=(8,)))
    ivf = IVFIndex.build_ivf(emb, lab, normalize=False, clusters=3,
                             train_size=None)
    eng = QueryEngine(ivf, EngineConfig(top_k=5, buckets=(8,), probes=8))
    assert topk_recall(eng.query(q)["rows"],
                       oracle.query(q)["rows"]) == 1.0


@pytest.mark.parametrize("mesh_width", [1, 8])
def test_empty_clusters_never_pollute_answers(rng, mesh_width):
    """More centroids than distinct points forces duplicate centroids
    and EMPTY clusters (plus mesh padding clusters on width 8); no
    -1 pad row may ever reach an answer, and the answer must still be
    the exact top-k."""
    base = rng.standard_normal((6, 16)).astype(np.float32)
    emb = np.repeat(base, 4, axis=0)  # 24 rows, only 6 distinct points
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    lab = np.repeat(np.arange(6), 4).astype(np.int32)
    q = emb[:5]
    mesh = _mesh(mesh_width)
    ivf = IVFIndex.build_ivf(emb, lab, mesh=mesh, normalize=False,
                             clusters=10, train_size=None)
    sizes = np.bincount(ivf.assign_host, minlength=10)
    assert (sizes == 0).any(), "fixture must actually produce empties"
    eng = QueryEngine(ivf, EngineConfig(top_k=4, buckets=(8,),
                                        probes=10))
    out = eng.query(q)
    assert (out["rows"] >= 0).all() and (out["rows"] < 24).all()
    flat = GalleryIndex.build(emb, lab, normalize=False)
    oracle = QueryEngine(flat, EngineConfig(top_k=4, buckets=(8,)))
    assert topk_recall(out["rows"], oracle.query(q)["rows"]) == 1.0


def test_probe_set_smaller_than_top_k_pads_safely(rng):
    """A probe set that cannot yield top_k candidates (one probed
    1-row cluster per query) pads with -inf scores and VALID row 0 —
    the host label/id mapping must never index a sentinel."""
    emb = np.eye(8, 16, dtype=np.float32)  # orthogonal: 1 row/cluster
    lab = np.arange(8, dtype=np.int32)
    ivf = IVFIndex.build_ivf(emb, lab, normalize=False, clusters=8,
                             train_size=None)
    eng = QueryEngine(ivf, EngineConfig(top_k=4, buckets=(4,), probes=1))
    out = eng.query(emb[:3])
    assert out["rows"].shape == (3, 4)
    assert (out["rows"] >= 0).all()
    # the real candidate leads; the padded tail carries -inf scores
    assert (out["scores"][:, 0] > 0.99).all()
    assert (out["scores"][:, 1:] < -1e30).all()


def test_int8_requires_ivf(rng):
    emb, lab = _clustered_data(rng, n_clusters=4, per=10)
    flat = GalleryIndex.build(emb, lab, normalize=False)
    with pytest.raises(ValueError, match="int8"):
        QueryEngine(flat, EngineConfig(top_k=2, buckets=(4,),
                                       scoring="int8"))


def test_engine_config_validates_scoring_and_probes():
    with pytest.raises(ValueError, match="scoring"):
        EngineConfig(scoring="fp16")
    with pytest.raises(ValueError, match="probes"):
        EngineConfig(probes=0)


# -- add() / atomic republish -------------------------------------------------


@pytest.mark.parametrize("mesh_width", [1, 8])
def test_add_reassigns_into_existing_clusters(rng, mesh_width):
    """add() assigns new rows to their nearest EXISTING centroid and
    republishes atomically: the layout object is REPLACED (not
    mutated), the cluster count is unchanged, and a full-probe query
    afterwards is exact over the union gallery."""
    emb, lab = _clustered_data(rng, n_clusters=8, per=25)
    mesh = _mesh(mesh_width)
    ivf = IVFIndex.build_ivf(emb, lab, mesh=mesh, normalize=False,
                             clusters=8, train_size=None)
    eng = QueryEngine(ivf, EngineConfig(top_k=6, buckets=(8,), probes=8))
    old_layout = ivf.layout
    q = _queries(rng, emb, n=8)
    eng.query(q)  # warm the pre-add shapes

    extra, extra_lab = _clustered_data(rng, n_clusters=8, per=5)
    ivf.add(extra, extra_lab, normalize=False)
    assert ivf.layout is not old_layout, "republish must swap, not mutate"
    assert ivf.layout.n_clusters == old_layout.n_clusters
    assert ivf.size == 240
    assert ivf.assign_host.shape == (240,)
    # new rows went to their nearest centroid
    from npairloss_tpu.ops.kmeans import assign_to_centroids

    np.testing.assert_array_equal(
        ivf.assign_host[200:],
        assign_to_centroids(
            extra / np.linalg.norm(extra, axis=1, keepdims=True),
            ivf.centroids_host))

    all_emb = np.concatenate([emb, extra])
    all_emb /= np.linalg.norm(all_emb, axis=1, keepdims=True)
    all_lab = np.concatenate([lab, extra_lab])
    flat = GalleryIndex.build(all_emb, all_lab, normalize=False)
    oracle = QueryEngine(flat, EngineConfig(top_k=6, buckets=(8,)))
    assert topk_recall(eng.query(q)["rows"],
                       oracle.query(q)["rows"]) == 1.0


def test_add_invalidates_scored_cache(rng):
    """The bf16/int8 slabs derive from the layout; a republish must
    rebuild them (a stale quantized slab would silently drop the new
    rows from every int8 answer)."""
    emb, lab = _clustered_data(rng, n_clusters=4, per=10)
    ivf = IVFIndex.build_ivf(emb, lab, normalize=False, clusters=4,
                             train_size=None)
    slab8, scale8 = ivf.scored_arrays("int8")
    assert ivf.scored_arrays("int8")[0] is slab8  # cached
    ivf.add(emb[:4] + 0.01, lab[:4])
    slab8b, _ = ivf.scored_arrays("int8")
    assert slab8b is not slab8


# -- persistence --------------------------------------------------------------


def test_ivf_save_load_roundtrip(rng, tmp_path):
    """Commit + restore under kind ivf-index: same centroids/assign,
    same answers; load_index dispatches on the manifest kind."""
    from npairloss_tpu.serve.index import load_index, read_manifest

    emb, lab = _clustered_data(rng, n_clusters=6, per=20)
    ivf = IVFIndex.build_ivf(emb, lab, normalize=False, clusters=6,
                             train_size=None)
    path = str(tmp_path / "g.ivf.gidx")
    ivf.save(path)
    m = read_manifest(path)
    assert m["kind"] == "ivf-index" and m["n_clusters"] == 6

    restored = load_index(path)
    assert isinstance(restored, IVFIndex)
    np.testing.assert_array_equal(restored.assign_host, ivf.assign_host)
    np.testing.assert_allclose(restored.centroids_host,
                               ivf.centroids_host)
    q = _queries(rng, emb, n=8)
    cfg = EngineConfig(top_k=5, buckets=(8,), probes=3)
    a = QueryEngine(ivf, cfg).query(q)
    b = QueryEngine(restored, cfg).query(q)
    np.testing.assert_array_equal(a["rows"], b["rows"])
    np.testing.assert_allclose(a["scores"], b["scores"], atol=1e-6)


def test_flat_loader_refuses_ivf_commit(rng, tmp_path):
    """GalleryIndex.load on an ivf-index commit fails validation loudly
    (kind mismatch) instead of serving half an index."""
    from npairloss_tpu.resilience.snapshot import SnapshotValidationError

    emb, lab = _clustered_data(rng, n_clusters=4, per=10)
    ivf = IVFIndex.build_ivf(emb, lab, normalize=False, clusters=4,
                             train_size=None)
    path = str(tmp_path / "g.ivf.gidx")
    ivf.save(path)
    with pytest.raises(SnapshotValidationError, match="kind"):
        GalleryIndex.load(path)


def test_load_newest_serves_mixed_kinds(rng, tmp_path):
    """A serving prefix can mix flat and ivf commits; load_newest picks
    the newest valid one whatever its kind."""
    from npairloss_tpu.serve.index import load_newest

    emb, lab = _clustered_data(rng, n_clusters=4, per=10)
    GalleryIndex.build(emb, lab, normalize=False).save(
        str(tmp_path / "g.0001.gidx"))
    IVFIndex.build_ivf(emb, lab, normalize=False, clusters=4,
                       train_size=None).save(
        str(tmp_path / "g.0002.gidx"))
    path, idx = load_newest(str(tmp_path / "g"))
    assert path.endswith("g.0002.gidx")
    assert isinstance(idx, IVFIndex)


# -- recall harness sanity ----------------------------------------------------


def test_topk_recall_counts_set_overlap():
    a = np.array([[1, 2, 3], [4, 5, 6]])
    b = np.array([[3, 2, 9], [4, 5, 6]])
    assert topk_recall(a, b) == pytest.approx((2 + 3) / 6)
    assert topk_recall(a, b, k=1) == pytest.approx(0.5)
    with pytest.raises(ValueError):
        topk_recall(a, b[:1])
