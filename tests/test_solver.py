"""Solver-loop tests: Caffe SGD semantics, lr policies, end-to-end training
(the SURVEY.md §4 integration tier), snapshots, and the sharded step."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from npairloss_tpu import MiningMethod, MiningRegion, NPairLossConfig
from npairloss_tpu.data import synthetic_identity_batches
from npairloss_tpu.models import get_model
from npairloss_tpu.parallel import data_parallel_mesh
from npairloss_tpu.train import Solver, SolverConfig, caffe_sgd, lr_schedule


def test_lr_policies():
    # step: base * gamma^floor(it/stepsize)  (solver.prototxt:8-10 semantics)
    f = lr_schedule("step", 0.001, gamma=0.5, stepsize=10)
    assert float(f(0)) == pytest.approx(0.001)
    assert float(f(9)) == pytest.approx(0.001)
    assert float(f(10)) == pytest.approx(0.0005)
    assert float(f(25)) == pytest.approx(0.00025)
    f = lr_schedule("fixed", 0.1)
    assert float(f(12345)) == pytest.approx(0.1)
    f = lr_schedule("poly", 1.0, power=2.0, max_iter=100)
    assert float(f(50)) == pytest.approx(0.25)
    f = lr_schedule("multistep", 1.0, gamma=0.1, stepvalues=(5, 8))
    assert float(f(4)) == pytest.approx(1.0)
    assert float(f(5)) == pytest.approx(0.1)
    assert float(f(8)) == pytest.approx(0.01)
    f = lr_schedule("inv", 1.0, gamma=0.5, power=1.0)
    assert float(f(2)) == pytest.approx(0.5)


def test_caffe_sgd_lr_inside_momentum():
    """v = mu*v + lr*(g + wd*w); w -= v — lr folded BEFORE momentum, so a
    lr drop mid-run decays the buffer differently from optax.sgd."""
    lr0, lr1, mu, wd = 0.1, 0.05, 0.9, 0.01
    rates = [lr0, lr1]
    tx = caffe_sgd(lambda s: jnp.float32(rates[int(s)] if int(s) < 2 else lr1), mu, wd)
    w = jnp.asarray([1.0])
    g = jnp.asarray([2.0])
    state = tx.init(w)
    upd, state = tx.update(g, state, w)
    v1 = lr0 * (2.0 + wd * 1.0)
    np.testing.assert_allclose(np.asarray(upd), [-v1], rtol=1e-6)
    w = w + upd[0]
    upd, state = tx.update(g, state, w)
    v2 = mu * v1 + lr1 * (2.0 + wd * float(w[0]))
    np.testing.assert_allclose(np.asarray(upd), [-v2], rtol=1e-6)


def _make_solver(mesh=None, ids_per_batch=16):
    cfg = SolverConfig(
        base_lr=0.5, lr_policy="fixed", momentum=0.9, weight_decay=0.0,
        display=0, test_interval=0, snapshot=0, average_loss=10,
    )
    loss_cfg = NPairLossConfig(
        margin_diff=-0.05,
        an_mining_method=MiningMethod.HARD,
        ap_mining_method=MiningMethod.RAND,
    )
    model = get_model("mlp", hidden=(64,), embedding_dim=32)
    return Solver(
        model, loss_cfg, cfg, mesh=mesh, input_shape=(16,),
    ), synthetic_identity_batches(ids_per_batch, ids_per_batch, 2, (16,), noise=0.6)


def test_training_learns_single_device():
    solver, batches = _make_solver()
    first = None
    for i in range(150):
        x, lab = next(batches)
        m = solver.step(x, lab)
        if first is None:
            first = float(m["retrieve_top1"])
    final = float(m["retrieve_top1"])
    assert final > 0.9, f"recall@1 {first} -> {final}"
    assert float(m["loss"]) < 0.5


def test_blockwise_engine_matches_dense_solver_step():
    """engine="blockwise" routes the Solver's loss through the Pallas
    streaming engine; the resulting parameter updates must match the
    dense engine's step for step (the engines are loss/grad-parity
    pinned, so any drift here is solver wiring, not math)."""
    cfg = SolverConfig(
        base_lr=0.5, lr_policy="fixed", momentum=0.9, weight_decay=0.0,
        display=0, test_interval=0, snapshot=0,
    )
    loss_cfg = NPairLossConfig(
        margin_diff=-0.05,
        an_mining_method=MiningMethod.HARD,
        ap_mining_method=MiningMethod.RAND,
    )
    batches = synthetic_identity_batches(8, 8, 2, (16,), noise=0.6)
    solvers = [
        Solver(get_model("mlp", hidden=(64,), embedding_dim=32), loss_cfg,
               cfg, input_shape=(16,), engine=eng)
        for eng in ("dense", "blockwise")
    ]
    for i in range(3):
        x, lab = next(batches)
        m_d = solvers[0].step(x, lab)
        m_b = solvers[1].step(x, lab)
        np.testing.assert_allclose(
            float(m_b["loss"]), float(m_d["loss"]), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            float(m_b["retrieve_top1"]), float(m_d["retrieve_top1"]),
            rtol=1e-6,
        )
    deltas = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))),
        solvers[0].state["params"], solvers[1].state["params"],
    )
    assert max(jax.tree_util.tree_leaves(deltas)) < 1e-4, deltas

    with pytest.raises(ValueError):
        Solver(get_model("mlp"), loss_cfg, cfg, engine="blockwise",
               mesh=data_parallel_mesh())


def test_train_loop_with_eval_and_window(caplog):
    solver, batches = _make_solver()
    test_cfg = SolverConfig(
        base_lr=0.5, lr_policy="fixed", display=5, average_loss=5,
        test_interval=10, test_iter=2, test_initialization=True, snapshot=0,
    )
    solver.cfg = test_cfg
    logs = []
    last = solver.train(batches, num_iters=20, test_batches=batches, log_fn=logs.append)
    assert any("TEST" in line for line in logs)
    assert any("iter 5 " in line for line in logs)
    assert "retrieve_top1" in last


def test_snapshot_roundtrip(tmp_path):
    solver, batches = _make_solver()
    solver.cfg.snapshot_prefix = str(tmp_path / "snap_")
    x, lab = next(batches)
    solver.step(x, lab)
    path = solver.save_snapshot(1)
    before = jax.tree_util.tree_map(np.asarray, solver.state["params"])
    for _ in range(5):
        x, lab = next(batches)
        solver.step(x, lab)
    solver.restore_snapshot(path)
    after = jax.tree_util.tree_map(np.asarray, solver.state["params"])
    jax.tree_util.tree_map(np.testing.assert_array_equal, before, after)
    # training continues from the restored state
    x, lab = next(batches)
    m = solver.step(x, lab)
    assert np.isfinite(m["loss"])


@pytest.mark.slow
def test_training_learns_sharded_mesh():
    """Full solver step over the virtual 8-device mesh: sharded batch,
    all_gather negative pool, replicated params."""
    mesh = data_parallel_mesh(jax.devices()[:8])
    solver, batches = _make_solver(mesh=mesh)
    for i in range(100):
        x, lab = next(batches)
        m = solver.step(x, lab)
    assert float(m["retrieve_top1"]) > 0.85
    assert np.isfinite(float(m["loss"]))


@pytest.mark.slow  # ~31s; tier-1 budget, run with -m slow
def test_batchnorm_model_trains():
    """Mutable batch_stats path (resnet18 on tiny inputs)."""
    cfg = SolverConfig(base_lr=0.01, lr_policy="fixed", display=0, snapshot=0)
    model = get_model("resnet18", dtype=jnp.float32)
    solver = Solver(model, NPairLossConfig(), cfg, input_shape=(16, 16, 3))
    batches = synthetic_identity_batches(4, 4, 2, (16, 16, 3), noise=0.3)
    for _ in range(2):
        x, lab = next(batches)
        m = solver.step(x, lab)
    assert np.isfinite(float(m["loss"]))
    assert solver.state["batch_stats"], "batch_stats should be tracked"


def test_iteration_resume_cadence(tmp_path):
    """Caffe solverstate semantics: a solver restored from the iter-k
    snapshot resumes at k+1 with the snapshot/display cadence aligned —
    the next snapshot lands at k + cfg.snapshot (solver.prototxt:15-16)."""
    import dataclasses

    solver, batches = _make_solver()
    # A DECAYING schedule (step every 2 iters) so the final lr assertion
    # can actually detect a lost optimizer step counter on restore.
    solver.cfg = dataclasses.replace(
        solver.cfg, lr_policy="step", stepsize=2, gamma=0.5,
        snapshot=3, snapshot_prefix=str(tmp_path / "snap_"),
    )
    logs = []
    solver.train(batches, num_iters=4, log_fn=logs.append)
    assert solver.iteration == 4
    path3 = solver.snapshot_path(3)
    import os

    assert os.path.exists(path3)  # snapshot fired at iter 3

    # Fresh solver restores the iter-3 snapshot: iteration comes back
    # from the optimizer step inside the checkpoint, not from the path.
    solver2, batches2 = _make_solver()
    solver2.cfg = dataclasses.replace(
        solver2.cfg, lr_policy="step", stepsize=2, gamma=0.5,
        snapshot=3, snapshot_prefix=str(tmp_path / "snap_"),
    )
    solver2.restore_snapshot(path3)
    assert solver2.iteration == 3

    logs2 = []
    last = solver2.train(batches2, num_iters=7, log_fn=logs2.append)
    assert any("resuming from iteration 3" in line for line in logs2)
    assert solver2.iteration == 7
    # Cadence continued from 3: snapshot fired at 6 (3 + snapshot), not 7.
    assert os.path.exists(solver2.snapshot_path(6))
    assert not os.path.exists(solver2.snapshot_path(7))

    # The lr schedule resumed from the restored counter: the final step
    # (it=6) applied rate(6) = base * gamma^floor(6/2), which a restore
    # that reset the step to 0 would report as base * gamma^floor(3/2).
    assert float(last["lr"]) == pytest.approx(0.5 * 0.5**3)


def test_caffe_sgd_param_mults_bias_recipe():
    """param_mults=((1,1),(2,0)) — the reference template's recipe —
    must give biases 2x the learning rate and exempt them from weight
    decay, with weights unchanged vs the uniform optimizer."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from npairloss_tpu.train.optim import caffe_sgd, lr_schedule

    rate = lr_schedule("fixed", 0.1)
    # Structural classification: a "bias" whose parent also holds a
    # "kernel" is a conv/dense second blob — under ANY module name
    # (mlp's custom "dense0" caught a name-prefix version silently
    # no-opping) — while BatchNorm beta (bias + scale, no kernel) must
    # NOT inherit the conv recipe.
    params = {"blk": {"Conv_0": {"kernel": jnp.ones((2, 2)),
                                 "bias": jnp.ones((2,))},
                      "BatchNorm_0": {"bias": jnp.ones((2,)),
                                      "scale": jnp.ones((2,))}},
              "dense0": {"kernel": jnp.ones((2, 2)),
                         "bias": jnp.ones((2,))}}
    grads = jax.tree_util.tree_map(lambda a: jnp.full_like(a, 0.5), params)

    tx = caffe_sgd(rate, momentum=0.0, weight_decay=0.01,
                   param_mults=((1.0, 1.0), (2.0, 0.0)))
    upd, _ = tx.update(grads, tx.init(params), params)
    # weights: -lr * (g + wd*w) = -0.1 * (0.5 + 0.01) = -0.051
    np.testing.assert_allclose(
        np.asarray(upd["blk"]["Conv_0"]["kernel"]), -0.051, rtol=1e-6)
    # conv bias: -lr * 2 * g (no decay) = -0.1 * 2 * 0.5 = -0.1
    np.testing.assert_allclose(
        np.asarray(upd["blk"]["Conv_0"]["bias"]), -0.1, rtol=1e-6)
    # Custom-named dense layer: same recipe by structure, not by name.
    np.testing.assert_allclose(
        np.asarray(upd["dense0"]["bias"]), -0.1, rtol=1e-6)
    # BatchNorm beta/gamma: NOT a conv bias — weight recipe applies.
    np.testing.assert_allclose(
        np.asarray(upd["blk"]["BatchNorm_0"]["bias"]), -0.051, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(upd["blk"]["BatchNorm_0"]["scale"]), -0.051, rtol=1e-6)

    # Uniform (param_mults=None) treats every leaf identically.
    tx_u = caffe_sgd(rate, momentum=0.0, weight_decay=0.01)
    upd_u, _ = tx_u.update(grads, tx_u.init(params), params)
    np.testing.assert_allclose(
        np.asarray(upd_u["blk"]["Conv_0"]["bias"]), -0.051, rtol=1e-6)


def test_loss_weight_scales_objective_and_gradient():
    """The loss top's loss_weight scales the whole backward (reference
    cu:435) and the displayed objective; weight 2 must double both vs
    weight 1."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from npairloss_tpu import NPairLossConfig
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    from conftest import make_identity_batch

    rng = np.random.default_rng(0)
    (f,), (l,) = make_identity_batch(rng, 4, 2, 8)

    def one_step(weight):
        s = Solver(
            get_model("mlp", hidden=(8,), embedding_dim=4),
            NPairLossConfig(),
            SolverConfig(base_lr=0.1, lr_policy="fixed", momentum=0.0,
                         weight_decay=0.0, display=0, snapshot=0),
            input_shape=(8,),
            loss_weight=weight,
        )
        s.init(f[:2])
        before = jax.tree_util.tree_map(np.asarray, s.state["params"])
        m = s.step(f, l)
        after = jax.tree_util.tree_map(np.asarray, s.state["params"])
        delta = jax.tree_util.tree_map(lambda a, b: b - a, before, after)
        return float(m["loss"]), delta

    loss1, d1 = one_step(1.0)
    loss2, d2 = one_step(2.0)
    np.testing.assert_allclose(loss2, 2 * loss1, rtol=1e-5)

    def close(a, b):
        # The compared quantity is a DIFFERENCE of fp32-rounded params
        # (after - before): each operand rounds to fp32 at O(1) param
        # magnitude, so the delta's absolute error is bounded by
        # ~eps_f32 * |param| ≈ 1.2e-7 per rounding, NOT by the delta's
        # own (much smaller) magnitude — rtol alone cannot cover
        # near-cancelling entries, and the old atol=1e-8 sat below one
        # rounding ulp (observed flake: 1/64 elements off by 6e-8).
        # Four ulps at unit scale covers both runs' roundings on both
        # sides of the 2x comparison.
        atol = 4 * np.finfo(np.float32).eps
        np.testing.assert_allclose(b, 2 * a, rtol=1e-4, atol=atol)

    jax.tree_util.tree_map(close, d1, d2)
