"""Recall@k / feature_asum parity vs the oracle (cu:173-206, cu:390-401)."""

import jax
import numpy as np
import pytest

from conftest import make_identity_batch
from npairloss_tpu import NPairLossConfig
from npairloss_tpu.ops.metrics import feature_asum, recall_at_k, retrieval_metrics
from npairloss_tpu.ops.npair_loss import npair_loss_with_aux
from npairloss_tpu.testing import oracle


@pytest.mark.parametrize("k", [1, 5, 10])
def test_recall_matches_oracle(rng, k):
    feats, labs = make_identity_batch(rng, 8, 2, 16)
    cfg = NPairLossConfig()
    want = oracle.forward(feats, labs, cfg, top_ks=(k,))[0]
    _, aux = jax.jit(lambda f, l: npair_loss_with_aux(f, l, cfg))(feats[0], labs[0])
    got = recall_at_k(aux["sim_exp"], labs[0], aux["total_labels"], aux["rank"], k)
    np.testing.assert_allclose(float(got), want.recalls[k], atol=1e-7)


def test_recall_perfect_on_separable(rng):
    """Tight clusters per identity -> Recall@1 == 1."""
    num_ids, dim = 6, 16
    centers = np.eye(num_ids, dim, dtype=np.float32)
    f = np.repeat(centers, 2, axis=0) + 0.01 * rng.standard_normal((num_ids * 2, dim)).astype(np.float32)
    f /= np.linalg.norm(f, axis=1, keepdims=True)
    lab = np.repeat(np.arange(num_ids), 2).astype(np.int32)
    _, aux = jax.jit(lambda a, b: npair_loss_with_aux(a, b))(f, lab)
    got = recall_at_k(aux["sim_exp"], lab, aux["total_labels"], aux["rank"], 1)
    assert float(got) == 1.0


def test_threshold_tie_not_counted(rng):
    """cu:197 uses a strict '>' — an item exactly at the threshold is a miss.

    Craft: 3 items, query 0; with k=1 and list size 2, threshold index
    min(1, 1) = 1 -> the SMALLER of the two non-self sims.  If the same-label
    item ties the threshold (equal sims), it must not count.
    """
    f = np.array(
        [[1.0, 0.0], [0.5, 0.5], [0.5, 0.5]], dtype=np.float32
    )  # sims from q0 to items 1,2 are equal -> threshold == both values
    lab = np.array([0, 0, 1], dtype=np.int32)
    _, aux = jax.jit(lambda a, b: npair_loss_with_aux(a, b))(f, lab)
    got = recall_at_k(aux["sim_exp"], lab, aux["total_labels"], aux["rank"], 1)
    want = oracle.forward([f], [lab], NPairLossConfig(), top_ks=(1,))[0].recalls[1]
    assert float(got) == want
    # query 0's same-label item ties the threshold -> not retrieved
    assert want < 1.0


def test_feature_asum(rng):
    feats, labs = make_identity_batch(rng, 4, 2, 8)
    want = oracle.forward(feats, labs, NPairLossConfig())[0].feature_asum
    got = feature_asum(feats[0])
    np.testing.assert_allclose(float(got), want, rtol=1e-6)


def test_retrieval_metrics_names(rng):
    """Top names mirror def.prototxt:127-131."""
    feats, labs = make_identity_batch(rng, 4, 2, 8)
    _, aux = npair_loss_with_aux(feats[0], labs[0])
    m = retrieval_metrics(aux, labs[0], feats[0])
    assert set(m) == {"retrieve_top1", "retrieve_top5", "retrieve_top10", "feature_asum"}
