"""Data pipeline tests: identity-balanced sampler contract, on-device
augmentation semantics, list-file dataset, end-to-end loader."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from npairloss_tpu.config.schema import (
    DataLayerConfig,
    TransformParam,
    TransformerConfig,
)
from npairloss_tpu.data import (
    ArrayDataset,
    IdentityBalancedSampler,
    ListFileDataset,
    MultibatchLoader,
    apply_transform_param,
    data_transformer,
    multibatch_loader,
)


# ---------------------------------------------------------------------------
# Sampler
# ---------------------------------------------------------------------------


def _labels(n_ids=10, per_id=6):
    return np.repeat(np.arange(n_ids), per_id)


def test_sampler_batch_contract():
    """Every batch is ids_per_batch x imgs_per_id, identity-grouped."""
    s = IdentityBalancedSampler(_labels(), 4, 2, seed=0)
    labels = _labels()
    for _ in range(20):
        idx = next(s)
        assert idx.shape == (8,)
        lab = labels[idx]
        # Grouped in runs of imgs_per_id with matching labels.
        pairs = lab.reshape(4, 2)
        assert (pairs[:, 0] == pairs[:, 1]).all()
        # Identities within a batch are distinct.
        assert len(set(pairs[:, 0])) == 4


def test_sampler_without_replacement_within_identity():
    """An identity's images cycle before repeating."""
    labels = _labels(n_ids=2, per_id=4)
    s = IdentityBalancedSampler(
        labels, 2, 2, rand_identity=False, shuffle=False, seed=0
    )
    seen = {0: [], 1: []}
    for _ in range(2):  # 2 batches x 2 imgs = one full pool per identity
        idx = next(s)
        for i in idx:
            seen[labels[i]].append(i)
    for lbl, imgs in seen.items():
        assert len(set(imgs)) == 4, f"identity {lbl} repeated early: {imgs}"


def test_sampler_replacement_for_small_identity():
    labels = np.array([0, 1, 1, 2, 2])  # identity 0 has 1 image < 2
    s = IdentityBalancedSampler(labels, 3, 2, seed=0)
    idx = next(s)
    assert len(idx) == 6
    assert sorted(set(labels[idx])) == [0, 1, 2]


def test_sampler_deterministic_given_seed():
    a = IdentityBalancedSampler(_labels(), 4, 2, seed=7)
    b = IdentityBalancedSampler(_labels(), 4, 2, seed=7)
    for _ in range(5):
        np.testing.assert_array_equal(next(a), next(b))


def test_sampler_sequential_identities():
    labels = _labels(n_ids=6, per_id=2)
    s = IdentityBalancedSampler(
        labels, 2, 2, rand_identity=False, shuffle=False, seed=0
    )
    batches = [labels[next(s)].reshape(2, 2)[:, 0] for _ in range(3)]
    assert np.concatenate(batches).tolist() == [0, 1, 2, 3, 4, 5]


# ---------------------------------------------------------------------------
# transform_param
# ---------------------------------------------------------------------------


def test_mean_subtraction_reversed_for_rgb():
    tp = TransformParam(mean_value=(104.0, 117.0, 123.0))
    img = np.zeros((1, 4, 4, 3), np.float32)
    out = np.asarray(apply_transform_param(img, jax.random.PRNGKey(0), tp))
    # BGR-order means reversed onto RGB channels.
    assert out[0, 0, 0, 0] == -123.0
    assert out[0, 0, 0, 1] == -117.0
    assert out[0, 0, 0, 2] == -104.0


def test_crop_train_and_test():
    tp = TransformParam(crop_size=4)
    img = np.arange(2 * 8 * 8 * 3, dtype=np.float32).reshape(2, 8, 8, 3)
    out_tr = apply_transform_param(img, jax.random.PRNGKey(0), tp, train=True)
    out_te = apply_transform_param(img, jax.random.PRNGKey(0), tp, train=False)
    assert out_tr.shape == (2, 4, 4, 3)
    # TEST center crop is deterministic.
    np.testing.assert_array_equal(np.asarray(out_te), img[:, 2:6, 2:6, :])


def test_mirror_only_in_train():
    tp = TransformParam(mirror=True)
    img = np.arange(1 * 2 * 4 * 3, dtype=np.float32).reshape(1, 2, 4, 3)
    out_te = apply_transform_param(img, jax.random.PRNGKey(0), tp, train=False)
    np.testing.assert_array_equal(np.asarray(out_te), img)
    # With many samples, some must mirror in train.
    big = np.tile(img, (64, 1, 1, 1))
    out_tr = np.asarray(
        apply_transform_param(big, jax.random.PRNGKey(1), tp, train=True)
    )
    flipped = (out_tr == big[:, :, ::-1, :]).all(axis=(1, 2, 3))
    kept = (out_tr == big).all(axis=(1, 2, 3))
    assert flipped.any() and kept.any()
    assert (flipped | kept).all()


# ---------------------------------------------------------------------------
# DataTransformer warp
# ---------------------------------------------------------------------------


def test_zero_scopes_are_identity():
    cfg = TransformerConfig()  # all scopes zero / scales 1
    img = np.random.default_rng(0).uniform(0, 255, (2, 8, 8, 3)).astype(np.float32)
    out = np.asarray(data_transformer(img, jax.random.PRNGKey(0), cfg))
    np.testing.assert_allclose(out, img, atol=1e-4)


def test_translation_shifts_content():
    cfg = TransformerConfig(translation_w_scope=3.0)
    img = np.zeros((8, 16, 16, 1), np.float32)
    img[:, :, 8, 0] = 1.0  # vertical line at x=8
    out = np.asarray(data_transformer(img, jax.random.PRNGKey(2), cfg))
    cols = out[..., 0].sum(axis=1).argmax(axis=1)
    assert (np.abs(cols - 8) <= 3).all()
    assert len(set(cols.tolist())) > 1  # actually random per image


def test_rotation_preserves_center():
    cfg = TransformerConfig(rotate_angle_scope=0.349)
    img = np.zeros((4, 9, 9, 1), np.float32)
    img[:, 4, 4, 0] = 1.0
    out = np.asarray(data_transformer(img, jax.random.PRNGKey(3), cfg))
    # Center pixel is the rotation fixed point.
    assert (out[:, 4, 4, 0] > 0.5).all()


def test_elastic_runs_and_stays_bounded():
    cfg = TransformerConfig(
        elastic_transform=True, amplitude=2.0, radius=1.5
    )
    img = np.random.default_rng(0).uniform(0, 1, (2, 12, 12, 3)).astype(np.float32)
    out = np.asarray(data_transformer(img, jax.random.PRNGKey(4), cfg))
    assert out.shape == img.shape
    assert np.isfinite(out).all()
    assert out.min() >= img.min() - 1e-5 and out.max() <= img.max() + 1e-5


def test_reference_config_warp_shapes():
    """The exact def.prototxt:69-83 transformer config runs end-to-end."""
    cfg = TransformerConfig(
        rotate_angle_scope=0.349,
        translation_w_scope=70,
        translation_h_scope=70,
        scale_w_scope=1.2,
        scale_h_scope=1.2,
        h_flip=True,
        elastic_transform=False,
    )
    img = np.random.default_rng(1).uniform(0, 255, (4, 64, 64, 3)).astype(np.float32)
    out = np.asarray(data_transformer(img, jax.random.PRNGKey(5), cfg))
    assert out.shape == img.shape and np.isfinite(out).all()


# ---------------------------------------------------------------------------
# ListFileDataset + loader end-to-end
# ---------------------------------------------------------------------------


def _write_image_tree(tmp_path, n_ids=4, per_id=3, size=(10, 12)):
    from PIL import Image

    rng = np.random.default_rng(0)
    lines = []
    for i in range(n_ids):
        for j in range(per_id):
            arr = rng.integers(0, 255, (*size, 3), dtype=np.uint8)
            rel = f"id{i}/img{j}.png"
            os.makedirs(tmp_path / f"id{i}", exist_ok=True)
            Image.fromarray(arr).save(tmp_path / rel)
            lines.append(f"{rel} {i}")
    src = tmp_path / "list.txt"
    src.write_text("\n".join(lines) + "\n")
    return str(src)


def test_listfile_dataset(tmp_path):
    src = _write_image_tree(tmp_path)
    ds = ListFileDataset(str(tmp_path), src, new_height=8, new_width=8)
    assert len(ds) == 12
    img = ds.load(0)
    assert img.shape == (8, 8, 3) and img.dtype == np.uint8
    assert ds.labels.tolist() == [0] * 3 + [1] * 3 + [2] * 3 + [3] * 3


def test_multibatch_loader_end_to_end(tmp_path):
    src = _write_image_tree(tmp_path)
    cfg = DataLayerConfig(
        phase="TRAIN",
        root_folder=str(tmp_path),
        source=src,
        batch_size=4,
        shuffle=True,
        new_height=16,
        new_width=16,
        identity_num_per_batch=2,
        img_num_per_identity=2,
        rand_identity=True,
        transform=TransformParam(
            mirror=True, crop_size=12, mean_value=(104.0, 117.0, 123.0)
        ),
    )
    tr = TransformerConfig(rotate_angle_scope=0.2, h_flip=True)
    loader = multibatch_loader(cfg, tr, seed=0)
    try:
        for _ in range(3):
            images, labels = next(loader)
            images = np.asarray(images)
            assert images.shape == (4, 12, 12, 3)
            assert images.dtype == np.float32
            assert labels.shape == (4,)
            lab = labels.reshape(2, 2)
            assert (lab[:, 0] == lab[:, 1]).all()
    finally:
        loader.close()


def test_loader_with_array_dataset_no_augment():
    rng = np.random.default_rng(0)
    images = rng.uniform(0, 1, (20, 6, 6, 3)).astype(np.float32)
    labels = np.repeat(np.arange(5), 4)
    cfg = DataLayerConfig(
        identity_num_per_batch=3, img_num_per_identity=2, shuffle=True,
        rand_identity=True,
    )
    loader = MultibatchLoader(ArrayDataset(images, labels), cfg, seed=1)
    try:
        x, y = next(loader)
        assert np.asarray(x).shape == (6, 6, 6, 3)
        assert y.shape == (6,)
    finally:
        loader.close()


# ---------------------------------------------------------------------------
# Review-driven regressions
# ---------------------------------------------------------------------------


def test_sampler_no_duplicate_image_within_batch_group():
    """Pool refill mid-batch must not hand the same image to one group."""
    labels = np.repeat(np.arange(8), 3)  # 3 images/id, imgs_per_id=2
    s = IdentityBalancedSampler(labels, 4, 2, seed=0)
    for _ in range(200):
        idx = next(s).reshape(4, 2)
        assert (idx[:, 0] != idx[:, 1]).all()


def test_loader_worker_error_surfaces(tmp_path):
    src = tmp_path / "bad.txt"
    src.write_text("missing.png 0\nalso_missing.png 1\n")
    ds = ListFileDataset(str(tmp_path), str(src), 8, 8)
    cfg = DataLayerConfig(identity_num_per_batch=2, img_num_per_identity=1)
    loader = MultibatchLoader(ds, cfg, seed=0)
    try:
        with pytest.raises(RuntimeError, match="prefetch worker failed"):
            next(loader)
    finally:
        loader.close()


def test_scale_scope_below_one_still_scales():
    cfg = TransformerConfig(scale_w_scope=0.5)
    img = np.zeros((16, 17, 17, 1), np.float32)
    img[:, :, 8, 0] = 1.0
    out = np.asarray(data_transformer(img, jax.random.PRNGKey(6), cfg))
    widths = (out[..., 0].sum(axis=1) > 0.05).sum(axis=1)
    assert len(set(widths.tolist())) > 1, "scale augmentation was a no-op"


def test_crop_larger_than_image_raises():
    tp = TransformParam(crop_size=64)
    img = np.zeros((1, 32, 32, 3), np.float32)
    with pytest.raises(ValueError, match="crop_size"):
        apply_transform_param(img, jax.random.PRNGKey(0), tp)


def test_bad_mean_value_length_raises():
    tp = TransformParam(mean_value=(104.0, 117.0))
    img = np.zeros((1, 4, 4, 3), np.float32)
    with pytest.raises(ValueError, match="mean_value"):
        apply_transform_param(img, jax.random.PRNGKey(0), tp)


def test_listfile_tabs_and_multispace(tmp_path):
    from PIL import Image

    arr = np.zeros((4, 4, 3), np.uint8)
    Image.fromarray(arr).save(tmp_path / "a.png")
    Image.fromarray(arr).save(tmp_path / "b.png")
    src = tmp_path / "list.txt"
    src.write_text("a.png\t0\nb.png  1\n")
    ds = ListFileDataset(str(tmp_path), str(src))
    assert ds.paths == ["a.png", "b.png"]
    assert ds.labels.tolist() == [0, 1]
    assert ds.load(1).shape == (4, 4, 3)


def test_sampler_sequential_wrap_keeps_identities_distinct():
    """A mid-batch wrap + reshuffle must not repeat an identity in-batch."""
    labels = np.repeat(np.arange(6), 2)
    s = IdentityBalancedSampler(
        labels, 4, 2, rand_identity=False, shuffle=True, seed=0
    )
    for _ in range(100):
        idx = next(s).reshape(4, 2)
        ids = labels[idx[:, 0]]
        assert len(set(ids.tolist())) == 4
        assert (idx[:, 0] != idx[:, 1]).all()


def test_loader_garbage_collected_without_close():
    """Abandoned loaders must not pin the prefetch thread forever."""
    import gc
    import weakref as wr

    images = np.zeros((8, 4, 4, 3), np.float32)
    labels = np.repeat(np.arange(4), 2)
    cfg = DataLayerConfig(identity_num_per_batch=2, img_num_per_identity=2)
    loader = MultibatchLoader(ArrayDataset(images, labels), cfg, seed=0)
    next(loader)
    ref = wr.ref(loader)
    thread = loader._thread
    del loader
    gc.collect()
    assert ref() is None, "loader leaked (worker holds a strong ref)"
    thread.join(timeout=5.0)
    assert not thread.is_alive()
