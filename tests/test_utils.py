"""utils: profiling annotations/timer and numeric debug guards."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import make_identity_batch
from npairloss_tpu.ops.npair_loss import NPairLossConfig, npair_loss_with_aux
from npairloss_tpu.utils import (
    StepTimer,
    annotate,
    assert_all_finite,
    checked,
    debug_checks_enabled,
    enable_debug_checks,
    trace,
)


def test_named_scopes_reach_hlo(rng):
    """The stage annotations must survive into the lowered module so
    XProf timelines show the pipeline stages.  ``lowered_text`` is the
    version shim: the debug_info kwarg only exists on newer jax."""
    from npairloss_tpu.parallel._compat import lowered_text

    (f,), (l,) = make_identity_batch(rng, 4, 2, 8)
    text = lowered_text(jax.jit(
        lambda x: npair_loss_with_aux(x, jnp.asarray(l), NPairLossConfig())[0]
    ).lower(jnp.asarray(f)))
    for scope in ("npair/sim", "npair/mine", "npair/select", "npair/loss"):
        assert scope in text, scope


def test_annotate_composes_under_jit():
    @jax.jit
    def f(x):
        with annotate("stage/a"):
            y = x * 2
        with annotate("stage/b"):
            return y + 1

    assert float(f(jnp.float32(3))) == 7.0


def test_step_timer():
    t = StepTimer(window=4)
    assert t.tick(10)["steps_per_sec"] == 0.0  # first tick only arms
    for _ in range(5):
        t.tick(10)
    s = t.stats()
    assert s["steps_per_sec"] > 0 and s["items_per_sec"] > 0
    assert len(t._durations) == 4  # window bounded
    t.reset()
    assert t.stats()["steps_per_sec"] == 0.0


@pytest.mark.slow  # ~46s (XProf profiler session); tier-1 budget, run with -m slow
def test_trace_writes_profile(tmp_path):
    with trace(str(tmp_path)):
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    found = []
    for root, _, files in os.walk(tmp_path):
        found.extend(files)
    assert found, "no profile artifacts written"


def test_assert_all_finite():
    assert_all_finite({"a": jnp.ones(3), "b": 2.0}, "ok")
    with pytest.raises(FloatingPointError, match="bad"):
        assert_all_finite({"x": jnp.array([1.0, np.nan])}, "bad")
    # integer leaves are skipped
    assert_all_finite({"i": jnp.arange(3)})


def test_checked_catches_nan_under_jit():
    from jax.experimental import checkify

    f = checked(lambda x: jnp.log(x))  # jits internally
    assert np.isclose(float(f(jnp.float32(1.0))), 0.0)
    with pytest.raises(checkify.JaxRuntimeError):
        f(jnp.float32(-1.0))  # log of negative -> NaN


def test_checked_npair_loss_is_clean(rng):
    """The production loss must pass checkify's NaN/div tracking: the
    div/log guards (cu:162-169 semantics) hold under instrumentation."""
    (f,), (l,) = make_identity_batch(rng, 4, 2, 8)
    fn = checked(
        lambda x: npair_loss_with_aux(x, jnp.asarray(l), NPairLossConfig())[0]
    )
    assert np.isfinite(float(fn(jnp.asarray(f))))
    # including the degenerate all-unique-labels batch (zero-count guard)
    lu = jnp.arange(f.shape[0], dtype=jnp.int32)
    fn_u = checked(
        lambda x: npair_loss_with_aux(x, lu, NPairLossConfig())[0]
    )
    assert float(fn_u(jnp.asarray(f))) == 0.0


def test_solver_debug_checks_flag(rng):
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    solver = Solver(
        get_model("mlp", hidden=(16,), embedding_dim=8),
        NPairLossConfig(),
        SolverConfig(base_lr=0.1, lr_policy="fixed", display=0, snapshot=0),
        input_shape=(8,),
    )
    (f,), (l,) = make_identity_batch(rng, 4, 2, 8)
    enable_debug_checks(True)
    try:
        assert debug_checks_enabled()
        m = solver.step(f, l)  # finite case passes
        assert np.isfinite(float(m["loss"]))
        # poison the params -> next step must raise with the metric name
        solver.state["params"] = jax.tree_util.tree_map(
            lambda p: jnp.full_like(p, jnp.nan), solver.state["params"]
        )
        with pytest.raises(FloatingPointError):
            solver.step(f, l)
    finally:
        enable_debug_checks(False)


def test_time_scan_measures_and_salts_uniquely():
    """time_scan returns a sane ms/iter and every dispatch in the process
    draws a distinct salt (memoizing-tunnel defense; docs/DESIGN.md §6)."""
    import jax.numpy as jnp

    from npairloss_tpu.utils import profiling

    def body(acc, s):
        return acc + jnp.sin(s)

    ms1 = profiling.time_scan(body, jnp.float32(0.0), steps=3)
    ms2 = profiling.time_scan(body, jnp.float32(0.0), steps=3)
    assert ms1 > 0 and ms2 > 0
    with pytest.raises(ValueError):
        profiling.time_scan(body, jnp.float32(0.0), steps=0)
    # Distinctness of the underlying salt ints, and float32 exactness of
    # the 2**-20 scaling for every value the counter can emit.
    a, b = profiling._next_salt_int(), profiling._next_salt_int()
    assert a != b
    assert float(jnp.float32(a * 2.0 ** -20)) != float(
        jnp.float32(b * 2.0 ** -20))


def test_dispatch_floor_positive_and_bounded():
    from npairloss_tpu.utils.profiling import dispatch_floor

    f1 = dispatch_floor()
    f2 = dispatch_floor()
    assert 0 < f1 < 10.0 and 0 < f2 < 10.0  # seconds; CPU is microseconds
